"""Checkpoint loading tests: safetensors round trip, HF name mapping for
all three model families, forward parity, and Orbax save/restore.

The HF fixtures are synthetic state dicts written with the in-tree
safetensors writer — same names/shapes/layout as real exports, no network.
"""

import os

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
import pytest

from k8s_llm_rca_tpu.config import TINY, TINY_ENCODER, TINY_MOE
from k8s_llm_rca_tpu.models import encoder, llama, loader


def rng_tensor(rng, *shape):
    return rng.standard_normal(shape).astype(np.float32) * 0.05


def synth_llama_sd(cfg, rng):
    """Synthetic HF-Llama state dict (HF [out, in] Linear layout)."""
    h, q, kv, inter = (cfg.hidden_size, cfg.q_dim, cfg.kv_dim,
                       cfg.intermediate_size)
    sd = {
        "model.embed_tokens.weight": rng_tensor(rng, cfg.vocab_size, h),
        "model.norm.weight": rng_tensor(rng, h),
    }
    if not cfg.tie_embeddings:
        sd["lm_head.weight"] = rng_tensor(rng, cfg.vocab_size, h)
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = rng_tensor(rng, h)
        sd[p + "post_attention_layernorm.weight"] = rng_tensor(rng, h)
        sd[p + "self_attn.q_proj.weight"] = rng_tensor(rng, q, h)
        sd[p + "self_attn.k_proj.weight"] = rng_tensor(rng, kv, h)
        sd[p + "self_attn.v_proj.weight"] = rng_tensor(rng, kv, h)
        sd[p + "self_attn.o_proj.weight"] = rng_tensor(rng, h, q)
        if cfg.n_experts > 0:
            moe = p + "block_sparse_moe."
            sd[moe + "gate.weight"] = rng_tensor(rng, cfg.n_experts, h)
            for e in range(cfg.n_experts):
                ep = f"{moe}experts.{e}."
                sd[ep + "w1.weight"] = rng_tensor(rng, inter, h)
                sd[ep + "w2.weight"] = rng_tensor(rng, h, inter)
                sd[ep + "w3.weight"] = rng_tensor(rng, inter, h)
        else:
            sd[p + "mlp.gate_proj.weight"] = rng_tensor(rng, inter, h)
            sd[p + "mlp.up_proj.weight"] = rng_tensor(rng, inter, h)
            sd[p + "mlp.down_proj.weight"] = rng_tensor(rng, h, inter)
    return sd


def synth_bert_sd(cfg, rng, prefix=""):
    h, inter = cfg.hidden_size, cfg.intermediate_size
    sd = {
        prefix + "embeddings.word_embeddings.weight":
            rng_tensor(rng, cfg.vocab_size, h),
        prefix + "embeddings.position_embeddings.weight":
            rng_tensor(rng, cfg.max_seq_len, h),
        prefix + "embeddings.token_type_embeddings.weight":
            rng_tensor(rng, 2, h),
        prefix + "embeddings.LayerNorm.weight": rng_tensor(rng, h),
        prefix + "embeddings.LayerNorm.bias": rng_tensor(rng, h),
    }
    for i in range(cfg.n_layers):
        p = f"{prefix}encoder.layer.{i}."
        for name, shape in (
            ("attention.self.query", (h, h)), ("attention.self.key", (h, h)),
            ("attention.self.value", (h, h)),
            ("attention.output.dense", (h, h)),
            ("intermediate.dense", (inter, h)),
            ("output.dense", (h, inter)),
        ):
            sd[p + name + ".weight"] = rng_tensor(rng, *shape)
            sd[p + name + ".bias"] = rng_tensor(rng, shape[0])
        for ln in ("attention.output.LayerNorm", "output.LayerNorm"):
            sd[p + ln + ".weight"] = rng_tensor(rng, h)
            sd[p + ln + ".bias"] = rng_tensor(rng, h)
    return sd


class TestSafetensorsIO:
    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / "t.safetensors")
        rng = np.random.default_rng(0)
        tensors = {
            "a": rng_tensor(rng, 3, 5),
            "b.c": np.arange(7, dtype=np.int32),
            "bf": rng_tensor(rng, 2, 2).astype(ml_dtypes.bfloat16),
        }
        loader.write_safetensors(path, tensors)
        back = loader.read_safetensors(path)
        assert set(back) == set(tensors)
        for k in tensors:
            assert back[k].dtype == tensors[k].dtype
            np.testing.assert_array_equal(np.asarray(back[k]),
                                          np.asarray(tensors[k]))

    def test_sharded_dir(self, tmp_path):
        import json
        rng = np.random.default_rng(1)
        a, b = rng_tensor(rng, 2, 2), rng_tensor(rng, 3)
        loader.write_safetensors(str(tmp_path / "s1.safetensors"), {"a": a})
        loader.write_safetensors(str(tmp_path / "s2.safetensors"), {"b": b})
        with open(tmp_path / "model.safetensors.index.json", "w") as f:
            json.dump({"weight_map": {"a": "s1.safetensors",
                                      "b": "s2.safetensors"}}, f)
        tensors = loader.load_checkpoint_tensors(str(tmp_path))
        np.testing.assert_array_equal(tensors["a"], a)
        np.testing.assert_array_equal(tensors["b"], b)

    def test_missing_tensor_reports_name(self):
        with pytest.raises(KeyError, match="input_layernorm"):
            loader.llama_params_from_hf(TINY, {})


class TestHFMapping:
    def test_llama_forward_parity(self, tmp_path):
        """Loading the synthetic HF dict must give the same logits as
        assembling the pytree by hand from the same (transposed) arrays."""
        cfg = TINY
        rng = np.random.default_rng(2)
        sd = synth_llama_sd(cfg, rng)
        path = str(tmp_path / "m.safetensors")
        loader.write_safetensors(path, sd)
        params = loader.load_llama(cfg, path)

        # independent manual assembly
        manual = {
            "embedding": jnp.asarray(sd["model.embed_tokens.weight"]),
            "final_norm": jnp.asarray(sd["model.norm.weight"]),
            "layers": [],
        }
        for i in range(cfg.n_layers):
            p = f"model.layers.{i}."
            manual["layers"].append({
                "attn_norm": jnp.asarray(sd[p + "input_layernorm.weight"]),
                "mlp_norm": jnp.asarray(
                    sd[p + "post_attention_layernorm.weight"]),
                "wq": jnp.asarray(sd[p + "self_attn.q_proj.weight"].T),
                "wk": jnp.asarray(sd[p + "self_attn.k_proj.weight"].T),
                "wv": jnp.asarray(sd[p + "self_attn.v_proj.weight"].T),
                "wo": jnp.asarray(sd[p + "self_attn.o_proj.weight"].T),
                "w_gate": jnp.asarray(sd[p + "mlp.gate_proj.weight"].T),
                "w_up": jnp.asarray(sd[p + "mlp.up_proj.weight"].T),
                "w_down": jnp.asarray(sd[p + "mlp.down_proj.weight"].T),
            })
        tokens = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0,
                                    cfg.vocab_size)
        la = llama.forward(cfg, params, tokens)
        lb = llama.forward(cfg, manual, tokens)
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=1e-5, atol=1e-5)

    def test_mixtral_mapping_shapes(self, tmp_path):
        cfg = TINY_MOE
        sd = synth_llama_sd(cfg, np.random.default_rng(3))
        path = str(tmp_path / "moe.safetensors")
        loader.write_safetensors(path, sd)
        params = loader.load_llama(cfg, path)
        layer = params["layers"][0]
        e, h, i = cfg.n_experts, cfg.hidden_size, cfg.intermediate_size
        assert layer["router"].shape == (h, e)
        assert layer["w_gate"].shape == (e, h, i)
        assert layer["w_down"].shape == (e, i, h)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 6), 0,
                                    cfg.vocab_size)
        logits = llama.forward(cfg, params, tokens)
        assert bool(jnp.all(jnp.isfinite(logits)))

    @pytest.mark.parametrize("prefix", ["", "bert."])
    def test_encoder_mapping(self, tmp_path, prefix):
        cfg = TINY_ENCODER
        sd = synth_bert_sd(cfg, np.random.default_rng(4), prefix)
        path = str(tmp_path / "enc.safetensors")
        loader.write_safetensors(path, sd)
        params = loader.load_encoder(cfg, path)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 10), 0,
                                    cfg.vocab_size)
        vecs = encoder.embed(cfg, params, tokens)
        assert vecs.shape == (2, cfg.hidden_size)
        np.testing.assert_allclose(np.linalg.norm(np.asarray(vecs), axis=-1),
                                   np.ones(2), rtol=1e-5)

    def test_tied_checkpoint_fallback_lm_head(self, tmp_path):
        cfg = TINY.replace(tie_embeddings=False)
        sd = synth_llama_sd(TINY, np.random.default_rng(5))  # no lm_head
        path = str(tmp_path / "tied.safetensors")
        loader.write_safetensors(path, sd)
        params = loader.load_llama(cfg, path)
        np.testing.assert_array_equal(np.asarray(params["lm_head"]),
                                      np.asarray(params["embedding"]))


class TestOrbax:
    def test_params_roundtrip(self, tmp_path):
        from k8s_llm_rca_tpu.utils import checkpoint

        params = llama.init_params(TINY, jax.random.PRNGKey(0))
        path = str(tmp_path / "ckpt")
        checkpoint.save_params(path, params)
        back = checkpoint.restore_params(path, like=params)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), params, back)

    def test_train_checkpointer_retention_and_resume(self, tmp_path):
        import optax

        from k8s_llm_rca_tpu.utils.checkpoint import TrainCheckpointer

        params = llama.init_params(TINY, jax.random.PRNGKey(0))
        opt_state = optax.adamw(1e-3).init(params)
        ckpt = TrainCheckpointer(str(tmp_path / "train"), max_to_keep=2)
        assert ckpt.latest_step is None
        for step in (1, 2, 3):
            scaled = jax.tree.map(lambda x: x * step, params)
            ckpt.save(step, {"params": scaled, "opt_state": opt_state})
        assert ckpt.latest_step == 3
        state = ckpt.restore(like={"params": params, "opt_state": opt_state})
        np.testing.assert_allclose(
            np.asarray(state["params"]["final_norm"], np.float32),
            np.asarray(params["final_norm"], np.float32) * 3)
        ckpt.close()

    def test_untied_head_with_tied_config_raises(self, tmp_path):
        sd = synth_llama_sd(TINY, np.random.default_rng(6))
        sd["lm_head.weight"] = rng_tensor(np.random.default_rng(7),
                                          TINY.vocab_size, TINY.hidden_size)
        path = str(tmp_path / "u.safetensors")
        loader.write_safetensors(path, sd)
        with pytest.raises(ValueError, match="tie_embeddings"):
            loader.load_llama(TINY, path)   # TINY ties embeddings
