"""Paged KV cache: allocator invariants, model-path equivalence, engine
churn + preemption.

The allocator invariant tests are the "race detection" coverage SURVEY §5
requires the build to add (the reference is single-threaded and has no
cache to corrupt).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_llm_rca_tpu.config import TINY, EngineConfig
from k8s_llm_rca_tpu.engine.engine import InferenceEngine
from k8s_llm_rca_tpu.engine.paged import (
    TRASH_PAGE, AllocatorError, OutOfPages, PageAllocator,
    PagedInferenceEngine, init_paged_cache, paged_decode_step, paged_prefill,
)
from k8s_llm_rca_tpu.engine.prefix import PrefixCache
from k8s_llm_rca_tpu.utils.logging import METRICS
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer


class TestPageAllocator:
    def test_alloc_free_roundtrip(self):
        a = PageAllocator(16)
        pages = a.alloc(5, owner=1)
        assert len(set(pages)) == 5 and TRASH_PAGE not in pages
        a.free(pages, owner=1)
        a.check()
        assert a.n_free == 15

    def test_double_free_detected(self):
        a = PageAllocator(8)
        pages = a.alloc(2, owner=1)
        a.free(pages, owner=1)
        with pytest.raises(AllocatorError, match="double free"):
            a.free(pages, owner=1)

    def test_cross_owner_free_detected(self):
        a = PageAllocator(8)
        pages = a.alloc(2, owner=1)
        with pytest.raises(AllocatorError, match="owned by"):
            a.free(pages, owner=2)
        a.check()

    def test_exhaustion_raises(self):
        a = PageAllocator(4)          # 3 usable
        a.alloc(3, owner=1)
        with pytest.raises(OutOfPages):
            a.alloc(1, owner=2)

    def test_trash_page_never_allocated(self):
        a = PageAllocator(4)
        assert TRASH_PAGE not in a.alloc(3, owner=1)
        with pytest.raises(AllocatorError, match="trash"):
            a.free([TRASH_PAGE], owner=1)


class TestPagedModelPath:
    """paged prefill+decode must produce the same greedy tokens as the
    contiguous cache path."""

    def _greedy_contiguous(self, cfg, params, prompt, n_steps):
        cache = llama.init_cache(cfg, 1, cfg.max_seq_len)
        toks = jnp.asarray([prompt], jnp.int32)
        cache, logits = llama.prefill(cfg, params, cache, toks,
                                      jnp.int32(len(prompt)), jnp.int32(0))
        out = [int(jnp.argmax(logits[0]))]
        lengths = jnp.asarray([len(prompt)], jnp.int32)
        cur = jnp.asarray(out, jnp.int32)
        for _ in range(n_steps - 1):
            cache, logits = llama.decode_step(cfg, params, cache, cur, lengths)
            lengths = lengths + 1
            cur = jnp.asarray([int(jnp.argmax(logits[0]))], jnp.int32)
            out.append(int(cur[0]))
        return out

    def test_greedy_equivalence(self):
        cfg = TINY.replace(max_seq_len=64)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        page = 8
        prompt = list(range(5, 18))      # 13 tokens -> 2 pages
        ref = self._greedy_contiguous(cfg, params, prompt, 6)

        pool = init_paged_cache(cfg, 32, page)
        # non-contiguous scattered pages on purpose
        page_map = jnp.asarray([7, 3], jnp.int32)
        padded = jnp.zeros((1, 16), jnp.int32).at[0, :13].set(
            jnp.asarray(prompt))
        pool, logits = paged_prefill(
            cfg, params, pool, padded, jnp.int32(13), page_map)
        got = [int(jnp.argmax(logits[0]))]

        tables = np.full((1, 8), TRASH_PAGE, np.int32)
        tables[0, :2] = [7, 3]
        extra = [11, 5, 9, 2, 30, 29]     # pages for growth
        lengths = 13
        cur = got[0]
        for _ in range(5):
            if lengths % page == 0:
                tables[0, lengths // page] = extra.pop(0)
            pool, logits = paged_decode_step(
                cfg, params, pool,
                jnp.asarray([cur], jnp.int32),
                jnp.asarray([lengths], jnp.int32),
                jnp.asarray(tables), use_kernel=False)
            lengths += 1
            cur = int(jnp.argmax(logits[0]))
            got.append(cur)
        assert got == ref


class TestPagedEngine:
    def _engine(self, **kw):
        cfg = TINY.replace(max_seq_len=64)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        # prefix_cache off: these tests pin exact page counts and engineer
        # pool-exhaustion scenarios; sharing would shift the arithmetic.
        # TestPrefixCaching covers the cache-on behavior.
        defaults = dict(max_batch=4, max_seq_len=64, page_size=8,
                        num_pages=64, prefill_buckets=(16, 32, 64),
                        max_new_tokens=8, temperature=0.0,
                        prefix_cache=False)
        defaults.update(kw)
        ecfg = EngineConfig(**defaults)
        tok = get_tokenizer()
        return (PagedInferenceEngine(cfg, ecfg, params, tok,
                                     use_kernel=False),
                InferenceEngine(cfg, ecfg, params, tok), tok, cfg)

    def test_matches_contiguous_engine(self):
        paged, contiguous, tok, cfg = self._engine()
        prompts = [tok.encode(t, add_bos=True) for t in
                   ["pod crashloop", "pvc pending why", "node notready"]]
        a = paged.generate(prompts, max_new_tokens=6)
        b = contiguous.generate(prompts, max_new_tokens=6)
        for ra, rb in zip(a, b):
            assert ra.token_ids == rb.token_ids
            assert ra.finish_reason == rb.finish_reason
        paged.allocator.check()
        assert paged.allocator.n_free == 63   # everything returned

    def test_churn_many_sequences(self):
        paged, _, tok, _ = self._engine(num_pages=32)
        prompts = [tok.encode(f"incident number {i} pod failing", add_bos=True)
                   for i in range(10)]
        results = paged.generate(prompts, max_new_tokens=5)
        assert len(results) == 10
        assert sorted(r.seq_id for r in results) == list(range(10))
        paged.allocator.check()
        assert paged.allocator.n_free == 31

    def test_lockstep_page_boundary_preemption(self):
        # regression: two sequences admitted with identical prompt lengths
        # hit a page boundary on the SAME tick with zero free pages; the
        # growth loop must skip the slot that _preempt_youngest() evicted
        # mid-loop instead of KeyError-ing on the stale snapshot.
        paged, _, tok, _ = self._engine(
            num_pages=5, max_batch=2, page_size=8, max_seq_len=32,
            prefill_buckets=(16,), max_new_tokens=10)
        prompt = tok.encode("0123456789abcde")   # 15 chars + BOS = 16 tokens
        prompt = [tok.bos_id] + prompt
        assert len(prompt) == 16
        results = paged.generate([prompt, list(prompt)], max_new_tokens=10)
        assert len(results) == 2
        paged.allocator.check()
        assert paged.allocator.n_free == 4

    def test_preemption_under_pressure(self):
        # pool barely holds one max sequence: concurrent seqs force preempts
        paged, _, tok, _ = self._engine(num_pages=12, max_batch=3,
                                        max_new_tokens=16)
        prompts = [tok.encode("a b c d e f g h i j k l m n o p", add_bos=True)
                   for _ in range(3)]
        results = paged.generate(prompts, max_new_tokens=16)
        assert len(results) == 3
        for r in results:
            assert r.completion_tokens >= 16 or r.finish_reason in (
                "eos", "stop", "length")
        paged.allocator.check()
        assert paged.allocator.n_free == 11


class TestPreemptionPolicy:
    def _engine(self, **kw):
        return TestPagedEngine()._engine(**kw)

    def test_admission_waits_instead_of_evicting(self):
        """A queued request that doesn't fit must NOT evict running work
        (regression: admission used to preempt the youngest active sequence,
        which was requeued at the queue front and instantly readmitted —
        one full re-prefill per generated token while the head-of-queue
        request starved)."""
        from k8s_llm_rca_tpu.utils.logging import METRICS

        # 5 usable pages, 2-page sequences at bucket 16 -> two admit
        # (4 pages), the third's admission raises OutOfPages and must wait.
        # 12-token prompts + 4 new tokens end exactly at the 16-slot bucket
        # edge, so growth never allocates and the only possible preemption
        # source is admission — the counter stays flat iff admission waits.
        paged, _, tok, _ = self._engine(num_pages=6, max_batch=3,
                                        page_size=8, max_seq_len=32,
                                        prefill_buckets=(16,),
                                        max_new_tokens=4)
        before = METRICS.count("engine.preemptions")
        prompts = [tok.encode("0123456789a", add_bos=True)   # 12 tokens
                   for _ in range(5)]
        assert all(len(p) == 12 for p in prompts)
        results = paged.generate(prompts, max_new_tokens=4)
        assert len(results) == 5
        assert METRICS.count("engine.preemptions") == before
        paged.allocator.check()
        assert paged.allocator.n_free == 5

    def test_stop_string_spans_resume_boundary(self):
        """Stop strings split by a preemption must still terminate the
        sequence: the match window sees pre-preemption tokens too.

        decode_chunk=1 pins step() to one generated token: the test pokes
        engine internals between steps, and on hardware the default chunked
        scan tick would decode the whole 8-token budget inside the first
        step() and retire the sequence before we can simulate a preemption.
        """
        paged, _, tok, _ = self._engine(decode_chunk=1)
        seq = paged.submit(tok.encode("x", add_bos=True),
                           max_new_tokens=8, stop_strings=("```",))
        paged.step()                      # admit; one token generated
        (slot, st), = paged._active.items()
        # simulate: two backticks generated, then the engine preempts
        st.generated = tok.encode("ab``")
        paged.lengths[slot] = st.prompt_tokens + len(st.generated)
        paged._preempt_slot(slot)
        assert paged._resumed[seq] == tok.encode("ab``")
        # resume; if the model doesn't emit the completing backtick itself,
        # feed one through _finish_reason by hand
        finished = paged.step()           # re-admit (re-prefill)
        if finished:
            (res,) = finished
        else:
            (slot, st), = paged._active.items()
            st.generated = tok.encode("`")
            reason = paged._finish_reason(st, tok.encode("`")[0],
                                          int(paged.lengths[slot]))
            assert reason == "stop"
            res = paged._retire(slot, reason)
        assert res.finish_reason == "stop"
        assert res.text == "ab"           # trimmed at the spanning stop string
        paged.allocator.check()


class TestPrefixCaching:
    """Prefix-cache behavior (engine/prefix.py): KV reuse across sequences
    sharing a prompt prefix, refcounts, eviction under pressure."""

    def _engine(self, **kw):
        cfg = TINY.replace(max_seq_len=64)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        defaults = dict(max_batch=4, max_seq_len=64, page_size=8,
                        num_pages=64, prefill_buckets=(16, 32, 64),
                        max_new_tokens=8, temperature=0.0,
                        prefix_cache=True)
        defaults.update(kw)
        ecfg = EngineConfig(**defaults)
        tok = get_tokenizer()
        return PagedInferenceEngine(cfg, ecfg, params, tok,
                                    use_kernel=False), tok, cfg, params

    def test_unit_match_insert_release_evict(self):
        a = PageAllocator(16)
        pc = PrefixCache(a, page_size=4)
        prompt = list(range(1, 12))                    # 11 tokens -> 2 full pages
        pages = a.alloc(2, owner=7)
        assert pc.match(prompt) == ([], 0)
        n_shared = pc.insert(prompt, pages, owner=7, n_matched_pages=0)
        assert n_shared == 2 and pc.n_resident == 2 and pc.n_evictable == 0
        # a second prompt sharing the first 8 tokens: both full pages hit
        other = prompt[:8] + [99, 98, 97]
        got, n = pc.match(other)
        assert n == 8 and got == pages
        # a third sharing only the first page's tokens
        third = prompt[:4] + [77, 76, 75, 74, 73]
        got3, n3 = pc.match(third)
        assert n3 == 4 and got3 == [pages[0]]
        pc.release(got3)
        pc.release(got)
        pc.release(pages)
        assert pc.n_evictable == 2
        assert pc.evict(10) == 2
        a.check()
        assert a.n_free == 15                 # everything back in the pool

    def test_admission_group_breaks_at_member_prefix_hit(self):
        """A batched-admission group must END before a member whose prompt
        already has cached prefix pages: batch-prefilling it would redo the
        cached work and allocate fresh pages for it (ADVICE r1).  The member
        must instead admit singly through the chunked path with a hit."""
        from k8s_llm_rca_tpu.utils.logging import METRICS

        eng, tok, _, _ = self._engine(max_batch=8)
        shared = tok.encode("kubelet failed to mount the configmap volume "
                            "for pod api-0", add_bos=True)
        assert len(shared) > 16
        # seed the cache, then drain
        eng.generate([list(shared)], max_new_tokens=4)
        base_hits = METRICS.counters.get("engine.prefix_hit_tokens", 0)
        # burst: a cold head + a prefix-hitting member + another cold one
        cold1 = tok.encode("node pressure eviction started on worker-3 xx",
                           add_bos=True)
        cold2 = tok.encode("pvc stuck pending storageclass missing here yy",
                           add_bos=True)
        ids = [eng.submit(list(cold1), max_new_tokens=4),
               eng.submit(list(shared), max_new_tokens=4),
               eng.submit(list(cold2), max_new_tokens=4)]
        results = {r.seq_id: r for r in eng.run_to_completion()}
        assert all(results[i].completion_tokens == 4 for i in ids)
        # the shared-prefix member went through the single-admit chunked
        # path and recorded its hit
        assert METRICS.counters.get("engine.prefix_hit_tokens", 0) \
            > base_hits
        eng.allocator.check()

    def test_second_submit_skips_cached_prefill(self):
        from k8s_llm_rca_tpu.utils.logging import METRICS

        eng, tok, _, _ = self._engine()
        prompt = tok.encode("kubelet failed to pull image from registry "
                            "backoff error", add_bos=True)
        assert len(prompt) > 16                        # > 2 pages of 8
        base_hits = METRICS.counters.get("engine.prefix_hit_tokens", 0)
        r1 = eng.generate([prompt], max_new_tokens=4)[0]
        assert METRICS.counters.get("engine.prefix_hit_tokens", 0) == base_hits
        r2 = eng.generate([list(prompt)], max_new_tokens=4)[0]
        hit = METRICS.counters.get("engine.prefix_hit_tokens", 0) - base_hits
        assert hit == (len(prompt) - 1) // 8 * 8       # full pages re-used
        assert r2.token_ids == r1.token_ids            # greedy: identical
        eng.allocator.check()
        # cached pages stay resident, everything else returned
        assert eng.allocator.n_free + eng.prefix_cache.n_resident == 63
        assert eng.prefix_cache.n_evictable == eng.prefix_cache.n_resident

    def test_shared_prefix_matches_uncached_output(self):
        eng, tok, cfg, params = self._engine()
        off, _, _, _ = self._engine(prefix_cache=False)
        common = tok.encode("incident: pod crashloop in namespace redis ",
                            add_bos=True)
        suffixes = ["why is it failing", "give the root cause",
                    "what should we check"]
        prompts = [common + tok.encode(s) for s in suffixes]
        # warm the cache with the common prefix, then submit the variants
        eng.generate([prompts[0]], max_new_tokens=4)
        got = eng.generate(prompts, max_new_tokens=6)
        ref = off.generate(prompts, max_new_tokens=6)
        for g, r in zip(got, ref):
            assert g.token_ids == r.token_ids, (g.token_ids, r.token_ids)
        eng.allocator.check()

    def test_eviction_under_pressure(self):
        # small pool: cached pages must be evicted (not deadlock) when new
        # sequences need the space
        eng, tok, _, _ = self._engine(num_pages=9, max_batch=2,
                                      prefill_buckets=(16,))
        for i in range(6):
            prompt = tok.encode(f"unique incident number {i} pod oom",
                                add_bos=True)
            res = eng.generate([prompt], max_new_tokens=4)
            assert len(res) == 1
        eng.allocator.check()
        assert eng.allocator.n_free + eng.prefix_cache.n_resident == 8

    def test_refcount_protects_in_use_pages(self):
        a = PageAllocator(8)
        pc = PrefixCache(a, page_size=4)
        prompt = list(range(1, 10))
        pages = a.alloc(2, owner=1)
        pc.insert(prompt, pages, owner=1, n_matched_pages=0)
        # still referenced by owner 1: nothing evictable
        assert pc.evict(10) == 0
        got, n = pc.match(prompt)                      # second user
        assert got == pages[:2] and n == 8
        pc.release(pages)                              # owner 1 done
        assert pc.evict(10) == 0                       # owner 2 still holds
        pc.release(got)
        assert pc.evict(10) == 2
        a.check()

    def test_preemption_resume_with_shared_pages(self):
        # pool under pressure with identical prompts: preempted sequences
        # resume via the cache without corrupting refcounts
        eng, tok, _, _ = self._engine(num_pages=12, max_batch=3,
                                      max_new_tokens=16)
        prompts = [tok.encode("a b c d e f g h i j k l m n o p",
                              add_bos=True) for _ in range(3)]
        results = eng.generate(prompts, max_new_tokens=16)
        assert len(results) == 3
        eng.allocator.check()


class TestRandomizedChurn:
    def test_prefix_cache_random_schedule_matches_cache_off(self):
        """Fuzz: 24 prompts with overlapping prefixes through a small pool
        (forced evictions + preemptions), cache-on vs cache-off — outputs
        must be identical and the allocator must end clean."""
        cfg = TINY.replace(max_seq_len=64)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tok = get_tokenizer()
        rng = np.random.default_rng(7)
        commons = [tok.encode(f"incident type {i} in namespace prod ",
                              add_bos=True) for i in range(3)]
        prompts = []
        for _ in range(24):
            base = commons[int(rng.integers(0, 3))]
            suffix = tok.encode("pod " + "x" * int(rng.integers(1, 12)))
            prompts.append(base + suffix)

        def run(prefix_cache):
            ecfg = EngineConfig(max_batch=3, max_seq_len=64, page_size=8,
                                num_pages=24, prefill_buckets=(16, 32, 64),
                                max_new_tokens=6, temperature=0.0,
                                prefix_cache=prefix_cache)
            eng = PagedInferenceEngine(cfg, ecfg, params, tok,
                                       use_kernel=False)
            out = eng.generate([list(p) for p in prompts], max_new_tokens=6)
            eng.allocator.check()
            if eng.prefix_cache is not None:
                assert (eng.allocator.n_free + eng.prefix_cache.n_resident
                        == 23)
                assert (eng.prefix_cache.n_evictable
                        == eng.prefix_cache.n_resident)
            else:
                assert eng.allocator.n_free == 23
            return [(r.token_ids, r.finish_reason) for r in out]

        assert run(True) == run(False)


class TestPagedScanTick:
    def test_chunk_on_off_identical_across_boundaries(self):
        """Paged scan ticks must produce identical greedy output to the
        stepwise path, including around page boundaries and eos."""
        cfg = TINY.replace(max_seq_len=64)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tok = get_tokenizer()
        prompts = [tok.encode("pod crashloop backoff", add_bos=True),
                   tok.encode("pvc stuck pending", add_bos=True)]

        def run(chunk):
            ecfg = EngineConfig(max_batch=2, max_seq_len=64, page_size=8,
                                num_pages=64, prefill_buckets=(16, 32, 64),
                                max_new_tokens=20, temperature=0.0,
                                decode_chunk=chunk, prefix_cache=False)
            eng = PagedInferenceEngine(cfg, ecfg, params, tok,
                                       use_kernel=False)
            out = eng.generate([list(p) for p in prompts],
                               max_new_tokens=20)
            eng.allocator.check()
            assert eng.allocator.n_free == 63
            return [(r.token_ids, r.finish_reason) for r in out]

        assert run(1) == run(16)


class TestQuantizedPool:
    """int8/int4 paged KV: pool shapes, numerics vs the bf16 pool, and the
    full engine loop (prefill, chunked prefix prefill, decode, speculative,
    scan ticks) over a quantized pool."""

    def _pools(self, cfg):
        return {
            "int8": init_paged_cache(cfg, 32, 8, kv_dtype=jnp.int8),
            "int4": init_paged_cache(cfg, 32, 8, kv_dtype="int4"),
        }

    def test_pool_shapes(self):
        cfg = TINY
        p8 = init_paged_cache(cfg, 32, 8, kv_dtype=jnp.int8)
        assert p8.quantized and p8.k.dtype == jnp.int8
        assert p8.k.shape == (cfg.n_layers, 32, 8, cfg.kv_dim)
        assert p8.k_scale.shape == (cfg.n_layers, 32, 8)
        p4 = init_paged_cache(cfg, 32, 8, kv_dtype="int4")
        assert p4.k.shape == (cfg.n_layers, 32, 8, cfg.kv_dim // 2)
        assert not init_paged_cache(cfg, 32, 8).quantized

    def test_quantized_decode_correlates_with_bf16(self):
        cfg = TINY.replace(max_seq_len=64)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        prompt = list(range(5, 18))
        page_map = jnp.asarray([7, 3], jnp.int32)
        padded = jnp.zeros((1, 16), jnp.int32).at[0, :13].set(
            jnp.asarray(prompt))
        tables = np.full((1, 8), TRASH_PAGE, np.int32)
        tables[0, :3] = [7, 3, 11]

        def run(pool):
            pool, logits = paged_prefill(cfg, params, pool, padded,
                                         jnp.int32(13), page_map)
            out = [np.asarray(logits[0])]
            lengths, cur = 13, int(np.argmax(out[-1]))
            for _ in range(5):
                pool, logits = paged_decode_step(
                    cfg, params, pool, jnp.asarray([cur], jnp.int32),
                    jnp.asarray([lengths], jnp.int32),
                    jnp.asarray(tables), use_kernel=False)
                lengths += 1
                cur = int(np.argmax(np.asarray(logits[0])))
                out.append(np.asarray(logits[0]))
            return np.stack(out)

        ref = run(init_paged_cache(cfg, 32, 8))
        for name, pool in self._pools(cfg).items():
            got = run(pool)
            assert np.isfinite(got).all()
            corr = np.corrcoef(ref.ravel(), got.ravel())[0, 1]
            floor = 0.99 if name == "int8" else 0.95
            assert corr > floor, (name, corr)

    def _engine(self, kv_dtype, **kw):
        cfg = TINY.replace(max_seq_len=64)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        defaults = dict(max_batch=4, max_seq_len=64, page_size=8,
                        num_pages=64, prefill_buckets=(16, 32, 64),
                        max_new_tokens=8, temperature=0.0,
                        kv_cache_dtype=kv_dtype)
        defaults.update(kw)
        tok = get_tokenizer()
        return PagedInferenceEngine(cfg, EngineConfig(**defaults), params,
                                    tok, use_kernel=False), tok

    def test_engine_generates_and_returns_pages(self):
        for kv_dtype in ("int8", "int4"):
            eng, tok = self._engine(kv_dtype, prefix_cache=False)
            res = eng.generate(
                [tok.encode("pod oom killed", add_bos=True),
                 tok.encode("pvc pending", add_bos=True)],
                max_new_tokens=12)
            assert all(r.completion_tokens == 12 for r in res), kv_dtype
            assert eng.pool.quantized
            eng.allocator.check()
            assert eng.allocator.n_free == 63

    def test_engine_prefix_cache_chunked_prefill(self):
        # second submit of a shared-prefix prompt drives the quantized
        # chunked-prefill path (gather+dequant of cached prefix pages).
        # No exact-token assertion: the re-submit attends over the
        # quantization-roundtripped prefix, so a greedy near-tie may
        # legitimately flip — the mechanics (full completion, no page
        # leaks, a recorded prefix hit) are the contract here.
        for kv_dtype in ("int8", "int4"):
            eng, tok = self._engine(kv_dtype, prefix_cache=True)
            prompt = tok.encode("kubelet failed to mount volume for pod "
                                "web-0 secret missing", add_bos=True)
            r1 = eng.generate([list(prompt)], max_new_tokens=6)[0]
            hits_before = METRICS.counters.get("engine.prefix_hit_tokens", 0)
            r2 = eng.generate([list(prompt)], max_new_tokens=6)[0]
            assert r1.completion_tokens == 6, kv_dtype
            assert r2.completion_tokens == 6, kv_dtype
            # strictly increased across THIS resubmit (the counter is
            # process-global; an absolute >0 check could pass on earlier
            # tests' hits)
            assert METRICS.counters.get("engine.prefix_hit_tokens", 0) \
                > hits_before, kv_dtype
            eng.allocator.check()

    def test_engine_scan_and_speculative_ticks(self):
        for kw in (dict(decode_chunk=8), dict(speculative_k=3)):
            for kv_dtype in ("int8", "int4"):
                eng, tok = self._engine(kv_dtype, prefix_cache=False, **kw)
                r = eng.generate(
                    [tok.encode("aaaa bbbb aaaa bbbb", add_bos=True)],
                    max_new_tokens=12)[0]
                assert r.completion_tokens == 12, (kw, kv_dtype)
                eng.allocator.check()


class TestPagedBatchedAdmission:
    def _mk(self, **kw):
        cfg = TINY.replace(max_seq_len=64)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        defaults = dict(max_batch=8, max_seq_len=64, page_size=8,
                        num_pages=64, prefill_buckets=(16, 32, 64),
                        max_new_tokens=6, temperature=0.0,
                        prefix_cache=False)
        defaults.update(kw)
        tok = get_tokenizer()
        return (PagedInferenceEngine(cfg, EngineConfig(**defaults), params,
                                     tok, use_kernel=False), tok)

    def test_batched_admission_matches_serial(self):
        # same-bucket prompts admit in one dispatch and must emit exactly
        # the tokens the serial (max_batch=1 -> singleton groups) run does
        texts = ["pod crashloop", "node notready", "pvc pending why",
                 "dns resolution fails"]
        eng, tok = self._mk()
        prompts = [tok.encode(t, add_bos=True) for t in texts]
        before = METRICS.counters.get("engine.batched_admissions", 0)
        batched = eng.generate([list(p) for p in prompts], max_new_tokens=6)
        # at least the same-bucket run batches (the odd-bucket prompt may
        # admit singly)
        assert METRICS.counters.get("engine.batched_admissions", 0) \
            >= before + 3
        eng.allocator.check()
        assert eng.allocator.n_free == 63

        serial, tok2 = self._mk(max_batch=1)
        for p, rb in zip(prompts, batched):
            rs = serial.generate([list(p)], max_new_tokens=6)[0]
            assert rs.token_ids == rb.token_ids

    def test_batched_admission_under_page_pressure(self):
        # regression: a group sized past the free list must not wedge the
        # engine (all-or-nothing batch alloc raising OutOfPages forever);
        # the admission group is bounded by free pages so the head admits
        eng, tok = self._mk(num_pages=9, max_batch=4, max_new_tokens=8)
        prompts = [tok.encode("incident %d pod oom" % i, add_bos=True)
                   for i in range(4)]
        res = eng.generate([list(p) for p in prompts], max_new_tokens=8)
        assert len(res) == 4
        eng.allocator.check()
        assert eng.allocator.n_free == 8

    def test_batched_admission_quantized_pool(self):
        for kv_dtype in ("int8", "int4"):
            eng, tok = self._mk(kv_cache_dtype=kv_dtype)
            prompts = [tok.encode(t, add_bos=True)
                       for t in ["pod oom", "pvc lost", "node gone"]]
            res = eng.generate([list(p) for p in prompts], max_new_tokens=6)
            assert all(r.completion_tokens == 6 for r in res), kv_dtype
            eng.allocator.check()

    def test_prefix_hit_still_takes_chunk_path(self):
        # head with a cached prefix must admit singly (chunked prefill),
        # not lose its hit to a batch
        eng, tok = self._mk(prefix_cache=True)
        prompt = tok.encode("kubelet failed to mount volume for pod web-0",
                           add_bos=True)
        eng.generate([list(prompt)], max_new_tokens=4)
        before = METRICS.counters.get("engine.prefix_hit_tokens", 0)
        eng.generate([list(prompt)], max_new_tokens=4)
        assert METRICS.counters.get("engine.prefix_hit_tokens", 0) > before
        eng.allocator.check()


class TestBatchedPrefixHitAdmission:
    """Equal-prefix HIT waves admit through ONE batched chunked prefill
    (paged_prefill_chunk_batch) instead of single-file — measured 5x
    faster for same-prefix waves on the dispatch-bound bench host —
    with exact greedy parity and intact pool accounting."""

    def _mk(self, prefix_cache, kv_dtype=None, max_batch=8):
        from k8s_llm_rca_tpu.config import TINY, EngineConfig
        from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine
        from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

        cfg = TINY.replace(max_seq_len=128)
        ecfg = EngineConfig(max_batch=max_batch, max_seq_len=128,
                            paged=True, page_size=8, num_pages=160,
                            prefill_buckets=(32, 64), max_new_tokens=6,
                            temperature=0.0, decode_chunk=1,
                            prefix_cache=prefix_cache,
                            kv_cache_dtype=kv_dtype)
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        return (PagedInferenceEngine(cfg, ecfg, params, tok,
                                     use_kernel=False), tok)

    def _wave(self, tok, n, seed=0):
        # shared 18-token prefix (2 full cacheable pages at page 8) +
        # distinct suffixes of VARYING length within one bucket
        base = tok.encode("incident pod crashloop ns prod", add_bos=True)
        rng = np.random.default_rng(seed)
        return [list(base)
                + list(rng.integers(1, 400, 6 + (i % 4)).astype(int))
                for i in range(n)]

    @pytest.mark.parametrize("kv_dtype", [None, "int8", "int4"])
    def test_wave_parity_and_batched_path(self, kv_dtype):
        from k8s_llm_rca_tpu.utils.logging import METRICS

        plain, tok = self._mk(prefix_cache=False, kv_dtype=kv_dtype)
        eng, _ = self._mk(prefix_cache=True, kv_dtype=kv_dtype)
        seed_wave = self._wave(tok, 2, seed=9)
        want_seed = plain.generate([list(p) for p in seed_wave],
                                   max_new_tokens=6)
        got_seed = eng.generate([list(p) for p in seed_wave],
                                max_new_tokens=6)   # seeds the cache
        for a, b in zip(want_seed, got_seed):
            assert a.token_ids == b.token_ids
        wave = self._wave(tok, 8, seed=1)
        want = plain.generate([list(p) for p in wave], max_new_tokens=6)
        before = METRICS.count("engine.prefix_batch_hit_admissions")
        got = eng.generate([list(p) for p in wave], max_new_tokens=6)
        for a, b in zip(want, got):
            assert a.token_ids == b.token_ids, kv_dtype
        # the wave really admitted through the BATCHED hit path
        assert METRICS.count("engine.prefix_batch_hit_admissions") \
            - before >= 8, kv_dtype
        eng.allocator.check()

    def test_heterogeneous_prefixes_split_groups(self):
        """Hits with DIFFERENT cached lengths must not share one batched
        chunk shape: interleaved waves over two distinct prefixes still
        match the plain engine exactly."""
        plain, tok = self._mk(prefix_cache=False)
        eng, _ = self._mk(prefix_cache=True)
        base_a = tok.encode("incident pod crashloop ns prod",
                            add_bos=True)
        base_b = tok.encode("node disk pressure", add_bos=True)
        rng = np.random.default_rng(3)
        mk = lambda base, s: list(base) + list(
            rng.integers(1, 400, 5 + s).astype(int))
        seed_wave = [mk(base_a, 0), mk(base_b, 1)]
        plain.generate([list(p) for p in seed_wave], max_new_tokens=6)
        eng.generate([list(p) for p in seed_wave], max_new_tokens=6)
        wave = [mk(base_a, 2), mk(base_a, 3), mk(base_b, 2),
                mk(base_b, 3), mk(base_a, 4), mk(base_b, 4)]
        want = plain.generate([list(p) for p in wave], max_new_tokens=6)
        got = eng.generate([list(p) for p in wave], max_new_tokens=6)
        for a, b in zip(want, got):
            assert a.token_ids == b.token_ids
        eng.allocator.check()

    def test_hit_wave_releases_refs_on_pool_exhaustion(self):
        """OutOfPages mid-hit-group releases every acquired match ref:
        after the queue drains (retirements free pages), the cache's
        evictable count equals its resident count again."""
        eng, tok = self._mk(prefix_cache=True, max_batch=4)
        seed_wave = self._wave(tok, 2, seed=9)
        eng.generate([list(p) for p in seed_wave], max_new_tokens=6)
        wave = self._wave(tok, 12, seed=2)   # > slots: forces retries
        eng.generate([list(p) for p in wave], max_new_tokens=6)
        eng.allocator.check()
        pc = eng.prefix_cache
        assert pc.n_evictable == pc.n_resident, (
            pc.n_evictable, pc.n_resident)

    def test_oversized_hit_group_does_not_livelock(self):
        """A hit group sized past the pool's free list must shrink (the
        free-page bound), not OutOfPages-retry forever: a tiny pool with
        8 equal-prefix pending hits still serves every request."""
        from k8s_llm_rca_tpu.config import TINY, EngineConfig
        from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine
        from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

        cfg = TINY.replace(max_seq_len=128)
        # 40 pages total: one 8-member hit group at ~56-token suffix
        # buckets (8 pages each) cannot allocate all-or-nothing
        ecfg = EngineConfig(max_batch=8, max_seq_len=128, paged=True,
                            page_size=8, num_pages=40,
                            prefill_buckets=(32, 64), max_new_tokens=4,
                            temperature=0.0, decode_chunk=1,
                            prefix_cache=True)
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        eng = PagedInferenceEngine(cfg, ecfg, params, tok,
                                   use_kernel=False)
        base = tok.encode("incident pod crashloop ns prod", add_bos=True)
        rng = np.random.default_rng(4)
        mk = lambda i: list(base) + list(
            rng.integers(1, 400, 40 + (i % 3)).astype(int))
        eng.generate([mk(0)], max_new_tokens=4)        # seed the cache
        res = eng.generate([mk(i) for i in range(1, 9)], max_new_tokens=4)
        assert len(res) == 8
        eng.allocator.check()


class TestEvictableAwareAdmissionCap:
    """ADVICE low #2: the prefix-HIT group cap must count free pages PLUS
    refcount-0 (evictable) prefix-cache pages — what _alloc_with_evict can
    actually satisfy — so a hit wave under pool pressure admits in ONE
    batched dispatch instead of splitting."""

    def _engine(self):
        cfg = TINY.replace(max_seq_len=64)
        ecfg = EngineConfig(max_batch=8, max_seq_len=64, paged=True,
                            page_size=8, num_pages=24,
                            prefill_buckets=(16, 32), max_new_tokens=4,
                            temperature=0.0, decode_chunk=1,
                            prefix_cache=True)
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        return PagedInferenceEngine(cfg, ecfg, params, tok,
                                    use_kernel=False), tok

    def test_hit_wave_under_pool_pressure_forms_one_group(self):
        eng, tok = self._engine()
        rng = np.random.default_rng(5)
        prefix = list(rng.integers(1, 400, 16).astype(int))   # 2 full pages

        # seed the prefix chain (24-token prompt: 3 full pages chained)
        eng.generate([prefix + list(rng.integers(1, 400, 8).astype(int))],
                     max_new_tokens=2)
        # evictable ballast: a long unrelated prompt chains 6 more pages
        eng.generate([list(rng.integers(1, 400, 48).astype(int))],
                     max_new_tokens=2)
        evictable = eng.prefix_cache.n_evictable
        assert evictable >= 7                       # 1 (3rd P page) + 6 (Q)

        # drain the free list to 2 pages: per-member suffix needs 2 pages,
        # so the OLD free-only cap would be max(1, 2 // 2) = 1 (split into
        # single admits) while free+evictable serves the whole wave of 4
        drain = eng.allocator.n_free - 2
        held = eng.allocator.alloc(drain, owner=999)
        wave = [prefix + list(rng.integers(1, 400, 8).astype(int))
                for _ in range(4)]
        for w in wave:
            eng.submit(w, max_new_tokens=2)

        hits0 = METRICS.count("engine.prefix_batch_hit_admissions")
        dispatches0 = METRICS.snapshot().get("engine.prefill.count", 0.0)
        done = eng.step()                           # admission tick
        assert METRICS.count("engine.prefix_batch_hit_admissions") \
            - hits0 == 4, "hit wave split instead of admitting as one group"
        assert METRICS.snapshot().get("engine.prefill.count", 0.0) \
            - dispatches0 == 1, "hit wave took more than one prefill dispatch"

        results = {r.seq_id: r
                   for r in list(done) + eng.run_to_completion()}
        assert len(results) == 4
        eng.allocator.free(held, owner=999)
        eng.allocator.check()
