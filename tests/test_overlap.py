"""Overlapped serving hot loop (``EngineConfig.host_overlap``,
docs/performance.md): exact greedy byte-parity against the plain tick
across the engine feature matrix, loud ValueError exclusions, and exact
host<->device traffic counter regressions.

Why counters, not timers: the tunnel memoizes identical executions and
adds ~0.25 s/dispatch, so wall-clock cannot witness the win hermetically
(CLAUDE.md).  ``engine.h2d_uploads``/``engine.d2h_syncs``/
``engine.dispatches`` are exact event counts of the hot loop, so a
host-loop regression fails these tests loudly with zero timing flake.
"""

import dataclasses

import jax
import pytest

from k8s_llm_rca_tpu.config import TINY, EngineConfig, MeshConfig
from k8s_llm_rca_tpu.engine import make_engine
from k8s_llm_rca_tpu.engine.constrain import SchemaGrammar, make_grammar
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.runtime.mesh import build_mesh
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer


@pytest.fixture(scope="module")
def setup():
    cfg = TINY.replace(max_seq_len=128)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    return cfg, params, tok


def _ecfg(paged, **over):
    base = dict(max_batch=4, max_seq_len=128, prefill_buckets=(16, 32, 64),
                max_new_tokens=12, temperature=0.0, decode_chunk=1)
    if paged:
        base.update(paged=True, page_size=16, num_pages=96,
                    prefix_cache=False)
    base.update(over)
    return EngineConfig(**base)


def _prompts(tok):
    return [tok.encode(s, add_bos=True) for s in
            ("secret not found", "configmap missing from pod spec",
             "stale NFS file handle on mount", "incident number 4",
             "exceeded quota: pods=50", "hello")]


def _run(cfg, params, tok, ecfg, prompts, grammars=(), **kw):
    """Generate the mixed workload; returns ([token_ids...], counters).
    ``grammars`` entries are (prompt, grammar_factory) appended to the
    plain prompts so constrained and unconstrained slots share ticks."""
    eng = make_engine(cfg, ecfg, params, tok, **kw)
    ids = [eng.submit(list(p), max_new_tokens=ecfg.max_new_tokens)
           for p in prompts]
    for p, gf in grammars:
        ids.append(eng.submit(list(p), max_new_tokens=ecfg.max_new_tokens,
                              grammar=gf()))
    res = {r.seq_id: r for r in eng.run_to_completion()}
    if hasattr(eng, "allocator"):
        eng.allocator.check()
    return ([(res[i].token_ids, res[i].finish_reason) for i in ids],
            dict(eng._counts))


# ---------------------------------------------------------------------------
# byte-parity matrix: overlap on vs off must be invisible to every sequence
# ---------------------------------------------------------------------------


class TestOverlapParity:
    @pytest.mark.parametrize("paged", [False, True])
    @pytest.mark.parametrize("chunk,spec_k", [(1, 0), (8, 0), (1, 3)])
    def test_matrix_matches_plain(self, setup, paged, chunk, spec_k):
        """contiguous + paged × stepwise/scan × n-gram speculation, with
        a DFA grammar slot and an interpreted python-FSM grammar slot
        sharing the batch with plain slots: byte parity, same finish
        reasons."""
        cfg, params, tok = setup
        prompts = _prompts(tok)
        gspec = [(tok.encode("emit json", add_bos=True),
                  lambda: make_grammar("json", tok)),
                 (tok.encode("diagnose:", add_bos=True),
                  lambda: SchemaGrammar({"type": "choice", "options": [
                      "verdict: missing secret",
                      "checked: node pressure"]}, tok))]
        ecfg = _ecfg(paged, decode_chunk=chunk, speculative_k=spec_k)
        kw = dict(use_kernel=False) if paged else {}
        plain, _ = _run(cfg, params, tok, ecfg, prompts, gspec, **kw)
        over, _ = _run(cfg, params, tok,
                       dataclasses.replace(ecfg, host_overlap=True),
                       prompts, gspec, **kw)
        assert plain == over

    def test_prefix_cache_hit_and_miss_admissions(self, setup):
        """Paged + prefix cache: the FIRST wave admits as misses, the
        SECOND wave of identical prompts admits through the chunked-hit
        path — both waves byte-identical with overlap on."""
        cfg, params, tok = setup
        prompts = _prompts(tok)[:4]

        def run(overlap):
            ecfg = _ecfg(True, prefix_cache=True, host_overlap=overlap)
            eng = make_engine(cfg, ecfg, params, tok, use_kernel=False)
            first = eng.generate([list(p) for p in prompts],
                                 max_new_tokens=12)
            second = eng.generate([list(p) for p in prompts],
                                  max_new_tokens=12)
            eng.allocator.check()
            hits = eng._counts.get("engine.prefix_hit_tokens", 0)
            return ([r.token_ids for r in first + second], hits)

        (plain, plain_hits), (over, over_hits) = run(False), run(True)
        assert plain == over
        assert over_hits == plain_hits and over_hits > 0

    @pytest.mark.parametrize("paged", [False, True])
    def test_model_draft_matches_plain(self, setup, paged):
        """Draft-MODEL speculation under overlap: the draft scan's
        blocking token fetch stays accounted and greedy output is byte-
        identical to the non-overlapped speculative engine."""
        cfg, params, tok = setup
        prompts = _prompts(tok)[:3]
        ecfg = _ecfg(paged, speculative_k=3, max_batch=2)
        kw = dict(use_kernel=False) if paged else {}

        def run(overlap):
            eng = make_engine(
                cfg, dataclasses.replace(ecfg, host_overlap=overlap),
                params, tok, draft_model=(cfg, params), **kw)
            return [r.token_ids for r in
                    eng.generate([list(p) for p in prompts],
                                 max_new_tokens=12)]

        assert run(False) == run(True)

    @pytest.mark.parametrize("paged", [False, True])
    def test_stop_strings_truncate_identically(self, setup, paged):
        """Stop-string slots ride the lagged commit (post-hoc truncation
        at flush, like the chunked scan): same text, same finish reason,
        no sync fallback required."""
        cfg, params, tok = setup
        prompt = tok.encode("hello", add_bos=True)
        ecfg = _ecfg(paged)
        kw = dict(use_kernel=False) if paged else {}
        free = make_engine(cfg, ecfg, params, tok, **kw).generate(
            [list(prompt)], max_new_tokens=12)[0]
        stop = free.text[2:5]

        def run(overlap):
            eng = make_engine(
                cfg, dataclasses.replace(ecfg, host_overlap=overlap),
                params, tok, **kw)
            return eng.generate([list(prompt)], max_new_tokens=12,
                                stop_strings=(stop,))[0]

        a, b = run(False), run(True)
        assert (a.text, a.token_ids, a.finish_reason) == \
            (b.text, b.token_ids, b.finish_reason)
        assert b.finish_reason == "stop" and stop not in b.text

    def test_snapshot_mid_overlap_restores_in_place(self, setup):
        """cancel/snapshot/restore barrier: snapshotting while tokens are
        in flight flushes them first, so the snapshot is a committed-
        prefix view and the restored run finishes byte-identically."""
        cfg, params, tok = setup
        prompts = _prompts(tok)[:2]
        ecfg = _ecfg(True, host_overlap=True)
        eng = make_engine(cfg, ecfg, params, tok, use_kernel=False)
        want = eng.generate([list(p) for p in prompts], max_new_tokens=12)
        sids = [eng.submit(list(p), max_new_tokens=12) for p in prompts]
        partial = []
        for _ in range(3):
            partial.extend(eng.step())
        snap = eng.snapshot_sequences()
        assert not eng._inflight          # the barrier drained the lag
        for s in snap["sequences"]:
            ref = want[sids.index(s["seq_id"])]
            assert s["generated"] == ref.token_ids[:len(s["generated"])]
        for s in list(snap["sequences"]):
            eng.cancel_seq(s["seq_id"])
        eng.restore_sequences(snap)
        results = list(partial)
        while eng.has_work:
            results.extend(eng.step())
        got = {r.seq_id: r for r in results}
        for sid, ref in zip(sids, want):
            assert got[sid].token_ids == ref.token_ids
        eng.allocator.check()


# ---------------------------------------------------------------------------
# composed meshes (GSPMD over virtual CPU is ~10x slower: marked slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_tp_sharded_overlap_matches_plain(setup, cpu_devices):
    """Serving TP under overlap: TP-sharded params, overlap on vs off,
    byte-identical greedy tokens (contiguous and paged)."""
    from k8s_llm_rca_tpu.runtime.sharding import (
        llama_param_specs, shard_pytree,
    )

    cfg, params, tok = setup
    mesh = build_mesh(MeshConfig(data=2, model=2), devices=cpu_devices[:4])
    sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
    prompts = _prompts(tok)[:3]
    for paged in (False, True):
        ecfg = _ecfg(paged, max_batch=2, max_new_tokens=6)
        kw = dict(use_kernel=False) if paged else {}
        with jax.default_matmul_precision("float32"):
            plain = make_engine(cfg, ecfg, sharded, tok, **kw).generate(
                [list(p) for p in prompts], max_new_tokens=6)
            over = make_engine(
                cfg, dataclasses.replace(ecfg, host_overlap=True),
                sharded, tok, **kw).generate(
                [list(p) for p in prompts], max_new_tokens=6)
        for r, g in zip(plain, over):
            assert r.token_ids == g.token_ids, paged


@pytest.mark.slow
@pytest.mark.skipif(not hasattr(jax, "shard_map"),
                    reason="pipeline stages need jax.shard_map (same "
                           "capability gate as the dryrun's shard_map rows)")
def test_pp_tp_overlap_matches_plain(setup, cpu_devices):
    """PP×TP in one mesh under overlap (the multi-host pod serving
    shape): the fused overlap step routes through the stage-local
    pp_decode_fn and must keep exact greedy parity, both engines."""
    _, _, tok = setup
    cfg = TINY.replace(max_seq_len=128, n_layers=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(stage=2, model=2), devices=cpu_devices[:4])
    prompts = _prompts(tok)[:3]
    for paged in (False, True):
        ecfg = _ecfg(paged, max_batch=2, max_new_tokens=6)
        with jax.default_matmul_precision("float32"):
            plain = make_engine(cfg, ecfg, params, tok, pp_mesh=mesh,
                                tp_mesh=mesh).generate(
                [list(p) for p in prompts], max_new_tokens=6)
            over = make_engine(
                cfg, dataclasses.replace(ecfg, host_overlap=True),
                params, tok, pp_mesh=mesh, tp_mesh=mesh).generate(
                [list(p) for p in prompts], max_new_tokens=6)
        for r, g in zip(plain, over):
            assert r.token_ids == g.token_ids, paged


def test_cp_composition_rejected_loudly(setup, cpu_devices):
    """host_overlap × CP is excluded: CP's multi-process host_np
    collectives must line up SPMD-identically, which a lagged commit
    would reorder — both engines refuse at construction."""
    cfg, params, tok = setup
    mesh = build_mesh(MeshConfig(seq=4), devices=cpu_devices[:4])
    for paged in (False, True):
        ecfg = _ecfg(paged, host_overlap=True)
        kw = dict(use_kernel=False) if paged else {}
        with pytest.raises(ValueError, match="host_overlap"):
            make_engine(cfg, ecfg, params, tok, cp_mesh=mesh, **kw)


# ---------------------------------------------------------------------------
# exact-count regressions (the perf marker suite): h2d / d2h / dispatches
# ---------------------------------------------------------------------------


@pytest.mark.perf
class TestHostTrafficCounters:
    """Fixed scripted workload, exact counter assertions.  The plain
    paged stepwise tick re-uploads all three arrays (3 h2d) and blocks on
    one fetch per tick; overlap must hold h2d at the single initial
    upload and at least halve the sync points for the same tokens."""

    def _counts(self, setup, paged, overlap):
        """4 identical same-bucket prompts into 4 slots: exactly ONE
        batched prefill dispatch, all retirements on the same tick — the
        counter arithmetic below is exact, not approximate."""
        cfg, params, tok = setup
        prompts = [tok.encode("pod crashloop", add_bos=True)] * 4
        _, counts = _run(cfg, params, tok,
                         _ecfg(paged, host_overlap=overlap), prompts,
                         **(dict(use_kernel=False) if paged else {}))
        for k in ("engine.h2d_uploads", "engine.d2h_syncs",
                  "engine.dispatches", "engine.decode_tokens"):
            counts.setdefault(k, 0.0)
        return counts

    def test_paged_exact_counts(self, setup):
        pc = self._counts(setup, True, False)
        oc = self._counts(setup, True, True)
        # same committed work either way
        assert oc["engine.decode_tokens"] == pc["engine.decode_tokens"] > 0
        # plain stepwise: with D decode dispatches after the single
        # prefill, every decode tick blocks on one fetch (D), plus ONE
        # coalesced drain of the deferred admission firsts — and re-
        # uploads all three arrays (3 h2d) per decode tick
        d_plain = pc["engine.dispatches"] - 1
        assert pc["engine.d2h_syncs"] == d_plain + 1
        assert pc["engine.h2d_uploads"] == 3 * d_plain
        # overlap: exactly ONE dirty materialisation of the three arrays
        # (zero steady-state per-tick h2d), and one coalesced fetch per
        # lag-2 flush — exactly half the dispatches
        d_over = oc["engine.dispatches"] - 1
        assert oc["engine.h2d_uploads"] == 3
        assert 2 * oc["engine.d2h_syncs"] == d_over
        # the acceptance ratio: >= 2x fewer sync points per decoded token
        assert 2 * oc["engine.d2h_syncs"] <= pc["engine.d2h_syncs"], (
            oc, pc)

    def test_contiguous_exact_counts(self, setup):
        pc = self._counts(setup, False, False)
        oc = self._counts(setup, False, True)
        assert oc["engine.decode_tokens"] == pc["engine.decode_tokens"] > 0
        # the contiguous engine's arrays are born device-resident: no
        # full-array uploads in either mode on this grammar-free workload
        assert pc["engine.h2d_uploads"] == 0
        assert oc["engine.h2d_uploads"] == 0
        # same sync-point arithmetic as the paged engine
        assert pc["engine.d2h_syncs"] == pc["engine.dispatches"]
        assert 2 * oc["engine.d2h_syncs"] == oc["engine.dispatches"] - 1
        assert 2 * oc["engine.d2h_syncs"] <= pc["engine.d2h_syncs"], (
            oc, pc)

    def test_paged_steady_state_has_zero_h2d(self, setup):
        """Direct steady-state proof: once the resident state is
        materialised, further fast ticks dispatch without ANY h2d upload
        of cur_tokens/lengths/block_tables."""
        cfg, params, tok = setup
        eng = make_engine(cfg, _ecfg(True, host_overlap=True), params,
                          tok, use_kernel=False)
        eng.submit(list(_prompts(tok)[0]), max_new_tokens=12)
        for _ in range(3):                 # admission + state upload
            eng.step()
        h2d0 = eng._counts.get("engine.h2d_uploads", 0)
        disp0 = eng._counts.get("engine.dispatches", 0)
        for _ in range(3):
            eng.step()
        assert eng._counts["engine.dispatches"] > disp0
        assert eng._counts.get("engine.h2d_uploads", 0) == h2d0

    def test_plain_admission_coalesces_first_token_fetch(self, setup):
        """Satellite of the deferred-admission rework: even with
        host_overlap OFF, admission first tokens defer to ONE coalesced
        drain fetch per tick — two admission waves (different buckets)
        in one tick cost one sync, not two."""
        cfg, params, tok = setup
        eng = make_engine(cfg, _ecfg(True), params, tok, use_kernel=False)
        eng.submit(tok.encode("short", add_bos=True), max_new_tokens=4)
        eng.submit(tok.encode(
            "a much longer prompt that lands in the next prefill bucket "
            "by repeating repeating repeating", add_bos=True),
            max_new_tokens=4)
        d2h0 = (eng._counts or {}).get("engine.d2h_syncs", 0)
        eng.step()                         # both admission waves
        prefills = eng._counts.get("engine.dispatches", 0)
        assert prefills >= 2               # two separate prefill buckets
        assert eng._counts.get("engine.d2h_syncs", 0) - d2h0 <= 2
        list(eng.run_to_completion())
        eng.allocator.check()
