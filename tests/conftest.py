"""Hermetic test harness: force an 8-virtual-device CPU platform before the
JAX backend initializes, so mesh/collective/sharding logic is exercised
without TPUs (SURVEY.md §4's prescription).  Bench/serve on the real chip use
the default platform instead."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _with_host_device_count  # noqa: E402

os.environ["XLA_FLAGS"] = _with_host_device_count(
    os.environ.get("XLA_FLAGS", ""), 8)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = [d for d in jax.devices() if d.platform == "cpu"]
    assert len(devs) >= 8, f"expected 8 virtual cpu devices, got {len(devs)}"
    return devs
