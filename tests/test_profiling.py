"""Profiling/observability tests (CPU: MFU math, timers, memory stats
shape; trace capture is exercised for the no-crash property only)."""

import os

import pytest

from k8s_llm_rca_tpu.config import LLAMA3_8B, MIXTRAL_8X7B, TINY
from k8s_llm_rca_tpu.runtime import profiling


class TestFlopsModel:
    def test_param_count_llama3_8b(self):
        # public number: ~8.03B parameters
        n = profiling.decoder_param_count(LLAMA3_8B)
        assert 7.9e9 < n < 8.2e9, n

    def test_param_count_mixtral(self):
        # public number: ~46.7B total parameters
        n = profiling.decoder_param_count(MIXTRAL_8X7B)
        assert 45e9 < n < 48e9, n

    def test_decode_flops_scale_with_context(self):
        f1 = profiling.decode_flops_per_token(TINY, 128)
        f2 = profiling.decode_flops_per_token(TINY, 1024)
        assert f2 > f1
        # dense ~2*params FLOPs/token dominates at short context
        params = profiling.decoder_param_count(TINY)
        assert f1 == pytest.approx(2 * params, rel=0.35)

    def test_moe_flops_count_topk_not_all_experts(self):
        dense_equiv = MIXTRAL_8X7B.replace(n_experts=0)
        moe = profiling.decode_flops_per_token(MIXTRAL_8X7B, 128)
        dense = profiling.decode_flops_per_token(dense_equiv, 128)
        # top-2 of 8 experts ~= 2x one dense MLP, not 8x
        assert moe < 3 * dense

    def test_mfu_none_on_cpu(self):
        assert profiling.mfu(TINY, 1000.0, 128) is None  # tests run on CPU


class _FakeV5e:
    device_kind = "TPU v5 lite"


class _FakeV5p:
    device_kind = "TPU v5"


class TestRoofline:
    def test_none_on_cpu(self):
        assert profiling.roofline_decode_tps(TINY, 128, 8) is None

    def test_prefix_disambiguation(self):
        # "TPU v5" must not pick up the v5e ("TPU v5 lite") row or
        # vice versa: v5p has both higher peak and higher bandwidth,
        # so its roofline strictly dominates at identical config
        a = profiling.roofline_decode_tps(TINY, 128, 8, device=_FakeV5e())
        b = profiling.roofline_decode_tps(TINY, 128, 8, device=_FakeV5p())
        assert a is not None and b is not None and b > a

    def test_memory_bound_at_small_batch(self):
        # batch 1 streams ~the full weights per token (layer matmuls plus
        # ONE vocab table — the untied input embedding is a gather, not a
        # stream, so bytes land slightly under 2*param_count bf16 bytes)
        bpt = profiling.decode_bytes_per_token(LLAMA3_8B, 128, 1, 16, 16)
        full = profiling.decoder_param_count(LLAMA3_8B) * 2
        assert 0.8 * full < bpt < 1.02 * full

    def test_batch_amortizes_weight_traffic(self):
        b1 = profiling.decode_bytes_per_token(TINY, 128, 1, 16, 16)
        b64 = profiling.decode_bytes_per_token(TINY, 128, 64, 16, 16)
        assert b64 < b1 / 8            # weights dominate at short context

    def test_quantization_raises_roofline(self):
        bf16 = profiling.roofline_decode_tps(TINY, 896, 512, 16, 16,
                                             device=_FakeV5e())
        int4 = profiling.roofline_decode_tps(TINY, 896, 512, 4, 4,
                                             device=_FakeV5e())
        # int4 shrinks bytes; at batch 512 the compute leg caps both, so
        # int4 is >= bf16 but cannot exceed the compute ceiling
        compute = 197e12 / profiling.decode_flops_per_token(TINY, 896)
        assert bf16 <= int4 <= compute * 1.001

    def test_bench_config_roofline_is_finite_and_physical(self):
        # the r2 bench wall-clock (208k tok/s TinyLlama int4) must cap
        from k8s_llm_rca_tpu.config import MODEL_REGISTRY

        cfg = MODEL_REGISTRY["tinyllama-1.1b"]
        roof = profiling.roofline_decode_tps(cfg, 896, 512, 4, 4,
                                             device=_FakeV5e())
        assert 10_000 < roof < 208_000, roof


class TestStepTimer:
    def test_tokens_per_sec_and_report(self):
        t = profiling.StepTimer()
        t.start()
        for _ in range(5):
            t.tick(8)
        rep = t.report(TINY, context_len=128)
        assert rep["steps"] == 5 and rep["tokens"] == 40
        assert rep["tokens_per_sec"] > 0
        assert "mfu" in rep                  # None on CPU, key present


class TestTraceAndMemory:
    def test_memory_stats_shape(self):
        stats = profiling.device_memory_stats()
        assert isinstance(stats, dict)
        for v in stats.values():
            assert isinstance(v, float)

    def test_trace_capture_writes_files(self, tmp_path):
        import jax
        import jax.numpy as jnp

        if not hasattr(jax.profiler, "ProfileOptions"):
            pytest.skip("jax.profiler.ProfileOptions unavailable on this "
                        "jax (capability gate, not a regression)")
        d = str(tmp_path / "trace")
        with profiling.trace(d):
            with profiling.annotate("test.region"):
                (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
        # plugins/profile/<ts>/*.xplane.pb must exist
        found = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
        assert any(f.endswith(".xplane.pb") for f in found), found


class TestStageLocalCpVsTp:
    def test_tp_dominates_cp_below_the_gqa_limit(self):
        """The PP×CP exclusion's quantitative basis (docs/parallelism.md
        "a quantified no"): for n_intra <= n_kv_heads, spending a
        pipeline stage's intra-stage devices on TP beats CP on BOTH
        per-device decode FLOPs and HBM bytes at every context length —
        CP divides only the attention/KV terms while TP divides the
        matmul/weight terms too."""
        from k8s_llm_rca_tpu.config import LLAMA3_8B, TINYLLAMA_1B

        for cfg in (LLAMA3_8B, TINYLLAMA_1B):
            for n_intra in (2, 4, 8):
                if n_intra > cfg.n_kv_heads:
                    continue
                for s in (1024, 4096, 32768, 131072):
                    r = profiling.stage_local_cp_vs_tp(
                        cfg, s, batch=16, n_intra=n_intra,
                        weight_bits=4, kv_bits=4)
                    assert r["flops_cp_over_tp"] > 1.0, (cfg.name, s)
                    assert r["bytes_cp_over_tp"] > 1.0, (cfg.name, s)

    def test_cp_wins_kv_bytes_past_the_gqa_limit(self):
        """The model is honest about CP's genuine regime: past the GQA
        limit (n_intra > n_kv_heads) at long context, TP's KV stream
        replicates across the devices sharing a kv head while CP keeps
        dividing it — so CP wins on HBM bytes there (the case served by
        the non-PP CP×TP composition, docs/parallelism.md)."""
        from k8s_llm_rca_tpu.config import TINYLLAMA_1B

        assert TINYLLAMA_1B.n_kv_heads == 4
        r = profiling.stage_local_cp_vs_tp(TINYLLAMA_1B, 131072, batch=16,
                                           n_intra=8)
        assert r["bytes_cp_over_tp"] < 1.0, r
        # ... while matmul-replication still costs CP the FLOP axis
        assert r["flops_cp_over_tp"] > 1.0, r

    def test_ratio_shrinks_with_context_but_never_crosses(self):
        """CP's relative loss shrinks as attention dominates (its only
        asymptotic argument) yet stays >1 even at 1M tokens — the
        crossover never happens because weights are still streamed per
        seq shard."""
        from k8s_llm_rca_tpu.config import LLAMA3_8B

        prev = None
        for s in (4096, 65536, 1048576):
            r = profiling.stage_local_cp_vs_tp(LLAMA3_8B, s, batch=16,
                                               n_intra=4)
            if prev is not None:
                assert r["flops_cp_over_tp"] < prev
            assert r["flops_cp_over_tp"] > 1.0
            prev = r["flops_cp_over_tp"]
