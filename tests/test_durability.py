"""Crash-safe serving: WAL codec, run journal, recovery replay, engine
sequence snapshot/restore, and the supervised kill/restart chaos proof.

The durability layer's contract (docs/durability.md): every mutation the
service acknowledged is on disk before the acknowledgement (fsync'd WAL
append), a crash at ANY byte offset leaves a journal whose intact prefix
replays to the exact pre-crash store, settled runs are never re-executed,
and interrupted runs are re-queued for a fresh prefill whose greedy output
is byte-identical to the never-interrupted run.  Everything here is
deterministic: greedy decode, seeded fault plans, virtual clocks.

The disarmed path is load-bearing too: a service built without a journal
must do ZERO journal work — asserted by monkeypatching the whole journal
surface to raise and driving every run path.
"""

import json
import os
import re

import jax
import pytest

from k8s_llm_rca_tpu.config import TINY, EngineConfig
from k8s_llm_rca_tpu.engine import make_engine
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.faults.plan import Fault, FaultPlan
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.serve.api import AssistantService, RunStatus
from k8s_llm_rca_tpu.serve.backend import (
    BudgetError, EchoBackend, EngineBackend, GenOptions,
)
from k8s_llm_rca_tpu.serve.journal import (
    RunJournal, decode_gen, encode_gen, read_journal,
)
from k8s_llm_rca_tpu.serve.recover import recover_service
from k8s_llm_rca_tpu.sweeps.run_file import scan_output
from k8s_llm_rca_tpu.utils import wal
from k8s_llm_rca_tpu.utils.logging import METRICS
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer


@pytest.fixture(autouse=True)
def _disarmed():
    """Never leak an armed plan into other tests."""
    yield
    if inject.active() is not None:
        inject.disarm()


@pytest.fixture(scope="module")
def tiny_engine():
    """One TINY paged engine shared by the engine-path durability tests
    (greedy decode: outputs depend only on weights/prompts, same rationale
    as test_faults.shared_engine)."""
    cfg = TINY.replace(max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    eng = make_engine(
        cfg, EngineConfig(max_batch=4, max_seq_len=64, paged=True,
                          page_size=8, num_pages=24,
                          prefill_buckets=(16, 32), max_new_tokens=8,
                          temperature=0.0, decode_chunk=1,
                          prefix_cache=False),
        params, tok, use_kernel=False)
    return eng, tok


# ---------------------------------------------------------------------------
# WAL codec: framing, torn tails, corruption
# ---------------------------------------------------------------------------


class TestWal:
    def test_roundtrip_and_clean_end(self, tmp_path):
        path = str(tmp_path / "w.wal")
        payloads = [b"alpha", b"", b'{"k":1}' * 40]
        with open(path, "ab") as f:
            for p in payloads:
                wal.append_record(f, p)
        got, end = wal.scan_wal(path)
        assert got == payloads
        assert end == os.path.getsize(path)

    def test_torn_tail_recovers_prefix_and_truncates_atomically(
            self, tmp_path):
        path = str(tmp_path / "w.wal")
        with open(path, "ab") as f:
            wal.append_record(f, b"one")
            wal.append_record(f, b"two")
        clean_size = os.path.getsize(path)
        # the crash artifact: a frame cut mid-write
        with open(path, "ab") as f:
            f.write(wal.pack_record(b"torn-away")[:-3])
        got, end = wal.scan_wal(path)
        assert got == [b"one", b"two"] and end == clean_size
        # still un-truncated without the flag
        assert os.path.getsize(path) > clean_size
        got2, _ = wal.scan_wal(path, truncate_partial=True)
        assert got2 == [b"one", b"two"]
        assert os.path.getsize(path) == clean_size
        assert not os.path.exists(path + ".tmp")   # replaced, not left over
        # the truncated file appends cleanly at a record boundary
        with open(path, "ab") as f:
            wal.append_record(f, b"three")
        assert wal.scan_wal(path)[0] == [b"one", b"two", b"three"]

    def test_corrupt_checksum_stops_the_reader(self, tmp_path):
        path = str(tmp_path / "w.wal")
        with open(path, "ab") as f:
            wal.append_record(f, b"good")
            wal.append_record(f, b"flipped")
            wal.append_record(f, b"unreachable")
        data = bytearray(open(path, "rb").read())
        # flip one payload byte of record 2; everything after is suspect
        off = wal.HEADER_SIZE + 4 + wal.HEADER_SIZE
        data[off] ^= 0xFF
        open(path, "wb").write(bytes(data))
        got, end = wal.scan_wal(path)
        assert got == [b"good"]
        assert end == wal.HEADER_SIZE + 4

    def test_garbage_length_field_is_torn_tail_not_record(self, tmp_path):
        path = str(tmp_path / "w.wal")
        with open(path, "ab") as f:
            wal.append_record(f, b"real")
            f.write(wal._HEADER.pack(wal.MAX_RECORD_SIZE + 5, 0))
            f.flush()
        got, _ = wal.scan_wal(path)
        assert got == [b"real"]

    def test_oversized_record_rejected_at_write_time(self):
        with pytest.raises(ValueError, match="MAX_RECORD_SIZE"):
            wal.pack_record(b"x" * (wal.MAX_RECORD_SIZE + 1))

    def test_missing_and_empty_files(self, tmp_path):
        assert wal.scan_wal(str(tmp_path / "absent.wal")) == ([], 0)
        empty = tmp_path / "empty.wal"
        empty.touch()
        assert wal.scan_wal(str(empty), truncate_partial=True) == ([], 0)


# ---------------------------------------------------------------------------
# run journal: record codec + reopen discipline
# ---------------------------------------------------------------------------


class TestRunJournal:
    def test_append_read_roundtrip(self, tmp_path):
        path = str(tmp_path / "serve.wal")
        with RunJournal(path) as j:
            j.append("create_thread", id="thread_00000000")
            j.append("add_message", thread_id="thread_00000000",
                     id="msg_00000001", role="user", content="pod down",
                     created_at=12.5)
            assert j.appended == 2 and j.bytes_written > 0
        records, end = read_journal(path)
        assert [r["kind"] for r in records] == ["create_thread",
                                                "add_message"]
        assert records[1]["content"] == "pod down"
        assert end == os.path.getsize(path)

    def test_reopen_drops_torn_tail_then_appends(self, tmp_path):
        path = str(tmp_path / "serve.wal")
        with RunJournal(path) as j:
            j.append("create_thread", id="t_0")
        with open(path, "ab") as f:      # the crash artifact
            f.write(b"\x00\x00\x00\x07garbage-without-checksum")
        with RunJournal(path) as j:      # open truncates, then appends
            j.append("create_thread", id="t_1")
        records, end = read_journal(path)
        assert [r["id"] for r in records] == ["t_0", "t_1"]
        assert end == os.path.getsize(path)

    def test_gen_options_roundtrip_specs_only(self):
        gen = GenOptions(max_new_tokens=9, stop=("```",), forced_prefix="p",
                         suffix="s", grammar={"type": "object"},
                         assistant_name="a")
        assert decode_gen(encode_gen(gen)) == gen
        assert encode_gen(None) is None and decode_gen(None) is None

        class CompiledFsm:
            pass

        with pytest.raises(ValueError, match="spec"):
            encode_gen(GenOptions(grammar=CompiledFsm()))


# ---------------------------------------------------------------------------
# sweep output partial-tail tolerance (the layer of record above the WAL)
# ---------------------------------------------------------------------------


def _sweep_record(msg):
    return json.dumps({"error_message": msg, "analysis": []},
                      indent=4) + "\n"


class TestScanOutputPartialTail:
    def test_crash_tail_dropped_atomically_completed_survive(self, tmp_path):
        out = tmp_path / "rca.json"
        out.write_text(_sweep_record("a") + _sweep_record("b")
                       + '{\n    "error_message": "c", "anal')
        # without the flag: completed records found, file untouched
        msgs, end = scan_output(str(out))
        assert msgs == ["a", "b"]
        assert "anal" in out.read_text()
        # with the flag: tail gone, completed records byte-intact
        msgs, end2 = scan_output(str(out), truncate_partial=True)
        assert msgs == ["a", "b"] and end2 == end
        text = out.read_text()
        assert "c" not in text
        assert not os.path.exists(str(out) + ".tmp")
        # the truncated file is append-ready: a resumed sweep record parses
        with open(out, "a") as f:
            f.write(_sweep_record("c"))
        assert scan_output(str(out))[0] == ["a", "b", "c"]

    def test_empty_and_missing_files(self, tmp_path):
        assert scan_output(str(tmp_path / "absent.json")) == ([], 0)
        empty = tmp_path / "empty.json"
        empty.write_text("")
        assert scan_output(str(empty), truncate_partial=True) == ([], 0)

    def test_whitespace_only_tail_is_not_a_crash_artifact(self, tmp_path):
        out = tmp_path / "rca.json"
        out.write_text(_sweep_record("a") + "\n   \n")
        before = out.read_text()
        msgs, _ = scan_output(str(out), truncate_partial=True)
        assert msgs == ["a"]
        assert out.read_text() == before   # no pointless rewrite


# ---------------------------------------------------------------------------
# service journaling hooks + the disarmed path
# ---------------------------------------------------------------------------


def _drive_lifecycle(service, text="pod crashloop", wait=True):
    a = service.create_assistant("test", "t")
    th = service.create_thread()
    service.add_message(th.id, text)
    run = service.create_run(th.id, a.id,
                             gen=GenOptions(max_new_tokens=8))
    if wait:
        run = service.wait_run(run.id)
    return a, th, run


class TestServiceJournaling:
    def test_full_lifecycle_is_journaled(self, tmp_path):
        path = str(tmp_path / "serve.wal")
        tok = get_tokenizer()
        service = AssistantService(EchoBackend(tok, reply="the answer"),
                                   journal=RunJournal(path))
        _, _, run = _drive_lifecycle(service)
        assert run.status == RunStatus.COMPLETED
        service._journal.close()
        records, _ = read_journal(path)
        assert [r["kind"] for r in records] == [
            "create_assistant", "create_thread", "add_message",
            "run_submit", "run_settle"]
        submit, settle = records[3], records[4]
        assert submit["id"] == run.id
        assert "<|assistant|>" in submit["prompt"]   # the RENDERED prompt
        assert settle["status"] == RunStatus.COMPLETED
        assert settle["response"]["content"] == "the answer"

    def test_cancel_and_expiry_are_journaled_settles(self, tmp_path):
        path = str(tmp_path / "serve.wal")
        tok = get_tokenizer()
        service = AssistantService(EchoBackend(tok, delay_pumps=10 ** 9),
                                   journal=RunJournal(path))
        _, _, r_cancel = _drive_lifecycle(service, wait=False)
        service.cancel_run(r_cancel.id)
        _, _, r_expire = _drive_lifecycle(service, wait=False)
        got = service.wait_run(r_expire.id, timeout_s=0.0)
        assert got.status == RunStatus.EXPIRED
        service._journal.close()
        settles = {r["id"]: r for r in read_journal(path)[0]
                   if r["kind"] == "run_settle"}
        assert settles[r_cancel.id]["status"] == RunStatus.CANCELLED
        assert settles[r_expire.id]["status"] == RunStatus.EXPIRED
        assert settles[r_expire.id]["response"] is None

    def test_cancel_after_settle_is_a_noop(self, tmp_path):
        """A terminal run re-cancelled: no state change, no extra settle
        record (the journal must carry exactly one terminal transition)."""
        path = str(tmp_path / "serve.wal")
        tok = get_tokenizer()
        journal = RunJournal(path)
        service = AssistantService(EchoBackend(tok, reply="done"),
                                   journal=journal)
        _, _, run = _drive_lifecycle(service)
        assert run.status == RunStatus.COMPLETED
        appended = journal.appended
        got = service.cancel_run(run.id)
        assert got.status == RunStatus.COMPLETED    # not flipped
        assert journal.appended == appended         # nothing re-journaled
        journal.close()
        settles = [r for r in read_journal(path)[0]
                   if r["kind"] == "run_settle"]
        assert len(settles) == 1

    def test_disarmed_path_does_zero_journal_work(self, monkeypatch):
        """The inertness proof: with no journal configured, the whole
        journal surface is unreachable.  Every entry point is patched to
        raise; every run path (complete, cancel, expire) must still work."""
        import k8s_llm_rca_tpu.serve.journal as journal_mod

        def boom(*a, **k):
            raise AssertionError("journal I/O on the default path")

        monkeypatch.setattr(journal_mod.RunJournal, "__init__", boom)
        monkeypatch.setattr(journal_mod.RunJournal, "append", boom)
        monkeypatch.setattr(wal, "append_record", boom)
        tok = get_tokenizer()
        service = AssistantService(EchoBackend(tok, reply="ok"))
        _, _, run = _drive_lifecycle(service)
        assert run.status == RunStatus.COMPLETED
        slow = AssistantService(EchoBackend(tok, delay_pumps=10 ** 9))
        _, _, r_cancel = _drive_lifecycle(slow, wait=False)
        assert service.cancel_run is not None
        slow.cancel_run(r_cancel.id)
        _, _, r_expire = _drive_lifecycle(slow, wait=False)
        got = slow.wait_run(r_expire.id, timeout_s=0.0)
        assert got.status == RunStatus.EXPIRED


# ---------------------------------------------------------------------------
# recovery replay (echo backend)
# ---------------------------------------------------------------------------


class TestRecovery:
    def _crashed_journal(self, tmp_path, delay_pumps=10 ** 9):
        """Build a journaled service, leave one run in flight, 'crash'."""
        path = str(tmp_path / "serve.wal")
        tok = get_tokenizer()
        service = AssistantService(EchoBackend(tok, delay_pumps=delay_pumps),
                                   journal=RunJournal(path))
        a, th, run = _drive_lifecycle(service, wait=False)
        service._journal.close()         # process death
        return path, tok, service, run

    def test_interrupted_run_is_resubmitted_and_completes(self, tmp_path):
        path, tok, _, run = self._crashed_journal(tmp_path)
        svc, report = recover_service(path, EchoBackend(tok, reply="after"))
        assert report["resubmitted"] == [run.id]
        assert report["interrupted"] == 1
        assert svc.runs[run.id].status == RunStatus.IN_PROGRESS
        got = svc.wait_run(run.id)
        assert got.status == RunStatus.COMPLETED
        msgs = svc.list_messages(svc.runs[run.id].thread_id)
        assert msgs.data[0].raw_content == "after"

    def test_settled_run_replayed_not_reexecuted(self, tmp_path):
        path = str(tmp_path / "serve.wal")
        tok = get_tokenizer()
        service = AssistantService(EchoBackend(tok, reply="first answer"),
                                   journal=RunJournal(path))
        _, th, run = _drive_lifecycle(service)
        service._journal.close()

        class NeverStarts(EchoBackend):
            def start(self, prompt, opts):
                raise AssertionError("settled run re-executed")

        svc, report = recover_service(path, NeverStarts(tok))
        assert report["resubmitted"] == []
        got = svc.runs[run.id]
        assert got.status == RunStatus.COMPLETED
        assert got.usage == run.usage
        # the journaled response message is back in the thread
        texts = [m.raw_content for m in svc.threads[th.id].messages]
        assert "first answer" in texts

    def test_cancelled_before_crash_stays_cancelled(self, tmp_path):
        """Satellite: journal and recovery must agree on cancellation —
        a run cancelled pre-crash is NOT resurrected by replay."""
        path = str(tmp_path / "serve.wal")
        tok = get_tokenizer()
        service = AssistantService(EchoBackend(tok, delay_pumps=10 ** 9),
                                   journal=RunJournal(path))
        _, _, r_cancelled = _drive_lifecycle(service, wait=False)
        service.cancel_run(r_cancelled.id)
        _, _, r_inflight = _drive_lifecycle(service, wait=False)
        service._journal.close()
        svc, report = recover_service(path, EchoBackend(tok))
        assert svc.runs[r_cancelled.id].status == RunStatus.CANCELLED
        assert report["resubmitted"] == [r_inflight.id]
        assert svc.wait_run(r_inflight.id).status == RunStatus.COMPLETED

    def test_expired_before_crash_stays_expired(self, tmp_path):
        path = str(tmp_path / "serve.wal")
        tok = get_tokenizer()
        service = AssistantService(EchoBackend(tok, delay_pumps=10 ** 9),
                                   journal=RunJournal(path))
        _, _, run = _drive_lifecycle(service, wait=False)
        service.wait_run(run.id, timeout_s=0.0)
        service._journal.close()
        svc, report = recover_service(path, EchoBackend(tok))
        assert svc.runs[run.id].status == RunStatus.EXPIRED
        assert report["resubmitted"] == []

    def test_reconciliation_against_sweep_output(self, tmp_path):
        """An interrupted run whose incident is already durable in the
        sweep output is cancelled, not re-run (the output file is the
        layer of record above the journal)."""
        path, tok, _, run = self._crashed_journal(tmp_path)
        out = tmp_path / "rca.json"
        out.write_text(_sweep_record("pod crashloop"))
        svc, report = recover_service(path, EchoBackend(tok),
                                      sweep_output=str(out))
        assert report["reconciled"] == [run.id]
        assert report["resubmitted"] == []
        got = svc.runs[run.id]
        assert got.status == RunStatus.CANCELLED
        assert "already durable" in got.error

    def test_budget_rejected_resubmission_fails_the_run(self, tmp_path):
        path, tok, _, run = self._crashed_journal(tmp_path)

        class Shrunk(EchoBackend):
            def start(self, prompt, opts):
                raise BudgetError("prompt over the recovery budget")

        svc, report = recover_service(path, Shrunk(tok))
        assert report["failed_resubmit"] == [run.id]
        got = svc.runs[run.id]
        assert got.status == RunStatus.FAILED
        assert "resubmit rejected" in got.error

    def test_id_counter_resumes_past_journaled_ids(self, tmp_path):
        path, tok, service, run = self._crashed_journal(tmp_path)
        svc, _ = recover_service(path, EchoBackend(tok))
        top = max(int(m.group(1))
                  for r in read_journal(path)[0]
                  for m in [re.search(r"_(\d+)$", str(r.get("id", "")))]
                  if m)
        fresh = svc.create_thread()
        assert int(re.search(r"_(\d+)$", fresh.id).group(1)) > top

    def test_unknown_record_kind_refuses_to_replay(self, tmp_path):
        path = str(tmp_path / "serve.wal")
        with RunJournal(path) as j:
            j.append("frobnicate", id="x_1")
        with pytest.raises(ValueError, match="unknown journal record"):
            recover_service(path, EchoBackend(get_tokenizer()))


# ---------------------------------------------------------------------------
# engine sequence snapshot / restore
# ---------------------------------------------------------------------------


class TestEngineSnapshotRestore:
    def test_mid_decode_snapshot_restores_with_greedy_parity(
            self, tiny_engine):
        """The exact-resume proof at the engine layer: snapshot after a
        few decode ticks, abandon the device KV (cancel), restore, finish
        — tokens byte-identical to the never-interrupted run."""
        eng, tok = tiny_engine
        ids = [list(tok.encode(p, add_bos=True))
               for p in ("pod crashloop kube-system", "node disk pressure")]
        want = eng.generate([list(i) for i in ids], max_new_tokens=8)

        seq_ids = [eng.submit(list(i), max_new_tokens=8) for i in ids]
        partial = []
        for _ in range(3):
            partial.extend(eng.step())
        snap = eng.snapshot_sequences()
        by_id = {s["seq_id"]: s for s in snap["sequences"]}
        assert set(by_id) <= set(seq_ids)
        # snapshotted progress is a greedy prefix of the final output
        for sid, ref in zip(seq_ids, want):
            if sid in by_id:
                gen = by_id[sid]["generated"]
                assert gen == ref.token_ids[:len(gen)]
                assert by_id[sid]["prompt_ids"] == list(
                    ids[seq_ids.index(sid)])
        # the crash: device KV dies with the process
        for sid in list(by_id):
            eng.cancel_seq(sid)
        assert not eng.has_work
        eng.allocator.check()

        restored = eng.restore_sequences(snap)
        assert restored == sorted(by_id)
        results = list(partial)
        while eng.has_work:
            results.extend(eng.step())
        got = {r.seq_id: r for r in results}
        for sid, ref in zip(seq_ids, want):
            assert got[sid].token_ids == ref.token_ids
            assert got[sid].prompt_tokens == ref.prompt_tokens
            assert got[sid].text == ref.text
        eng.allocator.check()
        assert eng.allocator.n_free == eng.engine_cfg.num_pages - 1
        assert not eng._resumed                    # stitching bookkeeping drained

    def test_restore_collision_and_cap_overflow_fail_loudly(
            self, tiny_engine):
        eng, tok = tiny_engine
        ids = list(tok.encode("api server timeout", add_bos=True))
        sid = eng.submit(list(ids), max_new_tokens=4)
        snap = eng.snapshot_sequences()
        with pytest.raises(ValueError, match="collision"):
            eng.restore_sequences(snap)
        eng.cancel_seq(sid)
        assert not eng.has_work
        over = {"rng_key": [0, 0], "sequences": [{
            "seq_id": 10 ** 6, "prompt_ids": list(range(40)),
            "generated": list(range(30)), "remaining_new_tokens": 4,
            "stop_strings": [], "grammar": False}]}
        with pytest.raises(ValueError, match="max_seq_len"):
            eng.restore_sequences(over)

    def test_mid_overlap_snapshot_restores_into_fresh_engine(self):
        """Device-resident follow-through (docs/performance.md): with the
        overlapped hot loop on, tokens live in flight between flushes —
        ``snapshot_sequences`` must barrier them into host state first,
        and the snapshot must restore into a FRESH overlapped engine
        (new device arrays, cold resident mirrors) with greedy output
        byte-identical to the never-interrupted run."""
        cfg = TINY.replace(max_seq_len=64)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        ecfg = EngineConfig(max_batch=4, max_seq_len=64, paged=True,
                            page_size=8, num_pages=24,
                            prefill_buckets=(16, 32), max_new_tokens=8,
                            temperature=0.0, decode_chunk=1,
                            prefix_cache=False, host_overlap=True)

        def fresh():
            return make_engine(cfg, ecfg, params, tok, use_kernel=False)

        ids = [list(tok.encode(p, add_bos=True))
               for p in ("pod crashloop kube-system", "node disk pressure")]
        want = fresh().generate([list(i) for i in ids], max_new_tokens=8)

        crash = fresh()
        sids = [crash.submit(list(i), max_new_tokens=8) for i in ids]
        partial = []
        for _ in range(3):                 # mid-overlap: lag in flight
            partial.extend(crash.step())
        snap = crash.snapshot_sequences()
        assert not crash._inflight         # barrier drained the lag
        by_id = {s["seq_id"]: s for s in snap["sequences"]}
        for sid, ref in zip(sids, want):
            if sid in by_id:               # committed-prefix view only
                gen = by_id[sid]["generated"]
                assert gen == ref.token_ids[:len(gen)]
        # the crash: this engine's device state (including the resident
        # mirrors and any in-flight dispatches) dies with the process
        resume = fresh()
        resume.restore_sequences(snap)
        results = list(partial)
        while resume.has_work:
            results.extend(resume.step())
        got = {r.seq_id: r for r in results}
        for sid, ref in zip(sids, want):
            assert got[sid].token_ids == ref.token_ids
            assert got[sid].text == ref.text
        resume.allocator.check()

    def test_restore_requires_fresh_fsm_for_grammar_sequences(
            self, tiny_engine):
        eng, _ = tiny_engine
        snap = {"rng_key": [0, 0], "sequences": [{
            "seq_id": 10 ** 6 + 1, "prompt_ids": [1, 2, 3],
            "generated": [], "remaining_new_tokens": 4,
            "stop_strings": [], "grammar": True}]}
        with pytest.raises(ValueError, match="grammar-constrained"):
            eng.restore_sequences(snap)
        assert not eng.has_work                    # nothing half-admitted

    def test_tick_crash_fault_preserves_greedy_output(self, tiny_engine):
        """The paged engine's 'crash' tick fault: every active sequence
        loses its device KV and requeues — output must not change."""
        eng, tok = tiny_engine
        ids = [list(tok.encode(p, add_bos=True))
               for p in ("pvc not bound storageclass", "dns nxdomain")]
        want = eng.generate([list(i) for i in ids], max_new_tokens=8)
        pre = METRICS.count("engine.crash_evictions")
        plan = FaultPlan([Fault(inject.SITE_ENGINE_TICK, 2, "crash")])
        with inject.armed(plan):
            got = eng.generate([list(i) for i in ids], max_new_tokens=8)
        assert [r.token_ids for r in got] == [r.token_ids for r in want]
        assert METRICS.count("engine.crash_evictions") > pre
        assert len(plan.fired) == 1
        eng.allocator.check()
        assert eng.allocator.n_free == eng.engine_cfg.num_pages - 1


# ---------------------------------------------------------------------------
# serve-level resume on the real engine
# ---------------------------------------------------------------------------


class TestServeEngineResume:
    def test_recovered_run_matches_uninterrupted_engine_run(
            self, tmp_path, tiny_engine):
        """End-to-end exact resume: journaled run interrupted mid-decode,
        backend torn down (engine slots cancelled, like a worker kill),
        recovery resubmits the journaled prompt onto a fresh backend —
        the completed reply is byte-identical to a never-interrupted run
        of the same prompt (greedy re-prefill parity)."""
        eng, tok = tiny_engine
        # the never-interrupted reference
        ref_svc = AssistantService(EngineBackend(eng))
        _, ref_th, ref_run = _drive_lifecycle(ref_svc)
        assert ref_run.status == RunStatus.COMPLETED
        ref_text = ref_svc.list_messages(ref_th.id).data[0].raw_content

        path = str(tmp_path / "serve.wal")
        backend = EngineBackend(eng)
        service = AssistantService(backend, journal=RunJournal(path))
        _, _, run = _drive_lifecycle(service, wait=False)
        service.retrieve_run(run.id)     # pump: prefill + some decode
        assert service.runs[run.id].status == RunStatus.IN_PROGRESS
        # the crash: journal handle and engine sequences die
        service._journal.close()
        for handle in list(backend._live):
            backend.cancel(handle)
        assert not eng.has_work

        svc, report = recover_service(path, EngineBackend(eng))
        assert report["resubmitted"] == [run.id]
        got = svc.wait_run(run.id)
        assert got.status == RunStatus.COMPLETED
        got_text = svc.list_messages(got.thread_id).data[0].raw_content
        assert got_text == ref_text
        eng.allocator.check()
        assert eng.allocator.n_free == eng.engine_cfg.num_pages - 1


# ---------------------------------------------------------------------------
# supervised kill/restart chaos proof
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestKillRestartChaos:
    def test_mid_sweep_crash_report_byte_identical(self, tmp_path):
        """The acceptance bar: a chaos soak killed and journal-recovered
        mid-sweep produces a report byte-identical to the uninterrupted
        same-seed run.  The supervisor polls its OWN plan, so the armed
        plan's fault schedule — and therefore the report — is untouched
        by the crash."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak
        from k8s_llm_rca_tpu.faults.supervisor import CrashSupervisor

        base = run_chaos_soak(seed=5, n_incidents=3, backend="oracle")
        sup = CrashSupervisor(
            FaultPlan([Fault(inject.SITE_PROCESS, 1, "crash")]),
            str(tmp_path / "serve.wal"))
        resumed = run_chaos_soak(seed=5, n_incidents=3, backend="oracle",
                                 durable_dir=str(tmp_path), supervisor=sup)
        assert sup.crashes == 1
        assert len(sup.recoveries) == 1
        assert sup.recoveries[0]["records"] > 0
        assert report_bytes(base) == report_bytes(resumed)
        assert resumed["failed"] == 0 and resumed["completed"] == 3
        # the journal survived the whole soak: it replays cleanly
        records, end = read_journal(str(tmp_path / "serve.wal"))
        assert records and end == os.path.getsize(
            str(tmp_path / "serve.wal"))

    def test_supervisor_requires_durable_dir(self):
        from k8s_llm_rca_tpu.faults.soak import run_chaos_soak
        from k8s_llm_rca_tpu.faults.supervisor import CrashSupervisor

        sup = CrashSupervisor(FaultPlan(), "/tmp/never-used.wal")
        with pytest.raises(ValueError, match="durable_dir"):
            run_chaos_soak(seed=0, n_incidents=1, backend="oracle",
                           supervisor=sup)

    def test_journaled_soak_report_matches_unjournaled(self, tmp_path):
        """Arming the journal alone (no supervisor) must not perturb the
        report: journaling adds no report fields and no clock reads."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak

        plain = run_chaos_soak(seed=7, n_incidents=2, backend="oracle")
        journaled = run_chaos_soak(seed=7, n_incidents=2, backend="oracle",
                                   durable_dir=str(tmp_path))
        assert report_bytes(plain) == report_bytes(journaled)
        assert os.path.getsize(str(tmp_path / "serve.wal")) > 0
