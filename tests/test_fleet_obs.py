"""Fleet flight-recorder tests (obs/ + cluster/proc.py telemetry seam).

Layers, cheapest first:

- **units** (no subprocess): TelemetryRing drop-oldest bounds + shed
  accounting, propagation-context shape, the extended
  ``validate_chrome_trace`` (per-pid track metadata, flow pairing with
  the unpaired flow id named loudly), critical-path decomposition on
  hand-built trees (priority waterfall, relink synthesis, exact integer
  residual), and the ``{replica=}`` Prometheus aggregation.
- **one-worker fleets** (real spawns, ~0.5 s each): span propagation
  over BOTH transports — worker ``cluster.proc.serve`` spans parent
  onto the parent's ``cluster.proc.rpc`` spans and ride the parent's
  virtual timebase; untraced fleets ship nothing; SIGKILL loses at most
  the unshipped tail; a partitioned link never carries a drain RPC.
- **acceptance bars**: one RCA sweep on a 1P+1D socket disagg fleet
  yields a single merged Chrome trace (per-incarnation pid tracks,
  paired handoff flows across tier tracks, validator-clean,
  byte-identical per seed under VirtualClock); the seeded 100-incident
  proc-cluster SIGKILL soak settles ``report_bytes`` — and every
  ``faults.polls`` counter — byte-identical with telemetry on vs off.
"""

from __future__ import annotations

import pytest

from k8s_llm_rca_tpu.cluster.proc import build_proc_replicas
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.faults.plan import FaultPlan, VirtualClock
from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak
from k8s_llm_rca_tpu.obs import (
    SEGMENTS, TelemetryRing, Tracer, chrome_trace, chrome_trace_bytes,
    critical_path, critical_path_stats, prometheus_text,
    validate_chrome_trace,
)
from k8s_llm_rca_tpu.obs import trace as obs_trace
from k8s_llm_rca_tpu.serve.backend import GenOptions

pytestmark = pytest.mark.fleetobs


def _drive_one(transport="pipe", trace=True, pumps=20):
    """One traced oracle worker through start -> settle -> close;
    returns (tracer, replica) with the replica already closed."""
    tr = Tracer(clock=VirtualClock())
    with obs_trace.tracing(tr):
        (rep,) = build_proc_replicas(
            1, kind="oracle", transport=transport,
            **({"trace": True} if trace else {}))
        try:
            h = rep.backend.start("node notready", GenOptions())
            for _ in range(pumps):
                if h in rep.backend.pump():
                    break
        finally:
            rep.close()
    return tr, rep


# ---------------------------------------------------------------------------
# units: bounded ring + propagation context
# ---------------------------------------------------------------------------


class TestTelemetryRing:
    def test_overflow_drops_oldest_and_counts_shed(self):
        ring = TelemetryRing(capacity=4)
        for i in range(10):
            ring.push({"i": i})
        assert len(ring) == 4
        assert ring.shed == 6
        # the NEWEST pre-overflow items survive (post-SIGKILL, the last
        # thing the worker did is the valuable part)
        assert [it["i"] for it in ring.pop(10)] == [6, 7, 8, 9]
        assert len(ring) == 0

    def test_pop_respects_budget_in_fifo_order(self):
        ring = TelemetryRing(capacity=8)
        for i in range(5):
            ring.push({"i": i})
        assert [it["i"] for it in ring.pop(2)] == [0, 1]
        assert [it["i"] for it in ring.pop(10)] == [2, 3, 4]
        assert ring.shed == 0

    def test_capacity_validated_loudly(self):
        with pytest.raises(ValueError, match="capacity"):
            TelemetryRing(capacity=0)


class TestPropagationContext:
    def test_context_carries_trace_id_parent_and_clock(self):
        clock = VirtualClock()
        tr = Tracer(clock=clock, trace_id=7)
        clock.sleep(1.5)
        with tr.span("cluster.proc.rpc", cat="cluster") as sp:
            ctx = tr.context()
            assert ctx == {"id": 7, "parent": sp.span_id, "ts": 1.5}
        # outside any span the parent is None (root attachment)
        assert tr.context()["parent"] is None

    def test_ingest_remote_buckets_by_incarnation(self):
        tr = Tracer()
        item = {"k": "span", "name": "cluster.proc.serve",
                "cat": "cluster", "span_id": 1, "parent_id": None,
                "t0": 0.0, "t1": 0.0, "tid": 1, "args": {}}
        assert tr.ingest_remote(0, 0, {"pid": 10, "items": [item],
                                       "shed": 0}) == 1
        assert tr.ingest_remote(0, 1, {"pid": 11, "items": [item],
                                       "shed": 3}) == 1
        # a respawn is a NEW bucket — never merged into the corpse's
        assert sorted(tr.remote) == [(0, 0), (0, 1)]
        assert tr.remote[(0, 1)]["shed"] == 3
        assert "cluster.proc.serve" in tr.emitted_names()


# ---------------------------------------------------------------------------
# units: validator (flow pairing + per-pid track metadata)
# ---------------------------------------------------------------------------


def _mini_fleet_doc():
    tr = Tracer(clock=VirtualClock())
    with tr.span("serve.run", cat="serve", run="r-1"):
        pass
    tr.ingest_remote(0, 0, {"pid": 4242, "items": [
        {"k": "span", "name": "cluster.proc.serve", "cat": "cluster",
         "span_id": 1, "parent_id": None, "t0": 0.0, "t1": 0.0,
         "tid": 1, "args": {"op": "pump"}}], "shed": 0})
    return chrome_trace(tr)


class TestValidator:
    def test_fleet_doc_validates_and_names_worker_track(self):
        doc = _mini_fleet_doc()
        assert validate_chrome_trace(doc) == len(doc["traceEvents"])
        tracks = [e for e in doc["traceEvents"]
                  if e["ph"] == "M" and e["name"] == "process_name"]
        # deterministic Chrome pid (2 + bucket ordinal), NOT the OS pid
        assert [(e["pid"], e["args"]["name"]) for e in tracks] == \
            [(2, "0/2/0")]

    def test_unpaired_flow_id_named_loudly(self):
        doc = _mini_fleet_doc()
        doc["traceEvents"].append(
            {"name": "cluster.handoff", "cat": "handoff", "ph": "s",
             "ts": 10 ** 9, "pid": 2, "tid": 0, "id": 7, "bp": "e",
             "args": {}})
        with pytest.raises(ValueError, match="unpaired flow id 7"):
            validate_chrome_trace(doc)

    def test_finish_without_start_rejected(self):
        doc = _mini_fleet_doc()
        doc["traceEvents"].append(
            {"name": "cluster.handoff", "cat": "handoff", "ph": "f",
             "ts": 10 ** 9, "pid": 2, "tid": 0, "id": 9, "bp": "e",
             "args": {}})
        with pytest.raises(ValueError, match="unpaired flow id 9"):
            validate_chrome_trace(doc)

    def test_unnamed_worker_pid_rejected(self):
        doc = _mini_fleet_doc()
        doc["traceEvents"] = [e for e in doc["traceEvents"]
                              if not (e["ph"] == "M"
                                      and e["name"] == "process_name")]
        with pytest.raises(ValueError, match="process_name"):
            validate_chrome_trace(doc)


# ---------------------------------------------------------------------------
# units: prometheus {replica=} aggregation of shipped worker counters
# ---------------------------------------------------------------------------


class TestPrometheusFleet:
    def test_worker_counters_render_with_replica_label(self):
        tr = Tracer()
        tr.ingest_remote(0, 0, {"pid": 10, "items": [], "shed": 0,
                                "counters": {"serve.runs": 2.0,
                                             "rpc.total_s": 1.0,
                                             "rpc.count": 4,
                                             "rpc.p50_s": 0.25}})
        # a respawned incarnation's counters SUM into the same replica
        tr.ingest_remote(0, 1, {"pid": 11, "items": [], "shed": 0,
                                "counters": {"serve.runs": 3.0}})
        text = prometheus_text(tracer=tr)
        assert 'k8s_llm_rca_serve_runs_total{replica="0"} 5' in text
        # timer-derived snapshot keys are not counters — skipped
        assert "rpc_total_s" not in text and "rpc_p50_s" not in text

    def test_no_fleet_means_no_replica_lines(self):
        text = prometheus_text(tracer=Tracer())
        assert 'replica="' not in text


# ---------------------------------------------------------------------------
# one-worker fleets: propagation + shipping over both transports
# ---------------------------------------------------------------------------


class TestFleetPropagation:
    @pytest.mark.parametrize("transport", ["pipe", "socket"])
    def test_worker_spans_parent_onto_rpc_context(self, transport):
        tr, rep = _drive_one(transport=transport)
        assert (0, 0) in tr.remote
        serve = [s for s in tr.remote[(0, 0)]["spans"]
                 if s["name"] == "cluster.proc.serve"]
        assert serve
        # causal link: every shipped serve span parents onto one of the
        # parent tracer's rpc spans — one tree across both processes
        rpc_ids = {s.span_id for s in tr.spans
                   if s.name == "cluster.proc.rpc"}
        assert {s["parent_id"] for s in serve} <= rpc_ids
        assert {s["args"]["op"] for s in serve} >= {"start", "pump"}
        # worker stamps ride the parent's (virtual) timebase, not the
        # worker's wall clock
        assert max(s["t0"] for s in serve) <= tr.now()
        assert {"cluster.telemetry.ship", "cluster.telemetry.drain"} \
            <= tr.emitted_names()
        assert rep.backend.telemetry_frames > 0
        assert rep.backend.telemetry_items >= len(serve)

    def test_untraced_fleet_ships_nothing(self):
        tr, rep = _drive_one(trace=False)
        assert not tr.remote
        assert not rep.backend.telemetry
        assert rep.backend.telemetry_frames == 0
        assert not ({"cluster.telemetry.ship", "cluster.telemetry.drain",
                     "cluster.proc.serve"} & tr.emitted_names())

    def test_telemetry_without_parent_tracer_is_harmless(self):
        # worker records + ships, parent has no tracer to ingest into:
        # payloads are dropped on the floor, nothing raises, nothing
        # leaks into a later-activated tracer
        (rep,) = build_proc_replicas(1, kind="oracle", trace=True)
        try:
            h = rep.backend.start("node notready", GenOptions())
            for _ in range(20):
                if h in rep.backend.pump():
                    break
        finally:
            rep.close()
        assert rep.backend.telemetry_items == 0


class TestSigkillDrain:
    def test_sigkill_loses_at_most_the_unshipped_tail(self):
        tr = Tracer(clock=VirtualClock())
        with obs_trace.tracing(tr):
            (rep,) = build_proc_replicas(1, kind="oracle", trace=True)
            try:
                rep.backend.start("node notready", GenOptions())
                rep.backend.pump()
                shipped = rep.backend.telemetry_items
                assert shipped > 0
                rep.backend.kill()
                # dead process: the drain short-circuits on liveness
                # evidence instead of timing out on a corpse's pipe
                assert rep.backend.drain_telemetry() == 0
            finally:
                rep.close()
        # everything shipped before the SIGKILL survives in the parent
        bucket = tr.remote[(0, 0)]
        retained = (len(bucket["spans"]) + len(bucket["events"])
                    + len(bucket["ticks"]))
        assert retained == shipped

    def test_partitioned_link_carries_no_drain_rpc(self):
        tr = Tracer(clock=VirtualClock())
        with obs_trace.tracing(tr):
            (rep,) = build_proc_replicas(1, kind="oracle",
                                         transport="socket", trace=True)
            try:
                h = rep.backend.start("node notready", GenOptions())
                for _ in range(20):
                    if h in rep.backend.pump():
                        break
                rep.partition_link()
                # link down, process alive: no RPC is attempted, so the
                # drain can never poison the link evidence
                assert rep.backend.drain_telemetry() == 0
                assert rep.backend.relink()
                # healed link ships again (the drain op's own serve
                # span rides its reply at minimum)
                assert rep.backend.drain_telemetry() > 0
            finally:
                rep.close()


# ---------------------------------------------------------------------------
# acceptance: 1P+1D socket disagg fleet -> one merged golden trace
# ---------------------------------------------------------------------------


class TestMergedFleetTrace:
    def _disagg_tracer(self):
        tr = Tracer()
        report = run_chaos_soak(seed=5, n_incidents=2,
                                backend="disagg-cluster",
                                cluster_replicas=2, tier_split=(1, 1),
                                tracer=tr, fleet_telemetry=True)
        assert report["failed"] == 0
        return tr

    def test_single_merged_trace_with_flows_golden(self):
        tr = self._disagg_tracer()
        doc = chrome_trace(tr)
        assert validate_chrome_trace(doc) > 0
        events = doc["traceEvents"]
        # one pid track per worker incarnation, deterministically named
        tracks = sorted(e["args"]["name"] for e in events
                        if e["ph"] == "M" and e["name"] == "process_name")
        assert tracks == ["0/2/0", "1/3/0"]
        assert doc["metadata"]["fleet"]["workers"] == 2
        # handoff flows pair up ACROSS the tier tracks: every committed
        # EXPORT->ADOPT->RELEASE draws one s (prefill pid) -> f (decode
        # pid) arc
        starts = {e["id"]: e for e in events if e["ph"] == "s"}
        finishes = {e["id"]: e for e in events if e["ph"] == "f"}
        assert starts and sorted(starts) == sorted(finishes)
        for fid, s_ev in starts.items():
            assert s_ev["pid"] != finishes[fid]["pid"]
        # causally linked: both workers' serve spans parent onto the
        # parent tracer's rpc spans
        rpc_ids = {s.span_id for s in tr.spans
                   if s.name == "cluster.proc.rpc"}
        for bucket in tr.remote.values():
            serve = [s for s in bucket["spans"]
                     if s["name"] == "cluster.proc.serve"]
            assert serve
            assert {s["parent_id"] for s in serve} <= rpc_ids
        # byte-identical per seed under the frozen VirtualClock — the
        # second fleet has different OS pids, same trace bytes
        again = chrome_trace_bytes(chrome_trace(self._disagg_tracer()))
        assert chrome_trace_bytes(doc) == again

    def test_critical_path_covers_every_settled_run(self):
        tr = self._disagg_tracer()
        rows = critical_path(tr)
        assert rows
        for row in rows.values():
            assert sum(row["segments_us"].values()) == row["total_us"]
            assert set(row["segments_us"]) == set(SEGMENTS)


# ---------------------------------------------------------------------------
# acceptance: telemetry changes no fault draws (SIGKILL soak identity)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestSoakTelemetryIdentity:
    def test_100_incident_sigkill_soak_identical_on_vs_off(self):
        """The flight recorder must be a pure observer: same seeds, same
        kills, same polls, same report BYTES whether or not the fleet is
        shipping telemetry — shipping rides reply frames and ops that
        poll no fault sites."""
        from k8s_llm_rca_tpu.faults.supervisor import ProcKiller

        def killer():
            return ProcKiller(FaultPlan.from_spec(
                2, {inject.SITE_PROC: {"rate": 0.03, "horizon": 100,
                                       "kinds": ("crash",)}}))

        k_off = killer()
        off = run_chaos_soak(seed=11, n_incidents=100,
                             backend="proc-cluster", cluster_replicas=4,
                             killer=k_off, selfheal=True)
        k_on = killer()
        on = run_chaos_soak(seed=11, n_incidents=100,
                            backend="proc-cluster", cluster_replicas=4,
                            killer=k_on, selfheal=True,
                            fleet_telemetry=True)
        assert k_off.kills                     # SIGKILLs actually landed
        assert k_on.kills == k_off.kills       # same kill schedule
        assert on["faults"]["polls"] == off["faults"]["polls"]
        assert report_bytes(on) == report_bytes(off)

    def test_fleet_telemetry_refused_off_proc_backends(self):
        with pytest.raises(ValueError, match="fleet_telemetry"):
            run_chaos_soak(n_incidents=1, backend="cluster-oracle",
                           fleet_telemetry=True)


# ---------------------------------------------------------------------------
# critical path: decomposition units + serve surface
# ---------------------------------------------------------------------------


class TestCriticalPath:
    def _run_span(self, tr, t0, t1, run="r-1"):
        tr.add_span("serve.run", t0, t1, cat="serve",
                    args={"run": run, "status": "completed"})

    def test_segments_sum_exactly_with_priority_waterfall(self):
        clock = VirtualClock()
        tr = Tracer(clock=clock)
        with tr.span("cluster.handoff.export", cat="handoff"):
            clock.sleep(0.010)
            # rpc INSIDE the export window: export (outermost actionable
            # cause) takes the overlap, wire gets nothing here
            with tr.span("cluster.proc.rpc", cat="cluster"):
                clock.sleep(0.005)
        with tr.span("cluster.proc.rpc", cat="cluster"):
            clock.sleep(0.007)
        clock.sleep(0.003)                    # unattributed -> queue_wait
        self._run_span(tr, 0.0, clock.time())
        row = critical_path(tr)["r-1"]
        assert row["total_us"] == 25000
        segs = row["segments_us"]
        assert segs["cp.handoff.export"] == 15000
        assert segs["cp.wire"] == 7000
        assert segs["cp.queue_wait"] == 3000
        assert sum(segs.values()) == row["total_us"]

    def test_relink_outage_synthesized_and_retries_counted(self):
        clock = VirtualClock()
        tr = Tracer(clock=clock)
        tr.event("cluster.net.partition", replica=0)
        clock.sleep(0.020)
        tr.event("cluster.net.relink", replica=0)
        tr.event("resilience.retry", dep="graph.meta")
        clock.sleep(0.004)
        self._run_span(tr, 0.0, clock.time())
        row = critical_path(tr)["r-1"]
        assert row["segments_us"]["cp.relink"] == 20000
        assert row["segments_us"]["cp.queue_wait"] == 4000
        assert row["retries"] == 1
        assert sum(row["segments_us"].values()) == row["total_us"]

    def test_window_clipping_and_run_filter(self):
        clock = VirtualClock()
        tr = Tracer(clock=clock)
        # a prefill span straddling the run's start is clipped to the
        # overlap, never attributed outside the window
        with tr.span("engine.prefill", cat="engine"):
            clock.sleep(0.010)
        clock.sleep(0.002)
        self._run_span(tr, 0.005, clock.time(), run="r-a")
        self._run_span(tr, 0.005, clock.time(), run="r-b")
        rows = critical_path(tr, runs={"r-a"})
        assert set(rows) == {"r-a"}
        segs = rows["r-a"]["segments_us"]
        assert segs["cp.prefill"] == 5000
        assert segs["cp.queue_wait"] == 2000

    def test_stats_aggregate_and_empty_tracer(self):
        clock = VirtualClock()
        tr = Tracer(clock=clock)
        with tr.span("engine.decode_step", cat="engine"):
            clock.sleep(0.006)
        self._run_span(tr, 0.0, clock.time())
        stats = critical_path_stats(tr)
        assert stats["runs"] == 1
        assert stats["end_to_end_us"] == 6000
        assert stats["total_us"]["cp.decode"] == 6000
        assert critical_path_stats(Tracer()) == {"runs": 0}

    def test_usage_for_runs_exposes_critical_path(self):
        from k8s_llm_rca_tpu.serve.api import AssistantService, RunStatus
        from k8s_llm_rca_tpu.serve.backend import EchoBackend
        from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

        tr = Tracer(clock=VirtualClock())
        with obs_trace.tracing(tr):
            svc = AssistantService(EchoBackend(get_tokenizer()))
            a = svc.create_assistant("inst", "cp")
            t = svc.create_thread()
            svc.add_message(t.id, "node notready")
            run = svc.create_run(t.id, a.id)
            assert svc.wait_run(run.id).status == RunStatus.COMPLETED
            usage = svc.usage_for_runs([run.id], critical_path=True)
            assert run.id in usage["critical_path"]
            row = usage["critical_path"][run.id]
            assert sum(row["segments_us"].values()) == row["total_us"]
            # the default surface is unchanged (report_bytes safety)
            assert "critical_path" not in svc.usage_for_runs([run.id])
