"""Graph-layer tests: the mini-Cypher interpreter against the exact query
shapes the RCA pipeline emits (reference query inventory, SURVEY §2)."""

import pytest

from k8s_llm_rca_tpu.graph import (
    CypherSyntaxError, Graph, InMemoryGraphExecutor, Path, Record,
)
from k8s_llm_rca_tpu.graph.fixtures import (
    INCIDENTS, TS_EVENT, build_metagraph, build_stategraph,
)


@pytest.fixture(scope="module")
def meta():
    return InMemoryGraphExecutor(build_metagraph())


@pytest.fixture(scope="module")
def state():
    return InMemoryGraphExecutor(build_stategraph())


def test_kind_vocabulary(meta):
    """Reference find_native_external_kinds query shape (:63-72)."""
    records = meta.run_query("""
        MATCH (n1)
        WHERE n1.category IN ['NativeEntity', 'ExternalEntity']
        RETURN n1.category AS category, n1.kind AS kind
        """)
    native = sorted(r["kind"] for r in records if r["category"] == "NativeEntity")
    external = sorted(r["kind"] for r in records if r["category"] == "ExternalEntity")
    assert "Pod" in native and "ResourceQuota" in native
    assert external == ["container", "hostPath", "image", "nfs"]
    assert "Event" not in native + external


def test_srckind_discovery(state):
    """Reference find_srcKind query shape (:75-90): two MATCHes, WITH carry,
    param CONTAINS, distinct + limit."""
    records = state.run_query("""
        MATCH (n1:Event)-[s1:HasEvent]->(N1:EVENT)
        WHERE N1.message contains $message
        WITH n1, N1, s1
        MATCH (n1:Event)-[r1:ReferInternal]->(n2)
        WHERE r1.key = 'involvedObject_uid'
        RETURN distinct n2.kind2
        LIMIT 5;
        """, {"message": "secret \"es-account-token\" not found"})
    assert records[0]["n2.kind2"] == "Pod"
    records = state.run_query("""
        MATCH (n1:Event)-[s1:HasEvent]->(N1:EVENT)
        WHERE N1.message contains $message
        WITH n1, N1, s1
        MATCH (n1:Event)-[r1:ReferInternal]->(n2)
        WHERE r1.key = 'involvedObject_uid'
        RETURN distinct n2.kind2
        LIMIT 5;
        """, {"message": "exceeded quota: compute-resources-team1"})
    assert records[0]["n2.kind2"] == "CronJob"


METAPATH_DIRECTED = """
    MATCH path = (n1)-[*1..3]->(n2)
    WHERE n1.kind = $srcKind and n2.kind = $destKind
    AND all(node in nodes(path) WHERE single(x in nodes(path) WHERE x = node))
    AND all(node in nodes(path) WHERE not node.kind in ['Event', 'Namespace'])
    AND ($intermediateKinds IS NULL
        OR size($intermediateKinds) = 0
        OR any(node in nodes(path)[1..-1] WHERE node.kind in $intermediateKinds))
    RETURN path
    """

METAPATH_UNDIRECTED = METAPATH_DIRECTED.replace("-[*1..3]->", "-[*1..3]-")


def test_metapath_directed(meta):
    records = meta.run_query(METAPATH_DIRECTED, {
        "srcKind": "Pod", "destKind": "Secret", "intermediateKinds": []})
    assert len(records) == 1
    path = records[0]["path"]
    assert isinstance(path, Path) and len(path) == 1
    assert [n["kind"] for n in path.nodes] == ["Pod", "Secret"]


def test_metapath_directed_fails_against_flow(meta):
    """Pod->nfs requires traversing PV->PVC against the arrow: the directed
    rung must return nothing (this is what drives the reference to rung 2)."""
    records = meta.run_query(METAPATH_DIRECTED, {
        "srcKind": "Pod", "destKind": "nfs",
        "intermediateKinds": ["PersistentVolumeClaim", "PersistentVolume"]})
    # directed route Pod->container-... does not reach nfs
    for r in records:
        kinds = [n["kind"] for n in r["path"].nodes]
        assert "nfs" != kinds[-1] or False, f"unexpected directed path {kinds}"
    assert records == []


def test_metapath_undirected_pod_nfs(meta):
    records = meta.run_query(METAPATH_UNDIRECTED, {
        "srcKind": "Pod", "destKind": "nfs",
        "intermediateKinds": ["PersistentVolumeClaim", "PersistentVolume"]})
    kinds = {tuple(n["kind"] for n in r["path"].nodes) for r in records}
    assert ("Pod", "PersistentVolumeClaim", "PersistentVolume", "nfs") in kinds


def test_metapath_namespace_rung(meta):
    """Rung 4: explicit src-Namespace-dest two-hop (reference :125-129)."""
    records = meta.run_query("""
        MATCH path = (n1)-[r1]-(n2)-[r2]-(n3)
        WHERE n1.kind = $srcKind and n2.kind = 'Namespace' and n3.kind = $destKind
        RETURN path
        """, {"srcKind": "CronJob", "destKind": "ResourceQuota"})
    assert len(records) == 1
    assert [n["kind"] for n in records[0]["path"].nodes] == [
        "CronJob", "Namespace", "ResourceQuota"]
    # ...and the directed/undirected rungs exclude Namespace, so they miss it
    assert meta.run_query(METAPATH_UNDIRECTED, {
        "srcKind": "CronJob", "destKind": "ResourceQuota",
        "intermediateKinds": []}) == []


def test_generated_query_shape(state):
    """The LLM/deterministic-compiler query shape (reference
    generate_query.py:195-207): EVENT filter + chained MATCH + interleaved
    RETURN."""
    msg = INCIDENTS[0].message
    records = state.run_query(f"""
        MATCH (evt:EVENT)
        WHERE evt.message CONTAINS {msg!r}
        WITH evt
        LIMIT 1
        MATCH (event:Event)-[r1:HasEvent]->(evt)
        WHERE r1.key = 'metadata_uid'
        MATCH (event)-[r2:ReferInternal]->(pod:Pod)
        WHERE r2.key = 'involvedObject_uid'
        MATCH (pod)-[r3:ReferInternal]->(secret:Secret)
        WHERE r3.key = 'spec_volumes_secret_secretName'
        RETURN event, r1, evt, r2, pod, r3, secret
        """)
    assert len(records) == 2            # real secret + decoy
    rec = records[0]
    assert len(rec) == 7
    # positional access + kind probing, as message_compatible does
    names = {rec[len(rec) - 1]["name2"] for rec in records}
    assert names == {"es-account-token", "other-secret"}
    # record iteration yields values
    kinds = [e["kind"] for e in rec if hasattr(e, "labels")]
    assert "Event" in kinds


def test_strict_state_query(state):
    """Temporal point-in-interval lookup (reference analyze_root_cause:70-79),
    half-open [tmin, tmax)."""
    q = f"""
    MATCH (n1:ResourceQuota)-[r1:HasState]->(n2:RESOURCEQUOTA)
    WHERE n1.id = 'rq-0001'
    AND r1.tmin <= '{TS_EVENT}' AND r1.tmax > '{TS_EVENT}'
    RETURN n2
    LIMIT 10;
    """
    records = state.run_query(q)
    assert len(records) == 1
    assert "used" in records[0]["n2"]["status"]
    # timestamp exactly at tmax is excluded (right-open)
    q2 = q.replace(TS_EVENT, "2020-12-11 07:00:00.000")
    assert state.run_query(q2) == []
    # missing STATE: the es-account-token secret has none
    q3 = """
    MATCH (n1:Secret)-[r1:HasState]->(n2:SECRET)
    WHERE n1.id = 'sec-0001'
    RETURN n2 LIMIT 10;
    """
    assert state.run_query(q3) == []


def test_adhoc_entity_name(state):
    """lowercase keywords (reference analyze_root_cause:200-207)."""
    records = state.run_query("""
    match (n1:Secret)
    where n1.id = 'sec-0001'
    return n1
    limit 1
    """)
    assert records[0]["n1"]["name2"] == "es-account-token"


def test_syntax_errors_raise():
    g = InMemoryGraphExecutor(Graph())
    with pytest.raises(CypherSyntaxError):
        g.run_query("MATCH (n RETURN n")
    with pytest.raises(CypherSyntaxError):
        g.run_query("FROB (n) RETURN n")
    with pytest.raises(CypherSyntaxError):
        g.run_query("MATCH (n)")          # no RETURN
    with pytest.raises(CypherSyntaxError):
        g.run_query("MATCH (n) RETURN unknownVar")


def test_dump_roundtrip(tmp_path):
    g = build_stategraph()
    p = str(tmp_path / "state.json")
    g.save(p)
    g2 = InMemoryGraphExecutor.from_dump_file(p)
    records = g2.run_query(
        "MATCH (n:Pod) RETURN n.name2 ORDER BY n.name2")
    assert [r[0] for r in records] == ["es-gen-pod", "es-pod-0", "redis-0"]


def test_relationship_trail_uniqueness(meta):
    """A relationship may appear once per match: no infinite/degenerate
    paths bouncing over one edge."""
    records = meta.run_query("""
        MATCH path = (n1)-[*1..3]-(n2)
        WHERE n1.kind = 'Secret' and n2.kind = 'Secret'
        RETURN path
        """)
    assert records == []   # would require reusing the single Pod-Secret edge
