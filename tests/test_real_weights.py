"""Real-weights readiness path (VERDICT r1 item 7).

Every semantic path in this repo is otherwise validated against random
weights or the scripted oracle — fine for mechanics, silent on whether
real checkpoints load and produce usable stage output.  This module is the
gated bridge: set ``K8S_RCA_WEIGHTS`` to a directory holding TinyLlama(-
compatible) HF safetensors + tokenizer files and these tests load them
through models/loader.py + utils/tokenizer.HFTokenizer, run one REAL
incident end-to-end on the engine, and check the stage-1 plan names a
kind from the metagraph vocabulary (guaranteed by the schema grammar) —
with real weights the content should also be sensible, which is what a
human inspects in the printed report.

Skipped (not failed) when the env var is unset — the zero-egress CI image
has no checkpoints.  Usage:

    K8S_RCA_WEIGHTS=/ckpts/tinyllama-1.1b-chat \\
        python -m pytest tests/test_real_weights.py -s

The directory must contain ``*.safetensors`` (HF Llama layout) and HF
tokenizer files (tokenizer.json or tokenizer.model).  Mirrors the
reference's implicit dependency on a capable model (reference
find_metapath/find_srckind_metapath_neo4j.py:20-45) — made explicit,
local, and testable.
"""

import os

import pytest

WEIGHTS = os.environ.get("K8S_RCA_WEIGHTS")

pytestmark = pytest.mark.skipif(
    not WEIGHTS, reason="K8S_RCA_WEIGHTS not set (real-checkpoint test)")


@pytest.fixture(scope="module")
def real_stack():
    from k8s_llm_rca_tpu.config import MODEL_REGISTRY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.models.loader import load_llama
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = MODEL_REGISTRY["tinyllama-1.1b"]
    params = load_llama(cfg, WEIGHTS)
    tokenizer = get_tokenizer(WEIGHTS)
    engine = make_engine(
        cfg,
        EngineConfig(max_batch=4, max_seq_len=2048,
                     prefill_buckets=(512, 1024, 2048),
                     max_new_tokens=512, temperature=0.0),
        params, tokenizer)
    return cfg, engine, tokenizer


def test_weights_load_and_decode_text(real_stack):
    """The checkpoint loads, the HF tokenizer round-trips, and greedy
    decode emits non-degenerate text."""
    _, engine, tok = real_stack
    ids = tok.encode("Kubernetes is", add_bos=True)
    (res,) = engine.generate([ids], max_new_tokens=16)
    text = res.text
    assert len(res.token_ids) > 0
    assert text.strip(), f"degenerate output: {text!r}"


def test_real_incident_end_to_end(real_stack):
    """One real incident through the full pipeline on real weights: the
    stage-1 plan must name kinds from the metagraph vocabulary and the
    incident must complete with the batch-driver schema."""
    from k8s_llm_rca_tpu.config import RCAConfig
    from k8s_llm_rca_tpu.graph import InMemoryGraphExecutor
    from k8s_llm_rca_tpu.graph.fixtures import (
        INCIDENTS, build_metagraph, build_stategraph,
    )
    from k8s_llm_rca_tpu.rca import RCAPipeline
    from k8s_llm_rca_tpu.rca.locator import find_native_external_kinds
    from k8s_llm_rca_tpu.serve.api import AssistantService
    from k8s_llm_rca_tpu.serve.backend import EngineBackend

    _, engine, _ = real_stack
    meta = InMemoryGraphExecutor(build_metagraph())
    pipeline = RCAPipeline(
        AssistantService(EngineBackend(engine)), meta,
        InMemoryGraphExecutor(build_stategraph()), RCAConfig())

    result = pipeline.analyze_incident(INCIDENTS[0].message)

    native, external = find_native_external_kinds(meta)
    vocabulary = set(native) | set(external)
    # re-extract the stage-1 plan from the locator thread to inspect it
    from k8s_llm_rca_tpu.utils.fenced import extract_json

    reply = pipeline.locator.get_last_k_message(1).data[0] \
        .content[0].text.value
    plan = extract_json(reply)       # the production fence parser
    assert plan["DestinationKind"] in vocabulary
    assert all(r in vocabulary for r in plan["RelevantResources"])

    assert result["locator_attempts"] == 1
    assert result["time_cost"] > 0
    for analysis in result["analysis"]:
        for audited in analysis["statepath"]:
            assert isinstance(audited["report"], str)
    print("\n=== real-weights RCA report(s) ===")
    for analysis in result["analysis"]:
        for audited in analysis["statepath"]:
            print(audited["report"][:2000])
