"""Hermetic CONTENT-level validation: distill the oracle into TINY, then
run the RCA pipeline through the real engine with grammars OFF.

Every other e2e path either uses the scripted oracle directly or leans on
grammar-constrained decode to keep a random-weight model's output
structurally valid; `tests/test_real_weights.py` stays skipped in this
zero-egress image.  This test closes the content gap with zero external
weights: the model itself must produce the correct plan (right
DestinationKind), a working Cypher query (no deterministic fallback), and
a parseable scored report — tokenize -> train (engine/train.py on a mesh)
-> Orbax checkpoint (utils/checkpoint.py) -> safetensors export ->
models/loader.py reload -> serve (engine + assistants service) -> RCA.

SURVEY §4's deterministic-small-model prescription, upgraded from
"scripted backend" to "trained weights through the full serving stack".
"""

import json

import jax
import numpy as np

from k8s_llm_rca_tpu.config import TINY, EngineConfig, MeshConfig, RCAConfig
from k8s_llm_rca_tpu.engine.engine import InferenceEngine
from k8s_llm_rca_tpu.graph import InMemoryGraphExecutor
from k8s_llm_rca_tpu.graph.fixtures import (
    INCIDENTS, build_metagraph, build_stategraph,
)
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.models.loader import (
    llama_params_to_hf, load_llama, write_safetensors,
)
from k8s_llm_rca_tpu.rca.distill import (
    build_rows, collect_transcripts, distill,
)
from k8s_llm_rca_tpu.rca.pipeline import RCAPipeline
from k8s_llm_rca_tpu.runtime.mesh import build_mesh
from k8s_llm_rca_tpu.serve.api import AssistantService
from k8s_llm_rca_tpu.serve.backend import EngineBackend
from k8s_llm_rca_tpu.utils.checkpoint import restore_params, save_params
from k8s_llm_rca_tpu.utils.tokenizer import BPETokenizer


def test_distill_oracle_into_tiny_end_to_end(tmp_path, cpu_devices):
    incident = INCIDENTS[0]                       # secret-not-found
    # the SERVING config, used for recording too so the recorded prompts
    # and GenOptions equal the serving-time ones verbatim: fresh threads,
    # reference-serial audits, grammars OFF
    rca_cfg = RCAConfig(fresh_threads=True, concurrent_audits=False,
                        constrained=False, locator_max_new_tokens=256,
                        cypher_max_new_tokens=256,
                        analyzer_max_new_tokens=256)

    # 1. transcripts from the oracle-backed pipeline
    pairs = collect_transcripts(rca_cfg, incidents=[incident])
    assert len(pairs) >= 4                        # plan/cypher/audit/report

    # 2. in-tree BPE trained on the transcript corpus (save/load roundtrip)
    corpus = [t.prompt + t.opts.forced_prefix + t.body for t in pairs]
    bpe = BPETokenizer.train(corpus, vocab_size=2048)
    bpe.save(str(tmp_path / "bpe.json"))
    bpe = BPETokenizer.load(str(tmp_path / "bpe.json"))

    # 3. training rows rendered EXACTLY as the engine will see them
    cfg = TINY.replace(vocab_size=2048, max_seq_len=1024)
    ecfg = EngineConfig(max_batch=4, max_seq_len=1024,
                        prefill_buckets=(256, 512, 1024),
                        max_new_tokens=256, temperature=0.0,
                        decode_chunk=16)
    clamp_eng = InferenceEngine(
        cfg, ecfg, llama.init_params(cfg, jax.random.PRNGKey(0)), bpe)
    rows, masks = build_rows(pairs, bpe, clamp_eng._clamp_prompt, 1024)

    # 4. fine-tune on a DP mesh until teacher-forced exact match == 1.0
    # (which implies greedy decode reproduces every target verbatim)
    mesh = build_mesh(MeshConfig(data=2), devices=cpu_devices[:2])
    params, match, steps = distill(cfg, rows, masks, mesh, max_steps=600,
                                   batch=4, lr=3e-3, eval_every=50)
    assert match == 1.0, f"distill failed to memorize after {steps} steps"

    # 5. Orbax checkpoint -> restore -> HF-interchange safetensors export
    # -> models/loader reload (the full weight lifecycle, zero egress)
    save_params(str(tmp_path / "orbax"), jax.tree.map(np.asarray, params))
    restored = restore_params(str(tmp_path / "orbax"))
    write_safetensors(str(tmp_path / "model.safetensors"),
                      llama_params_to_hf(cfg, restored))
    served = load_llama(cfg, str(tmp_path / "model.safetensors"))

    # 6. serve through the real engine, grammars OFF
    engine = InferenceEngine(cfg, ecfg, served, bpe)
    pipeline = RCAPipeline(
        AssistantService(EngineBackend(engine)),
        InMemoryGraphExecutor(build_metagraph()),
        InMemoryGraphExecutor(build_stategraph()), rca_cfg)

    # the PLAN names the right destination kind, first attempt, no grammar
    plan, attempts = pipeline.plan_destination(incident.message,
                                               incident.src_kind)
    assert attempts == 1
    assert plan["DestinationKind"] == incident.dest_kind
    assert plan["SourceKind"] == incident.src_kind

    # full incident: the model's own Cypher runs (no deterministic
    # fallback) and the REPORT parses with the root cause named
    result = pipeline.analyze_incident(incident.message)
    assert result["locator_attempts"] == 1
    analysis = result["analysis"][0]
    assert analysis["cypher_attempts"] == 1
    assert "human_cypher_query" not in analysis
    report = json.loads(analysis["statepath"][0]["report"])
    assert {"summary", "conclusion", "resolution"} <= set(report)
    assert incident.dest_kind in report["conclusion"]
    scores = {e["kind"]: int(e["relevance_score"])
              for e in report["summary"]}
    assert scores.get(incident.dest_kind, 0) >= 8   # the missing Secret
