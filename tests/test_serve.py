"""Serve-layer tests: run-state machine, message shapes, token windows —
the contracts stage code depends on (reference:
common/openai_generic_assistant.py:92-135)."""

import time

import jax
import pytest

from k8s_llm_rca_tpu.config import TINY, EngineConfig
from k8s_llm_rca_tpu.engine import InferenceEngine
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.serve import (
    AssistantService, EchoBackend, EngineBackend, GenericAssistant, RunStatus,
)
from k8s_llm_rca_tpu.serve.backend import GenOptions
from k8s_llm_rca_tpu.utils import get_tokenizer


@pytest.fixture()
def echo_service():
    tok = get_tokenizer()
    return AssistantService(EchoBackend(tok, reply="the answer"))


def make_client(service, name="helper"):
    c = GenericAssistant(service)
    c.create_assistant("you are a test assistant", name)
    c.create_thread()
    return c


def test_run_lifecycle_completed(echo_service):
    c = make_client(echo_service)
    c.add_message("question?")
    c.run_assistant()
    assert c.run.status in (RunStatus.QUEUED, RunStatus.IN_PROGRESS)
    msgs = c.wait_get_last_k_message(1)
    assert msgs is not None
    # newest-first, OpenAI content shape
    assert msgs.data[0].content[0].text.value == "the answer"
    run = c.get_run_status()
    assert run.status == RunStatus.COMPLETED
    assert run.usage["prompt_tokens"] > 0
    assert run.usage["total_tokens"] == (
        run.usage["prompt_tokens"] + run.usage["completion_tokens"])
    # thread history: system-less, user then assistant, oldest first
    roles = [m.role for m in c.thread.messages]
    assert roles == ["user", "assistant"]


def test_run_failure_returns_none():
    tok = get_tokenizer()
    service = AssistantService(EchoBackend(tok, fail=True))
    c = make_client(service)
    c.add_message("q")
    c.run_assistant()
    assert c.wait_get_last_k_message(1) is None
    assert c.get_run_status().status == RunStatus.FAILED


def test_run_expiry():
    tok = get_tokenizer()
    service = AssistantService(EchoBackend(tok, delay_pumps=10 ** 9),
                               run_timeout_s=0.05)
    c = make_client(service)
    c.add_message("q")
    c.run_assistant()
    time.sleep(0.06)
    assert c.wait_get_last_k_message(1) is None
    assert c.get_run_status().status == RunStatus.EXPIRED


def test_wait_run_timeout_releases_backend_slot():
    """wait_run(timeout_s=) must cancel the backend run and drop it from
    the in-flight map (mirroring the deadline path), so the slot frees
    and a later pump cannot flip the observed EXPIRED run to COMPLETED."""
    tok = get_tokenizer()
    backend = EchoBackend(tok, delay_pumps=2)
    service = AssistantService(backend)
    c = make_client(service)
    c.add_message("q")
    c.run_assistant()
    run = service.wait_run(c.run.id, timeout_s=0.0)
    assert run.status == RunStatus.EXPIRED
    assert run.backend_handle not in service._inflight
    for _ in range(5):                  # enough pumps to pass the delay
        service._pump()
    assert service.runs[c.run.id].status == RunStatus.EXPIRED


def test_cancel_run(echo_service):
    c = make_client(echo_service)
    c.add_message("q")
    c.run_assistant()
    c.service.cancel_run(c.run.id)
    assert c.get_run_status().status == RunStatus.CANCELLED
    assert c.wait_get_last_k_message(1) is None


def test_token_usage_window(echo_service):
    """Window semantics of reference :117-135: created_at AND completed_at
    in [tmin, tmax)."""
    c = make_client(echo_service)
    t0 = int(time.time())
    c.add_message("q1")
    c.run_assistant()
    c.wait_get_last_k_message(1)
    t1 = int(time.time()) + 1
    usage = c.get_token_usage(t0, t1)
    assert usage["total_tokens"] > 0
    # empty window before the run
    assert c.get_token_usage(t0 - 100, t0 - 50)["total_tokens"] == 0
    # half-open: window ending at created_at excludes the run
    run = c.get_run_status()
    assert c.get_token_usage(t0 - 100, run.created_at)["total_tokens"] == 0


def test_forced_prefix_and_suffix(echo_service):
    c = GenericAssistant(echo_service)
    c.create_assistant("a", "fenced",
                       gen=GenOptions(forced_prefix="```json\n", suffix="\n```"))
    c.create_thread()
    c.add_message("emit")
    c.run_assistant()
    text = c.wait_get_last_k_message(1).data[0].content[0].text.value
    assert text.startswith("```json\n") and text.endswith("\n```")


def test_engine_backend_end_to_end():
    """Two clients share one service + engine; both runs complete through
    the continuous batch."""
    cfg = TINY
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer()
    engine = InferenceEngine(
        cfg, EngineConfig(max_batch=4, max_seq_len=256,
                          prefill_buckets=(64, 128), max_new_tokens=8),
        params, tok)
    service = AssistantService(EngineBackend(engine))
    c1, c2 = make_client(service, "a"), make_client(service, "b")
    c1.add_message("first incident")
    c2.add_message("second incident")
    c1.run_assistant()
    c2.run_assistant()
    m1 = c1.wait_get_last_k_message(1)
    m2 = c2.wait_get_last_k_message(1)
    assert m1 is not None and m2 is not None
    assert c1.get_run_status().status == RunStatus.COMPLETED
    assert c2.get_run_status().status == RunStatus.COMPLETED
    u = c1.get_token_usage(0, int(time.time()) + 10)
    assert u["completion_tokens"] > 0


def test_service_state_roundtrip(tmp_path, echo_service):
    """Session checkpoint/resume: the whole assistant/thread/run store
    round-trips through JSON; resumed threads answer retrieve-by-id and
    token-usage windows exactly as before the restart."""
    from k8s_llm_rca_tpu.serve.api import (
        load_service_state, save_service_state,
    )
    from k8s_llm_rca_tpu.serve.backend import EchoBackend
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    service = echo_service
    a = service.create_assistant("be terse", "helper")
    t = service.create_thread()
    service.add_message(t.id, "first question")
    run = service.create_run(t.id, a.id)
    service.wait_run(run.id)
    service.add_message(t.id, "second question")
    run2 = service.create_run(t.id, a.id)
    service.wait_run(run2.id)

    path = str(tmp_path / "serve_state.json")
    save_service_state(service, path)
    restored = load_service_state(path, EchoBackend(get_tokenizer()))

    rt = restored.retrieve_thread(t.id)
    assert [m.raw_content for m in rt.messages] == \
        [m.raw_content for m in service.threads[t.id].messages]
    assert restored.retrieve_assistant(a.id).instructions == "be terse"
    # token-usage windows over the restored runs match the live service
    from k8s_llm_rca_tpu.serve.api import GenericAssistant

    lo = min(r.created_at for r in service.runs.values())
    hi = max(r.completed_at for r in service.runs.values()) + 1

    def usage_of(svc):
        ga = GenericAssistant(svc)
        ga.retrieve_assistant(a.id)
        ga.retrieve_thread(t.id)
        return ga.get_token_usage(lo, hi)

    assert usage_of(restored) == usage_of(service)
    assert usage_of(restored)["total_tokens"] > 0
    assert [r.id for r in restored.list_runs(t.id)] == \
        [r.id for r in service.list_runs(t.id)]
    # the restored service keeps allocating non-colliding ids
    t2 = restored.create_thread()
    assert t2.id not in {t.id}
    # and a new run on the restored thread still works end-to-end
    restored.add_message(t.id, "third question")
    r3 = restored.create_run(t.id, a.id)
    assert restored.wait_run(r3.id).status == "completed"


def test_service_state_preserves_gen_options(tmp_path):
    """Restored assistants must keep their GenOptions — the RCA stage
    assistants rely on grammar/fence/stop settings for parse guarantees."""
    from k8s_llm_rca_tpu.serve.api import (
        AssistantService, load_service_state, save_service_state,
    )
    from k8s_llm_rca_tpu.serve.backend import EchoBackend, GenOptions
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    tok = get_tokenizer()
    service = AssistantService(EchoBackend(tok))
    gen = GenOptions(max_new_tokens=512, stop=("```",),
                     forced_prefix="```json\n", suffix="\n```",
                     grammar="json")
    a = service.create_assistant("plan", "locator", gen=gen)
    path = str(tmp_path / "state.json")
    save_service_state(service, path)
    # saving must not mutate the live service (snapshot idempotence)
    save_service_state(service, path)
    restored = load_service_state(path, EchoBackend(tok))
    got = restored.retrieve_assistant(a.id).gen
    assert got == gen


def test_scan_tick_matches_stepwise_near_cache_cap():
    """decode_chunk must not change WHERE a cache-capacity 'length' fires
    (regression: the scan tick once passed an off-by-one device length)."""
    import jax

    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.engine import InferenceEngine
    from k8s_llm_rca_tpu.models import llama
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompt = list(range(5, 25))           # 20 tokens; cap at 32

    def run(chunk):
        eng = InferenceEngine(
            cfg, EngineConfig(max_batch=1, max_seq_len=32,
                              prefill_buckets=(32,), max_new_tokens=30,
                              temperature=0.0, decode_chunk=chunk),
            params, tok)
        r = eng.generate([list(prompt)], max_new_tokens=30)[0]
        return r.token_ids, r.finish_reason, r.completion_tokens

    assert run(1) == run(8)
