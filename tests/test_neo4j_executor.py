"""Contract tests for Neo4jQueryExecutor with a mocked bolt driver.

The live-bolt path can't run hermetically (no Neo4j in the image), but its
CONTRACT — mirroring the reference executor (reference
common/neo4j_query_executor.py:6-24) — is testable: connectivity verified
at construction, parameters passed through verbatim, results eagerly
materialized (usable after the session closes), close() delegated to the
driver.  VERDICT r1 item 10.
"""

import sys
import types
from unittest import mock

import pytest


class _FakeResult:
    """Iterable that poisons itself after its session exits, like a real
    bolt result consumed lazily would."""

    def __init__(self, records):
        self._records = records
        self.session_open = True

    def __iter__(self):
        for r in self._records:
            if not self.session_open:
                raise RuntimeError("result consumed after session close")
            yield r


class _FakeSession:
    def __init__(self, records, log):
        self._result = _FakeResult(records)
        self._log = log

    def run(self, query, parameters=None):
        self._log.append(("run", query, parameters))
        return self._result

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._result.session_open = False
        self._log.append(("session_closed",))
        return False


class _FakeDriver:
    def __init__(self, records):
        self.records = records
        self.log = []
        self.closed = False

    def verify_connectivity(self):
        self.log.append(("verify_connectivity",))

    def session(self):
        return _FakeSession(self.records, self.log)

    def close(self):
        self.closed = True


@pytest.fixture
def fake_neo4j(monkeypatch):
    """Install a fake ``neo4j`` module so the deferred import resolves."""
    driver_box = {}

    def make_driver(uri, auth=None):
        d = _FakeDriver(records=[{"n": 1}, {"n": 2}])
        d.uri, d.auth = uri, auth
        driver_box["driver"] = d
        return d

    mod = types.ModuleType("neo4j")
    mod.GraphDatabase = types.SimpleNamespace(driver=make_driver)
    monkeypatch.setitem(sys.modules, "neo4j", mod)
    return driver_box


def _executor(fake_neo4j):
    from k8s_llm_rca_tpu.graph.executor import Neo4jQueryExecutor

    ex = Neo4jQueryExecutor("bolt://10.1.0.176:7687", "neo4j", "pw")
    return ex, fake_neo4j["driver"]


def test_connectivity_verified_at_construction(fake_neo4j):
    ex, driver = _executor(fake_neo4j)
    assert ("verify_connectivity",) in driver.log
    assert driver.uri == "bolt://10.1.0.176:7687"
    assert driver.auth == ("neo4j", "pw")


def test_parameters_passed_through_verbatim(fake_neo4j):
    ex, driver = _executor(fake_neo4j)
    params = {"message": 'quoted "msg" with $dollar', "limit": 5}
    ex.run_query("MATCH (n) WHERE n.m CONTAINS $message RETURN n", params)
    run_calls = [c for c in driver.log if c[0] == "run"]
    assert run_calls == [("run",
                          "MATCH (n) WHERE n.m CONTAINS $message RETURN n",
                          params)]
    # None parameters forward as None (driver treats it as no params)
    ex.run_query("MATCH (n) RETURN n")
    assert driver.log[-2] == ("run", "MATCH (n) RETURN n", None)


def test_results_eagerly_materialized(fake_neo4j):
    """list(session.run(...)) must happen INSIDE the session context: the
    reference's callers iterate records long after the query returns
    (reference test_all.py:133-135)."""
    ex, driver = _executor(fake_neo4j)
    records = ex.run_query("MATCH (n) RETURN n")
    # session is closed by now; a lazy result would raise on iteration
    assert [r["n"] for r in records] == [1, 2]
    assert driver.log[-1] == ("session_closed",)


def test_close_delegates_to_driver(fake_neo4j):
    ex, driver = _executor(fake_neo4j)
    ex.close()
    assert driver.closed


def test_in_memory_executor_same_protocol(fake_neo4j):
    """Both executors satisfy GraphQueryExecutor: run_query(query, params)
    -> eager list, close() -> None.  The pipeline treats them uniformly."""
    from k8s_llm_rca_tpu.graph import InMemoryGraphExecutor
    from k8s_llm_rca_tpu.graph.fixtures import build_metagraph

    bolt, _ = _executor(fake_neo4j)
    mem = InMemoryGraphExecutor(build_metagraph())
    for ex in (bolt, mem):
        out = ex.run_query("MATCH (n1) WHERE n1.category IN "
                           "['NativeEntity', 'ExternalEntity'] "
                           "RETURN n1.category AS category, n1.kind AS kind")
        assert isinstance(out, list)
        assert ex.close() is None
