"""utils/ coverage: tokenizer roundtrip properties and the METRICS sink."""

import pytest

from k8s_llm_rca_tpu.utils.logging import Metrics
from k8s_llm_rca_tpu.utils.tokenizer import ByteTokenizer, get_tokenizer


class TestTokenizer:
    @pytest.mark.parametrize("text", [
        "kubelet Failed to pull image",
        "MountVolume.SetUp failed for volume \"pv-1\": ümlaut → 中文",
        "",
        "```json\n{\"a\": 1}\n```",
    ])
    def test_roundtrip(self, text):
        tok = get_tokenizer()
        assert tok.decode(tok.encode(text)) == text

    def test_bos_eos_framing(self):
        tok = get_tokenizer()
        ids = tok.encode("x", add_bos=True, add_eos=True)
        assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
        assert tok.bos_id != tok.eos_id

    def test_count_matches_encode(self):
        tok = get_tokenizer()
        text = "pod pending: unschedulable (0/3 nodes available)"
        assert tok.count(text) == len(tok.encode(text))

    def test_byte_fallback_handles_any_bytes(self):
        tok = ByteTokenizer()
        text = bytes(range(256)).decode("latin-1")
        assert tok.decode(tok.encode(text)) == text

    def test_ids_within_vocab(self):
        tok = get_tokenizer(vocab_size=256)
        ids = tok.encode("Error: ÿ boundary")
        assert all(0 <= i < 256 for i in ids)


class TestMetrics:
    def test_inc_and_timer(self):
        m = Metrics()
        m.inc("a")
        m.inc("a", 2)
        assert m.count("a") == 3
        with m.timer("t"):
            pass
        assert len(m.timings["t"]) == 1
        assert m.total("t") >= 0
        assert m.p50("t") == m.timings["t"][0]
        snap = m.snapshot()
        assert snap["a"] == 3 and "t.total_s" in snap
