"""utils/ coverage: tokenizer roundtrip properties and the METRICS sink."""

import pytest

from k8s_llm_rca_tpu.utils.logging import Metrics
from k8s_llm_rca_tpu.utils.tokenizer import ByteTokenizer, get_tokenizer


class TestBPETokenizer:
    """In-tree trainable byte-level BPE (utils/tokenizer.BPETokenizer)."""

    def _tok(self):
        from k8s_llm_rca_tpu.utils.tokenizer import BPETokenizer

        corpus = ["MountVolume.SetUp failed for volume",
                  'secret "es-account-token" not found',
                  '{"DestinationKind": "Secret"}'] * 20
        return BPETokenizer.train(corpus, vocab_size=512)

    def test_roundtrip_exact(self):
        tok = self._tok()
        for text in ['secret "x" not found\n', "kubectl apply -f m.yaml",
                     '{"a": [1, 2], "b": "c\\"d"}', "päivää \u00e9\u00e9"]:
            assert tok.decode(tok.encode(text)) == text

    def test_compresses_vs_bytes(self):
        tok = self._tok()
        text = "MountVolume.SetUp failed for volume: secret not found"
        assert len(tok.encode(text)) < len(text.encode()) // 2

    def test_specials_and_framing(self):
        tok = self._tok()
        ids = tok.encode("pod", add_bos=True, add_eos=True)
        assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
        assert tok.decode(ids) == "pod"       # specials filtered on decode
        assert {tok.pad_id, tok.bos_id, tok.eos_id} == {0, 1, 2}

    def test_save_load_roundtrip(self, tmp_path):
        from k8s_llm_rca_tpu.utils.tokenizer import BPETokenizer

        tok = self._tok()
        path = str(tmp_path / "bpe.json")
        tok.save(path)
        tok2 = BPETokenizer.load(path)
        text = 'exceeded quota: pods=50'
        assert tok2.encode(text) == tok.encode(text)
        assert tok2.vocab_size == tok.vocab_size


class TestTokenizer:
    @pytest.mark.parametrize("text", [
        "kubelet Failed to pull image",
        "MountVolume.SetUp failed for volume \"pv-1\": ümlaut → 中文",
        "",
        "```json\n{\"a\": 1}\n```",
    ])
    def test_roundtrip(self, text):
        tok = get_tokenizer()
        assert tok.decode(tok.encode(text)) == text

    def test_bos_eos_framing(self):
        tok = get_tokenizer()
        ids = tok.encode("x", add_bos=True, add_eos=True)
        assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
        assert tok.bos_id != tok.eos_id

    def test_count_matches_encode(self):
        tok = get_tokenizer()
        text = "pod pending: unschedulable (0/3 nodes available)"
        assert tok.count(text) == len(tok.encode(text))

    def test_byte_fallback_handles_any_bytes(self):
        tok = ByteTokenizer()
        text = bytes(range(256)).decode("latin-1")
        assert tok.decode(tok.encode(text)) == text

    def test_ids_within_vocab(self):
        tok = get_tokenizer(vocab_size=256)
        ids = tok.encode("Error: ÿ boundary")
        assert all(0 <= i < 256 for i in ids)


class TestMetrics:
    def test_inc_and_timer(self):
        m = Metrics()
        m.inc("a")
        m.inc("a", 2)
        assert m.count("a") == 3
        with m.timer("t"):
            pass
        assert len(m.timings["t"]) == 1
        assert m.total("t") >= 0
        assert m.p50("t") == m.timings["t"][0]
        snap = m.snapshot()
        assert snap["a"] == 3 and "t.total_s" in snap
