"""Encoder + embed/rerank tests (BASELINE config[4] path).

Invariants: bidirectionality (a late-token perturbation changes early
hidden states — the opposite of the decoder's causality test), padding
invariance (padded positions must not leak into the pooled embedding),
unit-norm pooling, deterministic rerank ordering, and the pipeline
integration (rerank_scores present and record order by score).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_llm_rca_tpu.config import TINY_ENCODER, RCAConfig
from k8s_llm_rca_tpu.models import encoder
from k8s_llm_rca_tpu.rca.rerank import (
    Embedder, Reranker, cosine_rerank, _record_text,
)


@pytest.fixture(scope="module")
def enc_setup():
    cfg = TINY_ENCODER
    params = encoder.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_forward_shapes_and_finite(enc_setup):
    cfg, params = enc_setup
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    hidden = encoder.forward(cfg, params, tokens)
    assert hidden.shape == (2, 16, cfg.hidden_size)
    assert bool(jnp.all(jnp.isfinite(hidden)))


def test_bidirectional(enc_setup):
    """Perturbing a LATE token must change EARLY hidden states (no causal
    mask — this is the defining difference from the decoder)."""
    cfg, params = enc_setup
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 12), 0,
                                cfg.vocab_size)
    perturbed = tokens.at[0, 10].set((tokens[0, 10] + 1) % cfg.vocab_size)
    ha = encoder.forward(cfg, params, tokens)
    hb = encoder.forward(cfg, params, perturbed)
    assert not np.allclose(ha[0, :5], hb[0, :5], atol=1e-5)


def test_padding_invariance(enc_setup):
    """Same valid tokens under different pad widths -> same embedding."""
    cfg, params = enc_setup
    base = jax.random.randint(jax.random.PRNGKey(3), (1, 6), 0,
                              cfg.vocab_size)
    lengths = jnp.array([6], jnp.int32)
    short = jnp.zeros((1, 8), jnp.int32).at[:, :6].set(base)
    long = jnp.full((1, 16), 99, jnp.int32).at[:, :6].set(base)
    ea = encoder.embed(cfg, params, short, lengths)
    eb = encoder.embed(cfg, params, long, lengths)
    np.testing.assert_allclose(np.asarray(ea), np.asarray(eb),
                               rtol=1e-4, atol=1e-4)


def test_embed_unit_norm(enc_setup):
    cfg, params = enc_setup
    tokens = jax.random.randint(jax.random.PRNGKey(4), (3, 10), 0,
                                cfg.vocab_size)
    vecs = encoder.embed(cfg, params, tokens)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(vecs), axis=-1),
                               np.ones(3), rtol=1e-5)


def test_embedder_batches_and_buckets():
    emb = Embedder(buckets=(8, 16), batch_size=2)
    texts = ["pod failed", "a much longer message about a configmap that "
             "does not exist in the namespace", "x", "secret missing"]
    vecs = emb.encode(texts)
    assert vecs.shape == (4, emb.cfg.hidden_size)
    np.testing.assert_allclose(np.linalg.norm(vecs, axis=-1), np.ones(4),
                               rtol=1e-5)
    # per-text embedding must not depend on batch composition
    solo = emb.encode([texts[1]])
    np.testing.assert_allclose(vecs[1], solo[0], rtol=1e-4, atol=1e-4)


def test_cosine_rerank_orders_by_similarity():
    q = np.array([1.0, 0.0], np.float32)
    p = np.array([[0.6, 0.8], [1.0, 0.0], [0.0, 1.0]], np.float32)
    ranked = cosine_rerank(q, p)
    assert [i for i, _ in ranked] == [1, 0, 2]
    assert ranked[0][1] == pytest.approx(1.0)


def test_reranker_identical_passage_wins():
    """The passage equal to the query must embed closest to it."""
    rr = Reranker()
    query = "MountVolume failed for volume secret not found"
    passages = ["completely unrelated text about networking",
                query,
                "another unrelated row"]
    ranked = rr.rerank(query, passages)
    assert ranked[0][0] == 1


def test_record_text_flattens_graph_elements():
    from k8s_llm_rca_tpu.graph.store import Node

    n1 = Node("e1", ["Entity"], {"kind": "pod", "name2": "web-1"})
    n2 = Node("e2", ["Entity"], {"kind": "secret", "val": "db-cred"})
    text = _record_text([n1, n2])
    assert "pod" in text and "web-1" in text and "db-cred" in text


def test_pipeline_rerank_integration():
    """Full hermetic pipeline with a reranker: rerank_scores recorded,
    descending, and statepath audits still produce reports."""
    from k8s_llm_rca_tpu.graph import InMemoryGraphExecutor
    from k8s_llm_rca_tpu.graph.fixtures import (
        INCIDENTS, build_metagraph, build_stategraph,
    )
    from k8s_llm_rca_tpu.rca import RCAPipeline
    from k8s_llm_rca_tpu.rca.oracle import OracleBackend
    from k8s_llm_rca_tpu.serve.api import AssistantService
    from k8s_llm_rca_tpu.utils import get_tokenizer

    pipeline = RCAPipeline(
        AssistantService(OracleBackend(get_tokenizer())),
        InMemoryGraphExecutor(build_metagraph()),
        InMemoryGraphExecutor(build_stategraph()),
        RCAConfig(),
        reranker=Reranker())
    result = pipeline.analyze_incident(INCIDENTS[0].message)
    assert result["analysis"], "pipeline found no metapaths"
    audited = [sp for a in result["analysis"] for sp in a["statepath"]]
    assert audited, "no statepath audits ran"
    for analysis in result["analysis"]:
        scores = analysis.get("rerank_scores")
        if scores is not None:
            assert scores == sorted(scores, reverse=True)


def test_quantized_encoder_embeddings_correlate(enc_setup):
    # the encoder consumes weights through dq/gather_rows, so int8/int4
    # quantized params run the same code; pooled embeddings must stay
    # close to full precision (cosine similarity per row)
    from k8s_llm_rca_tpu.models.quant import quantize_params

    cfg, params = enc_setup
    tokens = jax.random.randint(jax.random.PRNGKey(5), (3, 12), 0,
                                cfg.vocab_size)
    ref = np.asarray(encoder.embed(cfg, params, tokens))
    for bits, floor in ((8, 0.999), (4, 0.98)):
        qp = quantize_params(params, compute_dtype=jnp.float32, bits=bits)
        got = np.asarray(encoder.embed(cfg, qp, tokens))
        cos = np.sum(ref * got, axis=-1)     # both unit-norm
        assert np.all(cos > floor), (bits, cos)


def test_project_fields_reranks_and_caps():
    """Field-level rerank fusion (BASELINE configs[4]): _project_fields
    keeps the top-k fields BY RELEVANCE (not list position), in stable
    field order, and the resulting prompt is strictly smaller."""
    from k8s_llm_rca_tpu.rca import auditor

    class FakeNode(dict):
        def __getitem__(self, k):
            return self.get(k)

    node = FakeNode(kind="POD", id="s1",
                    status={"phase": "Pending", "reason": "unschedulable"},
                    spec={"volumes": [{"secret": "db-cred"}]},
                    data={"huge": "x" * 200},
                    metadata={"name": "web-1"})

    class FakeReranker:
        def rerank(self, query, passages, top_k=None):
            # rank 'spec' and 'status' highest regardless of position
            order = sorted(range(len(passages)),
                           key=lambda i: (not passages[i].startswith("spec"),
                                          not passages[i].startswith("status")))
            return [(i, 1.0) for i in order[:top_k]]

    fields = auditor._project_fields(node, "secret not found",
                                     FakeReranker(), fields_top_k=2)
    assert fields == ["status", "spec"]       # stable IMPORTANT_FIELDS order
    full = auditor._semantic_prompt(node, "secret not found")
    slim = auditor._semantic_prompt(node, "secret not found", fields)
    assert len(slim) < len(full)
    assert "huge" not in slim and "x" * 50 not in slim
    # no reranker / top_k=0 / few fields: unchanged reference projection
    assert auditor._project_fields(node, "m") == ["status", "spec", "data",
                                                  "metadata"]
    assert auditor._project_fields(node, "m", FakeReranker(), 0) == \
        ["status", "spec", "data", "metadata"]


def test_rerank_fused_prompts_shrink_and_preserve_findings():
    """VERDICT r2 item 8: with field-level rerank fusion ON, the analyzer
    reads FEWER prompt tokens for the same incident while the report's
    findings (clue labels, missing-STATE scores, report schema) are
    preserved."""
    import json

    from k8s_llm_rca_tpu.graph import InMemoryGraphExecutor
    from k8s_llm_rca_tpu.graph.fixtures import (
        INCIDENTS, build_metagraph, build_stategraph,
    )
    from k8s_llm_rca_tpu.rca import RCAPipeline
    from k8s_llm_rca_tpu.rca.oracle import OracleBackend
    from k8s_llm_rca_tpu.serve.api import AssistantService
    from k8s_llm_rca_tpu.utils import get_tokenizer

    def run(cfg):
        pipeline = RCAPipeline(
            AssistantService(OracleBackend(get_tokenizer())),
            InMemoryGraphExecutor(build_metagraph()),
            InMemoryGraphExecutor(build_stategraph()),
            cfg, reranker=Reranker())
        result = pipeline.analyze_incident(INCIDENTS[3].message)
        tokens = result["token_usage"]["prompt_tokens"]
        labels = sorted(k for a in result["analysis"]
                        for sp in a["statepath"] for k in sp["clue"])
        reports = [json.loads(sp["report"]) for a in result["analysis"]
                   for sp in a["statepath"]]
        return tokens, labels, reports

    base_tokens, base_labels, base_reports = run(RCAConfig())
    slim_tokens, slim_labels, slim_reports = run(
        RCAConfig(rerank_fields_top_k=2))

    assert slim_tokens < base_tokens, (slim_tokens, base_tokens)
    assert slim_labels == base_labels          # same entities audited
    for rep in slim_reports:                   # report contract preserved
        assert {"summary", "conclusion", "resolution"} <= set(rep)
