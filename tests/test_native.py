"""Native (C++) runtime component tests: strict parity with the Python
implementations they replace.

The allocator must enforce identical invariants (same exception types on
double-free / foreign-free / trash-free / exhaustion) and the grammar
engine must be mask-for-mask identical with the Python FSM along random
decode trajectories — greedy decoding under either backend must therefore
produce byte-identical output.
"""

import json

import numpy as np
import pytest

from k8s_llm_rca_tpu import native
from k8s_llm_rca_tpu.engine.constrain import JsonGrammar
from k8s_llm_rca_tpu.engine.paged import (
    AllocatorError, OutOfPages, PageAllocator,
)
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


class TestNativeAllocator:
    def test_roundtrip_and_n_free(self):
        a = native.NativePageAllocator(16)
        pages = a.alloc(5, owner=1)
        assert len(set(pages)) == 5 and 0 not in pages
        assert a.n_free == 10
        assert a.pages_of(1) == sorted(pages)
        a.free(pages, owner=1)
        a.check()
        assert a.n_free == 15

    def test_error_parity_with_python(self):
        for cls in (PageAllocator, native.NativePageAllocator):
            a = cls(8)
            pages = a.alloc(2, owner=1)
            with pytest.raises(OutOfPages):
                a.alloc(99, owner=2)
            with pytest.raises(AllocatorError):
                a.free(pages, owner=2)          # foreign owner
            a.free(pages, owner=1)
            with pytest.raises(AllocatorError):
                a.free(pages, owner=1)          # double free
            with pytest.raises(AllocatorError):
                a.free([0], owner=1)            # trash page
            a.check()
            assert a.n_free == 7


    def test_transfer_parity_with_python(self):
        for cls in (PageAllocator, native.NativePageAllocator):
            a = cls(8)
            pages = a.alloc(3, owner=1)
            a.transfer(pages[:2], from_owner=1, to_owner=-2)
            assert sorted(a.pages_of(-2)) == sorted(pages[:2])
            assert sorted(a.pages_of(1)) == sorted(pages[2:])
            with pytest.raises(AllocatorError):      # wrong from_owner
                a.transfer(pages[:1], from_owner=1, to_owner=-2)
            with pytest.raises(AllocatorError):      # trash page
                a.transfer([0], from_owner=1, to_owner=-2)
            a.free(pages[:2], owner=-2)
            with pytest.raises(AllocatorError):      # free page transfer
                a.transfer(pages[:1], from_owner=-2, to_owner=1)
            a.free(pages[2:], owner=1)
            a.check()
            assert a.n_free == 7

    def test_interleaved_sequence_parity(self):
        """Drive both allocators through the same random alloc/free
        schedule; free-list order may differ, but counts and failures
        must match exactly."""
        rng = np.random.default_rng(0)
        py, cc = PageAllocator(32), native.NativePageAllocator(32)
        held_py, held_cc = {}, {}
        for step in range(300):
            if rng.random() < 0.55 or not held_py:
                n = int(rng.integers(1, 5))
                owner = int(rng.integers(0, 6))
                try:
                    p1 = py.alloc(n, owner)
                    ok1 = True
                except OutOfPages:
                    ok1 = False
                try:
                    p2 = cc.alloc(n, owner)
                    ok2 = True
                except OutOfPages:
                    ok2 = False
                assert ok1 == ok2, f"step {step}"
                if ok1:
                    held_py.setdefault(owner, []).extend(p1)
                    held_cc.setdefault(owner, []).extend(p2)
            else:
                owner = list(held_py)[int(rng.integers(0, len(held_py)))]
                py.free(held_py.pop(owner), owner)
                cc.free(held_cc.pop(owner), owner)
            assert py.n_free == cc.n_free, f"step {step}"
        py.check()
        cc.check()


class TestNativeGrammar:
    def _pair(self):
        tok = get_tokenizer()
        return JsonGrammar(tok), native.NativeJsonGrammar(tok), tok

    def test_mask_parity_along_trajectories(self):
        """At every step of a random grammar-legal decode, the native and
        Python masks must be identical."""
        rng = np.random.default_rng(1)
        for trajectory in range(5):
            py, cc, tok = self._pair()
            for step in range(40):
                cp = py.constraint()
                cn = cc.constraint()
                assert (cp.force is None) == (cn.force is None), step
                if cp.force is not None:
                    assert cp.force == cn.force
                    token = cp.force
                else:
                    np.testing.assert_array_equal(cp.allow, cn.allow), step
                    legal = np.flatnonzero(cp.allow)
                    token = int(legal[rng.integers(0, len(legal))])
                if token == tok.eos_id:
                    break
                py.advance(token)
                cc.advance(token)
                assert py.done == cc.done

    def test_minimal_completion_parity(self):
        prefixes = ['', '{', '{"key', '{"key": ', '{"a": [1, {"b": "x',
                    '-1.2e', '{"a": tr', '{"s": "esc\\', '[[[',
                    '{"a": {"b": [0, ']
        for prefix in prefixes:
            py, cc, tok = self._pair()
            for ch in prefix:
                (t,) = tok.encode(ch)
                py.advance(t)
                cc.advance(t)
            assert py.auto.minimal_completion() == cc.minimal_completion(), \
                prefix

    def test_violation_raises_both(self):
        py, cc, tok = self._pair()
        (brace,) = tok.encode("}")
        with pytest.raises(ValueError):
            py.advance(brace)
        with pytest.raises(ValueError):
            cc.advance(brace)

    def test_greedy_decode_identical_under_both_backends(self):
        import jax

        from k8s_llm_rca_tpu.config import TINY, EngineConfig
        from k8s_llm_rca_tpu.engine.engine import InferenceEngine
        from k8s_llm_rca_tpu.models import llama

        cfg = TINY.replace(max_seq_len=256)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        ecfg = EngineConfig(max_batch=2, max_seq_len=128, max_new_tokens=32,
                            prefill_buckets=(32,), temperature=0.0)
        tok = get_tokenizer()
        outs = {}
        for name, grammar_cls in (("py", JsonGrammar),
                                  ("cc", native.NativeJsonGrammar)):
            eng = InferenceEngine(cfg, ecfg, params, tok)
            seq = eng.submit(tok.encode("emit json", add_bos=True),
                             grammar=grammar_cls(tok))
            (res,) = eng.run_to_completion()
            assert res.seq_id == seq
            json.loads(res.text)
            outs[name] = res.token_ids
        assert outs["py"] == outs["cc"]

    def test_engine_config_native_flag_selects_backend(self, monkeypatch):
        from k8s_llm_rca_tpu.engine import constrain
        from k8s_llm_rca_tpu.engine.constrain import make_grammar
        from k8s_llm_rca_tpu.engine.paged import make_allocator

        tok = get_tokenizer()
        # grammar="json" now compiles the BOUNDED-depth DFA first (it rides
        # the on-device scan); the native/python unbounded grammars are the
        # fallback when the tables don't fit
        assert isinstance(make_grammar("json", tok), constrain.DFAGrammar)
        monkeypatch.setattr(constrain, "_DFA_MAX_TABLE_BYTES", 1024)
        tok2 = get_tokenizer()            # fresh: no cached tables
        assert isinstance(make_grammar("json", tok2),
                          native.NativeJsonGrammar)
        assert isinstance(make_grammar("json", tok2, prefer_native=False),
                          JsonGrammar)
        assert isinstance(make_allocator(8), native.NativePageAllocator)
        assert isinstance(make_allocator(8, prefer_native=False),
                          PageAllocator)
