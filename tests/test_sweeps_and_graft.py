"""Driver-level tests: batch sweep output schema + resumability, and the
graft entry points (single-chip compile check, multi-chip dry run)."""

import json
import os

import pytest

from k8s_llm_rca_tpu.sweeps import run_file


def test_run_file_schema_and_resume(tmp_path):
    inp = str(tmp_path / "incidents.csv")
    out = str(tmp_path / "results.json")

    summary = run_file.main([
        "--input", inp, "--output", out, "--slice", "0:2"])
    assert summary["incidents"] == 2
    assert summary["p50_incident_s"] > 0

    # output: concatenated pretty-printed JSON records, reference schema
    assert run_file.completed_incidents(out) == 2
    first = json.loads(open(out).read().split("}\n{")[0] + "}")
    assert {"error_message", "locator_attempts", "analysis", "time_cost",
            "token_usage"} <= set(first)
    a = first["analysis"][0]
    assert {"extend_metapath", "cypher_query", "cypher_attempts",
            "statepath"} <= set(a)
    assert {"report", "clue"} <= set(a["statepath"][0])

    # resume: skips the two finished incidents, appends the rest
    summary2 = run_file.main([
        "--input", inp, "--output", out, "--resume"])
    assert summary2["incidents"] == 2          # 4 total - 2 done
    assert run_file.completed_incidents(out) == 4


def test_graft_entry_jits():
    import jax

    import __graft_entry__ as graft

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == 2 and out.ndim == 3


def test_graft_dryrun_multichip():
    import __graft_entry__ as graft

    graft.dryrun_multichip(8)


def test_run_file_replicated_oracle(tmp_path):
    """DP sweep serving (VERDICT r1 item 6): N pipeline replicas drain one
    queue; every incident lands exactly once, per-replica accounting sums."""
    inp = str(tmp_path / "incidents.csv")
    out = str(tmp_path / "results.json")
    run_file.write_default_corpus(inp, repeat=2)    # 8 incidents

    summary = run_file.main([
        "--input", inp, "--output", out, "--replicas", "3"])
    assert summary["incidents"] == 8
    assert summary["failures"] == 0
    assert run_file.completed_incidents(out) == 8
    reps = summary["replicas"]
    assert [r["replica"] for r in reps] == [0, 1, 2]
    assert sum(r["incidents"] for r in reps) == 8
    # records parse individually (concurrent appends serialized by the lock)
    text = open(out).read()
    decoder = json.JSONDecoder()
    idx, seen = 0, 0
    while idx < len(text.rstrip()):
        obj, idx = decoder.raw_decode(text, idx)
        while idx < len(text) and text[idx].isspace():
            idx += 1
        assert "error_message" in obj
        seen += 1
    assert seen == 8


def test_run_file_replicated_engine(tmp_path):
    """DP x engine: two device-pinned TINY engine replicas share the queue
    (the virtual-CPU stand-in for one-replica-per-chip pod serving)."""
    import jax

    inp = str(tmp_path / "incidents.csv")
    out = str(tmp_path / "results.json")

    summary = run_file.main([
        "--input", inp, "--output", out, "--slice", "0:2",
        "--backend", "engine", "--replicas", "2",
        "--max-seq-len", "1024"])
    assert summary["incidents"] == 2
    assert run_file.completed_incidents(out) == 2
    reps = summary["replicas"]
    assert sum(r["incidents"] for r in reps) == 2
    devs = {r["device"] for r in reps}
    assert len(devs) == 2              # round-robin actually pinned 2 devices


def test_stage_harnesses(capsys):
    """The four stage-isolated operator harnesses (the reference's
    test_find_metapath/test_generate_query/test_check_state/test_token
    equivalents) each run hermetically and print a JSON result."""
    import json as _json

    from k8s_llm_rca_tpu.sweeps import stage

    out = stage.main(["locate"])
    assert out["srcKind"] == "Pod"
    assert out["plan"]["DestinationKind"] == "Secret"
    assert ["Pod", "Secret"] in out["metapaths"]

    out = stage.main(["cypher"])
    assert out["records"] >= 1 and out["human_records"] >= 1
    assert "MATCH" in out["human_cypher_query"]

    out = stage.main(["audit"])
    assert out["entity"] == "Secret(sec-0001)"
    assert any("apparent error" in c for c in out["clues"])

    out = stage.main(["token"])
    assert out["run_status"] == "completed"
    assert out["token_usage"]["total_tokens"] > 0
    # every harness printed a JSON document (last one is parseable as-is)
    printed = capsys.readouterr().out.strip()
    assert printed.endswith("}")
    _json.loads(printed[printed.rindex("\n{"):])
