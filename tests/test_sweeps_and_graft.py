"""Driver-level tests: batch sweep output schema + resumability, and the
graft entry points (single-chip compile check, multi-chip dry run)."""

import json
import os

import pytest

from k8s_llm_rca_tpu.sweeps import run_file


def test_run_file_schema_and_resume(tmp_path):
    inp = str(tmp_path / "incidents.csv")
    out = str(tmp_path / "results.json")

    summary = run_file.main([
        "--input", inp, "--output", out, "--slice", "0:2"])
    assert summary["incidents"] == 2
    assert summary["p50_incident_s"] > 0

    # output: concatenated pretty-printed JSON records, reference schema
    assert run_file.completed_incidents(out) == 2
    first = json.loads(open(out).read().split("}\n{")[0] + "}")
    assert {"error_message", "locator_attempts", "analysis", "time_cost",
            "token_usage"} <= set(first)
    a = first["analysis"][0]
    assert {"extend_metapath", "cypher_query", "cypher_attempts",
            "statepath"} <= set(a)
    assert {"report", "clue"} <= set(a["statepath"][0])

    # resume: skips the two finished incidents, appends the rest
    summary2 = run_file.main([
        "--input", inp, "--output", out, "--resume"])
    assert summary2["incidents"] == 2          # 4 total - 2 done
    assert run_file.completed_incidents(out) == 4


def test_graft_entry_jits():
    import jax

    import __graft_entry__ as graft

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == 2 and out.ndim == 3


def test_graft_dryrun_multichip():
    import __graft_entry__ as graft

    graft.dryrun_multichip(8)


def test_run_file_replicated_oracle(tmp_path):
    """DP sweep serving (VERDICT r1 item 6): N pipeline replicas drain one
    queue; every incident lands exactly once, per-replica accounting sums."""
    inp = str(tmp_path / "incidents.csv")
    out = str(tmp_path / "results.json")
    run_file.write_default_corpus(inp, repeat=2)    # 8 incidents

    summary = run_file.main([
        "--input", inp, "--output", out, "--replicas", "3"])
    assert summary["incidents"] == 8
    assert summary["failures"] == 0
    assert run_file.completed_incidents(out) == 8
    reps = summary["replicas"]
    assert [r["replica"] for r in reps] == [0, 1, 2]
    assert sum(r["incidents"] for r in reps) == 8
    # records parse individually (concurrent appends serialized by the lock)
    text = open(out).read()
    decoder = json.JSONDecoder()
    idx, seen = 0, 0
    while idx < len(text.rstrip()):
        obj, idx = decoder.raw_decode(text, idx)
        while idx < len(text) and text[idx].isspace():
            idx += 1
        assert "error_message" in obj
        seen += 1
    assert seen == 8


def test_run_file_replicated_engine(tmp_path):
    """DP x engine: two device-pinned TINY engine replicas share the queue
    (the virtual-CPU stand-in for one-replica-per-chip pod serving)."""
    import jax

    inp = str(tmp_path / "incidents.csv")
    out = str(tmp_path / "results.json")

    summary = run_file.main([
        "--input", inp, "--output", out, "--slice", "0:2",
        "--backend", "engine", "--replicas", "2",
        "--max-seq-len", "1024"])
    assert summary["incidents"] == 2
    assert run_file.completed_incidents(out) == 2
    reps = summary["replicas"]
    assert sum(r["incidents"] for r in reps) == 2
    devs = {r["device"] for r in reps}
    assert len(devs) == 2              # round-robin actually pinned 2 devices


def test_run_file_shared_workers_oracle(tmp_path):
    """Shared-service concurrent sweep (--workers): N threads drive their
    own pipelines against ONE AssistantService; every incident lands
    exactly once and each record is a full, valid report."""
    inp = str(tmp_path / "incidents.csv")
    out = str(tmp_path / "results.json")
    run_file.write_default_corpus(inp, repeat=2)    # 8 incidents

    summary = run_file.main([
        "--input", inp, "--output", out, "--workers", "4"])
    assert summary["incidents"] == 8
    assert summary["failures"] == 0
    assert summary["workers"] == 4
    assert run_file.completed_incidents(out) == 8


def test_run_file_chaos_kill_and_resume(tmp_path):
    """Chaos: SIGKILL the shared-engine sweep process mid-flight, then
    --resume.  The resumed run must complete the sweep with NO duplicated
    and NO lost incidents — even though concurrent workers complete
    incidents out of input order (so a count-based "skip the first N"
    would corrupt the sweep) and the kill can leave a partial tail record
    (which resume truncates)."""
    import os
    import signal
    import subprocess
    import sys
    import time
    from collections import Counter

    inp = str(tmp_path / "incidents.csv")
    out = str(tmp_path / "results.json")
    run_file.write_default_corpus(inp, repeat=6)    # 24 incidents
    corpus = run_file.load_corpus(inp)

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "k8s_llm_rca_tpu.sweeps.run_file",
         "--input", inp, "--output", out, "--workers", "4"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            done = run_file.completed_incidents(out)
            if done >= 4:
                break
            if proc.poll() is not None:
                break
            time.sleep(0.05)
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)    # hard kill, mid-append ok
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()

    survivors, _ = run_file.scan_output(out)
    # the kill must land mid-sweep for the test to mean anything
    assert 0 < len(survivors) < len(corpus), len(survivors)

    summary = run_file.main([
        "--input", inp, "--output", out, "--workers", "4", "--resume"])
    assert summary["incidents"] == len(corpus) - len(survivors)

    final, _ = run_file.scan_output(out)
    # exactly-once at incident granularity: multiset equality with input
    assert Counter(final) == Counter(corpus), (
        Counter(final) - Counter(corpus), Counter(corpus) - Counter(final))


def test_run_file_shared_workers_engine(tmp_path):
    """Concurrent workers over ONE TINY engine: the continuous batcher
    carries runs from different incidents in the same ticks, and the
    per-incident reports match a serial run of the same slice (greedy
    decode => order-independent outputs)."""
    inp = str(tmp_path / "incidents.csv")
    out_shared = str(tmp_path / "shared.json")
    out_serial = str(tmp_path / "serial.json")

    common = ["--input", inp, "--slice", "0:3", "--backend", "engine",
              "--max-seq-len", "1024", "--max-batch", "6"]
    s1 = run_file.main(common + ["--output", out_shared, "--workers", "3"])
    assert s1["incidents"] == 3 and s1["failures"] == 0
    s2 = run_file.main(common + ["--output", out_serial])
    assert s2["incidents"] == 3 and s2["failures"] == 0

    def reports(path):
        text, decoder, idx, objs = open(path).read(), json.JSONDecoder(), 0, []
        while idx < len(text.rstrip()):
            obj, idx = decoder.raw_decode(text, idx)
            while idx < len(text) and text[idx].isspace():
                idx += 1
            objs.append(obj)
        return objs

    shared = {r["error_message"]: r for r in reports(out_shared)}
    serial = {r["error_message"]: r for r in reports(out_serial)}
    assert shared.keys() == serial.keys()
    for msg, rec in serial.items():
        # timing/token fields differ; the analysis content must not
        assert shared[msg]["analysis"] == rec["analysis"], msg


def test_workers_and_replicas_mutually_exclusive(tmp_path):
    import pytest

    inp = str(tmp_path / "incidents.csv")
    run_file.write_default_corpus(inp)
    with pytest.raises(SystemExit):
        run_file.main(["--input", inp, "--workers", "2", "--replicas", "2"])


def test_stage_harnesses(capsys):
    """The four stage-isolated operator harnesses (the reference's
    test_find_metapath/test_generate_query/test_check_state/test_token
    equivalents) each run hermetically and print a JSON result."""
    import json as _json

    from k8s_llm_rca_tpu.sweeps import stage

    out = stage.main(["locate"])
    assert out["srcKind"] == "Pod"
    assert out["plan"]["DestinationKind"] == "Secret"
    assert ["Pod", "Secret"] in out["metapaths"]

    out = stage.main(["cypher"])
    assert out["records"] >= 1 and out["human_records"] >= 1
    assert "MATCH" in out["human_cypher_query"]

    out = stage.main(["audit"])
    assert out["entity"] == "Secret(sec-0001)"
    assert any("apparent error" in c for c in out["clues"])

    out = stage.main(["token"])
    assert out["run_status"] == "completed"
    assert out["token_usage"]["total_tokens"] > 0
    # every harness printed a JSON document (last one is parseable as-is)
    printed = capsys.readouterr().out.strip()
    assert printed.endswith("}")
    _json.loads(printed[printed.rindex("\n{"):])
