"""Driver-level tests: batch sweep output schema + resumability, and the
graft entry points (single-chip compile check, multi-chip dry run)."""

import json
import os

import pytest

from k8s_llm_rca_tpu.sweeps import run_file


def test_run_file_schema_and_resume(tmp_path):
    inp = str(tmp_path / "incidents.csv")
    out = str(tmp_path / "results.json")

    summary = run_file.main([
        "--input", inp, "--output", out, "--slice", "0:2"])
    assert summary["incidents"] == 2
    assert summary["p50_incident_s"] > 0

    # output: concatenated pretty-printed JSON records, reference schema
    assert run_file.completed_incidents(out) == 2
    first = json.loads(open(out).read().split("}\n{")[0] + "}")
    assert {"error_message", "locator_attempts", "analysis", "time_cost",
            "token_usage"} <= set(first)
    a = first["analysis"][0]
    assert {"extend_metapath", "cypher_query", "cypher_attempts",
            "statepath"} <= set(a)
    assert {"report", "clue"} <= set(a["statepath"][0])

    # resume: skips the two finished incidents, appends the rest
    summary2 = run_file.main([
        "--input", inp, "--output", out, "--resume"])
    assert summary2["incidents"] == 2          # 4 total - 2 done
    assert run_file.completed_incidents(out) == 4


def test_graft_entry_jits():
    import jax

    import __graft_entry__ as graft

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == 2 and out.ndim == 3


def test_graft_dryrun_multichip():
    import __graft_entry__ as graft

    graft.dryrun_multichip(8)
