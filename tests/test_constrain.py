"""Grammar-constrained decoding tests.

The decisive property: a RANDOM-weight model decoding under the JSON
grammar must always produce ``json.loads``-able output — greedy or
stochastic, contiguous or paged engine, even when the token budget runs
out mid-structure (budget-aware force-close) or the sequence is preempted
and resumed.  This is what turns the reference's JSONDecodeError
retry-with-feedback loop (reference test_all.py:70-83) into dead code.
"""

import json

import jax
import pytest

from k8s_llm_rca_tpu.config import TINY, EngineConfig
from k8s_llm_rca_tpu.engine.constrain import (
    JsonCharAutomaton, JsonGrammar, make_grammar,
)
from k8s_llm_rca_tpu.engine.engine import InferenceEngine
from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer


def feed(text):
    a = JsonCharAutomaton()
    for ch in text:
        if not a.accept(ch):
            return None
    return a


class TestJsonCharAutomaton:
    @pytest.mark.parametrize("text", [
        '{}', '[]', '"hi"', 'true', 'false', 'null', '0', '-12.5e+3',
        '{"a": 1}', '{"a": [1, 2, {"b": null}], "c": "x\\n"}',
        '[", \\" {] [", -0.5]', '{"u": "\\u00e9"}', '  { "k" : [ ] } ',
        '{"": 0}',
    ])
    def test_accepts_valid(self, text):
        a = feed(text)
        assert a is not None and a.can_terminate
        json.loads(text)   # sanity: stdlib agrees

    @pytest.mark.parametrize("text", [
        '{', '{"a" 1}', '{"a": 1,}', '[1 2]', '01', '1.', '1e', '--1',
        'tru', '{"a": }', '}', '"\\x"', '{"a": "b",}', '[1,]', 'nul ',
    ])
    def test_rejects_or_incomplete(self, text):
        a = feed(text)
        # either a character was rejected, or the value cannot end here
        assert a is None or not a.can_terminate

    def test_trailing_junk_rejected(self):
        a = feed('{"a": 1}')
        assert a.complete
        assert not a.accept('x')
        assert a.accept(' ')       # trailing whitespace is fine

    @pytest.mark.parametrize("prefix", [
        '', '{', '{"key', '{"key": ', '{"a": [1, {"b": "x', '-1.2e',
        '{"a": tr', '{"s": "esc\\',
    ])
    def test_minimal_completion_closes_any_prefix(self, prefix):
        a = feed(prefix)
        assert a is not None, prefix
        completion = a.minimal_completion()
        done = feed(prefix + completion)
        assert done is not None and done.can_terminate
        if prefix + completion:
            json.loads(prefix + completion)


class TestConstrainedEngine:
    def _engine(self, paged=False, **ecfg_kw):
        cfg = TINY.replace(max_seq_len=256)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        defaults = dict(max_batch=4, max_seq_len=128, max_new_tokens=48,
                        prefill_buckets=(32, 64), temperature=0.0)
        defaults.update(ecfg_kw)
        ecfg = EngineConfig(**defaults)
        tok = get_tokenizer()
        if paged:
            eng = PagedInferenceEngine(cfg, ecfg, params, tok,
                                       use_kernel=False)
        else:
            eng = InferenceEngine(cfg, ecfg, params, tok)
        return eng, tok

    def _run(self, eng, tok, prompts, **kw):
        ids = [eng.submit(tok.encode(p, add_bos=True),
                          grammar=JsonGrammar(tok), **kw) for p in prompts]
        results = {r.seq_id: r for r in eng.run_to_completion()}
        return [results[i] for i in ids]

    def test_greedy_random_model_emits_valid_json(self):
        eng, tok = self._engine()
        outs = self._run(eng, tok, ["report the incident as json",
                                    "another prompt entirely"])
        for r in outs:
            parsed = json.loads(r.text)   # must not raise
            assert parsed is not None or parsed is None  # any JSON value

    def test_stochastic_sampling_stays_in_grammar(self):
        eng, tok = self._engine(temperature=1.0, top_k=40)
        outs = self._run(eng, tok, ["a", "b", "c", "d"])
        for r in outs:
            json.loads(r.text)

    def test_budget_exhaustion_force_closes(self):
        # tiny budget: the FSM must close whatever structure it opened
        eng, tok = self._engine(temperature=1.0)
        outs = self._run(eng, tok, ["x", "y"], max_new_tokens=7)
        for r in outs:
            json.loads(r.text)
            assert len(r.token_ids) <= 7

    def test_paged_engine_with_preemption_keeps_grammar(self):
        # tight pool forces growth-path preemption mid-generation; the FSM
        # must survive the requeue/resume cycle
        eng, tok = self._engine(paged=True, max_batch=3, max_seq_len=64,
                                page_size=8, num_pages=12,
                                prefill_buckets=(16,), temperature=1.0)
        outs = self._run(eng, tok, ["aaaaaaaaaaaa", "bbbbbbbbbbbb",
                                    "cccccccccccc"], max_new_tokens=24)
        assert len(outs) == 3
        for r in outs:
            json.loads(r.text)
        eng.allocator.check()

    def test_eos_finish_reason_and_no_trailing_garbage(self):
        eng, tok = self._engine()
        (r,) = self._run(eng, tok, ["emit json"])
        assert r.finish_reason in ("eos", "length")
        # json.loads only succeeds if the ENTIRE text is one JSON value
        # (plus whitespace) — parsing is itself the no-trailing-junk proof
        json.loads(r.text)


class TestBackendIntegration:
    def test_gen_options_grammar_roundtrip(self):
        from k8s_llm_rca_tpu.serve.api import AssistantService
        from k8s_llm_rca_tpu.serve.backend import EngineBackend, GenOptions

        cfg = TINY.replace(max_seq_len=256)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        ecfg = EngineConfig(max_batch=2, max_seq_len=128, max_new_tokens=32,
                            prefill_buckets=(32, 64))
        tok = get_tokenizer()
        backend = EngineBackend(InferenceEngine(cfg, ecfg, params, tok))
        service = AssistantService(backend)
        asst = service.create_assistant(
            "emit json", "t", "m",
            gen=GenOptions(max_new_tokens=32, forced_prefix="```json\n",
                           suffix="\n```", grammar="json"))
        th = service.create_thread()
        service.add_message(th.id, "incident: pod failed")
        run = service.create_run(th.id, asst.id)
        run = service.wait_run(run.id)
        assert run.status == "completed"
        text = service.list_messages(th.id, limit=1).data[0] \
            .content[0].text.value
        assert text.startswith("```json\n") and text.endswith("\n```")
        body = text[len("```json\n"):-len("\n```")]
        json.loads(body)

    def test_make_grammar_rejects_unknown(self):
        with pytest.raises(ValueError):
            make_grammar("yaml", get_tokenizer())
        assert make_grammar(None, get_tokenizer()) is None


# ---------------------------------------------------------------------------
# schema-constrained decoding (structured outputs)
# ---------------------------------------------------------------------------

KINDS = ("ConfigMap", "Pod", "PodDisruptionBudget", "Secret", "nfs")

PLAN_SCHEMA = {"type": "object", "properties": [
    ("SourceKind", {"enum": list(KINDS)}),
    ("DestinationKind", {"enum": list(KINDS)}),
    ("RelevantResources", {"type": "array", "items": {"enum": list(KINDS)},
                           "min_items": 1, "max_items": 5}),
    ("PrimaryPath", {"type": "array", "min_items": 1, "max_items": 4,
                     "items": {"type": "object", "properties": [
                         ("Edge", {"type": "integer", "max_digits": 2}),
                         ("start", {"enum": list(KINDS)}),
                         ("end", {"enum": list(KINDS)})]}}),
]}


def schema_feed(schema, text):
    from k8s_llm_rca_tpu.engine.constrain import (
        SchemaAutomaton, _compile_schema,
    )

    a = SchemaAutomaton(_compile_schema(schema))
    for ch in text:
        if not a.accept(ch):
            return None
    return a


class TestSchemaAutomaton:
    def test_accepts_conforming_document(self):
        doc = ('{"SourceKind": "Pod", "DestinationKind": "Secret", '
               '"RelevantResources": ["Pod", "nfs"], '
               '"PrimaryPath": [{"Edge": 1, "start": "Pod", "end": "Secret"},'
               ' {"Edge": 12, "start": "PodDisruptionBudget", "end": "nfs"}]}')
        a = schema_feed(PLAN_SCHEMA, doc)
        assert a is not None and a.complete
        json.loads(doc)

    @pytest.mark.parametrize("doc", [
        '{"SourceKind": "Pox',                  # not an enum continuation
        '{"sourceKind',                         # wrong key
        '{"SourceKind": "Pod", "DestinationKind": "Pod", '
        '"RelevantResources": [], ',            # below min_items
        '{"SourceKind": "Pod", "DestinationKind": "Pod", '
        '"RelevantResources": ["Pod", "Pod", "Pod", "Pod", "Pod", "P',
        '{"SourceKind": 3',                     # wrong type
    ])
    def test_rejects_nonconforming(self, doc):
        assert schema_feed(PLAN_SCHEMA, doc) is None

    def test_enum_prefix_ambiguity(self):
        # "Pod" is a strict prefix of "PodDisruptionBudget": both the early
        # close and the continuation must be legal at the fork
        head = '{"SourceKind": "Pod'
        a = schema_feed(PLAN_SCHEMA, head)
        assert a.clone().accept('"')
        assert a.clone().accept('D')
        assert not a.clone().accept('X')

    @pytest.mark.parametrize("prefix", [
        '', '{', '{"SourceKind": "', '{"SourceKind": "PodD',
        '{"SourceKind": "Pod", "DestinationKind": "nfs", '
        '"RelevantResources": ["Secret"',
        '{"SourceKind": "Pod", "DestinationKind": "Pod", '
        '"RelevantResources": ["Pod"], "PrimaryPath": [{"Edge": 4',
    ])
    def test_minimal_completion_closes_any_prefix(self, prefix):
        a = schema_feed(PLAN_SCHEMA, prefix)
        assert a is not None, prefix
        completion = a.minimal_completion()
        done = schema_feed(PLAN_SCHEMA, prefix + completion)
        assert done is not None and done.complete
        parsed = json.loads(prefix + completion)
        assert parsed["DestinationKind"] in KINDS

    def test_integer_rules(self):
        schema = {"type": "object",
                  "properties": [("n", {"type": "integer", "max_digits": 3})]}
        assert schema_feed(schema, '{"n": 0}').complete
        assert schema_feed(schema, '{"n": 123}').complete
        assert schema_feed(schema, '{"n": 01') is None      # leading zero
        assert schema_feed(schema, '{"n": 1234') is None    # over max_digits

    def test_boolean_and_free_string(self):
        schema = {"type": "object", "properties": [
            ("ok", {"type": "boolean"}),
            ("note", {"type": "string", "max_len": 4})]}
        assert schema_feed(schema, '{"ok": true, "note": "ab"}').complete
        assert schema_feed(schema, '{"ok": false, "note": ""}').complete
        assert schema_feed(schema, '{"ok": maybe') is None
        assert schema_feed(schema, '{"ok": true, "note": "abcde') is None


class TestSchemaGrammar:
    def _random_walk(self, grammar, tok, budget, seed=0, pick="choice"):
        import numpy as np

        rng = np.random.default_rng(seed)
        out = []
        for step in range(budget):
            c = grammar.constraint(remaining=budget - step)
            if c.force is not None:
                t = c.force
            else:
                allowed = np.flatnonzero(c.allow)
                t = int(allowed[-1]) if pick == "last" \
                    else int(rng.choice(allowed))
            if t == tok.eos_id:
                return out
            grammar.advance(t)
            out.append(t)
        raise AssertionError("schema decode never terminated")

    def test_random_walk_parses_and_respects_enums(self):
        from k8s_llm_rca_tpu.engine.constrain import SchemaGrammar

        tok = get_tokenizer()
        for seed in range(3):
            g = SchemaGrammar(PLAN_SCHEMA, tok)
            ids = self._random_walk(g, tok, budget=600, seed=seed)
            parsed = json.loads(tok.decode(ids))
            assert set(parsed) == {"SourceKind", "DestinationKind",
                                   "RelevantResources", "PrimaryPath"}
            assert parsed["DestinationKind"] in KINDS
            assert all(r in KINDS for r in parsed["RelevantResources"])
            for edge in parsed["PrimaryPath"]:
                assert edge["start"] in KINDS and edge["end"] in KINDS

    def test_budget_force_close_still_parses(self):
        from k8s_llm_rca_tpu.engine.constrain import SchemaGrammar

        tok = get_tokenizer()
        g = SchemaGrammar(PLAN_SCHEMA, tok)
        lo = g.min_budget()
        for budget in (lo + 1, lo + 30):
            g = SchemaGrammar(PLAN_SCHEMA, tok)
            ids = self._random_walk(g, tok, budget=budget, pick="last")
            json.loads(tok.decode(ids))

    def test_min_budget_rejected_by_backend(self):
        from k8s_llm_rca_tpu.serve.api import AssistantService
        from k8s_llm_rca_tpu.serve.backend import EngineBackend, GenOptions

        cfg = TINY.replace(max_seq_len=256)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        ecfg = EngineConfig(max_batch=2, max_seq_len=256,
                            prefill_buckets=(64,))
        backend = EngineBackend(InferenceEngine(cfg, ecfg, params,
                                                get_tokenizer()))
        with pytest.raises(ValueError, match="minimal document"):
            backend.start("p", GenOptions(max_new_tokens=8,
                                          grammar=PLAN_SCHEMA))

    def test_engine_decode_under_schema(self):
        from k8s_llm_rca_tpu.engine.constrain import SchemaGrammar

        cfg = TINY.replace(max_seq_len=1024)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        ecfg = EngineConfig(max_batch=2, max_seq_len=1024,
                            prefill_buckets=(64,), max_new_tokens=512,
                            temperature=1.0)
        tok = get_tokenizer()
        eng = InferenceEngine(cfg, ecfg, params, tok)
        sid = eng.submit(tok.encode("plan the incident", add_bos=True),
                         max_new_tokens=512,
                         grammar=SchemaGrammar(PLAN_SCHEMA, tok))
        (res,) = eng.run_to_completion()
        assert res.seq_id == sid
        parsed = json.loads(res.text)
        assert parsed["DestinationKind"] in KINDS

    def test_make_grammar_accepts_schema_dict(self):
        from k8s_llm_rca_tpu.engine.constrain import (
            DFAGrammar, SchemaGrammar,
        )

        g = make_grammar(PLAN_SCHEMA, get_tokenizer())
        # schemas compile to the DFA-backed grammar (SchemaGrammar is the
        # fallback for state-space blowups)
        assert isinstance(g, (DFAGrammar, SchemaGrammar))
        if isinstance(g, DFAGrammar):
            assert g.tables.n_states > 0


class TestCompiledDFA:
    """Schema grammars compiled to token-level DFA tables: on-device
    constrained decode (engine.decode_scan_dfa) with zero per-token host
    work.  The DFA must be constraint-for-constraint equivalent to the
    interpreted SchemaGrammar."""

    STRING_SCHEMA = {"type": "object", "properties": [
        ("note", {"type": "string", "max_len": 10}),
        ("n", {"type": "integer", "max_digits": 3}),
        ("ok", {"type": "boolean"})]}

    @staticmethod
    def _as_set(c):
        import numpy as np

        return ({int(c.force)} if c.force is not None
                else set(np.flatnonzero(c.allow).tolist()))

    @pytest.mark.parametrize("schema", [PLAN_SCHEMA, STRING_SCHEMA])
    def test_matches_interpreted_grammar(self, schema):
        import numpy as np

        from k8s_llm_rca_tpu.engine.constrain import (
            DFAGrammar, SchemaGrammar,
        )

        tok = get_tokenizer()
        for seed in range(3):
            rng = np.random.default_rng(seed)
            ref, dfa = SchemaGrammar(schema, tok), DFAGrammar(schema, tok)
            budget = 700
            for step in range(budget):
                sr = self._as_set(ref.constraint(remaining=budget - step))
                sd = self._as_set(dfa.constraint(remaining=budget - step))
                if len(sr) > 1 or len(sd) > 1:
                    # non-forced steps must agree exactly; forced closes
                    # may differ only in equally-minimal path choice
                    assert sr == sd, (seed, step, sorted(sr ^ sd)[:6])
                t = (next(iter(sr)) if len(sr) == 1
                     else int(rng.choice(sorted(sr))))
                if t == tok.eos_id:
                    break
                ref.advance(t)
                dfa.advance(t)
            else:
                raise AssertionError("walk never terminated")
            assert ref.done == dfa.done

    def test_make_grammar_compiles_schemas(self):
        from k8s_llm_rca_tpu.engine.constrain import DFAGrammar

        g = make_grammar(PLAN_SCHEMA, get_tokenizer())
        assert isinstance(g, DFAGrammar)
        assert g.tables.n_states > 100
        # tables are cached per tokenizer: same object on re-make
        tok = get_tokenizer()
        assert make_grammar(PLAN_SCHEMA, tok).tables \
            is make_grammar(PLAN_SCHEMA, tok).tables

    def test_engine_chunked_scan_matches_stepwise(self):
        """The DFA rides inside the decode scan: chunked greedy output ==
        per-tick host-FSM output, and both parse + respect enums."""
        outs = {}
        tok = get_tokenizer()
        cfg = TINY.replace(max_seq_len=512)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        for chunk in (1, 8):
            ecfg = EngineConfig(max_batch=2, max_seq_len=512,
                                prefill_buckets=(32,), max_new_tokens=256,
                                temperature=0.0, decode_chunk=chunk)
            eng = InferenceEngine(cfg, ecfg, params, tok)
            ids = [eng.submit(tok.encode(p, add_bos=True),
                              grammar=make_grammar(PLAN_SCHEMA, tok),
                              max_new_tokens=256)
                   for p in ("plan a", "plan b")]
            res = {r.seq_id: r for r in eng.run_to_completion()}
            outs[chunk] = [res[i].text for i in ids]
            for text in outs[chunk]:
                parsed = json.loads(text)
                assert parsed["DestinationKind"] in KINDS
        assert outs[1] == outs[8]

    def test_engine_scan_mixed_grammar_and_free_slots(self):
        """A scan batch mixing one DFA-constrained slot with unconstrained
        slots: the FREE state row leaves free slots untouched."""
        tok = get_tokenizer()
        cfg = TINY.replace(max_seq_len=256)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        ecfg = EngineConfig(max_batch=3, max_seq_len=256,
                            prefill_buckets=(32,), max_new_tokens=200,
                            temperature=0.0, decode_chunk=8)
        eng = InferenceEngine(cfg, ecfg, params, tok)
        gid = eng.submit(tok.encode("plan", add_bos=True),
                         grammar=make_grammar(PLAN_SCHEMA, tok),
                         max_new_tokens=200)
        fids = [eng.submit(tok.encode(p, add_bos=True), max_new_tokens=24)
                for p in ("free one", "free two")]
        # reference for the free slots: same engine config, no grammar slot
        ref_eng = InferenceEngine(cfg, ecfg, params, tok)
        ref_ids = [ref_eng.submit(tok.encode(p, add_bos=True),
                                  max_new_tokens=24)
                   for p in ("free one", "free two")]
        res = {r.seq_id: r for r in eng.run_to_completion()}
        ref = {r.seq_id: r for r in ref_eng.run_to_completion()}
        json.loads(res[gid].text)
        for f, r in zip(fids, ref_ids):
            assert res[f].token_ids == ref[r].token_ids

    def test_engine_scan_fuses_heterogeneous_grammars(self):
        """Slots carrying DIFFERENT compiled schemas decode in ONE fused
        scan (offset-relabeled stacked tables) instead of degrading to
        stepwise ticks, and emit exactly what a stepwise engine emits.
        This is the shared-engine sweep shape: planner/reporter schemas
        from different workers in flight at once."""
        tok = get_tokenizer()
        other_schema = {"type": "object", "properties": [
            ("verdict", {"enum": ["healthy", "broken"]}),
            ("score", {"type": "integer", "max_digits": 2}),
        ]}
        cfg = TINY.replace(max_seq_len=256)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))

        def run(chunk):
            ecfg = EngineConfig(max_batch=3, max_seq_len=256,
                                prefill_buckets=(32,), max_new_tokens=200,
                                temperature=0.0, decode_chunk=chunk)
            eng = InferenceEngine(cfg, ecfg, params, tok)
            a = eng.submit(tok.encode("plan", add_bos=True),
                           grammar=make_grammar(PLAN_SCHEMA, tok),
                           max_new_tokens=200)
            b = eng.submit(tok.encode("verdict", add_bos=True),
                           grammar=make_grammar(other_schema, tok),
                           max_new_tokens=64)
            c = eng.submit(tok.encode("free text", add_bos=True),
                           max_new_tokens=24)
            res = {r.seq_id: r for r in eng.run_to_completion()}
            return eng, (res[a], res[b], res[c])

        eng_scan, scan = run(chunk=8)
        _, step = run(chunk=1)
        for s, t in zip(scan, step):
            assert s.token_ids == t.token_ids
        assert json.loads(scan[0].text)["DestinationKind"] in KINDS
        v = json.loads(scan[1].text)
        assert v["verdict"] in ("healthy", "broken")
        # the fused path actually ran: one cache entry stacking BOTH tables
        fused = getattr(eng_scan, "_dfa_fused", {})
        assert any(len(key) == 2 for key in fused), list(fused)

    def test_engine_scan_continues_with_queued_admissions(self):
        """A full engine with pendings queued keeps taking chunked scan
        ticks (queued admissions no longer force per-token ticks); queued
        work still admits and completes, greedy-identical to stepwise."""
        tok = get_tokenizer()
        cfg = TINY.replace(max_seq_len=128)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))

        def run(chunk):
            ecfg = EngineConfig(max_batch=2, max_seq_len=128,
                                prefill_buckets=(32,), max_new_tokens=24,
                                temperature=0.0, decode_chunk=chunk)
            eng = InferenceEngine(cfg, ecfg, params, tok)
            ids = [eng.submit(tok.encode(p, add_bos=True),
                              max_new_tokens=24)
                   for p in ("alpha", "beta", "gamma", "delta", "epsilon")]
            res = {r.seq_id: r for r in eng.run_to_completion()}
            return [res[i] for i in ids]

        scan, step = run(chunk=8), run(chunk=1)
        for s, t in zip(scan, step):
            assert s.token_ids == t.token_ids

    def test_engine_budget_force_close_on_device(self):
        """Tight budgets force-close THROUGH the scan: output still parses."""
        tok = get_tokenizer()
        cfg = TINY.replace(max_seq_len=512)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        ecfg = EngineConfig(max_batch=1, max_seq_len=512,
                            prefill_buckets=(32,), max_new_tokens=256,
                            temperature=1.0, top_k=40, decode_chunk=8)
        eng = InferenceEngine(cfg, ecfg, params, tok)
        g = make_grammar(PLAN_SCHEMA, tok)
        budget = g.min_budget() + 8
        sid = eng.submit(tok.encode("x", add_bos=True), grammar=g,
                         max_new_tokens=budget)
        (res,) = eng.run_to_completion()
        assert res.seq_id == sid
        parsed = json.loads(res.text)
        assert parsed["DestinationKind"] in KINDS
        assert res.completion_tokens <= budget

    def test_paged_engine_chunked_scan_matches_stepwise(self):
        """The DFA scan also runs on the PAGED engine (chunk bounded by
        page boundaries): chunked greedy output == stepwise output."""
        outs = {}
        tok = get_tokenizer()
        cfg = TINY.replace(max_seq_len=512)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        for chunk in (1, 8):
            ecfg = EngineConfig(max_batch=2, max_seq_len=512, paged=True,
                                page_size=16, num_pages=80,
                                prefill_buckets=(32,), max_new_tokens=256,
                                temperature=0.0, decode_chunk=chunk)
            eng = PagedInferenceEngine(cfg, ecfg, params, tok,
                                       use_kernel=False)
            ids = [eng.submit(tok.encode(p, add_bos=True),
                              grammar=make_grammar(PLAN_SCHEMA, tok),
                              max_new_tokens=256)
                   for p in ("plan a", "plan b")]
            res = {r.seq_id: r for r in eng.run_to_completion()}
            outs[chunk] = [res[i].text for i in ids]
            for text in outs[chunk]:
                parsed = json.loads(text)
                assert parsed["DestinationKind"] in KINDS
            eng.allocator.check()
        assert outs[1] == outs[8]

    def test_paged_scan_crosses_page_boundaries(self):
        """decode_chunk larger than page_size: the growth pass
        pre-allocates the scan window, the chunk crosses page boundaries
        inside one dispatch, and output is greedy-identical to stepwise
        (allocator invariants intact)."""
        outs = {}
        tok = get_tokenizer()
        cfg = TINY.replace(max_seq_len=256)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        for chunk in (1, 16):
            ecfg = EngineConfig(max_batch=2, max_seq_len=256, paged=True,
                                page_size=4, num_pages=140,
                                prefill_buckets=(32,), max_new_tokens=48,
                                temperature=0.0, decode_chunk=chunk)
            eng = PagedInferenceEngine(cfg, ecfg, params, tok,
                                       use_kernel=False)
            ids = [eng.submit(tok.encode(p, add_bos=True),
                              max_new_tokens=48)
                   for p in ("free one", "free two")]
            res = {r.seq_id: r for r in eng.run_to_completion()}
            outs[chunk] = [res[i].token_ids for i in ids]
            eng.allocator.check()
        assert outs[1] == outs[16]

    def test_schema_string_escapes(self):
        """Opt-in escape pairs in schema strings: quoted kubectl/JSON
        content is expressible where the field declares escapes=True,
        rejected where it doesn't."""
        from k8s_llm_rca_tpu.engine.constrain import DFAGrammar

        schema = {"type": "object", "properties": [
            ("plain", {"type": "string", "max_len": 10}),
            ("cmd", {"type": "string", "max_len": 60, "escapes": True})]}
        ok = ('{"plain": "abc", '
              '"cmd": "kubectl -p \'{\\"a\\": \\"b\\"}\'"}')
        a = schema_feed(schema, ok)
        assert a is not None and a.complete
        import json as _json

        assert _json.loads(ok)["cmd"].count('"') == 4
        # a backslash in the non-escaping field is illegal
        assert schema_feed(schema, '{"plain": "a\\\\') is None
        # a lone backslash escapes the closing quote: the string (and the
        # document) must remain open
        dangling = schema_feed(schema, '{"plain": "abc", "cmd": "x\\"}')
        assert dangling is not None and not dangling.complete
        # an escaped backslash then quote closes it: valid JSON
        closed = schema_feed(schema, '{"plain": "abc", "cmd": "x\\\\"}')
        assert closed is not None and closed.complete
        # the DFA path accepts the same document
        tok = get_tokenizer()
        g = DFAGrammar(schema, tok)
        for t in tok.encode(ok):
            g.advance(t)
        assert g.done

    def test_report_schema_fits_32k_vocab_budget(self):
        """The RCA report schema must stay compilable to an on-device DFA
        at production vocab sizes (the on-device guarantee in docs/rca.md
        depends on it)."""
        from k8s_llm_rca_tpu.engine.constrain import (
            _DFA_MAX_TABLE_BYTES, _compile_schema, _enumerate_char_dfa,
        )
        from k8s_llm_rca_tpu.rca.auditor import report_schema

        tok = get_tokenizer()
        strings = [tok.decode([t]) for t in range(tok.vocab_size)]
        alphabet = sorted(set("".join(strings)))
        cn, _ = _enumerate_char_dfa(_compile_schema(report_schema()),
                                    alphabet, max_states=10**6)
        assert cn.shape[0] <= _DFA_MAX_TABLE_BYTES // (5 * 32000)


# ---------------------------------------------------------------------------
# raw-text template nodes (choice / seq) — the stage-2 Cypher skeleton
# grammar (rca/cyphergen.cypher_query_schema) is built from these
# ---------------------------------------------------------------------------


def test_choice_node_accepts_each_option_exactly():
    from k8s_llm_rca_tpu.engine.constrain import (
        SchemaAutomaton, _compile_schema,
    )

    schema = {"type": "choice", "options": ["MATCH (n:Pod)\nRETURN n",
                                            "MATCH (p:Node)\nRETURN p"]}
    for opt in schema["options"]:
        auto = SchemaAutomaton(_compile_schema(schema))
        for ch in opt:
            assert auto.accept(ch), (opt, ch)
        assert auto.complete
    # diverging from every option is rejected at the divergence point
    auto = SchemaAutomaton(_compile_schema(schema))
    for ch in "MATCH (":
        assert auto.accept(ch)
    assert not auto.accept("x")


def test_choice_node_rejects_prefix_pairs_and_empty():
    from k8s_llm_rca_tpu.engine.constrain import _compile_schema

    with pytest.raises(ValueError, match="prefix-free"):
        _compile_schema({"type": "choice", "options": ["ab", "abc"]})
    with pytest.raises(ValueError, match="non-empty"):
        _compile_schema({"type": "choice", "options": []})
    with pytest.raises(ValueError, match="non-empty"):
        _compile_schema({"type": "choice", "options": ["a", ""]})
    # a single option degrades to a literal
    assert _compile_schema({"type": "choice", "options": ["one"]}) == \
        ("lit", "one")


def test_seq_node_concatenates_raw():
    from k8s_llm_rca_tpu.engine.constrain import (
        SchemaAutomaton, _compile_schema,
    )

    schema = {"type": "seq", "items": [
        {"const": "score="},
        {"type": "integer", "max_digits": 2},
        {"const": ";"}]}
    auto = SchemaAutomaton(_compile_schema(schema))
    for ch in "score=42;":
        assert auto.accept(ch), ch
    assert auto.complete


def test_choice_engine_scan_emits_one_option_exactly():
    """A raw-text choice grammar through the REAL engine (DFA in-scan):
    random weights must emit one option verbatim, chunked == stepwise."""
    import jax

    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import InferenceEngine
    from k8s_llm_rca_tpu.models import llama

    cfg = TINY
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    schema = {"type": "choice", "options": [
        "MATCH (evt:EVENT)\nWHERE evt.message CONTAINS 'x'\nRETURN evt",
        "MATCH (pod:Pod)-[r1:HasEvent]->(evt:EVENT)\nRETURN pod, r1, evt"]}
    outs = {}
    for chunk in (1, 8):
        eng = InferenceEngine(
            cfg, EngineConfig(max_batch=2, max_seq_len=256,
                              prefill_buckets=(16,), max_new_tokens=128,
                              decode_chunk=chunk), params, tok)
        rid = eng.submit(tok.encode("q:", add_bos=True), max_new_tokens=128,
                         grammar=make_grammar(schema, tok))
        res = {r.seq_id: r for r in eng.run_to_completion()}
        outs[chunk] = res[rid].text
    assert outs[1] == outs[8]
    assert outs[1] in schema["options"]


def test_cypher_schema_variants_compile_and_run():
    """cypher_query_schema's options are exactly the deterministic
    compiler's two alias styles, and BOTH execute against the stategraph
    (valid mini-Cypher, same records)."""
    from k8s_llm_rca_tpu.graph import InMemoryGraphExecutor
    from k8s_llm_rca_tpu.graph.fixtures import INCIDENTS, build_stategraph
    from k8s_llm_rca_tpu.rca import cyphergen

    mp = ("\n    HasEvent, Event, EVENT, metadata_uid;\n"
          "    ReferInternal, Event, Pod, involvedObject_uid;\n"
          "    ReferInternal, Pod, ConfigMap, spec_volumes_configMap_name;\n")
    msg = INCIDENTS[0].message
    schema = cyphergen.cypher_query_schema(mp, msg)
    assert schema["type"] == "choice" and len(schema["options"]) == 2
    ex = InMemoryGraphExecutor(build_stategraph())
    results = [ex.run_query(q) for q in schema["options"]]
    assert len(results[0]) == len(results[1])


def test_choice_dedups_by_value_and_seq_rejects_empty():
    from k8s_llm_rca_tpu.engine.constrain import _compile_schema

    s = "same option"
    assert _compile_schema({"type": "choice", "options": [s, s]}) == \
        ("lit", s)
    with pytest.raises(ValueError, match="non-empty"):
        _compile_schema({"type": "seq", "items": []})


def test_template_grammar_dfa_policy():
    """Small template (choice/seq) grammars now COMPILE to DFA tables so
    they ride the fused on-device scan (an interpreted slot would force
    the whole shared batch to stepwise host ticks); templates whose
    estimated table exceeds the one-shot budget still route to the
    interpreted FSM, which forces agreed spans O(1) per tick."""
    from k8s_llm_rca_tpu.engine.constrain import (
        _DFA_TEMPLATE_TABLE_BYTES, DFAGrammar, SchemaGrammar,
    )

    tok = get_tokenizer()
    schema = {"type": "choice", "options": ["alpha variant one",
                                            "beta variant two"]}
    g = make_grammar(schema, tok)
    assert isinstance(g, DFAGrammar)

    # oversized template: estimate (json chars x vocab x 5B) > budget
    n = _DFA_TEMPLATE_TABLE_BYTES // (tok.vocab_size * 5) + 64
    big = {"type": "choice", "options": ["x" * n, "y" * n]}
    g_big = make_grammar(big, tok)
    assert isinstance(g_big, SchemaGrammar)
    # after the first char narrows to one candidate, the span is forced
    g_big.advance(tok.encode("x")[0])
    c = g_big.constraint(4 * n)
    assert c.force is not None


# ---------------------------------------------------------------------------
# bounded any-JSON DFA (grammar="json" on the fast path)
# ---------------------------------------------------------------------------


def test_bounded_json_automaton_accepts_canonical_docs():
    from k8s_llm_rca_tpu.engine.constrain import (
        SchemaAutomaton, _compile_schema,
    )

    root = _compile_schema({"type": "json"})
    for doc in ['true', 'null', '"hi there"', '[]', '[1, 2, 3]', '[42]',
                '{}', '{"a": 1, "b": [true, "x"]}', '[{"k": null}]',
                '{"s": "with \\"esc\\" ok"}']:
        auto = SchemaAutomaton(root)
        assert all(auto.accept(ch) for ch in doc) and auto.complete, doc


def test_bounded_json_depth_cap_rejects():
    from k8s_llm_rca_tpu.engine.constrain import (
        SchemaAutomaton, _compile_schema,
    )

    auto = SchemaAutomaton(_compile_schema({"type": "json", "max_depth": 2}))
    assert not all(auto.accept(ch) for ch in "[[[[")


def test_json_grammar_compiles_to_dfa_and_scan_parity():
    """grammar="json" now rides the on-device DFA scan (VERDICT r2 item
    6): chunked scan and stepwise host ticks emit identical parseable
    JSON from random weights."""
    import jax
    import json as jsonlib

    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import InferenceEngine
    from k8s_llm_rca_tpu.engine.constrain import DFAGrammar
    from k8s_llm_rca_tpu.models import llama

    cfg = TINY
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    outs = {}
    for chunk in (1, 8):
        eng = InferenceEngine(
            cfg, EngineConfig(max_batch=2, max_seq_len=256,
                              prefill_buckets=(16,), max_new_tokens=64,
                              decode_chunk=chunk), params, tok)
        g = make_grammar("json", tok)
        assert isinstance(g, DFAGrammar)
        rid = eng.submit(tok.encode("emit json:", add_bos=True),
                         max_new_tokens=64, grammar=g)
        res = {r.seq_id: r for r in eng.run_to_completion()}
        outs[chunk] = res[rid].text
    assert outs[1] == outs[8]
    jsonlib.loads(outs[1])


def test_json_node_composes_inside_schema():
    """{"type": "json"} as a FIELD of a structured output: bounded free-
    form JSON inside a fixed envelope."""
    from k8s_llm_rca_tpu.engine.constrain import (
        SchemaAutomaton, _compile_schema,
    )

    schema = {"type": "object", "properties": [
        ("tag", {"enum": ["ok"]}),
        ("data", {"type": "json", "max_depth": 1})]}
    for doc in ('{"tag": "ok", "data": [1, true, "x"]}',
                # nested json keeps the bare-int child: the envelope's
                # closing brace is the delimiter that pops it
                '{"tag": "ok", "data": 7}'):
        auto = SchemaAutomaton(_compile_schema(schema))
        assert all(auto.accept(ch) for ch in doc) and auto.complete, doc
