"""int8/int4 KV cache: numerics and engine mechanics."""

import jax
import jax.numpy as jnp
import numpy as np

from k8s_llm_rca_tpu.config import TINY, EngineConfig
from k8s_llm_rca_tpu.engine.engine import InferenceEngine
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer


def _decode_chain(cfg, params, cache, prompt, n_steps):
    toks = jnp.asarray([prompt], jnp.int32)
    cache, logits = llama.prefill(cfg, params, cache, toks,
                                  jnp.int32(len(prompt)), jnp.int32(0))
    all_logits = [np.asarray(logits[0])]
    lengths = jnp.asarray([len(prompt)], jnp.int32)
    cur = jnp.asarray([int(np.argmax(all_logits[-1]))], jnp.int32)
    for _ in range(n_steps):
        cache, lg = llama.decode_step(cfg, params, cache, cur, lengths)
        all_logits.append(np.asarray(lg[0]))
        lengths = lengths + 1
        cur = jnp.asarray([int(np.argmax(all_logits[-1]))], jnp.int32)
    return np.stack(all_logits)


def test_int8_cache_close_to_full_precision():
    cfg = TINY.replace(max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    prompt = list(range(5, 25))
    full = _decode_chain(cfg, params,
                         llama.init_cache(cfg, 1, 64), prompt, 6)
    q = _decode_chain(cfg, params,
                      llama.init_cache(cfg, 1, 64, kv_dtype=jnp.int8),
                      prompt, 6)
    assert np.isfinite(q).all()
    corr = np.corrcoef(full.ravel(), q.ravel())[0, 1]
    assert corr > 0.99, corr


def test_int8_cache_shapes_and_flag():
    cfg = TINY
    c = llama.init_cache(cfg, 2, 32, kv_dtype=jnp.int8)
    assert c.quantized and c.k.dtype == jnp.int8
    assert c.k_scale.shape == (cfg.n_layers, 2, 32)
    assert not llama.init_cache(cfg, 2, 32).quantized


def test_engine_with_int8_kv_cache():
    cfg = TINY.replace(max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    eng = InferenceEngine(
        cfg, EngineConfig(max_batch=2, max_seq_len=64,
                          prefill_buckets=(16, 32, 64), max_new_tokens=6,
                          temperature=0.0, kv_cache_dtype="int8"),
        params, tok)
    res = eng.generate([tok.encode("pod oom killed", add_bos=True),
                        tok.encode("pvc pending", add_bos=True)],
                       max_new_tokens=6)
    assert all(r.completion_tokens == 6 for r in res)
    assert eng.cache.quantized


def test_int4_cache_correlates_with_full_precision():
    cfg = TINY.replace(max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    prompt = list(range(5, 25))
    full = _decode_chain(cfg, params,
                         llama.init_cache(cfg, 1, 64), prompt, 6)
    q = _decode_chain(cfg, params,
                      llama.init_cache(cfg, 1, 64, kv_dtype="int4"),
                      prompt, 6)
    assert np.isfinite(q).all()
    # 4-bit KV with per-token scalar scales: noisier than int8 but the
    # logit structure must survive
    corr = np.corrcoef(full.ravel(), q.ravel())[0, 1]
    assert corr > 0.95, corr


def test_int4_cache_shapes_and_flag():
    cfg = TINY
    c = llama.init_cache(cfg, 2, 32, kv_dtype="int4")
    assert c.quantized and c.k.dtype == jnp.int8
    assert c.k.shape == (cfg.n_layers, 2, 32, cfg.kv_dim // 2)  # packed
    assert c.k_scale.shape == (cfg.n_layers, 2, 32)
    assert llama._kv_packed(cfg, c)
    assert not llama._kv_packed(cfg, llama.init_cache(cfg, 2, 32,
                                                      kv_dtype=jnp.int8))


def test_engine_with_int4_kv_cache():
    cfg = TINY.replace(max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    eng = InferenceEngine(
        cfg, EngineConfig(max_batch=2, max_seq_len=64,
                          prefill_buckets=(16, 32, 64), max_new_tokens=6,
                          temperature=0.0, kv_cache_dtype="int4"),
        params, tok)
    res = eng.generate([tok.encode("pod oom killed", add_bos=True),
                        tok.encode("pvc pending", add_bos=True)],
                       max_new_tokens=6)
    assert all(r.completion_tokens == 6 for r in res)
    assert eng.cache.quantized and eng.cache.k.shape[-1] == cfg.kv_dim // 2


def test_int4_cache_speculative_tick_runs():
    cfg = TINY.replace(max_seq_len=128)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    eng = InferenceEngine(
        cfg, EngineConfig(max_batch=1, max_seq_len=128,
                          prefill_buckets=(32, 64, 128), max_new_tokens=12,
                          temperature=0.0, kv_cache_dtype="int4",
                          speculative_k=4),
        params, tok)
    r = eng.generate([tok.encode("aaaa bbbb aaaa bbbb", add_bos=True)],
                     max_new_tokens=12)[0]
    assert r.completion_tokens == 12


def test_int8_cache_speculative_tick_runs():
    # decode_multi path with a quantized cache
    cfg = TINY.replace(max_seq_len=128)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    eng = InferenceEngine(
        cfg, EngineConfig(max_batch=1, max_seq_len=128,
                          prefill_buckets=(32, 64, 128), max_new_tokens=12,
                          temperature=0.0, kv_cache_dtype="int8",
                          speculative_k=4),
        params, tok)
    r = eng.generate([tok.encode("aaaa bbbb aaaa bbbb", add_bos=True)],
                     max_new_tokens=12)[0]
    assert r.completion_tokens == 12
