"""Pipelined cross-incident sweep scheduler (rca/scheduler.py + the
plan-free sweep driver faults/soak.py::run_pipelined_sweep) — ISSUE 11.

The acceptance bar is BYTE-IDENTITY: the pipelined sweep's report (per-
incident statuses, degradation annotations, attempt counts, decoded
cypher/audit text, exact run-id-attributed token usage) must serialize to
the same bytes at every concurrency, because greedy decode is batch-
invariant and the scheduler's interleave never reaches the prompts
(``fresh_threads``).  Everything scheduling-dependent (pump counts,
inflight samples, queue-wait spans) lives in ``out["stats"]`` and is
asserted separately.

Oracle-backed sweeps are sub-second at n=100, so the 100-incident
acceptance run is tier-1; engine-backed parity runs one small pair, and
the composition matrix (prefix cache x host overlap x chunked prefill x
speculative decode) is ``slow``-marked.
"""

import copy

import pytest

from k8s_llm_rca_tpu.config import RCAConfig
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.faults.plan import FaultPlan, VirtualClock
from k8s_llm_rca_tpu.faults.soak import (
    _build_oracle_service, default_plan_spec, report_bytes, run_chaos_soak,
    run_pipelined_sweep,
)
from k8s_llm_rca_tpu.graph import InMemoryGraphExecutor
from k8s_llm_rca_tpu.graph.fixtures import (
    INCIDENTS, build_metagraph, build_stategraph,
)
from k8s_llm_rca_tpu.rca import RCAPipeline
from k8s_llm_rca_tpu.rca.scheduler import IncidentFailure, SweepScheduler

pytestmark = pytest.mark.sweep

# matches no Event node -> the locator's deterministic retry-with-feedback
# path exhausts and the incident fails the same way at every concurrency
BOGUS = "flux capacitor underflow in warp nacelle {}"

# an armed-but-EMPTY plan: from_spec treats a falsy spec as "use the
# default chaos mix", so the no-fault spec must be truthy — a site with
# no indices and no rate samples zero faults (plan.has_faults False)
NOOP_SPEC = {"noop.site": {}}


def _oracle_stack(n_pipelines=1, fresh_threads=True, service=None):
    """n slot pipelines over one oracle service (soak constants)."""
    clock = VirtualClock()
    if service is None:
        service, _, _ = _build_oracle_service(1.5, clock)
    cfg = RCAConfig(locator_max_new_tokens=192, cypher_max_new_tokens=96,
                    analyzer_max_new_tokens=96,
                    fresh_threads=fresh_threads)
    pipelines = [
        RCAPipeline(service,
                    InMemoryGraphExecutor(build_metagraph()),
                    InMemoryGraphExecutor(build_stategraph()), cfg)
        for _ in range(n_pipelines)]
    return service, pipelines


def _mixed_messages(n_good=6, n_bogus=2):
    """Corpus incidents with deterministic failures interleaved."""
    msgs = [INCIDENTS[i % len(INCIDENTS)].message for i in range(n_good)]
    for j in range(n_bogus):
        msgs.insert(1 + 2 * j, BOGUS.format(j))
    return msgs


@pytest.fixture(autouse=True)
def _disarmed():
    """Never leak an armed plan into other tests."""
    yield
    if inject.active() is not None:
        inject.disarm()


# ---------------------------------------------------------------------------
# loud exclusions: every composition whose outputs would depend on
# scheduling must refuse with ValueError, not silently diverge
# ---------------------------------------------------------------------------


class TestExclusions:
    def test_concurrency_zero_refused(self):
        with pytest.raises(ValueError, match="concurrency must be >= 1"):
            run_pipelined_sweep(backend="oracle", concurrency=0)

    def test_empty_pipeline_list_refused(self):
        with pytest.raises(ValueError, match="at least one pipeline"):
            SweepScheduler([])

    def test_reused_pipeline_refused(self):
        _, (p,) = _oracle_stack(1)
        with pytest.raises(ValueError, match="OWN RCAPipeline"):
            SweepScheduler([p, p])

    def test_disjoint_services_refused(self):
        _, (p1,) = _oracle_stack(1)
        _, (p2,) = _oracle_stack(1)
        with pytest.raises(ValueError, match="ONE AssistantService"):
            SweepScheduler([p1, p2])

    def test_shared_threads_refused_above_one(self):
        service, pipelines = _oracle_stack(2, fresh_threads=False)
        with pytest.raises(ValueError, match="fresh_threads=True"):
            SweepScheduler(pipelines)

    def test_shared_threads_refused_even_at_k1_in_driver(self):
        # the K=1 leg is the parity BASELINE, so the driver holds it to
        # the same prompt regime as the K>1 legs
        with pytest.raises(ValueError, match="fresh_threads"):
            run_pipelined_sweep(backend="oracle", concurrency=1,
                                rca_overrides={"fresh_threads": False})

    def test_armed_faulted_plan_refused_above_one(self):
        service, pipelines = _oracle_stack(2)
        plan = FaultPlan.from_spec(0, default_plan_spec(),
                                   clock=VirtualClock())
        assert plan.has_faults
        sched = SweepScheduler(pipelines)
        with inject.armed(plan):
            with pytest.raises(ValueError, match="concurrency > 1"):
                sched.run([INCIDENTS[0].message] * 2)

    def test_armed_empty_plan_allowed_above_one(self):
        service, pipelines = _oracle_stack(2)
        plan = FaultPlan.from_spec(0, NOOP_SPEC, clock=VirtualClock())
        assert not plan.has_faults
        with inject.armed(plan):
            results = SweepScheduler(pipelines).run(
                [INCIDENTS[0].message, INCIDENTS[1].message])
        assert all(not isinstance(r, IncidentFailure) for r in results)

    def test_chaos_soak_faulted_plan_refused_above_one(self):
        with pytest.raises(ValueError, match="concurrency > 1"):
            run_chaos_soak(seed=0, n_incidents=2, backend="oracle",
                           concurrency=2)

    def test_chaos_soak_boundary_machinery_refused_above_one(self):
        # supervisor/killer/selfheal all poll once per incident BOUNDARY,
        # which a pipelined sweep does not have
        for kw in ({"supervisor": object()}, {"killer": object()},
                   {"selfheal": True}):
            with pytest.raises(ValueError, match="BOUNDARY"):
                run_chaos_soak(seed=0, n_incidents=2,
                               backend="cluster-oracle",
                               plan_spec=NOOP_SPEC, concurrency=2, **kw)

    def test_engine_overrides_need_engine_backend(self):
        for backend in ("oracle", "cluster-oracle"):
            with pytest.raises(ValueError, match="engine_overrides"):
                run_pipelined_sweep(backend=backend, concurrency=1,
                                    engine_overrides={"prefix_cache": True})


# ---------------------------------------------------------------------------
# oracle parity: the cheap backend proves the SCHEDULER invariant
# (prompt/interleave independence) at every concurrency
# ---------------------------------------------------------------------------


class TestOracleParity:
    def test_byte_identity_mixed_incidents_k_1_4_16(self):
        """Corpus incidents with deterministic failures interleaved:
        failed rows (error strings included) must also be byte-stable."""
        msgs = _mixed_messages(n_good=6, n_bogus=2)
        outs = {k: run_pipelined_sweep(backend="oracle", concurrency=k,
                                       incidents=msgs)
                for k in (1, 4, 16)}
        blobs = {k: report_bytes(o["report"]) for k, o in outs.items()}
        assert blobs[4] == blobs[1]
        assert blobs[16] == blobs[1]
        rep = outs[1]["report"]
        assert rep["failed"] == 2
        assert rep["completed"] == 6
        statuses = [r["status"] for r in rep["incidents"]]
        assert statuses.count("failed") == 2
        # evidence the K>1 legs actually interleaved (stats, not report)
        assert outs[4]["stats"]["inflight_max"] > 1
        assert outs[16]["stats"]["pumps"] < outs[1]["stats"]["pumps"]

    def test_byte_identity_with_resilience_ladder(self):
        out1 = run_pipelined_sweep(backend="oracle", concurrency=1,
                                   n_incidents=8, resilience=True)
        out4 = run_pipelined_sweep(backend="oracle", concurrency=4,
                                   n_incidents=8, resilience=True)
        assert report_bytes(out1["report"]) == report_bytes(out4["report"])
        # ladder counters are summed across slot policies in stats —
        # interleaving-invariant totals even though the split is not
        assert out4["stats"]["policy"]["counters"] \
            == out1["stats"]["policy"]["counters"]

    def test_hundred_incident_acceptance_twice_over(self):
        """The ISSUE 11 bar: a seeded 100-incident pipelined sweep,
        byte-identical to sequential AND to its own rerun."""
        out1 = run_pipelined_sweep(backend="oracle", concurrency=1,
                                   n_incidents=100)
        outa = run_pipelined_sweep(backend="oracle", concurrency=16,
                                   n_incidents=100)
        outb = run_pipelined_sweep(backend="oracle", concurrency=16,
                                   n_incidents=100)
        b1, ba, bb = (report_bytes(o["report"])
                      for o in (out1, outa, outb))
        assert ba == b1
        assert bb == ba
        assert out1["report"]["completed"] == 100
        assert outa["stats"]["inflight_max"] == 16
        assert outa["stats"]["inflight_mean"] > 8

    def test_scheduler_k1_matches_blocking_driver(self):
        """The scheduler at K=1 drives the SAME generator the blocking
        path does — results must match field for field (wall-clock cost
        excluded)."""
        msgs = [i.message for i in INCIDENTS[:3]]
        _, (p_sched,) = _oracle_stack(1)
        sched_results = SweepScheduler([p_sched]).run(msgs)
        _, (p_block,) = _oracle_stack(1)
        for msg, got in zip(msgs, sched_results):
            want = p_block.analyze_incident(msg, usage_by_runs=True)
            got, want = copy.deepcopy(got), copy.deepcopy(want)
            got.pop("time_cost", None)
            want.pop("time_cost", None)
            assert got == want


# ---------------------------------------------------------------------------
# cluster routing composes (oracle replicas; engine replicas are slow)
# ---------------------------------------------------------------------------


@pytest.mark.cluster
class TestClusterOracleParity:
    def test_byte_identity_k1_vs_k4(self):
        out1 = run_pipelined_sweep(backend="cluster-oracle", concurrency=1,
                                   n_incidents=8)
        out4 = run_pipelined_sweep(backend="cluster-oracle", concurrency=4,
                                   n_incidents=8)
        assert report_bytes(out1["report"]) == report_bytes(out4["report"])
        assert out1["report"]["cluster_replicas"] == 2
        assert out4["stats"]["inflight_max"] > 1


# ---------------------------------------------------------------------------
# durability: the journal records the interleaved truth and recovery
# agrees with the live service
# ---------------------------------------------------------------------------


class TestJournalAgreement:
    @staticmethod
    def _max_inflight_depth(path):
        """Max submitted-but-unsettled depth in journal record order."""
        from k8s_llm_rca_tpu.serve.journal import read_journal

        records, _ = read_journal(path)
        depth = peak = 0
        for rec in records:
            if rec.get("kind") == "run_submit":
                depth += 1
                peak = max(peak, depth)
            elif rec.get("kind") == "run_settle":
                depth -= 1
        return peak

    def test_journal_interleaves_and_recovery_agrees(self, tmp_path):
        import os

        from k8s_llm_rca_tpu.rca.oracle import OracleBackend
        from k8s_llm_rca_tpu.serve.recover import recover_service
        from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

        d1, d4 = str(tmp_path / "k1"), str(tmp_path / "k4")
        out1 = run_pipelined_sweep(backend="oracle", concurrency=1,
                                   n_incidents=6, durable_dir=d1)
        out4 = run_pipelined_sweep(backend="oracle", concurrency=4,
                                   n_incidents=6, durable_dir=d4)
        assert report_bytes(out1["report"]) == report_bytes(out4["report"])

        # the WAL is the scheduling truth: at K=4 strictly more runs sit
        # submitted-but-unsettled than the K=1 incident ever holds
        p1 = self._max_inflight_depth(os.path.join(d1, "serve.wal"))
        p4 = self._max_inflight_depth(os.path.join(d4, "serve.wal"))
        assert p4 > p1

        # replay onto a fresh backend: every run settled before close, so
        # nothing is resubmitted and every status agrees with the live
        # service the sweep returned
        svc = out4["service"]
        recovered, rep = recover_service(
            os.path.join(d4, "serve.wal"),
            OracleBackend(get_tokenizer()))
        assert rep["resubmitted"] == []
        assert set(recovered.runs) == set(svc.runs)
        for rid, run in recovered.runs.items():
            assert run.status == svc.runs[rid].status


# ---------------------------------------------------------------------------
# chaos soak at K>1: legal exactly when the armed plan is EMPTY, and then
# byte-identical to the sequential soak (poll counters included)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestChaosSoakEmptyPlan:
    def test_byte_identity_k1_vs_k2(self):
        r1 = run_chaos_soak(seed=0, n_incidents=4, backend="oracle",
                            plan_spec=NOOP_SPEC, concurrency=1)
        r2 = run_chaos_soak(seed=0, n_incidents=4, backend="oracle",
                            plan_spec=NOOP_SPEC, concurrency=2)
        assert report_bytes(r1) == report_bytes(r2)
        # the armed plan's per-site poll sums are in the report — setup
        # polls (pipeline construction) must NOT scale with concurrency
        assert r1["faults"]["polls"] == r2["faults"]["polls"]
        assert r1["failed"] == 0


# ---------------------------------------------------------------------------
# engine parity: the real paged TINY engine, sized for tier-1 (one
# compile shape, 3 incidents); the composition matrix is slow-marked
# ---------------------------------------------------------------------------


class TestEngineParity:
    def test_byte_identity_k1_vs_k3(self):
        out1 = run_pipelined_sweep(backend="engine", concurrency=1,
                                   n_incidents=3)
        out3 = run_pipelined_sweep(backend="engine", concurrency=3,
                                   n_incidents=3)
        assert report_bytes(out1["report"]) == report_bytes(out3["report"])
        assert out1["report"]["engine_clean"] is True
        assert out3["report"]["engine_clean"] is True
        assert out1["report"]["failed"] == 0
        # exact run-id usage attribution rides the report (satellite 1)
        usage = out1["report"]["incidents"][0]["token_usage"]
        assert usage["total_tokens"] \
            == usage["prompt_tokens"] + usage["completion_tokens"] > 0
        # interleaving shrinks the pump count (the whole point)
        assert out3["stats"]["pumps"] < out1["stats"]["pumps"]


@pytest.mark.slow
class TestEngineCompositionMatrix:
    """Every greedy-exact engine feature must compose with the pipelined
    sweep WITHOUT moving a byte of the report: same baseline, one feature
    flipped per leg, all at K=3 vs the plain K=1 baseline."""

    OVERRIDES = (
        {"prefix_cache": True},
        {"host_overlap": True},
        {"prefill_chunk_budget": 64},   # page-aligned: sweep page_size=64
        {"speculative_k": 3},
    )

    def test_features_keep_byte_identity(self):
        from k8s_llm_rca_tpu.utils.logging import METRICS

        baseline = run_pipelined_sweep(backend="engine", concurrency=1,
                                       n_incidents=3)
        base_bytes = report_bytes(baseline["report"])
        for ov in self.OVERRIDES:
            drafted0 = METRICS.count("engine.spec_drafted")
            out = run_pipelined_sweep(backend="engine", concurrency=3,
                                      n_incidents=3, engine_overrides=ov)
            assert report_bytes(out["report"]) == base_bytes, ov
            assert out["report"]["engine_clean"] is True, ov
            if "speculative_k" in ov:
                # the n-gram drafter actually ran (satellite 2): accepted
                # drafts are what keep the byte-identity non-vacuous
                drafted = METRICS.count("engine.spec_drafted") - drafted0
                accepted = METRICS.count("engine.spec_accepted")
                assert drafted > 0
                assert accepted > 0

    def test_cluster_engine_byte_identity(self):
        out1 = run_pipelined_sweep(backend="cluster", concurrency=1,
                                   n_incidents=2)
        out2 = run_pipelined_sweep(backend="cluster", concurrency=2,
                                   n_incidents=2)
        assert report_bytes(out1["report"]) == report_bytes(out2["report"])
        assert out1["report"]["engine_clean"] is True
        assert out2["report"]["engine_clean"] is True
