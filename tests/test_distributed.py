"""Multi-process distributed init: the DCN path, actually executed.

SURVEY §2.2's collectives row and §5's distributed-backend row call for
``jax.distributed.initialize``-based multi-host init (the reference has no
distributed anything — its whole comm story is HTTPS + two bolt sockets,
reference common/neo4j_query_executor.py:3-8).  Everything else multi-chip
in this suite runs on ONE process with virtual devices; these tests spawn
TWO separate processes that form a real cluster through
``runtime.mesh.initialize_distributed`` (coordinator + worker over a local
TCP port), build a global mesh spanning both processes' devices, and run
one cross-process psum and one sharded train step (tests/_distributed_worker.py).
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "_distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _spawn(pid: int, n_proc: int, port: int) -> subprocess.Popen:
    env = dict(os.environ)
    # pin the platform in the ENVIRONMENT, not just inside the worker: a
    # harness sitecustomize (e.g. an accelerator-tunnel site dir on
    # PYTHONPATH) may pre-import jax and force its platform before the
    # worker's own os.environ writes run (same trap
    # __graft_entry__._respawn_clean documents), and a backend
    # initialized on another platform ignores the distributed init —
    # so replace PYTHONPATH with the repo root and pin cpu
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(_WORKER))
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    return subprocess.Popen(
        [sys.executable, _WORKER, str(pid), str(n_proc), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)


def test_two_process_cluster_psum_train_and_serve():
    """Coordinator (process 0) + worker (process 1) form a cluster via
    initialize_distributed; each asserts the global device view, runs a
    cross-process psum, a DP×TP train step whose gradient reductions
    cross the process boundary, and then SERVES: both engines (contiguous
    + paged) prefill and decode over the process-spanning TP mesh, every
    tick's collectives crossing the process boundary.  Both processes
    must exit 0 with matching losses, matching served tokens, and the
    served tokens must equal a SINGLE-process unsharded engine's greedy
    output (computed here) — the DCN serving claim, executed."""
    port = _free_port()
    procs = [_spawn(i, 2, port) for i in range(2)]
    outs = []
    for i, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
        assert f"WORKER {i} OK" in out, out[-3000:]
    # the jitted train step is one program over one global mesh: both
    # processes must report the IDENTICAL loss
    losses = [line.split("loss=")[1].split()[0]
              for out in outs for line in out.splitlines()
              if "loss=" in line]
    assert len(losses) == 2 and losses[0] == losses[1], losses

    # serving parity: both processes emitted identical tokens per leg
    def serve_lines(out):
        return {line.split("serve[")[1].split("]=")[0]:
                line.split("]=")[1].strip()
                for line in out.splitlines() if "serve[" in line}

    served = [serve_lines(o) for o in outs]
    assert set(served[0]) == {"contig/batch", "contig/single",
                              "paged/batch", "paged/single"}, served[0]
    assert served[0] == served[1], (served[0], served[1])

    # ... and match the single-process unsharded engines exactly — the
    # scenario definition is SHARED with the worker
    # (tests/_distributed_serve_config.py), so both sides serve the same
    # prompts/configs by construction
    from k8s_llm_rca_tpu.engine import make_engine

    import _distributed_serve_config as serve_cfg

    def _make_plain(cfg, params, tok, ecfg, paged):
        kw = dict(use_kernel=False) if paged else {}
        return make_engine(cfg, ecfg, params, tok, **kw)

    want = serve_cfg.serve_all(_make_plain)
    assert served[0] == want, (served[0], want)
