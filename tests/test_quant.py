"""Weight-only int8 quantization (models/quant.py): numerics, pytree
mechanics, and end-to-end engine compatibility."""

import jax
import jax.numpy as jnp
import numpy as np

from k8s_llm_rca_tpu.config import TINY, TINY_MOE, EngineConfig
from k8s_llm_rca_tpu.engine.engine import InferenceEngine
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.models.quant import (
    QuantTensor, dq, gather_rows, quantize, quantize_params,
)
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer


def test_quantize_roundtrip_error_bound():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float32)
    qt = quantize(w, axis=-1, compute_dtype=jnp.float32)
    assert qt.q.dtype == jnp.int8 and qt.scale.shape == (1, 128)
    err = jnp.max(jnp.abs(dq(qt) - w))
    # per-channel symmetric: max error is half a quantization step
    step = jnp.max(jnp.abs(w), axis=0) / 127.0
    assert float(err) <= float(jnp.max(step)) * 0.5 + 1e-6


def test_row_quantized_gather_matches_dense():
    w = jax.random.normal(jax.random.PRNGKey(1), (50, 16), jnp.float32)
    qt = quantize(w, axis=0, compute_dtype=jnp.float32)
    idx = jnp.asarray([[3, 7], [49, 0]])
    np.testing.assert_allclose(np.asarray(gather_rows(qt, idx)),
                               np.asarray(dq(qt)[idx]), rtol=1e-6, atol=1e-6)


def test_dq_passthrough_for_plain_arrays():
    w = jnp.ones((4, 4))
    assert dq(w) is w
    assert gather_rows(w, jnp.asarray([1])).shape == (1, 4)


def test_quantize_params_skips_1d_and_quantizes_weights():
    params = llama.init_params(TINY, jax.random.PRNGKey(0))
    qp = quantize_params(params)
    assert isinstance(qp["layers"][0]["wq"], QuantTensor)
    assert isinstance(qp["embedding"], QuantTensor)
    # per-row scales on the embedding (usable as gather AND lm head)
    assert qp["embedding"].scale.shape == (TINY.vocab_size, 1)
    # norm gains stay full precision
    assert not isinstance(qp["layers"][0]["attn_norm"], QuantTensor)
    assert not isinstance(qp["final_norm"], QuantTensor)


def _top1_agreement(a, b):
    return float(jnp.mean((jnp.argmax(a, -1) == jnp.argmax(b, -1))))


def test_forward_close_to_fp_and_top1_mostly_agrees():
    cfg = TINY
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    qp = quantize_params(params, compute_dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 24), 0,
                                cfg.vocab_size)
    ref = llama.forward(cfg, params, tokens)
    got = llama.forward(cfg, qp, tokens)
    assert np.isfinite(np.asarray(got)).all()
    # int8 noise is real but small; logits correlate and top-1 mostly agrees
    corr = np.corrcoef(np.asarray(ref).ravel(), np.asarray(got).ravel())[0, 1]
    assert corr > 0.99, corr
    assert _top1_agreement(ref, got) > 0.8


def test_moe_forward_quantized_runs():
    cfg = TINY_MOE
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    qp = quantize_params(params, compute_dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 8), 0,
                                cfg.vocab_size)
    out = llama.forward(cfg, qp, tokens)
    assert np.isfinite(np.asarray(out)).all()


def test_engine_runs_with_quantized_params():
    cfg = TINY.replace(max_seq_len=64)
    params = quantize_params(llama.init_params(cfg, jax.random.PRNGKey(0)))
    ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    eng = InferenceEngine(cfg, ecfg, params, tok)
    res = eng.generate([tok.encode("pod oom", add_bos=True)],
                       max_new_tokens=6)
    assert res[0].completion_tokens == 6


def test_quantize_params_idempotent():
    params = llama.init_params(TINY, jax.random.PRNGKey(0))
    qp = quantize_params(params)
    qp2 = quantize_params(qp)
    w = qp2["layers"][0]["wq"]
    assert isinstance(w, QuantTensor) and not isinstance(w.scale, QuantTensor)
    assert dq(w).shape == (TINY.hidden_size, TINY.q_dim)


def test_gather_rows_rejects_column_scales():
    import pytest

    w = jax.random.normal(jax.random.PRNGKey(4), (10, 8))
    qt = quantize(w, axis=-1)                      # per-column: wrong for gather
    with pytest.raises(AssertionError, match="per-row"):
        gather_rows(qt, jnp.asarray([1, 2]))


def test_paged_engine_runs_with_quantized_params():
    from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine

    cfg = TINY.replace(max_seq_len=64)
    params = quantize_params(llama.init_params(cfg, jax.random.PRNGKey(0)))
    ecfg = EngineConfig(max_batch=2, max_seq_len=64, page_size=8,
                        num_pages=32, prefill_buckets=(16, 32, 64),
                        max_new_tokens=6, temperature=0.0)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    eng = PagedInferenceEngine(cfg, ecfg, params, tok, use_kernel=False)
    prompt = tok.encode("kubelet failed to mount volume for pod",
                        add_bos=True)
    r1 = eng.generate([prompt], max_new_tokens=6)[0]
    assert r1.completion_tokens == 6
    # second submit exercises the chunked prefill path with quantized params
    r2 = eng.generate([list(prompt)], max_new_tokens=6)[0]
    assert r2.token_ids == r1.token_ids
    eng.allocator.check()


def test_expert_parallel_moe_quantized(monkeypatch):
    # EP dispatch must accept quantized expert weights (dq at the boundary)
    import os
    if jax.default_backend() != "cpu":
        import pytest
        pytest.skip("mesh test runs on the CPU backend")
    from k8s_llm_rca_tpu.config import MeshConfig
    from k8s_llm_rca_tpu.parallel import expert_parallel_moe
    from k8s_llm_rca_tpu.runtime.mesh import build_mesh

    cfg = TINY_MOE
    mesh = build_mesh(MeshConfig(data=2, expert=4), devices=jax.devices()[:8])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    layer = params["layers"][0]
    qlayer = quantize_params(layer, compute_dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 16, cfg.hidden_size))
    out_q = expert_parallel_moe(x, qlayer, mesh, top_k=cfg.n_experts_per_tok,
                                capacity_factor=8.0)
    ref = expert_parallel_moe(x, layer, mesh, top_k=cfg.n_experts_per_tok,
                              capacity_factor=8.0)
    assert np.isfinite(np.asarray(out_q)).all()
    corr = np.corrcoef(np.asarray(out_q).ravel(), np.asarray(ref).ravel())[0, 1]
    assert corr > 0.99


def test_int4_pack_unpack_roundtrip():
    from k8s_llm_rca_tpu.models.quant import _pack_nibbles, _unpack_nibbles

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.integers(-8, 8, (6, 32)), jnp.int8)
    packed = _pack_nibbles(q)
    assert packed.shape == (6, 16) and packed.dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(_unpack_nibbles(packed)),
                                  np.asarray(q))


def test_int4_quantize_roundtrip_error_bound():
    from k8s_llm_rca_tpu.models.quant import QuantTensor4

    w = jax.random.normal(jax.random.PRNGKey(6), (64, 128), jnp.float32)
    qt = quantize(w, axis=-1, compute_dtype=jnp.float32, bits=4)
    assert isinstance(qt, QuantTensor4)
    assert qt.q.shape == (64, 64) and qt.shape == (64, 128)
    assert qt.scale.shape == (1, 128)
    err = jnp.max(jnp.abs(dq(qt) - w))
    # per-channel symmetric at 4 bits: max error is half a step of amax/7
    step = jnp.max(jnp.abs(w), axis=0) / 7.0
    assert float(err) <= float(jnp.max(step)) * 0.5 + 1e-6


def test_int4_rejects_odd_last_dim():
    import pytest

    with pytest.raises(AssertionError, match="even last dim"):
        quantize(jnp.ones((4, 7)), bits=4)


def test_int4_row_quantized_gather_matches_dense():
    w = jax.random.normal(jax.random.PRNGKey(7), (50, 16), jnp.float32)
    qt = quantize(w, axis=0, compute_dtype=jnp.float32, bits=4)
    idx = jnp.asarray([[3, 7], [49, 0]])
    np.testing.assert_allclose(np.asarray(gather_rows(qt, idx)),
                               np.asarray(dq(qt)[idx]), rtol=1e-6, atol=1e-6)


def test_int4_forward_correlates_with_fp():
    cfg = TINY
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    qp = quantize_params(params, compute_dtype=jnp.float32, bits=4)
    tokens = jax.random.randint(jax.random.PRNGKey(8), (2, 24), 0,
                                cfg.vocab_size)
    ref = llama.forward(cfg, params, tokens)
    got = llama.forward(cfg, qp, tokens)
    assert np.isfinite(np.asarray(got)).all()
    corr = np.corrcoef(np.asarray(ref).ravel(), np.asarray(got).ravel())[0, 1]
    # 4-bit noise is substantially larger than 8-bit but structure must hold
    assert corr > 0.9, corr


def test_int4_quantize_params_idempotent_and_moe_scales():
    from k8s_llm_rca_tpu.models.quant import QuantTensor4

    params = llama.init_params(TINY_MOE, jax.random.PRNGKey(0))
    qp = quantize_params(params, bits=4)
    qp2 = quantize_params(qp, bits=4)
    gate = qp2["layers"][0]["w_gate"]
    assert isinstance(gate, QuantTensor4)
    assert gate.scale.shape[0] == TINY_MOE.n_experts   # per-expert scales
    assert gate.q.shape[-1] == TINY_MOE.intermediate_size // 2
    assert not isinstance(qp2["layers"][0]["attn_norm"], QuantTensor4)


def test_quantize_params_rejects_width_change():
    import pytest

    params = llama.init_params(TINY, jax.random.PRNGKey(0))
    qp8 = quantize_params(params, bits=8)
    with pytest.raises(AssertionError, match="already int8"):
        quantize_params(qp8, bits=4)


def test_int4_engine_generates():
    cfg = TINY.replace(max_seq_len=64)
    params = quantize_params(llama.init_params(cfg, jax.random.PRNGKey(0)),
                             bits=4)
    ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    eng = InferenceEngine(cfg, ecfg, params, tok)
    res = eng.generate([tok.encode("pod oom", add_bos=True)],
                       max_new_tokens=6)
    assert res[0].completion_tokens == 6


def test_int4_quantizing_transform_streaming_init():
    from k8s_llm_rca_tpu.models.quant import QuantTensor4, quantizing_transform

    cfg = TINY
    params = llama.init_params(cfg, jax.random.PRNGKey(0),
                               tensor_transform=quantizing_transform(bits=4))
    assert isinstance(params["layers"][0]["wq"], QuantTensor4)
    assert isinstance(params["embedding"], QuantTensor4)
    tokens = jax.random.randint(jax.random.PRNGKey(9), (1, 8), 0,
                                cfg.vocab_size)
    out = llama.forward(cfg, params, tokens)
    assert np.isfinite(np.asarray(out)).all()


def test_moe_experts_get_per_expert_scales():
    # [E, H, I] expert stacks must not share one scale across experts
    w = jnp.stack([jnp.ones((8, 16)) * 0.01,
                   jnp.ones((8, 16)) * 10.0])      # outlier expert
    qt = quantize(w, axis=(0, -1), compute_dtype=jnp.float32)
    assert qt.scale.shape == (2, 1, 16)
    np.testing.assert_allclose(np.asarray(dq(qt)), np.asarray(w),
                               rtol=1e-2, atol=1e-4)
    # and quantize_params picks that layout for 3-D weights
    params = llama.init_params(TINY_MOE, jax.random.PRNGKey(0))
    qp = quantize_params(params, compute_dtype=jnp.float32)
    gate = qp["layers"][0]["w_gate"]
    assert gate.scale.shape[0] == TINY_MOE.n_experts


def test_repack_nibbles_grouped_shard_local_unpack():
    """The "shard first, pack second" property that makes int4 compose
    with PP×TP: after repacking into G groups, each contiguous 1/G block
    of the PACKED axis is a self-contained split-half buffer whose local
    unpack yields exactly that shard's logical columns (with the
    matching contiguous scale block) — for every group count dividing
    the column pairs."""
    from k8s_llm_rca_tpu.models.quant import (
        _unpack_nibbles, quantize, repack_nibbles_grouped,
    )

    w = jax.random.normal(jax.random.PRNGKey(3), (16, 24), jnp.float32)
    qt = quantize(w, axis=-1, compute_dtype=jnp.float32, bits=4)
    full = np.asarray(dq(qt))                       # global dequant [16, 24]
    for groups in (1, 2, 3, 4, 6):
        rp = repack_nibbles_grouped(qt, groups)
        assert rp.q.shape == qt.q.shape
        packed_w = qt.q.shape[-1] // groups         # packed cols per shard
        logical_w = 24 // groups
        for g in range(groups):
            q_shard = rp.q[:, g * packed_w:(g + 1) * packed_w]
            s_shard = np.asarray(
                qt.scale[:, g * logical_w:(g + 1) * logical_w])
            local = np.asarray(_unpack_nibbles(q_shard)).astype(np.float32)
            np.testing.assert_array_equal(
                local * s_shard,
                full[:, g * logical_w:(g + 1) * logical_w])


def test_repack_nibbles_grouped_rejects_odd_pairs():
    import pytest

    from k8s_llm_rca_tpu.models.quant import quantize, repack_nibbles_grouped

    w = jax.random.normal(jax.random.PRNGKey(4), (4, 10), jnp.float32)
    qt = quantize(w, axis=-1, bits=4)
    with pytest.raises(ValueError, match="divisible"):
        repack_nibbles_grouped(qt, 3)               # 10 % (2*3) != 0
