"""Fault-injection + resilience subsystem (k8s_llm_rca_tpu/faults/).

Everything here is seeded and deterministic: fault schedules are pure
functions of (seed, spec), backoff jitter is seeded, slow/stall time runs
on the virtual clock, and the chaos soak asserts byte-identical reports
across two runs of the same seed.  The soak is sized to stay inside the
tier-1 time budget (``chaos`` marker, registered in pyproject.toml).

Greedy decode ignores the sampling PRNG (temperature 0), so one shared
module engine serves every non-soak test: outputs depend only on weights
and prompts, and each test leaves the engine drained (asserted).
"""

import jax
import jax.numpy as jnp
import pytest

from k8s_llm_rca_tpu.config import TINY, EngineConfig
from k8s_llm_rca_tpu.engine import make_engine
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.faults.plan import Fault, FaultPlan, VirtualClock
from k8s_llm_rca_tpu.faults.policy import (
    CircuitBreaker, CircuitOpen, ResiliencePolicy, ResilientExecutor,
    RetriesExhausted, RetryPolicy,
)
from k8s_llm_rca_tpu.graph import InMemoryGraphExecutor
from k8s_llm_rca_tpu.graph.fixtures import build_stategraph
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.serve.api import AssistantService, RunStatus
from k8s_llm_rca_tpu.serve.backend import BudgetError, EngineBackend, GenOptions
from k8s_llm_rca_tpu.utils.logging import METRICS
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer


@pytest.fixture(autouse=True)
def _disarmed():
    """Never leak an armed plan into other tests."""
    yield
    if inject.active() is not None:
        inject.disarm()


@pytest.fixture(scope="module")
def shared_engine():
    """One TINY paged engine for every non-soak test (see module
    docstring); decode_chunk=1 so tick-indexed fault schedules see one
    poll per decode step."""
    cfg = TINY.replace(max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    eng = make_engine(
        cfg, EngineConfig(max_batch=4, max_seq_len=64, paged=True,
                          page_size=8, num_pages=24,
                          prefill_buckets=(16, 32), max_new_tokens=8,
                          temperature=0.0, decode_chunk=1,
                          prefix_cache=False),
        params, tok, use_kernel=False)
    return eng, tok


# ---------------------------------------------------------------------------
# plan: determinism
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_same_seed_same_schedule(self):
        spec = {"site.a": {"rate": 0.5, "horizon": 40,
                           "kinds": ("error", "timeout")},
                "site.b": {"indices": {3: "empty"}}}
        p1 = FaultPlan.from_spec(7, spec)
        p2 = FaultPlan.from_spec(7, spec)
        assert p1._by_site == p2._by_site
        p3 = FaultPlan.from_spec(8, spec)
        assert p1._by_site != p3._by_site   # overwhelmingly at rate 0.5/40

    def test_poll_fires_at_scheduled_index_only(self):
        plan = FaultPlan([Fault("s", 2, "error")])
        assert plan.poll("s") is None
        assert plan.poll("s") is None
        f = plan.poll("s")
        assert f is not None and f.kind == "error"
        assert plan.poll("s") is None
        snap = plan.snapshot()
        assert snap["polls"] == {"s": 4}
        assert snap["fired"] == [["s", 2, "error"]]

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultPlan([Fault("s", 0, "kaboom")])

    def test_double_arm_rejected(self):
        with inject.armed(FaultPlan()):
            with pytest.raises(RuntimeError, match="already armed"):
                inject.arm(FaultPlan())


# ---------------------------------------------------------------------------
# policy: retry / breaker / resilient executor
# ---------------------------------------------------------------------------


class TestRetryPolicy:
    def test_succeeds_after_transient_failures_on_virtual_clock(self):
        clock = VirtualClock()
        policy = RetryPolicy(max_attempts=3, base_delay_s=0.1,
                             max_delay_s=1.0, jitter=0.5, seed=11,
                             clock=clock)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise inject.InjectedFault("boom")
            return "ok"

        assert policy.call(flaky) == "ok"
        assert len(calls) == 3
        # backoff advanced the VIRTUAL clock by the seeded deterministic sum
        expected = sum(RetryPolicy(max_attempts=3, base_delay_s=0.1,
                                   max_delay_s=1.0, jitter=0.5,
                                   seed=11).delays())
        assert clock.time() == pytest.approx(expected)

    def test_deadline_budget_stops_retries_early(self):
        clock = VirtualClock()
        policy = RetryPolicy(max_attempts=10, base_delay_s=10.0,
                             max_delay_s=10.0, jitter=0.0, deadline_s=5.0,
                             clock=clock)
        calls = []

        def always_fails():
            calls.append(1)
            raise inject.InjectedFault("down")

        with pytest.raises(RetriesExhausted):
            policy.call(always_fails)
        # the first backoff (10s) would blow the 5s budget: exactly one call
        assert len(calls) == 1 and clock.time() == 0.0

    def test_breaker_opens_and_half_opens(self):
        clock = VirtualClock()
        br = CircuitBreaker("dep", failure_threshold=2, reset_timeout_s=1.0,
                            clock=clock)
        assert br.allow()
        br.record_failure()
        br.record_failure()
        assert br.state == "open" and not br.allow()
        clock.sleep(1.5)
        assert br.allow() and br.state == "half_open"
        br.record_failure()                 # probe fails -> re-open
        assert br.state == "open"
        clock.sleep(1.5)
        assert br.allow()
        br.record_success()
        assert br.state == "closed" and br.opens == 2

    def test_open_breaker_short_circuits_retry(self):
        clock = VirtualClock()
        policy = RetryPolicy(max_attempts=3, clock=clock)
        br = CircuitBreaker("dep", failure_threshold=1,
                            reset_timeout_s=100.0, clock=clock)
        br.record_failure()
        with pytest.raises(CircuitOpen):
            policy.call(lambda: "never", breaker=br)

    def test_resilient_executor_degrades_to_empty_rows(self):
        clock = VirtualClock()
        policy = ResiliencePolicy(
            retry=RetryPolicy(max_attempts=2, base_delay_s=0.01,
                              clock=clock))

        class Down:
            def run_query(self, q, p=None):
                raise inject.InjectedFault("neo4j down")

            def close(self):
                pass

        ex = ResilientExecutor(Down(), policy, dep="graph.state")
        assert ex.run_query("MATCH (n) RETURN n") == []
        assert policy.counters["retries"] == 1
        assert policy.counters["degraded_queries:graph.state"] == 1


# ---------------------------------------------------------------------------
# injection sites
# ---------------------------------------------------------------------------


class TestGraphInjection:
    Q = """
        MATCH (n1:Event)-[s1:HasEvent]->(N1:EVENT)
        WHERE N1.message CONTAINS $message
        RETURN n1.kind
        """
    P = {"message": "secret"}

    def _ex(self):
        return InMemoryGraphExecutor(build_stategraph())

    @staticmethod
    def _vals(rows):
        return [r["n1.kind"] for r in rows]

    def test_inert_when_disarmed(self):
        ex = self._ex()
        assert inject.active() is None
        rows = ex.run_query(self.Q, self.P)
        assert rows and self._vals(rows) == self._vals(
            ex.run_query(self.Q, self.P))

    def test_fault_kinds(self):
        ex = self._ex()
        want = self._vals(ex.run_query(self.Q, self.P))
        plan = FaultPlan([Fault(inject.SITE_GRAPH, 0, "error"),
                          Fault(inject.SITE_GRAPH, 1, "timeout"),
                          Fault(inject.SITE_GRAPH, 2, "empty"),
                          Fault(inject.SITE_GRAPH, 3, "slow", delay_s=0.5),
                          Fault(inject.SITE_GRAPH, 4, "poison")])
        with inject.armed(plan):
            with pytest.raises(inject.InjectedFault):
                ex.run_query(self.Q, self.P)
            with pytest.raises(inject.InjectedTimeout):
                ex.run_query(self.Q, self.P)
            assert ex.run_query(self.Q, self.P) == []
            t0 = plan.clock.time()
            # slow but correct
            assert self._vals(ex.run_query(self.Q, self.P)) == want
            assert plan.clock.time() == pytest.approx(t0 + 0.5)
            poisoned = ex.run_query(self.Q, self.P)
            assert len(poisoned) == max(1, len(want))
            with pytest.raises(KeyError, match="poisoned"):
                poisoned[0]["n1.kind"]
            # past the schedule
            assert self._vals(ex.run_query(self.Q, self.P)) == want
        # disarmed again
        assert self._vals(ex.run_query(self.Q, self.P)) == want


class TestEngineInjection:
    def test_tick_faults_preserve_greedy_output(self, shared_engine):
        """oom + preemption-wave + stall tick faults churn the pool but
        must not change greedy output (preemption resumes via re-prefill),
        and the allocator must stay leak-free."""
        eng, tok = shared_engine
        ids = [tok.encode(p, add_bos=True)
               for p in ("pod crashloop kube-system", "node disk pressure")]
        want = eng.generate([list(i) for i in ids], max_new_tokens=8)

        plan = FaultPlan([Fault(inject.SITE_ENGINE_TICK, 1, "oom"),
                          Fault(inject.SITE_ENGINE_TICK, 3, "preempt",
                                wave=2),
                          Fault(inject.SITE_ENGINE_TICK, 5, "stall",
                                delay_s=0.2)])
        pre = METRICS.count("engine.preemptions")
        with inject.armed(plan):
            got = eng.generate([list(i) for i in ids], max_new_tokens=8)
        assert [r.token_ids for r in got] == [r.token_ids for r in want]
        assert len(plan.fired) == 3
        assert METRICS.count("engine.preemptions") > pre
        assert plan.clock.time() >= 0.2              # the stall ran
        eng.allocator.check()
        assert not eng._fault_pages                  # cleanup ran
        assert eng.allocator.n_free == eng.engine_cfg.num_pages - 1

    def test_empty_plan_is_inert_for_greedy_output(self, shared_engine):
        eng, tok = shared_engine
        ids = [tok.encode("pvc not bound storageclass", add_bos=True)]
        want = eng.generate([list(i) for i in ids], max_new_tokens=8)
        with inject.armed(FaultPlan()):              # armed but empty
            got = eng.generate([list(i) for i in ids], max_new_tokens=8)
        assert [r.token_ids for r in got] == [r.token_ids for r in want]


class TestBackendInjection:
    def _service(self, shared_engine, clock=None, run_timeout_s=600.0):
        eng, _ = shared_engine
        return AssistantService(EngineBackend(eng),
                                run_timeout_s=run_timeout_s,
                                clock=clock), eng

    def _run(self, service, text="q", max_new=8):
        a = service.create_assistant("test", "t")
        th = service.create_thread()
        service.add_message(th.id, text)
        return service.create_run(th.id, a.id,
                                  gen=GenOptions(max_new_tokens=max_new))

    def test_error_fault_fails_run(self, shared_engine):
        service, _ = self._service(shared_engine)
        with inject.armed(FaultPlan([Fault(inject.SITE_BACKEND, 0,
                                           "error")])):
            run = self._run(service)
            run = service.wait_run(run.id)
        assert run.status == RunStatus.FAILED
        assert "injected" in run.error

    def test_budget_fault_raises_budget_error(self, shared_engine):
        service, _ = self._service(shared_engine)
        with inject.armed(FaultPlan([Fault(inject.SITE_BACKEND, 0,
                                           "budget")])):
            with pytest.raises(BudgetError, match="injected"):
                self._run(service)

    def test_stalled_run_expires_on_virtual_deadline(self, shared_engine):
        clock = VirtualClock()
        plan = FaultPlan([Fault(inject.SITE_BACKEND, 0, "stall")],
                         clock=clock)
        service, eng = self._service(shared_engine, clock=clock,
                                     run_timeout_s=0.5)
        with inject.armed(plan):
            run = self._run(service)
            run = service.wait_run(run.id)        # no wall-clock timeout
        assert run.status == RunStatus.EXPIRED
        assert not eng.has_work                   # nothing leaked in-engine

    def test_expired_run_frees_engine_pages(self, shared_engine):
        """Satellite: a run reaped by the serve deadline/cancel paths must
        free its engine pages — no leaked allocator blocks — and wait_run
        must surface the expired status."""
        service, eng = self._service(shared_engine)
        run = self._run(service, text="pod oom " * 8, max_new=40)
        got = service.wait_run(run.id, timeout_s=0.0)   # expire mid-decode
        assert got.status == RunStatus.EXPIRED
        assert run.backend_handle not in service._inflight
        eng.allocator.check()
        assert not eng.has_work
        assert eng.allocator.n_free == eng.engine_cfg.num_pages - 1

    def test_cancelled_run_frees_engine_pages(self, shared_engine):
        service, eng = self._service(shared_engine)
        run = self._run(service, text="node disk pressure", max_new=40)
        got = service.cancel_run(run.id)
        assert got.status == RunStatus.CANCELLED
        eng.allocator.check()
        assert not eng.has_work
        assert eng.allocator.n_free == eng.engine_cfg.num_pages - 1
        # the state machine stays terminal through later pumps
        service._pump()
        assert service.runs[run.id].status == RunStatus.CANCELLED


class TestMeshAddressability:
    def test_backend_rejects_non_addressable_engine(self):
        """Satellite (ADVICE low #1): EngineBackend must refuse an engine
        whose arrays span non-addressable devices — its threaded drivers
        would misalign host_np's process_allgather."""

        class FakeLeaf:
            is_fully_addressable = False

        class FakeEngine:
            params = {"w": FakeLeaf()}
            cache = None
            tokenizer = None

        with pytest.raises(ValueError, match="fully-addressable"):
            EngineBackend(FakeEngine())


class TestChunkAttentionGQAAssert:
    def test_mismatched_head_sharding_fails_loudly(self):
        """Satellite (ADVICE low #3): q-heads sharded without kv-heads
        must trip the repeat-factor assertion inside _chunk_attention."""
        from k8s_llm_rca_tpu.engine.paged import _chunk_attention

        cfg = TINY                      # n_heads=4, n_kv_heads=2 -> n_rep=2
        d = cfg.head_dim
        q = jnp.zeros((1, 4, 2, d))     # 2 local q heads (sharded)
        k = jnp.zeros((1, 8, 2, d))     # 2 kv heads (unsharded)
        mask = jnp.ones((4, 8), bool)
        with pytest.raises(AssertionError, match="GQA repeat mismatch"):
            _chunk_attention(cfg, q, k, k, mask)
        # the consistent shapes still pass
        out = _chunk_attention(cfg, jnp.zeros((1, 4, 4, d)), k, k, mask)
        assert out.shape == (1, 4, 4, d)


# ---------------------------------------------------------------------------
# chaos soak
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestChaosSoak:
    def test_engine_soak_completes_and_is_byte_identical(self):
        """The acceptance bar: the multi-incident RCA sweep under a seeded
        FaultPlan (graph faults + engine tick faults + backend stalls)
        completes with every incident either fully resolved or explicitly
        degraded-and-annotated — no hangs, no unhandled exceptions — and
        two runs with the same seed produce byte-identical reports."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak

        r1 = run_chaos_soak(seed=0, n_incidents=2, backend="engine")
        r2 = run_chaos_soak(seed=0, n_incidents=2, backend="engine")
        assert report_bytes(r1) == report_bytes(r2)
        assert r1["failed"] == 0
        assert r1["completed"] == 2
        assert r1["engine_clean"]
        for row in r1["incidents"]:
            assert row["status"] in ("resolved", "degraded")
            if row["status"] == "degraded":
                assert row["degraded"], "degraded incident lacks annotations"

    def test_backend_down_soak_degrades_with_annotations(self):
        """Every backend run faulted: incidents must still complete via
        the scripted-oracle/skip rungs, each annotated as degraded."""
        from k8s_llm_rca_tpu.faults.soak import run_chaos_soak

        spec = {inject.SITE_BACKEND:
                {"indices": {i: "error" for i in range(64)}}}
        r = run_chaos_soak(seed=1, n_incidents=2, backend="engine",
                           plan_spec=spec)
        assert r["failed"] == 0 and r["completed"] == 2
        assert r["degraded"] == 2
        for row in r["incidents"]:
            assert row["status"] == "degraded"
            stages = {d["stage"] for d in row["degraded"]}
            assert "locate.plan" in stages
        assert r["engine_clean"]

    def test_oracle_soak_byte_identical(self):
        """The cheap soak mode (scripted backend, graph faults only) —
        what bench.py's chaos leg publishes."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak

        r1 = run_chaos_soak(seed=3, n_incidents=4, backend="oracle")
        r2 = run_chaos_soak(seed=3, n_incidents=4, backend="oracle")
        assert report_bytes(r1) == report_bytes(r2)
        assert r1["failed"] == 0 and r1["completed"] == 4
