"""Parallelism-module tests on the virtual 8-device CPU mesh: every sharded
path must match its single-device reference implementation exactly
(tolerance = fp32 accumulation noise)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_llm_rca_tpu.config import TINY, TINY_MOE, MeshConfig
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.ops.attention import causal_attention
from k8s_llm_rca_tpu.parallel import (
    expert_parallel_moe, pipeline_apply, ring_attention, ulysses_attention,
)
from k8s_llm_rca_tpu.runtime.mesh import build_mesh


@pytest.fixture(scope="module")
def seq_mesh(cpu_devices):
    return build_mesh(MeshConfig(seq=4), devices=cpu_devices[:4])


def _qkv(key, b=2, s=32, n_heads=4, n_kv=2, d=16):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, n_heads, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, n_kv, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, n_kv, d), jnp.float32)
    return q, k, v


def test_ring_attention_matches_reference(seq_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    ref = causal_attention(q, k, v, jnp.full((2,), 32, jnp.int32))
    out = ring_attention(q, k, v, seq_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_jit(seq_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(1))
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, seq_mesh))(q, k, v)
    ref = causal_attention(q, k, v, jnp.full((2,), 32, jnp.int32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_matches_reference(seq_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(2))
    ref = causal_attention(q, k, v, jnp.full((2,), 32, jnp.int32))
    out = ulysses_attention(q, k, v, seq_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_rejects_indivisible_heads(seq_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(3), n_heads=6, n_kv=6)
    with pytest.raises(ValueError):
        ulysses_attention(q, k, v, seq_mesh)


def test_pipeline_matches_sequential(cpu_devices):
    mesh = build_mesh(MeshConfig(stage=4), devices=cpu_devices[:4])
    n_stages, m, b, h = 4, 6, 2, 8
    keys = jax.random.split(jax.random.PRNGKey(4), n_stages)
    stacked = {
        "w": jnp.stack([jax.random.normal(k, (h, h)) * 0.3 for k in keys]),
        "b": jnp.stack([jax.random.normal(k, (h,)) * 0.1 for k in keys]),
    }
    x_mb = jax.random.normal(jax.random.PRNGKey(5), (m, b, h))

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    out = pipeline_apply(stage_fn, stacked, x_mb, mesh)

    ref = x_mb
    for i in range(n_stages):
        ref = stage_fn(jax.tree.map(lambda a, i=i: a[i], stacked), ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_llama_pipeline_forward_matches_sequential(cpu_devices):
    from k8s_llm_rca_tpu.models import llama
    from k8s_llm_rca_tpu.parallel import llama_pipeline_forward

    cfg = TINY.replace(n_layers=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(6))
    tokens = jax.random.randint(jax.random.PRNGKey(7), (8, 16), 0,
                                cfg.vocab_size)
    ref = llama.forward(cfg, params, tokens)
    for n_stages, microbatches in ((4, 4), (2, 2)):
        mesh = build_mesh(MeshConfig(stage=n_stages),
                          devices=cpu_devices[:n_stages])
        out = llama_pipeline_forward(cfg, params, tokens, mesh,
                                     microbatches=microbatches)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


def test_llama_pipeline_prestacked_layers_match(cpu_devices):
    from k8s_llm_rca_tpu.models import llama
    from k8s_llm_rca_tpu.parallel import (
        llama_pipeline_forward, stack_llama_stages,
    )

    cfg = TINY.replace(n_layers=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(11))
    tokens = jax.random.randint(jax.random.PRNGKey(12), (4, 8), 0,
                                cfg.vocab_size)
    mesh = build_mesh(MeshConfig(stage=2), devices=cpu_devices[:2])
    stacked = stack_llama_stages(params, 2)    # hoisted once by the caller
    out = llama_pipeline_forward(cfg, params, tokens, mesh, microbatches=2,
                                 stacked_layers=stacked)
    ref = llama_pipeline_forward(cfg, params, tokens, mesh, microbatches=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_llama_pipeline_forward_quantized(cpu_devices):
    from k8s_llm_rca_tpu.models import llama
    from k8s_llm_rca_tpu.models.quant import quantize_params
    from k8s_llm_rca_tpu.parallel import llama_pipeline_forward

    cfg = TINY.replace(n_layers=4)
    params = quantize_params(llama.init_params(cfg, jax.random.PRNGKey(8)),
                             compute_dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(9), (4, 12), 0,
                                cfg.vocab_size)
    mesh = build_mesh(MeshConfig(stage=2), devices=cpu_devices[:2])
    out = llama_pipeline_forward(cfg, params, tokens, mesh, microbatches=2)
    ref = llama.forward(cfg, params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_llama_pipeline_rejects_indivisible_layers(cpu_devices):
    from k8s_llm_rca_tpu.models import llama
    from k8s_llm_rca_tpu.parallel import llama_pipeline_forward

    cfg = TINY.replace(n_layers=3)
    params = llama.init_params(cfg, jax.random.PRNGKey(10))
    tokens = jnp.zeros((2, 8), jnp.int32)
    mesh = build_mesh(MeshConfig(stage=2), devices=cpu_devices[:2])
    with pytest.raises(AssertionError, match="stages"):
        llama_pipeline_forward(cfg, params, tokens, mesh, microbatches=2)


def test_expert_parallel_moe_matches_dense(cpu_devices):
    """Hard EP dispatch == dense soft-dispatch when capacity is ample."""
    mesh = build_mesh(MeshConfig(data=2, expert=4),
                      devices=cpu_devices[:8])
    cfg = TINY_MOE.replace(n_experts=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(6))
    layer = params["layers"][0]
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 16, cfg.hidden_size),
                          jnp.float32)

    dense = llama._moe_mlp(cfg, layer, x)
    ep = expert_parallel_moe(x, layer, mesh, top_k=cfg.n_experts_per_tok,
                             capacity_factor=8.0)
    np.testing.assert_allclose(np.asarray(ep), np.asarray(dense),
                               rtol=2e-4, atol=2e-4)


def test_expert_parallel_moe_drops_under_pressure(cpu_devices):
    """With capacity ~0 the output collapses toward zero (tokens dropped),
    proving the capacity accounting actually binds."""
    mesh = build_mesh(MeshConfig(data=2, expert=4), devices=cpu_devices[:8])
    cfg = TINY_MOE.replace(n_experts=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(8))
    layer = params["layers"][0]
    x = jax.random.normal(jax.random.PRNGKey(9), (2, 16, cfg.hidden_size),
                          jnp.float32)
    tight = expert_parallel_moe(x, layer, mesh, top_k=2,
                                capacity_factor=0.01)
    dense = llama._moe_mlp(cfg, layer, x)
    assert float(jnp.abs(tight).sum()) < float(jnp.abs(dense).sum())


def test_kv_cache_spec_sharded_decode_matches_unsharded(cpu_devices):
    """kv_cache_specs must match the merged cache rank ([L, B, S, n_kv*d])
    and a decode step over the sharded cache must equal the unsharded one."""
    from k8s_llm_rca_tpu.config import TINY
    from k8s_llm_rca_tpu.runtime.sharding import (
        kv_cache_specs, llama_param_specs, shard_pytree,
    )

    cfg = TINY
    mesh = build_mesh(MeshConfig(data=2, model=2), devices=cpu_devices[:4])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    cache = llama.init_cache(cfg, n_slots=4, max_seq_len=32)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0,
                                cfg.vocab_size)
    cache, _ = jax.jit(llama.prefill, static_argnums=0)(
        cfg, params, cache, prompt, jnp.int32(16), jnp.int32(0))
    cur = jnp.full((4,), 5, jnp.int32)
    lengths = jnp.asarray([16, 0, 0, 0], jnp.int32)
    ref_cache, ref_logits = jax.jit(llama.decode_step, static_argnums=0)(
        cfg, params, cache, cur, lengths)

    sharded_params = shard_pytree(params, llama_param_specs(cfg), mesh)
    spec = kv_cache_specs()
    sharded_cache = shard_pytree(cache, llama.KVCache(spec, spec), mesh)
    out_cache, logits = jax.jit(llama.decode_step, static_argnums=0)(
        cfg, sharded_params, sharded_cache, cur, lengths)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(out_cache.k),
                               np.asarray(ref_cache.k), rtol=1e-5, atol=1e-5)


def test_tp_sharded_engine_matches_unsharded(cpu_devices):
    """Serving TP: the continuous-batching engine fed TP-sharded params
    must emit the same greedy tokens as the unsharded engine."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.runtime.sharding import (
        llama_param_specs, shard_pytree,
    )
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    mesh = build_mesh(MeshConfig(data=2, model=2), devices=cpu_devices[:4])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("pod pending unschedulable", add_bos=True),
               tok.encode("pvc not bound", add_bos=True)]

    ref = make_engine(cfg, ecfg, params, tok).generate(
        prompts, max_new_tokens=6)
    sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
    got = make_engine(cfg, ecfg, sharded, tok).generate(
        prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids


def test_tp_sharded_engine_quantized_params(cpu_devices):
    """TP x quantization: sharding int8/int4 params must work (the int
    payload takes the weight spec, per-channel scales replicate their
    reduced dims) and the sharded engine must emit the unsharded engine's
    greedy tokens."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.models.quant import quantize_params
    from k8s_llm_rca_tpu.runtime.sharding import (
        llama_param_specs, shard_pytree,
    )
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    mesh = build_mesh(MeshConfig(data=2, model=2), devices=cpu_devices[:4])
    ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("pod pending unschedulable", add_bos=True)]

    for bits in (8, 4):
        qp = quantize_params(llama.init_params(cfg, jax.random.PRNGKey(0)),
                             compute_dtype=jnp.float32, bits=bits)
        ref = make_engine(cfg, ecfg, qp, tok).generate(
            prompts, max_new_tokens=6)
        sharded = shard_pytree(qp, llama_param_specs(cfg), mesh)
        got = make_engine(cfg, ecfg, sharded, tok).generate(
            prompts, max_new_tokens=6)
        assert ref[0].token_ids == got[0].token_ids, bits


def test_cp_prefill_matches_single_device(seq_mesh):
    """Ring-attention (context-parallel) prefill must produce the same KV
    and last-token logits as the plain single-device prefill."""
    from k8s_llm_rca_tpu.config import TINY

    cfg = TINY
    mesh = seq_mesh
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.zeros((1, 32), jnp.int32).at[0, :27].set(
        jax.random.randint(jax.random.PRNGKey(1), (27,), 0, cfg.vocab_size))
    length = jnp.int32(27)

    ref_k, ref_v, ref_logits = llama.prefill_kv(cfg, params, tokens, length)
    cp_k, cp_v, cp_logits = llama.prefill_kv_cp(cfg, params, tokens, length,
                                                mesh)
    np.testing.assert_allclose(np.asarray(cp_logits), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-4)
    # only positions < length matter (padded KV is never attended to)
    np.testing.assert_allclose(np.asarray(cp_k[:, :27]),
                               np.asarray(ref_k[:, :27]),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(cp_v[:, :27]),
                               np.asarray(ref_v[:, :27]),
                               rtol=2e-4, atol=2e-4)


def test_engine_cp_prefill_matches_plain_engine(seq_mesh):
    """InferenceEngine in context-parallel prefill mode emits the same
    greedy tokens as the plain engine."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.engine import InferenceEngine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    mesh = seq_mesh
    ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("pod sandbox changed restarting", add_bos=True),
               tok.encode("oom killed container", add_bos=True)]

    ref = InferenceEngine(cfg, ecfg, params, tok).generate(
        prompts, max_new_tokens=6)
    got = InferenceEngine(cfg, ecfg, params, tok, cp_mesh=mesh).generate(
        prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids


def test_engine_cp_rejects_indivisible_buckets(seq_mesh):
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.engine import InferenceEngine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    ecfg = EngineConfig(max_batch=1, max_seq_len=64, prefill_buckets=(18,))
    with pytest.raises(ValueError, match="must divide"):
        InferenceEngine(cfg, ecfg, llama.init_params(cfg, jax.random.PRNGKey(0)),
                        get_tokenizer(vocab_size=cfg.vocab_size),
                        cp_mesh=seq_mesh)


def test_engine_ulysses_prefill_matches_plain_engine(seq_mesh):
    """Ulysses is the second engine CP mode: identical greedy output."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.engine import InferenceEngine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    ecfg = EngineConfig(max_batch=1, max_seq_len=64,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompt = tok.encode("image pull backoff registry timeout", add_bos=True)

    ref = InferenceEngine(cfg, ecfg, params, tok).generate(
        [prompt], max_new_tokens=6)
    got = InferenceEngine(cfg, ecfg, params, tok, cp_mesh=seq_mesh,
                          cp_mode="ulysses").generate(
        [list(prompt)], max_new_tokens=6)
    assert ref[0].token_ids == got[0].token_ids


def test_paged_engine_cp_prefill_matches_plain_engine(seq_mesh):
    """PagedInferenceEngine in context-parallel prefill mode emits the
    same greedy tokens as the plain paged engine (ring and ulysses)."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    ecfg = EngineConfig(max_batch=2, max_seq_len=64, page_size=8,
                        num_pages=32, prefill_buckets=(16, 32, 64),
                        max_new_tokens=6, temperature=0.0,
                        prefix_cache=False)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("pod sandbox changed restarting", add_bos=True),
               tok.encode("oom killed container", add_bos=True)]

    ref = PagedInferenceEngine(cfg, ecfg, params, tok,
                               use_kernel=False).generate(
        prompts, max_new_tokens=6)
    for mode in ("ring", "ulysses"):
        eng = PagedInferenceEngine(cfg, ecfg, params, tok, use_kernel=False,
                                   cp_mesh=seq_mesh, cp_mode=mode)
        got = eng.generate([list(p) for p in prompts], max_new_tokens=6)
        for r, g in zip(ref, got):
            assert r.token_ids == g.token_ids, mode
        eng.allocator.check()


def test_paged_engine_cp_rejects_bad_configs(seq_mesh):
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    with pytest.raises(ValueError, match="prefix_cache"):
        PagedInferenceEngine(
            cfg, EngineConfig(max_batch=1, max_seq_len=64, page_size=8,
                              num_pages=32, prefix_cache=True),
            params, tok, cp_mesh=seq_mesh)
    with pytest.raises(ValueError, match="must divide"):
        PagedInferenceEngine(
            cfg, EngineConfig(max_batch=1, max_seq_len=64, page_size=6,
                              num_pages=32, prefill_buckets=(18,),
                              prefix_cache=False),
            params, tok, cp_mesh=seq_mesh)


def test_ep_sharded_engine_matches_unsharded(cpu_devices):
    """EP serving: MoE engine fed expert-sharded params must emit the same
    greedy tokens as the unsharded engine (GSPMD partitions the dense
    soft-dispatch einsums over the expert axis)."""
    from k8s_llm_rca_tpu.config import EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.runtime.sharding import (
        llama_param_specs, shard_pytree,
    )
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY_MOE.replace(n_experts=4, max_seq_len=64)
    mesh = build_mesh(MeshConfig(data=2, expert=4), devices=cpu_devices[:8])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("node notready kubelet down", add_bos=True)]

    ref = make_engine(cfg, ecfg, params, tok).generate(
        prompts, max_new_tokens=6)
    sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
    got = make_engine(cfg, ecfg, sharded, tok).generate(
        [list(prompts[0])], max_new_tokens=6)
    assert ref[0].token_ids == got[0].token_ids


def test_ep_engine_matches_dense(cpu_devices):
    """Serving EP (VERDICT r1 item 4): an engine built with an expert-axis
    mesh — every MoE MLP dispatching through the all-to-all path, prefill
    AND decode — must emit the same greedy tokens as the dense
    soft-dispatch engine (lossless capacity)."""
    from k8s_llm_rca_tpu.config import TINY_MOE, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.models import mixtral
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY_MOE.replace(max_seq_len=64, n_experts=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(max_batch=4, max_seq_len=64,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("pod pending unschedulable", add_bos=True),
               tok.encode("pvc not bound", add_bos=True),
               tok.encode("secret missing for mount", add_bos=True)]

    ref = make_engine(cfg, ecfg, params, tok).generate(
        prompts, max_new_tokens=6)
    ep_engine = mixtral.make_ep_engine(
        cfg, ecfg, params, tok, n_expert_shards=4, n_data=1,
        devices=cpu_devices[:4])
    got = ep_engine.generate(prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids
        assert r.finish_reason == g.finish_reason


def test_ep_paged_engine_matches_dense(cpu_devices):
    """EP x paged: the paged engine under an expert mesh (page-scatter
    writes + all-to-all MoE) matches the dense paged engine."""
    from k8s_llm_rca_tpu.config import TINY_MOE, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.models import mixtral
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY_MOE.replace(max_seq_len=64, n_experts=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(max_batch=4, max_seq_len=64, paged=True,
                        page_size=8, num_pages=48,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("node notready kubelet stopped", add_bos=True),
               tok.encode("image pull backoff", add_bos=True)]

    ref = make_engine(cfg, ecfg, params, tok, use_kernel=False).generate(
        prompts, max_new_tokens=6)
    ep_engine = mixtral.make_ep_engine(
        cfg, ecfg, params, tok, n_expert_shards=4, n_data=1,
        devices=cpu_devices[:4], use_kernel=False)
    got = ep_engine.generate(prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids
    ep_engine.allocator.check()


def test_ep_mesh_validation():
    """Misconfigured EP serving fails loudly at construction."""
    from k8s_llm_rca_tpu.config import TINY, TINY_MOE, EngineConfig
    from k8s_llm_rca_tpu.engine.engine import validate_ep_mesh
    from k8s_llm_rca_tpu.models import mixtral

    mesh = build_mesh(MeshConfig(data=1, expert=4),
                      devices=jax.devices("cpu")[:4])
    ecfg = EngineConfig(max_batch=4, max_seq_len=64, prefill_buckets=(16,))
    with pytest.raises(ValueError, match="MoE model"):
        validate_ep_mesh(mesh, TINY, ecfg, None)
    with pytest.raises(ValueError, match="not divisible"):
        validate_ep_mesh(mesh, TINY_MOE.replace(n_experts=4),
                         EngineConfig(max_batch=3, max_seq_len=64,
                                      prefill_buckets=(16,)), None)
    with pytest.raises(ValueError, match="n_experts"):
        validate_ep_mesh(mesh, TINY_MOE.replace(n_experts=3), ecfg, None)
    with pytest.raises(ValueError, match="not an MoE"):
        mixtral.make_ep_engine(TINY, ecfg, {}, None, n_expert_shards=4)


def test_paged_tp_engine_matches_unsharded(cpu_devices):
    """Paged serving TP (VERDICT r1 item 5): the paged engine with
    TP-sharded params AND the page pool sharded on the merged kv axis must
    emit the unsharded paged engine's greedy tokens."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.runtime.sharding import (
        llama_param_specs, shard_pytree,
    )
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    mesh = build_mesh(MeshConfig(data=2, model=2), devices=cpu_devices[:4])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(max_batch=2, max_seq_len=64, paged=True,
                        page_size=8, num_pages=32,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("pod pending unschedulable", add_bos=True),
               tok.encode("pvc not bound", add_bos=True)]

    ref = make_engine(cfg, ecfg, params, tok, use_kernel=False).generate(
        prompts, max_new_tokens=6)
    sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
    eng = make_engine(cfg, ecfg, sharded, tok, tp_mesh=mesh)
    got = eng.generate(prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids
        assert r.finish_reason == g.finish_reason
    eng.allocator.check()
    # the pool really is distributed: each device holds 1/model of kv bytes
    shard_shape = eng.pool.k.sharding.shard_shape(eng.pool.k.shape)
    assert shard_shape[-1] == cfg.kv_dim // 2


@pytest.mark.parametrize("kv_dtype", ["int8", "int4"])
def test_paged_tp_engine_quantized_pool(cpu_devices, kv_dtype):
    """Paged TP x quantized pool: int8/int4 pages shard on the merged kv
    axis (int4's nibble-packed halved axis included), per-token scale
    pools replicate, greedy tokens match the unsharded quantized engine."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.runtime.sharding import (
        llama_param_specs, shard_pytree,
    )
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    mesh = build_mesh(MeshConfig(data=2, model=2), devices=cpu_devices[:4])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(max_batch=2, max_seq_len=64, paged=True,
                        page_size=8, num_pages=32,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0, kv_cache_dtype=kv_dtype)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("node notready kubelet stopped", add_bos=True),
               tok.encode("image pull backoff", add_bos=True)]

    ref = make_engine(cfg, ecfg, params, tok, use_kernel=False).generate(
        prompts, max_new_tokens=6)
    sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
    eng = make_engine(cfg, ecfg, sharded, tok, tp_mesh=mesh)
    got = eng.generate(prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids
    eng.allocator.check()


@pytest.mark.parametrize("use_kernel", [True, False])
def test_paged_tp_kernel_matches_unsharded(cpu_devices, use_kernel):
    """The paged-attention KERNEL under TP (VERDICT r4 item 3): decode
    runs ops.paged_attention_sharded — the Pallas kernel per head shard
    inside shard_map — and emits exactly the plain paged engine's greedy
    tokens.  Parametrized against the XLA path so a silent fallback
    cannot fake the parity."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.runtime.sharding import (
        llama_param_specs, shard_pytree,
    )
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    mesh = build_mesh(MeshConfig(data=2, model=2), devices=cpu_devices[:4])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(max_batch=2, max_seq_len=64, paged=True,
                        page_size=8, num_pages=32,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0, decode_chunk=4)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("pod pending unschedulable", add_bos=True),
               tok.encode("pvc not bound", add_bos=True)]

    ref = make_engine(cfg, ecfg, params, tok, use_kernel=False).generate(
        prompts, max_new_tokens=6)
    sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
    eng = make_engine(cfg, ecfg, sharded, tok, tp_mesh=mesh,
                      use_kernel=use_kernel)
    assert (eng._kernel_mesh is mesh) == use_kernel
    got = eng.generate(prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids
        assert r.finish_reason == g.finish_reason
    eng.allocator.check()


def test_paged_tp_kernel_int8_pool_matches_unsharded(cpu_devices):
    """TP x int8 pool x kernel: paged_attention_quant_sharded (per-shard
    quantized kernel, replicated full-row scales) matches the unsharded
    quantized engine's greedy tokens."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.runtime.sharding import (
        llama_param_specs, shard_pytree,
    )
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    mesh = build_mesh(MeshConfig(data=2, model=2), devices=cpu_devices[:4])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(max_batch=2, max_seq_len=64, paged=True,
                        page_size=8, num_pages=32,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0, kv_cache_dtype="int8",
                        decode_chunk=4)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("node notready kubelet stopped", add_bos=True),
               tok.encode("image pull backoff", add_bos=True)]

    ref = make_engine(cfg, ecfg, params, tok, use_kernel=False).generate(
        prompts, max_new_tokens=6)
    sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
    eng = make_engine(cfg, ecfg, sharded, tok, tp_mesh=mesh,
                      use_kernel=True)
    assert eng._kernel_mesh is mesh
    got = eng.generate(prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids
    eng.allocator.check()


def test_paged_tp_rejects_kernel_unsupported_configs(cpu_devices):
    """The sharded kernel's remaining exclusions stay loud: packed-int4
    pools (split-half packing vs head shard), indivisible kv heads, and
    CP seq-sharded pools all reject use_kernel=True."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    mesh = build_mesh(MeshConfig(data=2, model=2), devices=cpu_devices[:4])
    ecfg = EngineConfig(max_batch=2, max_seq_len=64, paged=True,
                        page_size=8, num_pages=32, prefill_buckets=(16,),
                        kv_cache_dtype="int4")
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="int4"):
        PagedInferenceEngine(cfg, ecfg, params, get_tokenizer(),
                             use_kernel=True, tp_mesh=mesh)
    # indivisible kv heads: 2 kv heads cannot split over model=4
    mesh4 = build_mesh(MeshConfig(data=2, model=4),
                       devices=cpu_devices[:8])
    ecfg8 = EngineConfig(max_batch=2, max_seq_len=64, paged=True,
                         page_size=8, num_pages=32, prefill_buckets=(16,))
    with pytest.raises(ValueError, match="divisible"):
        PagedInferenceEngine(cfg, ecfg8, params, get_tokenizer(),
                             use_kernel=True, tp_mesh=mesh4)
    # CP seq-sharded pool: pages are distributed across the seq axis,
    # which the per-head-shard kernel cannot express — even with
    # unsharded (host) params the mesh alone must reject the kernel
    seq_mesh = build_mesh(MeshConfig(seq=2), devices=cpu_devices[:2])
    ecfg_cp = EngineConfig(max_batch=2, max_seq_len=64, paged=True,
                           page_size=8, num_pages=32,
                           prefill_buckets=(16,), prefix_cache=False)
    with pytest.raises(ValueError, match="cp_mesh"):
        PagedInferenceEngine(cfg, ecfg_cp, params, get_tokenizer(),
                             use_kernel=True, cp_mesh=seq_mesh)


def test_contiguous_tp_engine_cache_sharded(cpu_devices):
    """tp_mesh on the CONTIGUOUS engine: the KV cache is placed sharded
    (slots over data, merged kv axis over model) and greedy output still
    matches the unsharded engine — including a quantized cache whose
    per-token scale arrays shard on data only."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.runtime.sharding import (
        llama_param_specs, shard_pytree,
    )
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    mesh = build_mesh(MeshConfig(data=2, model=2), devices=cpu_devices[:4])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("pod pending unschedulable", add_bos=True),
               tok.encode("pvc not bound", add_bos=True)]
    for kv_dtype in (None, "int8"):
        ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                            prefill_buckets=(16, 32, 64), max_new_tokens=6,
                            temperature=0.0, kv_cache_dtype=kv_dtype)
        ref = make_engine(cfg, ecfg, params, tok).generate(
            prompts, max_new_tokens=6)
        sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
        eng = make_engine(cfg, ecfg, sharded, tok, tp_mesh=mesh)
        shard_shape = eng.cache.k.sharding.shard_shape(eng.cache.k.shape)
        assert shard_shape[1] == 1                  # slots over data
        assert shard_shape[-1] == eng.cache.k.shape[-1] // 2   # kv over model
        got = eng.generate(prompts, max_new_tokens=6)
        for r, g in zip(ref, got):
            assert r.token_ids == g.token_ids, kv_dtype


def test_pp_prefill_decode_matches_plain(cpu_devices):
    """PP SERVING (VERDICT r1 item 9): pipelined prefill writes per-stage
    KV (cache layer axis sharded over "stage") and the pipelined decode
    step — slot-group microbatches flowing GPipe-style — produces the
    plain path's exact greedy tokens over multiple steps."""
    from k8s_llm_rca_tpu.parallel import (
        llama_pp_decode_step, llama_pp_prefill, stack_llama_stages,
    )

    cfg = TINY.replace(max_seq_len=64, n_layers=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    n_stages, m, b, s_pad, steps = 2, 2, 4, 16, 5
    mesh = build_mesh(MeshConfig(stage=n_stages),
                      devices=cpu_devices[:n_stages])
    tokens = jax.random.randint(jax.random.PRNGKey(1), (b, s_pad), 0,
                                cfg.vocab_size)
    lengths = jnp.asarray([16, 13, 9, 16], jnp.int32)

    # plain reference: batched prefill + stepwise greedy decode
    ref_cache = llama.init_cache(cfg, b, cfg.max_seq_len)
    ref_cache, ref_logits = llama.prefill_batch(
        cfg, params, ref_cache, tokens, lengths, jnp.arange(b))
    ref_toks = [jnp.argmax(ref_logits, -1)]
    ref_lens = lengths
    for _ in range(steps - 1):
        ref_cache, lg = llama.decode_step(cfg, params, ref_cache,
                                          ref_toks[-1], ref_lens)
        ref_lens = ref_lens + 1
        ref_toks.append(jnp.argmax(lg, -1))

    # PP: same schedule through the stage pipeline
    stacked = stack_llama_stages(params, n_stages)
    pp_cache = llama.init_cache(cfg, b, cfg.max_seq_len)
    pp_cache, pp_logits = llama_pp_prefill(
        cfg, params, pp_cache, tokens, lengths, mesh, microbatches=m,
        stacked_layers=stacked)
    np.testing.assert_allclose(np.asarray(pp_logits), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-4)
    pp_toks = [jnp.argmax(pp_logits, -1)]
    pp_lens = lengths
    for _ in range(steps - 1):
        pp_cache, lg = llama_pp_decode_step(
            cfg, params, pp_cache, pp_toks[-1], pp_lens, mesh,
            microbatches=m, stacked_layers=stacked)
        pp_lens = pp_lens + 1
        pp_toks.append(jnp.argmax(lg, -1))

    for r, g in zip(ref_toks, pp_toks):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))
    # the caches agree where valid (same KV written stage-locally)
    np.testing.assert_allclose(np.asarray(pp_cache.k),
                               np.asarray(ref_cache.k), rtol=1e-4, atol=1e-4)


def test_pp_decode_under_jit_with_sharded_cache(cpu_devices):
    """The PP decode step compiles under jit with the cache PLACED sharded
    (layer axis over "stage") — each stage device holds 1/P of KV bytes."""
    from jax.sharding import NamedSharding
    from k8s_llm_rca_tpu.parallel import (
        kv_cache_stage_specs, llama_pp_decode_step, llama_pp_prefill,
    )

    cfg = TINY.replace(max_seq_len=64, n_layers=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(3))
    mesh = build_mesh(MeshConfig(stage=2), devices=cpu_devices[:2])
    b = 4
    cache = llama.init_cache(cfg, b, cfg.max_seq_len)
    spec = NamedSharding(mesh, kv_cache_stage_specs())
    cache = type(cache)(jax.device_put(cache.k, spec),
                        jax.device_put(cache.v, spec))
    tokens = jax.random.randint(jax.random.PRNGKey(4), (b, 16), 0,
                                cfg.vocab_size)
    lengths = jnp.full((b,), 16, jnp.int32)
    from k8s_llm_rca_tpu.parallel import stack_llama_stages

    stacked = stack_llama_stages(params, 2)     # hoisted off the hot path
    cache, logits = llama_pp_prefill(cfg, params, cache, tokens, lengths,
                                     mesh, stacked_layers=stacked)

    step = jax.jit(lambda c, t, ln: llama_pp_decode_step(
        cfg, params, c, t, ln, mesh, stacked_layers=stacked))
    cache, logits = step(cache, jnp.argmax(logits, -1), lengths)
    assert bool(jnp.isfinite(logits).all())
    shard_shape = cache.k.sharding.shard_shape(cache.k.shape)
    assert shard_shape[0] == cfg.n_layers // 2      # layers over stages


def test_cp_decode_with_seq_sharded_cache(cpu_devices):
    """Context-parallel DECODE: with the KV cache's sequence axis sharded
    over the seq mesh, plain decode_step produces the exact greedy tokens
    of the unsharded path — GSPMD partitions the attention reduction over
    S and inserts the combine collectives.  This is the long-context
    serving half that complements CP prefill: each device holds 1/P of
    the context's KV bytes."""
    from jax.sharding import NamedSharding
    from k8s_llm_rca_tpu.runtime.sharding import kv_cache_cp_specs

    cfg = TINY.replace(max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(seq=4), devices=cpu_devices[:4])
    b = 2
    prompts = jax.random.randint(jax.random.PRNGKey(1), (b, 16), 0,
                                 cfg.vocab_size)
    lengths = jnp.asarray([16, 12], jnp.int32)
    cache = llama.init_cache(cfg, b, cfg.max_seq_len)
    cache, logits = llama.prefill_batch(cfg, params, cache, prompts,
                                        lengths, jnp.arange(b))
    kv_spec, _ = kv_cache_cp_specs()
    sharded = llama.KVCache(
        jax.device_put(cache.k, NamedSharding(mesh, kv_spec)),
        jax.device_put(cache.v, NamedSharding(mesh, kv_spec)))

    step = jax.jit(llama.decode_step, static_argnums=0)
    cur = r_cur = jnp.argmax(logits, -1).astype(jnp.int32)
    lens = lengths
    cp_cache, ref_cache = sharded, cache
    for _ in range(6):
        ref_cache, ref_lg = step(cfg, params, ref_cache, r_cur, lens)
        cp_cache, cp_lg = step(cfg, params, cp_cache, cur, lens)
        r_cur = jnp.argmax(ref_lg, -1).astype(jnp.int32)
        cur = jnp.argmax(cp_lg, -1).astype(jnp.int32)
        np.testing.assert_array_equal(np.asarray(cur), np.asarray(r_cur))
        lens = lens + 1
    # the cache stayed sequence-sharded across steps
    shard = cp_cache.k.sharding.shard_shape(cp_cache.k.shape)
    assert shard[2] == cfg.max_seq_len // 4


def test_cp_engine_decodes_with_sharded_cache(cpu_devices):
    """The CP engine now places its cache sequence-sharded: greedy output
    matches the plain engine while each device stores 1/P of the KV."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    mesh = build_mesh(MeshConfig(seq=4), devices=cpu_devices[:4])
    ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    prompts = [tok.encode("pod pending unschedulable", add_bos=True),
               tok.encode("pvc not bound", add_bos=True)]

    ref = make_engine(cfg, ecfg, params, tok).generate(
        prompts, max_new_tokens=6)
    eng = make_engine(cfg, ecfg, params, tok, cp_mesh=mesh)
    got = eng.generate(prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids
    shard = eng.cache.k.sharding.shard_shape(eng.cache.k.shape)
    assert shard[2] == ecfg.max_seq_len // 4


def test_cp_tp_requires_one_composed_mesh(cpu_devices):
    """CP×TP composes only on ONE mesh carrying both axes: two distinct
    mesh objects (which would each claim the cache layout) are rejected,
    as is a composed mesh whose head counts don't split over 'model'."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.engine import InferenceEngine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    mesh_a = build_mesh(MeshConfig(data=1, model=2, seq=2),
                        devices=cpu_devices[:4])
    mesh_b = build_mesh(MeshConfig(data=1, model=2, seq=2),
                        devices=cpu_devices[4:8])
    ecfg = EngineConfig(max_batch=2, max_seq_len=64, prefill_buckets=(16,))
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="SAME composed mesh"):
        InferenceEngine(cfg, ecfg, params, get_tokenizer(),
                        cp_mesh=mesh_a, tp_mesh=mesh_b)
    with pytest.raises(ValueError, match="not divisible by model"):
        # n_kv_heads=2 cannot split over model=4
        mesh4 = build_mesh(MeshConfig(data=1, model=4, seq=2),
                           devices=cpu_devices[:8])
        InferenceEngine(cfg, ecfg, params, get_tokenizer(),
                        cp_mesh=mesh4, tp_mesh=mesh4)


@pytest.mark.parametrize("cp_mode", ["ring", "ulysses"])
def test_cp_tp_composed_engine_matches_plain(cpu_devices, cp_mode):
    """CP×TP in ONE mesh (SURVEY §7 hard part 6 — the long-context 8B
    shape: TP heads within a node, sequence ring across): the cache takes
    the seq-major × head-minor layout (S over 'seq', merged kv over
    'model', slots over 'data'), prefill runs the TP-aware ring/Ulysses
    per head shard, decode composes via GSPMD — exact greedy parity."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.engine import InferenceEngine
    from k8s_llm_rca_tpu.runtime.sharding import (
        llama_param_specs, shard_pytree,
    )
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    mesh = build_mesh(MeshConfig(data=2, model=2, seq=2),
                      devices=cpu_devices[:8])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                        prefill_buckets=(16, 32), max_new_tokens=6,
                        decode_chunk=1)
    prompts = [tok.encode("pod crashloop kube-system", add_bos=True),
               tok.encode("node disk pressure taint", add_bos=True)]

    with jax.default_matmul_precision("float32"):
        ref = InferenceEngine(cfg, ecfg, params, tok).generate(
            prompts, max_new_tokens=6)
        eng = InferenceEngine(cfg, ecfg, sharded, tok, cp_mesh=mesh,
                              tp_mesh=mesh, cp_mode=cp_mode)
        got = eng.generate(prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids, cp_mode
    # the cache is genuinely sharded on BOTH axes: seq and merged-kv halved
    shard = eng.cache.k.sharding.shard_shape(eng.cache.k.shape)
    assert shard[2] == cfg.max_seq_len // 2        # seq over 'seq'
    assert shard[3] == cfg.kv_dim // 2             # kv over 'model'
    assert shard[1] == 1                           # slots over 'data'


def test_cp_tp_composed_engine_quantized_cache(cpu_devices):
    """CP×TP × int8 KV: the composed layout shards the quantized payload
    and its per-token scales; greedy parity holds."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.engine import InferenceEngine
    from k8s_llm_rca_tpu.runtime.sharding import (
        llama_param_specs, shard_pytree,
    )
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    mesh = build_mesh(MeshConfig(data=2, model=2, seq=2),
                      devices=cpu_devices[:8])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                        prefill_buckets=(16, 32), max_new_tokens=6,
                        kv_cache_dtype="int8", decode_chunk=1)
    prompts = [tok.encode("pvc not bound", add_bos=True)]

    with jax.default_matmul_precision("float32"):
        ref = InferenceEngine(cfg, ecfg, params, tok).generate(
            prompts, max_new_tokens=6)
        eng = InferenceEngine(cfg, ecfg, sharded, tok, cp_mesh=mesh,
                              tp_mesh=mesh)
        got = eng.generate(prompts, max_new_tokens=6)
    assert ref[0].token_ids == got[0].token_ids


def test_cp_tp_composed_paged_engine_matches_plain(cpu_devices):
    """Paged CP×TP: TP-aware ring prefill scatters into the seq×model
    sharded page pool (page axis over 'seq', merged kv over 'model');
    decode composes via GSPMD — exact greedy parity with the plain paged
    engine."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine
    from k8s_llm_rca_tpu.runtime.sharding import (
        llama_param_specs, shard_pytree,
    )
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    mesh = build_mesh(MeshConfig(data=2, model=2, seq=2),
                      devices=cpu_devices[:8])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                        prefill_buckets=(16, 32), max_new_tokens=6,
                        paged=True, page_size=16, num_pages=32,
                        prefix_cache=False, decode_chunk=1)
    prompts = [tok.encode("pod crashloop kube-system", add_bos=True),
               tok.encode("node disk pressure taint", add_bos=True)]

    with jax.default_matmul_precision("float32"):
        ref = PagedInferenceEngine(cfg, ecfg, params, tok).generate(
            prompts, max_new_tokens=6)
        eng = PagedInferenceEngine(cfg, ecfg, sharded, tok, cp_mesh=mesh,
                                   tp_mesh=mesh)
        got = eng.generate(prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids
    eng.allocator.check()
    # the pool is sharded on BOTH axes: pages over 'seq', kv over 'model'
    shard = eng.pool.k.sharding.shard_shape(eng.pool.k.shape)
    assert shard[1] == ecfg.num_pages // 2
    assert shard[3] == cfg.kv_dim // 2


def test_cp_paged_seq_sharded_pool(cpu_devices):
    """CP seq-sharded paged pool (page-aligned CP splits): each CP device
    owns the page RANGE covering its sequence shard, so the paged engine
    stores 1/P of a long context's KV per device — the memory win the
    contiguous CP cache already had.  Greedy parity with the plain paged
    engine through decode that GROWS across the partition boundary, plus
    pool-bytes-per-device and allocator-partition assertions."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.paged import (
        PagedInferenceEngine, PartitionedPageAllocator, TRASH_PAGE,
    )
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=32)
    mesh = build_mesh(MeshConfig(seq=2), devices=cpu_devices[:2])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    # pages_per_seq = 4, partition boundary at page idx 2 (position 16):
    # a 12-token prompt + 12 new tokens crosses into partition 1 mid-decode
    ecfg = EngineConfig(max_batch=2, max_seq_len=32, page_size=8,
                        num_pages=16, prefill_buckets=(16,),
                        max_new_tokens=12, temperature=0.0,
                        prefix_cache=False, paged=True, decode_chunk=1)
    prompts = [tok.encode("0123456789a", add_bos=True),   # 12 tokens
               tok.encode("pvc not bnd", add_bos=True)]
    assert all(len(p) == 12 for p in prompts)

    with jax.default_matmul_precision("float32"):
        ref = PagedInferenceEngine(cfg, ecfg, params, tok).generate(
            prompts, max_new_tokens=12)
        eng = PagedInferenceEngine(cfg, ecfg, params, tok, cp_mesh=mesh)
        # partition-aware allocation is active
        assert isinstance(eng.allocator, PartitionedPageAllocator)
        got = eng.generate(prompts, max_new_tokens=12)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids
        # every sequence decoded past position 16 (the partition boundary)
        assert r.prompt_tokens + r.completion_tokens > 16
    eng.allocator.check()
    assert eng.allocator.n_free == 15              # nothing leaked

    # 1/P pool bytes per device: page axis sharded over 'seq'
    shard = eng.pool.k.sharding.shard_shape(eng.pool.k.shape)
    assert shard[1] == ecfg.num_pages // 2

    # partition alignment invariant: after a fresh admission, the page
    # covering positions [16, 24) must come from partition 1's id range
    seq = eng.submit(tok.encode("0123456789a", add_bos=True),
                     max_new_tokens=12)
    for _ in range(40):
        if not eng.has_work:
            break
        eng.step()
        for slot, st in eng._active.items():
            table = eng.block_tables[slot]
            for j in range(eng.pages_per_seq):
                if table[j] != TRASH_PAGE:
                    assert eng.allocator.part_of(int(table[j])) == \
                        eng._page_part(j), (j, int(table[j]))
    eng.allocator.check()


@pytest.mark.parametrize("paged", [False, True])
def test_cp_speculative_matches_plain(cpu_devices, paged):
    """Speculation composes with CP on both engines: the multi-token
    verify step runs over the seq-sharded cache (contiguous) / the
    seq-sharded page pool (paged) through GSPMD, with exact greedy
    parity against the non-speculative non-CP engine."""
    import dataclasses

    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=32)
    mesh = build_mesh(MeshConfig(seq=2), devices=cpu_devices[:2])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    extra = (dict(paged=True, page_size=8, num_pages=16,
                  prefix_cache=False) if paged else {})
    kw = dict(use_kernel=False) if paged else {}
    ecfg = EngineConfig(max_batch=2, max_seq_len=32, prefill_buckets=(16,),
                        max_new_tokens=10, temperature=0.0, **extra)
    prompts = [tok.encode("the pod the pod", add_bos=True),
               tok.encode("pvc bound pvc", add_bos=True)]
    with jax.default_matmul_precision("float32"):
        ref = make_engine(cfg, ecfg, params, tok, **kw).generate(
            [list(p) for p in prompts], max_new_tokens=10)
        spec = make_engine(cfg, dataclasses.replace(ecfg, speculative_k=3),
                           params, tok, cp_mesh=mesh, **kw)
        got = spec.generate([list(p) for p in prompts], max_new_tokens=10)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids, paged
    if paged:
        spec.allocator.check()


def test_cp_paged_partition_exhaustion_preempts_not_crashes(cpu_devices):
    """CP seq-sharded pool under PARTITION pressure: when the partition a
    growing slot needs is exhausted, evicting the youngest slot may free
    pages only in OTHER partitions — step() must keep evicting (and
    finally preempt the growing slot itself) instead of crashing on the
    unsatisfied retry (regression: the single-retry grow assumed any
    freed page could satisfy alloc, true only for the unpartitioned
    pool)."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine
    from k8s_llm_rca_tpu.utils.logging import METRICS
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=32)
    mesh = build_mesh(MeshConfig(seq=2), devices=cpu_devices[:2])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    ecfg = EngineConfig(max_batch=2, max_seq_len=32, page_size=8,
                        num_pages=16, prefill_buckets=(16,),
                        max_new_tokens=12, temperature=0.0,
                        prefix_cache=False, paged=True, decode_chunk=1)
    eng = PagedInferenceEngine(cfg, ecfg, params, tok, cp_mesh=mesh)
    # exhaust partition 1 (pages 8..15) so crossing position 16 cannot grow
    stolen = eng.allocator.alloc(8, owner=999, part=1)
    prompts = [tok.encode("0123456789a", add_bos=True) for _ in range(2)]
    assert all(len(p) == 12 for p in prompts)
    for p in prompts:
        eng.submit(p, max_new_tokens=12)
    before = METRICS.count("engine.preemptions")
    for _ in range(12):                      # churns, must not raise
        if eng.has_work:
            eng.step()
    assert METRICS.count("engine.preemptions") > before
    eng.allocator.check()
    # free the hostage partition: the sweep completes normally
    eng.allocator.free(stolen, owner=999)
    results = eng.run_to_completion()
    assert len(results) == 2
    eng.allocator.check()
    assert eng.allocator.n_free == 15


def test_ep_tp_dp_composed_engine_matches_dense(cpu_devices):
    """EP x TP x DP in ONE mesh (the v5e-16 Mixtral shape: experts across
    nodes, tensor-parallel heads within, batch replicas on top): the
    stacked expert weights shard over 'expert' AND their hidden dims over
    'model' (llama_param_specs composes both in one spec), the MoE MLPs
    dispatch all-to-all, and greedy output matches the dense single-device
    engine exactly."""
    from k8s_llm_rca_tpu.config import TINY_MOE, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.runtime.sharding import (
        llama_param_specs, shard_pytree,
    )
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY_MOE.replace(max_seq_len=64, n_experts=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    ecfg = EngineConfig(max_batch=4, max_seq_len=64,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0)
    prompts = [tok.encode("pod pending", add_bos=True),
               tok.encode("pvc not bound", add_bos=True),
               tok.encode("secret missing", add_bos=True)]
    ref = make_engine(cfg, ecfg, params, tok).generate(
        prompts, max_new_tokens=6)

    mesh = build_mesh(MeshConfig(data=2, expert=2, model=2),
                      devices=cpu_devices[:8])
    sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
    eng = make_engine(cfg, ecfg, sharded, tok, ep_mesh=mesh)
    got = eng.generate(prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids


def test_sp_forward_matches_and_shards_sequence(cpu_devices):
    """Megatron-style SP (SURVEY §2.2 SP row): under TP, constraining the
    residual stream's sequence dim over 'model' must not change the
    function, and the lowered module must actually carry the sequence
    sharding constraints (XLA then chooses reduce-scatter/all-gather or
    all-reduce+slice per its cost model — on TPU the former)."""
    from k8s_llm_rca_tpu.runtime.sharding import (
        llama_param_specs, shard_pytree,
    )

    cfg = TINY.replace(max_seq_len=64)
    mesh = build_mesh(MeshConfig(data=1, model=4),
                      devices=cpu_devices[:4])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                cfg.vocab_size)
    with jax.default_matmul_precision("float32"):
        ref = llama.forward(cfg, params, tokens)
        fn = jax.jit(lambda p, t: llama.forward(cfg, p, t, sp_mesh=mesh))
        got = fn(sharded, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=5e-4, atol=5e-4)
        lowered = fn.lower(sharded, tokens).as_text()
    # two constraints per layer on the [B, S, H] residual stream: the
    # seq (middle) dim sharded over the model axis (shardy dialect:
    # `sdy.sharding_constraint ... [{}, {"model"}, {}]`; pre-shardy:
    # `custom_call @Sharding`)
    n_sp = (lowered.count('sdy.sharding_constraint')
            + lowered.count('custom_call @Sharding'))
    assert n_sp >= 2 * cfg.n_layers, \
        f"expected >= {2 * cfg.n_layers} SP sharding constraints, " \
        f"found {n_sp}"
    assert ('[{}, {"model"}, {}]' in lowered
            or "Sharding" in lowered), \
        "no seq-over-model sharding annotation in the lowered module"


def test_sp_engine_matches_unsharded(cpu_devices):
    """sp=True on both engines: TP prefill with sequence-parallel
    activations emits the plain engine's greedy tokens."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.runtime.sharding import (
        llama_param_specs, shard_pytree,
    )
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    mesh = build_mesh(MeshConfig(data=2, model=2),
                      devices=cpu_devices[:4])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("pod crashloop kube-system", add_bos=True),
               tok.encode("node disk pressure taint", add_bos=True)]
    for paged in (False, True):
        ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                            prefill_buckets=(16, 32), max_new_tokens=6,
                            temperature=0.0, paged=paged, page_size=16,
                            num_pages=32, prefix_cache=False,
                            decode_chunk=1)
        kw = {"use_kernel": False} if paged else {}
        with jax.default_matmul_precision("float32"):
            ref = make_engine(cfg, ecfg, params, tok, **kw).generate(
                prompts, max_new_tokens=6)
            got = make_engine(cfg, ecfg, sharded, tok, tp_mesh=mesh,
                              sp=True, **kw).generate(
                prompts, max_new_tokens=6)
        for r, g in zip(ref, got):
            assert r.token_ids == g.token_ids, paged


def test_sp_requires_tp(cpu_devices):
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.engine import InferenceEngine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64)
    with pytest.raises(ValueError, match="requires tp_mesh"):
        InferenceEngine(cfg, EngineConfig(max_batch=2, max_seq_len=64,
                                          prefill_buckets=(16,)),
                        llama.init_params(cfg, jax.random.PRNGKey(0)),
                        get_tokenizer(vocab_size=cfg.vocab_size), sp=True)


@pytest.mark.parametrize("cp_mode", ["ring", "ulysses"])
def test_cp_ep_composed_engine_matches_dense(cpu_devices, cp_mode):
    """CP×EP in ONE mesh (long-context MoE serving: experts across the
    expert axis, sequence ring over 'seq'): CP prefill shards MoE tokens
    over (seq, expert) — the sequence never moves, dispatch rides the
    expert all-to-all — decode tokens shard over (data, expert) against
    the seq-sharded cache.  Exact greedy parity vs the dense engine."""
    from k8s_llm_rca_tpu.config import TINY_MOE, EngineConfig
    from k8s_llm_rca_tpu.engine.engine import InferenceEngine
    from k8s_llm_rca_tpu.models import mixtral
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY_MOE.replace(max_seq_len=64, n_experts=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0, decode_chunk=1)
    prompts = [tok.encode("pod pending unschedulable node", add_bos=True),
               tok.encode("pvc not bound storageclass", add_bos=True)]

    mesh = mixtral.build_ep_mesh(2, n_data=1, n_seq=2,
                                 devices=cpu_devices[:4])
    sharded = mixtral.shard_params_ep(cfg, params, mesh)
    with jax.default_matmul_precision("float32"):
        ref = InferenceEngine(cfg, ecfg, params, tok).generate(
            prompts, max_new_tokens=6)
        eng = InferenceEngine(cfg, ecfg, sharded, tok, cp_mesh=mesh,
                              ep_mesh=mesh, cp_mode=cp_mode)
        got = eng.generate(prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids, cp_mode
    # the cache is genuinely sequence-sharded across the composed mesh
    shard = eng.cache.k.sharding.shard_shape(eng.cache.k.shape)
    assert shard[2] == cfg.max_seq_len // 2


def test_cp_ep_composed_paged_engine_matches_dense(cpu_devices):
    """CP×EP on the paged engine: ring prefill writes through the
    page-scatter path while MoE MLPs dispatch over (seq, expert)."""
    from k8s_llm_rca_tpu.config import TINY_MOE, EngineConfig
    from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine
    from k8s_llm_rca_tpu.models import mixtral
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY_MOE.replace(max_seq_len=64, n_experts=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    ecfg = EngineConfig(max_batch=2, max_seq_len=64, paged=True,
                        page_size=8, num_pages=32,
                        prefill_buckets=(16, 32, 64), max_new_tokens=6,
                        temperature=0.0, prefix_cache=False,
                        decode_chunk=1)
    prompts = [tok.encode("node notready kubelet stopped", add_bos=True),
               tok.encode("image pull backoff", add_bos=True)]

    mesh = mixtral.build_ep_mesh(2, n_data=1, n_seq=2,
                                 devices=cpu_devices[:4])
    sharded = mixtral.shard_params_ep(cfg, params, mesh)
    with jax.default_matmul_precision("float32"):
        ref = PagedInferenceEngine(cfg, ecfg, params, tok,
                                   use_kernel=False).generate(
            prompts, max_new_tokens=6)
        eng = PagedInferenceEngine(cfg, ecfg, sharded, tok, cp_mesh=mesh,
                                   ep_mesh=mesh, use_kernel=False)
        got = eng.generate(prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids
    eng.allocator.check()


def test_cp_ep_requires_one_composed_mesh(cpu_devices):
    """CP×EP composes only on ONE mesh; distinct mesh objects are
    rejected, and prefill buckets must split over seq*expert."""
    from k8s_llm_rca_tpu.config import TINY_MOE, EngineConfig
    from k8s_llm_rca_tpu.engine.engine import InferenceEngine
    from k8s_llm_rca_tpu.models import mixtral
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY_MOE.replace(max_seq_len=64, n_experts=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    mesh_a = mixtral.build_ep_mesh(2, n_seq=2, devices=cpu_devices[:4])
    mesh_b = mixtral.build_ep_mesh(2, n_seq=2, devices=cpu_devices[4:8])
    ecfg = EngineConfig(max_batch=2, max_seq_len=64, prefill_buckets=(16,))
    with pytest.raises(ValueError, match="SAME composed mesh"):
        InferenceEngine(cfg, ecfg, params, get_tokenizer(),
                        cp_mesh=mesh_a, ep_mesh=mesh_b)
    with pytest.raises(ValueError, match="prefill token sharding"):
        # 18 splits over seq=2 but not over seq*expert=4
        InferenceEngine(cfg, EngineConfig(max_batch=2, max_seq_len=64,
                                          prefill_buckets=(18, 64)),
                        params, get_tokenizer(), cp_mesh=mesh_a,
                        ep_mesh=mesh_a)


# ---------------------------------------------------------------------------
# PP ENGINE integration (VERDICT r2 item 1): pp_mesh= on both engines
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv_dtype", [None, "int8", "int4"])
def test_pp_engine_matches_plain(cpu_devices, kv_dtype):
    """Serving PP: the continuous-batching engine with ``pp_mesh=`` — layer
    axis of weights AND KV cache sharded over "stage", admissions through
    the batched pipelined prefill, decode GPipe-microbatched — must emit
    the plain engine's exact greedy tokens, incl. quantized KV (the
    optimization that carries the big single-chip configs)."""
    from k8s_llm_rca_tpu.config import EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64, n_layers=4)
    mesh = build_mesh(MeshConfig(stage=2), devices=cpu_devices[:2])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(max_batch=4, max_seq_len=64,
                        prefill_buckets=(16, 32), max_new_tokens=6,
                        temperature=0.0, kv_cache_dtype=kv_dtype,
                        decode_chunk=1)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("pod pending unschedulable", add_bos=True),
               tok.encode("pvc not bound", add_bos=True),
               tok.encode("oom killed container", add_bos=True)]

    with jax.default_matmul_precision("float32"):
        ref = make_engine(cfg, ecfg, params, tok).generate(
            prompts, max_new_tokens=6)
        eng = make_engine(cfg, ecfg, params, tok, pp_mesh=mesh)
        got = eng.generate(prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids, kv_dtype
    # the cache is genuinely stage-sharded: 1/P of the layer axis per device
    shard = eng.cache.k.sharding.shard_shape(eng.cache.k.shape)
    assert shard[0] == cfg.n_layers // 2


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_pp_paged_engine_matches_plain(cpu_devices, kv_dtype):
    """Paged PP serving: the page pool's layer axis shards over "stage";
    pipelined prefill scatters pages per stage and decode reads the
    gathered local page view — exact greedy parity with the plain paged
    engine, incl. continuous-batching admission/retirement churn."""
    from k8s_llm_rca_tpu.config import EngineConfig
    from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64, n_layers=4)
    mesh = build_mesh(MeshConfig(stage=2), devices=cpu_devices[:2])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(max_batch=4, max_seq_len=64,
                        prefill_buckets=(16, 32), max_new_tokens=6,
                        temperature=0.0, kv_cache_dtype=kv_dtype,
                        paged=True, page_size=16, num_pages=32,
                        prefix_cache=False, decode_chunk=1)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("pod pending unschedulable", add_bos=True),
               tok.encode("pvc not bound", add_bos=True),
               tok.encode("oom killed container", add_bos=True),
               tok.encode("node disk pressure taint", add_bos=True),
               tok.encode("dns resolution failing", add_bos=True)]

    with jax.default_matmul_precision("float32"):
        ref = PagedInferenceEngine(cfg, ecfg, params, tok).generate(
            prompts, max_new_tokens=6)
        eng = PagedInferenceEngine(cfg, ecfg, params, tok, pp_mesh=mesh)
        got = eng.generate(prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids, kv_dtype
    shard = eng.pool.k.sharding.shard_shape(eng.pool.k.shape)
    assert shard[0] == cfg.n_layers // 2
    eng.allocator.check()                      # no pages leaked under PP


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_pp_paged_prefix_cache_reuse(cpu_devices, kv_dtype):
    """Prefix caching composes with (stage-only) PP: a repeated prompt's
    second admission routes through the PIPELINED chunked prefix prefill
    — each stage reuses its own layers' cached prefix pages from its
    local pool slice — with greedy output identical to the plain paged
    prefix engine and real page-level KV reuse (prefix_hit_tokens),
    including the quantized pool (scale gather + scale scatter in the
    pipelined chunk body)."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine
    from k8s_llm_rca_tpu.utils.logging import METRICS
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64, n_layers=4)
    mesh = build_mesh(MeshConfig(stage=2), devices=cpu_devices[:2])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    ecfg = EngineConfig(max_batch=2, max_seq_len=64, page_size=8,
                        num_pages=64, prefill_buckets=(16, 32),
                        max_new_tokens=6, temperature=0.0,
                        prefix_cache=True, decode_chunk=1,
                        kv_cache_dtype=kv_dtype)
    prompt = tok.encode("incident pod crashloop in namespace prod",
                        add_bos=True)
    assert len(prompt) > 16            # spans >2 pages -> cacheable prefix

    with jax.default_matmul_precision("float32"):
        plain = PagedInferenceEngine(cfg, ecfg, params, tok,
                                     use_kernel=False)
        p1 = plain.generate([list(prompt)], max_new_tokens=6)[0]
        eng = PagedInferenceEngine(cfg, ecfg, params, tok,
                                   use_kernel=False, pp_mesh=mesh)
        r1 = eng.generate([list(prompt)], max_new_tokens=6)[0]
        before = METRICS.count("engine.prefix_hit_tokens")
        r2 = eng.generate([list(prompt)], max_new_tokens=6)[0]
    assert r1.token_ids == p1.token_ids
    assert r2.token_ids == r1.token_ids
    # the second admission actually REUSED cached prefix KV through the
    # pipelined chunk path
    assert METRICS.count("engine.prefix_hit_tokens") > before, kv_dtype
    eng.allocator.check()


@pytest.mark.parametrize("kv_dtype", [None, "int8", "int4"])
def test_pp_tp_paged_prefix_cache_reuse(cpu_devices, kv_dtype):
    """Prefix caching composes with PP×TP (VERDICT r4 item 9 — the
    production mesh of the agent workload the cache was built for): a
    repeated prompt's second admission routes through the pipelined
    chunked prefix prefill whose stage bodies run the MANUAL-TP chunk
    layer (paged._chunk_layer(tp_axis=): per-shard prefix gather incl. the per-shard
    int4 layout, psum combines, pmax full-row scales) — greedy output
    identical to the plain paged prefix engine, with real page-level KV
    reuse."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine
    from k8s_llm_rca_tpu.utils.logging import METRICS
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64, n_layers=4)
    mesh = build_mesh(MeshConfig(stage=2, model=2),
                      devices=cpu_devices[:4])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    ecfg = EngineConfig(max_batch=2, max_seq_len=64, page_size=8,
                        num_pages=64, prefill_buckets=(16, 32),
                        max_new_tokens=6, temperature=0.0,
                        prefix_cache=True, decode_chunk=1,
                        kv_cache_dtype=kv_dtype)
    prompt = tok.encode("incident pod crashloop in namespace prod",
                        add_bos=True)
    assert len(prompt) > 16            # spans >2 pages -> cacheable prefix

    with jax.default_matmul_precision("float32"):
        plain = PagedInferenceEngine(cfg, ecfg, params, tok,
                                     use_kernel=False)
        p1 = plain.generate([list(prompt)], max_new_tokens=6)[0]
        eng = PagedInferenceEngine(cfg, ecfg, params, tok,
                                   use_kernel=False, pp_mesh=mesh,
                                   tp_mesh=mesh)
        r1 = eng.generate([list(prompt)], max_new_tokens=6)[0]
        before = METRICS.count("engine.prefix_hit_tokens")
        r2 = eng.generate([list(prompt)], max_new_tokens=6)[0]
    assert r1.token_ids == p1.token_ids, kv_dtype
    assert r2.token_ids == r1.token_ids, kv_dtype
    # the second admission actually REUSED cached prefix KV through the
    # pipelined manual-TP chunk path
    assert METRICS.count("engine.prefix_hit_tokens") > before, kv_dtype
    eng.allocator.check()


def test_pp_engine_dfa_scan_parity(cpu_devices):
    """Grammar-constrained decode stays on the fast path under PP: the
    DFA rides inside the chunked scan whose body is the PIPELINED decode
    step, emitting the same tokens as the stepwise host path."""
    import json as jsonlib

    from k8s_llm_rca_tpu.config import EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.engine.constrain import make_grammar
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=128, n_layers=4)
    mesh = build_mesh(MeshConfig(stage=2), devices=cpu_devices[:2])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    schema = {"type": "object", "properties": [
        ("kind", {"enum": ["Pod", "Service", "Node"]}),
        ("ok", {"type": "boolean"})]}
    prompt = tok.encode("diagnose:", add_bos=True)

    outs = {}
    with jax.default_matmul_precision("float32"):
        for chunk in (1, 8):
            ecfg = EngineConfig(max_batch=4, max_seq_len=128,
                                prefill_buckets=(16, 32), max_new_tokens=40,
                                decode_chunk=chunk)
            eng = make_engine(cfg, ecfg, params, tok, pp_mesh=mesh)
            rid = eng.submit(prompt, max_new_tokens=40,
                             grammar=make_grammar(schema, tok))
            res = {r.seq_id: r for r in eng.run_to_completion()}
            outs[chunk] = res[rid].text
    assert outs[1] == outs[8], outs
    jsonlib.loads(outs[1])


@pytest.mark.parametrize("kv_dtype", [None, "int8", "int4"])
def test_pp_tp_composed_engine_matches_plain(cpu_devices, kv_dtype):
    """PP×TP in ONE mesh (the multi-host pod topology: stages over DCN,
    heads/hidden over ICI): weights shard (stage, model), the cache
    shards layer-over-stage × kv-over-model, stage bodies run the
    manual-TP block with psum combines — exact greedy parity with the
    plain engine, through prefill, decode and the chunked scan.
    Quantized KV composes: the pmax full-row scale makes int8/int4
    PP×TP bit-identical to the plain quantized engine."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(n_layers=4, max_seq_len=64)
    mesh = build_mesh(MeshConfig(stage=2, model=2),
                      devices=cpu_devices[:4])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("pod crashloop kube-system", add_bos=True),
               tok.encode("node disk pressure taint", add_bos=True)]
    for chunk in (1, 4):
        ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                            prefill_buckets=(16, 32), max_new_tokens=6,
                            temperature=0.0, decode_chunk=chunk,
                            kv_cache_dtype=kv_dtype)
        with jax.default_matmul_precision("float32"):
            ref = make_engine(cfg, ecfg, params, tok).generate(
                prompts, max_new_tokens=6)
            eng = make_engine(cfg, ecfg, params, tok, pp_mesh=mesh,
                              tp_mesh=mesh)
            got = eng.generate(prompts, max_new_tokens=6)
        for r, g in zip(ref, got):
            assert r.token_ids == g.token_ids, (kv_dtype, chunk)
    # the cache is genuinely sharded on BOTH axes
    shard = eng.cache.k.sharding.shard_shape(eng.cache.k.shape)
    assert shard[0] == cfg.n_layers // 2           # layers over 'stage'
    assert shard[3] == eng.cache.k.shape[3] // 2   # kv over 'model'
    if kv_dtype is not None:
        # scale caches shard layer-over-stage, replicate across model
        sc = eng.cache.k_scale.sharding.shard_shape(eng.cache.k_scale.shape)
        assert sc[0] == cfg.n_layers // 2


@pytest.mark.parametrize("kv_dtype", [None, "int8", "int4"])
def test_pp_tp_paged_engine_matches_plain(cpu_devices, kv_dtype):
    """Paged PP×TP — the realistic multi-host pod serving shape (paged
    KV + continuous batching, stages over DCN, TP over ICI): weights
    shard (stage, model), the pool shards layer-over-stage ×
    kv-over-model, stage bodies run manual-TP qkv/attention with psum
    combines.  Quantized pools (int8 + packed int4) compose via the pmax
    full-row scale, so greedy parity with the plain paged engine is
    exact — through admission churn, page growth and the chunked scan."""
    from k8s_llm_rca_tpu.config import EngineConfig
    from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64, n_layers=4)
    mesh = build_mesh(MeshConfig(stage=2, model=2),
                      devices=cpu_devices[:4])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("pod pending unschedulable", add_bos=True),
               tok.encode("pvc not bound", add_bos=True),
               tok.encode("oom killed container", add_bos=True),
               tok.encode("node disk pressure taint", add_bos=True),
               tok.encode("dns resolution failing", add_bos=True)]
    for chunk in (1, 4):
        ecfg = EngineConfig(max_batch=4, max_seq_len=64,
                            prefill_buckets=(16, 32), max_new_tokens=6,
                            temperature=0.0, kv_cache_dtype=kv_dtype,
                            paged=True, page_size=16, num_pages=32,
                            prefix_cache=False, decode_chunk=chunk)
        with jax.default_matmul_precision("float32"):
            ref = PagedInferenceEngine(cfg, ecfg, params, tok).generate(
                prompts, max_new_tokens=6)
            eng = PagedInferenceEngine(cfg, ecfg, params, tok,
                                       pp_mesh=mesh, tp_mesh=mesh)
            got = eng.generate(prompts, max_new_tokens=6)
        for r, g in zip(ref, got):
            assert r.token_ids == g.token_ids, (kv_dtype, chunk)
        eng.allocator.check()                  # no pages leaked
    # the pool is genuinely sharded on BOTH axes
    shard = eng.pool.k.sharding.shard_shape(eng.pool.k.shape)
    assert shard[0] == cfg.n_layers // 2           # layers over 'stage'
    assert shard[3] == eng.pool.k.shape[3] // 2    # kv over 'model'
    if kv_dtype is not None:
        sc = eng.pool.k_scale.sharding.shard_shape(eng.pool.k_scale.shape)
        assert sc[0] == cfg.n_layers // 2


@pytest.mark.parametrize("paged", [False, True])
def test_pp_ep_composed_engine_matches_dense(cpu_devices, paged):
    """PP×EP in ONE mesh (Mixtral across pods: stages over DCN, expert
    dispatch over ICI within each stage): stacked expert weights shard
    (stage, expert), stage bodies run dense attention on the replicated
    stream and route each expert peer's token slice through the shared
    all-to-all dispatch — exact greedy parity with the dense
    single-device engine, on both the contiguous and the paged engine."""
    from k8s_llm_rca_tpu.config import TINY_MOE, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY_MOE.replace(n_layers=4, n_experts=4, max_seq_len=64)
    mesh = build_mesh(MeshConfig(stage=2, expert=2),
                      devices=cpu_devices[:4])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    prompts = [tok.encode("pod pending unschedulable", add_bos=True),
               tok.encode("pvc not bound", add_bos=True),
               tok.encode("oom killed container", add_bos=True)]
    extra = (dict(paged=True, page_size=16, num_pages=32,
                  prefix_cache=False) if paged else {})
    for chunk in (1, 4):
        ecfg = EngineConfig(max_batch=4, max_seq_len=64,
                            prefill_buckets=(16, 32), max_new_tokens=6,
                            temperature=0.0, decode_chunk=chunk, **extra)
        kw = dict(use_kernel=False) if paged else {}
        with jax.default_matmul_precision("float32"):
            ref = make_engine(cfg, ecfg, params, tok).generate(
                prompts, max_new_tokens=6)
            eng = make_engine(cfg, ecfg, params, tok, pp_mesh=mesh,
                              ep_mesh=mesh, **kw)
            got = eng.generate(prompts, max_new_tokens=6)
        for r, g in zip(ref, got):
            assert r.token_ids == g.token_ids, (paged, chunk)
    # expert weights genuinely sharded on BOTH axes: stage × expert
    _, stacked = eng.params
    shard = stacked["w_gate"].sharding.shard_shape(stacked["w_gate"].shape)
    assert shard[0] == 1                            # stages split
    assert shard[2] == cfg.n_experts // 2           # experts split
    if paged:
        eng.allocator.check()


@pytest.mark.parametrize("paged", [False, True])
@pytest.mark.parametrize("draft", ["ngram", "model", "ngram-int8"])
def test_pp_speculative_matches_plain(cpu_devices, paged, draft):
    """Speculation composes with PP on both engines: the verify step runs
    the PIPELINED multi-token decode (llama_pp_decode_multi /
    paged_pp_decode_multi) over the stage-sharded cache/pool, with exact
    greedy parity against the non-speculative non-PP engine — for n-gram
    drafts, a draft MODEL, and an int8-quantized cache/pool (the
    pipelined verify's quantized scale-write path)."""
    import dataclasses

    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(n_layers=4, max_seq_len=64)
    mesh = build_mesh(MeshConfig(stage=2), devices=cpu_devices[:2])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    extra = (dict(paged=True, page_size=16, num_pages=32,
                  prefix_cache=False) if paged else {})
    if draft == "ngram-int8":
        extra["kv_cache_dtype"] = "int8"
    kw = dict(use_kernel=False) if paged else {}
    dm = dict(draft_model=(cfg, params)) if draft == "model" else {}
    ecfg = EngineConfig(max_batch=2, max_seq_len=64, prefill_buckets=(16,),
                        max_new_tokens=10, temperature=0.0, **extra)
    prompts = [tok.encode("the pod the pod", add_bos=True),
               tok.encode("pvc bound pvc", add_bos=True)]
    with jax.default_matmul_precision("float32"):
        ref = make_engine(cfg, ecfg, params, tok, **kw).generate(
            [list(p) for p in prompts], max_new_tokens=10)
        spec = make_engine(cfg, dataclasses.replace(ecfg, speculative_k=3),
                           params, tok, pp_mesh=mesh, **kw, **dm)
        got = spec.generate([list(p) for p in prompts], max_new_tokens=10)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids, (paged, draft)
    if paged:
        spec.allocator.check()


def test_pp_composed_speculative_matches_plain(cpu_devices):
    """Speculation through the COMPOSED pipelined verify: PP×TP (paged,
    the pod serving shape) and PP×EP (MoE) both match their
    non-speculative plain engines exactly."""
    import dataclasses

    from k8s_llm_rca_tpu.config import TINY, TINY_MOE, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    prompts_txt = ["the pod the pod", "pvc bound pvc"]
    with jax.default_matmul_precision("float32"):
        # PP×TP × spec on the paged engine
        cfg = TINY.replace(n_layers=4, max_seq_len=64)
        mesh = build_mesh(MeshConfig(stage=2, model=2),
                          devices=cpu_devices[:4])
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        prompts = [tok.encode(t, add_bos=True) for t in prompts_txt]
        ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                            prefill_buckets=(16,), max_new_tokens=8,
                            temperature=0.0, paged=True, page_size=16,
                            num_pages=32, prefix_cache=False)
        ref = make_engine(cfg, ecfg, params, tok,
                          use_kernel=False).generate(
            [list(p) for p in prompts], max_new_tokens=8)
        spec = make_engine(cfg, dataclasses.replace(ecfg, speculative_k=3),
                           params, tok, pp_mesh=mesh, tp_mesh=mesh,
                           use_kernel=False)
        got = spec.generate([list(p) for p in prompts], max_new_tokens=8)
        for r, g in zip(ref, got):
            assert r.token_ids == g.token_ids
        spec.allocator.check()

        # PP×EP × spec
        mcfg = TINY_MOE.replace(n_layers=4, n_experts=4, max_seq_len=64)
        emesh = build_mesh(MeshConfig(stage=2, expert=2),
                           devices=cpu_devices[:4])
        mparams = llama.init_params(mcfg, jax.random.PRNGKey(1))
        mtok = get_tokenizer(vocab_size=mcfg.vocab_size)
        mp = [mtok.encode(t, add_bos=True) for t in prompts_txt]
        mecfg = EngineConfig(max_batch=4, max_seq_len=64,
                             prefill_buckets=(16,), max_new_tokens=8,
                             temperature=0.0)
        mref = make_engine(mcfg, mecfg, mparams, mtok).generate(
            [list(p) for p in mp], max_new_tokens=8)
        mspec = make_engine(mcfg,
                            dataclasses.replace(mecfg, speculative_k=3),
                            mparams, mtok, pp_mesh=emesh, ep_mesh=emesh)
        mgot = mspec.generate([list(p) for p in mp], max_new_tokens=8)
        for r, g in zip(mref, mgot):
            assert r.token_ids == g.token_ids


@pytest.mark.parametrize("paged", [False, True])
@pytest.mark.parametrize("bits", [8, 4])
def test_pp_tp_quantized_weights_matches_plain(cpu_devices, paged, bits):
    """Quantized WEIGHTS compose with PP×TP (the quantized-flagship pod
    serving shape): stacked QuantTensor leaves shard their payload on
    the weight spec and their per-channel scales with reduced dims
    replicated; int4 leaves are additionally RE-PACKED per shard at the
    sharding boundary ("shard first, pack second") so the manual-TP
    stage bodies' shard-local dequant is exact — greedy parity with the
    plain engine on the same quantized params.  bits=4 runs the bench's
    own flagship quant config (int4 weights + int4 KV)."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.models.quant import quantize_params
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(n_layers=4, max_seq_len=64)
    mesh = build_mesh(MeshConfig(stage=2, model=2),
                      devices=cpu_devices[:4])
    params = quantize_params(
        llama.init_params(cfg, jax.random.PRNGKey(0)),
        compute_dtype=jnp.float32, bits=bits)
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    extra = (dict(paged=True, page_size=16, num_pages=32,
                  prefix_cache=False) if paged else {})
    kw = dict(use_kernel=False) if paged else {}
    ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                        prefill_buckets=(16, 32), max_new_tokens=6,
                        temperature=0.0,
                        kv_cache_dtype="int8" if bits == 8 else "int4",
                        **extra)
    prompts = [tok.encode("pod crashloop kube-system", add_bos=True),
               tok.encode("node disk pressure taint", add_bos=True)]
    with jax.default_matmul_precision("float32"):
        ref = make_engine(cfg, ecfg, params, tok, **kw).generate(
            prompts, max_new_tokens=6)
        eng = make_engine(cfg, ecfg, params, tok, pp_mesh=mesh,
                          tp_mesh=mesh, **kw)
        got = eng.generate(prompts, max_new_tokens=6)
    for r, g in zip(ref, got):
        assert r.token_ids == g.token_ids, paged
    # the int8 payloads are genuinely sharded on BOTH axes
    _, stacked = eng.params
    shard = stacked["wq"].q.sharding.shard_shape(stacked["wq"].q.shape)
    assert shard[0] == 1                          # stages split
    assert shard[3] == stacked["wq"].q.shape[3] // 2   # columns over model
    if paged:
        eng.allocator.check()


def test_pp_tp_exclusions(cpu_devices):
    """PP×TP rejects loudly: distinct meshes, int4 weights whose channel
    dims don't divide 2*n_tp (per-shard split-half packing needs even
    per-shard pairs; divisible int4 composes — see the parity tests
    above), MoE models, and Megatron SP."""
    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.models.quant import quantize_params
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(n_layers=4, max_seq_len=64)
    mesh = build_mesh(MeshConfig(stage=2, model=2),
                      devices=cpu_devices[:4])
    mesh_b = build_mesh(MeshConfig(stage=2, model=2),
                        devices=cpu_devices[4:8])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    ecfg = EngineConfig(max_batch=2, max_seq_len=64, prefill_buckets=(16,))
    with pytest.raises(ValueError, match="SAME composed mesh"):
        make_engine(cfg, ecfg, params, tok, pp_mesh=mesh, tp_mesh=mesh_b)
    # intermediate_size=250 is even (packable) but 250 % (2*n_tp)=4 != 0:
    # the per-shard repack cannot split its column pairs evenly
    odd_cfg = cfg.replace(intermediate_size=250)
    odd_params = quantize_params(
        llama.init_params(odd_cfg, jax.random.PRNGKey(2)), bits=4)
    with pytest.raises(ValueError, match="per-shard split-half"):
        make_engine(odd_cfg, ecfg, odd_params, tok,
                    pp_mesh=mesh, tp_mesh=mesh)
    with pytest.raises(ValueError, match="per-shard split-half"):
        # the paged engine applies the same divisibility rejection
        make_engine(odd_cfg, dataclasses.replace(ecfg, paged=True,
                                                 page_size=16,
                                                 num_pages=16,
                                                 prefix_cache=False),
                    odd_params, tok,
                    pp_mesh=mesh, tp_mesh=mesh, use_kernel=False)
    with pytest.raises(ValueError, match="MoE"):
        moe_cfg = TINY_MOE.replace(n_layers=4, n_experts=4, max_seq_len=64)
        make_engine(moe_cfg, ecfg,
                    llama.init_params(moe_cfg, jax.random.PRNGKey(1)),
                    tok, pp_mesh=mesh, tp_mesh=mesh)
    with pytest.raises(ValueError, match="unsupported on the PP paths"):
        make_engine(cfg, ecfg, params, tok, pp_mesh=mesh, tp_mesh=mesh,
                    sp=True)


def test_pp_mesh_validation(cpu_devices):
    """PP preconditions fail loudly at construction, not mid-serve."""
    from k8s_llm_rca_tpu.config import EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=64, n_layers=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    pp = build_mesh(MeshConfig(stage=2), devices=cpu_devices[:2])
    tp = build_mesh(MeshConfig(data=2, model=2), devices=cpu_devices[:4])
    base = dict(max_batch=4, max_seq_len=64, prefill_buckets=(16, 32),
                max_new_tokens=4)

    with pytest.raises(ValueError, match="SAME composed mesh"):
        # PP×TP composes only on ONE mesh; two distinct meshes reject
        make_engine(cfg, EngineConfig(**base), params, tok,
                    pp_mesh=pp, tp_mesh=tp)
    from jax.sharding import Mesh as _Mesh

    no_stage = _Mesh(np.array(cpu_devices[:2]), ("x",))
    with pytest.raises(ValueError, match="stage"):
        make_engine(cfg, EngineConfig(**base), params, tok, pp_mesh=no_stage)
    with pytest.raises(ValueError, match="n_layers"):
        make_engine(cfg.replace(n_layers=3), EngineConfig(**base),
                    llama.init_params(cfg.replace(n_layers=3),
                                      jax.random.PRNGKey(0)),
                    tok, pp_mesh=pp)
    with pytest.raises(ValueError, match="microbatches"):
        make_engine(cfg, EngineConfig(**base), params, tok, pp_mesh=pp,
                    pp_microbatches=3)
    with pytest.raises(ValueError, match="prefix_cache"):
        # prefix caching composes with stage-only PP and PP×TP (see
        # test_pp_paged_prefix_cache_reuse / test_pp_tp_paged_prefix_
        # cache_reuse) but not with PP×EP — the chunk layer has no
        # expert dispatch
        moe_cfg4 = TINY_MOE.replace(n_layers=4, n_experts=4,
                                    max_seq_len=64)
        ppep = build_mesh(MeshConfig(stage=2, expert=2),
                          devices=cpu_devices[:4])
        PagedInferenceEngine(
            moe_cfg4, EngineConfig(paged=True, page_size=16, num_pages=32,
                                   prefix_cache=True, **base),
            llama.init_params(moe_cfg4, jax.random.PRNGKey(3)), tok,
            pp_mesh=ppep, ep_mesh=ppep, use_kernel=False)
    with pytest.raises(ValueError, match="use_kernel"):
        PagedInferenceEngine(
            cfg, EngineConfig(paged=True, page_size=16, num_pages=32,
                              prefix_cache=False, **base),
            params, tok, pp_mesh=pp, use_kernel=True)
