"""Pallas kernels vs pure-XLA reference implementations.

Runs hermetically on CPU via the Pallas interpreter (auto-selected when
the backend is not TPU), so kernel logic is covered without hardware —
the CPU-fallback test path SURVEY §4 calls for.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_llm_rca_tpu.ops.attention import causal_attention
from k8s_llm_rca_tpu.ops.flash_attention import flash_attention
from k8s_llm_rca_tpu.ops.paged_attention import (
    paged_attention, paged_attention_xla,
)


def _mk_qkv(key, b, s, n_heads, n_kv, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, n_heads, d), dtype)
    k = jax.random.normal(kk, (b, s, n_kv, d), dtype)
    v = jax.random.normal(kv, (b, s, n_kv, d), dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("n_heads,n_kv", [(4, 4), (8, 2)])
    def test_matches_reference(self, n_heads, n_kv):
        b, s, d = 2, 96, 64          # s deliberately not a block multiple
        q, k, v = _mk_qkv(jax.random.PRNGKey(0), b, s, n_heads, n_kv, d)
        seq_lens = jnp.array([96, 57], jnp.int32)

        ref = causal_attention(q, k, v, seq_lens)
        out = flash_attention(q, k, v, seq_lens, block_q=32, block_k=32)
        # rows past seq_len are padding garbage in both paths; compare valid
        for bi, n in enumerate([96, 57]):
            np.testing.assert_allclose(
                np.asarray(out)[bi, :n], np.asarray(ref)[bi, :n],
                rtol=2e-5, atol=2e-5)

    def test_chunked_prefill_offset(self):
        # queries for positions 32..63 attending to a 64-wide kv prefix
        b, d = 1, 64
        q, k, v = _mk_qkv(jax.random.PRNGKey(1), b, 64, 4, 4, d)
        q_chunk = q[:, 32:64]
        seq_lens = jnp.array([64], jnp.int32)
        off = jnp.array([32], jnp.int32)

        ref = causal_attention(q_chunk, k, v, seq_lens, q_offset=off)
        out = flash_attention(q_chunk, k, v, seq_lens, off,
                              block_q=32, block_k=32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_bfloat16(self):
        b, s, d = 1, 64, 64
        q, k, v = _mk_qkv(jax.random.PRNGKey(2), b, s, 4, 4, d, jnp.bfloat16)
        seq_lens = jnp.array([64], jnp.int32)
        ref = causal_attention(q, k, v, seq_lens)
        out = flash_attention(q, k, v, seq_lens, block_q=32, block_k=32)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=3e-2, atol=3e-2)


class TestPagedAttention:
    def _mk_pool(self, key, n_kv, n_pages, page, d):
        kk, kv = jax.random.split(key)
        kp = jax.random.normal(kk, (n_pages, page, n_kv * d))
        vp = jax.random.normal(kv, (n_pages, page, n_kv * d))
        return kp, vp

    @pytest.mark.parametrize("n_heads,n_kv", [(4, 4), (8, 2)])
    def test_matches_xla_reference(self, n_heads, n_kv):
        b, d, page, n_pages, pp_seq = 3, 64, 16, 32, 4
        key = jax.random.PRNGKey(3)
        q = jax.random.normal(key, (b, n_heads, d))
        kp, vp = self._mk_pool(jax.random.PRNGKey(4), n_kv, n_pages, page, d)
        # scattered, non-contiguous page assignments; unused entries = 0
        tables = jnp.array([[5, 9, 2, 0],
                            [7, 0, 0, 0],
                            [1, 30, 11, 21]], jnp.int32)
        lengths = jnp.array([3 * page + 5, page - 2, 4 * page], jnp.int32)

        ref = paged_attention_xla(q, kp, vp, lengths, tables)
        out = paged_attention(q, kp, vp, lengths, tables)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        assert tables.shape == (b, pp_seq)

    def test_single_token_sequence(self):
        b, n_heads, n_kv, d, page = 1, 4, 4, 64, 16
        q = jax.random.normal(jax.random.PRNGKey(5), (b, n_heads, d))
        kp, vp = self._mk_pool(jax.random.PRNGKey(6), n_kv, 8, page, d)
        tables = jnp.zeros((1, 2), jnp.int32).at[0, 0].set(3)
        lengths = jnp.array([1], jnp.int32)
        ref = paged_attention_xla(q, kp, vp, lengths, tables)
        out = paged_attention(q, kp, vp, lengths, tables)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestPagedAttentionQuant:
    """Quantized-pool kernel vs a dense gather+dequant reference."""

    def _mk_quant_pool(self, key, n_kv, n_pages, page, d, packed):
        from k8s_llm_rca_tpu.models.llama import _quantize_kv

        kk, kv = jax.random.split(key)
        kd = jax.random.normal(kk, (n_pages, page, n_kv * d))
        vd = jax.random.normal(kv, (n_pages, page, n_kv * d))
        kq, ks = _quantize_kv(kd, packed)
        vq, vs = _quantize_kv(vd, packed)
        return kq, vq, ks, vs

    def _reference(self, q, kq, vq, ks, vs, lengths, tables, packed):
        from k8s_llm_rca_tpu.models.llama import _dequant_layer

        kd = _dequant_layer(kq, ks, jnp.float32, packed)
        vd = _dequant_layer(vq, vs, jnp.float32, packed)
        return paged_attention_xla(q, kd, vd, lengths, tables)

    @pytest.mark.parametrize("packed", [False, True])
    @pytest.mark.parametrize("n_heads,n_kv", [(4, 4), (8, 2)])
    def test_matches_dequant_reference(self, n_heads, n_kv, packed):
        from k8s_llm_rca_tpu.ops.paged_attention import paged_attention_quant

        b, d, page, n_pages = 3, 64, 16, 32
        q = jax.random.normal(jax.random.PRNGKey(7), (b, n_heads, d))
        kq, vq, ks, vs = self._mk_quant_pool(jax.random.PRNGKey(8), n_kv,
                                             n_pages, page, d, packed)
        # page ids straddle the (8, page) scale-block boundaries on purpose
        tables = jnp.array([[5, 9, 2, 0],
                            [7, 0, 0, 0],
                            [16, 30, 11, 23]], jnp.int32)
        lengths = jnp.array([3 * page + 5, page - 2, 4 * page], jnp.int32)

        ref = self._reference(q, kq, vq, ks, vs, lengths, tables, packed)
        out = paged_attention_quant(q, kq, vq, ks, vs, lengths, tables,
                                    packed=packed)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.parametrize("packed", [False, True])
    def test_single_token_sequence(self, packed):
        from k8s_llm_rca_tpu.ops.paged_attention import paged_attention_quant

        b, n_heads, n_kv, d, page = 1, 4, 4, 64, 16
        q = jax.random.normal(jax.random.PRNGKey(9), (b, n_heads, d))
        kq, vq, ks, vs = self._mk_quant_pool(jax.random.PRNGKey(10), n_kv,
                                             9, page, d, packed)
        tables = jnp.zeros((1, 2), jnp.int32).at[0, 0].set(8)
        lengths = jnp.array([1], jnp.int32)
        ref = self._reference(q, kq, vq, ks, vs, lengths, tables, packed)
        out = paged_attention_quant(q, kq, vq, ks, vs, lengths, tables,
                                    packed=packed)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_engine_decode_step_uses_kernel_path(self):
        # use_kernel=True on CPU runs the quant kernel in interpret mode;
        # its logits must match the gather+dequant path (use_kernel=False)
        from k8s_llm_rca_tpu.config import TINY
        from k8s_llm_rca_tpu.engine.paged import (
            init_paged_cache, paged_decode_step, paged_prefill,
        )
        from k8s_llm_rca_tpu.models import llama

        cfg = TINY.replace(max_seq_len=64)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        for kv_dtype in (jnp.int8, "int4"):
            pool = init_paged_cache(cfg, 32, 8, kv_dtype=kv_dtype)
            prompt = list(range(5, 18))
            padded = jnp.zeros((1, 16), jnp.int32).at[0, :13].set(
                jnp.asarray(prompt))
            pool, logits = paged_prefill(cfg, params, pool, padded,
                                         jnp.int32(13),
                                         jnp.asarray([7, 3], jnp.int32))
            tables = jnp.asarray([[7, 3, 11, 0, 0, 0, 0, 0]], jnp.int32)
            args = (jnp.asarray([int(jnp.argmax(logits[0]))], jnp.int32),
                    jnp.asarray([13], jnp.int32), tables)
            _, lg_kernel = paged_decode_step(cfg, params, pool, *args,
                                             use_kernel=True)
            _, lg_xla = paged_decode_step(cfg, params, pool, *args,
                                          use_kernel=False)
            np.testing.assert_allclose(np.asarray(lg_kernel),
                                       np.asarray(lg_xla),
                                       rtol=2e-4, atol=2e-4)


class TestFlashSharded:
    """flash under TP (VERDICT r2 item 7): the kernel runs PER HEAD SHARD
    inside shard_map instead of conceding sharded prefill to XLA."""

    def _mesh(self, cpu_devices):
        from k8s_llm_rca_tpu.config import MeshConfig
        from k8s_llm_rca_tpu.runtime.mesh import build_mesh

        return build_mesh(MeshConfig(data=2, model=2),
                          devices=cpu_devices[:4])

    def test_matches_xla_reference(self, cpu_devices):
        from k8s_llm_rca_tpu.ops.attention import causal_attention
        from k8s_llm_rca_tpu.ops.flash_attention import (
            flash_attention_sharded,
        )

        mesh = self._mesh(cpu_devices)
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(kq, (2, 1024, 4, 16), jnp.float32)
        k = jax.random.normal(kk, (2, 1024, 2, 16), jnp.float32)
        v = jax.random.normal(kv, (2, 1024, 2, 16), jnp.float32)
        lens = jnp.asarray([1024, 700], jnp.int32)
        with jax.default_matmul_precision("float32"):
            ref = causal_attention(q, k, v, lens)
            out = flash_attention_sharded(q, k, v, lens, mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=5e-4, atol=5e-4)

    def test_rejects_indivisible_heads(self, cpu_devices):
        from k8s_llm_rca_tpu.ops.flash_attention import (
            flash_attention_sharded,
        )

        mesh = self._mesh(cpu_devices)
        q = jnp.zeros((1, 16, 3, 8), jnp.float32)     # 3 heads, model=2
        kv = jnp.zeros((1, 16, 3, 8), jnp.float32)
        with pytest.raises(ValueError, match="not divisible"):
            flash_attention_sharded(q, kv, kv, jnp.asarray([16]), mesh)

    def test_tp_prefill_runs_the_sharded_kernel(self, cpu_devices):
        """llama.prefill with flash_mesh= on TP-sharded params (the path
        flash_prefill_plan selects on TPU) matches the plain XLA prefill
        token-for-token."""
        from k8s_llm_rca_tpu.config import TINY
        from k8s_llm_rca_tpu.models import llama
        from k8s_llm_rca_tpu.runtime.sharding import (
            llama_param_specs, shard_pytree,
        )

        mesh = self._mesh(cpu_devices)
        cfg = TINY.replace(max_seq_len=1024)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 1024), 0,
                                    cfg.vocab_size)
        n = jnp.int32(900)
        with jax.default_matmul_precision("float32"):
            ref_cache = llama.init_cache(cfg, 2, 1024)
            ref_cache, ref_lg = llama.prefill(cfg, params, ref_cache,
                                              tokens, n, jnp.int32(0))
            fl_cache = llama.init_cache(cfg, 2, 1024)
            fl_cache, fl_lg = llama.prefill(cfg, sharded, fl_cache, tokens,
                                            n, jnp.int32(0), use_flash=True,
                                            flash_mesh=mesh)
        assert int(jnp.argmax(ref_lg)) == int(jnp.argmax(fl_lg))
        np.testing.assert_allclose(np.asarray(fl_cache.k[:, 0, :900]),
                                   np.asarray(ref_cache.k[:, 0, :900]),
                                   rtol=5e-4, atol=5e-4)

    def test_flash_prefill_plan_gating(self, cpu_devices, monkeypatch):
        from k8s_llm_rca_tpu.config import TINY
        from k8s_llm_rca_tpu.engine import engine as eng_mod
        from k8s_llm_rca_tpu.models import llama
        from k8s_llm_rca_tpu.runtime.sharding import (
            llama_param_specs, shard_pytree,
        )

        mesh = self._mesh(cpu_devices)
        cfg = TINY
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        sharded = shard_pytree(params, llama_param_specs(cfg), mesh)
        # CPU: no kernel anywhere
        assert eng_mod.flash_prefill_plan(params, None, cfg) == (False, None)
        assert eng_mod.flash_prefill_plan(sharded, mesh, cfg) == (False,
                                                                  None)
        # "TPU": plain kernel unsharded, per-shard kernel under TP
        monkeypatch.setattr(eng_mod.jax, "default_backend", lambda: "tpu")
        assert eng_mod.flash_prefill_plan(params, None, cfg) == (True, None)
        assert eng_mod.flash_prefill_plan(sharded, mesh, cfg) == (True,
                                                                  mesh)
        # indivisible heads: concede to XLA
        cfg3 = cfg.replace(n_heads=6, n_kv_heads=3)
        assert eng_mod.flash_prefill_plan(sharded, mesh, cfg3) == (False,
                                                                   None)
        # EP token sharding: concede to XLA even with a TP mesh present
        assert eng_mod.flash_prefill_plan(sharded, mesh, cfg,
                                          ep_mesh=mesh) == (False, None)
