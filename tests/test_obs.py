"""Observability / flight recorder (k8s_llm_rca_tpu/obs/).

Covers the ISSUE-2 acceptance bars:

- deterministic traces: two seeded chaos soaks with a VirtualClock export
  byte-identical Chrome trace-event JSON, and the document validates
  (sorted ts, complete X events);
- the Prometheus renderer escapes HELP text, types counters/summaries/
  gauges correctly and never duplicates a HELP line; the serve API
  surfaces the rendering with live engine gauges;
- the SITES registry self-check: every name the tracer registry declares
  is emitted by at least one instrumented call site (instrumentation
  cannot silently rot);
- Metrics.timings growth is bounded (reservoir) with exact total/count,
  and reset()/scoped() isolate tests from the global METRICS.
"""

import jax
import pytest

from k8s_llm_rca_tpu.config import TINY, EngineConfig
from k8s_llm_rca_tpu.engine import make_engine
from k8s_llm_rca_tpu.faults.plan import VirtualClock
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.obs import (
    SITES, Tracer, chrome_trace, chrome_trace_bytes, coverage_missing,
    prometheus_text, validate_chrome_trace,
)
from k8s_llm_rca_tpu.obs import trace as obs_trace
from k8s_llm_rca_tpu.utils.logging import METRICS, Metrics, TIMING_RESERVOIR
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _no_leaked_tracer():
    """Never leak an active tracer into other tests."""
    yield
    if obs_trace.active() is not None:
        obs_trace.deactivate()


@pytest.fixture(scope="module")
def small_engine():
    """One TINY paged engine shared by the obs tests (greedy decode:
    outputs depend only on weights/prompts, same rationale as
    test_faults.shared_engine)."""
    cfg = TINY.replace(max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    eng = make_engine(
        cfg, EngineConfig(max_batch=4, max_seq_len=64, paged=True,
                          page_size=8, num_pages=24,
                          prefill_buckets=(16, 32), max_new_tokens=8,
                          temperature=0.0, decode_chunk=1,
                          prefix_cache=False),
        params, tok, use_kernel=False)
    return eng, tok


# ---------------------------------------------------------------------------
# bounded metrics (satellite 1)
# ---------------------------------------------------------------------------


class TestBoundedMetrics:
    def test_reservoir_bounds_growth_keeps_exact_totals(self):
        m = Metrics()
        n = TIMING_RESERVOIR + 300
        for _ in range(n):
            with m.timer("t"):
                pass
        r = m.timings["t"]
        assert len(r) == TIMING_RESERVOIR          # bounded retention
        assert r.count == n                        # exact count
        assert r.total == pytest.approx(sum([r.total]))  # finite
        snap = m.snapshot()
        assert snap["t.count"] == float(n)         # snapshot uses EXACT count
        assert snap["t.total_s"] == pytest.approx(r.total)

    def test_p50_over_retained_window(self):
        m = Metrics()
        # bypass the timer to control sample values
        with m._lock:
            res = m.timings["t"]
        for v in range(TIMING_RESERVOIR + 100):
            res.append(float(v))
        # the retained window is the NEWEST TIMING_RESERVOIR samples
        window = res.window()
        assert len(window) == TIMING_RESERVOIR
        assert min(window) == 100.0
        import statistics
        assert m.p50("t") == statistics.median(window)

    def test_reset_and_scoped_isolation(self):
        m = Metrics()
        m.inc("a", 2)
        with m.timer("t"):
            pass
        with m.scoped():
            assert m.count("a") == 0               # fresh inside
            m.inc("a", 99)
            m.inc("only_inside")
        assert m.count("a") == 2                   # restored
        assert m.count("only_inside") == 0
        assert len(m.timings["t"]) == 1
        m.reset()
        assert m.count("a") == 0
        assert m.total("t") == 0.0


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------


def _record_fixed(tracer: Tracer) -> None:
    clock = tracer.clock
    with tracer.span("outer", cat="test", k="v"):
        clock.sleep(0.5)
        with tracer.span("inner"):
            clock.sleep(0.25)
        tracer.event("blip", x=1)
    tracer.add_span("detached", t0=0.1, t1=0.3, args={"run": "run_0"})


class TestTracer:
    def test_deterministic_ids_and_parentage(self):
        t1, t2 = Tracer(clock=VirtualClock()), Tracer(clock=VirtualClock())
        _record_fixed(t1)
        _record_fixed(t2)
        assert [(s.span_id, s.parent_id, s.name, s.t0, s.t1)
                for s in t1.spans] == \
               [(s.span_id, s.parent_id, s.name, s.t0, s.t1)
                for s in t2.spans]
        outer, inner, detached = t1.spans
        assert inner.parent_id == outer.span_id
        assert detached.parent_id is None          # stack empty at add time
        assert t1.events[0].parent_id == outer.span_id
        assert outer.t1 - outer.t0 == pytest.approx(0.75)   # virtual time

    def test_bounded_store_counts_drops(self):
        tr = Tracer(clock=VirtualClock(), max_spans=3)
        for i in range(6):
            with tr.span(f"s{i}"):
                pass
        assert len(tr.spans) == 3
        assert tr.dropped == 3
        doc = chrome_trace(tr)
        assert doc["metadata"]["dropped"] == 3

    def test_inactive_helpers_are_noops(self):
        assert obs_trace.active() is None
        with obs_trace.span("nope"):
            obs_trace.event("nope.event")
        # nothing recorded anywhere, nothing raised
        tr = Tracer()
        with obs_trace.tracing(tr):
            assert obs_trace.active() is tr
            with pytest.raises(RuntimeError, match="already active"):
                obs_trace.activate(Tracer())
        assert obs_trace.active() is None

    def test_flight_summary_since_mark(self):
        tr = Tracer(clock=VirtualClock())
        with tr.span("before"):
            pass
        mark = tr.mark()
        with tr.span("after"):
            tr.event("after.event")
        s = tr.flight_summary(since=mark)
        assert s["spans"] == 1 and s["events"] == 1
        assert s["by_name"] == {"after": 1}


# ---------------------------------------------------------------------------
# Chrome trace exporter
# ---------------------------------------------------------------------------


class TestChromeExport:
    def test_validates_and_is_byte_stable(self):
        t1, t2 = Tracer(clock=VirtualClock()), Tracer(clock=VirtualClock())
        _record_fixed(t1)
        _record_fixed(t2)
        d1, d2 = chrome_trace(t1), chrome_trace(t2)
        assert validate_chrome_trace(d1) == len(d1["traceEvents"]) == 4
        assert chrome_trace_bytes(d1) == chrome_trace_bytes(d2)
        ts = [e["ts"] for e in d1["traceEvents"]]
        assert ts == sorted(ts)
        for ev in d1["traceEvents"]:
            if ev["ph"] == "X":
                assert ev["dur"] >= 0

    def test_subtree_export_per_incident(self):
        tr = Tracer(clock=VirtualClock())
        with tr.span("rca.incident") as root:
            with tr.span("rca.stage.locate"):
                pass
        with tr.span("other"):
            pass
        doc = chrome_trace(tr, root=root.span_id)
        names = {e["name"] for e in doc["traceEvents"]}
        assert names == {"rca.incident", "rca.stage.locate"}
        validate_chrome_trace(doc)

    def test_validator_rejects_unsorted_and_unmatched(self):
        good = {"traceEvents": [
            {"name": "a", "ph": "X", "ts": 2, "dur": 1, "pid": 1, "tid": 1},
            {"name": "b", "ph": "i", "ts": 1, "pid": 1, "tid": 1},
        ]}
        with pytest.raises(ValueError, match="unsorted"):
            validate_chrome_trace(good)
        with pytest.raises(ValueError, match="without matching B"):
            validate_chrome_trace({"traceEvents": [
                {"name": "a", "ph": "E", "ts": 1, "pid": 1, "tid": 1}]})
        with pytest.raises(ValueError, match="unmatched B"):
            validate_chrome_trace({"traceEvents": [
                {"name": "a", "ph": "B", "ts": 1, "pid": 1, "tid": 1}]})


# ---------------------------------------------------------------------------
# Prometheus renderer
# ---------------------------------------------------------------------------


class _StubEngine:
    """Engine-shaped stub for gauge rendering (no device work)."""

    def __init__(self):
        self._active = {0: object(), 1: object()}
        self._pending = [object()]
        self._counts = {"engine.prefix_hit_tokens": 7.0,
                        "engine.prefix_hits_l1": 5.0,
                        "engine.prefix_demotions": 9.0}
        self.allocator = type("A", (), {"n_free": 11})()
        self.prefix_cache = type("P", (), {"n_evictable": 3})()


class TestPrometheus:
    def test_counter_and_summary_families(self):
        m = Metrics()
        m.inc("engine.decode_tokens", 5)
        with m.timer("rca.incident"):
            pass
        text = prometheus_text(m)
        assert "# TYPE k8s_llm_rca_engine_decode_tokens_total counter" \
            in text
        assert "k8s_llm_rca_engine_decode_tokens_total 5" in text
        assert "# TYPE k8s_llm_rca_rca_incident_seconds summary" in text
        assert 'k8s_llm_rca_rca_incident_seconds{quantile="0.5"}' in text
        assert "k8s_llm_rca_rca_incident_seconds_count 1" in text

    def test_help_escaping_and_no_duplicate_help(self):
        m = Metrics()
        m.inc("weird\nname\\x", 1)
        m.inc("weird name x", 1)      # sanitizes to the SAME family
        text = prometheus_text(m)
        help_lines = [ln for ln in text.splitlines()
                      if ln.startswith("# HELP")]
        assert len(help_lines) == len(set(help_lines))
        # one family name appears in exactly one HELP line
        fam = "k8s_llm_rca_weird_name_x_total"
        assert sum(ln.split()[2] == fam for ln in help_lines) == 1
        # newline/backslash escaped per the exposition format
        assert "\\n" in text.split(fam)[1].splitlines()[0] \
            or any("\\n" in ln or "\\\\" in ln for ln in help_lines)
        for ln in text.splitlines():
            assert "\n" not in ln     # trivially true; no raw newlines leak

    def test_engine_gauges(self):
        text = prometheus_text(Metrics(), engine=_StubEngine())
        assert "k8s_llm_rca_engine_running_seqs 2" in text
        assert "k8s_llm_rca_engine_queued_seqs 1" in text
        assert "k8s_llm_rca_engine_free_pages 11" in text
        assert "k8s_llm_rca_engine_evictable_pages 3" in text
        assert "k8s_llm_rca_engine_prefix_hit_tokens 7" in text
        assert "k8s_llm_rca_engine_prefix_hits_l1 5" in text
        assert "k8s_llm_rca_engine_prefix_hits_l0 0" in text
        assert "k8s_llm_rca_engine_prefix_demotions 9" in text
        assert "# TYPE k8s_llm_rca_engine_free_pages gauge" in text

    def test_serve_api_surfaces_rendering(self, small_engine):
        from k8s_llm_rca_tpu.serve.api import AssistantService
        from k8s_llm_rca_tpu.serve.backend import EngineBackend

        engine, tok = small_engine
        service = AssistantService(EngineBackend(engine))
        text = service.prometheus_metrics()
        assert "k8s_llm_rca_engine_running_seqs" in text
        assert "k8s_llm_rca_engine_free_pages" in text

    def test_cluster_router_gauges(self):
        """Router-aware exposition: per-replica queue depth / occupancy
        as labelled gauges plus the alive-replica count (satellite 2 of
        the cluster subsystem)."""
        from k8s_llm_rca_tpu.cluster import ClusterRouter, Replica
        from k8s_llm_rca_tpu.serve.backend import EchoBackend, GenOptions
        from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

        tok = get_tokenizer()
        router = ClusterRouter([
            Replica(0, EchoBackend(tok, delay_pumps=10 ** 9)),
            Replica(1, EchoBackend(tok, delay_pumps=10 ** 9))])
        router.start("p", GenOptions())
        text = prometheus_text(Metrics(), router=router)
        assert "k8s_llm_rca_cluster_replicas_alive 2" in text
        assert ('k8s_llm_rca_cluster_replica_queue_depth'
                '{replica="0"} 1') in text
        assert ('k8s_llm_rca_cluster_replica_queue_depth'
                '{replica="1"} 0') in text
        assert 'k8s_llm_rca_cluster_replica_occupancy{replica="0"}' in text
        assert "# TYPE k8s_llm_rca_cluster_replicas_alive gauge" in text
        router.fail_replica(0)
        text = prometheus_text(Metrics(), router=router)
        assert "k8s_llm_rca_cluster_replicas_alive 1" in text
        assert '{replica="0"}' not in text    # dead replicas drop out

    def test_serve_api_cluster_router_rendering(self):
        """AssistantService.prometheus_metrics detects a router backend
        and renders the cluster families."""
        from k8s_llm_rca_tpu.cluster import ClusterRouter, Replica
        from k8s_llm_rca_tpu.serve.api import AssistantService
        from k8s_llm_rca_tpu.serve.backend import EchoBackend
        from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

        tok = get_tokenizer()
        service = AssistantService(ClusterRouter(
            [Replica(0, EchoBackend(tok)), Replica(1, EchoBackend(tok))]))
        text = service.prometheus_metrics()
        assert "k8s_llm_rca_cluster_replicas_alive 2" in text

    def test_autoscaler_fleet_gauges_two_way(self):
        """Elastic-fleet exposition (cluster/autoscale.py): the
        cluster_fleet_size{tier=} gauge and the
        cluster_scale_events_total{kind=} counter render from the
        router's autoscaler backref once actions fired — and stay
        absent on a router without one (two-way coverage)."""
        from k8s_llm_rca_tpu.cluster import (
            Autoscaler, ClusterRouter, HealthPolicy, HealthWatchdog,
            Replica, ReplicaSupervisor,
        )
        from k8s_llm_rca_tpu.serve.backend import EchoBackend
        from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

        tok = get_tokenizer()
        mk = lambda i: Replica(i, EchoBackend(tok),         # noqa: E731
                               rebuild=lambda: EchoBackend(tok))
        router = ClusterRouter([mk(0), mk(1)])
        # no autoscaler attached: the elastic families stay absent
        text = prometheus_text(Metrics(), router=router)
        assert "cluster_fleet_size" not in text
        assert "cluster_scale_events_total" not in text
        router.attach_health(
            HealthWatchdog(HealthPolicy(miss_budget=1,
                                        hung_tick_threshold=2),
                           clock=VirtualClock()),
            ReplicaSupervisor())
        scaler = Autoscaler(router, reserve=[mk(2)])
        text = prometheus_text(Metrics(), router=router)
        assert 'k8s_llm_rca_cluster_fleet_size{tier="all"} 2' in text
        assert "# TYPE k8s_llm_rca_cluster_fleet_size gauge" in text
        assert "cluster_scale_events_total" not in text  # no actions yet
        scaler.scale_up()
        scaler.scale_down()
        text = prometheus_text(Metrics(), router=router)
        assert 'k8s_llm_rca_cluster_fleet_size{tier="all"} 2' in text
        assert ('k8s_llm_rca_cluster_scale_events_total'
                '{kind="up"} 1') in text
        assert ('k8s_llm_rca_cluster_scale_events_total'
                '{kind="down"} 1') in text
        assert '{kind="rebalance"}' not in text   # never fired: no row
        assert ("# TYPE k8s_llm_rca_cluster_scale_events_total "
                "counter") in text

    def test_autoscaler_tier_labels(self):
        """On a TierRouter the fleet-size gauge splits per tier."""
        from k8s_llm_rca_tpu.cluster import (
            Autoscaler, HealthPolicy, HealthWatchdog, Replica,
            ReplicaSupervisor, TierRouter,
        )
        from k8s_llm_rca_tpu.serve.backend import EchoBackend
        from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

        tok = get_tokenizer()
        mk = lambda i: Replica(i, EchoBackend(tok),         # noqa: E731
                               rebuild=lambda: EchoBackend(tok))
        router = TierRouter([mk(0)], [mk(1), mk(2)])
        router.attach_health(
            HealthWatchdog(HealthPolicy(miss_budget=1,
                                        hung_tick_threshold=2),
                           clock=VirtualClock()),
            ReplicaSupervisor())
        Autoscaler(router)
        text = prometheus_text(Metrics(), router=router)
        assert ('k8s_llm_rca_cluster_fleet_size'
                '{tier="prefill"} 1') in text
        assert ('k8s_llm_rca_cluster_fleet_size'
                '{tier="decode"} 2') in text

    def test_store_fabric_families_two_way(self):
        """Cache-fabric exposition (cluster/store.py): with a live store
        handle, hits render as the labeled
        cluster_store_hits_total{tier=} counter plus op/residency
        gauges; without one — or with a DEAD store, whose stats()
        degrades to {} by the fabric's cold-miss contract — the
        families stay absent and the scrape never errors (two-way
        coverage)."""

        class _StubStore:
            def stats(self):
                return {"puts": 3.0, "gets": 5.0, "hits_l1": 2.0,
                        "hits_l2": 1.0, "misses": 2.0, "rejected": 0.0,
                        "n_host": 2, "n_disk": 1}

        class _DeadStore:
            def stats(self):
                return {}

        text = prometheus_text(Metrics())
        assert "cluster_store_" not in text
        text = prometheus_text(Metrics(), store=_StubStore())
        assert ('k8s_llm_rca_cluster_store_hits_total'
                '{tier="l1"} 2') in text
        assert ('k8s_llm_rca_cluster_store_hits_total'
                '{tier="l2"} 1') in text
        assert ("# TYPE k8s_llm_rca_cluster_store_hits_total "
                "counter") in text
        assert "k8s_llm_rca_cluster_store_puts 3" in text
        assert "k8s_llm_rca_cluster_store_misses 2" in text
        assert "k8s_llm_rca_cluster_store_n_host 2" in text
        assert "# TYPE k8s_llm_rca_cluster_store_n_disk gauge" in text
        text = prometheus_text(Metrics(), store=_DeadStore())
        assert "cluster_store_" not in text


# ---------------------------------------------------------------------------
# golden byte-identity: traced seeded chaos soak (acceptance bar)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestTracedSoak:
    def test_traced_soak_chrome_json_byte_identical(self):
        """Two runs of the seeded chaos soak with a VirtualClock-bound
        tracer must export byte-identical, Perfetto-valid Chrome trace
        JSON — the flight recorder's golden acceptance bar."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak

        t1, t2 = Tracer(), Tracer()
        r1 = run_chaos_soak(seed=0, n_incidents=2, backend="oracle",
                            tracer=t1)
        r2 = run_chaos_soak(seed=0, n_incidents=2, backend="oracle",
                            tracer=t2)
        d1, d2 = chrome_trace(t1), chrome_trace(t2)
        assert validate_chrome_trace(d1) > 0
        assert chrome_trace_bytes(d1) == chrome_trace_bytes(d2)
        # the traced report (incl. per-incident flight digests) is still
        # byte-identical, and tracing didn't change the soak outcome
        assert report_bytes(r1) == report_bytes(r2)
        assert r1["flight"]["spans"] > 0
        untr = run_chaos_soak(seed=0, n_incidents=2, backend="oracle")
        for row, row_t in zip(untr["incidents"], r1["incidents"]):
            assert row["status"] == row_t["status"]
            assert "flight" in row_t and row_t["flight"]["spans"] > 0

    def test_engine_tick_timeline_gauges(self, small_engine):
        """Traced paged-engine run: the tick timeline samples pool
        gauges, and tracing does not perturb greedy output."""
        engine, tok = small_engine
        prompts = [tok.encode("pod oom killed", add_bos=True),
                   tok.encode("pvc unbound", add_bos=True)]
        ref = engine.generate(prompts, max_new_tokens=6)
        tr = Tracer(clock=VirtualClock())
        with obs_trace.tracing(tr):
            got = engine.generate(prompts, max_new_tokens=6)
        assert [r.token_ids for r in ref] == [r.token_ids for r in got]
        assert tr.timeline.total > 0
        samples = tr.timeline.samples()
        last = samples[-1]
        assert last.free_pages == engine.allocator.n_free
        assert last.decode_tokens > 0 and last.prefill_tokens > 0
        assert any(s.running > 0 for s in samples)
        doc = chrome_trace(tr)
        validate_chrome_trace(doc)
        counter_names = {e["name"] for e in doc["traceEvents"]
                         if e["ph"] == "C"}
        assert {"engine.seqs", "engine.pages", "engine.tokens",
                "engine.sched", "engine.prefix"} <= counter_names

    def test_cluster_counter_tracks_separate_by_replica(self):
        """TickSamples stamped with engine_id render onto per-replica
        Chrome counter tracks (tid = replica id) and the engine.host
        track carries the router's queue-depth/occupancy gauges
        (satellite 2 of the cluster subsystem)."""
        from k8s_llm_rca_tpu.obs.timeline import TickSample

        tr = Tracer(clock=VirtualClock())
        tr.timeline.record(TickSample(
            tick=0, ts=0.001, running=1, queued=0, engine_id=0,
            cluster_queue_depth=2.0, cluster_occupancy=0.5))
        tr.timeline.record(TickSample(
            tick=0, ts=0.002, running=1, queued=1, engine_id=1,
            cluster_queue_depth=1.0, cluster_occupancy=0.25))
        doc = chrome_trace(tr)
        validate_chrome_trace(doc)
        host = sorted((e for e in doc["traceEvents"]
                       if e["name"] == "engine.host"),
                      key=lambda e: e["ts"])
        assert [e["tid"] for e in host] == [0, 1]   # separate tracks
        assert host[0]["args"]["cluster_queue_depth"] == 2.0
        assert host[1]["args"]["cluster_occupancy"] == 0.25
        # every counter event of one sample rides that sample's track
        assert {e["tid"] for e in doc["traceEvents"]
                if e["ph"] == "C" and e["ts"] == host[1]["ts"]} == {1}

    def test_scale_events_counter_track(self):
        """Autoscaler actions render as a running per-kind Chrome
        counter track (cluster.scale_events) plus fleet-size samples
        (cluster.fleet_size), mirroring the Prometheus families."""
        from k8s_llm_rca_tpu.cluster import (
            Autoscaler, ClusterRouter, HealthPolicy, HealthWatchdog,
            Replica, ReplicaSupervisor,
        )
        from k8s_llm_rca_tpu.serve.backend import EchoBackend
        from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

        tok = get_tokenizer()
        mk = lambda i: Replica(i, EchoBackend(tok),         # noqa: E731
                               rebuild=lambda: EchoBackend(tok))
        clock = VirtualClock()
        tr = Tracer(clock=clock)
        with obs_trace.tracing(tr):
            router = ClusterRouter([mk(0), mk(1)])
            router.attach_health(
                HealthWatchdog(HealthPolicy(miss_budget=1,
                                            hung_tick_threshold=2),
                               clock=clock),
                ReplicaSupervisor())
            scaler = Autoscaler(router, reserve=[mk(2)], clock=clock)
            scaler.scale_up()
            clock.sleep(0.001)
            scaler.scale_down()
        doc = chrome_trace(tr)
        validate_chrome_trace(doc)
        tracks = [e for e in doc["traceEvents"]
                  if e["ph"] == "C" and e["name"] == "cluster.scale_events"]
        # running counts per kind, one sample per action
        assert [t["args"] for t in tracks] == [{"up": 1},
                                               {"down": 1, "up": 1}]
        fleet = [e for e in doc["traceEvents"]
                 if e["ph"] == "C" and e["name"] == "cluster.fleet_size"]
        assert [f["args"]["alive"] for f in fleet] == [3, 2]


# ---------------------------------------------------------------------------
# site registry self-check (satellite 5): instrumentation cannot rot
# ---------------------------------------------------------------------------


class TestSiteCoverage:
    def test_every_registered_site_is_emitted(self, small_engine, tmp_path):
        """Drive each instrumented layer under a tracer and assert the
        SITES registry is fully covered — a renamed or deleted call site
        fails HERE, not silently on a dashboard."""
        from k8s_llm_rca_tpu.faults.policy import (
            CircuitOpen, ResiliencePolicy, RetriesExhausted, RetryPolicy,
        )
        from k8s_llm_rca_tpu.faults.soak import run_chaos_soak
        from k8s_llm_rca_tpu.serve.api import AssistantService, RunStatus
        from k8s_llm_rca_tpu.serve.backend import EngineBackend, GenOptions

        engine, tok = small_engine
        tracers = []

        # (1) serve + backend + engine + durability sites: one journaled
        # run through the assistants API on the real engine backend, then
        # a journal replay (serve/recover.py)
        from k8s_llm_rca_tpu.serve.journal import RunJournal
        from k8s_llm_rca_tpu.serve.recover import recover_service

        wal = str(tmp_path / "serve.wal")
        tr_engine = Tracer(clock=VirtualClock())
        tracers.append(tr_engine)
        with obs_trace.tracing(tr_engine):
            service = AssistantService(EngineBackend(engine),
                                       journal=RunJournal(wal))
            a = service.create_assistant("inst", "cover", gen=GenOptions(
                max_new_tokens=4))
            t = service.create_thread()
            service.add_message(t.id, "node notready")
            run = service.create_run(t.id, a.id)
            assert service.wait_run(run.id).status == RunStatus.COMPLETED
            service._journal.close()
            recovered, _ = recover_service(wal, EngineBackend(engine))
            assert recovered.runs[run.id].status == RunStatus.COMPLETED

        # (2) rca + graph sites: one clean oracle soak incident
        tr_soak = Tracer()
        tracers.append(tr_soak)
        run_chaos_soak(seed=0, n_incidents=1, backend="oracle",
                       plan_spec={}, tracer=tr_soak)

        # (3) resilience sites: retry -> breaker open -> probe close ->
        # ladder rung drop, on a virtual clock
        clock = VirtualClock()
        policy = ResiliencePolicy(
            retry=RetryPolicy(max_attempts=2, base_delay_s=0.01,
                              clock=clock),
            failure_threshold=1, reset_timeout_s=0.05)
        tr_pol = Tracer(clock=clock)
        tracers.append(tr_pol)
        with obs_trace.tracing(tr_pol):
            with pytest.raises((RetriesExhausted, CircuitOpen)):
                policy.call("dep", lambda: (_ for _ in ()).throw(
                    RuntimeError("boom")))
            clock.sleep(0.1)
            assert policy.call("dep", lambda: "ok") == "ok"
            assert policy.ladder("stage", [
                ("full", lambda: (_ for _ in ()).throw(RuntimeError("no"))),
                ("fallback", lambda: 42),
            ]) == 42

        # (4) cluster sites: route one run through a 2-replica echo
        # cluster, then fail a replica over (cluster/router.py)
        from k8s_llm_rca_tpu.cluster import ClusterRouter, Replica
        from k8s_llm_rca_tpu.serve.backend import EchoBackend

        tr_cluster = Tracer(clock=VirtualClock())
        tracers.append(tr_cluster)
        with obs_trace.tracing(tr_cluster):
            router = ClusterRouter([
                Replica(0, EchoBackend(tok, delay_pumps=10 ** 9)),
                Replica(1, EchoBackend(tok))])
            h = router.start("node notready", GenOptions(session="t"))
            router.fail_replica(router._handle_map[h][0])
            assert h in router.pump()

        # (5) overload sites: preempt a victim on a spill-enabled engine
        # so engine.spill (d2h) and engine.restore (h2d) both fire
        tr_spill = Tracer(clock=VirtualClock())
        tracers.append(tr_spill)
        spill_eng = make_engine(
            TINY.replace(max_seq_len=64),
            EngineConfig(max_batch=2, max_seq_len=64, paged=True,
                         page_size=8, num_pages=24,
                         prefill_buckets=(16, 32), max_new_tokens=8,
                         temperature=0.0, decode_chunk=1,
                         prefix_cache=False, max_spilled_pages=24),
            engine.params, tok, use_kernel=False)
        with obs_trace.tracing(tr_spill):
            spill_eng.submit(tok.encode("node notready"))
            spill_eng.step()
            spill_eng.step()
            assert spill_eng._preempt_victim()
            while spill_eng.has_work:
                spill_eng.step()
        assert {"engine.spill", "engine.restore"} \
            <= tr_spill.emitted_names()

        # (6) self-heal sites: wedge a replica on a watchdog-armed echo
        # cluster — SUSPECT/DEAD verdicts, poison-run quarantine (K=1),
        # supervisor restart and the MTTD/MTTR spans all fire
        # (cluster/health.py)
        from k8s_llm_rca_tpu.cluster import (
            HealthPolicy, HealthWatchdog, ReplicaSupervisor,
        )

        tr_heal = Tracer(clock=VirtualClock())
        tracers.append(tr_heal)
        with obs_trace.tracing(tr_heal):
            heal_router = ClusterRouter(
                [Replica(0, EchoBackend(tok, delay_pumps=10 ** 9),
                         rebuild=lambda: EchoBackend(tok)),
                 Replica(1, EchoBackend(tok, delay_pumps=10 ** 9),
                         rebuild=lambda: EchoBackend(tok))],
                quarantine_after=1)
            heal_router.attach_health(
                HealthWatchdog(HealthPolicy(miss_budget=1,
                                            hung_tick_threshold=2),
                               clock=VirtualClock()),
                ReplicaSupervisor())
            h_heal = heal_router.start("node notready",
                                       GenOptions(session="s"))
            heal_router.replicas[heal_router._handle_map[h_heal][0]].wedge()
            heal_res = {}
            for _ in range(6):
                heal_res.update(heal_router.pump())
            assert "quarantined" in heal_res[h_heal].error
        assert {"cluster.health", "cluster.restart", "cluster.quarantine",
                "cluster.mttd", "cluster.mttr"} <= tr_heal.emitted_names()

        # (7) tiered prefix-cache sites: run a prefix-hitting prompt,
        # demote every resident page into the host store (engine
        # .prefix_demote, d2h), then re-run so tier-aware match promotes
        # them back (engine.prefix_promote, h2d)
        tr_tier = Tracer(clock=VirtualClock())
        tracers.append(tr_tier)
        tier_eng = make_engine(
            TINY.replace(max_seq_len=64),
            EngineConfig(max_batch=2, max_seq_len=64, paged=True,
                         page_size=8, num_pages=24,
                         prefill_buckets=(16, 32), max_new_tokens=4,
                         temperature=0.0, prefix_cache=True,
                         prefix_host_pages=24),
            engine.params, tok, use_kernel=False)
        with obs_trace.tracing(tr_tier):
            tier_eng.submit(tok.encode("node notready on node-3"))
            while tier_eng.has_work:
                tier_eng.step()
            assert tier_eng.prefix_cache.evict(10 ** 6) > 0
            tier_eng.submit(tok.encode("node notready on node-3"))
            while tier_eng.has_work:
                tier_eng.step()
        assert {"engine.prefix_demote", "engine.prefix_promote"} \
            <= tr_tier.emitted_names()
        tier_c = tier_eng._counts or {}
        assert tier_c.get("engine.prefix_demotions", 0) > 0
        assert tier_c.get("engine.prefix_hits_l1", 0) > 0

        # (8) pipelined-sweep sites: a 2-in-flight oracle sweep parks
        # machines on the shared pump (rca.stage.queue_wait spans from
        # rca/scheduler.py), and a pump with a live-but-orphaned handle
        # on a drained engine counts an idle tick (serve/backend.py)
        from k8s_llm_rca_tpu.faults.soak import run_pipelined_sweep

        tr_sweep = Tracer()
        tracers.append(tr_sweep)
        run_pipelined_sweep(n_incidents=2, backend="oracle",
                            concurrency=2, tracer=tr_sweep)
        assert "rca.stage.queue_wait" in tr_sweep.emitted_names()

        tr_idle = Tracer(clock=VirtualClock())
        tracers.append(tr_idle)
        with obs_trace.tracing(tr_idle):
            idle_backend = EngineBackend(engine)
            idle_backend.start("node notready", GenOptions(max_new_tokens=2))
            while engine.has_work:     # drain around the backend: the
                engine.step()          # handle stays live, nothing decodable
            idle_backend.pump()
        assert "engine.idle_ticks" in tr_idle.emitted_names()
        assert (engine._counts or {}).get("engine.idle_ticks", 0) > 0

        # (9) out-of-process sites: spawn ONE real oracle worker (own
        # interpreter, ~0.5 s), run a start/pump round-trip over the
        # framed pipe, and close it — spawn span, rpc spans and the exit
        # event all fire (cluster/proc.py)
        from k8s_llm_rca_tpu.cluster.proc import build_proc_replicas

        tr_proc = Tracer(clock=VirtualClock())
        tracers.append(tr_proc)
        with obs_trace.tracing(tr_proc):
            (proc_replica,) = build_proc_replicas(1, kind="oracle")
            try:
                hp = proc_replica.backend.start("node notready",
                                                GenOptions())
                for _ in range(20):
                    if hp in proc_replica.backend.pump():
                        break
                assert not proc_replica.backend.busy(hp)
            finally:
                proc_replica.close()
        assert {"cluster.proc.spawn", "cluster.proc.rpc",
                "cluster.proc.exit"} <= tr_proc.emitted_names()

        # (10) cross-host link sites: sever ONE socket worker's link
        # (process stays alive) and relink it under a fresh nonce — the
        # link-evidence event and the relink span both fire
        # (cluster/proc.py: link death =/= process death)
        from k8s_llm_rca_tpu.cluster.wire import WireError

        tr_net = Tracer(clock=VirtualClock())
        tracers.append(tr_net)
        with obs_trace.tracing(tr_net):
            (net_replica,) = build_proc_replicas(1, kind="oracle",
                                                 transport="socket")
            try:
                net_replica.partition_link()
                with pytest.raises(WireError):
                    net_replica.backend._rpc("ping", probe=0)
                assert net_replica.backend.relink()
            finally:
                net_replica.close()
        assert {"cluster.net.partition", "cluster.net.relink"} \
            <= tr_net.emitted_names()

        # (11) disaggregated-tier sites: one run through an in-process
        # echo TierRouter — admitted on the prefill tier, moved to the
        # decode tier by the EXPORT -> ADOPT -> RELEASE handoff
        # (cluster/disagg.py), which emits the cluster.handoff event
        from k8s_llm_rca_tpu.cluster import TierRouter

        tr_disagg = Tracer(clock=VirtualClock())
        tracers.append(tr_disagg)
        with obs_trace.tracing(tr_disagg):
            disagg_router = TierRouter(
                [Replica(0, EchoBackend(tok, delay_pumps=2))],
                [Replica(1, EchoBackend(tok, delay_pumps=2))])
            h_d = disagg_router.start("node notready", GenOptions())
            disagg_out = {}
            for _ in range(8):
                disagg_out.update(disagg_router.pump())
            assert disagg_out[h_d].error is None
            assert disagg_router.handoffs == 1
        assert "cluster.handoff" in tr_disagg.emitted_names()

        # (12) elastic-fleet sites: a scale-up spawn through the
        # supervisor rebuild-recipe path and a drain-down retirement
        # both emit the cluster.scale event (cluster/autoscale.py)
        from k8s_llm_rca_tpu.cluster import Autoscaler

        tr_scale = Tracer(clock=VirtualClock())
        tracers.append(tr_scale)
        with obs_trace.tracing(tr_scale):
            scale_router = ClusterRouter(
                [Replica(0, EchoBackend(tok),
                         rebuild=lambda: EchoBackend(tok)),
                 Replica(1, EchoBackend(tok),
                         rebuild=lambda: EchoBackend(tok))])
            scale_router.attach_health(
                HealthWatchdog(HealthPolicy(miss_budget=1,
                                            hung_tick_threshold=2),
                               clock=VirtualClock()),
                ReplicaSupervisor())
            scaler = Autoscaler(
                scale_router,
                reserve=[Replica(2, EchoBackend(tok),
                                 rebuild=lambda: EchoBackend(tok))])
            up = scaler.scale_up()
            down = scaler.scale_down()
            assert up["kind"] == "up" and down["kind"] == "down"
        assert "cluster.scale" in tr_scale.emitted_names()

        # (13) fleet-telemetry + critical-path sites: ONE worker spawned
        # with the flight recorder on — its cluster.proc.serve spans
        # ship back piggybacked on reply frames (cluster.telemetry.ship)
        # and close() flushes the ring (cluster.telemetry.drain); the
        # handoff PHASE spans (cluster.handoff.export/adopt/release,
        # disagg._attempt_handoff) already fired in segment (11).  Then
        # the critical-path pass re-emits its cp.* segment vocabulary
        # over the recorded serve.run spans (obs/critical_path.py)
        from k8s_llm_rca_tpu.obs import critical_path

        tr_fleet = Tracer(clock=VirtualClock())
        tracers.append(tr_fleet)
        with obs_trace.tracing(tr_fleet):
            (tel_replica,) = build_proc_replicas(1, kind="oracle",
                                                 trace=True)
            try:
                ht = tel_replica.backend.start("node notready",
                                               GenOptions())
                for _ in range(20):
                    if ht in tel_replica.backend.pump():
                        break
            finally:
                tel_replica.close()
            tr_fleet.add_span("serve.run", 0.0, tr_fleet.now(),
                              cat="serve", args={"run": "cover-cp",
                                                 "status": "completed"})
            assert critical_path(tr_fleet, emit=True)
        assert {"cluster.proc.serve", "cluster.telemetry.ship",
                "cluster.telemetry.drain"} <= tr_fleet.emitted_names()

        # (14) cache-fabric sites: spawn ONE real store server (own
        # interpreter, ~0.5 s), round-trip a page record through the
        # RemoteStore client — the serve (spawn) event and the
        # put/get success events all fire (cluster/store.py; failed
        # ops emit nothing by the cold-miss contract)
        import numpy as np

        from k8s_llm_rca_tpu.cluster.store import RemoteStore, StoreServer

        tr_store = Tracer(clock=VirtualClock())
        tracers.append(tr_store)
        with obs_trace.tracing(tr_store):
            store_server = StoreServer(host_pages=4, transport="pipe")
            try:
                remote_store = RemoteStore(server=store_server)
                rec = {"n_pages": 1,
                       "k": np.zeros((1, 1, 2, 4), np.float32),
                       "v": np.zeros((1, 1, 2, 4), np.float32)}
                remote_store.put(b"\x01" * 20, rec)
                assert remote_store.get(b"\x01" * 20) is not None
            finally:
                store_server.close()
        assert {"cluster.store.serve", "cluster.store.put",
                "cluster.store.get"} <= tr_store.emitted_names()

        missing = coverage_missing(*tracers)
        assert not missing, f"registered sites never emitted: {missing}"
        # and the registry is the full emitted vocabulary for our names:
        # anything we emit under a known prefix must be registered
        prefixes = ("engine.", "serve.", "backend.", "graph.", "rca.",
                    "resilience.", "cluster.", "cp.")
        emitted = set()
        for tr in tracers:
            emitted |= tr.emitted_names()
        unregistered = {n for n in emitted
                        if n.startswith(prefixes) and n not in SITES}
        assert not unregistered, \
            f"emitted sites missing from the registry: {unregistered}"
