"""Stage-3-isolated harness (the reference test_check_state.py equivalent):
drive the auditor with pinned entity ids/timestamps against the canned
stategraph, covering both strict and loose temporal queries and the
legacy single-query audit entry point."""

import pytest

from k8s_llm_rca_tpu.graph import InMemoryGraphExecutor
from k8s_llm_rca_tpu.graph.fixtures import (
    TS_EVENT, TS_STATE_MAX, TS_STATE_MIN, build_stategraph,
)
from k8s_llm_rca_tpu.rca import auditor
from k8s_llm_rca_tpu.rca.oracle import OracleBackend
from k8s_llm_rca_tpu.serve.api import AssistantService
from k8s_llm_rca_tpu.utils import get_tokenizer


@pytest.fixture(scope="module")
def state():
    return InMemoryGraphExecutor(build_stategraph())


@pytest.fixture()
def analyzer():
    service = AssistantService(OracleBackend(get_tokenizer()))
    return auditor.setup_state_semantic_analyzer(service)


def test_strict_states_present(state, analyzer):
    """ResourceQuota rq-0001 has a STATE covering the event timestamp
    (the reference's pinned ExceedQuota case shape)."""
    q = auditor.find_strict_states("ResourceQuota", "rq-0001", TS_EVENT)
    clues = auditor.check_states_existence_and_semantic(
        state, q, analyzer, "exceeded quota: compute-resources-team1")
    assert len(clues) == 1
    assert clues[0].startswith("ResourceQuota(rq-0001):")


def test_strict_states_absent(state, analyzer):
    q = auditor.find_strict_states("Secret", "sec-0001", TS_EVENT)
    clues = auditor.check_states_existence_and_semantic(
        state, q, analyzer, 'secret "es-account-token" not found')
    assert clues == ["There is not a STATE node corresponds to the Entity node"]


def test_loose_states_interval_overlap(state, analyzer):
    """Loose query: [E.tmin, E.tmax) must overlap [S.tmin, S.tmax)."""
    # window overlapping the state interval -> hit
    q = auditor.find_loose_states("Pod", "pod-0001",
                                  TS_EVENT, "2020-12-11 08:00:00.000")
    assert len(state.run_query(q)) == 1
    # window entirely after the state interval -> miss
    q2 = auditor.find_loose_states("Pod", "pod-0001",
                                   TS_STATE_MAX, "2020-12-11 09:00:00.000")
    assert state.run_query(q2) == []
    # window entirely before -> miss (r1.tmax > tmin fails)
    q3 = auditor.find_loose_states("Pod", "pod-0001",
                                   "2020-12-10 00:00:00.000",
                                   "2020-12-10 01:00:00.000")
    # tmin <= tmax' passes but tmax > tmin' comparison: state tmax (07:00)
    # > 2020-12-10 00:00 -> overlap rule admits it only because the loose
    # query checks r1.tmin <= query_tmax; with query_tmax before state tmin
    # the first predicate fails
    assert state.run_query(q3) == []


def test_adhoc_name_for_external_entity(state):
    assert auditor.ad_hoc_find_entity_name(
        "nfs", "nfs-0001", state) == "172.16.112.63:/mnt/k8s_nfs_pv/redis-pv"
    assert auditor.ad_hoc_find_entity_name(
        "Secret", "sec-0001", state) == "es-account-token"
    # unknown id falls back to the id itself
    assert auditor.ad_hoc_find_entity_name("Pod", "nope", state) == "nope"


def test_concurrent_audits_match_serial():
    """Fan-out/barrier audits must produce the same clues and report as
    the reference-serial order (oracle backend is deterministic)."""
    from k8s_llm_rca_tpu.config import RCAConfig
    from k8s_llm_rca_tpu.graph import InMemoryGraphExecutor
    from k8s_llm_rca_tpu.graph.fixtures import (
        INCIDENTS, build_metagraph, build_stategraph,
    )
    from k8s_llm_rca_tpu.rca import RCAPipeline
    from k8s_llm_rca_tpu.rca.oracle import OracleBackend
    from k8s_llm_rca_tpu.serve.api import AssistantService
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    def run(concurrent):
        pipeline = RCAPipeline(
            AssistantService(OracleBackend(get_tokenizer())),
            InMemoryGraphExecutor(build_metagraph()),
            InMemoryGraphExecutor(build_stategraph()),
            RCAConfig(concurrent_audits=concurrent))
        res = pipeline.analyze_incident(INCIDENTS[0].message)
        return [sp["clue"] for a in res["analysis"]
                for sp in a["statepath"]]

    assert run(True) == run(False)
