"""Partition-rule sharding layer tests (runtime/rules.py; ROADMAP item 1).

- **rule matcher units**: first-match-wins precedence, scalar skip, the
  loud no-match ValueError naming the param, None passthrough, and
  TWO-WAY coverage of every per-model table (every param matched, every
  rule used) under both the TP and the FSDP layouts — provable on
  shape-only templates, no devices touched;
- **layout pre-flight** (``validate_layout``): undefined axes,
  non-default mappings onto size-1 axes, and overlapping tier submeshes
  are named ValueErrors at build time;
- **page-record conversion** (``utils.pages.convert_page_record``): the
  deterministic page-size re-chunk the tier handoff rides, plus its
  loud refusals;
- **exact greedy parity** (slow, virtual 8-device CPU mesh): fsdp and
  fsdp×tp sharded engines (contiguous AND paged) decode byte-identically
  to the plain single-device engine, a 1P+2D TierRouter fleet with
  DIFFERING per-tier KV page sizes settles byte-identically, and a
  mid-decode export adopts across the page-size boundary with the
  ``engine.handoff_kv_relayout`` counter asserted;
- **loud exclusions**: fsdp×CP/EP/PP/SP refusals, carve divisibility,
  proc-spec layout validation, and TierRouter kv-geometry refusals.
"""

import dataclasses

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from k8s_llm_rca_tpu.config import TINY, TINY_MOE, EncoderConfig, \
    EngineConfig, MeshConfig
from k8s_llm_rca_tpu.runtime.mesh import build_mesh
from k8s_llm_rca_tpu.runtime.rules import (
    FSDP_LAYOUT, TP_LAYOUT, SpecLayout, encoder_param_template,
    encoder_rules, llama_param_template, llama_rules, match_partition_rules,
    unused_rules, validate_layout,
)
from k8s_llm_rca_tpu.serve.backend import GenOptions
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

pytestmark = pytest.mark.sharding

_ENC = EncoderConfig(vocab_size=64, hidden_size=32, n_layers=2, n_heads=4,
                     intermediate_size=64, max_seq_len=32)


def _arr(*shape):
    return jax.ShapeDtypeStruct(shape, np.float32)


# ---------------------------------------------------------------------------
# rule matcher units
# ---------------------------------------------------------------------------


class TestMatcher:
    def test_first_match_wins(self):
        # "w" matches wq before the more specific rule can: precedence
        # is table order, NOT specificity
        rules = [("w", P("model", None)), (r"wq$", P(None, "model"))]
        specs = match_partition_rules(rules, {"wq": _arr(4, 4)})
        assert specs["wq"] == P("model", None)

    def test_scalars_replicate_without_consulting_rules(self):
        # a table with NO rules still matches a tree of scalars/size-1
        specs = match_partition_rules([], {"step": _arr(), "one": _arr(1)})
        assert specs == {"step": P(), "one": P()}

    def test_no_match_is_a_loud_valueerror_naming_the_param(self):
        with pytest.raises(ValueError) as exc:
            match_partition_rules([(r"wq$", P(None))],
                                  {"layers": [{"mystery": _arr(4, 4)}]},
                                  table="llama")
        msg = str(exc.value)
        assert "layers/0/mystery" in msg
        assert "llama" in msg
        assert "never silently replicated" in msg

    def test_none_leaves_pass_through(self):
        specs = match_partition_rules([], {"opt": None})
        assert specs == {"opt": P()}

    @pytest.mark.parametrize("layout", [TP_LAYOUT, FSDP_LAYOUT])
    @pytest.mark.parametrize("name,rules_fn,tmpl_fn,cfg", [
        ("llama-dense", llama_rules, llama_param_template, TINY),
        ("llama-moe", llama_rules, llama_param_template, TINY_MOE),
        ("encoder", encoder_rules, encoder_param_template, _ENC),
    ])
    def test_two_way_coverage(self, layout, name, rules_fn, tmpl_fn, cfg):
        """Every param matched (no ValueError) AND every rule used (no
        dead pattern) for every per-model table under both layouts."""
        rules = rules_fn(cfg, layout)
        tmpl = tmpl_fn(cfg)
        match_partition_rules(rules, tmpl, table=name)   # must not raise
        assert unused_rules(rules, tmpl) == []

    def test_llama_specs_reproduce_historical_layout(self):
        from k8s_llm_rca_tpu.runtime.sharding import llama_param_specs

        specs = llama_param_specs(TINY)
        assert specs["layers"][0]["wq"] == P(None, "model")
        assert specs["layers"][0]["wo"] == P("model", None)
        assert specs["layers"][0]["w_down"] == P("model", None)
        assert specs["embedding"] == P(None, "model")
        assert specs["final_norm"] == P(None)
        fs = llama_param_specs(TINY, layout=FSDP_LAYOUT)
        assert fs["layers"][0]["wq"] == P("fsdp", "model")
        assert fs["layers"][0]["wo"] == P("model", "fsdp")
        assert fs["embedding"] == P("fsdp", "model")
        assert fs["final_norm"] == P(None)
        moe = llama_param_specs(TINY_MOE, layout=FSDP_LAYOUT)
        assert moe["layers"][0]["w_gate"] == P("expert", "fsdp", "model")
        assert moe["layers"][0]["w_down"] == P("expert", "model", "fsdp")
        assert moe["layers"][0]["router"] == P(None, None)

    def test_spec_layout_dict_round_trip(self):
        d = FSDP_LAYOUT.to_dict()
        assert SpecLayout.from_dict(d) == FSDP_LAYOUT
        with pytest.raises(ValueError, match="unknown logical axes"):
            SpecLayout.from_dict({"fsdp": "fsdp", "tensor": "model"})


# ---------------------------------------------------------------------------
# layout pre-flight
# ---------------------------------------------------------------------------


class TestValidateLayout:
    def test_undefined_axis_is_named(self, cpu_devices):
        mesh = build_mesh(MeshConfig(model=2), devices=cpu_devices[:2])
        with pytest.raises(ValueError, match="'nope'.*undefined"):
            validate_layout(SpecLayout(tp="nope"), mesh)

    def test_nondefault_mapping_onto_size1_axis_is_named(self, cpu_devices):
        mesh = build_mesh(MeshConfig(model=2), devices=cpu_devices[:2])
        with pytest.raises(ValueError, match="fsdp.*size 1"):
            validate_layout(FSDP_LAYOUT, mesh)

    def test_default_mapping_tolerates_size1_axes(self, cpu_devices):
        # the pervasive single-chip degenerate case: tp over model=1
        mesh = build_mesh(MeshConfig(), devices=cpu_devices[:1])
        assert validate_layout(TP_LAYOUT, mesh) is TP_LAYOUT

    def test_none_layout_defaults_to_tp(self, cpu_devices):
        mesh = build_mesh(MeshConfig(model=2), devices=cpu_devices[:2])
        assert validate_layout(None, mesh) == TP_LAYOUT

    def test_overlapping_peer_meshes_are_refused(self, cpu_devices):
        m1 = build_mesh(MeshConfig(model=2), devices=cpu_devices[:2])
        m2 = build_mesh(MeshConfig(model=2), devices=cpu_devices[1:3])
        with pytest.raises(ValueError, match="overlap"):
            validate_layout(TP_LAYOUT, m1, peers=[m2])
        disjoint = build_mesh(MeshConfig(model=2), devices=cpu_devices[2:4])
        validate_layout(TP_LAYOUT, m1, peers=[disjoint])


# ---------------------------------------------------------------------------
# page-record conversion (the handoff layout bridge)
# ---------------------------------------------------------------------------


class TestConvertPageRecord:
    def _rec(self, L=2, n=3, ps=4, kv=6, scales=False, seed=0):
        rng = np.random.default_rng(seed)
        rec = {"n_pages": n,
               "k": rng.standard_normal((L, n, ps, kv)).astype(np.float32),
               "v": rng.standard_normal((L, n, ps, kv)).astype(np.float32)}
        if scales:
            rec["k_scale"] = rng.standard_normal((L, n, ps)).astype(
                np.float32)
            rec["v_scale"] = rng.standard_normal((L, n, ps)).astype(
                np.float32)
        return rec

    def test_rechunk_preserves_valid_tokens_and_zero_pads(self):
        from k8s_llm_rca_tpu.utils.pages import convert_page_record

        rec = self._rec()
        out = convert_page_record(rec, 10, 8)
        assert out["n_pages"] == 2
        src = rec["k"].reshape(2, 12, 6)[:, :10]
        dst = out["k"].reshape(2, 16, 6)
        assert np.array_equal(dst[:, :10], src)
        assert not dst[:, 10:].any()          # deterministic zero tail
        back = convert_page_record(out, 10, 4)
        assert back["n_pages"] == 3
        assert np.array_equal(back["k"].reshape(2, 12, 6)[:, :10], src)

    def test_scale_fields_rechunk_alongside(self):
        from k8s_llm_rca_tpu.utils.pages import convert_page_record

        rec = self._rec(scales=True)
        out = convert_page_record(rec, 10, 8)
        assert out["k_scale"].shape == (2, 2, 8)
        assert np.array_equal(out["k_scale"].reshape(2, 16)[:, :10],
                              rec["k_scale"].reshape(2, 12)[:, :10])

    def test_same_page_size_is_identity(self):
        from k8s_llm_rca_tpu.utils.pages import convert_page_record

        rec = self._rec()
        assert convert_page_record(rec, 10, 4) is rec

    def test_refusals_are_loud(self):
        from k8s_llm_rca_tpu.utils.pages import convert_page_record

        rec = self._rec()
        with pytest.raises(ValueError, match="length=0"):
            convert_page_record(rec, 0, 8)
        with pytest.raises(ValueError, match="does not fit"):
            convert_page_record(rec, 13, 8)
        with pytest.raises(ValueError, match="dst_page_size"):
            convert_page_record(rec, 10, 0)
        torn = dict(rec, n_pages=5)
        with pytest.raises(ValueError, match="claims 5 pages"):
            convert_page_record(torn, 10, 8)


# ---------------------------------------------------------------------------
# loud exclusions: fsdp mesh validation, carve, proc specs, tier geometry
# ---------------------------------------------------------------------------


class TestFsdpExclusions:
    def _mesh(self, cpu_devices, **axes):
        return build_mesh(MeshConfig(**axes),
                          devices=cpu_devices[:MeshConfig(**axes).n_devices])

    def test_fsdp_refuses_cp_ep_pp_and_sp(self, cpu_devices):
        from k8s_llm_rca_tpu.engine.engine import validate_fsdp_mesh

        ecfg = EngineConfig(max_batch=2, max_seq_len=64)
        mesh = self._mesh(cpu_devices, fsdp=2)
        other = self._mesh(cpu_devices, model=2)
        for kw in ("cp_mesh", "ep_mesh", "pp_mesh"):
            with pytest.raises(ValueError, match="unsupported until"):
                validate_fsdp_mesh(mesh, TINY, ecfg, **{kw: other})
        with pytest.raises(ValueError, match="SP is unsupported"):
            validate_fsdp_mesh(mesh, TINY, ecfg, sp=True)

    def test_fsdp_and_tp_must_share_one_mesh(self, cpu_devices):
        from k8s_llm_rca_tpu.engine.engine import validate_fsdp_mesh

        ecfg = EngineConfig(max_batch=2, max_seq_len=64)
        mesh = self._mesh(cpu_devices, fsdp=2)
        other = self._mesh(cpu_devices, model=2)
        with pytest.raises(ValueError, match="SAME composed mesh"):
            validate_fsdp_mesh(mesh, TINY, ecfg, tp_mesh=other)

    def test_fsdp_divisibility_is_checked(self, cpu_devices):
        from k8s_llm_rca_tpu.engine.engine import validate_fsdp_mesh

        ecfg = EngineConfig(max_batch=2, max_seq_len=64)
        mesh = self._mesh(cpu_devices, fsdp=3)
        cfg = TINY.replace(vocab_size=512)    # hidden 128 % 3 != 0
        with pytest.raises(ValueError, match="hidden_size"):
            validate_fsdp_mesh(mesh, cfg, ecfg)

    def test_carve_refuses_indivisible_fsdp(self, cpu_devices):
        from k8s_llm_rca_tpu.cluster.submesh import carve_replica_meshes

        with pytest.raises(ValueError, match="fsdp axis of 3"):
            carve_replica_meshes(2, devices=cpu_devices[:8], fsdp=3)
        meshes = carve_replica_meshes(2, devices=cpu_devices[:8], fsdp=2)
        assert all(m.shape["fsdp"] == 2 for m in meshes)

    def test_proc_spec_layout_validation_is_parent_side(self):
        from k8s_llm_rca_tpu.cluster.proc import build_proc_replicas

        with pytest.raises(ValueError, match="kind='engine'"):
            build_proc_replicas(1, kind="oracle", layout=FSDP_LAYOUT)
        with pytest.raises(ValueError, match="data/fsdp/model axes only"):
            build_proc_replicas(1, kind="engine", mesh_shape={"seq": 2})
        with pytest.raises(ValueError, match="does not match"):
            build_proc_replicas(1, kind="engine", devices=4,
                                mesh_shape={"model": 2})
        with pytest.raises(ValueError, match="no fsdp axis"):
            build_proc_replicas(1, kind="engine", layout=FSDP_LAYOUT,
                                mesh_shape={"model": 2})
        with pytest.raises(ValueError, match="unknown logical axes"):
            build_proc_replicas(1, kind="engine", layout={"tensor": "model"})


class TestTierGeometry:
    def _replica(self, rid, kv_layout=None, layout=None, mesh=None):
        from k8s_llm_rca_tpu.cluster.replica import Replica
        from k8s_llm_rca_tpu.serve.backend import EchoBackend

        return Replica(rid, EchoBackend(get_tokenizer()), mesh=mesh,
                       layout=layout, kv_layout=kv_layout)

    def test_mismatched_kv_geometry_is_refused_at_construction(self):
        from k8s_llm_rca_tpu.cluster.disagg import TierRouter

        a = {"page_size": 16, "kv_dtype": None, "kv_dim": 64, "n_layers": 2}
        for field, val in (("kv_dtype", "int8"), ("kv_dim", 32),
                           ("n_layers", 4)):
            b = dict(a, **{field: val})
            with pytest.raises(ValueError, match=field):
                TierRouter([self._replica(0, kv_layout=a)],
                           [self._replica(1, kv_layout=b)])

    def test_differing_page_size_is_allowed(self):
        from k8s_llm_rca_tpu.cluster.disagg import TierRouter

        a = {"page_size": 16, "kv_dtype": None, "kv_dim": 64, "n_layers": 2}
        b = dict(a, page_size=32)
        TierRouter([self._replica(0, kv_layout=a)],
                   [self._replica(1, kv_layout=b)])

    def test_paged_vs_contiguous_mix_is_refused(self):
        from k8s_llm_rca_tpu.cluster.disagg import TierRouter

        a = {"page_size": 16, "kv_dtype": None, "kv_dim": 64, "n_layers": 2}
        b = dict(a, page_size=None)
        with pytest.raises(ValueError, match="same cache kind"):
            TierRouter([self._replica(0, kv_layout=a)],
                       [self._replica(1, kv_layout=b)])

    def test_scripted_replicas_skip_geometry_checks(self):
        from k8s_llm_rca_tpu.cluster.disagg import TierRouter

        TierRouter([self._replica(0)], [self._replica(1)])

    def test_overlapping_tier_submeshes_are_refused(self, cpu_devices):
        from k8s_llm_rca_tpu.cluster.disagg import TierRouter

        m1 = build_mesh(MeshConfig(model=2), devices=cpu_devices[:2])
        m2 = build_mesh(MeshConfig(model=2), devices=cpu_devices[1:3])
        with pytest.raises(ValueError, match="overlap"):
            TierRouter([self._replica(0, layout=TP_LAYOUT, mesh=m1)],
                       [self._replica(1, layout=TP_LAYOUT, mesh=m2)])
        m3 = build_mesh(MeshConfig(model=2), devices=cpu_devices[2:4])
        TierRouter([self._replica(0, layout=TP_LAYOUT, mesh=m1)],
                   [self._replica(1, layout=TP_LAYOUT, mesh=m3)])

    def test_late_admission_runs_the_same_checks(self):
        from k8s_llm_rca_tpu.cluster.disagg import TIER_DECODE, TierRouter

        a = {"page_size": 16, "kv_dtype": None, "kv_dim": 64, "n_layers": 2}
        router = TierRouter([self._replica(0, kv_layout=a)],
                            [self._replica(1, kv_layout=dict(a))])
        bad = self._replica(2, kv_layout=dict(a, kv_dim=32))
        with pytest.raises(ValueError, match="kv_dim"):
            router.add_replica(bad, tier=TIER_DECODE)
        router.add_replica(
            self._replica(3, kv_layout=dict(a, page_size=64)),
            tier=TIER_DECODE)


# ---------------------------------------------------------------------------
# exact greedy parity on the virtual 8-device CPU mesh (slow tier)
# ---------------------------------------------------------------------------


def _engine_kw(ecfg):
    # the kernel toggle exists only on the paged engine
    return {"use_kernel": False} if ecfg.paged else {}


def _plain_reference(cfg, ecfg, params, tok, prompt, opts):
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.serve.backend import EngineBackend

    ref = EngineBackend(make_engine(cfg, ecfg, params, tok,
                                    **_engine_kw(ecfg)))
    h = ref.start(prompt, opts)
    while True:
        res = ref.pump().get(h)
        if res is not None:
            assert res.error is None
            return res.text


@pytest.mark.slow
class TestFsdpGreedyParity:
    """Byte-identical greedy decode for every fsdp composition: the
    params are rule-sharded and COMMITTED before the engine builds, so
    GSPMD inserts the all-gathers (committed-input propagation) whether
    or not the engine also receives the mesh for cache placement."""

    @pytest.mark.parametrize("paged", [False, True])
    @pytest.mark.parametrize("axes,pass_mesh", [
        ({"fsdp": 4}, False),                 # fsdp-only, params-committed
        ({"fsdp": 4}, True),                  # fsdp-only + cache placement
        ({"fsdp": 2, "model": 2}, True),      # fsdp×tp on one mesh
    ])
    def test_fsdp_matches_plain_engine(self, cpu_devices, paged, axes,
                                       pass_mesh):
        from k8s_llm_rca_tpu.engine import make_engine
        from k8s_llm_rca_tpu.models import llama
        from k8s_llm_rca_tpu.runtime.sharding import (
            llama_param_specs, shard_pytree,
        )
        from k8s_llm_rca_tpu.serve.backend import EngineBackend

        cfg = TINY.replace(max_seq_len=64)
        knobs = dict(max_batch=2, max_seq_len=64, prefill_buckets=(32,),
                     max_new_tokens=8, temperature=0.0, prefix_cache=False)
        if paged:
            knobs.update(paged=True, page_size=8, num_pages=24)
        ecfg = EngineConfig(**knobs)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        prompt = "node notready on node-3"
        opts = GenOptions(max_new_tokens=8)
        want = _plain_reference(cfg, ecfg, params, tok, prompt, opts)

        mcfg = MeshConfig(**axes)
        mesh = build_mesh(mcfg, devices=cpu_devices[:mcfg.n_devices])
        layout = validate_layout(FSDP_LAYOUT, mesh)
        sharded = shard_pytree(params, llama_param_specs(cfg, layout),
                               mesh)
        kw = {}
        if pass_mesh:
            kw["fsdp_mesh"] = mesh
            if axes.get("model", 1) > 1:
                kw["tp_mesh"] = mesh
        kw.update(_engine_kw(ecfg))
        backend = EngineBackend(make_engine(cfg, ecfg, sharded, tok, **kw))
        h = backend.start(prompt, opts)
        while True:
            res = backend.pump().get(h)
            if res is not None:
                break
        assert res.error is None
        assert res.text == want               # byte-identical greedy

    def test_fsdp_cp_composition_is_refused_loudly(self, cpu_devices):
        from k8s_llm_rca_tpu.engine import make_engine
        from k8s_llm_rca_tpu.models import llama

        cfg = TINY.replace(max_seq_len=64)
        ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                            prefill_buckets=(32,), max_new_tokens=8,
                            temperature=0.0)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        mesh = build_mesh(MeshConfig(fsdp=2, seq=2),
                          devices=cpu_devices[:4])
        with pytest.raises(ValueError, match="fsdp×CP is unsupported"):
            make_engine(cfg, ecfg, params, tok, fsdp_mesh=mesh,
                        cp_mesh=mesh)


@pytest.mark.slow
@pytest.mark.disagg
class TestPerTierLayoutParity:
    def _fleet(self, cpu_devices, page_size_decode):
        from k8s_llm_rca_tpu.cluster.disagg import TierRouter
        from k8s_llm_rca_tpu.cluster.replica import build_replicas

        cfg = TINY.replace(max_seq_len=512)
        ecfg = EngineConfig(max_batch=2, max_seq_len=512,
                            prefill_buckets=(512,), max_new_tokens=16,
                            temperature=0.0, paged=True, page_size=16,
                            num_pages=96, prefix_cache=False)
        ecfg_d = dataclasses.replace(ecfg, page_size=page_size_decode,
                                     num_pages=96 * 16 // page_size_decode)
        # prefill TP-heavy (tp4), decode KV-wide (tp2 × 2 replicas) —
        # same checkpoint, same seed, different per-tier layouts
        pre = build_replicas(cfg, ecfg, 1, devices=cpu_devices[:4],
                             use_kernel=False)
        dec = build_replicas(cfg, ecfg_d, 2, devices=cpu_devices[4:8],
                             use_kernel=False)
        for i, r in enumerate(dec):
            r.replica_id = i + 1
            r.backend.engine.obs_replica = i + 1
        return cfg, ecfg, TierRouter(pre, dec)

    def test_1p2d_differing_kv_page_sizes_settle_byte_identically(
            self, cpu_devices):
        from k8s_llm_rca_tpu.models import llama

        cfg, ecfg, router = self._fleet(cpu_devices, page_size_decode=32)
        assert router.replicas[0].kv_layout["page_size"] == 16
        assert router.replicas[1].kv_layout["page_size"] == 32
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        prompt = "node notready on node-3"
        opts = GenOptions(max_new_tokens=8)
        want = _plain_reference(cfg, ecfg, params, tok, prompt, opts)
        h = router.start(prompt, opts)
        res = None
        for _ in range(300):
            res = router.pump().get(h)
            if res is not None:
                break
        assert res is not None and res.error is None
        assert res.text == want
        assert router.handoffs == 1

    def test_mid_decode_relayout_adopt_is_byte_identical(self):
        """The conversion path proper: export mid-decode from a
        page_size=8 engine, adopt on a page_size=4 engine — the record
        is re-chunked (relayout counter), never re-prefilled, and the
        finished text matches the uninterrupted run byte for byte."""
        from k8s_llm_rca_tpu.engine import make_engine
        from k8s_llm_rca_tpu.models import llama
        from k8s_llm_rca_tpu.serve.backend import EngineBackend

        cfg = TINY.replace(max_seq_len=64)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        knobs = dict(max_batch=2, max_seq_len=64, paged=True, page_size=8,
                     num_pages=24, prefill_buckets=(16, 32),
                     max_new_tokens=8, temperature=0.0, decode_chunk=1,
                     prefix_cache=False)
        eng_a = make_engine(cfg, EngineConfig(**knobs), params, tok,
                            use_kernel=False)
        eng_b = make_engine(cfg, EngineConfig(**dict(knobs, page_size=4,
                                                     num_pages=48)),
                            params, tok, use_kernel=False)
        prompt = "node notready on node-3"
        opts = GenOptions(max_new_tokens=8)
        backend_a = EngineBackend(eng_a)
        ref_h = backend_a.start(prompt, opts)
        ref = {}
        while ref_h not in ref:
            ref.update(backend_a.pump())
        assert ref[ref_h].error is None

        h = backend_a.start(prompt, opts)
        frame = None
        for _ in range(6):
            assert h not in backend_a.pump()
            frame = backend_a.export_run(h)
            if frame is not None:
                break
        assert frame is not None and frame["kv"] is not None
        backend_b = EngineBackend(eng_b)
        h2 = backend_b.adopt_run(frame, opts)
        counts = eng_b._counts or {}
        assert counts.get("engine.handoff_kv_adopted") == 1
        assert counts.get("engine.handoff_kv_relayout") == 1
        assert counts.get("engine.handoff_kv_rejected") is None
        out = {}
        for _ in range(64):
            out.update(backend_b.pump())
            if h2 in out:
                break
        assert out[h2].error is None
        assert out[h2].text == ref[ref_h].text

    def test_incompatible_kv_dtype_is_a_loud_adopt_error(self):
        from k8s_llm_rca_tpu.engine import make_engine
        from k8s_llm_rca_tpu.models import llama
        from k8s_llm_rca_tpu.serve.backend import EngineBackend

        cfg = TINY.replace(max_seq_len=64)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        knobs = dict(max_batch=2, max_seq_len=64, paged=True, page_size=8,
                     num_pages=24, prefill_buckets=(16, 32),
                     max_new_tokens=8, temperature=0.0, decode_chunk=1,
                     prefix_cache=False)
        eng_a = make_engine(cfg, EngineConfig(**knobs), params, tok,
                            use_kernel=False)
        eng_c = make_engine(cfg,
                            EngineConfig(**dict(knobs,
                                                kv_cache_dtype="int8")),
                            params, tok, use_kernel=False)
        backend_a = EngineBackend(eng_a)
        backend_c = EngineBackend(eng_c)
        opts = GenOptions(max_new_tokens=8)
        h = backend_a.start("node notready on node-3", opts)
        frame = None
        for _ in range(6):
            backend_a.pump()
            frame = backend_a.export_run(h)
            if frame is not None:
                break
        assert frame is not None and frame["kv"] is not None
        with pytest.raises(ValueError, match="misconfigured tier pair"):
            backend_c.adopt_run(frame, opts)
        assert not eng_c.has_work             # nothing half-adopted
