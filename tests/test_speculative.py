"""N-gram speculative decoding: drafts, multi-token verification, and the
engine-level exact-equivalence guarantee (speculation must never change
greedy output, only how many tokens a tick commits)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_llm_rca_tpu.config import TINY, EngineConfig
from k8s_llm_rca_tpu.engine.engine import InferenceEngine
from k8s_llm_rca_tpu.engine.speculative import ngram_draft
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.utils.logging import METRICS
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer


class TestNgramDraft:
    def test_finds_most_recent_continuation(self):
        #          0  1  2  3  4  5  6  7
        ctx = [5, 6, 7, 9, 5, 6, 8, 5, 6]
        # last 2-gram (5, 6) last occurred at 4..5, followed by 8, 5, 6
        assert ngram_draft(ctx, n=2, k=3) == [8, 5, 6]

    def test_no_match_returns_empty(self):
        assert ngram_draft([1, 2, 3, 4], n=2, k=4) == []

    def test_short_context(self):
        assert ngram_draft([1, 2], n=3, k=4) == []
        assert ngram_draft([], n=2, k=4) == []

    def test_continuation_clipped_to_k(self):
        ctx = [1, 2, 3, 4, 5, 6, 1, 2]
        assert ngram_draft(ctx, n=2, k=2) == [3, 4]


class TestDecodeMulti:
    def test_matches_sequential_decode_steps(self):
        cfg = TINY.replace(max_seq_len=64)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        prompt = list(range(5, 17))

        def prefilled_cache():
            cache = llama.init_cache(cfg, 2, 64)
            toks = jnp.zeros((1, 16), jnp.int32).at[0, :12].set(
                jnp.asarray(prompt))
            cache, logits = llama.prefill(cfg, params, cache, toks,
                                          jnp.int32(12), jnp.int32(0))
            return cache, int(jnp.argmax(logits[0]))

        # reference: 4 sequential decode steps
        cache, first = prefilled_cache()
        cur = jnp.asarray([first, 0], jnp.int32)
        lengths = jnp.asarray([12, 0], jnp.int32)
        seq_logits = []
        for _ in range(4):
            cache, lg = llama.decode_step(cfg, params, cache, cur, lengths)
            seq_logits.append(np.asarray(lg[0]))
            cur = cur.at[0].set(int(jnp.argmax(lg[0])))
            lengths = lengths + jnp.asarray([1, 0], jnp.int32)
        chain = [first] + [int(np.argmax(l)) for l in seq_logits[:-1]]

        # decode_multi over the same 4-token chain in ONE call
        cache2, _ = prefilled_cache()
        tokens = jnp.asarray([chain, [0, 0, 0, 0]], jnp.int32)
        _, logits = llama.decode_multi(cfg, params, cache2, tokens,
                                       jnp.asarray([12, 0], jnp.int32))
        for i in range(4):
            np.testing.assert_allclose(np.asarray(logits[0, i]),
                                       seq_logits[i], rtol=2e-4, atol=2e-4)


class TestSpeculativeEngine:
    def _engines(self, **kw):
        cfg = TINY.replace(max_seq_len=128)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        base = dict(max_batch=2, max_seq_len=128,
                    prefill_buckets=(32, 64, 128), max_new_tokens=24,
                    temperature=0.0)
        base.update(kw)
        plain = InferenceEngine(cfg, EngineConfig(**base), params, tok)
        spec = InferenceEngine(
            cfg, EngineConfig(speculative_k=4, **base), params, tok)
        return plain, spec, tok

    def test_exact_equivalence_with_plain_greedy(self):
        plain, spec, tok = self._engines()
        prompts = [tok.encode("the pod the pod the pod the", add_bos=True),
                   tok.encode("error: mount failed mount failed",
                              add_bos=True)]
        a = plain.generate(prompts, max_new_tokens=20)
        b = spec.generate(prompts, max_new_tokens=20)
        for ra, rb in zip(a, b):
            assert ra.token_ids == rb.token_ids
            assert ra.finish_reason == rb.finish_reason

    def test_accepts_drafts_on_repetitive_output(self):
        # random TINY weights degenerate into repeating tokens — ideal for
        # prompt lookup; assert the accept counter actually moves
        _, spec, tok = self._engines()
        before = METRICS.counters.get("engine.spec_accepted", 0)
        spec.generate([tok.encode("aaaa bbbb aaaa bbbb", add_bos=True)],
                      max_new_tokens=20)
        assert METRICS.counters.get("engine.spec_accepted", 0) > before

    def test_sampling_disables_speculation(self):
        _, spec, tok = self._engines(temperature=0.8)
        # must fall back to the regular tick (and still work)
        res = spec.generate([tok.encode("hello", add_bos=True)],
                            max_new_tokens=8)
        assert res[0].completion_tokens == 8

    def test_grammar_composes_with_speculation(self):
        from k8s_llm_rca_tpu.engine.constrain import make_grammar

        plain, spec, tok = self._engines()
        prompt = tok.encode("emit json", add_bos=True)

        def run(eng):
            g = make_grammar("json", eng.tokenizer, prefer_native=False)
            sid = eng.submit(prompt, max_new_tokens=24, grammar=g)
            return {r.seq_id: r for r in eng.run_to_completion()}[sid]

        ra, rb = run(plain), run(spec)
        assert ra.token_ids == rb.token_ids
        import json
        json.loads(rb.text)      # grammar guarantee survives speculation


class TestPagedSpeculative:
    def _paged(self, spec_k, **kw):
        from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine

        cfg = TINY.replace(max_seq_len=128)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        base = dict(max_batch=2, max_seq_len=128, page_size=16,
                    num_pages=64, prefill_buckets=(32, 64, 128),
                    max_new_tokens=24, temperature=0.0,
                    speculative_k=spec_k, prefix_cache=False)
        base.update(kw)
        return PagedInferenceEngine(cfg, EngineConfig(**base), params, tok,
                                    use_kernel=False), tok

    def test_paged_exact_equivalence_with_plain_greedy(self):
        plain, tok = self._paged(0)
        spec, _ = self._paged(4)
        prompts = [tok.encode("the pod the pod the pod the", add_bos=True),
                   tok.encode("mount failed mount failed mount",
                              add_bos=True)]
        a = plain.generate([list(p) for p in prompts], max_new_tokens=20)
        b = spec.generate([list(p) for p in prompts], max_new_tokens=20)
        for ra, rb in zip(a, b):
            assert ra.token_ids == rb.token_ids
            assert ra.finish_reason == rb.finish_reason
        spec.allocator.check()
        assert spec.allocator.n_free == plain.allocator.n_free

    def test_paged_spec_accepts_drafts(self):
        spec, tok = self._paged(4)
        before = METRICS.counters.get("engine.spec_accepted", 0)
        spec.generate([tok.encode("aaaa bbbb aaaa bbbb", add_bos=True)],
                      max_new_tokens=20)
        assert METRICS.counters.get("engine.spec_accepted", 0) > before

    def test_paged_spec_with_prefix_cache(self):
        spec, tok = self._paged(4, prefix_cache=True)
        prompt = tok.encode("incident pod crashloop in namespace prod "
                            "again and again and again", add_bos=True)
        r1 = spec.generate([list(prompt)], max_new_tokens=16)[0]
        r2 = spec.generate([list(prompt)], max_new_tokens=16)[0]
        assert r1.token_ids == r2.token_ids
        spec.allocator.check()


def test_feature_matrix_greedy_equivalence():
    """Crown invariant: greedy output is identical across EVERY engine
    feature combination — speculation x chunked scan x prefix cache x KV
    dtype, with a mixed workload of grammar-constrained and plain runs.
    Quantized KV legitimately shifts logits, so each KV dtype has its OWN
    baseline; within a dtype every feature combination must agree."""
    import json as jsonlib

    from k8s_llm_rca_tpu.config import EngineConfig
    from k8s_llm_rca_tpu.engine.constrain import make_grammar
    from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine

    cfg = TINY.replace(max_seq_len=128)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    plain_prompts = [tok.encode("the pod the pod the pod", add_bos=True),
                     tok.encode("mount failed mount failed", add_bos=True)]
    json_prompt = tok.encode("emit json", add_bos=True)

    def run(spec_k, chunk, prefix, kv=None):
        eng = PagedInferenceEngine(
            cfg, EngineConfig(max_batch=3, max_seq_len=128, page_size=16,
                              num_pages=96, prefill_buckets=(32, 64, 128),
                              max_new_tokens=18, temperature=0.0,
                              speculative_k=spec_k, decode_chunk=chunk,
                              prefix_cache=prefix, kv_cache_dtype=kv),
            params, tok, use_kernel=False)
        ids = [eng.submit(list(p), max_new_tokens=18) for p in plain_prompts]
        g = make_grammar("json", tok, prefer_native=False)
        ids.append(eng.submit(list(json_prompt), max_new_tokens=18,
                              grammar=g))
        res = {r.seq_id: r for r in eng.run_to_completion()}
        eng.allocator.check()
        out = [(res[i].token_ids, res[i].finish_reason) for i in ids]
        jsonlib.loads(res[ids[-1]].text)      # grammar guarantee holds
        return out

    for kv in (None, "int8", "int4"):
        baseline = run(0, 1, False, kv)
        for spec_k in (0, 4):
            for chunk in (1, 16):
                for prefix in (False, True):
                    assert run(spec_k, chunk, prefix, kv) == baseline, (
                        kv, spec_k, chunk, prefix)


@pytest.mark.parametrize("paged", [False, True])
@pytest.mark.parametrize("grammar_name", ["schema", "json"])
def test_speculative_dfa_greedy_exactness(paged, grammar_name):
    """spec × DFA (VERDICT r2 item 6): with every grammar slot on one
    compiled DFA, drafted tokens verify through the DFA ON DEVICE
    (engine.dfa_greedy_multi) — multi-token verify is kept and the output
    must equal the non-speculative greedy run token-for-token."""
    import json as jsonlib

    import jax

    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import InferenceEngine
    from k8s_llm_rca_tpu.engine.constrain import make_grammar
    from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine
    from k8s_llm_rca_tpu.models import llama
    from k8s_llm_rca_tpu.utils import get_tokenizer

    cfg = TINY
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    gname = ({"type": "object", "properties": [
        ("kind", {"enum": ["Pod", "Service", "Node"]}),
        ("ok", {"type": "boolean"})]} if grammar_name == "schema"
        else "json")
    prompt = tok.encode("diagnose: pod crashloop backoff", add_bos=True)

    def run(spec_k):
        kw = dict(paged=True, page_size=16, num_pages=64,
                  prefix_cache=False) if paged else {}
        cls = PagedInferenceEngine if paged else InferenceEngine
        extra = dict(use_kernel=False) if paged else {}
        eng = cls(cfg, EngineConfig(max_batch=2, max_seq_len=256,
                                    prefill_buckets=(16, 32),
                                    max_new_tokens=48,
                                    speculative_k=spec_k, decode_chunk=1,
                                    **kw), params, tok, **extra)
        rid = eng.submit(prompt, max_new_tokens=48,
                         grammar=make_grammar(gname, tok))
        res = {r.seq_id: r for r in eng.run_to_completion()}
        return res[rid].text

    base, spec = run(0), run(3)
    assert base == spec
    jsonlib.loads(base)


def test_speculative_interpreted_grammar_host_fallback_exactness():
    """An INTERPRETED grammar (no compiled tables — here a raw-text choice
    template) cannot verify on device: the verify tick must take the host
    path (ship logits, per-position _greedy_with_grammar) and still equal
    the non-speculative run exactly."""
    from k8s_llm_rca_tpu.engine.constrain import SchemaGrammar

    cfg = TINY
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    schema = {"type": "choice", "options": [
        "verdict: pod failed due to missing secret",
        "checked: node pressure taint evicted the pod"]}
    prompt = tok.encode("diagnose:", add_bos=True)

    def run(spec_k):
        eng = InferenceEngine(
            cfg, EngineConfig(max_batch=2, max_seq_len=256,
                              prefill_buckets=(16,), max_new_tokens=64,
                              speculative_k=spec_k, decode_chunk=1),
            params, tok)
        # built DIRECTLY as the interpreted FSM: make_grammar now
        # DFA-compiles small templates, but the host-fallback verify path
        # under test needs a grammar with no compiled tables
        g = SchemaGrammar(schema, tok)
        assert getattr(g, "tables", None) is None
        rid = eng.submit(prompt, max_new_tokens=64, grammar=g)
        res = {r.seq_id: r for r in eng.run_to_completion()}
        return res[rid].text

    base, spec = run(0), run(3)
    assert base == spec
    assert base in schema["options"]


@pytest.mark.parametrize("paged", [False, True])
@pytest.mark.parametrize("quality", ["random", "self"])
def test_model_draft_engine_matches_plain(paged, quality):
    """Draft-MODEL speculation (``draft_model=`` on either engine):
    greedy output is identical to the plain engine for ANY draft —
    a random-weight 1-layer draft (worst case: near-zero acceptance)
    and the target model as its own draft (best case) — and the good
    draft actually accepts tokens, through admission/retirement churn
    and the draft-cache lazy re-sync."""
    import dataclasses

    from k8s_llm_rca_tpu.engine import make_engine

    cfg = TINY.replace(max_seq_len=128)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    if quality == "self":
        draft = (cfg, params)
    else:
        dcfg = cfg.replace(n_layers=1)
        draft = (dcfg, llama.init_params(dcfg, jax.random.PRNGKey(9)))
    extra = (dict(paged=True, page_size=16, num_pages=64,
                  prefix_cache=False) if paged else {})
    kw = dict(use_kernel=False) if paged else {}
    ecfg0 = EngineConfig(max_batch=2, max_seq_len=128,
                         prefill_buckets=(32, 64), max_new_tokens=20,
                         temperature=0.0, **extra)
    prompts = [tok.encode("the pod the pod the pod", add_bos=True),
               tok.encode("mount failed mount failed again", add_bos=True),
               tok.encode("pvc not bound why", add_bos=True)]

    with jax.default_matmul_precision("float32"):
        plain = make_engine(cfg, ecfg0, params, tok, **kw)
        a = plain.generate([list(p) for p in prompts], max_new_tokens=20)
        before = METRICS.counters.get("engine.spec_accepted", 0)
        spec = make_engine(cfg, dataclasses.replace(ecfg0, speculative_k=3),
                           params, tok, draft_model=draft, **kw)
        b = spec.generate([list(p) for p in prompts], max_new_tokens=20)
    for ra, rb in zip(a, b):
        assert ra.token_ids == rb.token_ids, quality
        assert ra.finish_reason == rb.finish_reason
    if paged:
        spec.allocator.check()
    if quality == "self":
        # the target drafting for itself accepts nearly everything
        accepted = METRICS.counters.get("engine.spec_accepted", 0) - before
        assert accepted > 10, accepted


def test_model_draft_validation():
    """draft_model rejects loudly: no speculative_k, vocab mismatch."""
    from k8s_llm_rca_tpu.engine import make_engine

    cfg = TINY.replace(max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    ecfg = EngineConfig(max_batch=2, max_seq_len=64, prefill_buckets=(16,))
    with pytest.raises(ValueError, match="speculative_k"):
        make_engine(cfg, ecfg, params, tok, draft_model=(cfg, params))
    import dataclasses

    bad_cfg = cfg.replace(vocab_size=1024)
    with pytest.raises(ValueError, match="vocab"):
        make_engine(cfg, dataclasses.replace(ecfg, speculative_k=3),
                    params, tok,
                    draft_model=(bad_cfg,
                                 llama.init_params(bad_cfg,
                                                   jax.random.PRNGKey(1))))


def test_model_draft_long_context_stays_roomy():
    """A draft whose cache is SMALLER than the target's must keep
    drafting once the context exceeds it: the sync tail-clip leaves
    k+1 steps of headroom, so the slot re-prefills only every ~headroom
    tokens instead of every tick with zero drafts (regression: clipping
    to the cache edge made long slots a pure per-tick dispatch tax)."""
    import dataclasses

    from k8s_llm_rca_tpu.engine import make_engine

    cfg = TINY.replace(max_seq_len=256)
    draft_cfg = cfg.replace(max_seq_len=64)      # draft cache << target
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    ecfg = EngineConfig(max_batch=1, max_seq_len=256,
                        prefill_buckets=(32, 64), max_new_tokens=160,
                        temperature=0.0, speculative_k=3)
    prompt = tok.encode("the pod the pod the pod", add_bos=True)

    with jax.default_matmul_precision("float32"):
        plain = make_engine(cfg, dataclasses.replace(ecfg, speculative_k=0),
                            params, tok)
        a = plain.generate([list(prompt)], max_new_tokens=160)
        spec = make_engine(cfg, ecfg, params, tok,
                           draft_model=(draft_cfg, params))
        b = spec.generate([list(prompt)], max_new_tokens=160)
    assert a[0].token_ids == b[0].token_ids
    # the context passed 64 tokens many times over; re-prefills must be
    # amortized (~once per ~60-token headroom span), not per-tick
    assert spec._draft.prefills < 12, spec._draft.prefills
