"""Worker program for tests/test_distributed.py — runs in a FRESH process.

Forms a 2-process JAX cluster through runtime.mesh.initialize_distributed
(the jax.distributed.initialize wrapper — the DCN init path SURVEY §2.2's
collectives row requires), builds a GLOBAL mesh spanning both processes'
devices, then executes one cross-process psum and one sharded train step
(fwd + bwd + optimizer update) through the framework's own entry points.

Invoked as: python _distributed_worker.py <process_id> <num_processes> <port>
Prints "WORKER <pid> OK" on success; any assertion/exception exits nonzero.
"""

import os
import sys

# platform must be pinned BEFORE jax initializes a backend: each process
# exposes 2 virtual CPU devices, so the cluster's global mesh has 4
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

pid = int(sys.argv[1])
n_proc = int(sys.argv[2])
port = sys.argv[3]

import jax as _jax  # noqa: E402

# belt-and-braces platform pin: if a sitecustomize pre-imported jax and
# selected another platform at the CONFIG level, env vars alone lose —
# the config knob still wins while no backend is live
_jax.config.update("jax_platforms", "cpu")

from k8s_llm_rca_tpu.runtime.mesh import initialize_distributed  # noqa: E402

initialize_distributed(coordinator_address=f"localhost:{port}",
                       num_processes=n_proc, process_id=pid)

import jax  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from k8s_llm_rca_tpu.config import TINY, MeshConfig  # noqa: E402
from k8s_llm_rca_tpu.engine.train import (  # noqa: E402
    init_sharded_train_state, make_train_step, shard_batch,
)
from k8s_llm_rca_tpu.runtime.mesh import build_mesh  # noqa: E402

# the cluster formed: every process sees every process's devices
assert jax.process_count() == n_proc, jax.process_count()
assert jax.process_index() == pid, jax.process_index()
n_global = 2 * n_proc
assert len(jax.devices()) == n_global, jax.devices()
addressable = jax.local_device_count()
assert addressable == 2, addressable

# --- one cross-process psum over the global mesh
mesh = build_mesh(MeshConfig(data=n_proc, model=2))
x = jax.make_array_from_callback(
    (n_global,), NamedSharding(mesh, P(("data", "model"))),
    lambda idx: np.arange(n_global, dtype=np.float32)[idx])
out = jax.jit(jax.shard_map(
    lambda v: jax.lax.psum(v, ("data", "model")), mesh=mesh,
    in_specs=P(("data", "model")), out_specs=P(("data", "model"))))(x)
expected = float(np.arange(n_global).sum())
for shard in out.addressable_shards:
    got = np.asarray(shard.data)
    assert np.allclose(got, expected), (got, expected)
print(f"WORKER {pid} psum={expected}", flush=True)

# --- one sharded train step (fwd + bwd + adamw) across the cluster:
# params TP-sharded over 'model' per llama_param_specs, batch DP-sharded
# over 'data' (which spans the two PROCESSES — gradient psums cross the
# process boundary, the DCN path on a real pod)
cfg = TINY
optimizer = optax.adamw(1e-3)
params, opt_state = init_sharded_train_state(cfg, mesh, optimizer)
tokens = shard_batch(
    np.asarray(jax.random.randint(jax.random.PRNGKey(0), (2 * n_proc, 16),
                                  0, cfg.vocab_size)), mesh)
step = jax.jit(make_train_step(cfg, optimizer))
params, opt_state, loss = step(params, opt_state, tokens)
loss.block_until_ready()
assert np.isfinite(float(loss)), float(loss)
print(f"WORKER {pid} loss={float(loss):.6f}", flush=True)

# --- multi-process SERVING (VERDICT r4 item 5): an ENGINE over the
# process-spanning mesh actually prefills and decodes.  TP weights and
# the KV cache / page pool shard over 'model' and the batch over 'data'
# — BOTH axes span the two processes' devices, so every decode tick's
# collectives cross the process boundary (the DCN serving path).  The
# engine's host driver runs SPMD-identically in each process (same
# prompts, same deterministic schedule), which is exactly how a real
# multi-host serving deployment drives per-host engine replicas of one
# global program.  Greedy tokens must match the single-process plain
# engine (asserted by the test harness against an unsharded reference).
import _distributed_serve_config as serve_cfg  # noqa: E402

from k8s_llm_rca_tpu.engine import make_engine  # noqa: E402
from k8s_llm_rca_tpu.runtime.sharding import (  # noqa: E402
    llama_param_specs, shard_pytree,
)


def _make_sharded(cfg, sparams, stok, secfg, paged):
    skw = dict(use_kernel=False) if paged else {}
    return make_engine(
        cfg, secfg, shard_pytree(sparams, llama_param_specs(cfg), mesh),
        stok, tp_mesh=mesh, **skw)


for key, toks in serve_cfg.serve_all(_make_sharded).items():
    print(f"WORKER {pid} serve[{key}]={toks}", flush=True)
print(f"WORKER {pid} OK", flush=True)
