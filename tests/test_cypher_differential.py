"""Differential validation of graph/cypher.py (VERDICT r3+r4: the
interpreter's trail-uniqueness / var-length / direction semantics must be
checked against something that is NOT the interpreter's own expectations).

No Neo4j exists in this image, so the oracle is an INDEPENDENT
brute-force evaluator written from the Cypher spec, sharing nothing with
graph/cypher.py but the store's data model: it enumerates every
relationship-sequence of bounded length by recursion over
``graph.relationships`` adjacency, applying the spec rules directly —

- **trail uniqueness**: a relationship instance appears at most once per
  pattern match (openCypher "relationship isomorphism"; nodes MAY
  repeat),
- **var-length bounds**: ``*lo..hi`` inclusive on both ends,
- **direction**: ``->`` follows start→end, ``<-`` end→start, ``-`` either,
- **type filters** apply per traversed relationship, label filters per
  bound node.

The interpreter takes the same inputs as QUERY TEXT (its real boundary:
parser + planner + matcher), the oracle as structured steps — a bug in
either representation shows up as a multiset mismatch of
(node-id-sequence, rel-id-sequence) paths.  Randomized graphs include
cycles, self-loops, parallel edges and multi-label nodes, the exact
shapes that make trail semantics non-trivial (the reference's ``*1..3``
ladder terminates on cyclic metagraphs only because of rule 1 —
find_metapath/find_srckind_metapath_neo4j.py:96,152-154).
"""

import itertools
import random
from collections import Counter

import pytest

from k8s_llm_rca_tpu.graph.cypher import run_query
from k8s_llm_rca_tpu.graph.store import Graph

# ---------------------------------------------------------------------------
# the independent oracle
# ---------------------------------------------------------------------------


def brute_paths(graph, start_labels, steps, end_labels):
    """Every path matching a linear pattern, by exhaustive enumeration.

    ``steps``: [(direction, type_or_None, lo, hi)] with direction in
    {">", "<", "-"}.  Returns a list of (node_ids, rel_ids) tuples —
    one entry per MATCH row the pattern should produce.
    """

    def has_labels(node, labels):
        return all(lb in node.labels for lb in labels)

    def expansions(node, direction, rel_type):
        """(rel, neighbor) pairs leaving ``node`` along one hop."""
        out = []
        for rel in graph.relationships:
            if rel_type is not None and rel.type != rel_type:
                continue
            if direction in (">", "-") and rel.start_node == node:
                out.append((rel, rel.end_node))
            if direction in ("<", "-") and rel.end_node == node:
                out.append((rel, rel.start_node))
            # an undirected self-loop matches once per orientation,
            # which duplicates the (rel, node) pair — Cypher counts the
            # loop once for `-` patterns, so dedupe that case
        if direction == "-":
            seen, dedup = set(), []
            for rel, nbr in out:
                key = (rel.element_id, nbr.element_id)
                if rel.start_node == rel.end_node and key in seen:
                    continue
                seen.add(key)
                dedup.append((rel, nbr))
            out = dedup
        return out

    results = []

    def advance(step_idx, node, nodes, rels, used):
        if step_idx == len(steps):
            if has_labels(node, end_labels):
                results.append((tuple(n.element_id for n in nodes),
                                tuple(r.element_id for r in rels)))
            return
        direction, rel_type, lo, hi = steps[step_idx]

        def hop(cur, depth, pnodes, prels, pused):
            if lo <= depth:
                advance(step_idx + 1, cur, pnodes, prels, pused)
            if depth == hi:
                return
            for rel, nbr in expansions(cur, direction, rel_type):
                if rel.element_id in pused:          # trail uniqueness
                    continue
                hop(nbr, depth + 1, pnodes + [nbr], prels + [rel],
                    pused | {rel.element_id})

        hop(node, 0, nodes, rels, used)

    for start in graph.nodes:
        if has_labels(start, start_labels):
            advance(0, start, [start], [], frozenset())
    return results


# ---------------------------------------------------------------------------
# query-text construction for the same pattern
# ---------------------------------------------------------------------------


def pattern_query(start_labels, steps, end_labels):
    def label_txt(labels):
        return "".join(f":{lb}" for lb in labels)

    txt = f"(a{label_txt(start_labels)})"
    for i, (direction, rel_type, lo, hi) in enumerate(steps):
        body = f":{rel_type}" if rel_type else ""
        if (lo, hi) != (1, 1):
            body += f"*{lo}..{hi}"
        # empty body exercises the bare `--` parser form
        seg = f"-[{body}]-" if body else "--"
        if direction == ">":
            seg = seg[:-1] + "->"
        elif direction == "<":
            seg = "<" + seg
        mid = (f"(b{label_txt(end_labels)})" if i == len(steps) - 1
               else "()")
        txt += seg + mid
    return f"MATCH p = {txt} RETURN p"


def interp_paths(graph, query):
    rows = run_query(graph, query)
    out = []
    for row in rows:
        p = row["p"]
        out.append((tuple(n.element_id for n in p.nodes),
                    tuple(r.element_id for r in p.relationships)))
    return out


# ---------------------------------------------------------------------------
# randomized graphs
# ---------------------------------------------------------------------------

LABELS = ["Pod", "Node", "Svc", "Pvc"]
TYPES = ["Flow", "Ref", "Has"]


def random_graph(rng):
    g = Graph()
    nodes = []
    for i in range(rng.randint(3, 7)):
        labels = rng.sample(LABELS, rng.randint(1, 2))
        nodes.append(g.add_node(labels, kind=labels[0], idx=i))
    for _ in range(rng.randint(2, 14)):
        a, b = rng.choice(nodes), rng.choice(nodes)   # self-loops allowed
        g.add_relationship(a, rng.choice(TYPES), b)
    return g


def random_pattern(rng):
    start = rng.sample(LABELS, rng.randint(0, 1))
    end = rng.sample(LABELS, rng.randint(0, 1))
    steps = []
    for _ in range(rng.randint(1, 2)):
        direction = rng.choice([">", "<", "-"])
        rel_type = rng.choice([None] + TYPES)
        if rng.random() < 0.6:
            lo = rng.randint(1, 2)
            hi = rng.randint(lo, 3)
        else:
            lo = hi = 1
        steps.append((direction, rel_type, lo, hi))
    return start, steps, end


# ---------------------------------------------------------------------------
# the differential properties
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(40))
def test_random_patterns_match_brute_force(seed):
    """400 random (graph, pattern) pairs: the interpreter's MATCH rows —
    parsed from query TEXT — equal the spec oracle's enumeration as
    multisets of (node ids, rel ids)."""
    rng = random.Random(1000 + seed)
    for _ in range(10):
        g = random_graph(rng)
        start, steps, end = random_pattern(rng)
        query = pattern_query(start, steps, end)
        got = Counter(interp_paths(g, query))
        want = Counter(brute_paths(g, start, steps, end))
        assert got == want, (query, seed,
                             sorted(got - want), sorted(want - got))


def test_ladder_rung1_directed_varlength_on_adversarial_graphs():
    """Rung 1 of the metapath ladder (`-[*1..3]->`) against the oracle on
    hand-built adversarial graphs: a directed triangle (cycle), a
    diamond with parallel edges, and a self-loop — where naive node- or
    no-uniqueness semantics diverge from trail semantics."""
    # directed triangle + chord
    g = Graph()
    a = g.add_node(["Pod"], kind="Pod")
    b = g.add_node(["Node"], kind="Node")
    c = g.add_node(["Svc"], kind="Svc")
    g.add_relationship(a, "Flow", b)
    g.add_relationship(b, "Flow", c)
    g.add_relationship(c, "Flow", a)           # cycle back
    g.add_relationship(a, "Ref", c)            # chord
    for start, end in itertools.product([["Pod"], []], [["Svc"], []]):
        steps = [(">", None, 1, 3)]
        got = Counter(interp_paths(g, pattern_query(start, steps, end)))
        want = Counter(brute_paths(g, start, steps, end))
        assert got == want, (start, end, got, want)

    # parallel edges: two distinct Flow rels a->b are two distinct trails
    g2 = Graph()
    a2 = g2.add_node(["Pod"], kind="Pod")
    b2 = g2.add_node(["Node"], kind="Node")
    r1 = g2.add_relationship(a2, "Flow", b2)
    r2 = g2.add_relationship(a2, "Flow", b2)
    g2.add_relationship(b2, "Flow", a2)
    steps = [(">", "Flow", 1, 3)]
    got = Counter(interp_paths(g2, pattern_query(["Pod"], steps, ["Pod"])))
    want = Counter(brute_paths(g2, ["Pod"], steps, ["Pod"]))
    assert got == want
    # the a->b->a trails exist via BOTH parallel edges
    assert sum(1 for (ns, rs) in got if len(rs) == 2) >= 2

    # self-loop: one rel, trail-usable once
    g3 = Graph()
    s = g3.add_node(["Pod"], kind="Pod")
    g3.add_relationship(s, "Flow", s)
    for direction in (">", "-"):
        steps = [(direction, None, 1, 3)]
        got = Counter(interp_paths(g3, pattern_query([], steps, [])))
        want = Counter(brute_paths(g3, [], steps, []))
        assert got == want, (direction, got, want)
        assert len(got) == 1                      # exactly one 1-hop trail


def test_ladder_rung2_undirected_varlength_random():
    """Rung 2 (`-[*1..3]-`): undirected var-length on random cyclic
    graphs, where each relationship may be traversed in either
    orientation but still only once per trail."""
    for seed in range(60):
        rng = random.Random(7000 + seed)
        g = random_graph(rng)
        start, _, end = random_pattern(rng)
        steps = [("-", None, 1, 3)]
        got = Counter(interp_paths(g, pattern_query(start, steps, end)))
        want = Counter(brute_paths(g, start, steps, end))
        assert got == want, (seed, sorted(got - want), sorted(want - got))


def test_distinct_endpoints_match_brute_force():
    """The srcKind-walk shape (`RETURN DISTINCT b.kind`): the
    interpreter's DISTINCT projection equals the oracle's de-duplicated
    endpoint kinds."""
    for seed in range(30):
        rng = random.Random(3000 + seed)
        g = random_graph(rng)
        start, steps, end = random_pattern(rng)
        base = pattern_query(start, steps, end)
        query = base.replace("RETURN p", "RETURN DISTINCT b.kind AS k")
        got = sorted(row["k"] for row in run_query(g, query))
        by_end = brute_paths(g, start, steps, end)
        # element ids are assigned interleaved with rels; map via lookup
        id_to_node = {n.element_id: n for n in g.nodes}
        want = sorted({id_to_node[ns[-1]]["kind"] for ns, _ in by_end})
        assert got == want, (seed, query, got, want)


def test_shortest_pruning_inputs_match_brute_force():
    """The ladder's shortest-only pruning consumes len(path) — validate
    the LENGTH DISTRIBUTION of returned paths against the oracle, per
    (start, end) pair, on random graphs (the pruning itself is host
    Python in rca/locator.py; its input contract is what the interpreter
    must get right)."""
    for seed in range(30):
        rng = random.Random(5000 + seed)
        g = random_graph(rng)
        steps = [(">", None, 1, 3)]
        got = interp_paths(g, pattern_query([], steps, []))
        want = brute_paths(g, [], steps, [])

        def dist(paths):
            d = {}
            for ns, rs in paths:
                d.setdefault((ns[0], ns[-1]), Counter())[len(rs)] += 1
            return d

        assert dist(got) == dist(want), seed


# ---------------------------------------------------------------------------
# the ladder's FULL rung-1/rung-2 query text, WHERE clauses included
# ---------------------------------------------------------------------------

# the PRODUCTION rung queries, imported — not retyped — so an edit to
# the locator's WHERE clauses is differentially validated automatically
from k8s_llm_rca_tpu.rca.locator import _Q_DIRECTED, _Q_UNDIRECTED

LADDER = {"->": _Q_DIRECTED.format(hops=3),
          "-": _Q_UNDIRECTED.format(hops=3)}


def brute_ladder(graph, direction, src_kind, dest_kind, inter_kinds):
    """Spec oracle for the FULL rung query: raw var-length trails plus an
    independent re-implementation of every WHERE clause — node
    uniqueness (the all/single quantifier pair), the Event/Namespace
    kind exclusion, endpoint kinds, and the optional intermediate-kind
    disjunction.  Written against the openCypher semantics, not against
    the interpreter's quantifier machinery."""
    out = []
    by_id = {n.element_id: n for n in graph.nodes}
    for node_ids, rel_ids in brute_paths(graph, [],
                                         [(direction, None, 1, 3)], []):
        path_nodes = [by_id[i] for i in node_ids]
        if path_nodes[0]["kind"] != src_kind:
            continue
        if path_nodes[-1]["kind"] != dest_kind:
            continue
        if len(set(node_ids)) != len(node_ids):     # node uniqueness
            continue
        if any(n["kind"] in ("Event", "Namespace") for n in path_nodes):
            continue
        if inter_kinds:
            if not any(n["kind"] in inter_kinds
                       for n in path_nodes[1:-1]):
                continue
        out.append((node_ids, rel_ids))
    return out


LADDER_KINDS = ["Pod", "Node", "Svc", "Pvc", "Event", "Namespace"]


def ladder_graph(rng):
    g = Graph()
    nodes = []
    for i in range(rng.randint(4, 8)):
        kind = rng.choice(LADDER_KINDS)
        nodes.append(g.add_node([kind], kind=kind, idx=i))
    for _ in range(rng.randint(3, 14)):
        a, b = rng.choice(nodes), rng.choice(nodes)
        g.add_relationship(a, rng.choice(TYPES), b)
    return g


@pytest.mark.parametrize("arrow", ["->", "-"])
def test_full_ladder_query_matches_brute_force(arrow):
    """Rungs 1 (directed) and 2 (undirected) of the metapath ladder —
    the exact query TEXT the locator runs, quantifier WHERE clauses and
    all — against the spec oracle on random graphs that include Event /
    Namespace decoys and cycles, across empty / null / non-empty
    $intermediateKinds."""
    direction = ">" if arrow == "->" else "-"
    for seed in range(40):
        rng = random.Random(11000 + seed)
        g = ladder_graph(rng)
        src, dest = rng.choice(LADDER_KINDS[:4]), rng.choice(LADDER_KINDS[:4])
        inter = rng.choice([None, [], ["Node"], ["Node", "Svc"]])
        rows = run_query(g, LADDER[arrow],
                         {"srcKind": src, "destKind": dest,
                          "intermediateKinds": inter})
        got = Counter(
            (tuple(n.element_id for n in row["path"].nodes),
             tuple(r.element_id for r in row["path"].relationships))
            for row in rows)
        want = Counter(brute_ladder(g, direction, src, dest, inter or []))
        assert got == want, (arrow, seed, sorted(got - want),
                             sorted(want - got))
