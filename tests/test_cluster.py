"""Multi-replica serving cluster (k8s_llm_rca_tpu/cluster/).

Three layers of proof, mirroring the repo's parallelism conventions:

- **carving + loud exclusions**: every supported submesh shape on the
  8-virtual-device mesh (2×tp4, 4×tp2) carves disjointly; indivisible
  counts, overlapping device groups, and CP/PP/EP×replica compositions
  all raise ValueError at construction.
- **exact greedy parity**: each supported replica configuration emits
  byte-identical text to the plain single-engine path — the same parity
  bar every other parallelism mode meets (tests/test_parallel.py).
- **failover**: hard kills re-start journal-recorded prompts on
  survivors under unchanged global handles; graceful drains migrate
  sequences WITH decode position via snapshot/adopt and finish
  byte-identical to an undisturbed run, re-prefilling mostly from the
  target's prefix cache; the 100-incident cluster-oracle chaos soak
  under seeded replica kills reports byte-identically to the unkilled
  sweep (the killer polls its OWN plan — faults/supervisor.py).

Echo replicas drive the pure routing tests (affinity, balancing,
backpressure) — the router is backend-agnostic by design.
"""

import pytest

from k8s_llm_rca_tpu.cluster import (
    ClusterRouter, Replica, RouterAdmissionError, build_replicas,
    carve_replica_meshes,
)
from k8s_llm_rca_tpu.config import TINY, EngineConfig, MeshConfig
from k8s_llm_rca_tpu.engine.engine import (
    validate_disjoint_submeshes, validate_replica_mesh,
)
from k8s_llm_rca_tpu.runtime.mesh import build_mesh
from k8s_llm_rca_tpu.serve.backend import EchoBackend, GenOptions
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

pytestmark = pytest.mark.cluster


# ---------------------------------------------------------------------------
# submesh carving + loud exclusions
# ---------------------------------------------------------------------------


class TestCarving:
    @pytest.mark.parametrize("n,tp", [(2, 4), (4, 2)])
    def test_supported_shapes_carve_disjointly(self, cpu_devices, n, tp):
        meshes = carve_replica_meshes(n, devices=cpu_devices)
        assert len(meshes) == n
        seen = set()
        for mesh in meshes:
            assert mesh.shape["model"] == tp
            assert mesh.shape["data"] == 1
            ids = {d.id for d in mesh.devices.flat}
            assert not (ids & seen)         # disjoint
            seen |= ids
        assert len(seen) == len(cpu_devices[:8])

    def test_indivisible_count_rejected(self, cpu_devices):
        with pytest.raises(ValueError, match="do not split"):
            carve_replica_meshes(3, devices=cpu_devices)

    def test_indivisible_data_axis_rejected(self, cpu_devices):
        with pytest.raises(ValueError, match="data"):
            carve_replica_meshes(2, devices=cpu_devices, data=3)

    def test_overlapping_submeshes_rejected(self, cpu_devices):
        a = build_mesh(MeshConfig(model=4), devices=cpu_devices[:4])
        b = build_mesh(MeshConfig(model=4), devices=cpu_devices[2:6])
        with pytest.raises(ValueError, match="overlap"):
            validate_disjoint_submeshes([a, b])

    @pytest.mark.parametrize("axes,what", [
        (dict(data=2, model=2, seq=2), "CP×replica"),
        (dict(data=2, model=2, stage=2), "PP×replica"),
        (dict(data=2, model=2, expert=2), "EP×replica"),
    ])
    def test_cross_replica_compositions_rejected(self, cpu_devices, axes,
                                                 what):
        mesh = build_mesh(MeshConfig(**axes), devices=cpu_devices[:8])
        ecfg = EngineConfig(max_batch=2, max_seq_len=64)
        with pytest.raises(ValueError, match="unsupported"):
            validate_replica_mesh(mesh, TINY, ecfg)

    def test_mesh_count_mismatch_rejected(self, cpu_devices):
        meshes = carve_replica_meshes(2, devices=cpu_devices)
        with pytest.raises(ValueError, match="meshes for"):
            build_replicas(TINY.replace(max_seq_len=64),
                           EngineConfig(max_batch=2, max_seq_len=64),
                           3, meshes=meshes)


# ---------------------------------------------------------------------------
# router on echo replicas: affinity / balance / backpressure / failover
# ---------------------------------------------------------------------------


def _echo_router(n=2, cap=None, delay_pumps=0, tok=None):
    tok = tok or get_tokenizer()
    reps = [Replica(i, EchoBackend(tok, delay_pumps=delay_pumps))
            for i in range(n)]
    return ClusterRouter(reps, max_inflight_per_replica=cap), reps


def _settle(router, handles, pumps=64):
    out = {}
    for _ in range(pumps):
        out.update(router.pump())
        if all(h in out for h in handles):
            return out
    raise AssertionError(f"runs never settled: {out.keys()}")


class TestRouter:
    def test_session_affinity_sticks_while_alive(self):
        router, _ = _echo_router(n=2, delay_pumps=10 ** 9)
        h = [router.start("p", GenOptions(session="t1")) for _ in range(3)]
        rids = {router._handle_map[x][0] for x in h}
        assert len(rids) == 1               # pinned, despite load skew
        assert router._affinity["t1"] in rids

    def test_unpinned_runs_balance_to_least_depth(self):
        router, _ = _echo_router(n=2, delay_pumps=10 ** 9)
        rids = [router._handle_map[router.start("p", GenOptions())][0]
                for _ in range(4)]
        # depth-least with lowest-id tiebreak => strict alternation
        assert rids == [0, 1, 0, 1]

    def test_affinity_overflow_does_not_repin(self):
        router, _ = _echo_router(n=2, cap=1, delay_pumps=10 ** 9)
        h1 = router.start("p", GenOptions(session="t1"))
        pinned = router._handle_map[h1][0]
        h2 = router.start("p", GenOptions(session="t1"))   # pinned full
        assert router._handle_map[h2][0] != pinned         # overflowed
        assert router._affinity["t1"] == pinned            # pin kept

    def test_backpressure_sheds_loudly(self):
        router, _ = _echo_router(n=2, cap=1, delay_pumps=10 ** 9)
        router.start("p", GenOptions())
        router.start("p", GenOptions())
        with pytest.raises(RouterAdmissionError, match="inflight cap"):
            router.start("p", GenOptions())

    def test_queue_depth_and_occupancy_accessors(self):
        router, reps = _echo_router(n=2, delay_pumps=10 ** 9)
        router.start("p", GenOptions(session="a"))
        assert sorted(router.alive_ids()) == [0, 1]
        depths = router.queue_depths()
        assert sum(depths.values()) == 1
        assert set(router.occupancies()) == {0, 1}   # echo: 0.0 values

    def test_failover_keeps_global_handles_and_completes(self):
        tok = get_tokenizer()
        router, reps = _echo_router(n=2, delay_pumps=2, tok=tok)
        handles = [router.start(f"p{i}", GenOptions(session=f"s{i}"))
                   for i in range(4)]
        victim = 0
        moved = router.fail_replica(victim)
        assert moved                         # someone lived on replica 0
        assert not reps[victim].alive
        assert router.alive_ids() == [1]
        # the same global handles settle after the kill
        out = _settle(router, handles)
        assert sorted(out) == sorted(handles)
        assert all(v.error is None for v in out.values())
        # affinity repinned off the corpse
        h = router.start("p0", GenOptions(session="s0"))
        assert router._handle_map[h][0] == 1

    def test_failover_bypasses_admission_cap(self):
        router, _ = _echo_router(n=2, cap=1, delay_pumps=10 ** 9)
        router.start("a", GenOptions())      # -> replica 0
        router.start("b", GenOptions())      # -> replica 1 (cap reached)
        moved = router.fail_replica(0)
        assert len(moved) == 1               # re-homed despite the cap
        assert router.queue_depths() == {1: 2}

    def test_last_alive_replica_cannot_be_killed(self):
        router, _ = _echo_router(n=2)
        router.fail_replica(0)
        with pytest.raises(ValueError, match="last alive"):
            router.fail_replica(1)

    def test_dead_or_unknown_replica_rejected(self):
        router, _ = _echo_router(n=3)
        router.fail_replica(1)
        with pytest.raises(ValueError, match="not alive"):
            router.fail_replica(1)
        with pytest.raises(ValueError, match="not alive"):
            router.fail_replica(9)

    def test_duplicate_replica_ids_rejected(self):
        tok = get_tokenizer()
        with pytest.raises(ValueError, match="duplicate"):
            ClusterRouter([Replica(0, EchoBackend(tok)),
                           Replica(0, EchoBackend(tok))])

    def test_cancel_routes_to_owning_replica(self):
        router, reps = _echo_router(n=2, delay_pumps=10 ** 9)
        h = router.start("p", GenOptions())
        rid, lh = router._handle_map[h]
        router.cancel(h)
        assert not router.busy(h)
        assert reps[rid].queue_depth() == 0
        assert router.pump() == {}           # nothing leaks into results


# ---------------------------------------------------------------------------
# exact greedy parity per supported replica configuration (engine replicas)
# ---------------------------------------------------------------------------


_PARITY_PROMPTS = [
    "pod pending unschedulable node affinity mismatch",
    "pvc not bound storageclass missing",
    "image pull backoff registry unreachable",
    "oom killed container memory limit",
]


def _engine_cfgs():
    cfg = TINY.replace(max_seq_len=64)
    ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                        prefill_buckets=(16, 32), max_new_tokens=6,
                        temperature=0.0)
    return cfg, ecfg


class TestGreedyParity:
    @pytest.mark.parametrize("n_replicas", [2, 4])
    def test_replica_cluster_matches_plain_engine(self, cpu_devices,
                                                  n_replicas):
        """Every prompt's text from the N-replica cluster must be
        byte-identical to the plain unsharded single engine's — and
        every replica must actually serve at least one prompt (else the
        parity claim silently narrows to one submesh)."""
        import jax

        from k8s_llm_rca_tpu.engine import make_engine
        from k8s_llm_rca_tpu.models import llama

        cfg, ecfg = _engine_cfgs()
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        ref_engine = make_engine(cfg, ecfg, params, tok)
        prompts = _PARITY_PROMPTS[:n_replicas]
        ref = ref_engine.generate(
            [tok.encode(p, add_bos=True) for p in prompts],
            max_new_tokens=6)

        replicas = build_replicas(cfg, ecfg, n_replicas,
                                  devices=cpu_devices, seed=0)
        router = ClusterRouter(replicas)
        handles = [router.start(p, GenOptions(max_new_tokens=6))
                   for p in prompts]
        served = {router._handle_map[h][0] for h in handles}
        assert served == set(range(n_replicas))
        out = _settle(router, handles, pumps=256)
        for h, r in zip(handles, ref):
            assert out[h].text == r.text     # byte-identical greedy text
            assert out[h].error is None


# ---------------------------------------------------------------------------
# graceful drain: sequences migrate WITH decode position, byte-identical
# ---------------------------------------------------------------------------


class TestDrainMigration:
    def test_mid_decode_drain_is_byte_identical_and_prefix_hits(
            self, cpu_devices):
        import jax

        from k8s_llm_rca_tpu.engine import make_engine
        from k8s_llm_rca_tpu.models import llama

        cfg = TINY.replace(max_seq_len=64)
        ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                            prefill_buckets=(16, 32), max_new_tokens=10,
                            temperature=0.0, paged=True, page_size=8,
                            num_pages=32, decode_chunk=1,
                            prefix_cache=True)
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        prompt = "pod pending unschedulable node affinity mismatch"
        opts = GenOptions(max_new_tokens=10, session="thread_7")

        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        ref = make_engine(cfg, ecfg, params, tok, use_kernel=False).generate(
            [tok.encode(prompt, add_bos=True)], max_new_tokens=10)[0]

        replicas = build_replicas(cfg, ecfg, 2, devices=cpu_devices,
                                  seed=0, use_kernel=False)
        router = ClusterRouter(replicas)
        # warm BOTH prefix caches: a full run of the same session on each
        # replica (retired pages are inserted into the prefix cache), so
        # the migrated re-prefill on the target can mostly HIT
        for rid in (0, 1):
            router._affinity["thread_7"] = rid
            out = _settle(router, [router.start(prompt, opts)], pumps=256)
            assert list(out.values())[0].text == ref.text
        router._affinity["thread_7"] = 0

        h = router.start(prompt, opts)
        assert router._handle_map[h][0] == 0
        for _ in range(4):                    # mid-decode (chunk=1)
            assert not router.pump()
        target_engine = replicas[1].backend.engine
        hits_before = target_engine._counts.get(
            "engine.prefix_hit_tokens", 0)
        moved = router.drain_replica(0)
        assert moved == [h]
        assert router._handle_map[h][0] == 1
        assert router.migrated_runs == 1
        out = _settle(router, [h], pumps=256)
        # byte-identical to the undisturbed single-engine run
        assert out[h].text == ref.text
        # the re-prefill was a mostly-HIT path: at least one full page of
        # prompt+generated came from the target's prefix cache
        hits = target_engine._counts.get("engine.prefix_hit_tokens", 0)
        assert hits - hits_before >= ecfg.page_size
        # the drained source ended clean (pages freed via normal retire)
        src_engine = replicas[0].backend.engine
        assert not src_engine.has_work
        src_engine.allocator.check()

    def test_drain_needs_engine_replicas(self):
        router, _ = _echo_router(n=2)
        with pytest.raises(ValueError, match="engine replicas"):
            router.drain_replica(0)

    def test_drain_refuses_bad_target(self, cpu_devices):
        router, _ = _echo_router(n=2)
        with pytest.raises(ValueError, match="DIFFERENT"):
            router.drain_replica(0, target=0)


# ---------------------------------------------------------------------------
# journal + recovery through the router
# ---------------------------------------------------------------------------


class TestJournaledFailover:
    def test_recover_service_routes_resubmits_with_affinity(self,
                                                            tmp_path):
        from k8s_llm_rca_tpu.serve.api import AssistantService, RunStatus
        from k8s_llm_rca_tpu.serve.journal import RunJournal
        from k8s_llm_rca_tpu.serve.recover import recover_service

        path = str(tmp_path / "serve.wal")
        tok = get_tokenizer()
        router, _ = _echo_router(n=2, delay_pumps=10 ** 9, tok=tok)
        service = AssistantService(router, journal=RunJournal(path))
        a = service.create_assistant("cluster-test", "answer briefly")
        th = service.create_thread()
        service.add_message(th.id, "what failed?")
        run = service.create_run(th.id, a.id,
                                 gen=GenOptions(max_new_tokens=8))
        assert router._affinity[th.id] in (0, 1)   # session = thread id
        service._journal.close()                   # process death

        fresh_router, _ = _echo_router(n=2, tok=tok)
        svc, report = recover_service(path, fresh_router)
        assert report["resubmitted"] == [run.id]
        # the journaled session re-pins the thread on the fresh cluster
        assert fresh_router._affinity[th.id] in (0, 1)
        got = svc.wait_run(run.id)
        assert got.status == RunStatus.COMPLETED

    def test_settled_runs_never_reexecuted_through_router(self, tmp_path):
        from k8s_llm_rca_tpu.serve.api import AssistantService, RunStatus
        from k8s_llm_rca_tpu.serve.journal import RunJournal
        from k8s_llm_rca_tpu.serve.recover import recover_service

        path = str(tmp_path / "serve.wal")
        tok = get_tokenizer()
        router, _ = _echo_router(n=2, tok=tok)
        service = AssistantService(router, journal=RunJournal(path))
        a = service.create_assistant("cluster-test", "answer briefly")
        th = service.create_thread()
        service.add_message(th.id, "what failed?")
        run = service.wait_run(service.create_run(th.id, a.id).id)
        assert run.status == RunStatus.COMPLETED
        service._journal.close()

        class NeverStarts(ClusterRouter):
            def start(self, prompt, opts):
                raise AssertionError("settled run re-executed")

        fresh = NeverStarts([Replica(0, EchoBackend(tok)),
                             Replica(1, EchoBackend(tok))])
        svc, report = recover_service(path, fresh)
        assert report["resubmitted"] == []
        assert svc.runs[run.id].status == RunStatus.COMPLETED


# ---------------------------------------------------------------------------
# chaos soak under seeded replica kills (the acceptance sweep)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestClusterChaosSoak:
    def test_100_incident_kill_soak_byte_identical(self):
        """The ISSUE acceptance bar: a 100-incident sweep on oracle
        replicas, with seeded replica kills mid-sweep, completes on the
        survivors with a report byte-identical to the unkilled sweep's
        (and to a rerun of itself)."""
        from k8s_llm_rca_tpu.faults import inject
        from k8s_llm_rca_tpu.faults.plan import FaultPlan
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak
        from k8s_llm_rca_tpu.faults.supervisor import ReplicaKiller

        base = run_chaos_soak(seed=11, n_incidents=100,
                              backend="cluster-oracle",
                              cluster_replicas=4)
        assert base["completed"] == 100
        assert base["failed"] == 0
        assert base["cluster_replicas"] == 4

        def killer():
            return ReplicaKiller(FaultPlan.from_spec(
                2, {inject.SITE_REPLICA: {
                    "rate": 0.03, "horizon": 100, "kinds": ("crash",)}}))

        k1 = killer()
        killed = run_chaos_soak(seed=11, n_incidents=100,
                                backend="cluster-oracle",
                                cluster_replicas=4, killer=k1)
        assert k1.kills                      # kills actually happened
        assert len(set(k1.kills)) == len(k1.kills)   # no double-kill
        assert report_bytes(killed) == report_bytes(base)

        k2 = killer()
        again = run_chaos_soak(seed=11, n_incidents=100,
                               backend="cluster-oracle",
                               cluster_replicas=4, killer=k2)
        assert k2.kills == k1.kills          # kill schedule is seeded
        assert report_bytes(again) == report_bytes(base)

    def test_killer_requires_cluster_backend(self):
        from k8s_llm_rca_tpu.faults import inject
        from k8s_llm_rca_tpu.faults.plan import FaultPlan
        from k8s_llm_rca_tpu.faults.soak import run_chaos_soak
        from k8s_llm_rca_tpu.faults.supervisor import ReplicaKiller

        k = ReplicaKiller(FaultPlan.from_spec(
            0, {inject.SITE_REPLICA: {"rate": 1.0, "horizon": 4,
                                      "kinds": ("crash",)}}))
        with pytest.raises(ValueError, match="cluster"):
            run_chaos_soak(seed=0, n_incidents=1, backend="oracle",
                           killer=k)

    @pytest.mark.slow
    def test_engine_cluster_kill_soak_byte_identical(self):
        """Engine replicas under a mid-sweep kill: graph-faults-only plan
        (per-tick fault polls would legitimately shift with the
        survivor's extra ticks — fault-schedule divergence, not
        nondeterminism), report byte-identical to the unkilled run, every
        replica engine left clean."""
        from k8s_llm_rca_tpu.faults import inject
        from k8s_llm_rca_tpu.faults.plan import FaultPlan
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak
        from k8s_llm_rca_tpu.faults.supervisor import ReplicaKiller

        spec = {inject.SITE_GRAPH: {
            "rate": 0.10, "horizon": 40, "delay_s": 0.01,
            "kinds": ("error", "timeout", "empty", "slow", "poison")}}
        base = run_chaos_soak(seed=5, n_incidents=2, backend="cluster",
                              plan_spec=spec, cluster_replicas=2)
        assert base["completed"] == 2
        assert base["engine_clean"] is True

        k = ReplicaKiller(FaultPlan.from_spec(
            3, {inject.SITE_REPLICA: {"rate": 0.6, "horizon": 2,
                                      "kinds": ("crash",)}}))
        killed = run_chaos_soak(seed=5, n_incidents=2, backend="cluster",
                                plan_spec=spec, cluster_replicas=2,
                                killer=k)
        assert k.kills                       # the kill fired mid-sweep
        assert killed["engine_clean"] is True
        assert report_bytes(killed) == report_bytes(base)
