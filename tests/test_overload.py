"""Overload hardening (docs/serving.md "overload & priorities"): priority
classes, per-run deadlines, and KV spill-to-host preemption.

The headline invariant is BYTE PARITY: a preempted sequence that spills
its KV pages to host and later restores them must produce exactly the
tokens an uninterrupted run produces — no re-prefill on the spill path,
across host_overlap x prefix_cache x prefill_chunk_budget.  Greedy decode
makes this checkable without tolerance: temperature=0 argmax depends only
on weights and committed KV, so any divergence is a real state-machine
bug, not noise (same rationale as tests/test_overlap.py).

Everything runs on the 8-virtual-device CPU platform the conftest pins.
"""

import dataclasses

import jax
import pytest

from k8s_llm_rca_tpu.config import TINY, EngineConfig, MeshConfig
from k8s_llm_rca_tpu.engine import make_engine
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

pytestmark = pytest.mark.overload


@pytest.fixture(scope="module")
def setup():
    cfg = TINY.replace(max_seq_len=128)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    return cfg, params, tok


PROMPTS = ("kubelet crashloop on node-7 gpu slice",
           "etcd leader lost quorum after upgrade",
           "kubelet crashloop on node-7 gpu slice then oom")


def _ecfg(**over):
    base = dict(max_batch=2, max_seq_len=128, prefill_buckets=(64, 128),
                max_new_tokens=24, temperature=0.0, paged=True,
                page_size=16, num_pages=40, prefix_cache=False,
                decode_chunk=4)
    base.update(over)
    return EngineConfig(**base)


class _Clock:
    """Injectable engine clock (engine._now prefers ``self.clock``)."""

    def __init__(self):
        self.t = 0.0

    def time(self):
        return self.t


def _drive(eng, sids, preempt_at=None):
    """Tick to drain, optionally forcing one preemption; assert the
    engine releases every page (allocator.check + exact free count)."""
    out, tick = {}, 0
    while eng.has_work:
        if preempt_at is not None and tick == preempt_at:
            assert eng._preempt_victim(), "no preemption victim"
        for r in eng.step():
            out[r.seq_id] = r
        tick += 1
    eng.allocator.check()
    resident = eng.prefix_cache.n_resident if eng.prefix_cache else 0
    assert (eng.allocator.n_free + resident
            == eng.engine_cfg.num_pages - 1)
    return [(out[s].token_ids, out[s].finish_reason) for s in sids]


def _run(setup, ecfg, priorities=(1, 2, 0), preempt_at=None):
    cfg, params, tok = setup
    eng = make_engine(cfg, ecfg, params, tok, use_kernel=False)
    sids = [eng.submit(tok.encode(p), priority=pri)
            for p, pri in zip(PROMPTS, priorities)]
    return _drive(eng, sids, preempt_at=preempt_at), dict(eng._counts or {})


# ---------------------------------------------------------------------------
# tentpole: spill/restore byte-parity across the feature matrix
# ---------------------------------------------------------------------------


class TestSpillParity:
    MATRIX = {
        "plain": dict(),
        "prefix": dict(prefix_cache=True),
        "overlap": dict(decode_chunk=1, host_overlap=True),
        "overlap_prefix": dict(prefix_cache=True, decode_chunk=1,
                               host_overlap=True),
        "chunked": dict(prefill_chunk_budget=32),
    }

    @pytest.mark.parametrize("feature", sorted(MATRIX))
    def test_preempt_spill_restore_matches_uninterrupted(self, setup,
                                                         feature):
        """Mixed-priority batch, preemption forced mid-decode: the spill
        run must (a) actually move pages d2h and back (counters prove the
        restore path ran, not the re-prefill fallback) and (b) emit
        byte-identical outputs to the uninterrupted run."""
        kw = self.MATRIX[feature]
        base, _ = _run(setup, _ecfg(max_spilled_pages=0, **kw))
        spill, c = _run(setup, _ecfg(max_spilled_pages=64, **kw),
                        preempt_at=2)
        assert base == spill
        assert c.get("engine.spilled_pages", 0) > 0
        assert c.get("engine.restored_pages", 0) > 0
        assert c.get("engine.spill_budget_fallbacks", 0) == 0

    def test_re_prefill_fallback_parity(self, setup):
        """With spill disabled the same preemption takes the legacy
        free-and-re-prefill path — still byte-identical, zero spills."""
        base, _ = _run(setup, _ecfg())
        re_pre, c = _run(setup, _ecfg(), preempt_at=2)
        assert base == re_pre
        assert c.get("engine.spilled_pages", 0) == 0
        assert c.get("engine.preemptions", 0) >= 1

    def test_budget_fallback_counts_and_preserves_parity(self, setup):
        """max_spilled_pages smaller than the victim's footprint: the
        spill is refused (counted), the sequence re-prefills, and the
        output is still byte-identical."""
        cfg, params, tok = setup
        ecfg = _ecfg(max_batch=1, max_spilled_pages=32)
        eng0 = make_engine(cfg, ecfg, params, tok, use_kernel=False)
        s0 = eng0.submit(tok.encode(PROMPTS[1]), priority=2)
        (base,) = _drive(eng0, [s0])

        eng = make_engine(cfg, dataclasses.replace(ecfg,
                                                   max_spilled_pages=1),
                          params, tok, use_kernel=False)
        s1 = eng.submit(tok.encode(PROMPTS[1]), priority=2)
        eng.step()
        eng.step()
        assert eng._preempt_victim()
        c = eng._counts or {}
        assert c.get("engine.spill_budget_fallbacks", 0) == 1
        assert not eng._spilled
        (out,) = _drive(eng, [s1])
        assert out == base


# ---------------------------------------------------------------------------
# priority queue + victim selection determinism
# ---------------------------------------------------------------------------


class TestPriorityScheduling:
    def test_pending_queue_orders_by_class_then_fifo(self, setup):
        """The admission queue is a deterministic priority queue: classes
        ascend, and WITHIN a class arrival order is preserved (stable
        insert — an all-NORMAL workload degenerates to plain FIFO)."""
        cfg, params, tok = setup
        eng = make_engine(cfg, _ecfg(), params, tok, use_kernel=False)
        prompt = tok.encode(PROMPTS[0])
        sids = [eng.submit(list(prompt), priority=pri)
                for pri in (2, 1, 0, 1, 2, 0)]
        got = [(p.priority, p.seq_id) for p in eng._pending]
        assert got == [(0, sids[2]), (0, sids[5]),
                       (1, sids[1]), (1, sids[3]),
                       (2, sids[0]), (2, sids[4])]
        for sid in sids:
            eng.cancel_seq(sid)
        assert not eng.has_work

    def test_victim_is_lowest_priority_then_youngest(self, setup):
        """Preemption evicts the least-urgent active sequence; ties break
        toward the youngest (largest seq_id) so old work keeps its KV."""
        cfg, params, tok = setup
        eng = make_engine(cfg, _ecfg(max_spilled_pages=64), params, tok,
                          use_kernel=False)
        s_crit = eng.submit(tok.encode(PROMPTS[0]), priority=0)
        s_batch = eng.submit(tok.encode(PROMPTS[1]), priority=2)
        eng.step()
        eng.step()
        assert {st.seq_id for st in eng._active.values()} \
            == {s_crit, s_batch}
        assert eng._preempt_victim()
        survivors = {st.seq_id for st in eng._active.values()}
        assert survivors == {s_crit}, "victim must be the BATCH sequence"
        assert s_batch in eng._spilled
        _drive(eng, [s_crit, s_batch])

    def test_victim_tiebreak_youngest_within_class(self, setup):
        cfg, params, tok = setup
        eng = make_engine(cfg, _ecfg(max_spilled_pages=64), params, tok,
                          use_kernel=False)
        s_old = eng.submit(tok.encode(PROMPTS[0]), priority=1)
        s_young = eng.submit(tok.encode(PROMPTS[1]), priority=1)
        eng.step()
        eng.step()
        assert eng._preempt_victim()
        assert {st.seq_id for st in eng._active.values()} == {s_old}
        _drive(eng, [s_old, s_young])


# ---------------------------------------------------------------------------
# per-run deadlines: eager reap, same-tick page free
# ---------------------------------------------------------------------------


class TestDeadlines:
    def test_active_expiry_frees_pages_same_tick(self, setup):
        """A deadline that passes mid-decode surfaces an ``expired``
        result on the very NEXT step call, and that same tick returns the
        sequence's pages to the allocator — expired work never squats on
        KV while live traffic queues."""
        cfg, params, tok = setup
        eng = make_engine(cfg, _ecfg(), params, tok, use_kernel=False)
        clk = _Clock()
        eng.clock = clk
        s1 = eng.submit(tok.encode(PROMPTS[0]), deadline_s=5.0)
        s2 = eng.submit(tok.encode(PROMPTS[1]))
        eng.step()
        free_before = eng.allocator.n_free
        clk.t = 10.0
        res = eng.step()
        exp = [r for r in res if r.seq_id == s1]
        assert exp and exp[0].finish_reason == "expired"
        assert eng.allocator.n_free > free_before
        out = {r.seq_id: r for r in res}
        while eng.has_work:
            for r in eng.step():
                out[r.seq_id] = r
        assert out[s2].finish_reason in ("stop", "length")
        eng.allocator.check()
        assert eng.allocator.n_free == eng.engine_cfg.num_pages - 1

    def test_pending_expiry_never_admits(self, setup):
        """A queued sequence whose deadline passes before admission is
        reaped from the queue — zero prefill work spent on it."""
        cfg, params, tok = setup
        eng = make_engine(cfg, _ecfg(max_batch=1), params, tok,
                          use_kernel=False)
        clk = _Clock()
        eng.clock = clk
        s1 = eng.submit(tok.encode(PROMPTS[0]))
        s2 = eng.submit(tok.encode(PROMPTS[1]), deadline_s=3.0)
        eng.step()
        clk.t = 4.0
        out = {}
        while eng.has_work:
            for r in eng.step():
                out[r.seq_id] = r
        assert out[s2].finish_reason == "expired"
        assert out[s2].completion_tokens == 0
        assert out[s1].finish_reason in ("stop", "length")
        eng.allocator.check()
        assert eng.allocator.n_free == eng.engine_cfg.num_pages - 1

    def test_expired_spilled_record_is_dropped(self, setup):
        """Deadline reap of a SPILLED (preempted, waiting) sequence must
        free its host record and shared-prefix refs, not just its queue
        entry."""
        cfg, params, tok = setup
        eng = make_engine(cfg, _ecfg(max_batch=1, max_spilled_pages=64),
                          params, tok, use_kernel=False)
        clk = _Clock()
        eng.clock = clk
        s1 = eng.submit(tok.encode(PROMPTS[1]), deadline_s=5.0)
        eng.step()
        eng.step()
        assert eng._preempt_victim()
        assert s1 in eng._spilled
        clk.t = 10.0
        out = {}
        while eng.has_work:
            for r in eng.step():
                out[r.seq_id] = r
        assert out[s1].finish_reason == "expired"
        assert not eng._spilled and eng._spilled_pages_total == 0
        eng.allocator.check()
        assert eng.allocator.n_free == eng.engine_cfg.num_pages - 1


# ---------------------------------------------------------------------------
# composition: snapshot/restore while spilled
# ---------------------------------------------------------------------------


class TestSnapshotComposition:
    def test_snapshot_while_spilled_restores_byte_identical(self, setup):
        """A spilled sequence sits in _pending, so snapshot_sequences
        captures it (with priority + absolute deadline); restored on a
        FRESH engine it re-prefills and finishes byte-identical, and the
        abandoned donor engine still cancels back to a clean allocator."""
        cfg, params, tok = setup
        ecfg = _ecfg(max_batch=1, max_spilled_pages=32)

        eng0 = make_engine(cfg, ecfg, params, tok, use_kernel=False)
        s0 = eng0.submit(tok.encode(PROMPTS[1]), priority=2,
                         deadline_s=99.0)
        (base,) = _drive(eng0, [s0])

        e1 = make_engine(cfg, ecfg, params, tok, use_kernel=False)
        s1 = e1.submit(tok.encode(PROMPTS[1]), priority=2, deadline_s=99.0)
        e1.step()
        e1.step()
        assert e1._preempt_victim()
        assert e1._spilled
        snap = e1.snapshot_sequences()
        (entry,) = snap["sequences"]
        assert entry["priority"] == 2
        assert entry["deadline"] is not None

        e2 = make_engine(cfg, ecfg, params, tok, use_kernel=False)
        e2.restore_sequences(snap)
        assert e2._deadlines, "deadline must survive restore"
        out = None
        while e2.has_work:
            for r in e2.step():
                out = (r.token_ids, r.finish_reason)
        assert out == base

        e1.cancel_seq(s1)
        e1.allocator.check()
        assert e1.allocator.n_free == ecfg.num_pages - 1
        assert not e1._spilled and e1._spilled_pages_total == 0


# ---------------------------------------------------------------------------
# loud exclusions
# ---------------------------------------------------------------------------


class TestExclusions:
    def test_contiguous_engine_rejects_spill(self, setup):
        cfg, params, tok = setup
        with pytest.raises(ValueError, match="paged"):
            make_engine(cfg, EngineConfig(
                max_batch=2, max_seq_len=128, prefill_buckets=(64, 128),
                max_new_tokens=8, temperature=0.0,
                max_spilled_pages=8), params, tok)

    def test_negative_budget_rejects(self, setup):
        cfg, params, tok = setup
        with pytest.raises(ValueError, match="must be >= 0"):
            make_engine(cfg, _ecfg(max_spilled_pages=-1), params, tok,
                        use_kernel=False)

    def test_cp_mesh_rejects_spill(self, setup, cpu_devices):
        from k8s_llm_rca_tpu.runtime.mesh import build_mesh

        cfg, params, tok = setup
        mesh = build_mesh(MeshConfig(seq=2), devices=cpu_devices[:2])
        with pytest.raises(ValueError, match="cp_mesh"):
            make_engine(cfg, _ecfg(max_spilled_pages=8), params, tok,
                        use_kernel=False, cp_mesh=mesh)

    def test_pp_mesh_rejects_spill(self, setup, cpu_devices):
        from k8s_llm_rca_tpu.runtime.mesh import build_mesh

        cfg, params, tok = setup
        mesh = build_mesh(MeshConfig(stage=2), devices=cpu_devices[:2])
        with pytest.raises(ValueError, match="pp_mesh"):
            make_engine(cfg, _ecfg(max_spilled_pages=8), params, tok,
                        use_kernel=False, pp_mesh=mesh)


# ---------------------------------------------------------------------------
# serve layer: EXPIRED terminal status, journal/recover agreement
# ---------------------------------------------------------------------------


class TestServeDeadlines:
    def test_run_expires_and_recovery_agrees(self, setup, tmp_path):
        """GenOptions.deadline_s flows into the engine reap; the run
        settles EXPIRED (typed terminal status, pages freed), the journal
        records it, and recovery replays EXPIRED verbatim — an expired
        run is never resurrected."""
        from k8s_llm_rca_tpu.faults.plan import VirtualClock
        from k8s_llm_rca_tpu.serve.api import AssistantService, RunStatus
        from k8s_llm_rca_tpu.serve.backend import (EngineBackend,
                                                   GenOptions, Priority)
        from k8s_llm_rca_tpu.serve.journal import RunJournal
        from k8s_llm_rca_tpu.serve.recover import recover_service

        cfg, params, tok = setup
        ecfg = _ecfg(max_new_tokens=200, max_spilled_pages=32)
        path = str(tmp_path / "serve.wal")

        eng = make_engine(cfg, ecfg, params, tok, use_kernel=False)
        clk = VirtualClock()
        eng.clock = clk
        svc = AssistantService(EngineBackend(eng), run_timeout_s=600.0,
                               clock=clk, journal=RunJournal(path))
        a = svc.create_assistant("analyze", "rca", model="tiny",
                                 gen=GenOptions(max_new_tokens=120))
        th = svc.create_thread()
        svc.add_message(th.id, "kubelet crashloop burning pages")
        run = svc.create_run(th.id, a.id, gen=GenOptions(
            max_new_tokens=120, deadline_s=0.5, priority=Priority.BATCH))
        assert eng._deadlines and len(eng._deadlines) == 1
        svc.retrieve_run(run.id)
        clk.sleep(1.0)
        r = svc.retrieve_run(run.id)
        assert r.status == RunStatus.EXPIRED
        assert "deadline" in (r.error or "")
        assert not eng.has_work
        eng.allocator.check()
        assert eng.allocator.n_free == ecfg.num_pages - 1
        svc._journal.close()

        eng2 = make_engine(cfg, ecfg, params, tok, use_kernel=False)
        clk2 = VirtualClock()
        eng2.clock = clk2
        svc2, report = recover_service(path, EngineBackend(eng2),
                                       run_timeout_s=600.0, clock=clk2)
        assert svc2.runs[run.id].status == RunStatus.EXPIRED
        assert not report["resubmitted"]


# ---------------------------------------------------------------------------
# cluster: priority-tiered shedding under saturation
# ---------------------------------------------------------------------------


@pytest.mark.cluster
class TestClusterSaturation:
    def test_batch_sheds_first_critical_always_completes(self):
        from k8s_llm_rca_tpu.faults.soak import run_saturation_scenario

        sat = run_saturation_scenario(n_replicas=2, max_inflight=2,
                                      n_requests=12)
        assert sat["shed_by_class"][0] == 0, "CRITICAL must never shed"
        assert sat["admitted_by_class"][0] == 4
        assert sat["shed_by_class"][2] >= sat["shed_by_class"][1]
        first_shed = next(o for o in sat["outcomes"] if not o["admitted"])
        assert first_shed["priority"] == 2, "BATCH sheds first"
        assert sat["completed"] == sum(sat["admitted_by_class"].values())
        for o in sat["outcomes"]:
            if not o["admitted"]:
                assert o["error"] == "RouterAdmissionError"
                assert "priority" in o["detail"]


# ---------------------------------------------------------------------------
# chaos soak: spill on/off byte-identity under scheduled faults
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestOverloadSoak:
    def _identity(self, n_runs):
        from k8s_llm_rca_tpu.faults.soak import (report_bytes,
                                                 run_overload_soak)

        on = run_overload_soak(seed=0, n_runs=n_runs, spill=True)
        off = run_overload_soak(seed=0, n_runs=n_runs, spill=False)
        assert report_bytes(on["report"]) == report_bytes(off["report"])
        assert on["stats"]["spilled_pages"] > 0
        assert on["stats"]["restored_pages"] > 0
        assert off["stats"]["spilled_pages"] == 0
        assert on["stats"]["engine_clean"]
        assert off["stats"]["engine_clean"]
        by_status = on["report"]["by_status"]
        assert sum(by_status.values()) == n_runs

    def test_soak_report_identical_spill_on_vs_off(self):
        """Preempt/oom fault schedule against a deep mixed-priority
        queue: the outcome report (per-run priority, finish reason, text,
        token count) is byte-identical whether preemption spills KV or
        re-prefills — sized to the tier-1 budget."""
        self._identity(24)

    @pytest.mark.slow
    def test_soak_100_incidents(self):
        """The full 100-incident soak from the issue spec."""
        self._identity(100)
