"""End-to-end RCA pipeline tests — hermetic: in-memory graphs + scripted
oracle backend (BASELINE config[0]-style slice, no weights, no network)."""

import json

import pytest

from k8s_llm_rca_tpu.config import RCAConfig
from k8s_llm_rca_tpu.graph import InMemoryGraphExecutor
from k8s_llm_rca_tpu.graph.fixtures import (
    INCIDENTS, build_metagraph, build_stategraph,
)
from k8s_llm_rca_tpu.rca import RCAPipeline
from k8s_llm_rca_tpu.rca.cyphergen import (
    compile_metapath_query, parse_metapath_string,
)
from k8s_llm_rca_tpu.rca.oracle import OracleBackend
from k8s_llm_rca_tpu.serve.api import AssistantService
from k8s_llm_rca_tpu.utils import get_tokenizer


def make_pipeline(chaos=None) -> RCAPipeline:
    service = AssistantService(OracleBackend(get_tokenizer(), chaos=chaos))
    return RCAPipeline(
        service=service,
        meta_executor=InMemoryGraphExecutor(build_metagraph()),
        state_executor=InMemoryGraphExecutor(build_stategraph()),
        cfg=RCAConfig(),
    )


@pytest.fixture(scope="module")
def pipeline():
    return make_pipeline()


@pytest.mark.parametrize("incident", INCIDENTS, ids=lambda i: i.name)
def test_incident_end_to_end(pipeline, incident):
    result = pipeline.analyze_incident(incident.message)

    assert result["error_message"] == incident.message
    assert result["locator_attempts"] == 1
    assert result["time_cost"] > 0
    assert result["token_usage"]["total_tokens"] > 0
    assert result["analysis"], "no metapath produced an analysis"

    analysis = result["analysis"][0]
    assert "HasEvent, Event, EVENT, metadata_uid;" in analysis["extend_metapath"]
    assert analysis["statepath"], "no statepath records audited"

    sp = analysis["statepath"][0]
    report = json.loads(sp["report"])          # oracle emits strict JSON
    assert {"summary", "conclusion", "resolution"} <= set(report)
    assert "kubectl" in report["resolution"]

    clue_text = json.dumps(sp["clue"])
    for kind in incident.expect_missing_state:
        assert "there is not a STATE" in clue_text
        # the missing kind scores high in the summary
        scores = {s["kind"]: s["relevance_score"] for s in report["summary"]}
        assert scores.get(kind) == "9", scores
    audited = set(sp["clue"].keys())
    for kind in incident.expect_state_kinds:
        assert any(k.startswith(f"{kind}(") for k in audited), (kind, audited)


def test_fresh_threads_bound_prompt_growth():
    """cfg.fresh_threads re-anchors each incident on fresh, re-seeded
    stage threads: the locator's prompt size stays flat across a sweep
    (the reference-style shared thread grows monotonically and overflows
    a real engine's cache budget), while reports stay intact."""
    grown = make_pipeline()
    fresh = make_pipeline()
    fresh.cfg = RCAConfig(fresh_threads=True)

    def locator_prompts(p):
        svc = p.service
        runs = [r for r in svc.runs.values()
                if r.assistant_id == p.locator.assistant.id]
        return [r.usage["prompt_tokens"] for r in
                sorted(runs, key=lambda r: int(r.id.split("_")[1]))]

    m = INCIDENTS[0].message           # same incident: prompt size is then
    for _ in range(4):                 # a pure function of thread growth
        r_grown = grown.analyze_incident(m)
        r_fresh = fresh.analyze_incident(m)
        assert r_fresh["analysis"]
        # same analysis content either way: prompts are self-contained
        assert len(r_fresh["analysis"]) == len(r_grown["analysis"])
    pg, pf = locator_prompts(grown), locator_prompts(fresh)
    assert pg[-1] > pg[0], "shared thread should grow across incidents"
    assert pf == [pf[0]] * len(pf), \
        f"fresh threads should stay exactly flat, got {pf}"


def test_decoy_record_is_filtered(pipeline):
    """Incident 1 matches two Secrets; message compatibility must drop the
    decoy (reference :88-129)."""
    result = pipeline.analyze_incident(INCIDENTS[0].message)
    statepaths = result["analysis"][0]["statepath"]
    assert len(statepaths) == 1
    assert "Secret(sec-0001)" in statepaths[0]["clue"]
    assert "sec-0002" not in json.dumps(statepaths[0]["clue"])


def test_chaos_retry_with_feedback():
    """First oracle replies are malformed: the locator retries with the
    exception text fed back; the cypher stage falls back to the
    deterministic compiler.  The incident must still complete."""
    pipeline = make_pipeline(chaos={"plan": 1})
    result = pipeline.analyze_incident(INCIDENTS[0].message)
    assert result["locator_attempts"] == 2
    assert result["analysis"][0]["statepath"]
    # the feedback message is in the locator thread
    thread_text = " ".join(
        m.raw_content for m in pipeline.locator.thread.messages)
    assert "JSON Error occurred" in thread_text


def test_chaos_cypher_fallback():
    """Chaos hits planning once, then the cypher generator once: the
    deterministic compiler must still produce records."""
    pipeline = make_pipeline(chaos={"plan": 1, "cypher": 1})
    result = pipeline.analyze_incident(INCIDENTS[1].message)
    analysis = result["analysis"][0]
    assert analysis["cypher_attempts"] > 1 or "human_cypher_query" in analysis
    assert analysis["statepath"]


def test_deterministic_compiler_golden():
    metapath = """
    HasEvent, Event, EVENT, metadata_uid;
    ReferInternal, Event, Pod, involvedObject_uid;
    ReferInternal, Pod, Secret, spec_volumes_secret_secretName;
    """
    q = compile_metapath_query(metapath, 'secret "x" not found')
    assert q.splitlines()[0] == "MATCH (evt:EVENT)"
    assert "WHERE evt.message CONTAINS 'secret \"x\" not found'" in q
    assert "MATCH (n1:Event)-[r1:HasEvent]->(evt:EVENT)" in q
    assert "WHERE r2.key = 'involvedObject_uid'" in q
    assert q.rstrip().endswith("RETURN evt, r1, n1, r2, n2, r3, n3")


def test_metapath_string_roundtrip():
    edges = parse_metapath_string(
        "HasEvent, Event, EVENT, metadata_uid; "
        "ReferInternal, Event, Pod, involvedObject_uid;")
    assert edges == [
        ["HasEvent", "Event", "EVENT", "metadata_uid"],
        ["ReferInternal", "Event", "Pod", "involvedObject_uid"]]


def test_pipeline_on_real_engine_backend_is_crash_safe():
    """Chaos: the full pipeline driven by the REAL inference engine with
    random weights and grammar-constrained JSON.  Random weights produce
    valid-but-meaningless JSON, so the run must either complete with the
    result schema or exhaust its retry budget with the reference's
    RuntimeError — never hang, corrupt engine state, or die on a parse.
    """
    import jax

    from k8s_llm_rca_tpu.config import TINY, EngineConfig, RCAConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.graph import InMemoryGraphExecutor
    from k8s_llm_rca_tpu.graph.fixtures import (
        INCIDENTS, build_metagraph, build_stategraph,
    )
    from k8s_llm_rca_tpu.models import llama
    from k8s_llm_rca_tpu.rca import RCAPipeline
    from k8s_llm_rca_tpu.serve.api import AssistantService
    from k8s_llm_rca_tpu.serve.backend import EngineBackend
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=512)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    engine = make_engine(
        cfg, EngineConfig(max_batch=2, max_seq_len=512, paged=True,
                          page_size=16, num_pages=256,
                          prefill_buckets=(128, 256, 512),
                          max_new_tokens=48, temperature=0.0),
        params, tok, use_kernel=False)
    pipeline = RCAPipeline(
        AssistantService(EngineBackend(engine)),
        InMemoryGraphExecutor(build_metagraph()),
        InMemoryGraphExecutor(build_stategraph()),
        RCAConfig())
    try:
        result = pipeline.analyze_incident(INCIDENTS[0].message)
        # completed despite a nonsense model: schema must hold
        assert "error_message" in result and "time_cost" in result
        assert "locator_attempts" in result
    except RuntimeError as e:
        # reference behavior: budget exhausted after retry-with-feedback
        assert "attempts" in str(e)
    # engine state stays clean for the next run either way
    engine.allocator.check()
    assert not engine.has_work


@pytest.mark.parametrize("paged", [False, True])
def test_incident_completes_on_engine_backend(paged):
    """VERDICT r1 item 3: the full pipeline on the REAL engine with random
    weights must COMPLETE — not merely fail gracefully.  Stage 1 is
    schema-constrained to the kind vocabulary (structured outputs), so the
    plan always names real kinds; stage 2 falls back to the deterministic
    compiler; stage 3 audits are free text.  Content is garbage, structure
    is valid (the reference needs GPT-4 for the same guarantee,
    find_srckind_metapath_neo4j.py:20-45).  Runs on BOTH engines — the
    paged variant exercises prefix caching (shared audit prefixes) and the
    DFA scan through the whole agent loop."""
    import jax

    from k8s_llm_rca_tpu.config import TINY, EngineConfig, RCAConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.models import llama
    from k8s_llm_rca_tpu.serve.backend import EngineBackend

    cfg = TINY.replace(max_seq_len=4096)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    paged_kw = dict(paged=True, page_size=64, num_pages=420,
                    decode_chunk=8) if paged else {}
    extra = dict(use_kernel=False) if paged else {}
    engine = make_engine(
        cfg, EngineConfig(max_batch=4, max_seq_len=4096,
                          prefill_buckets=(512, 1024, 2048, 4096),
                          max_new_tokens=96, temperature=0.0, **paged_kw),
        params, tok, **extra)
    pipeline = RCAPipeline(
        AssistantService(EngineBackend(engine)),
        InMemoryGraphExecutor(build_metagraph()),
        InMemoryGraphExecutor(build_stategraph()),
        RCAConfig(cypher_max_new_tokens=96, analyzer_max_new_tokens=96))

    result = pipeline.analyze_incident(INCIDENTS[0].message)

    # structured stage 1 must succeed on the FIRST attempt: no JSON retry
    assert result["locator_attempts"] == 1
    assert result["error_message"] == INCIDENTS[0].message
    assert result["time_cost"] > 0
    assert result["token_usage"]["total_tokens"] > 0
    # the plan's DestinationKind was vocabulary-constrained, so the metapath
    # ladder ran; whatever it matched carries the full analysis schema
    for analysis in result["analysis"]:
        assert "extend_metapath" in analysis
        # stage 2 is skeleton-grammar-constrained (cypher_query_schema):
        # even random weights emit a valid query on the FIRST attempt, so
        # the reference's retry loop (test_all.py:99-122) is dead code here
        # like stage 1's.  (The zero-record fallback can still fire for
        # metapaths that genuinely match nothing — it then compiles the
        # SAME skeleton, so it must agree with the generated query.)
        assert analysis["cypher_attempts"] == 1
        assert analysis["cypher_query"] is not None
        if "human_cypher_query" in analysis:
            from k8s_llm_rca_tpu.rca import cyphergen as _cg

            assert analysis["cypher_query"] in (
                _cg.compile_metapath_query(
                    analysis["extend_metapath"], result["error_message"],
                    alias_style=s, quiet=True)
                for s in ("numeric", "kind"))
        for audited in analysis["statepath"]:
            # the reporter's schema grammar guarantees the report parses in
            # the reference shape even from random weights
            report = json.loads(audited["report"])
            assert {"summary", "conclusion", "resolution"} <= set(report)
            for item in report["summary"]:
                assert item["relevance_score"] in {str(i) for i in range(11)}
            assert isinstance(audited["clue"], dict)
    assert not engine.has_work
    if paged:
        engine.allocator.check()       # allocator-internal invariants
        # true no-leak check: after drain, every owned page belongs to the
        # prefix cache (retired sequences freed or transferred theirs)
        resident = engine.prefix_cache.n_resident if engine.prefix_cache \
            else 0
        assert engine.allocator.n_free + resident \
            == engine.engine_cfg.num_pages - 1


def test_auditor_rejects_label_injection():
    """Cypher can't parameterize labels; kinds interpolated into label
    position must be identifier-whitelisted (VERDICT r1 weak #7)."""
    from k8s_llm_rca_tpu.rca.auditor import (
        ad_hoc_find_entity_name, find_loose_states, find_strict_states,
    )

    for evil in ("Pod) MATCH (x", "Pod:Admin", "Pod`", "", "1Pod",
                 "Pod WITH x"):
        with pytest.raises(ValueError, match="unsafe entity kind"):
            find_strict_states(evil, "id-1", "2020-12-07T01:00:00Z")
        with pytest.raises(ValueError, match="unsafe entity kind"):
            find_loose_states(evil, "id-1", "t0", "t1")
        with pytest.raises(ValueError, match="unsafe entity kind"):
            ad_hoc_find_entity_name(evil, "id-1", None)
    # the whole fixture vocabulary is label-safe
    meta = InMemoryGraphExecutor(build_metagraph())
    from k8s_llm_rca_tpu.rca.locator import find_native_external_kinds
    native, external = find_native_external_kinds(meta)
    for kind in native + external:
        assert "MATCH" in find_strict_states(kind, "x", "t")


def test_cypher_budget_error_skips_retries_to_fallback():
    """A BudgetError (grammar's minimal document exceeds the effective
    budget) is futile to retry — compile_and_run must go STRAIGHT to the
    deterministic fallback on attempt 1 instead of burning the retry
    budget on identical failures."""
    from k8s_llm_rca_tpu.rca import cyphergen
    from k8s_llm_rca_tpu.serve.backend import BudgetError

    class BudgetBackend:
        def start(self, prompt, opts):
            raise BudgetError("budget 4 cannot hold the minimal document")

        def pump(self):
            return {}

        def busy(self, handle):
            return False

        def cancel(self, handle):
            pass

        def count_tokens(self, text):
            return len(text.split())

    pipeline = RCAPipeline.__new__(RCAPipeline)
    pipeline.cfg = RCAConfig()
    pipeline.state_executor = InMemoryGraphExecutor(build_stategraph())
    service = AssistantService(BudgetBackend())
    gen = cyphergen.setup_cypher_generator(service)
    pipeline.cypher_generator = gen

    mp = ("\n    HasEvent, Event, EVENT, metadata_uid;\n"
          "    ReferInternal, Event, Pod, involvedObject_uid;\n")
    analysis = {}
    records = pipeline.compile_and_run(mp, INCIDENTS[0].message, analysis)
    assert analysis["cypher_attempts"] == 1          # no futile retries
    assert "human_cypher_query" in analysis          # fallback fired
    assert isinstance(records, list)
