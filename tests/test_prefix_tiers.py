"""Tiered prefix/KV cache (docs/performance.md "tiered prefix cache"):
HBM (L0) -> host-RAM PrefixStore (L1) -> disk (L2), with cross-replica
warm-start.

The headline invariant is BYTE PARITY: a prompt served from promoted
L1/L2 pages must produce exactly the tokens a cold re-prefill produces —
the promoted page holds the same KV bytes eviction demoted, so the
already-trusted prefix-hit prefill path computes the identical suffix.
Greedy decode makes this checkable without tolerance (temperature=0
argmax depends only on weights and committed KV; same rationale as
tests/test_overload.py).  The matrix composes the tiers with
host_overlap x prefill_chunk_budget x max_spilled_pages, and the
disk-robustness tests prove a torn/corrupt L2 entry is a silent cold
miss, never a crash.

Everything runs on the 8-virtual-device CPU platform the conftest pins;
engines are single-device (the ~10x GSPMD-on-virtual-CPU slowdown makes
sharded engines too slow for a parity matrix — the cluster warm-start
test uses one-device submeshes for the same reason).
"""

import os

import jax
import pytest

from k8s_llm_rca_tpu.config import TINY, EngineConfig, MeshConfig
from k8s_llm_rca_tpu.engine import make_engine
from k8s_llm_rca_tpu.engine.prefix import PrefixStore
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.utils.logging import METRICS
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

pytestmark = pytest.mark.prefix


@pytest.fixture(scope="module")
def setup():
    cfg = TINY.replace(max_seq_len=128)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    return cfg, params, tok


# the RCA-agent shape: one long shared preamble, short per-run suffixes
# (byte-level tokenizer: ~1 token/char; 75-token preamble = 4 full pages
# at page_size=16, and every prompt fits max_seq_len - max_new_tokens)
_PRE = "shared incident preamble " * 3
PROMPTS = (_PRE + "kubelet crashloop on node-7",
           _PRE + "etcd leader lost quorum",
           _PRE + "pvc unbound on nfs chain")


def _ecfg(**over):
    base = dict(max_batch=2, max_seq_len=128, prefill_buckets=(64, 128),
                max_new_tokens=16, temperature=0.0, paged=True,
                page_size=16, num_pages=40, prefix_cache=True,
                decode_chunk=4)
    base.update(over)
    return EngineConfig(**base)


def _drive(eng, sids):
    out = {}
    while eng.has_work:
        for r in eng.step():
            out[r.seq_id] = r
    eng.allocator.check()
    resident = eng.prefix_cache.n_resident if eng.prefix_cache else 0
    assert (eng.allocator.n_free + resident
            == eng.engine_cfg.num_pages - 1)
    return [out[s].token_ids for s in sids]


def _run(eng, tok, prompts=PROMPTS):
    return _drive(eng, [eng.submit(tok.encode(p)) for p in prompts])


# ---------------------------------------------------------------------------
# tentpole: tiered parity matrix (cold vs L0 vs L1 vs L2 vs legacy)
# ---------------------------------------------------------------------------


class TestTieredParity:
    # tier shape x engine features; "disk" swaps in a tmp_path L2 dir
    MATRIX = {
        "l1": dict(prefix_host_pages=64),
        "l1_small": dict(prefix_host_pages=4),      # L1 overflow drops (no L2)
        "l2_only": dict(prefix_host_pages=0, disk=True),
        "l1_l2": dict(prefix_host_pages=4, disk=True),
        "l1_overlap": dict(prefix_host_pages=64, decode_chunk=1,
                           host_overlap=True),
        "l1_chunked": dict(prefix_host_pages=64, prefill_chunk_budget=32),
        "l1_spill": dict(prefix_host_pages=64, max_spilled_pages=64),
        "l1_all": dict(prefix_host_pages=64, decode_chunk=1,
                       host_overlap=True, prefill_chunk_budget=32,
                       max_spilled_pages=64),
    }

    @pytest.mark.parametrize("feature", sorted(MATRIX))
    def test_demote_promote_byte_parity(self, setup, tmp_path, feature):
        """Run shared-preamble prompts cold, demote EVERY resident page
        (evict with a store attached), re-run: outputs must be
        byte-identical to a legacy (discarding) engine's, and the tier
        counters must prove pages actually moved d2h and back."""
        cfg, params, tok = setup
        kw = dict(self.MATRIX[feature])
        if kw.pop("disk", False):
            kw["prefix_disk_dir"] = str(tmp_path / "l2")
        feature_kw = {k: v for k, v in kw.items()
                      if not k.startswith("prefix_")}

        legacy = make_engine(cfg, _ecfg(**feature_kw), params, tok,
                             use_kernel=False)
        cold = _run(legacy, tok)
        assert legacy.prefix_cache.evict(10 ** 6) > 0   # legacy discard
        assert _run(legacy, tok) == cold                # re-prefill parity

        eng = make_engine(cfg, _ecfg(**kw), params, tok, use_kernel=False)
        assert _run(eng, tok) == cold                   # tiers off hot path
        assert eng.prefix_cache.evict(10 ** 6) > 0      # demote everything
        assert _run(eng, tok) == cold                   # promoted parity
        c = eng._counts or {}
        assert c.get("engine.prefix_demotions", 0) > 0
        hits = (c.get("engine.prefix_hits_l1", 0)
                + c.get("engine.prefix_hits_l2", 0))
        if feature == "l1_small":
            # the 4-page L1 (no disk) dropped most demoted pages; hits
            # depend on whether the chain HEADS survived the LRU, so only
            # parity is guaranteed here — the dropped-page path IS the test
            return
        assert hits > 0, c
        assert c.get("engine.prefix_promoted_pages", 0) == hits
        assert c.get("engine.prefix_bytes_restored", 0) > 0

    def test_l2_hits_after_l1_overflow(self, setup, tmp_path):
        """With a tiny L1, demotion overflows the early-chain pages to
        disk; a full re-run must still promote every page byte-
        identically.  The chain walk runs head->tail and each disk hit
        re-admits into the 2-page L1 (churning the old residents back
        out), so the hits legitimately read as L2 — the assertion is
        that the DISK tier carried the promotion, with nothing lost."""
        cfg, params, tok = setup
        eng = make_engine(
            cfg, _ecfg(prefix_host_pages=2,
                       prefix_disk_dir=str(tmp_path / "l2")),
            params, tok, use_kernel=False)
        cold = _run(eng, tok)
        eng.prefix_cache.evict(10 ** 6)
        assert eng.prefix_store.n_host == 2
        assert eng.prefix_store.n_disk > 0
        assert _run(eng, tok) == cold
        c = eng._counts or {}
        assert c.get("engine.prefix_hits_l2", 0) > 0
        hits = (c.get("engine.prefix_hits_l1", 0)
                + c.get("engine.prefix_hits_l2", 0))
        assert c.get("engine.prefix_promoted_pages", 0) == hits > 0

    def test_promotion_skipped_under_page_pressure(self, setup):
        """Promotion allocates WITHOUT evicting: when the pool is too
        full to host promoted pages the match quietly degrades to a
        cold re-prefill — never an error, still byte-identical."""
        cfg, params, tok = setup
        # pages_per_seq = 8, num_pages 9 = one sequence + trash: admission
        # drains the pool completely, so promotion can never allocate
        ecfg = _ecfg(max_batch=1, num_pages=9, prefix_host_pages=64)
        eng = make_engine(cfg, ecfg, params, tok, use_kernel=False)
        legacy = make_engine(
            cfg, _ecfg(max_batch=1, num_pages=9), params, tok,
            use_kernel=False)
        cold = _run(legacy, tok)
        assert _run(eng, tok) == cold
        eng.prefix_cache.evict(10 ** 6)
        assert _run(eng, tok) == cold
        eng.allocator.check()


# ---------------------------------------------------------------------------
# disk tier robustness: torn/corrupt entries are silent cold misses
# ---------------------------------------------------------------------------


class TestDiskRobustness:
    def _populated_dir(self, setup, tmp_path):
        cfg, params, tok = setup
        d = str(tmp_path / "l2")
        eng = make_engine(cfg, _ecfg(prefix_host_pages=0,
                                     prefix_disk_dir=d),
                          params, tok, use_kernel=False)
        cold = _run(eng, tok)
        eng.prefix_cache.evict(10 ** 6)
        entries = sorted(f for f in os.listdir(d) if f.endswith(".page"))
        assert entries
        return cfg, params, tok, d, cold, entries

    def test_corrupt_and_torn_entries_fall_back_cold(self, setup,
                                                     tmp_path):
        """Flip bytes in one entry, truncate another mid-frame: a fresh
        store re-indexes all of them, the CRC/torn-frame checks reject
        the damaged two at load, and the run still matches the cold
        output byte-for-byte (damaged pages simply re-prefill)."""
        cfg, params, tok, d, cold, entries = self._populated_dir(
            setup, tmp_path)
        with open(os.path.join(d, entries[0]), "r+b") as f:
            f.seek(12)
            f.write(b"\xa5\x5a\xa5\x5a")
        size = os.path.getsize(os.path.join(d, entries[1]))
        with open(os.path.join(d, entries[1]), "r+b") as f:
            f.truncate(size // 2)
        eng = make_engine(cfg, _ecfg(), params, tok, use_kernel=False,
                          prefix_store=PrefixStore(disk_dir=d))
        assert _run(eng, tok) == cold
        # damaged entries are dropped lazily (on first touch), never
        # crash the index; whatever the chain walk reached stays <= all
        assert eng.prefix_store.n_disk <= len(entries)

    def test_restart_reindexes_and_serves_l2(self, setup, tmp_path):
        """A brand-new PrefixStore pointed at the surviving directory
        (process restart) serves the same bytes from disk."""
        cfg, params, tok, d, cold, entries = self._populated_dir(
            setup, tmp_path)
        store = PrefixStore(host_pages=0, disk_dir=d)
        assert store.n_disk == len(entries)
        eng = make_engine(cfg, _ecfg(), params, tok, use_kernel=False,
                          prefix_store=store)
        assert _run(eng, tok) == cold
        assert (eng._counts or {}).get("engine.prefix_hits_l2", 0) > 0

    def test_foreign_files_ignored(self, tmp_path):
        d = str(tmp_path / "l2")
        os.makedirs(d)
        for name in ("notes.txt", "zzzz.page"):    # zzzz: non-hex digest
            with open(os.path.join(d, name), "w") as f:
                f.write("not a page record")
        assert PrefixStore(disk_dir=d).n_disk == 0

    def test_disk_cap_drops_oldest(self, setup, tmp_path):
        cfg, params, tok = setup
        d = str(tmp_path / "l2")
        eng = make_engine(cfg, _ecfg(prefix_host_pages=0,
                                     prefix_disk_dir=d,
                                     prefix_disk_pages=3),
                          params, tok, use_kernel=False)
        cold = _run(eng, tok)
        demoted = eng.prefix_cache.evict(10 ** 6)
        assert demoted > 3
        assert eng.prefix_store.n_disk == 3
        assert len([f for f in os.listdir(d) if f.endswith(".page")]) == 3
        # capped tier still serves what it kept; the rest re-prefills
        assert _run(eng, tok) == cold


# ---------------------------------------------------------------------------
# budget separation: store caps never interact with the spill budget
# ---------------------------------------------------------------------------


class TestBudgetSeparation:
    def test_demotions_do_not_consume_spill_budget(self, setup):
        """A store holding far more pages than max_spilled_pages must
        not trip the spill budget: demoted PREFIX pages are accounted by
        prefix_host_pages only, and _spilled_pages_total tracks spilled
        RUN pages only."""
        cfg, params, tok = setup
        eng = make_engine(cfg, _ecfg(prefix_host_pages=64,
                                     max_spilled_pages=2),
                          params, tok, use_kernel=False)
        _run(eng, tok)
        demoted = eng.prefix_cache.evict(10 ** 6)
        assert demoted > 2                       # exceeds the spill cap
        assert eng.prefix_store.n_host == demoted
        assert eng._spilled_pages_total == 0
        c = eng._counts or {}
        assert c.get("engine.spill_budget_fallbacks", 0) == 0
        assert c.get("engine.spilled_pages", 0) == 0

    def test_spill_parity_with_full_store(self, setup):
        """Forced preemption with spill enabled while the tiers are
        configured: the spill path still runs (its budget untouched by
        the store knobs) and outputs stay byte-identical to the
        re-prefill-fallback run."""
        cfg, params, tok = setup

        def _forced(ecfg):
            eng = make_engine(cfg, ecfg, params, tok, use_kernel=False)
            sids = [eng.submit(tok.encode(p), priority=pri)
                    for p, pri in zip(PROMPTS, (1, 2, 0))]
            out, tick = {}, 0
            while eng.has_work:
                if tick == 2:
                    assert eng._preempt_victim()
                for r in eng.step():
                    out[r.seq_id] = r
                tick += 1
            eng.allocator.check()
            return [out[s].token_ids for s in sids], dict(eng._counts or {})

        base, _ = _forced(_ecfg(max_spilled_pages=0))
        tiered, c = _forced(_ecfg(max_spilled_pages=64,
                                  prefix_host_pages=64))
        assert base == tiered
        assert c.get("engine.spilled_pages", 0) > 0
        assert c.get("engine.spill_budget_fallbacks", 0) == 0


# ---------------------------------------------------------------------------
# cross-replica warm-start (cluster/replica.py prefix_store=...)
# ---------------------------------------------------------------------------


class TestWarmStart:
    def test_shared_store_warm_starts_fresh_replica(self, setup,
                                                    cpu_devices):
        """Replica 0 serves a shared-preamble wave and flushes its
        resident pages; a FRESH replica sharing the store must emit
        byte-identical tokens while provably prefilling less (fewer
        engine.prefill dispatches, fewer prefill tokens, L1 hits > 0)."""
        from k8s_llm_rca_tpu.cluster.replica import build_replicas

        cfg, params, tok = setup
        store = PrefixStore(host_pages=256)
        # chunked prefill makes "dispatches saved" a robust signal: the
        # number of engine.prefill spans scales with prefilled TOKENS
        # (ceil(len/budget) chunks per admission), so promoted pages
        # provably remove whole chunks, not just shrink one bucket
        replicas = build_replicas(cfg, _ecfg(prefill_chunk_budget=32), 2,
                                  devices=cpu_devices[:2],
                                  prefix_store=store, use_kernel=False)
        eng0 = replicas[0].backend.engine
        eng1 = replicas[1].backend.engine
        assert eng0.prefix_store is store and eng1.prefix_store is store

        def _prefills(fn):
            # prefill dispatches = direct prefill spans + chunk spans
            def n():
                snap = METRICS.snapshot()
                return (snap.get("engine.prefill.count", 0)
                        + snap.get("engine.tick.prefill_chunk.count", 0))

            before = n()
            out = fn()
            return out, n() - before

        cold, cold_prefills = _prefills(lambda: _run(eng0, tok))
        assert eng0.flush_prefix_store() > 0
        warm, warm_prefills = _prefills(lambda: _run(eng1, tok))
        assert warm == cold                      # byte-identical reports
        assert warm_prefills < cold_prefills     # dispatches actually saved
        c1 = eng1._counts or {}
        assert c1.get("engine.prefix_hits_l1", 0) > 0
        assert (c1.get("engine.prefill_tokens", 0)
                < (eng0._counts or {}).get("engine.prefill_tokens", 1))

    def test_supervisor_restart_inherits_store(self, setup, cpu_devices):
        """The rebuild recipe build_replicas records threads the SHARED
        store through engine_kw, so a supervisor-restarted incarnation
        warm-starts too (PR 9 restart path)."""
        from k8s_llm_rca_tpu.cluster.replica import build_replicas

        cfg, params, tok = setup
        store = PrefixStore(host_pages=256)
        (replica,) = build_replicas(cfg, _ecfg(), 1,
                                    devices=cpu_devices[:1],
                                    prefix_store=store, use_kernel=False)
        cold = _run(replica.backend.engine, tok)
        assert replica.backend.engine.flush_prefix_store() > 0
        rebuilt = replica.rebuild()
        assert rebuilt.engine.prefix_store is store
        assert _run(rebuilt.engine, tok) == cold
        assert (rebuilt.engine._counts or {}).get(
            "engine.prefix_hits_l1", 0) > 0

    def test_seeded_wave_warm_start_sweep(self, setup):
        """Scaled-down acceptance sweep (the 100-incident version runs in
        bench_prefix_leg): a seeded wave of shared-preamble incidents on
        a warm-started engine is byte-identical to the cold run with
        counter-proven prefill reduction."""
        import random

        cfg, params, tok = setup
        rng = random.Random(0)
        causes = ("oom", "dns", "quota", "netpol", "pv chain", "kubelet")
        wave = [_PRE + f"incident {i}: {rng.choice(causes)}"
                for i in range(6)]

        cold_eng = make_engine(cfg, _ecfg(), params, tok,
                               use_kernel=False)
        cold = _run(cold_eng, tok, wave)

        store = PrefixStore(host_pages=256)
        src = make_engine(cfg, _ecfg(), params, tok, use_kernel=False,
                          prefix_store=store)
        _run(src, tok, wave[:2])
        assert src.flush_prefix_store() > 0
        warm_eng = make_engine(cfg, _ecfg(), params, tok,
                               use_kernel=False, prefix_store=store)
        assert _run(warm_eng, tok, wave) == cold
        cw, cc = warm_eng._counts or {}, cold_eng._counts or {}
        assert cw.get("engine.prefix_hits_l1", 0) > 0
        assert (cw.get("engine.prefill_tokens", 0)
                < cc.get("engine.prefill_tokens", 0))


# ---------------------------------------------------------------------------
# snapshot/restore seam: the "mostly-HIT re-prefill" upgrades to
# restore-by-pages when a shared store holds the chains
# ---------------------------------------------------------------------------


class TestSnapshotRestoreByPages:
    def test_restore_into_fresh_engine_promotes_from_store(self, setup):
        """``restore_sequences`` re-admits by re-prefill THROUGH the
        tier-aware match: with the source's chains flushed to a shared
        store (what ``drain_replica`` does before snapshotting), the
        fresh engine's re-prefill becomes h2d page promotion — greedy
        output byte-identical to the uninterrupted run, with L1 hits
        proving pages were restored rather than recomputed."""
        cfg, params, tok = setup
        store = PrefixStore(host_pages=256)

        want = _run(make_engine(cfg, _ecfg(), params, tok,
                                use_kernel=False), tok)

        src = make_engine(cfg, _ecfg(), params, tok, use_kernel=False,
                          prefix_store=store)
        sids = [src.submit(tok.encode(p)) for p in PROMPTS]
        out = {}
        for _ in range(4):                 # interrupt mid-decode
            for r in src.step():
                out[r.seq_id] = r
        assert src.flush_prefix_store() > 0
        snap = src.snapshot_sequences()

        resume = make_engine(cfg, _ecfg(), params, tok,
                             use_kernel=False, prefix_store=store)
        resume.restore_sequences(snap)
        while resume.has_work:
            for r in resume.step():
                out[r.seq_id] = r
        resume.allocator.check()
        assert [out[s].token_ids for s in sids] == want
        assert (resume._counts or {}).get("engine.prefix_hits_l1", 0) > 0


# ---------------------------------------------------------------------------
# acceptance sweep: 100 seeded incidents, warm-started fresh replica
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestAcceptanceSweep:
    def test_100_incident_warm_started_replica_report_bytes(
            self, setup, cpu_devices):
        """The ISSUE acceptance bar: a seeded 100-incident shared-
        preamble sweep where a FRESH replica warm-starts from a store
        its sibling flushed produces ``report_bytes`` byte-identical to
        the all-re-prefill run, with a counter-proven prefill reduction
        (L1 hits > 0, fewer prefill spans than the cold run)."""
        import random

        from k8s_llm_rca_tpu.cluster.replica import build_replicas
        from k8s_llm_rca_tpu.faults.soak import report_bytes

        cfg, params, tok = setup
        rng = random.Random(17)
        causes = ("oom", "dns", "quota", "netpol", "pv chain", "kubelet",
                  "evicted", "taint", "crashloop", "rate limit")
        wave = [_PRE + f"incident {i}: {rng.choice(causes)}"
                for i in range(100)]
        ecfg = _ecfg(prefill_chunk_budget=32)

        def prefill_spans():
            snap = METRICS.snapshot()
            return (snap.get("engine.prefill.count", 0)
                    + snap.get("engine.tick.prefill_chunk.count", 0))

        def sweep(eng):
            before = prefill_spans()
            toks = _run(eng, tok, wave)
            report = {"seed": 17, "n_incidents": len(wave),
                      "incidents": [
                          {"id": i, "token_ids": [int(t) for t in ts]}
                          for i, ts in enumerate(toks)]}
            return report, prefill_spans() - before

        # all-re-prefill baseline (no store: eviction discards)
        cold_report, cold_spans = sweep(
            make_engine(cfg, ecfg, params, tok, use_kernel=False))

        store = PrefixStore(host_pages=2048)
        replicas = build_replicas(cfg, ecfg, 2, devices=cpu_devices[:2],
                                  prefix_store=store, use_kernel=False)
        src = replicas[0].backend.engine
        _run(src, tok, wave[:10])          # sibling serves, then publishes
        assert src.flush_prefix_store() > 0

        warm_eng = replicas[1].backend.engine      # FRESH replica
        warm_report, warm_spans = sweep(warm_eng)

        assert report_bytes(warm_report) == report_bytes(cold_report)
        assert warm_spans < cold_spans
        c = warm_eng._counts or {}
        assert (c.get("engine.prefix_hits_l1", 0)
                + c.get("engine.prefix_hits_l2", 0)) > 0


# ---------------------------------------------------------------------------
# loud exclusions (mirror the spill exclusions, paged.py)
# ---------------------------------------------------------------------------


class TestExclusions:
    def test_contiguous_engine_rejects_tiers(self, setup):
        cfg, params, tok = setup
        with pytest.raises(ValueError, match="paged"):
            make_engine(cfg, EngineConfig(
                max_batch=2, max_seq_len=128, prefill_buckets=(64, 128),
                max_new_tokens=8, temperature=0.0,
                prefix_host_pages=8), params, tok)

    def test_contiguous_engine_rejects_shared_store(self, setup):
        cfg, params, tok = setup
        with pytest.raises(ValueError, match="paged"):
            make_engine(cfg, EngineConfig(
                max_batch=2, max_seq_len=128, prefill_buckets=(64, 128),
                max_new_tokens=8, temperature=0.0), params, tok,
                prefix_store=PrefixStore(host_pages=8))

    def test_cp_mesh_rejects_tiers(self, setup, cpu_devices):
        from k8s_llm_rca_tpu.runtime.mesh import build_mesh

        cfg, params, tok = setup
        mesh = build_mesh(MeshConfig(seq=2), devices=cpu_devices[:2])
        with pytest.raises(ValueError, match="cp_mesh"):
            make_engine(cfg, _ecfg(prefix_host_pages=8), params, tok,
                        use_kernel=False, cp_mesh=mesh)

    def test_pp_mesh_rejects_tiers(self, setup, cpu_devices):
        from k8s_llm_rca_tpu.runtime.mesh import build_mesh

        cfg, params, tok = setup
        mesh = build_mesh(MeshConfig(stage=2), devices=cpu_devices[:2])
        with pytest.raises(ValueError, match="pp_mesh"):
            make_engine(cfg, _ecfg(prefix_host_pages=8), params, tok,
                        use_kernel=False, pp_mesh=mesh)

    def test_negative_and_inconsistent_knobs_reject(self, setup,
                                                    tmp_path):
        cfg, params, tok = setup
        with pytest.raises(ValueError, match="must be >= 0"):
            make_engine(cfg, _ecfg(prefix_host_pages=-1), params, tok,
                        use_kernel=False)
        with pytest.raises(ValueError, match="needs prefix_disk_dir"):
            make_engine(cfg, _ecfg(prefix_disk_pages=4), params, tok,
                        use_kernel=False)
        with pytest.raises(ValueError, match="prefix_cache=True"):
            make_engine(cfg, _ecfg(prefix_cache=False,
                                   prefix_host_pages=8),
                        params, tok, use_kernel=False)

    def test_store_validates_its_own_knobs(self):
        with pytest.raises(ValueError, match="must be >= 0"):
            PrefixStore(host_pages=-1)
        with pytest.raises(ValueError, match="needs disk_dir"):
            PrefixStore(disk_pages=4)


# ---------------------------------------------------------------------------
# store + codec units (no engine)
# ---------------------------------------------------------------------------


class TestStoreAndCodecUnits:
    def _rec(self, fill=1.0):
        import numpy as np

        return {"n_pages": 1,
                "k": np.full((2, 1, 4, 8), fill, np.float32),
                "v": np.full((2, 1, 4, 8), -fill, np.float32)}

    def test_codec_roundtrip_and_rejection(self):
        import numpy as np

        from k8s_llm_rca_tpu.utils.pages import (
            decode_page_record, encode_page_record,
        )

        rec = self._rec()
        frame = encode_page_record(rec)
        back = decode_page_record(frame)
        assert back is not None
        assert np.array_equal(back["k"], rec["k"])
        assert np.array_equal(back["v"], rec["v"])
        assert back["k"].dtype == rec["k"].dtype
        # torn tail and corrupt payload both answer None, never raise
        assert decode_page_record(frame[:-3]) is None
        bad = bytearray(frame)
        bad[-1] ^= 0xFF
        assert decode_page_record(bytes(bad)) is None
        assert decode_page_record(b"") is None
        assert decode_page_record(b"garbage that is not a frame") is None

    def test_l1_lru_and_overflow_order(self, tmp_path):
        d = str(tmp_path / "l2")
        store = PrefixStore(host_pages=2, disk_dir=d)
        store.put(b"a" * 20, self._rec(1))
        store.put(b"b" * 20, self._rec(2))
        got = store.get(b"a" * 20)
        assert got is not None and got[1] == 1    # refreshed: now newest
        store.put(b"c" * 20, self._rec(3))        # overflows LRU "b"
        assert store.n_host == 2 and store.n_disk == 1
        got_b = store.get(b"b" * 20)
        assert got_b is not None and got_b[1] == 2     # served from disk
        assert store.contains(b"c" * 20)

    def test_put_is_idempotent_per_digest(self, tmp_path):
        d = str(tmp_path / "l2")
        store = PrefixStore(host_pages=0, disk_dir=d)
        store.put(b"k" * 20, self._rec())
        mtime = os.path.getmtime(os.path.join(d, ("6b" * 20) + ".page"))
        store.put(b"k" * 20, self._rec())          # digest pins the bytes
        assert os.path.getmtime(
            os.path.join(d, ("6b" * 20) + ".page")) == mtime
        assert store.n_disk == 1
