"""Disaggregated prefill/decode tier tests (cluster/disagg.py).

Layers, cheapest first:

- **TierRouter lifecycle** (in-process echo replicas): admission lands
  on the prefill tier, the EXPORT -> ADOPT -> RELEASE handoff moves
  every run to the decode tier, results match the plain cluster's, and
  failover/drain stay inside the dead replica's own tier.
- **frame faults** (the TierRouter's own SITE_HANDOFF plan): drop,
  corrupt, delay and stale-fence each discard the transfer WHOLE and
  retry — never a half-adopted sequence, never an armed-plan poll.
- **loud exclusions**: empty/overlapping tiers, cp/pp meshes, mixed
  seam/scripted fleets, cross-tier drains, pipelined-sweep-over-disagg,
  overlapping killer sites, and killer refusal messages that name the
  victim's replica id, backend kind and transport.
- **kill windows** (real subprocess workers): a HandoffKiller SIGKILLs
  (or partitions) a tier member exactly between EXPORT and ADOPT; the
  run settles with the correct text, the transfer is counted retried,
  and the watchdog attributes the death to the "handoff" evidence kind.
- **chaos soak** (the ISSUE acceptance bar): 100 incidents on a
  socket-transport disagg fleet with mid-handoff SIGKILLs — report
  bytes identical to the unkilled in-process cluster-oracle run, twice.
- **engine seam** (slow): per-run export/adopt round-trip byte-parity
  across the composition matrix (plain / prefix cache / host overlap /
  chunked prefill / spilled-while-snapshotted), and greedy byte-parity
  of 1P+2D (pipe) and 2P+1D (socket) proc engine tiers vs the plain
  engine.
"""

from __future__ import annotations

import types

import pytest

from k8s_llm_rca_tpu.cluster import (
    HealthPolicy, HealthWatchdog, Replica, ReplicaSupervisor, TierRouter,
)
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.faults.plan import FaultPlan, VirtualClock
from k8s_llm_rca_tpu.serve.backend import EchoBackend, GenOptions
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

pytestmark = pytest.mark.disagg


def _close_all(router) -> None:
    for r in router.replicas.values():
        close = getattr(r, "close", None)
        if close is not None:
            close()


def _settle(router, handles, pumps=64):
    out = {}
    for _ in range(pumps):
        out.update(router.pump())
        if all(h in out for h in handles):
            return out
    raise AssertionError(f"runs never settled: {sorted(out)}")


def _echo_tiers(tok, n_prefill=1, n_decode=1, delay_pumps=2, **kw):
    mk = lambda rid: Replica(rid, EchoBackend(tok,             # noqa: E731
                                              delay_pumps=delay_pumps),
                             rebuild=lambda: EchoBackend(
                                 tok, delay_pumps=delay_pumps))
    return TierRouter([mk(i) for i in range(n_prefill)],
                      [mk(n_prefill + i) for i in range(n_decode)], **kw)


def _watchdog():
    return HealthWatchdog(HealthPolicy(miss_budget=1,
                                       hung_tick_threshold=2),
                          clock=VirtualClock())


def _handoff_plan(indices):
    """A SITE_HANDOFF plan with an explicit per-attempt schedule."""
    return FaultPlan.from_spec(
        0, {inject.SITE_HANDOFF: {"indices": indices}})


def _handoff_killer(indices, **kw):
    from k8s_llm_rca_tpu.faults.supervisor import HandoffKiller

    return HandoffKiller(_handoff_plan(indices), **kw)


# ---------------------------------------------------------------------------
# TierRouter lifecycle (in-process, scripted)
# ---------------------------------------------------------------------------


class TestTierLifecycle:
    def test_run_admits_on_prefill_and_settles_on_decode(self):
        tok = get_tokenizer()
        router = _echo_tiers(tok)
        h = router.start("node notready", GenOptions())
        assert router._handle_map[h][0] == 0          # admitted on prefill
        assert router._handoff_queue == {h: 0}
        out = _settle(router, [h])
        assert out[h].error is None
        assert out[h].text == "echo: node notready"
        assert router.handoffs == 1
        assert router.handoffs_retried == 0
        assert router._handoff_queue == {}            # RELEASEd

    def test_one_prefill_many_decode_balances_adopters(self):
        tok = get_tokenizer()
        router = _echo_tiers(tok, n_prefill=1, n_decode=3, delay_pumps=4)
        handles = [router.start(f"p{i}", GenOptions()) for i in range(6)]
        out = _settle(router, handles)
        assert all(out[h].error is None for h in handles)
        assert router.handoffs == 6
        stats = router.tier_stats()
        assert stats["prefill_replicas"] == 1
        assert stats["decode_replicas"] == 3
        assert stats["pending_handoffs"] == 0

    def test_many_prefill_one_decode_funnels_through(self):
        tok = get_tokenizer()
        router = _echo_tiers(tok, n_prefill=3, n_decode=1, delay_pumps=4)
        handles = [router.start(f"p{i}", GenOptions(session=f"s{i}"))
                   for i in range(6)]
        # admissions spread over the prefill tier, never the decode tier
        assert {router._handle_map[h][0] for h in handles} <= {0, 1, 2}
        out = _settle(router, handles)
        assert all(out[h].text == f"echo: p{i}"
                   for i, h in enumerate(handles))
        assert router.handoffs == 6

    def test_prefill_death_before_handoff_fails_over_within_tier(self):
        tok = get_tokenizer()
        router = _echo_tiers(tok, n_prefill=2, n_decode=1, delay_pumps=3)
        h = router.start("p", GenOptions())
        src = router._handle_map[h][0]
        router.fail_replica(src)
        # the orphan re-started on the SURVIVING PREFILL replica, not on
        # the decode tier
        rid = router._handle_map[h][0]
        assert router.tier[rid] == "prefill" and rid != src
        out = _settle(router, [h])
        assert out[h].text == "echo: p"
        assert router.handoffs == 1               # still handed off after

    def test_decode_death_after_handoff_fails_over_within_tier(self):
        tok = get_tokenizer()
        router = _echo_tiers(tok, n_prefill=1, n_decode=2,
                             delay_pumps=10 ** 9)
        h = router.start("p", GenOptions())
        router.pump()                             # handoff commits
        rid = router._handle_map[h][0]
        assert router.tier[rid] == "decode"
        router.fail_replica(rid)
        new_rid = router._handle_map[h][0]
        assert router.tier[new_rid] == "decode" and new_rid != rid
        # the settled run never re-enters the handoff queue
        assert h not in router._handoff_queue

    def test_whole_decode_tier_down_keeps_serving_on_prefill(self):
        tok = get_tokenizer()
        router = _echo_tiers(tok, n_prefill=2, n_decode=1, delay_pumps=2)
        router.fail_replica(2)                    # the only decode replica
        h = router.start("p", GenOptions())
        out = _settle(router, [h])
        assert out[h].text == "echo: p"           # degraded but alive
        assert router.handoffs == 0               # nowhere to hand off to

    def test_drain_defaults_to_same_tier_peer(self):
        # live-sequence migration itself is the base router's engine
        # seam (snapshot/adopt); what the TierRouter adds — and what we
        # pin here — is that the DEFAULT target resolves inside the
        # drained replica's own tier, never across
        from unittest import mock

        from k8s_llm_rca_tpu.cluster import ClusterRouter

        tok = get_tokenizer()
        router = _echo_tiers(tok, n_prefill=2, n_decode=1,
                             delay_pumps=10 ** 9)
        h = router.start("p", GenOptions())
        src = router._handle_map[h][0]
        peer = ({0, 1} - {src}).pop()             # the other prefill
        with mock.patch.object(ClusterRouter, "drain_replica",
                               return_value=[h]) as base:
            moved = router.drain_replica(src)
        assert moved == [h]
        base.assert_called_once_with(src, target=peer)

    def test_cancel_clears_the_handoff_queue(self):
        tok = get_tokenizer()
        router = _echo_tiers(tok, delay_pumps=10 ** 9)
        h = router.start("p", GenOptions())
        router.cancel(h)
        assert h not in router._handoff_queue
        router.pump()                             # no stale-queue blowup
        assert router.handoffs == 0


# ---------------------------------------------------------------------------
# frame faults on the handoff plan (own-plan discipline)
# ---------------------------------------------------------------------------


class TestFrameFaults:
    def _run_one(self, indices, pumps=16):
        tok = get_tokenizer()
        plan = _handoff_plan(indices)
        router = _echo_tiers(tok, delay_pumps=4, handoff_plan=plan)
        h = router.start("p", GenOptions())
        out = _settle(router, [h], pumps=pumps)
        assert out[h].error is None
        assert out[h].text == "echo: p"
        return router, plan

    def test_dropped_frame_is_retried_whole(self):
        router, plan = self._run_one({0: "drop"})
        assert router.handoffs_retried == 1
        assert router.handoffs == 1
        assert [f.kind for f in plan.fired] == ["drop"]

    def test_corrupt_frame_is_discarded_whole_and_retried(self):
        router, _ = self._run_one({0: "corrupt"})
        assert router.handoffs_retried == 1
        assert router.handoffs == 1

    def test_stale_fenced_ack_cancels_the_adopted_twin(self):
        router, _ = self._run_one({0: "stale-fence"})
        assert router.handoffs_retried == 1
        assert router.handoffs == 1
        # the fenced twin was cancelled on the adopter: exactly ONE live
        # copy settled, and nothing is still inflight on either backend
        for r in router.replicas.values():
            assert r.backend.queue_depth() == 0

    def test_delay_advances_only_the_handoff_plans_clock(self):
        router, plan = self._run_one({0: "delay"})
        assert router.handoffs_retried == 0       # delay is not a failure
        assert router.handoffs == 1
        assert plan.clock.time() > 0.0            # virtual transfer time

    def test_handoff_polls_never_touch_the_armed_plan(self):
        # an ARMED chaos plan must see zero polls from the handoff path:
        # the transfer polls its own plan and re-admits under
        # inject.readmission, so chaos-soak byte-identity survives tiers
        tok = get_tokenizer()
        armed_plan = FaultPlan.from_spec(0, {})
        router = _echo_tiers(tok, delay_pumps=2,
                             handoff_plan=_handoff_plan({}))
        with inject.armed(armed_plan):
            h = router.start("p", GenOptions())
            _settle(router, [h])
        assert router.handoffs == 1
        assert armed_plan.snapshot()["polls"] == {}


# ---------------------------------------------------------------------------
# loud exclusions
# ---------------------------------------------------------------------------


class _SeamStub:
    """Minimal engine-seam-shaped backend (hasattr export_run) for the
    mixed-fleet exclusion test — never actually driven."""

    def start(self, prompt, opts):                # pragma: no cover
        raise NotImplementedError

    def export_run(self, handle):                 # pragma: no cover
        return None

    def adopt_run(self, frame, opts):             # pragma: no cover
        raise NotImplementedError


class TestExclusions:
    def test_empty_tier_rejected(self):
        tok = get_tokenizer()
        with pytest.raises(ValueError, match="at least one replica"):
            TierRouter([], [Replica(0, EchoBackend(tok))])
        with pytest.raises(ValueError, match="at least one replica"):
            TierRouter([Replica(0, EchoBackend(tok))], [])

    def test_overlapping_tiers_rejected(self):
        tok = get_tokenizer()
        shared = Replica(0, EchoBackend(tok))
        with pytest.raises(ValueError, match="disjoint"):
            TierRouter([shared], [shared, Replica(1, EchoBackend(tok))])

    @pytest.mark.parametrize("axis", ["cp", "pp"])
    def test_cp_pp_meshes_rejected_across_tiers(self, axis):
        # a handoff page record is ONE engine's pool layout: KV sharded
        # over a context/pipeline axis has no host-safe per-page image
        tok = get_tokenizer()
        mesh = types.SimpleNamespace(axis_names=("dp", axis))
        with pytest.raises(ValueError, match=f"mesh axes .*{axis}"):
            TierRouter([Replica(0, EchoBackend(tok), mesh=mesh)],
                       [Replica(1, EchoBackend(tok))])

    def test_mixed_seam_and_scripted_fleet_rejected(self):
        tok = get_tokenizer()
        with pytest.raises(ValueError, match="same handoff seam"):
            TierRouter([Replica(0, _SeamStub())],
                       [Replica(1, EchoBackend(tok))])

    def test_cross_tier_drain_target_rejected(self):
        tok = get_tokenizer()
        router = _echo_tiers(tok, n_prefill=1, n_decode=2)
        with pytest.raises(ValueError, match="own tier"):
            router.drain_replica(0, target=1)     # prefill -> decode

    def test_drain_without_tier_peer_rejected(self):
        tok = get_tokenizer()
        router = _echo_tiers(tok, n_prefill=1, n_decode=2)
        with pytest.raises(ValueError, match="no surviving prefill peer"):
            router.drain_replica(0)

    def test_pipelined_sweep_refuses_disagg(self):
        from k8s_llm_rca_tpu.faults.soak import run_pipelined_sweep

        with pytest.raises(ValueError, match="chaos-soak-only"):
            run_pipelined_sweep(n_incidents=1, backend="disagg-cluster")

    def test_tier_split_requires_disagg_backend(self):
        from k8s_llm_rca_tpu.faults.soak import run_chaos_soak

        with pytest.raises(ValueError, match="only applies to backend="):
            run_chaos_soak(n_incidents=1, backend="cluster-oracle",
                           tier_split=(1, 1))

    def test_tier_split_must_sum_to_fleet(self):
        from k8s_llm_rca_tpu.faults.soak import run_chaos_soak

        with pytest.raises(ValueError, match="must sum to the fleet"):
            run_chaos_soak(n_incidents=1, backend="disagg-cluster",
                           cluster_replicas=4, tier_split=(1, 2))

    def test_overlapping_killer_sites_rejected_before_any_spawn(self):
        from k8s_llm_rca_tpu.faults.soak import run_chaos_soak
        from k8s_llm_rca_tpu.faults.supervisor import ProcKiller

        k1 = ProcKiller(FaultPlan.from_spec(0, {}))
        k2 = ProcKiller(FaultPlan.from_spec(1, {}))
        with pytest.raises(ValueError,
                           match=r"disjoint fault sites.*cluster\.proc"):
            run_chaos_soak(n_incidents=1, backend="proc-cluster",
                           killer=[k1, k2])

    def test_handoff_killer_requires_disagg_backend(self):
        from k8s_llm_rca_tpu.faults.soak import run_chaos_soak

        k = _handoff_killer({})
        with pytest.raises(ValueError, match="requires backend='disagg"):
            run_chaos_soak(n_incidents=1, backend="proc-cluster",
                           killer=k)

    def test_handoff_killer_unknown_target_rejected(self):
        with pytest.raises(ValueError, match="unknown handoff kill "
                                             "target"):
            _handoff_killer({}, target="bystander")

    def test_killer_refusals_name_kind_and_transport(self):
        # satellite: a refusal must tell the operator WHICH fleet shape
        # the plan mismatched — victim id, backend kind, transport
        from k8s_llm_rca_tpu.cluster import ClusterRouter
        from k8s_llm_rca_tpu.faults.supervisor import ReplicaKiller

        tok = get_tokenizer()
        router = ClusterRouter([Replica(0, EchoBackend(tok)),
                                Replica(1, EchoBackend(tok))])
        k = ReplicaKiller(FaultPlan.from_spec(
            0, {inject.SITE_REPLICA: {"indices": {0: "crash"}}}),
            router=router, mode="sigkill")
        with pytest.raises(ValueError) as exc:
            k.checkpoint()
        msg = str(exc.value)
        assert "replica 0" in msg
        assert "kind='EchoBackend'" in msg
        assert "transport='in-process'" in msg

    def test_partition_refusal_names_kind_and_transport(self):
        from k8s_llm_rca_tpu.cluster import ClusterRouter
        from k8s_llm_rca_tpu.faults.supervisor import ReplicaKiller

        tok = get_tokenizer()
        router = ClusterRouter([Replica(0, EchoBackend(tok)),
                                Replica(1, EchoBackend(tok))])
        k = ReplicaKiller(FaultPlan.from_spec(
            0, {inject.SITE_REPLICA: {"indices": {0: "partition"}}}),
            router=router)
        with pytest.raises(ValueError) as exc:
            k.checkpoint()
        msg = str(exc.value)
        assert "replica 0" in msg and "needs a socket-transport" in msg
        assert "kind='EchoBackend'" in msg
        assert "transport='in-process'" in msg


# ---------------------------------------------------------------------------
# kill windows (real subprocess workers, scripted oracles)
# ---------------------------------------------------------------------------


def _proc_tiers(n_prefill=2, n_decode=2, transport="pipe", **kw):
    # echo workers with a pump delay: an instantly-settling oracle would
    # finish on the prefill tier right after a failover re-start, before
    # the retried transfer gets a second attempt — the delay keeps the
    # run alive long enough for the retry to COMMIT, which is the path
    # these tests pin
    from k8s_llm_rca_tpu.cluster.proc import build_proc_replicas

    reps = build_proc_replicas(n_prefill + n_decode, kind="echo",
                               echo_delay_pumps=4, transport=transport)
    return TierRouter(reps[:n_prefill], reps[n_prefill:], **kw)


class TestKillWindows:
    def test_prefill_sigkill_between_export_and_adopt(self):
        """The exporter dies with the frame in flight: the pinned source
        copy rides ordinary failover back onto the surviving prefill
        replica, the transfer retries whole, and the death is attributed
        to the 'handoff' evidence kind."""
        killer = _handoff_killer({0: "crash"}, target="prefill")
        router = _proc_tiers(handoff_killer=killer)
        try:
            router.attach_health(_watchdog(), ReplicaSupervisor())
            h = router.start("node notready", GenOptions())
            victim = router._handle_map[h][0]
            out = _settle(router, [h], pumps=16)
            assert out[h].error is None
            assert out[h].text == "echo: node notready"
            assert killer.kills == [victim]
            assert router.handoffs_retried >= 1   # the killed attempt
            assert router.handoffs == 1           # the retry committed
            assert router._handoff_queue == {}
            assert "handoff" in router.health.hard_kinds
            # the fleet healed back to full strength
            for _ in range(8):
                if all(r.healthy() for r in router.replicas.values()):
                    break
                router.pump()
            assert sorted(router.alive_ids()) == [0, 1, 2, 3]
        finally:
            _close_all(router)

    def test_decode_sigkill_between_export_and_adopt(self):
        """The adopter dies before ADOPT: nothing was registered on the
        decode side, the source stays pinned, and the retry lands on the
        surviving decode replica."""
        killer = _handoff_killer({0: "crash"}, target="decode")
        router = _proc_tiers(handoff_killer=killer)
        try:
            router.attach_health(_watchdog(), ReplicaSupervisor())
            h = router.start("node notready", GenOptions())
            out = _settle(router, [h], pumps=16)
            assert out[h].error is None
            assert out[h].text == "echo: node notready"
            assert len(killer.kills) == 1
            assert router.tier[killer.kills[0]] == "decode"
            assert router.handoffs_retried >= 1
            assert router.handoffs == 1
            assert "handoff" in router.health.hard_kinds
        finally:
            _close_all(router)

    def test_mid_handoff_partition_heals_by_relink(self):
        """A partitioned (not killed) tier member mid-window: the link
        relinks under the SAME incarnation and the transfer retries —
        no process death, no restart."""
        killer = _handoff_killer({0: "partition"}, target="decode")
        router = _proc_tiers(n_prefill=1, n_decode=1, transport="socket",
                             handoff_killer=killer)
        try:
            router.attach_health(_watchdog(), ReplicaSupervisor())
            h = router.start("node notready", GenOptions())
            out = _settle(router, [h], pumps=16)
            assert out[h].error is None
            assert out[h].text == "echo: node notready"
            assert killer.kills == [1]
            assert router.handoffs == 1
            # the severed link heals INSIDE the ADOPT rpc: the transport
            # relinks under the same incarnation and replays, so the
            # router never even has to discard the attempt
            assert router.handoffs_retried == 0
            backend = router.replicas[1].backend
            assert backend.incarnation == 0       # same process throughout
            assert backend.relinks >= 1
            assert router.health.hard_kinds == [] # evidence, no death
        finally:
            _close_all(router)


# ---------------------------------------------------------------------------
# the acceptance bar: 100-incident mid-handoff-kill soak, byte-identical
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestDisaggChaosSoak:
    def _handoff_rate_killer(self, seed=13):
        from k8s_llm_rca_tpu.faults.supervisor import HandoffKiller

        return HandoffKiller(FaultPlan.from_spec(
            seed, {inject.SITE_HANDOFF: {"rate": 0.03, "horizon": 400,
                                         "kinds": ("crash",)}}),
            target="alternate")

    def test_100_incident_mid_handoff_kill_soak_byte_identical(self):
        """Mid-handoff SIGKILLs against real socket workers, on both
        sides of the transfer: every partial handoff resolves
        deterministically, every retried transfer is counted, zero torn
        sequences — and the report is byte-identical to the unkilled
        IN-PROCESS cluster-oracle run, twice over (tiers, transports and
        murder are deployment details, not outcomes)."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak

        base = run_chaos_soak(seed=13, n_incidents=100,
                              backend="cluster-oracle",
                              cluster_replicas=4)
        assert base["completed"] == 100
        assert base["failed"] == 0

        k1 = self._handoff_rate_killer()
        healed = run_chaos_soak(seed=13, n_incidents=100,
                                backend="disagg-cluster",
                                cluster_replicas=4, killer=k1,
                                selfheal=True)
        assert k1.kills                       # mid-window kills landed
        assert report_bytes(healed) == report_bytes(base)
        router = k1.router
        # both tiers took kills (target="alternate" + seeded plan)
        assert {router.tier[rid] for rid in k1.kills} == \
            {"prefill", "decode"}
        # every discarded transfer attempt was counted, then committed:
        # nothing is left half-adopted or parked in the queue
        assert router.handoffs_retried >= len(k1.kills)
        assert router.handoffs > 0
        assert router._handoff_queue == {}
        # every mid-window death was detected on hard OS evidence and
        # attributed to the handoff window
        assert router.health.hard_kinds.count("handoff") == len(k1.kills)
        assert router.supervisor.restarts == k1.kills
        assert sorted(router.alive_ids()) == [0, 1, 2, 3]
        # the soak's reaping context closed every worker on exit
        for r in router.replicas.values():
            assert r.backend._proc.poll() is not None

        k2 = self._handoff_rate_killer()
        again = run_chaos_soak(seed=13, n_incidents=100,
                               backend="disagg-cluster",
                               cluster_replicas=4, killer=k2,
                               selfheal=True)
        assert k2.kills == k1.kills           # the kill schedule is seeded
        assert k2.router.handoffs_retried == router.handoffs_retried
        assert report_bytes(again) == report_bytes(base)

    def test_mixed_fault_soak_with_disjoint_killers(self):
        """ProcKiller + NetKiller + HandoffKiller side by side on one
        disagg fleet (disjoint sites): boundary SIGKILLs, boundary
        partitions and mid-handoff kills compose, and the report still
        matches the unkilled in-process run byte for byte."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak
        from k8s_llm_rca_tpu.faults.supervisor import NetKiller, ProcKiller

        base = run_chaos_soak(seed=17, n_incidents=30,
                              backend="cluster-oracle",
                              cluster_replicas=4)
        pk = ProcKiller(FaultPlan.from_spec(
            5, {inject.SITE_PROC: {"rate": 0.05, "horizon": 30,
                                   "kinds": ("crash",)}}))
        nk = NetKiller(FaultPlan.from_spec(
            9, {inject.SITE_NET: {"rate": 0.05, "horizon": 30,
                                  "kinds": ("partition",)}}))
        hk = self._handoff_rate_killer(seed=19)
        mixed = run_chaos_soak(seed=17, n_incidents=30,
                               backend="disagg-cluster",
                               cluster_replicas=4,
                               killer=[pk, nk, hk], selfheal=True)
        assert report_bytes(mixed) == report_bytes(base)
        assert pk.kills or nk.kills or hk.kills
        router = hk.router
        assert sorted(router.alive_ids()) == [0, 1, 2, 3]
        kinds = router.health.hard_kinds
        if hk.kills:
            assert "handoff" in kinds
        if pk.kills:
            assert "proc" in kinds

    def test_disagg_soak_without_chaos_matches_in_process(self):
        """Tier invariance alone: no killer, no selfheal — the disagg
        sweep's report (runs admitted on prefill, handed off, settled
        on decode) must already be byte-identical to the in-process
        single-tier cluster-oracle run."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak

        base = run_chaos_soak(seed=3, n_incidents=6,
                              backend="cluster-oracle",
                              cluster_replicas=3)
        dis = run_chaos_soak(seed=3, n_incidents=6,
                             backend="disagg-cluster",
                             cluster_replicas=3, tier_split=(2, 1))
        assert report_bytes(dis) == report_bytes(base)
        assert dis["backend"] == "cluster-oracle"


# ---------------------------------------------------------------------------
# engine seam: per-run export/adopt round trips (slow: compiles)
# ---------------------------------------------------------------------------


# EngineConfig overrides per matrix leg — each composition must survive
# a mid-decode export/adopt round trip byte-identically
_MATRIX = {
    "plain": {},
    "prefix_cache": {"prefix_cache": True},
    "host_overlap": {"host_overlap": True},
    "chunked_prefill": {"prefill_chunk_budget": 16},
    "spilled": {"max_spilled_pages": 24},
}


def _small_pair(overrides):
    import jax

    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.models import llama

    cfg = TINY.replace(max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    knobs = dict(max_batch=2, max_seq_len=64, paged=True,
                 page_size=8, num_pages=24, prefill_buckets=(16, 32),
                 max_new_tokens=8, temperature=0.0, decode_chunk=1,
                 prefix_cache=False)
    knobs.update(overrides)
    ecfg = EngineConfig(**knobs)
    mk = lambda: make_engine(cfg, ecfg, params, tok,    # noqa: E731
                             use_kernel=False)
    return mk(), mk(), tok


@pytest.mark.slow
class TestEngineHandoffMatrix:
    @pytest.mark.parametrize("leg", sorted(_MATRIX))
    def test_export_adopt_round_trip_is_byte_identical(self, leg):
        """Start a run on engine A, export it mid-decode (KV pages and
        all), adopt it on engine B, and the finished text must match the
        uninterrupted single-engine run byte for byte — for every
        composition in the matrix."""
        from k8s_llm_rca_tpu.serve.backend import EngineBackend

        eng_a, eng_b, tok = _small_pair(_MATRIX[leg])
        prompt = "node notready on node-3"
        opts = GenOptions(max_new_tokens=8)
        # uninterrupted reference on engine A (also warms the prefix
        # cache for the prefix_cache leg, so the handoff run exports a
        # prefix-hit admission)
        backend_a = EngineBackend(eng_a)
        ref_h = backend_a.start(prompt, opts)
        ref = {}
        while ref_h not in ref:
            ref.update(backend_a.pump())
        assert ref[ref_h].error is None

        h = backend_a.start(prompt, opts)
        frame = None
        for _ in range(6):
            res = backend_a.pump()
            assert h not in res, "run completed before it could export"
            if leg == "spilled":
                # park the sequence via the spill path FIRST, so the
                # export serves the spilled-while-snapshotted case
                assert eng_a._preempt_victim()
                assert eng_a._spilled
            frame = backend_a.export_run(h)
            if frame is not None:
                break
        assert frame is not None
        assert frame["kv"] is not None            # pages actually moved
        backend_b = EngineBackend(eng_b)
        h2 = backend_b.adopt_run(frame, opts)
        # the KV must be ADOPTED, not silently dropped to a re-prefill
        assert (eng_b._counts or {}).get("engine.handoff_kv_adopted") == 1
        out = {}
        for _ in range(64):
            out.update(backend_b.pump())
            if h2 in out:
                break
        assert out[h2].error is None
        assert out[h2].text == ref[ref_h].text
        # RELEASE: the source frees its pinned copy through the normal
        # retire path and ends allocator-clean
        backend_a.cancel(h)
        while eng_a.has_work:
            eng_a.step()
        eng_a.allocator.check()
        assert not eng_a.has_work

    def test_torn_frames_are_rejected_whole(self):
        """Every torn-frame class raises before ANY engine state moves
        on the adopter: malformed entry, corrupt base64, CRC-failing
        page blob."""
        from k8s_llm_rca_tpu.serve.backend import EngineBackend

        eng_a, eng_b, tok = _small_pair({})
        backend_a = EngineBackend(eng_a)
        backend_b = EngineBackend(eng_b)
        opts = GenOptions(max_new_tokens=8)
        h = backend_a.start("node notready on node-3", opts)
        frame = None
        for _ in range(6):
            res = backend_a.pump()
            assert h not in res
            frame = backend_a.export_run(h)
            if frame is not None:
                break
        assert frame is not None and frame["kv"] is not None

        with pytest.raises(ValueError, match="torn handoff frame"):
            backend_b.adopt_run({"seq": {"nonsense": 1}, "kv": None},
                                opts)
        torn_b64 = dict(frame, kv=dict(frame["kv"]))
        torn_b64["kv"]["b64"] = "!!!" + torn_b64["kv"]["b64"][3:]
        with pytest.raises(ValueError, match="torn handoff frame"):
            backend_b.adopt_run(torn_b64, opts)
        torn_crc = dict(frame, kv=dict(frame["kv"]))
        b64 = torn_crc["kv"]["b64"]
        torn_crc["kv"]["b64"] = ("B" if b64[0] == "A" else "A") + b64[1:]
        with pytest.raises(ValueError, match="torn handoff frame"):
            backend_b.adopt_run(torn_crc, opts)
        # nothing half-adopted: the adopter is untouched and still clean
        assert not eng_b.has_work
        assert (eng_b._counts or {}).get("engine.handoff_kv_adopted",
                                         0) == 0
        # the source run survives all three rejections and still settles
        out = {}
        for _ in range(64):
            out.update(backend_a.pump())
            if h in out:
                break
        assert out[h].error is None

    def test_export_unknown_run_is_a_loud_error(self):
        eng_a, _eng_b, _tok = _small_pair({})
        with pytest.raises(ValueError, match="not live"):
            eng_a.export_run(10 ** 9)


# ---------------------------------------------------------------------------
# engine tiers over the wire: greedy byte-parity (slow: worker compiles)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestDisaggEngineParity:
    PROMPTS = ["pod pending unschedulable node affinity mismatch",
               "pvc not bound storageclass missing"]

    def _reference(self):
        import jax

        from k8s_llm_rca_tpu.config import TINY, EngineConfig
        from k8s_llm_rca_tpu.engine import make_engine
        from k8s_llm_rca_tpu.models import llama

        cfg = TINY.replace(max_seq_len=2560)
        ecfg = EngineConfig(max_batch=4, max_seq_len=2560,
                            prefill_buckets=(2560,), max_new_tokens=96,
                            temperature=0.0, paged=True, page_size=64,
                            num_pages=168, prefix_cache=False,
                            decode_chunk=16)
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        engine = make_engine(cfg, ecfg, params, tok, use_kernel=False)
        return engine.generate(
            [tok.encode(p, add_bos=True) for p in self.PROMPTS],
            max_new_tokens=8)

    @pytest.mark.parametrize("n_prefill,n_decode,transport",
                             [(1, 2, "pipe"), (2, 1, "socket")])
    def test_proc_engine_tiers_match_plain_engine(self, n_prefill,
                                                  n_decode, transport):
        """Greedy byte-parity through a REAL cross-process KV handoff:
        each prompt admits on a prefill engine worker, its pages cross
        the wire as a CRC-framed page record, and the decode worker's
        finished text must equal the plain in-process engine's — for
        1P+2D over pipes AND 2P+1D over sockets."""
        from k8s_llm_rca_tpu.cluster.proc import build_proc_replicas

        ref = self._reference()
        reps = build_proc_replicas(n_prefill + n_decode, kind="engine",
                                   seed=0, transport=transport)
        router = TierRouter(reps[:n_prefill], reps[n_prefill:])
        assert router._kv_seam                    # the REAL seam, not
        try:                                      # the scripted stand-in
            handles = [router.start(p, GenOptions(max_new_tokens=8))
                       for p in self.PROMPTS]
            out = _settle(router, handles, pumps=512)
            for h, r in zip(handles, ref):
                assert out[h].error is None
                assert out[h].text == r.text      # byte-identical greedy
            assert router.handoffs == len(handles)
            assert router.handoffs_retried == 0
        finally:
            _close_all(router)
