"""Fleet-scale cache fabric (docs/cluster.md "Cache fabric"): the
cross-host PrefixStore service (cluster/store.py), pressure-driven
watermark demotion, and store-backed instant recovery.

Three bars, mirroring the tiered-cache suite's (tests/test_prefix_tiers
.py) because the fabric IS the L1/L2 tier moved out of process:

- BYTE PARITY: an engine whose prefix store is a RemoteStore over a
  real server subprocess must generate exactly what the in-process
  PrefixStore engine and the store-less engine generate — the wire
  moves the same encode_page_record bytes the disk tier persists, so
  the promoted pages hold identical KV.
- SILENT DEGRADATION: every fabric failure (dead server, torn frame,
  drop/corrupt/delay/partition faults on SITE_STORE) is a counted cold
  miss (engine.prefix_store_misses_remote), never an engine error.
- BYTE-IDENTITY UNDER CHAOS: a seeded soak with the fabric attached
  and a StoreKiller SIGKILLing/respawning the store mid-sweep settles
  report_bytes byte-identical to the store-less run — fabric outcomes
  live on the fabric object, never in the report.

Everything runs on the 8-virtual-device CPU platform the conftest pins;
engines are single-device (test_prefix_tiers.py rationale).
"""

import os

import jax
import numpy as np
import pytest

from k8s_llm_rca_tpu.cluster import wire
from k8s_llm_rca_tpu.cluster.store import (
    RemoteStore, StoreFabric, StoreServer, build_store_fabric,
)
from k8s_llm_rca_tpu.config import TINY, EngineConfig
from k8s_llm_rca_tpu.engine import make_engine
from k8s_llm_rca_tpu.engine.prefix import PrefixStore
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.faults.plan import FaultPlan, VirtualClock
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.utils import pages, wal
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

pytestmark = pytest.mark.storefab


@pytest.fixture(scope="module")
def setup():
    cfg = TINY.replace(max_seq_len=128)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    return cfg, params, tok


# the RCA-agent shape (test_prefix_tiers.py): one long shared preamble,
# short per-run suffixes — byte-level tokenizer, 4 full pages of
# preamble at page_size=16
_PRE = "shared incident preamble " * 3
PROMPTS = (_PRE + "kubelet crashloop on node-7",
           _PRE + "etcd leader lost quorum",
           _PRE + "pvc unbound on nfs chain")


def _ecfg(**over):
    base = dict(max_batch=2, max_seq_len=128, prefill_buckets=(64, 128),
                max_new_tokens=16, temperature=0.0, paged=True,
                page_size=16, num_pages=40, prefix_cache=True,
                decode_chunk=4)
    base.update(over)
    return EngineConfig(**base)


def _drive(eng, sids):
    out = {}
    while eng.has_work:
        for r in eng.step():
            out[r.seq_id] = r
    eng.allocator.check()
    resident = eng.prefix_cache.n_resident if eng.prefix_cache else 0
    assert (eng.allocator.n_free + resident
            == eng.engine_cfg.num_pages - 1)
    return [out[s].token_ids for s in sids]


def _run(eng, tok, prompts=PROMPTS):
    return _drive(eng, [eng.submit(tok.encode(p)) for p in prompts])


def _rec(seed=0, n_pages=1):
    """A synthetic page record in the pool's (layers, n_pages, ...)
    layout — structurally valid for the codec, no engine needed."""
    rng = np.random.default_rng(seed)
    return {"n_pages": n_pages,
            "k": rng.standard_normal((2, n_pages, 4, 8)).astype(np.float32),
            "v": rng.standard_normal((2, n_pages, 4, 8)).astype(np.float32)}


def _same_rec(a, b):
    assert a is not None and b is not None
    assert a["n_pages"] == b["n_pages"]
    for f in ("k", "v"):
        np.testing.assert_array_equal(a[f], b[f])


# ---------------------------------------------------------------------------
# satellite: ONE frame header shared by WAL, disk tier and wire
# ---------------------------------------------------------------------------


class TestSharedHeader:
    def test_header_objects_are_identical(self):
        """wire.py re-exports wal.py's header/size-guard OBJECTS — not
        copies — so the disk and wire formats cannot drift."""
        assert wire.HEADER is wal.HEADER
        assert wire.HEADER_SIZE == wal.HEADER_SIZE == wal.HEADER.size
        assert wire.MAX_FRAME_SIZE == wal.MAX_RECORD_SIZE

    def test_disk_record_served_verbatim_over_wire(self, tmp_path):
        """A ``.page`` record written by the in-process L2 disk tier
        must be servable byte-for-byte by a store server pointed at
        the same directory: one format, three consumers (WAL framing,
        durable disk entries, wire frames)."""
        disk = str(tmp_path / "l2")
        local = PrefixStore(host_pages=0, disk_dir=disk, disk_pages=8)
        key = b"\x42" * 20
        rec = _rec(seed=3)
        local.put(key, rec)
        (entry,) = [f for f in os.listdir(disk) if f.endswith(".page")]
        assert entry == key.hex() + ".page"
        raw = open(os.path.join(disk, entry), "rb").read()
        # the durable bytes ARE exactly one legal WAL record
        (payload, end), = list(wal.iter_records(raw))
        assert end == len(raw) and payload
        assert pages.decode_page_record(raw) is not None
        # and a server re-indexing that directory serves them verbatim
        server = StoreServer(host_pages=0, disk_dir=disk, disk_pages=8,
                             transport="pipe")
        try:
            remote = RemoteStore(server=server)
            assert remote.contains(key)
            got, tier = remote.get(key)
            assert tier == 2                  # served from the disk tier
            _same_rec(got, rec)
        finally:
            server.close()


# ---------------------------------------------------------------------------
# store-op units over the wire codec
# ---------------------------------------------------------------------------


class TestStoreOps:
    @pytest.mark.parametrize("transport", ["pipe", "socket"])
    def test_put_get_probe_stats_roundtrip(self, transport):
        server = StoreServer(host_pages=8, transport=transport)
        try:
            remote = RemoteStore(server=server)
            key = b"\x07" * 20
            assert not remote.contains(key)
            assert remote.get(key) is None    # honest miss
            rec = _rec(seed=1)
            remote.put(key, rec)
            assert remote.contains(key)
            got, tier = remote.get(key)
            assert tier == 1
            _same_rec(got, rec)
            stats = remote.stats()
            assert stats["puts"] == 1 and stats["n_host"] == 1
            assert stats["hits_l1"] == 1 and stats["misses"] == 1
        finally:
            server.close()

    def test_addr_client_shares_server(self):
        """A second client dialing the socket address sees the first
        client's pages — the cross-host fleet shape."""
        server = StoreServer(host_pages=8, transport="socket")
        try:
            first = RemoteStore(server=server)
            key = b"\x11" * 20
            first.put(key, _rec(seed=2))
            second = RemoteStore(addr=server.addr)
            assert second.contains(key)
            _same_rec(second.get(key)[0], _rec(seed=2))
        finally:
            server.close()

    def test_host_lru_overflow_without_disk_drops(self):
        """The server's host tier is LRU-capped; with no disk tier the
        evicted page is simply gone — a later get is an honest miss."""
        server = StoreServer(host_pages=2, transport="pipe")
        try:
            remote = RemoteStore(server=server)
            keys = [bytes([i]) * 20 for i in range(3)]
            for i, k in enumerate(keys):
                remote.put(k, _rec(seed=i))
            assert remote.n_host == 2
            assert remote.get(keys[0]) is None        # LRU victim
            _same_rec(remote.get(keys[2])[0], _rec(seed=2))
        finally:
            server.close()

    def test_host_overflow_demotes_to_disk_and_survives_kill(self,
                                                             tmp_path):
        """Overflowed pages land in the durable disk tier and survive a
        SIGKILL + respawn of the server process."""
        disk = str(tmp_path / "store_l2")
        server = StoreServer(host_pages=1, disk_dir=disk, disk_pages=8,
                             transport="socket")
        try:
            remote = RemoteStore(server=server)
            keys = [bytes([0x20 + i]) * 20 for i in range(3)]
            for i, k in enumerate(keys):
                remote.put(k, _rec(seed=10 + i))
            assert remote.n_disk == 2
            server.kill()
            assert remote.get(keys[0]) is None        # dead: cold miss
            server.respawn()
            got, tier = remote.get(keys[0])
            assert tier == 2
            _same_rec(got, _rec(seed=10))
        finally:
            server.close()

    def test_dead_server_every_op_is_counted_cold_miss(self):
        """The failure contract: with the server SIGKILLed, put/get/
        probe/stats all degrade silently — no exception escapes, and
        every degraded op lands in the miss counter."""
        server = StoreServer(host_pages=8, transport="pipe")
        try:
            counted = []
            remote = RemoteStore(server=server,
                                 count=lambda n, v=1.0:
                                 counted.append((n, v)))
            server.kill()
            remote.put(b"\x01" * 20, _rec())
            assert remote.get(b"\x01" * 20) is None
            assert remote.contains(b"\x01" * 20) is False
            assert remote.stats() == {}
            misses = [c for c in counted
                      if c[0] == "engine.prefix_store_misses_remote"]
            assert len(misses) == 3           # put + get + probe
        finally:
            server.close()

    def test_corrupt_disk_entry_is_cold_miss(self, tmp_path):
        """A torn durable entry (host died mid-write) is dropped and
        unlinked at serve time — identical cold miss, never garbage."""
        disk = str(tmp_path / "l2")
        os.makedirs(disk)
        key = b"\x33" * 20
        frame = pages.encode_page_record(_rec(seed=4))
        with open(os.path.join(disk, key.hex() + ".page"), "wb") as f:
            f.write(frame[:len(frame) // 2])          # torn tail
        server = StoreServer(host_pages=0, disk_dir=disk, disk_pages=8,
                             transport="pipe")
        try:
            remote = RemoteStore(server=server)
            assert remote.get(key) is None
            assert not os.path.exists(
                os.path.join(disk, key.hex() + ".page"))
        finally:
            server.close()

    def test_oversized_record_is_local_drop(self):
        """A record past the shared size guard never reaches the wire:
        put degrades locally (encode raises, caught) with one counted
        miss."""
        server = StoreServer(host_pages=8, transport="pipe")
        try:
            counted = []
            remote = RemoteStore(server=server,
                                 count=lambda n, v=1.0:
                                 counted.append(n))
            big = wal.MAX_RECORD_SIZE // 4 + 1
            remote.put(b"\x44" * 20, {"n_pages": 1,
                                      "k": np.zeros((1, 1, 1, big),
                                                    np.float32),
                                      "v": np.zeros((1, 1, 1, 1),
                                                    np.float32)})
            assert "engine.prefix_store_misses_remote" in counted
            assert remote.stats()["puts"] == 0
        finally:
            server.close()


# ---------------------------------------------------------------------------
# the SITE_STORE fault seam (RemoteStore's OWN plan)
# ---------------------------------------------------------------------------


class TestStoreFaults:
    def _store(self, server, spec, seed=0, clock=None):
        plan = FaultPlan.from_spec(
            seed, {inject.SITE_STORE: spec},
            clock=clock or VirtualClock())
        counted = []
        remote = RemoteStore(server=server, plan=plan,
                             count=lambda n, v=1.0:
                             counted.append((n, v)))
        return remote, counted, plan

    def test_drop_is_counted_miss_then_heals_by_index(self):
        server = StoreServer(host_pages=8, transport="pipe")
        try:
            remote, counted, _ = self._store(
                server, {"indices": {0: "drop"}})
            key = b"\x05" * 20
            remote.put(key, _rec())           # op 0: dropped on the floor
            assert remote.stats()["puts"] == 0
            remote.put(key, _rec())           # op 1: clean
            assert remote.get(key) is not None
            assert counted[0][0] == "engine.prefix_store_misses_remote"
        finally:
            server.close()

    def test_corrupt_put_cannot_poison_corrupt_get_is_miss(self):
        """A corrupt fault on put flips a payload byte — the server's
        CRC check refuses the frame, so the store never holds garbage.
        On get the flip happens client-side after a clean serve — the
        record decoder rejects it: both directions are cold misses."""
        server = StoreServer(host_pages=8, transport="pipe")
        try:
            remote, counted, _ = self._store(
                server, {"indices": {0: "corrupt", 2: "corrupt"}})
            key = b"\x06" * 20
            remote.put(key, _rec(seed=5))     # op 0: corrupt -> refused
            assert remote.stats()["rejected"] == 1
            remote.put(key, _rec(seed=5))     # op 1: clean
            assert remote.get(key) is None    # op 2: corrupt -> miss
            _same_rec(remote.get(key)[0], _rec(seed=5))   # op 3: clean
            assert len([c for c in counted]) == 2
        finally:
            server.close()

    def test_delay_advances_the_plan_virtual_clock(self):
        clock = VirtualClock()
        server = StoreServer(host_pages=8, transport="pipe")
        try:
            remote, counted, _ = self._store(
                server, {"indices": {0: "delay"}, "delay_s": 0.25},
                clock=clock)
            remote.put(b"\x07" * 20, _rec())
            assert clock.time() == pytest.approx(0.25)
            assert not counted                # delayed, not degraded
            assert remote.get(b"\x07" * 20) is not None
        finally:
            server.close()

    def test_partition_is_sticky_until_heal(self):
        server = StoreServer(host_pages=8, transport="pipe")
        try:
            remote, counted, _ = self._store(
                server, {"indices": {1: "partition", 4: "heal"}})
            key = b"\x08" * 20
            remote.put(key, _rec(seed=6))     # op 0: clean
            assert remote.get(key) is None    # op 1: partition fires
            assert remote.get(key) is None    # op 2: still severed
            assert not remote.contains(key)   # op 3: still severed
            _same_rec(remote.get(key)[0], _rec(seed=6))   # op 4: healed
            assert len(counted) == 3
        finally:
            server.close()


# ---------------------------------------------------------------------------
# tentpole: greedy byte-parity — no-store vs local store vs REMOTE store
# ---------------------------------------------------------------------------


class TestRemoteParity:
    # engine-feature compositions the fabric must be invisible to
    # (test_prefix_tiers.py MATRIX, remote edition)
    MATRIX = {
        "base": dict(),
        "overlap": dict(decode_chunk=1, host_overlap=True),
        "chunked": dict(prefill_chunk_budget=32),
        "spill": dict(max_spilled_pages=64),
        "all": dict(decode_chunk=1, host_overlap=True,
                    prefill_chunk_budget=32, max_spilled_pages=64),
    }

    @pytest.mark.parametrize("feature", sorted(MATRIX))
    def test_remote_store_byte_parity(self, setup, feature):
        """Cold baseline (no cache), local-store engine and remote-store
        engine must agree byte-for-byte; after demoting every resident
        page through the WIRE, a re-run must still agree — and the
        promoted pages must be real L1 hits served by the subprocess."""
        cfg, params, tok = setup
        kw = self.MATRIX[feature]
        baseline = _run(make_engine(
            cfg, _ecfg(prefix_cache=False, **kw), params, tok,
            use_kernel=False), tok)
        local = make_engine(
            cfg, _ecfg(prefix_host_pages=64, **kw), params, tok,
            use_kernel=False)
        assert _run(local, tok) == baseline
        server = StoreServer(host_pages=64, transport="pipe")
        try:
            remote_eng = make_engine(
                cfg, _ecfg(**kw), params, tok, use_kernel=False,
                prefix_store=RemoteStore(server=server))
            assert _run(remote_eng, tok) == baseline
            assert remote_eng.prefix_cache.evict(10 ** 6) > 0
            assert server.rpc({"op": "stats"})["stats"]["n_host"] > 0
            assert _run(remote_eng, tok) == baseline
            counts = remote_eng._counts or {}
            assert counts.get("engine.prefix_hits_l1", 0) > 0
            assert counts.get("engine.prefix_store_misses_remote", 0) == 0
        finally:
            server.close()

    def test_dead_store_mid_run_is_cold_only_and_parity_holds(self, setup):
        """SIGKILL the store under a warm engine: every later op is a
        counted cold miss, outputs stay byte-identical, and a respawned
        (empty) server picks service back up without any client work."""
        cfg, params, tok = setup
        baseline = _run(make_engine(
            cfg, _ecfg(prefix_cache=False), params, tok,
            use_kernel=False), tok)
        server = StoreServer(host_pages=64, transport="socket")
        try:
            remote = RemoteStore(server=server)
            eng = make_engine(cfg, _ecfg(), params, tok,
                              use_kernel=False, prefix_store=remote)
            assert _run(eng, tok) == baseline
            assert eng.prefix_cache.evict(10 ** 6) > 0
            server.kill()
            assert _run(eng, tok) == baseline             # cold, no error
            counts = eng._counts or {}
            assert counts.get("engine.prefix_store_misses_remote", 0) > 0
            server.respawn()
            assert _run(eng, tok) == baseline             # healed
        finally:
            server.close()


# ---------------------------------------------------------------------------
# pressure-driven demotion: EngineConfig.prefix_hbm_watermark
# ---------------------------------------------------------------------------


class TestWatermark:
    def test_exact_deficit_demotion(self, setup):
        """The tick-boundary sweep demotes EXACTLY the page deficit
        below the watermark — oldest refcount-0 pages first, through
        the coalesced _demote gather — and the freed pages land back
        in the allocator."""
        cfg, params, tok = setup
        eng = make_engine(cfg, _ecfg(prefix_hbm_watermark=4,
                                     prefix_host_pages=64),
                          params, tok, use_kernel=False)
        assert _run(eng, tok)                 # leaves resident r/c-0 pages
        free0 = eng.allocator.n_free
        evictable = eng.prefix_cache.n_evictable
        assert evictable >= 3
        eng._hbm_watermark = free0 + 3        # manufacture a 3-page deficit
        eng._tick_pressure()
        assert eng.allocator.n_free == free0 + 3
        assert (eng._counts or {}).get(
            "engine.prefix_watermark_demotions") == 3.0
        eng._tick_pressure()                  # deficit cleared: no-op
        assert (eng._counts or {}).get(
            "engine.prefix_watermark_demotions") == 3.0
        eng.allocator.check()

    def test_watermark_under_pressure_parity_and_determinism(self, setup):
        """A tight-pool engine under a high watermark demotes
        autonomously DURING the run, stays byte-identical to the
        store-less run, and two identical runs count identically."""
        cfg, params, tok = setup
        baseline = _run(make_engine(
            cfg, _ecfg(prefix_cache=False, num_pages=24), params, tok,
            use_kernel=False), tok)

        def one():
            eng = make_engine(
                cfg, _ecfg(num_pages=24, prefix_hbm_watermark=16,
                           prefix_host_pages=64),
                params, tok, use_kernel=False)
            out = _run(eng, tok)
            return out, (eng._counts or {}).get(
                "engine.prefix_watermark_demotions", 0.0)

        out1, demoted1 = one()
        out2, demoted2 = one()
        assert out1 == baseline and out2 == baseline
        assert demoted1 == demoted2 > 0

    def test_demoted_pages_promote_back_from_remote_store(self, setup):
        """Watermark demotions through a RemoteStore are real L1 pages:
        a warm re-run promotes them back over the wire."""
        cfg, params, tok = setup
        server = StoreServer(host_pages=64, transport="pipe")
        try:
            eng = make_engine(
                cfg, _ecfg(num_pages=24, prefix_hbm_watermark=16),
                params, tok, use_kernel=False,
                prefix_store=RemoteStore(server=server))
            first = _run(eng, tok)
            counts = eng._counts or {}
            assert counts.get("engine.prefix_watermark_demotions", 0) > 0
            assert _run(eng, tok) == first
            assert (eng._counts or {}).get("engine.prefix_hits_l1", 0) > 0
        finally:
            server.close()


# ---------------------------------------------------------------------------
# store-backed instant recovery
# ---------------------------------------------------------------------------


class TestInstantRestore:
    def _interrupt(self, eng, tok, steps=2):
        sids = [eng.submit(tok.encode(p)) for p in PROMPTS]
        out = {}
        for _ in range(steps):
            for r in eng.step():
                out[r.seq_id] = r
        return sids, out

    def test_snapshot_publishes_and_fresh_engine_restores_hot(self, setup):
        """Crash/drain recovery: snapshot_sequences publishes every
        active sequence's full written pages (prompt AND generated)
        into the fabric; a FRESH engine sharing only the store restores
        and finishes byte-identically, re-prefilling from store hits
        instead of recomputing."""
        cfg, params, tok = setup
        baseline = _run(make_engine(
            cfg, _ecfg(prefix_cache=False), params, tok,
            use_kernel=False), tok)
        server = StoreServer(host_pages=64, transport="socket")
        try:
            remote = RemoteStore(server=server)
            src = make_engine(cfg, _ecfg(), params, tok,
                              use_kernel=False, prefix_store=remote)
            sids, out = self._interrupt(src, tok)
            snap = src.snapshot_sequences()
            assert (src._counts or {}).get(
                "engine.prefix_snapshot_published", 0) > 0
            assert server.rpc({"op": "stats"})["stats"]["n_host"] > 0
            fresh = make_engine(cfg, _ecfg(), params, tok,
                                use_kernel=False, prefix_store=remote)
            fresh.restore_sequences(snap)
            while fresh.has_work:
                for r in fresh.step():
                    out[r.seq_id] = r
            fresh.allocator.check()
            assert [out[s].token_ids for s in sids] == baseline
            counts = fresh._counts or {}
            assert counts.get("engine.prefix_hits_l1", 0) > 0
        finally:
            server.close()

    def test_restore_parity_survives_store_death(self, setup):
        """The store dying between snapshot and restore degrades the
        instant restore to a plain re-prefill — byte-identical output,
        counted cold misses, zero errors."""
        cfg, params, tok = setup
        baseline = _run(make_engine(
            cfg, _ecfg(prefix_cache=False), params, tok,
            use_kernel=False), tok)
        server = StoreServer(host_pages=64, transport="pipe")
        try:
            remote = RemoteStore(server=server)
            src = make_engine(cfg, _ecfg(), params, tok,
                              use_kernel=False, prefix_store=remote)
            sids, out = self._interrupt(src, tok)
            snap = src.snapshot_sequences()
            server.kill()
            fresh = make_engine(cfg, _ecfg(), params, tok,
                                use_kernel=False, prefix_store=remote)
            fresh.restore_sequences(snap)
            while fresh.has_work:
                for r in fresh.step():
                    out[r.seq_id] = r
            assert [out[s].token_ids for s in sids] == baseline
            counts = fresh._counts or {}
            assert counts.get("engine.prefix_hits_l1", 0.0) == 0.0
            assert counts.get("engine.prefix_store_misses_remote", 0) > 0
        finally:
            server.close()

    def test_writethrough_makes_peer_fallback_a_store_hit(self, setup):
        """The disagg fallback shape at engine level: a write-through
        engine (the prefill peer) publishes its resident chains every
        growth tick WITHOUT freeing them; after the peer dies, a fresh
        replica re-running the same prompts serves the prefix from the
        fabric — the fallback re-prefill is a store HIT, not a cold
        recompute."""
        cfg, params, tok = setup
        baseline = _run(make_engine(
            cfg, _ecfg(prefix_cache=False), params, tok,
            use_kernel=False), tok)
        server = StoreServer(host_pages=64, transport="socket")
        try:
            peer = make_engine(
                cfg, _ecfg(prefix_store_writethrough=True), params, tok,
                use_kernel=False, prefix_store=RemoteStore(server=server))
            assert _run(peer, tok) == baseline
            assert (peer._counts or {}).get(
                "engine.prefix_writethrough_pages", 0) > 0
            del peer                          # the peer is gone; store lives
            survivor = make_engine(
                cfg, _ecfg(), params, tok, use_kernel=False,
                prefix_store=RemoteStore(server=server))
            assert _run(survivor, tok) == baseline
            counts = survivor._counts or {}
            assert counts.get("engine.prefix_hits_l1", 0) > 0
        finally:
            server.close()


# ---------------------------------------------------------------------------
# satellite: loud exclusions
# ---------------------------------------------------------------------------


class TestExclusions:
    def test_remote_store_requires_prefix_cache(self, setup):
        cfg, params, tok = setup
        server = StoreServer(host_pages=4, transport="pipe")
        try:
            with pytest.raises(ValueError, match="prefix_cache=True"):
                make_engine(cfg, _ecfg(prefix_cache=False), params, tok,
                            use_kernel=False,
                            prefix_store=RemoteStore(server=server))
        finally:
            server.close()

    def test_remote_store_requires_paged_engine(self, setup):
        cfg, params, tok = setup
        server = StoreServer(host_pages=4, transport="pipe")
        try:
            with pytest.raises(ValueError, match="paged engine"):
                make_engine(
                    cfg, _ecfg(paged=False, prefix_cache=False,
                               page_size=0, num_pages=0), params, tok,
                    prefix_store=RemoteStore(server=server))
        finally:
            server.close()

    def test_watermark_validation(self, setup):
        cfg, params, tok = setup
        with pytest.raises(ValueError, match="paged engine"):
            make_engine(cfg, _ecfg(paged=False, prefix_cache=False,
                                   page_size=0, num_pages=0,
                                   prefix_hbm_watermark=4),
                        params, tok)
        with pytest.raises(ValueError, match=">= 0"):
            make_engine(cfg, _ecfg(prefix_hbm_watermark=-1), params, tok,
                        use_kernel=False)
        with pytest.raises(ValueError, match="over capacity"):
            make_engine(cfg, _ecfg(prefix_hbm_watermark=40), params, tok,
                        use_kernel=False)
        with pytest.raises(ValueError, match="prefix_cache=True"):
            make_engine(cfg, _ecfg(prefix_cache=False,
                                   prefix_hbm_watermark=4), params, tok,
                        use_kernel=False)

    def test_writethrough_requires_a_store(self, setup):
        cfg, params, tok = setup
        with pytest.raises(ValueError, match="write-through"):
            make_engine(cfg, _ecfg(prefix_store_writethrough=True),
                        params, tok, use_kernel=False)

    def test_store_server_validation(self, tmp_path):
        with pytest.raises(ValueError, match="transport"):
            StoreServer(transport="carrier-pigeon")
        with pytest.raises(ValueError, match=">= 0"):
            StoreServer(host_pages=-1)
        with pytest.raises(ValueError, match="disk_dir"):
            StoreServer(host_pages=4, disk_pages=4)
        with pytest.raises(ValueError, match="zero host AND disk"):
            StoreServer(host_pages=0, disk_pages=0)

    def test_remote_store_needs_exactly_one_endpoint(self):
        with pytest.raises(ValueError, match="exactly one"):
            RemoteStore()
        server = StoreServer(host_pages=4, transport="socket")
        try:
            with pytest.raises(ValueError, match="exactly one"):
                RemoteStore(server=server, addr=server.addr)
        finally:
            server.close()

    def test_store_killer_refusals(self):
        from k8s_llm_rca_tpu.faults.soak import run_chaos_soak
        from k8s_llm_rca_tpu.faults.supervisor import StoreKiller

        # unbound killer: no store process to kill
        bare = StoreKiller(FaultPlan.from_spec(
            0, {inject.SITE_STORE: {"indices": {0: "crash"}}}))
        with pytest.raises(ValueError, match="no store bound"):
            bare.checkpoint()
        # soak-level: a StoreKiller without a fabric is refused before
        # any worker spawns
        with pytest.raises(ValueError, match="requires store_fabric"):
            run_chaos_soak(seed=0, n_incidents=1, backend="cluster-oracle",
                           plan_spec={}, killer=bare)
        # SITE_STORE on the ARMED plan is refused: it belongs on the
        # store's own plan
        with pytest.raises(ValueError, match="OWN plan"):
            run_chaos_soak(seed=0, n_incidents=1, plan_spec={
                inject.SITE_STORE: {"indices": {0: "drop"}}})
        # two killers on SITE_STORE: pairwise-disjoint check fires
        other = StoreKiller(FaultPlan.from_spec(1, {}))
        with pytest.raises(ValueError, match="pairwise-disjoint"):
            run_chaos_soak(seed=0, n_incidents=1, backend="cluster-oracle",
                           plan_spec={}, killer=[bare, other])


# ---------------------------------------------------------------------------
# the soak bar: byte-identity with the fabric attached and dying
# ---------------------------------------------------------------------------


class TestSoakByteIdentity:
    def _fabric(self, seed=3):
        return build_store_fabric(
            transport="socket", host_pages=64,
            plan=FaultPlan.from_spec(seed, {inject.SITE_STORE: {
                "indices": {5: "drop", 9: "corrupt"}}}))

    def test_fabric_soak_report_byte_identical(self):
        """A socket fleet with the fabric attached and a StoreKiller
        SIGKILLing/respawning the store mid-sweep must settle
        report_bytes byte-identical to the store-less in-process run —
        kill/heal/miss evidence lives on the killer and fabric objects,
        never in the report."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak
        from k8s_llm_rca_tpu.faults.supervisor import StoreKiller

        n = 8
        base = report_bytes(run_chaos_soak(
            seed=5, n_incidents=n, backend="cluster-oracle",
            plan_spec={}))
        fabric = self._fabric()
        killer = StoreKiller(FaultPlan.from_spec(7, {inject.SITE_STORE: {
            "indices": {2: "crash", 5: "heal"}}}))
        try:
            rep = run_chaos_soak(
                seed=5, n_incidents=n, backend="net-cluster",
                plan_spec={}, killer=killer, store_fabric=fabric)
            assert report_bytes(rep) == base
            assert killer.kills == [2] and killer.heals == [5]
            assert fabric.exercised == n
            assert fabric.misses > 0          # the dead window missed
            assert fabric.hits > 0            # the healed window hit
        finally:
            fabric.close()

    def test_dead_fabric_soak_is_cold_only_and_byte_identical(self):
        """The store dead for the WHOLE sweep: every exercise is a cold
        miss, zero engine errors, and the report still matches."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak

        n = 4
        base = report_bytes(run_chaos_soak(
            seed=6, n_incidents=n, backend="cluster-oracle",
            plan_spec={}))
        fabric = build_store_fabric(transport="socket", host_pages=64)
        try:
            fabric.server.kill()
            rep = run_chaos_soak(
                seed=6, n_incidents=n, backend="net-cluster",
                plan_spec={}, killer=None, store_fabric=fabric)
            assert report_bytes(rep) == base
            assert rep["failed"] == 0
            assert fabric.exercised == n
            assert fabric.misses == n and fabric.hits == 0
        finally:
            fabric.close()

    @pytest.mark.slow
    def test_hundred_incident_store_chaos_soak_twice(self):
        """The acceptance bar: 100 seeded incidents on a socket fleet
        with the fabric attached, a StoreKiller (own plan) plus a
        ProcKiller on a DISJOINT site, the store dying and healing
        repeatedly mid-sweep — report_bytes must equal the store-less
        in-process run's, twice over."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak
        from k8s_llm_rca_tpu.faults.supervisor import ProcKiller, StoreKiller

        n = 100
        base = report_bytes(run_chaos_soak(
            seed=11, n_incidents=n, backend="cluster-oracle",
            plan_spec={}))

        def chaos_run():
            fabric = build_store_fabric(
                transport="socket", host_pages=64,
                plan=FaultPlan.from_spec(13, {inject.SITE_STORE: {
                    "rate": 0.1, "horizon": n,
                    "kinds": ("drop", "corrupt", "delay")}}))
            killers = [
                StoreKiller(FaultPlan.from_spec(17, {inject.SITE_STORE: {
                    "indices": {10: "crash", 25: "heal",
                                55: "crash", 70: "heal"}}})),
                ProcKiller(FaultPlan.from_spec(19, {inject.SITE_PROC: {
                    "indices": {40: "crash"}}})),
            ]
            try:
                rep = run_chaos_soak(
                    seed=11, n_incidents=n, backend="net-cluster",
                    plan_spec={}, killer=killers, store_fabric=fabric,
                    selfheal=True)
                return (report_bytes(rep), tuple(killers[0].kills),
                        tuple(killers[0].heals), fabric.exercised,
                        fabric.hits, fabric.misses)
            finally:
                fabric.close()

        r1 = chaos_run()
        r2 = chaos_run()
        assert r1[0] == base
        assert r1 == r2                       # twice over, all evidence
        assert r1[1] == (10, 55) and r1[2] == (25, 70)
        assert r1[3] == n and r1[5] > 0 and r1[4] > 0


# ---------------------------------------------------------------------------
# StoreFabric bundle
# ---------------------------------------------------------------------------


class TestStoreFabric:
    def test_exercise_counts_and_close(self):
        fabric = build_store_fabric(transport="pipe", host_pages=8)
        for i in range(3):
            fabric.exercise(i)
        assert fabric.exercised == 3
        assert fabric.put_ok == 3 and fabric.hits == 3
        assert fabric.misses == 0
        fabric.close()
        assert not fabric.server.alive()

    def test_fabric_remote_store_survives_respawn(self):
        """The fabric's RemoteStore holds the SERVER handle (not a
        frozen address), so a kill/respawn cycle heals transparently."""
        fabric = build_store_fabric(transport="socket", host_pages=8)
        try:
            fabric.exercise(0)
            fabric.server.kill()
            fabric.exercise(1)                # dead: counted miss
            fabric.server.respawn()
            fabric.exercise(2)                # healed: hit again
            assert fabric.misses == 1 and fabric.hits == 2
        finally:
            fabric.close()
