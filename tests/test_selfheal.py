"""Self-healing cluster (k8s_llm_rca_tpu/cluster/health.py).

Four layers of proof, mirroring the cluster test conventions
(tests/test_cluster.py):

- **watchdog determinism**: the ALIVE -> SUSPECT -> DEAD classifier is a
  pure function of the probe/beat sequence — exact verdict sequences
  under a frozen VirtualClock, fresh signals demote SUSPECT, idle
  replicas never false-positive (the pump beat IS a signal), and the
  probe interval gates evaluations on the injectable clock.
- **auto-failover + rejoin**: a wedged replica (dead process, nobody
  tells the router) is detected by silence, failed over through the SAME
  ``fail_replica`` path an external caller would use, and — with a
  restart-enabled ReplicaSupervisor — rebuilt on its original submesh so
  the fleet returns to N; the restarted engine replica serves new work
  byte-identical to the plain single engine (the parity bar every
  parallelism mode meets).
- **poison-run quarantine**: a run whose replica dies ``quarantine_after``
  times settles FAILED with a named error through the normal pump path,
  so the journal records it and recovery replay agrees.
- **kill-and-heal soak** (the ISSUE acceptance bar): a seeded
  100-incident chaos sweep where every kill is a silent wedge — NO
  external ``fail_replica`` call — completes with the fleet restored to
  N and ``report_bytes`` byte-identical to the unkilled run; plus the
  open-loop Poisson driver (faults/soak.py) and its SRE-storm
  composition with the kill-and-heal machinery.

Loud ValueError exclusions (repo convention): invalid HealthPolicy
knobs, quarantine_after < 1, a watchdog on a single-replica router
without restart, supervisor bind over overlapping submeshes, restart
without a rebuild recipe, selfheal on a non-cluster soak backend.
"""

import json

import pytest

from k8s_llm_rca_tpu.cluster import (
    ALIVE, DEAD, SUSPECT, ClusterRouter, HealthPolicy, HealthWatchdog,
    Replica, ReplicaSupervisor,
)
from k8s_llm_rca_tpu.faults.plan import VirtualClock
from k8s_llm_rca_tpu.serve.backend import EchoBackend, GenOptions
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

pytestmark = pytest.mark.selfheal


def _healing_router(n=2, delay_pumps=0, tok=None, policy=None,
                    quarantine_after=2, restart=True, clock=None):
    """Echo replicas with rebuild recipes behind a self-healing router."""
    tok = tok or get_tokenizer()
    reps = [Replica(i, EchoBackend(tok, delay_pumps=delay_pumps),
                    rebuild=lambda tok=tok, d=delay_pumps: EchoBackend(
                        tok, delay_pumps=d))
            for i in range(n)]
    router = ClusterRouter(reps, quarantine_after=quarantine_after)
    wd = HealthWatchdog(policy or HealthPolicy(miss_budget=1,
                                               hung_tick_threshold=2),
                        clock=clock or VirtualClock())
    sup = ReplicaSupervisor(restart=restart)
    router.attach_health(wd, sup)
    return router, reps, wd, sup


def _settle(router, handles, pumps=64):
    out = {}
    for _ in range(pumps):
        out.update(router.pump())
        if all(h in out for h in handles):
            return out
    raise AssertionError(f"runs never settled: {out.keys()}")


# ---------------------------------------------------------------------------
# watchdog state machine: deterministic verdicts under a frozen clock
# ---------------------------------------------------------------------------


class TestWatchdogStateMachine:
    def test_verdict_sequence_is_exact(self):
        """Probe-count classification: with miss_budget=2 and
        hung_tick_threshold=4 a silent replica goes SUSPECT on the 2nd
        miss and DEAD on the 4th — exactly, run after run, on a frozen
        VirtualClock (misses are per probe evaluation, never per wall
        second)."""
        tok = get_tokenizer()
        router = ClusterRouter([Replica(i, EchoBackend(tok))
                                for i in range(2)])
        wd = HealthWatchdog(HealthPolicy(miss_budget=2,
                                         hung_tick_threshold=4),
                            clock=VirtualClock())
        for rid in (0, 1):
            wd.register(rid)
            wd.beat(rid)
        assert wd.probe(router) == []        # baseline, never a miss
        seen = []
        for _ in range(4):
            wd.beat(1)                       # replica 1 keeps signalling
            dead = wd.probe(router)
            seen.append(wd.state(0))
        assert seen == [ALIVE, SUSPECT, SUSPECT, DEAD]
        assert dead == [0]                   # DEAD surfaced exactly once
        assert wd.detections == [0]
        assert len(wd.mttd_s) == 1
        assert wd.state(1) == ALIVE
        assert wd.probe(router) == []        # already DEAD: not re-reported

    def test_fresh_signal_demotes_suspect_and_resets_misses(self):
        tok = get_tokenizer()
        router = ClusterRouter([Replica(i, EchoBackend(tok))
                                for i in range(2)])
        wd = HealthWatchdog(HealthPolicy(miss_budget=2,
                                         hung_tick_threshold=4),
                            clock=VirtualClock())
        for rid in (0, 1):
            wd.register(rid)
            wd.beat(rid)
        def probe():                         # replica 1 stays healthy
            wd.beat(1)
            return wd.probe(router)

        probe()                              # baseline
        probe()                              # miss 1
        probe()                              # miss 2 -> SUSPECT
        assert wd.state(0) == SUSPECT
        wd.beat(0)                           # the replica comes back
        probe()
        assert wd.state(0) == ALIVE
        # the miss counter reset with the demotion: three MORE silent
        # probes reach SUSPECT again, not DEAD
        for _ in range(3):
            probe()
        assert wd.state(0) == SUSPECT
        assert wd.detections == []

    def test_idle_replica_never_false_positives(self):
        """An idle healthy replica ticks nothing, but its pump completes
        — the router's pump beat keeps it ALIVE forever."""
        router, _, wd, _ = _healing_router(n=2)
        for _ in range(10):
            assert router.pump() == {}
        assert wd.states() == {0: ALIVE, 1: ALIVE}
        assert wd.detections == []

    def test_probe_interval_gates_on_the_injectable_clock(self):
        tok = get_tokenizer()
        router = ClusterRouter([Replica(i, EchoBackend(tok))
                                for i in range(2)])
        clock = VirtualClock()
        wd = HealthWatchdog(HealthPolicy(probe_interval_s=1.0,
                                         miss_budget=1,
                                         hung_tick_threshold=2),
                            clock=clock)
        for rid in (0, 1):
            wd.register(rid)
            wd.beat(rid)
        def probe():                         # replica 1 stays healthy
            wd.beat(1)
            return wd.probe(router)

        probe()                              # baseline evaluation
        for _ in range(8):                   # same instant: all gated
            probe()
        assert wd.state(0) == ALIVE
        clock.advance(1.0)
        probe()                              # miss 1 -> SUSPECT
        assert wd.state(0) == SUSPECT
        clock.advance(1.0)
        assert probe() == [0]                # miss 2 -> DEAD
        assert wd.mttd_s == [2.0]            # last beat -> verdict, virtual


# ---------------------------------------------------------------------------
# auto-failover and restart-and-rejoin on echo replicas
# ---------------------------------------------------------------------------


class TestAutoFailover:
    def test_wedge_heals_to_same_results_as_manual_fail_replica(self):
        """A silent wedge must end exactly where an external
        ``fail_replica`` call ends — same global handles, same texts —
        except the self-healed fleet is back at N."""
        tok = get_tokenizer()
        prompts = [f"p{i}" for i in range(4)]
        # manual baseline (PR 6 semantics): external kill, fleet shrinks
        manual = ClusterRouter([Replica(i, EchoBackend(tok, delay_pumps=2))
                                for i in range(2)])
        mh = [manual.start(p, GenOptions(session=f"s{i}"))
              for i, p in enumerate(prompts)]
        manual.fail_replica(0)
        m_out = _settle(manual, mh)

        router, reps, wd, sup = _healing_router(n=2, delay_pumps=2)
        h = [router.start(p, GenOptions(session=f"s{i}"))
             for i, p in enumerate(prompts)]
        assert {router._handle_map[x][0] for x in h} == {0, 1}
        reps[0].wedge()                      # process dies, nobody told
        out = _settle(router, h, pumps=16)
        assert [out[x].text for x in h] == [m_out[y].text for y in mh]
        assert all(v.error is None for v in out.values())
        # the watchdog drove the whole loop: detect -> failover -> rejoin
        assert wd.detections == [0]
        assert router.failovers == 1
        assert sup.restarts == [0]
        assert sup.incarnations == {0: 1}
        assert len(sup.mttr_s) == 1
        assert router.alive_ids() == [0, 1]  # fleet restored to N
        assert not reps[0].wedged
        # manual fleet stays shrunk — restart is the self-healing delta
        assert manual.alive_ids() == [1]

    def test_single_replica_wedge_restarts_in_place(self):
        """Last-alive heal path: fail_replica would refuse (an outage),
        but with restart the outage is recoverable — the corpse is
        rebuilt in place and its run re-starts on the fresh
        incarnation."""
        router, reps, wd, sup = _healing_router(n=1, delay_pumps=2)
        h = router.start("solo", GenOptions(session="t"))
        reps[0].wedge()
        out = _settle(router, [h], pumps=16)
        assert out[h].error is None
        assert router.failovers == 1         # kind="restart-in-place"
        assert sup.restarts == [0]
        assert router.alive_ids() == [0]
        assert not router.replicas[0].wedged

    def test_pick_routes_new_work_around_suspect(self):
        router, reps, wd, _ = _healing_router(
            n=2, delay_pumps=10 ** 9,
            policy=HealthPolicy(miss_budget=1, hung_tick_threshold=9))
        reps[0].wedge()
        router.pump()                        # baseline probe
        router.pump()                        # miss 1 -> SUSPECT
        assert wd.is_suspect(0)
        # replica 0 has the smaller depth, but new work avoids it
        h = router.start("p", GenOptions())
        assert router._handle_map[h][0] == 1

    def test_pinned_session_unpins_off_a_suspect_replica(self):
        router, reps, wd, _ = _healing_router(
            n=2, delay_pumps=10 ** 9,
            policy=HealthPolicy(miss_budget=1, hung_tick_threshold=9))
        h0 = router.start("p", GenOptions(session="t1"))
        pinned = router._handle_map[h0][0]
        reps[pinned].wedge()
        router.pump()
        router.pump()
        assert wd.is_suspect(pinned)
        h1 = router.start("p", GenOptions(session="t1"))
        other = 1 - pinned
        assert router._handle_map[h1][0] == other
        assert router._affinity["t1"] == other   # re-pinned on healthy


# ---------------------------------------------------------------------------
# restarted ENGINE replica: byte-identical service on the fresh incarnation
# ---------------------------------------------------------------------------


class TestRestartEngineParity:
    def test_restarted_replica_serves_new_work_byte_identically(
            self, cpu_devices):
        """Kill an engine replica mid-decode by wedging it; the watchdog
        detects, the orphan re-runs on the survivor byte-identically,
        the supervisor rebuilds the corpse on its ORIGINAL submesh
        (re-sharding the same host params), and the fresh incarnation
        then serves new work byte-identical to the plain single engine
        — the parity bar every parallelism mode meets."""
        import jax

        from k8s_llm_rca_tpu.cluster import build_replicas
        from k8s_llm_rca_tpu.config import TINY, EngineConfig
        from k8s_llm_rca_tpu.engine import make_engine
        from k8s_llm_rca_tpu.models import llama

        cfg = TINY.replace(max_seq_len=64)
        # paged with decode_chunk=1 (the drain-migration test's config),
        # so the run is genuinely MID-decode when the wedge lands
        ecfg = EngineConfig(max_batch=2, max_seq_len=64,
                            prefill_buckets=(16, 32), max_new_tokens=6,
                            temperature=0.0, paged=True, page_size=8,
                            num_pages=32, decode_chunk=1)
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        prompts = ["pod pending unschedulable node affinity mismatch",
                   "pvc not bound storageclass missing"]
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        ref = make_engine(cfg, ecfg, params, tok,
                          use_kernel=False).generate(
            [tok.encode(p, add_bos=True) for p in prompts],
            max_new_tokens=6)

        replicas = build_replicas(cfg, ecfg, 2, devices=cpu_devices,
                                  seed=0, use_kernel=False)
        router = ClusterRouter(replicas)
        wd = HealthWatchdog(HealthPolicy(miss_budget=1,
                                         hung_tick_threshold=2),
                            clock=VirtualClock())
        sup = ReplicaSupervisor()
        router.attach_health(wd, sup)
        first_engine = replicas[0].backend.engine
        assert first_engine._hb_stamp        # heartbeats are clock-stamped

        h0 = router.start(prompts[0], GenOptions(max_new_tokens=6))
        assert router._handle_map[h0][0] == 0
        for _ in range(3):                   # mid-decode (chunk=1)
            assert not router.pump()
        replicas[0].wedge()                  # the worker process dies
        out = _settle(router, [h0], pumps=64)
        # the orphan re-ran on the survivor, byte-identical greedy text
        assert out[h0].text == ref[0].text
        assert out[h0].error is None
        assert wd.detections == [0]
        assert sup.restarts == [0]
        assert router.alive_ids() == [0, 1]
        fresh = router.replicas[0].backend.engine
        assert fresh is not first_engine     # a NEW incarnation
        assert fresh.obs_replica == 0        # obs identity re-tagged
        assert fresh._hb_stamp

        # the fresh incarnation serves new work byte-identically (both
        # replicas idle: least-depth lowest-id picks the restarted one)
        h1 = router.start(prompts[1], GenOptions(max_new_tokens=6))
        assert router._handle_map[h1][0] == 0
        out = _settle(router, [h1], pumps=64)
        assert out[h1].text == ref[1].text
        assert fresh.heartbeat > 0           # its ticks fed the watchdog


# ---------------------------------------------------------------------------
# poison-run quarantine: journaled settlement, recovery replay agrees
# ---------------------------------------------------------------------------


class TestQuarantine:
    def test_poison_run_quarantined_after_k_deaths(self):
        router, reps, wd, sup = _healing_router(n=2,
                                                delay_pumps=10 ** 9,
                                                quarantine_after=2)
        h = router.start("poison", GenOptions(session="t"))
        for death in range(2):
            rid = router._handle_map[h][0]
            router.replicas[rid].wedge()
            out = {}
            for _ in range(8):
                out.update(router.pump())
                if h in out:
                    break
        res = out[h]
        assert res.error is not None
        assert "quarantined" in res.error
        assert "died 2 times" in res.error
        assert router.quarantined == 1
        assert not router.busy(h)            # fully unmapped
        assert h not in router._deaths       # tracking cleaned up
        # the fleet healed around the poison run both times
        assert router.alive_ids() == [0, 1]
        assert sup.restarts and wd.detections

    def test_surviving_one_death_clears_the_death_count(self):
        """A run that fails over once and then COMPLETES must not leave
        a death count behind (quarantine is per in-flight life, not a
        permanent mark)."""
        router, reps, _, _ = _healing_router(n=2, delay_pumps=2,
                                             quarantine_after=2)
        h = router.start("transient", GenOptions(session="t"))
        reps[router._handle_map[h][0]].wedge()
        out = _settle(router, [h], pumps=16)
        assert out[h].error is None
        assert router._deaths == {}
        assert router.quarantined == 0

    def test_quarantine_is_journaled_and_recovery_agrees(self, tmp_path):
        from k8s_llm_rca_tpu.serve.api import AssistantService, RunStatus
        from k8s_llm_rca_tpu.serve.journal import RunJournal
        from k8s_llm_rca_tpu.serve.recover import recover_service

        path = str(tmp_path / "selfheal.wal")
        tok = get_tokenizer()
        router, reps, _, _ = _healing_router(n=2, delay_pumps=10 ** 9,
                                             tok=tok, quarantine_after=2)
        service = AssistantService(router, journal=RunJournal(path))
        a = service.create_assistant("sre", "answer briefly")
        th = service.create_thread()
        service.add_message(th.id, "what failed?")
        run = service.create_run(th.id, a.id,
                                 gen=GenOptions(max_new_tokens=8))
        h = service.runs[run.id].backend_handle
        for _ in range(2):                   # two fatal incarnations
            router.replicas[router._handle_map[h][0]].wedge()
            for _ in range(8):
                service._pump()
                if service.runs[run.id].status in RunStatus.TERMINAL:
                    break
        live = service.runs[run.id]
        assert live.status == RunStatus.FAILED
        assert "quarantined" in live.error
        service._journal.close()

        fresh_router, _, _, _ = _healing_router(n=2, tok=tok)
        svc, report = recover_service(path, fresh_router)
        # the quarantine settled through the normal pump path, so the
        # journal replay agrees byte-for-byte — never re-executed
        assert report["resubmitted"] == []
        replayed = svc.runs[run.id]
        assert replayed.status == RunStatus.FAILED
        assert replayed.error == live.error


# ---------------------------------------------------------------------------
# loud exclusions
# ---------------------------------------------------------------------------


class TestExclusions:
    @pytest.mark.parametrize("kw,match", [
        (dict(probe_interval_s=-1.0), "probe_interval_s"),
        (dict(miss_budget=0), "miss_budget"),
        (dict(miss_budget=3, hung_tick_threshold=3), "exceed"),
    ])
    def test_invalid_health_policy_rejected(self, kw, match):
        with pytest.raises(ValueError, match=match):
            HealthPolicy(**kw)

    def test_quarantine_threshold_below_one_rejected(self):
        tok = get_tokenizer()
        with pytest.raises(ValueError, match="quarantine_after"):
            ClusterRouter([Replica(0, EchoBackend(tok)),
                           Replica(1, EchoBackend(tok))],
                          quarantine_after=0)

    def test_single_replica_watchdog_without_restart_rejected(self):
        tok = get_tokenizer()
        wd = HealthWatchdog(clock=VirtualClock())
        router = ClusterRouter([Replica(0, EchoBackend(tok))])
        with pytest.raises(ValueError, match="single-replica"):
            router.attach_health(wd)
        router = ClusterRouter([Replica(0, EchoBackend(tok))])
        with pytest.raises(ValueError, match="single-replica"):
            router.attach_health(wd, ReplicaSupervisor(restart=False))
        # a restart-enabled supervisor makes the verdict recoverable
        router = ClusterRouter([Replica(0, EchoBackend(
            tok, delay_pumps=1), rebuild=lambda: EchoBackend(tok))])
        router.attach_health(HealthWatchdog(clock=VirtualClock()),
                             ReplicaSupervisor())
        assert router.health is not None

    def test_overlapping_submeshes_rejected_at_bind(self, cpu_devices):
        from k8s_llm_rca_tpu.config import MeshConfig
        from k8s_llm_rca_tpu.runtime.mesh import build_mesh

        tok = get_tokenizer()
        a = build_mesh(MeshConfig(model=4), devices=cpu_devices[:4])
        b = build_mesh(MeshConfig(model=4), devices=cpu_devices[2:6])
        router = ClusterRouter([Replica(0, EchoBackend(tok), mesh=a),
                                Replica(1, EchoBackend(tok), mesh=b)])
        with pytest.raises(ValueError, match="overlap"):
            router.attach_health(HealthWatchdog(clock=VirtualClock()),
                                 ReplicaSupervisor())

    def test_restart_without_rebuild_recipe_is_loud(self):
        tok = get_tokenizer()
        router = ClusterRouter([Replica(i, EchoBackend(tok))
                                for i in range(2)])
        router.attach_health(
            HealthWatchdog(HealthPolicy(miss_budget=1,
                                        hung_tick_threshold=2),
                           clock=VirtualClock()),
            ReplicaSupervisor())
        router.replicas[0].wedge()
        with pytest.raises(ValueError, match="rebuild recipe"):
            for _ in range(4):
                router.pump()

    def test_restart_before_bind_rejected(self):
        with pytest.raises(ValueError, match="bind"):
            ReplicaSupervisor().restart(0)

    def test_selfheal_requires_cluster_backend(self):
        from k8s_llm_rca_tpu.faults.soak import run_chaos_soak

        with pytest.raises(ValueError, match="cluster"):
            run_chaos_soak(seed=0, n_incidents=1, backend="oracle",
                           selfheal=True)

    def test_poisson_arrivals_validates(self):
        from k8s_llm_rca_tpu.faults.soak import poisson_arrivals

        with pytest.raises(ValueError, match="rate_per_s"):
            poisson_arrivals(0, 0.0, 4)
        with pytest.raises(ValueError, match="n must"):
            poisson_arrivals(0, 1.0, -1)


# ---------------------------------------------------------------------------
# kill-and-heal chaos soak (the acceptance sweep) + open-loop Poisson driver
# ---------------------------------------------------------------------------


def _wedge_killer(seed=2, rate=0.03, horizon=100):
    from k8s_llm_rca_tpu.faults import inject
    from k8s_llm_rca_tpu.faults.plan import FaultPlan
    from k8s_llm_rca_tpu.faults.supervisor import ReplicaKiller

    return ReplicaKiller(FaultPlan.from_spec(
        seed, {inject.SITE_REPLICA: {"rate": rate, "horizon": horizon,
                                     "kinds": ("crash",)}}))


@pytest.mark.chaos
class TestKillAndHealSoak:
    def test_100_incident_kill_and_heal_byte_identical(self):
        """The ISSUE acceptance bar: a 100-incident sweep on oracle
        replicas where every seeded kill is a silent WEDGE — the
        watchdog detects, fails over and the supervisor rejoins, with
        NO external fail_replica call — ends with the fleet restored to
        N and a report byte-identical to the unkilled sweep's (and to a
        rerun of itself: the heal schedule is seeded too)."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak

        base = run_chaos_soak(seed=11, n_incidents=100,
                              backend="cluster-oracle",
                              cluster_replicas=4)
        assert base["completed"] == 100
        assert base["failed"] == 0

        k1 = _wedge_killer()
        healed = run_chaos_soak(seed=11, n_incidents=100,
                                backend="cluster-oracle",
                                cluster_replicas=4, killer=k1,
                                selfheal=True)
        assert k1.kills                      # wedges actually happened
        assert report_bytes(healed) == report_bytes(base)
        router = k1.router
        # the whole loop ran in-tree: one detection, one failover and
        # one restart per kill, fleet back to full strength at the end
        assert router.health.detections == k1.kills
        assert router.supervisor.restarts == k1.kills
        assert router.failovers == len(k1.kills)
        assert sorted(router.alive_ids()) == [0, 1, 2, 3]
        assert all(not r.wedged for r in router.replicas.values())

        k2 = _wedge_killer()
        again = run_chaos_soak(seed=11, n_incidents=100,
                               backend="cluster-oracle",
                               cluster_replicas=4, killer=k2,
                               selfheal=True)
        assert k2.kills == k1.kills          # the wedge schedule is seeded
        assert report_bytes(again) == report_bytes(base)

    @pytest.mark.slow
    def test_engine_cluster_kill_and_heal_byte_identical(self):
        """Engine replicas under a silent wedge: graph-faults-only plan
        (tests/test_cluster.py rationale — survivor tick drift), report
        byte-identical to the unkilled run, every CURRENT engine
        incarnation left clean, fleet restored to N."""
        from k8s_llm_rca_tpu.faults import inject
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak

        spec = {inject.SITE_GRAPH: {
            "rate": 0.10, "horizon": 40, "delay_s": 0.01,
            "kinds": ("error", "timeout", "empty", "slow", "poison")}}
        base = run_chaos_soak(seed=5, n_incidents=2, backend="cluster",
                              plan_spec=spec, cluster_replicas=2)
        assert base["completed"] == 2
        assert base["engine_clean"] is True

        k = _wedge_killer(seed=3, rate=0.6, horizon=2)
        healed = run_chaos_soak(seed=5, n_incidents=2, backend="cluster",
                                plan_spec=spec, cluster_replicas=2,
                                killer=k, selfheal=True)
        assert k.kills                       # the wedge fired mid-sweep
        assert healed["engine_clean"] is True
        assert report_bytes(healed) == report_bytes(base)
        assert sorted(k.router.alive_ids()) == [0, 1]
        assert k.router.supervisor.restarts == k.kills


class TestOpenLoopPoisson:
    def test_arrivals_are_seeded_and_monotone(self):
        from k8s_llm_rca_tpu.faults.soak import poisson_arrivals

        a = poisson_arrivals(7, 100.0, 50)
        assert a == poisson_arrivals(7, 100.0, 50)
        assert a != poisson_arrivals(8, 100.0, 50)
        assert len(a) == 50
        assert all(b < c for b, c in zip(a, a[1:]))
        assert poisson_arrivals(7, 100.0, 0) == []

    def test_open_loop_report_is_deterministic(self):
        from k8s_llm_rca_tpu.faults.soak import run_open_loop_soak

        r1 = run_open_loop_soak(seed=4, rate_per_s=200.0, n_runs=16)
        r2 = run_open_loop_soak(seed=4, rate_per_s=200.0, n_runs=16)
        assert json.dumps(r1, sort_keys=True) == json.dumps(r2,
                                                            sort_keys=True)
        assert r1["completed"] == 16
        assert r1["failed"] == 0
        assert r1["p50_ttr_s"] <= r1["p99_ttr_s"]
        assert r1["fleet_alive"] == 2
        assert [o["i"] for o in r1["outcomes"]] == list(range(16))

    def test_sre_storm_heals_under_open_loop_arrivals(self):
        """The composition the ISSUE names: Poisson arrivals keep
        landing while seeded wedges kill replicas and the watchdog/
        supervisor loop heals the fleet mid-storm.  Deterministic run
        over run; the fleet ends at full strength."""
        from k8s_llm_rca_tpu.faults.soak import run_open_loop_soak

        k1 = _wedge_killer(seed=6, rate=0.2, horizon=24)
        r1 = run_open_loop_soak(seed=4, rate_per_s=200.0, n_runs=24,
                                selfheal=True, killer=k1)
        assert k1.kills                      # the storm drew blood
        assert r1["completed"] + r1["failed"] == 24
        assert r1["fleet_alive"] == 2        # and the fleet healed
        # arrivals land milliseconds apart, so a kill can hit a replica
        # that is ALREADY wedged (killing a dead process) — each wedge
        # WINDOW heals exactly once, so restarts <= kills, never zero
        restarts = k1.router.supervisor.restarts
        assert restarts
        assert len(restarts) <= len(k1.kills)
        assert set(restarts) <= set(k1.kills)
        assert all(not r.wedged
                   for r in k1.router.replicas.values())

        k2 = _wedge_killer(seed=6, rate=0.2, horizon=24)
        r2 = run_open_loop_soak(seed=4, rate_per_s=200.0, n_runs=24,
                                selfheal=True, killer=k2)
        assert k2.kills == k1.kills
        assert json.dumps(r1, sort_keys=True) == json.dumps(r2,
                                                            sort_keys=True)
