"""Cross-host replica tests (cluster/net.py, faults/netem.py, and the
socket half of cluster/proc.py).

Layers, cheapest first:

- **socket codec units** (socketpair, no subprocess): transport
  round-trips, trickle reassembly under ONE shared deadline, the
  bounded write deadline (a zero-window peer raises ``WireTimeout``
  instead of wedging the sender), the ``timeout_s <= 0`` refusal, and
  the ``max_buffered_bytes`` garbage guard.
- **netem proxy units** (socketpair, no subprocess): every SITE_NET
  fault kind — partition/halfopen/heal stickiness, delay on the
  virtual clock, trickle, duplicate, corrupt — applied deterministically
  from a seeded plan, never the armed one.
- **loud exclusions** (no subprocess): unknown transport, zero relink
  budget, partitioning a pipe replica, NetKiller misuse, and the
  pipelined sweep's net-cluster refusal.
- **socket fleet** (real spawns): the relink-vs-respawn decision matrix
  — link death heals the SAME incarnation under a fresh session nonce
  with in-flight runs replayed; SIGKILL still respawns incarnation N+1;
  relink-budget exhaustion converts the outage into hard "link"
  evidence and hands the respawn path the replica.  Plus nonce fencing:
  a stale dial is refused on ITS OWN connection, a newer dial drops the
  old link (no split-brain), and duplicate/stale reply frames are
  discarded, never desync evidence.
- **partition-and-heal soak** (the ISSUE acceptance bar): 100 incidents
  on a socket-oracle fleet under seeded partitions, zero manual
  intervention, report bytes identical to the unpartitioned in-process
  cluster-oracle run — twice over, every heal a relink.
- **engine parity** (slow): greedy byte-parity of a socket
  engine-worker cluster against the plain in-process engine.
"""

from __future__ import annotations

import io
import socket
import subprocess
import sys

import pytest

from k8s_llm_rca_tpu.cluster import (
    ClusterRouter, HealthPolicy, HealthWatchdog, Replica,
    ReplicaSupervisor,
)
from k8s_llm_rca_tpu.cluster.net import (
    SocketTransport, client_handshake, connect_transport,
    send_with_deadline,
)
from k8s_llm_rca_tpu.cluster.proc import (
    build_proc_replicas, worker_env,
)
from k8s_llm_rca_tpu.cluster.wire import (
    FrameReader, WireCorrupt, WireEOF, WireError, WireTimeout, pack_frame,
)
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.faults.netem import NetemTransport
from k8s_llm_rca_tpu.faults.plan import Fault, FaultPlan, VirtualClock
from k8s_llm_rca_tpu.serve.backend import EchoBackend, GenOptions
from k8s_llm_rca_tpu.utils.logging import METRICS
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

pytestmark = pytest.mark.netcluster


def _close_all(router: ClusterRouter) -> None:
    for r in router.replicas.values():
        close = getattr(r, "close", None)
        if close is not None:
            close()


def _settle(router, handles, pumps=64):
    out = {}
    for _ in range(pumps):
        out.update(router.pump())
        if all(h in out for h in handles):
            return out
    raise AssertionError(f"runs never settled: {sorted(out)}")


def _watchdog():
    return HealthWatchdog(HealthPolicy(miss_budget=1,
                                       hung_tick_threshold=2),
                          clock=VirtualClock())


def _net_killer(seed=2, rate=0.03, horizon=100,
                kinds=("partition", "halfopen")):
    from k8s_llm_rca_tpu.faults.supervisor import NetKiller

    return NetKiller(FaultPlan.from_spec(
        seed, {inject.SITE_NET: {"rate": rate, "horizon": horizon,
                                 "kinds": kinds}}))


def _pair():
    """A connected SocketTransport pair over a socketpair — real fds, so
    select deadlines and trickle reassembly behave exactly as on a TCP
    link."""
    a, b = socket.socketpair()
    return SocketTransport(a), SocketTransport(b)


# ---------------------------------------------------------------------------
# socket codec units (socketpair, no subprocess)
# ---------------------------------------------------------------------------


class TestSocketCodec:
    def test_socket_transport_round_trips_frames(self):
        left, right = _pair()
        try:
            msgs = [{"op": "ping", "id": 0},
                    {"op": "start", "id": 1, "nested": {"a": [1, 2]}}]
            for m in msgs:
                left.send(m)
            assert [right.recv(timeout_s=2.0) for _ in msgs] == msgs
        finally:
            left.close()
            right.close()

    def test_trickle_bytes_reassemble_under_one_deadline(self):
        # one frame fed a byte at a time must still decode, and the
        # reader spends ONE shared deadline across all the fills — not a
        # fresh timeout per byte
        left, right = _pair()
        try:
            frame = pack_frame({"op": "pump", "id": 3})
            for i in range(len(frame)):
                left.send_raw(frame[i:i + 1])
            assert right.recv(timeout_s=2.0) == {"op": "pump", "id": 3}
        finally:
            left.close()
            right.close()

    def test_wedged_peer_write_raises_timeout_not_hang(self):
        # the peer never reads: once both kernel buffers fill, the
        # bounded write deadline must surface WireTimeout instead of
        # wedging the sender in a blocking flush
        a, b = socket.socketpair()
        try:
            a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
            b.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
            with pytest.raises(WireTimeout, match="send window wedged"):
                send_with_deadline(a, b"x" * (1 << 22), timeout_s=0.2)
        finally:
            a.close()
            b.close()

    def test_write_deadline_rejects_nonpositive_timeout(self):
        a, b = socket.socketpair()
        try:
            with pytest.raises(ValueError, match="must be > 0"):
                send_with_deadline(a, b"x", timeout_s=0.0)
        finally:
            a.close()
            b.close()

    def test_read_frame_rejects_nonpositive_timeout(self):
        reader = FrameReader(io.BytesIO(pack_frame({"op": "ping"})))
        for bad in (0, 0.0, -1.0):
            with pytest.raises(ValueError, match="must be > 0"):
                reader.read_frame(timeout_s=bad)

    def test_pending_decodes_buffered_only_never_blocks(self):
        left, right = _pair()
        try:
            assert right.pending() is None       # nothing buffered
            left.send({"op": "ping", "id": 0})
            left.send({"op": "ping", "id": 1})
            # one deadlined read pulls bytes in; pending drains the rest
            assert right.recv(timeout_s=2.0)["id"] == 0
            assert right.pending() == {"op": "ping", "id": 1}
            assert right.pending() is None
        finally:
            left.close()
            right.close()

    def test_garbage_spew_bounded_by_max_buffered_bytes(self):
        # a plausible header whose payload never completes: the bounded
        # staging buffer declares corruption instead of growing forever
        from k8s_llm_rca_tpu.cluster.wire import HEADER

        header = HEADER.pack(1 << 20, 0)         # 1 MiB frame, legal size
        spew = header + b"\x00" * (1 << 16)

        class Endless:
            def read1(self, n):
                return spew[:n] if spew else b""

        reader = FrameReader(Endless(), max_buffered_bytes=32768)
        with pytest.raises(WireCorrupt, match="spewing garbage"):
            for _ in range(64):
                reader.read_frame()

    def test_closed_transport_raises_eof_loudly(self):
        left, right = _pair()
        right.close()
        try:
            with pytest.raises(WireEOF, match="already closed"):
                right.send({"op": "ping", "id": 0})
            with pytest.raises(WireEOF, match="already closed"):
                right.recv(timeout_s=0.1)
        finally:
            left.close()


# ---------------------------------------------------------------------------
# netem proxy units (socketpair, no subprocess)
# ---------------------------------------------------------------------------


def _netem_pair(faults):
    """A netem-wrapped transport facing a raw peer, with the given
    faults scheduled on the netem's OWN plan at SITE_NET (one poll per
    send)."""
    left, right = _pair()
    plan = FaultPlan([Fault(inject.SITE_NET, i, k) for i, k in
                      enumerate(faults)])
    return NetemTransport(left, plan), right


class TestNetemProxy:
    def test_partition_is_sticky_until_heal(self):
        netem, peer = _netem_pair(["partition", "heal"])
        try:
            with pytest.raises(WireTimeout, match="partitioned"):
                netem.send({"op": "ping", "id": 0})        # draw 0
            with pytest.raises(WireTimeout, match="partitioned"):
                netem.recv(timeout_s=0.1)                  # still down
            netem.send({"op": "ping", "id": 1})            # draw 1: heal
            assert peer.recv(timeout_s=2.0)["id"] == 1
            assert netem.faults_applied == {"partition": 1, "heal": 1}
        finally:
            netem.close()
            peer.close()

    def test_halfopen_sends_flow_replies_drop(self):
        netem, peer = _netem_pair(["halfopen"])
        try:
            netem.send({"op": "ping", "id": 0})            # send flows
            assert peer.recv(timeout_s=2.0)["id"] == 0
            peer.send({"id": 0, "ok": True})
            with pytest.raises(WireTimeout, match="half-open"):
                netem.recv(timeout_s=0.1)                  # reply dropped
        finally:
            netem.close()
            peer.close()

    def test_trickle_frame_reassembles(self):
        netem, peer = _netem_pair(["trickle"])
        try:
            netem.send({"op": "start", "id": 7, "prompt": "p" * 64})
            got = peer.recv(timeout_s=2.0)
            assert got["id"] == 7 and got["prompt"] == "p" * 64
        finally:
            netem.close()
            peer.close()

    def test_duplicate_reply_delivered_twice(self):
        netem, peer = _netem_pair(["duplicate"])
        try:
            netem.send({"op": "ping", "id": 0})
            peer.recv(timeout_s=2.0)
            peer.send({"id": 0, "ok": True})
            first = netem.recv(timeout_s=2.0)
            second = netem.recv(timeout_s=2.0)   # the duplicate, buffered
            assert first == second == {"id": 0, "ok": True}
        finally:
            netem.close()
            peer.close()

    def test_corrupt_surfaces_wire_corrupt(self):
        netem, peer = _netem_pair(["corrupt"])
        try:
            netem.send({"op": "ping", "id": 0})
            with pytest.raises(WireCorrupt, match="bit-flip"):
                netem.recv(timeout_s=0.5)
        finally:
            netem.close()
            peer.close()

    def test_delay_advances_the_plan_clock_not_wall_time(self):
        left, right = _pair()
        clock = VirtualClock()
        plan = FaultPlan([Fault(inject.SITE_NET, 0, "delay",
                                delay_s=1.5)], clock=clock)
        netem = NetemTransport(left, plan)
        try:
            netem.send({"op": "ping", "id": 0})
            assert clock.time() == 1.5           # virtual, not slept
            assert right.recv(timeout_s=2.0)["id"] == 0
        finally:
            netem.close()
            right.close()

    def test_non_link_fault_kind_is_a_loud_plan_bug(self):
        netem, peer = _netem_pair(["stall"])     # legal kind, wrong site
        try:
            with pytest.raises(ValueError, match="netem cannot apply"):
                netem.send({"op": "ping", "id": 0})
        finally:
            netem.close()
            peer.close()

    def test_netem_polls_its_own_plan_never_the_armed_one(self):
        armed = FaultPlan([Fault(inject.SITE_NET, 0, "partition")])
        netem, peer = _netem_pair([])            # own plan: empty
        try:
            with inject.armed(armed):
                netem.send({"op": "ping", "id": 0})   # must NOT partition
            assert peer.recv(timeout_s=2.0)["id"] == 0
            assert armed.snapshot()["polls"] == {}    # untouched
        finally:
            netem.close()
            peer.close()


# ---------------------------------------------------------------------------
# loud exclusions (no subprocess)
# ---------------------------------------------------------------------------


class TestExclusions:
    def test_unknown_transport_rejected_before_spawn(self):
        with pytest.raises(ValueError, match="unknown proc transport"):
            build_proc_replicas(1, transport="carrier-pigeon")

    def test_zero_relink_budget_rejected(self):
        with pytest.raises(ValueError, match="relink_budget must be"):
            build_proc_replicas(1, transport="socket", relink_budget=0)

    def test_netkiller_refuses_non_socket_victim(self):
        tok = get_tokenizer()
        router = ClusterRouter([Replica(0, EchoBackend(tok)),
                                Replica(1, EchoBackend(tok))])
        router.attach_health(_watchdog())
        k = _net_killer(rate=1.0, horizon=4)
        k.router = router
        with pytest.raises(ValueError, match="needs a socket-transport"):
            k.checkpoint()

    def test_pipelined_sweep_refuses_net_cluster(self):
        from k8s_llm_rca_tpu.faults.soak import run_pipelined_sweep

        with pytest.raises(ValueError, match="chaos-soak-only"):
            run_pipelined_sweep(n_incidents=1, backend="net-cluster")


# ---------------------------------------------------------------------------
# socket fleet (real spawns): the relink-vs-respawn decision matrix
# ---------------------------------------------------------------------------


class TestSocketFleet:
    def test_socket_roundtrip_graceful_close_exits_zero(self):
        (rep,) = build_proc_replicas(1, kind="oracle", transport="socket")
        try:
            b = rep.backend
            assert rep.supports_relink
            assert rep.healthy() and b.proc_liveness() is None
            assert b.link_stats() == {"nonce": 1, "alive": 1,
                                      "relinks": 0}
            h = b.start("node notready", GenOptions())
            assert h >= 0 and b.busy(h)
            out = {}
            for _ in range(20):
                out.update(b.pump())
                if h in out:
                    break
            assert out[h].error is None and out[h].text
        finally:
            rep.close()
        # drain frame crossed the socket -> worker exited 0
        assert rep.backend._proc.poll() == 0

    def test_pipe_replica_has_no_link_to_cut(self):
        (rep,) = build_proc_replicas(1, kind="oracle")   # pipe default
        try:
            assert not rep.supports_relink
            assert rep.backend.link_stats() is None
            assert rep.relink() is False
            with pytest.raises(ValueError, match="cannot partition"):
                rep.partition_link()
        finally:
            rep.close()

    def test_netkiller_without_watchdog_refused(self):
        router = ClusterRouter(build_proc_replicas(
            2, kind="oracle", transport="socket"))
        try:
            k = _net_killer(rate=1.0, horizon=4)
            k.router = router
            with pytest.raises(ValueError, match="attach_health first"):
                k.checkpoint()
        finally:
            _close_all(router)

    def test_partition_relinks_same_incarnation_byte_identical(self):
        """The tentpole path: link severed mid-flight -> link evidence
        (process alive) -> relink under a fresh nonce on the SAME
        incarnation -> orphans replayed in place -> results byte-equal
        to an unpartitioned in-process echo cluster.  No respawn, no
        death verdict."""
        tok = get_tokenizer()
        prompts = [f"incident p{i}" for i in range(4)]
        ref_router = ClusterRouter(
            [Replica(i, EchoBackend(tok, delay_pumps=2))
             for i in range(2)])
        ref_handles = [ref_router.start(p, GenOptions(session=f"s{i}"))
                       for i, p in enumerate(prompts)]
        ref = _settle(ref_router, ref_handles)

        router = ClusterRouter(build_proc_replicas(
            2, kind="echo", echo_delay_pumps=2, transport="socket"))
        try:
            router.attach_health(_watchdog(), ReplicaSupervisor())
            handles = [router.start(p, GenOptions(session=f"s{i}"))
                       for i, p in enumerate(prompts)]
            victim = router._handle_map[handles[0]][0]
            b = router.replicas[victim].backend
            pid = b.pid
            router.replicas[victim].partition_link()
            out = _settle(router, handles)
            for rh, h in zip(ref_handles, handles):
                assert out[h].text == ref[rh].text
                assert out[h].error is None
            # relink, not respawn: same pid, same incarnation, nonce +1
            assert b.pid == pid and b.incarnation == 0
            assert b.link_stats() == {"nonce": 2, "alive": 1,
                                      "relinks": 1}
            assert router.supervisor.relinks == [victim]
            assert router.supervisor.restarts == []
            assert router.health.hard_detections == []
            assert router.failovers == 0
            assert all(r.healthy() for r in router.replicas.values())
        finally:
            _close_all(router)

    def test_halfopen_link_also_heals_by_relink(self):
        router = ClusterRouter(build_proc_replicas(
            2, kind="echo", echo_delay_pumps=2, transport="socket"))
        try:
            router.attach_health(_watchdog(), ReplicaSupervisor())
            h = router.start("p", GenOptions())
            victim = router._handle_map[h][0]
            router.replicas[victim].partition_link(halfopen=True)
            out = _settle(router, [h])
            assert out[h].text == "echo: p" and out[h].error is None
            assert router.supervisor.relinks == [victim]
            assert router.supervisor.restarts == []
            assert router.replicas[victim].backend.incarnation == 0
        finally:
            _close_all(router)

    def test_sigkill_on_socket_fleet_still_respawns(self):
        """The other half of the decision matrix: poll() non-None is
        PROCESS death even on a socket transport — watchdog hard
        evidence of kind "proc", supervisor respawn at incarnation+1,
        never a relink."""
        router = ClusterRouter(build_proc_replicas(
            2, kind="oracle", transport="socket"))
        try:
            router.attach_health(_watchdog(), ReplicaSupervisor())
            old_pid = router.replicas[0].backend.pid
            router.replicas[0].kill_process()
            assert router.replicas[0].evidence_kind() == "proc"
            for _ in range(6):
                if router.replicas[0].healthy():
                    break
                router.pump()
            fresh = router.replicas[0].backend
            assert fresh.pid != old_pid
            assert fresh.incarnation == 1
            assert router.health.hard_detections == [0]
            assert router.health.hard_kinds == ["proc"]
            assert router.supervisor.restarts == [0]
            assert router.supervisor.relinks == []
        finally:
            _close_all(router)

    def test_relink_budget_exhaustion_becomes_link_death(self):
        """A worker whose listener closed after its first adoption:
        every relink dial dies at connect(), the budget converts the
        outage into hard evidence of kind "link", and the watchdog/
        supervisor respawn path takes the replica (fresh incarnation,
        fresh listener)."""
        router = ClusterRouter(build_proc_replicas(
            2, kind="oracle", transport="socket", chaos_max_accepts=1,
            relink_budget=2))
        try:
            router.attach_health(_watchdog(), ReplicaSupervisor())
            victim = 0
            b = router.replicas[victim].backend
            router.replicas[victim].partition_link()
            assert b.pump() == {}                 # records link evidence
            assert b.link_liveness() is not None
            for _ in range(12):
                if router.replicas[victim].healthy():
                    break
                router.pump()
            fresh = router.replicas[victim].backend
            assert fresh is not b and fresh.incarnation == 1
            assert "relink budget exhausted" in (b.proc_liveness() or "")
            assert router.health.hard_detections == [victim]
            assert router.health.hard_kinds == ["link"]
            assert router.supervisor.restarts == [victim]
            assert router.supervisor.relinks == []
        finally:
            _close_all(router)

    def test_stale_nonce_refused_newer_nonce_drops_old_link(self):
        """Nonce fencing, both halves: a dial at the serving nonce is
        refused on ITS OWN connection (the serving link untouched); a
        strictly-newer dial is adopted and the old link is dropped the
        instant of adoption — at most one live link per worker, and the
        superseded parent recovers by relinking above the hijacker."""
        (rep,) = build_proc_replicas(1, kind="oracle", transport="socket")
        try:
            b = rep.backend
            assert b._nonce == 1                  # the spawn-time link
            # stale dial (nonce == serving nonce): refused with a typed
            # error frame on the NEW connection
            sock = socket.create_connection(("127.0.0.1", b._port),
                                            timeout=5.0)
            sock.settimeout(None)
            probe = SocketTransport(sock)
            probe.send({"op": "hello", "inc": 0, "nonce": 1})
            refusal = probe.recv(timeout_s=5.0)
            assert refusal["err"]["type"] == "StaleNonce"
            probe.close()
            # the serving link never noticed
            assert b._rpc("ping")["ok"] is True
            # newer dial: adopted; the worker drops the old link
            hijack, ready = connect_transport("127.0.0.1", b._port,
                                              incarnation=0, nonce=2)
            assert ready["nonce"] == 2
            # clean FIN vs RST depends on whether the worker's close
            # raced our send — _rpc's contract is WireError OR OSError,
            # link evidence recorded either way
            with pytest.raises((WireError, OSError)):
                b._rpc("ping")                    # old link is dead
            assert b.link_liveness() is not None
            assert b.proc_liveness() is None      # process fine
            hijack.close()
            # relink climbs above the hijacker's nonce (attempt at 2 is
            # refused as stale, attempt at 3 adopts) within the budget
            assert rep.relink() is False
            assert rep.relink() is True
            assert b.link_stats() == {"nonce": 3, "alive": 1,
                                      "relinks": 1}
            assert b._rpc("ping")["ok"] is True
        finally:
            rep.close()

    def test_duplicate_and_stale_replies_discarded_not_desync(self):
        """netem 'duplicate' riding the REAL parent<->worker link: the
        second delivery of an already-consumed id is discarded by the
        reply loop (counted, never WireCorrupt), and the next RPC still
        pairs with its own reply."""
        (rep,) = build_proc_replicas(1, kind="oracle", transport="socket")
        try:
            b = rep.backend
            b._transport = NetemTransport(
                b._transport,
                FaultPlan([Fault(inject.SITE_NET, 0, "duplicate")]))
            with METRICS.scoped():
                assert b._rpc("ping")["ok"] is True   # reply duplicated
                assert b._rpc("ping")["ok"] is True   # dup discarded
                assert METRICS.count(
                    "cluster.net_dup_replies_discarded") == 1
        finally:
            rep.close()

    def test_connect_mode_worker_dials_listening_parent(self):
        """The cross-host inversion: the WORKER dials us.  The parent
        still initiates the hello/nonce on the accepted connection, so
        fencing is direction-agnostic; stdin EOF still ends the worker.
        """
        import json as _json

        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        port = listener.getsockname()[1]
        spec = _json.dumps({"kind": "oracle", "incarnation": 0,
                            "replica_id": 0}, sort_keys=True)
        proc = subprocess.Popen(
            [sys.executable, "-m", "k8s_llm_rca_tpu.cluster.proc",
             "--connect", f"127.0.0.1:{port}", spec],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, env=worker_env())
        transport = None
        try:
            listener.settimeout(300.0)            # worker imports first
            conn, _ = listener.accept()
            conn.settimeout(None)
            transport, ready = client_handshake(conn, incarnation=0,
                                                nonce=1)
            assert ready["op"] == "ready" and ready["nonce"] == 1
            transport.send({"op": "ping", "id": 0})
            resp = transport.recv(timeout_s=10.0)
            assert resp["ok"] is True and resp["nonce"] == 1
        finally:
            # leash FIRST: with the conn still up the worker exits 0 on
            # stdin EOF; closing the conn first would send it re-dialing
            proc.stdin.close()
            try:
                rc = proc.wait(timeout=10.0)
            finally:
                if transport is not None:
                    transport.close()
                listener.close()
                proc.stdout.close()
        assert rc == 0

    def test_prometheus_exports_link_gauge_both_ways(self):
        from k8s_llm_rca_tpu.obs.export import prometheus_text

        router = ClusterRouter(build_proc_replicas(
            2, kind="oracle", transport="socket"))
        try:
            router.replicas[1].partition_link()
            router.replicas[1].backend.pump()     # record the evidence
            text = prometheus_text(router=router)
            assert ('cluster_link_alive{replica="0",nonce="1"} 1'
                    in text)
            assert ('cluster_link_alive{replica="1",nonce="1"} 0'
                    in text)
            # link down but the process row still says alive: the
            # link-death-not-process-death signature on one scrape
            pid1 = router.replicas[1].backend.pid
            assert (f'cluster_proc_alive{{replica="1",pid="{pid1}",'
                    f'incarnation="0"}} 1') in text
        finally:
            _close_all(router)

    def test_net_trace_sites_are_registered(self):
        from k8s_llm_rca_tpu.obs.trace import SITES

        assert "cluster.net.partition" in SITES
        assert "cluster.net.relink" in SITES


# ---------------------------------------------------------------------------
# the acceptance bar: 100-incident partition-and-heal soak, byte-identical
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestPartitionAndHealSoak:
    def test_100_incident_partition_and_heal_byte_identical(self):
        """Real loopback sockets severed by a seeded NetKiller, zero
        manual intervention: every partition/halfopen heals by RELINK
        (same incarnation, fresh session nonce) with in-flight runs
        replayed through the journal boundary — and the report is
        byte-identical to the unpartitioned IN-PROCESS cluster-oracle
        run, twice over (the network is a deployment detail, not an
        outcome)."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak

        base = run_chaos_soak(seed=11, n_incidents=100,
                              backend="cluster-oracle",
                              cluster_replicas=4)
        assert base["completed"] == 100
        assert base["failed"] == 0

        k1 = _net_killer()
        healed = run_chaos_soak(seed=11, n_incidents=100,
                                backend="net-cluster",
                                cluster_replicas=4, killer=k1,
                                selfheal=True)
        assert k1.kills                       # partitions actually landed
        assert report_bytes(healed) == report_bytes(base)
        router = k1.router
        # every heal was a relink: same incarnations throughout, no
        # death verdicts, no respawns, no failovers — and no split-brain
        # (each replica's link ends alive under its latest nonce)
        assert router.supervisor.relinks == k1.kills
        assert router.supervisor.restarts == []
        assert router.health.hard_detections == []
        assert router.failovers == 0
        assert sorted(router.alive_ids()) == [0, 1, 2, 3]
        for r in router.replicas.values():
            assert r.backend.incarnation == 0
            stats = r.backend.link_stats()
            assert stats["relinks"] == k1.kills.count(r.replica_id)
            assert stats["nonce"] == 1 + stats["relinks"]
        # the soak's reaping context closed every worker on exit
        for r in router.replicas.values():
            assert r.backend._proc.poll() is not None

        k2 = _net_killer()
        again = run_chaos_soak(seed=11, n_incidents=100,
                               backend="net-cluster",
                               cluster_replicas=4, killer=k2,
                               selfheal=True)
        assert k2.kills == k1.kills           # the schedule is seeded
        assert report_bytes(again) == report_bytes(base)

    def test_net_soak_without_chaos_matches_in_process(self):
        """Transport invariance alone: no killer, no selfheal — the
        socket fleet's report must already be byte-identical to the
        in-process cluster-oracle run."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak

        base = run_chaos_soak(seed=3, n_incidents=6,
                              backend="cluster-oracle")
        net = run_chaos_soak(seed=3, n_incidents=6,
                             backend="net-cluster")
        assert report_bytes(net) == report_bytes(base)
        assert net["backend"] == "cluster-oracle"


# ---------------------------------------------------------------------------
# engine workers: greedy byte-parity over sockets (slow: worker compiles)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestEngineSocketParity:
    def test_socket_engine_cluster_matches_plain_engine(self):
        """Each prompt's greedy text from a 2-worker SOCKET engine
        cluster must be byte-identical to the plain in-process engine's
        on the identical TINY config and seed-0 params — the
        identical-replica invariant, now across a process boundary AND
        a network link."""
        import jax

        from k8s_llm_rca_tpu.config import TINY, EngineConfig
        from k8s_llm_rca_tpu.engine import make_engine
        from k8s_llm_rca_tpu.models import llama

        cfg = TINY.replace(max_seq_len=2560)
        ecfg = EngineConfig(max_batch=4, max_seq_len=2560,
                            prefill_buckets=(2560,), max_new_tokens=96,
                            temperature=0.0, paged=True, page_size=64,
                            num_pages=168, prefix_cache=False,
                            decode_chunk=16)
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        ref_engine = make_engine(cfg, ecfg, params, tok, use_kernel=False)
        prompts = ["pod pending unschedulable node affinity mismatch",
                   "pvc not bound storageclass missing"]
        ref = ref_engine.generate(
            [tok.encode(p, add_bos=True) for p in prompts],
            max_new_tokens=8)

        router = ClusterRouter(build_proc_replicas(
            2, kind="engine", seed=0, transport="socket"))
        try:
            handles = [router.start(p, GenOptions(max_new_tokens=8))
                       for p in prompts]
            assert {router._handle_map[h][0] for h in handles} == {0, 1}
            out = _settle(router, handles, pumps=256)
            for h, r in zip(handles, ref):
                assert out[h].text == r.text   # byte-identical greedy
                assert out[h].error is None
        finally:
            _close_all(router)
