"""Fused weight-dequant matmul kernels (ops/quant_matmul.py).

Three layers of coverage, all hermetic on CPU:

- interpret-mode NUMERIC PARITY of every kernel variant against the
  ``dq()`` XLA reference — the full (bits x scale-layout x consumer-
  shape) matrix, with dims sized past the 256/512 block targets so the
  multi-block grid paths execute (the tests/test_kernels.py pattern);
- ENGINE greedy byte-parity with ``fused_quant_matmul=True`` (the shim
  falls back to the identical dq() expression off-TPU — the flag must be
  token-inert for contiguous, paged and GSPMD-TP serving), plus the
  chunked-prefill tick budget's byte-parity against monolithic prefill;
- LOUD EXCLUSIONS: every unsupported composition documented in
  ops/quant_matmul.py and the prefill_chunk_budget validation raises a
  ValueError with a matching test here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_llm_rca_tpu.config import (
    TINY, TINY_MOE, EngineConfig, MeshConfig,
)
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.models.quant import (
    dq, quantize, quantize_params, repack_nibbles_grouped,
)
from k8s_llm_rca_tpu.ops.quant_matmul import (
    qmm, qmm_experts, qmm_head, quant_matmul, quant_matmul_experts,
    quant_matmul_head,
)

pytestmark = pytest.mark.kernels


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


def _close(got, ref, dtype=jnp.float32):
    tol = 3e-2 if dtype == jnp.bfloat16 else 2e-4
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=tol, atol=tol * float(jnp.max(jnp.abs(ref))))


# ---------------------------------------------------------------------------
# interpret-mode kernel parity: (bits x scale layout x consumer shape)
# ---------------------------------------------------------------------------


class TestKernelParity:
    # dims deliberately exceed the block targets (bm/bn 256, bk 512) so
    # the (m, n, k) grids are multi-block — single-block shapes would
    # never exercise the accumulate-across-k scratch logic
    M, K, N = 320, 640, 384

    @pytest.mark.parametrize("bits", [8, 4])
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_kn_per_column(self, bits, dtype):
        x = _rand(0, (self.M, self.K), dtype)
        w = quantize(_rand(1, (self.K, self.N)), axis=-1, bits=bits,
                     compute_dtype=dtype)
        _close(quant_matmul(x, w), x @ dq(w).astype(dtype), dtype)

    @pytest.mark.parametrize("bits", [8, 4])
    def test_kn_leading_batch_dims(self, bits):
        # [B, S, K] activations flatten through the same kernel
        x = _rand(2, (2, 5, self.K))
        w = quantize(_rand(3, (self.K, self.N)), axis=-1, bits=bits)
        _close(quant_matmul(x, w), x @ dq(w))

    @pytest.mark.parametrize("bits", [8, 4])
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_nk_per_row_head(self, bits, dtype):
        # the lm-head layout: [V, K] table, per-ROW scales, x @ W^T
        x = _rand(4, (2, 3, self.K), dtype)
        w = quantize(_rand(5, (self.N, self.K)), axis=0, bits=bits,
                     compute_dtype=dtype)
        _close(quant_matmul_head(x, w),
               jnp.einsum("bsh,vh->bsv", x, dq(w).astype(dtype)), dtype)

    @pytest.mark.parametrize("bits", [8, 4])
    def test_experts_shared_x(self, bits):
        # "bsh,ehi->bsei": every token through every stacked expert
        e = 3
        x = _rand(6, (2, 4, self.K))
        w = quantize(_rand(7, (e, self.K, self.N)), axis=(0, -1),
                     bits=bits)
        _close(quant_matmul_experts(x, w),
               jnp.einsum("bsh,ehi->bsei", x, dq(w)))

    @pytest.mark.parametrize("bits", [8, 4])
    def test_experts_per_expert_x(self, bits):
        # "bsei,eih->bseh": per-expert activations (the down projection)
        e = 3
        x = _rand(8, (2, 4, e, self.N))
        w = quantize(_rand(9, (e, self.N, self.K)), axis=(0, -1),
                     bits=bits)
        _close(quant_matmul_experts(x, w),
               jnp.einsum("bsei,eih->bseh", x, dq(w)))

    @pytest.mark.parametrize("bits", [8, 4])
    def test_decode_row_shapes(self, bits):
        # the decode hot shape: M=1 token row (single-block M)
        x = _rand(10, (1, self.K))
        w = quantize(_rand(11, (self.K, self.N)), axis=-1, bits=bits)
        _close(quant_matmul(x, w), x @ dq(w))


# ---------------------------------------------------------------------------
# shim dispatch + loud exclusions
# ---------------------------------------------------------------------------


class TestShimsAndExclusions:
    def test_qmm_plain_array_falls_back(self):
        # unquantized weights take the XLA matmul byte-identically
        x, w = _rand(0, (2, 8)), _rand(1, (8, 6))
        assert jnp.array_equal(qmm(x, w), x @ w)

    def test_qmm_quant_cpu_falls_back_byte_identical(self):
        x = _rand(2, (2, 8))
        w = quantize(_rand(3, (8, 6)), axis=-1, bits=4)
        assert jnp.array_equal(qmm(x, w), x @ dq(w))

    def test_qmm_head_and_experts_fall_back_byte_identical(self):
        x = _rand(4, (1, 2, 8))
        head = quantize(_rand(5, (10, 8)), axis=0, bits=8)
        assert jnp.array_equal(
            qmm_head(x, head), jnp.einsum("bsh,vh->bsv", x, dq(head)))
        we = quantize(_rand(6, (3, 8, 6)), axis=(0, -1), bits=8)
        assert jnp.array_equal(
            qmm_experts(x, we), jnp.einsum("bsh,ehi->bsei", x, dq(we)))

    def test_quant_matmul_rejects_plain_array(self):
        with pytest.raises(ValueError, match="QuantTensor"):
            quant_matmul(_rand(0, (2, 8)), _rand(1, (8, 6)))

    def test_quant_matmul_rejects_stacked_weight(self):
        w = quantize(_rand(2, (3, 8, 6)), axis=(0, -1), bits=8)
        with pytest.raises(ValueError, match="quant_matmul_experts"):
            quant_matmul(_rand(3, (2, 8)), w)

    def test_quant_matmul_rejects_per_row_scale(self):
        w = quantize(_rand(4, (8, 6)), axis=0, bits=8)   # scale [8, 1]
        with pytest.raises(ValueError, match="quant_matmul_head"):
            quant_matmul(_rand(5, (2, 8)), w)

    def test_quant_matmul_head_rejects_per_column_scale(self):
        w = quantize(_rand(6, (10, 8)), axis=-1, bits=8)  # scale [1, 8]
        with pytest.raises(ValueError, match="per-row"):
            quant_matmul_head(_rand(7, (1, 2, 8)), w)

    def test_experts_rejects_2d_weight(self):
        w = quantize(_rand(8, (8, 6)), axis=-1, bits=8)
        with pytest.raises(ValueError, match="stacked"):
            quant_matmul_experts(_rand(9, (1, 2, 8)), w)

    def test_shape_mismatch_raises(self):
        w = quantize(_rand(10, (8, 6)), axis=-1, bits=8)
        with pytest.raises(ValueError, match="mismatch"):
            quant_matmul(_rand(11, (2, 12)), w)

    def test_grouped_repack_rejected_globally(self):
        # the shard-local grouped int4 layout must refuse GLOBAL
        # consumption everywhere: dq, gather_rows, and every qmm shim
        from k8s_llm_rca_tpu.models.quant import gather_rows

        w4 = quantize(_rand(12, (8, 16)), axis=-1, bits=4)
        grouped = repack_nibbles_grouped(w4, groups=2)
        x = _rand(13, (2, 8))
        for op in (lambda: dq(grouped),
                   lambda: gather_rows(grouped, jnp.array([0])),
                   lambda: qmm(x, grouped),
                   lambda: qmm_head(_rand(14, (1, 1, 16)), grouped),
                   lambda: qmm_experts(_rand(15, (1, 1, 8)), grouped),
                   lambda: quant_matmul(x, grouped)):
            with pytest.raises(ValueError, match="grouped-repacked"):
                op()

    def test_grouped_repack_rejected_by_quantize_params(self):
        w4 = quantize(_rand(16, (8, 16)), axis=-1, bits=4)
        grouped = repack_nibbles_grouped(w4, groups=2)
        with pytest.raises(ValueError, match="grouped"):
            quantize_params({"layers": [{"w": grouped}]})


# ---------------------------------------------------------------------------
# engine integration: fused_quant_matmul byte-parity (CPU fallback path)
# ---------------------------------------------------------------------------


def _quant_engine(model_cfg, bits=4, fused=False, paged=True, params=None,
                  cp_mesh=None, pp_mesh=None, **ecfg_kw):
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    if params is None:
        params = quantize_params(
            llama.init_params(model_cfg, jax.random.PRNGKey(0)),
            compute_dtype=jnp.float32, bits=bits)
    defaults = dict(max_batch=2, max_seq_len=64, page_size=8,
                    num_pages=64, prefill_buckets=(16, 32, 64),
                    max_new_tokens=6, temperature=0.0, paged=paged,
                    prefix_cache=False)
    defaults.update(ecfg_kw)
    cfg = model_cfg.replace(max_seq_len=64,
                            fused_quant_matmul=fused)
    tok = get_tokenizer(vocab_size=model_cfg.vocab_size)
    kw = {"use_kernel": False} if paged else {}
    if cp_mesh is not None:
        kw["cp_mesh"] = cp_mesh
    if pp_mesh is not None:
        kw["pp_mesh"] = pp_mesh
    return make_engine(cfg, EngineConfig(**defaults), params, tok, **kw), tok


class TestEngineFusedFlagParity:
    # only the flagship int4-paged cell rides the tier-1 gate (each cell
    # compiles two engines, ~5-7 s); the rest run under -m slow
    @pytest.mark.parametrize(
        "paged", [pytest.param(False, marks=pytest.mark.slow), True])
    @pytest.mark.parametrize(
        "bits", [pytest.param(8, marks=pytest.mark.slow), 4])
    def test_greedy_byte_parity(self, paged, bits):
        ref_eng, tok = _quant_engine(TINY, bits=bits, paged=paged)
        fused_eng, _ = _quant_engine(TINY, bits=bits, fused=True,
                                     paged=paged)
        prompts = [tok.encode(t, add_bos=True) for t in
                   ["pod crashloop backoff", "pvc pending why"]]
        ref = ref_eng.generate([list(p) for p in prompts],
                               max_new_tokens=6)
        got = fused_eng.generate([list(p) for p in prompts],
                                 max_new_tokens=6)
        for r, g in zip(ref, got):
            assert r.token_ids == g.token_ids
            assert r.finish_reason == g.finish_reason

    @pytest.mark.slow
    def test_moe_greedy_byte_parity(self):
        # stacked-expert einsums route through qmm_experts
        ref_eng, tok = _quant_engine(TINY_MOE, bits=4)
        fused_eng, _ = _quant_engine(TINY_MOE, bits=4, fused=True)
        p = tok.encode("node notready with pressure", add_bos=True)
        ref = ref_eng.generate([list(p)], max_new_tokens=6)
        got = fused_eng.generate([list(p)], max_new_tokens=6)
        assert ref[0].token_ids == got[0].token_ids

    def test_gspmd_tp_sharded_byte_parity(self, cpu_devices):
        # GSPMD-sharded quantized params: the shim falls back to the
        # dq() expression (pallas has no SPMD partitioning rule), so the
        # fused flag must be token-inert under TP too
        from k8s_llm_rca_tpu.runtime.mesh import build_mesh
        from k8s_llm_rca_tpu.runtime.sharding import (
            llama_param_specs, shard_pytree,
        )

        qp = quantize_params(
            llama.init_params(TINY.replace(max_seq_len=64),
                              jax.random.PRNGKey(0)),
            compute_dtype=jnp.float32, bits=4)
        mesh = build_mesh(MeshConfig(data=2, model=2),
                          devices=cpu_devices[:4])
        sharded = shard_pytree(qp, llama_param_specs(TINY), mesh)
        ref_eng, tok = _quant_engine(TINY, params=qp)
        fused_eng, _ = _quant_engine(TINY, params=sharded, fused=True)
        p = tok.encode("pod pending unschedulable", add_bos=True)
        ref = ref_eng.generate([list(p)], max_new_tokens=6)
        got = fused_eng.generate([list(p)], max_new_tokens=6)
        assert ref[0].token_ids == got[0].token_ids


# ---------------------------------------------------------------------------
# chunked-prefill tick budget
# ---------------------------------------------------------------------------


class TestPrefillChunkBudget:
    def _long_prompt(self, tok):
        p = tok.encode("pod crashloop backoff in namespace prod",
                       add_bos=True)
        # spans several 16-token chunks, but short enough that the
        # 64-token cache cap never truncates it (truncation would shift
        # the chunk count the counter test pins down)
        assert 32 < len(p) <= 64 - 6 - 1
        return p

    @pytest.mark.parametrize(
        "overlap", [False, pytest.param(True, marks=pytest.mark.slow)])
    def test_byte_parity_vs_monolithic(self, overlap):
        ref_eng, tok = _quant_engine(TINY, host_overlap=overlap)
        chunk_eng, _ = _quant_engine(TINY, prefill_chunk_budget=16,
                                     host_overlap=overlap)
        long_p = self._long_prompt(tok)
        short_p = tok.encode("node notready", add_bos=True)
        ref = ref_eng.generate([list(long_p), list(short_p)],
                               max_new_tokens=6)
        got = chunk_eng.generate([list(long_p), list(short_p)],
                                 max_new_tokens=6)
        for r, g in zip(ref, got):
            assert r.token_ids == g.token_ids
            assert r.finish_reason == g.finish_reason
        # every page returned (chunk tables cannot leak)
        chunk_eng.allocator.check()
        assert chunk_eng.allocator.n_free == ref_eng.allocator.n_free

    def test_prefill_chunks_counter_and_timeline(self):
        eng, tok = _quant_engine(TINY, prefill_chunk_budget=16)
        long_p = self._long_prompt(tok)
        eng.generate([list(long_p)], max_new_tokens=4)
        n_chunks = eng._counts.get("engine.prefill_chunks", 0)
        # ceil(len / 16) chunks, each counted once
        assert n_chunks == -(-len(long_p) // 16)
        # prefill token totals match the monolithic accounting exactly
        assert eng._counts.get("engine.prefill_tokens") == len(long_p)

    def test_short_prompt_admits_monolithically(self):
        eng, tok = _quant_engine(TINY, prefill_chunk_budget=32)
        p = tok.encode("node notready", add_bos=True)
        assert len(p) <= 32
        eng.generate([list(p)], max_new_tokens=4)
        assert eng._counts.get("engine.prefill_chunks", 0) == 0

    @pytest.mark.slow
    def test_prefix_cache_composes(self):
        # second submission shares the long prompt as a cached prefix;
        # parity must hold with the cache splitting chunk boundaries
        ref_eng, tok = _quant_engine(TINY, prefix_cache=True)
        chunk_eng, _ = _quant_engine(TINY, prefix_cache=True,
                                     prefill_chunk_budget=16)
        long_p = self._long_prompt(tok)
        tail = tok.encode("node notready")
        prompts = [list(long_p), list(long_p) + tail]
        ref = ref_eng.generate([list(p) for p in prompts],
                               max_new_tokens=6)
        got = chunk_eng.generate([list(p) for p in prompts],
                                 max_new_tokens=6)
        for r, g in zip(ref, got):
            assert r.token_ids == g.token_ids

    def test_cancel_mid_prefill_frees_pages(self):
        eng, tok = _quant_engine(TINY, prefill_chunk_budget=16)
        long_p = self._long_prompt(tok)
        n_free0 = eng.allocator.n_free
        seq = eng.submit(list(long_p), max_new_tokens=4)
        eng.step()                      # first chunk(s) dispatched
        assert eng._prefilling          # still mid-prefill
        assert eng.cancel_seq(seq)
        eng.allocator.check()
        assert eng.allocator.n_free == n_free0
        assert not eng.has_work

    def test_snapshot_mid_prefill_exports_pending_entry(self):
        eng, tok = _quant_engine(TINY, prefill_chunk_budget=16)
        long_p = self._long_prompt(tok)
        eng.submit(list(long_p), max_new_tokens=4)
        eng.step()
        assert eng._prefilling
        snap = eng.snapshot_sequences()
        (entry,) = snap["sequences"]
        assert entry["prompt_ids"] == list(long_p)
        assert entry["generated"] == []
        assert entry["remaining_new_tokens"] == 4

    def test_contiguous_engine_rejects_budget(self):
        with pytest.raises(ValueError, match="paged-engine"):
            _quant_engine(TINY, paged=False, prefill_chunk_budget=16)

    def test_non_page_multiple_budget_rejects(self):
        with pytest.raises(ValueError, match="multiple of page_size"):
            _quant_engine(TINY, prefill_chunk_budget=12)   # 12 % 8 != 0

    def test_cp_mesh_rejects_budget(self, cpu_devices):
        from k8s_llm_rca_tpu.runtime.mesh import build_mesh

        mesh = build_mesh(MeshConfig(seq=2), devices=cpu_devices[:2])
        with pytest.raises(ValueError, match="cp_mesh"):
            _quant_engine(TINY, prefill_chunk_budget=16,
                          prefix_cache=False, cp_mesh=mesh)

    def test_pp_mesh_rejects_budget(self, cpu_devices):
        from k8s_llm_rca_tpu.runtime.mesh import build_mesh

        mesh = build_mesh(MeshConfig(stage=2), devices=cpu_devices[:2])
        with pytest.raises(ValueError, match="pp_mesh"):
            _quant_engine(TINY, prefill_chunk_budget=16, pp_mesh=mesh)
