"""Elastic fleet autoscaler tests (cluster/autoscale.py).

Layers, cheapest first:

- **ScalePolicy / constructor exclusions**: every watermark, hysteresis
  and cooldown nonsense value raises a loud ValueError, as do fleets
  without a health watchdog or restart-enabled supervisor and reserves
  with missing rebuild recipes or colliding ids.
- **decision sequences** (frozen VirtualClock, scripted replicas): the
  exact ``decisions`` list for a scripted gauge history — sustain
  thresholds, the hysteresis dead band, cooldown sit-outs, and the
  at-most-one-action-per-tick rule.
- **actuators**: scale-up spawns through the supervisor rebuild-recipe
  path onto a reserve submesh (loud refusal when none is free or the
  fleet is at max_replicas); scale-down drains the least-loaded worker
  (live runs migrate by deterministic re-start), retires it through
  ``close()`` and parks the submesh back on the reserve; rebalance
  moves a worker between TierRouter tiers via ``reassign_tier`` with
  settled-text byte parity against static tiers.
- **killer shield**: ReplicaKiller/HandoffKiller refuse (naming the
  victim) to target a worker mid-drain or mid-retire.
- **membership exclusions**: add_replica/remove_replica/reassign_tier
  refuse duplicate ids, in-flight removals, last-alive removals, tier
  emptying, seam mismatches and phase flips with queued work.
- **elastic soak** (faults/soak.py): ``run_elastic_soak`` is
  byte-deterministic, the chaos variant with killers armed DURING
  scale events settles byte-identical twice over, and (slow) the
  diurnal-ramp acceptance bar — elastic p99 time-to-report <= static
  with strictly fewer chip-seconds.
"""

from __future__ import annotations

import json

import pytest

from k8s_llm_rca_tpu.cluster import (
    Autoscaler, HealthPolicy, HealthWatchdog, Replica, ReplicaSupervisor,
    ScalePolicy, TierRouter, TIER_DECODE, TIER_PREFILL, ClusterRouter,
)
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.faults.plan import Fault, FaultPlan, VirtualClock
from k8s_llm_rca_tpu.faults.soak import (
    diurnal_arrivals, metered_echo_class, report_bytes, run_elastic_soak,
)
from k8s_llm_rca_tpu.faults.supervisor import HandoffKiller, ReplicaKiller
from k8s_llm_rca_tpu.serve.backend import EchoBackend, GenOptions
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

pytestmark = pytest.mark.autoscale


@pytest.fixture(scope="module")
def tok():
    return get_tokenizer()


def _mk(rid, tok, settle_per_pump=1):
    cls = metered_echo_class()
    return Replica(rid, cls(tok, settle_per_pump),
                   rebuild=lambda: cls(tok, settle_per_pump))


def _fleet(n_active, n_reserve, tok, policy=None, clock=None, **kw):
    """Plain elastic fleet: ``n_active`` metered-echo replicas serving,
    ``n_reserve`` parked on the autoscaler's reserve."""
    clock = clock if clock is not None else VirtualClock()
    replicas = [_mk(i, tok) for i in range(n_active + n_reserve)]
    router = ClusterRouter(replicas[:n_active])
    router.attach_health(HealthWatchdog(None, clock=clock),
                         ReplicaSupervisor())
    scaler = Autoscaler(router, policy, reserve=replicas[n_active:],
                        clock=clock, **kw)
    return router, scaler, clock


def _tier_fleet(n_prefill, n_decode, tok, policy=None, reserve=0):
    clock = VirtualClock()
    mk = lambda i: _mk(i, tok)                              # noqa: E731
    router = TierRouter([mk(i) for i in range(n_prefill)],
                        [mk(n_prefill + i) for i in range(n_decode)])
    router.attach_health(HealthWatchdog(None, clock=clock),
                         ReplicaSupervisor())
    parked = [mk(n_prefill + n_decode + i) for i in range(reserve)]
    scaler = Autoscaler(router, policy, reserve=parked, clock=clock)
    return router, scaler, clock


def _settle(router, handles, pumps=64):
    out = {}
    for _ in range(pumps):
        out.update(router.pump())
        if all(h in out for h in handles):
            return out
    raise AssertionError(f"runs never settled: {sorted(out)}")


# ---------------------------------------------------------------------------
# ScalePolicy / constructor exclusions
# ---------------------------------------------------------------------------


class TestScalePolicy:

    @pytest.mark.parametrize("kw,msg", [
        (dict(high_water=0.0), "high_water must be positive"),
        (dict(high_water=-1.0), "high_water must be positive"),
        (dict(low_water=-0.1), "hysteresis band"),
        (dict(low_water=0.8, high_water=0.8), "hysteresis band"),
        (dict(low_water=0.9, high_water=0.8), "hysteresis band"),
        (dict(depth_capacity=0), "depth_capacity must be >= 1"),
        (dict(sustain_ticks=0), "sustain_ticks must be >= 1"),
        (dict(cooldown_ticks=-1), "cooldown_ticks must be >= 0"),
        (dict(min_replicas=0), "min_replicas must be >= 1"),
        (dict(min_replicas=2, max_replicas=2),
         "max_replicas must exceed min_replicas"),
        (dict(min_replicas=4, max_replicas=2),
         "max_replicas must exceed min_replicas"),
        (dict(rebalance_band=0.0), "rebalance_band must sit in"),
        (dict(rebalance_band=1.0), "rebalance_band must sit in"),
        (dict(rebalance_sustain_ticks=0),
         "rebalance_sustain_ticks must be >= 1"),
    ])
    def test_loud_validation(self, kw, msg):
        with pytest.raises(ValueError, match=msg):
            ScalePolicy(**kw)

    def test_defaults_are_valid(self):
        pol = ScalePolicy()
        assert pol.low_water < pol.high_water

    def test_requires_health_watchdog(self, tok):
        router = ClusterRouter([_mk(0, tok)])
        with pytest.raises(ValueError, match="health-attached router"):
            Autoscaler(router)

    def test_requires_restart_enabled_supervisor(self, tok):
        router = ClusterRouter([_mk(0, tok), _mk(1, tok)])
        router.attach_health(HealthWatchdog(None, clock=VirtualClock()),
                             ReplicaSupervisor(restart=False))
        with pytest.raises(ValueError, match="restart-enabled"):
            Autoscaler(router)

    def test_reserve_needs_rebuild_recipe(self, tok):
        cls = metered_echo_class()
        router = ClusterRouter([_mk(0, tok)])
        router.attach_health(HealthWatchdog(None, clock=VirtualClock()),
                             ReplicaSupervisor())
        bare = Replica(1, cls(tok, 1))          # no rebuild recipe
        with pytest.raises(ValueError, match="no rebuild recipe"):
            Autoscaler(router, reserve=[bare])

    def test_reserve_id_collision(self, tok):
        router = ClusterRouter([_mk(0, tok)])
        router.attach_health(HealthWatchdog(None, clock=VirtualClock()),
                             ReplicaSupervisor())
        with pytest.raises(ValueError, match="collides"):
            Autoscaler(router, reserve=[_mk(0, tok)])

    def test_reserve_is_parked_not_alive(self, tok):
        _, scaler, _ = _fleet(1, 2, tok)
        assert [r.replica_id for r in scaler.reserve] == [1, 2]
        assert all(not r.alive for r in scaler.reserve)


# ---------------------------------------------------------------------------
# decision sequences under a frozen VirtualClock
# ---------------------------------------------------------------------------


class TestDecisionSequence:

    def test_scale_up_needs_sustained_high_water(self, tok):
        pol = ScalePolicy(high_water=0.75, low_water=0.1,
                          depth_capacity=2, sustain_ticks=3,
                          cooldown_ticks=0, max_replicas=4)
        router, scaler, _ = _fleet(1, 1, tok, pol)
        opts = GenOptions(max_new_tokens=4)
        for i in range(6):                      # depth 6 on replica 0
            router.start(f"incident {i}", opts)
        assert scaler.evaluate() is None        # tick 1: over = 1
        assert scaler.evaluate() is None        # tick 2: over = 2
        d = scaler.evaluate()                   # tick 3: over = 3 -> up
        assert d == {"tick": 3, "kind": "up", "tier": "all",
                     "replica": 1, "fleet": 2}
        assert scaler.scale_ups == 1
        assert router.replicas[1].alive
        assert router.supervisor.incarnations[1] == 1   # rebuild spawn
        assert scaler.reserve == []

    def test_one_noisy_sample_does_not_flap(self, tok):
        pol = ScalePolicy(high_water=0.75, low_water=0.1,
                          depth_capacity=2, sustain_ticks=2,
                          cooldown_ticks=0)
        router, scaler, _ = _fleet(1, 1, tok, pol)
        opts = GenOptions(max_new_tokens=4)
        handles = [router.start(f"i{i}", opts) for i in range(4)]
        assert scaler.evaluate() is None        # over = 1
        _settle(router, handles)                # gauge falls back to 0...
        router.start("keepalive", opts)         # ...well inside the band
        assert scaler.evaluate() is None        # over RESET, under = 0
        assert scaler.evaluate() is None
        assert scaler.decisions == []

    def test_hysteresis_dead_band_takes_no_action(self, tok):
        pol = ScalePolicy(high_water=2.0, low_water=0.1,
                          depth_capacity=2, sustain_ticks=1,
                          cooldown_ticks=0)
        router, scaler, _ = _fleet(1, 1, tok, pol)
        router.start("inside the band", GenOptions(max_new_tokens=4))
        for _ in range(5):                      # load = 0.5: low < 0.5 < high
            assert scaler.evaluate() is None
        assert scaler.decisions == []

    def test_cooldown_pauses_actions_not_counters(self, tok):
        pol = ScalePolicy(high_water=0.75, low_water=0.1,
                          depth_capacity=2, sustain_ticks=1,
                          cooldown_ticks=2, max_replicas=4)
        router, scaler, _ = _fleet(1, 3, tok, pol)
        opts = GenOptions(max_new_tokens=4)
        for i in range(12):
            router.start(f"i{i}", opts)
        d1 = scaler.evaluate()                  # tick 1: up
        assert d1["kind"] == "up" and d1["tick"] == 1
        assert scaler.evaluate() is None        # tick 2: cooldown
        assert scaler.evaluate() is None        # tick 3: cooldown
        d2 = scaler.evaluate()                  # tick 4: up again
        assert d2["kind"] == "up" and d2["tick"] == 4
        assert [d["replica"] for d in scaler.decisions] == [1, 2]

    def test_scale_down_after_sustained_idle(self, tok):
        pol = ScalePolicy(high_water=0.75, low_water=0.25,
                          depth_capacity=2, sustain_ticks=2,
                          cooldown_ticks=0, min_replicas=1)
        router, scaler, _ = _fleet(2, 0, tok, pol)
        assert scaler.evaluate() is None        # under = 1
        d = scaler.evaluate()                   # under = 2 -> down
        assert d["kind"] == "down" and d["replica"] == 0
        assert d["migrated"] == 0
        assert sorted(router.replicas) == [1]
        # the retired worker is parked back on the reserve: submesh freed
        assert [r.replica_id for r in scaler.reserve] == [0]
        assert not scaler.reserve[0].alive
        # floor: the survivor is the last one, never retired
        assert scaler.evaluate() is None
        assert scaler.evaluate() is None
        assert len(router.replicas) == 1

    def test_evaluate_waits_instead_of_raising_at_capacity(self, tok):
        pol = ScalePolicy(high_water=0.5, low_water=0.1,
                          depth_capacity=1, sustain_ticks=1,
                          cooldown_ticks=0)
        router, scaler, _ = _fleet(1, 0, tok, pol)   # empty reserve
        for i in range(4):
            router.start(f"i{i}", GenOptions(max_new_tokens=4))
        for _ in range(3):
            assert scaler.evaluate() is None    # hot, but nothing to spawn
        assert scaler.decisions == []


# ---------------------------------------------------------------------------
# actuators: refusals and live-run migration
# ---------------------------------------------------------------------------


class TestActuators:

    def test_scale_up_refuses_empty_reserve(self, tok):
        router, scaler, _ = _fleet(1, 0, tok)
        with pytest.raises(ValueError, match="no free submesh"):
            scaler.scale_up()

    def test_scale_up_refuses_past_max_replicas(self, tok):
        pol = ScalePolicy(min_replicas=1, max_replicas=2)
        router, scaler, _ = _fleet(2, 1, tok, pol)
        with pytest.raises(ValueError, match="max_replicas"):
            scaler.scale_up()

    def test_scale_down_refuses_min_replicas_floor(self, tok):
        pol = ScalePolicy(min_replicas=2, max_replicas=4)
        router, scaler, _ = _fleet(2, 0, tok, pol)
        with pytest.raises(ValueError, match="min_replicas"):
            scaler.scale_down()

    def test_scale_down_migrates_live_runs(self, tok):
        router, scaler, _ = _fleet(2, 0, tok)
        opts = GenOptions(max_new_tokens=4)
        handles = [router.start(f"incident {i}", opts) for i in range(6)]
        victim = min(router.replicas,
                     key=lambda r: (router.replicas[r].queue_depth(), r))
        d = scaler.scale_down()
        assert d["replica"] == victim
        assert d["migrated"] > 0                # live runs moved, not lost
        assert router.migrated_runs == d["migrated"]
        survivor = [r for r in (0, 1) if r != victim][0]
        assert sorted(router.replicas) == [survivor]
        out = _settle(router, handles)
        assert len(out) == 6
        assert all(res.error is None for res in out.values())

    def test_tiered_scale_up_requires_tier(self, tok):
        router, scaler, _ = _tier_fleet(1, 1, tok, reserve=1)
        with pytest.raises(ValueError, match="needs the tier"):
            scaler.scale_up()
        d = scaler.scale_up(TIER_DECODE)
        assert d["tier"] == TIER_DECODE
        assert router.decode_ids == [1, 2]

    def test_tiered_scale_down_keeps_last_member(self, tok):
        pol = ScalePolicy(min_replicas=1, max_replicas=8)
        router, scaler, _ = _tier_fleet(1, 2, tok, pol)
        with pytest.raises(ValueError, match="last healthy"):
            scaler.scale_down(TIER_PREFILL)
        d = scaler.scale_down(TIER_DECODE)      # 2 members: allowed
        assert d["tier"] == TIER_DECODE
        assert len(router.decode_ids) == 1


# ---------------------------------------------------------------------------
# tier rebalance: decision flow + settled-text byte parity
# ---------------------------------------------------------------------------


class TestRebalance:

    def test_rebalance_requires_tier_router(self, tok):
        _, scaler, _ = _fleet(2, 0, tok)
        with pytest.raises(ValueError, match="needs a TierRouter"):
            scaler.rebalance(TIER_PREFILL, TIER_DECODE)

    def test_rebalance_keeps_one_fat_member(self, tok):
        _, scaler, _ = _tier_fleet(1, 1, tok)
        with pytest.raises(ValueError, match="must keep one"):
            scaler.rebalance(TIER_PREFILL, TIER_DECODE)

    def _run(self, tok, rebalance):
        """Decode-heavy phase mix on 3P+2D scripted tiers: prefill hands
        off instantly, metered decode queues build, so the hot tier is
        decode and the fat tier is prefill."""
        clock = VirtualClock()
        router = TierRouter([_mk(i, tok) for i in range(3)],
                            [_mk(3 + i, tok) for i in range(2)])
        router.attach_health(HealthWatchdog(None, clock=clock),
                             ReplicaSupervisor())
        scaler = None
        if rebalance:
            pol = ScalePolicy(high_water=9.0, low_water=0.01,
                              depth_capacity=2, sustain_ticks=99,
                              cooldown_ticks=1, rebalance_band=0.5,
                              rebalance_sustain_ticks=2,
                              min_replicas=1, max_replicas=8)
            scaler = Autoscaler(router, pol, clock=clock)
        opts = GenOptions(max_new_tokens=8)
        handles = [router.start(f"incident {i}: pod crashloop", opts)
                   for i in range(12)]
        texts = {}
        for _ in range(60):
            if scaler is not None:
                scaler.evaluate()
            for h, res in router.pump().items():
                texts[h] = res.text
            clock.sleep(0.01)
            if len(texts) == len(handles):
                break
        return texts, router, scaler

    def test_phase_mix_shift_rebalances_with_byte_parity(self, tok):
        elastic, router, scaler = self._run(tok, rebalance=True)
        static, _, _ = self._run(tok, rebalance=False)
        assert scaler.rebalances >= 1
        kinds = [d["kind"] for d in scaler.decisions]
        assert set(kinds) == {"rebalance"}
        first = scaler.decisions[0]
        assert first["src_tier"] == TIER_PREFILL
        assert first["tier"] == TIER_DECODE
        # the mover changed phase for real
        assert first["replica"] in router.decode_ids
        assert first["replica"] not in router.prefill_ids
        # no in-flight run lost: settled texts byte-identical to the
        # static-tier twin
        b_e = json.dumps(elastic, sort_keys=True).encode()
        b_s = json.dumps(static, sort_keys=True).encode()
        assert b_e == b_s
        assert len(elastic) == 12


# ---------------------------------------------------------------------------
# killer shield: no kills inside the drain/retire window
# ---------------------------------------------------------------------------


class TestKillerShield:

    def _armed_killer(self, router):
        plan = FaultPlan([Fault(inject.SITE_REPLICA, 0, "crash")])
        return ReplicaKiller(plan, router=router, mode="auto")

    def test_replica_killer_refuses_mid_drain(self, tok):
        router, scaler, _ = _fleet(2, 0, tok)
        router.replicas[0].draining = True
        killer = self._armed_killer(router)
        with pytest.raises(ValueError, match=r"replica 0 .* mid-drain"):
            killer.checkpoint()

    def test_replica_killer_refuses_mid_retire(self, tok):
        router, scaler, _ = _fleet(2, 0, tok)
        router.replicas[0].retiring = True
        killer = self._armed_killer(router)
        with pytest.raises(ValueError, match=r"replica 0 .* mid-retire"):
            killer.checkpoint()

    def test_refusal_names_killer_and_victim(self, tok):
        router, scaler, _ = _fleet(2, 0, tok)
        router.replicas[0].draining = True
        killer = self._armed_killer(router)
        with pytest.raises(ValueError, match="ReplicaKiller"):
            killer.checkpoint()

    def test_handoff_killer_refuses_mid_drain_source(self, tok):
        router, scaler, _ = _tier_fleet(1, 1, tok)
        plan = FaultPlan([Fault(inject.SITE_HANDOFF, 0, "crash")])
        killer = HandoffKiller(plan, router=router, target="prefill")
        router.replicas[0].draining = True
        with pytest.raises(ValueError,
                           match=r"HandoffKiller refuses replica 0"):
            killer.window(router, ghandle=1, src_rid=0, dst_rid=1)

    def test_clean_replica_still_killable(self, tok):
        router, scaler, _ = _fleet(2, 0, tok)
        killer = self._armed_killer(router)
        victim = killer.checkpoint()            # nothing mid-scale
        assert victim == 0
        assert router.replicas[0].wedged


# ---------------------------------------------------------------------------
# fleet membership exclusions (router/disagg seams the autoscaler drives)
# ---------------------------------------------------------------------------


class TestMembershipExclusions:

    def test_add_replica_refuses_duplicate_id(self, tok):
        router, _, _ = _fleet(2, 0, tok)
        with pytest.raises(ValueError, match="already in the fleet"):
            router.add_replica(_mk(1, tok))

    def test_plain_router_refuses_tier_argument(self, tok):
        router, _, _ = _fleet(1, 0, tok)
        with pytest.raises(ValueError, match="has no tiers"):
            router.add_replica(_mk(1, tok), tier=TIER_PREFILL)

    def test_remove_replica_refuses_inflight(self, tok):
        router, _, _ = _fleet(2, 0, tok)
        h = router.start("live run", GenOptions(max_new_tokens=4))
        rid = router._handle_map[h][0]
        with pytest.raises(ValueError, match="in-flight"):
            router.remove_replica(rid)

    def test_remove_replica_refuses_last_alive(self, tok):
        router, _, _ = _fleet(1, 0, tok)
        with pytest.raises(ValueError, match="outage, not a scale-down"):
            router.remove_replica(0)

    def test_tier_add_requires_valid_tier(self, tok):
        router, _, _ = _tier_fleet(1, 1, tok)
        with pytest.raises(ValueError, match="tier"):
            router.add_replica(_mk(7, tok), tier=None)

    def test_tier_add_refuses_seam_mismatch(self, tok):
        router, _, _ = _tier_fleet(1, 1, tok)

        class _FakeSeam(EchoBackend):
            def export_run(self, *a, **kw):     # engine-seam marker
                raise NotImplementedError

        seam = Replica(9, _FakeSeam(tok), rebuild=lambda: _FakeSeam(tok))
        with pytest.raises(ValueError, match="seam"):
            router.add_replica(seam, tier=TIER_DECODE)

    def test_tier_remove_refuses_emptying_tier(self, tok):
        router, _, _ = _tier_fleet(1, 2, tok)
        with pytest.raises(ValueError, match="empty tier cannot serve"):
            router.remove_replica(0)

    def test_reassign_refuses_inflight_phase_flip(self, tok):
        router, _, _ = _tier_fleet(2, 1, tok)
        h = router.start("queued", GenOptions(max_new_tokens=4))
        rid = router._handle_map[h][0]
        with pytest.raises(ValueError, match="drain it first"):
            router.reassign_tier(rid, TIER_DECODE)

    def test_reassign_refuses_emptying_donor(self, tok):
        router, _, _ = _tier_fleet(1, 2, tok)
        with pytest.raises(ValueError, match="last"):
            router.reassign_tier(0, TIER_DECODE)


# ---------------------------------------------------------------------------
# elastic soak: determinism, chaos-during-scale, the acceptance bar
# ---------------------------------------------------------------------------

_FAST_SOAK = dict(seed=0, rate_low_per_s=60.0, rate_high_per_s=800.0,
                  period_s=0.3, n_runs=96)


class TestElasticSoak:

    def test_diurnal_arrivals_deterministic_and_monotone(self):
        a = diurnal_arrivals(7, 50.0, 500.0, 1.0, 64)
        b = diurnal_arrivals(7, 50.0, 500.0, 1.0, 64)
        assert a == b and len(a) == 64
        assert all(t1 > t0 for t0, t1 in zip(a, a[1:]))
        with pytest.raises(ValueError, match="rate_low"):
            diurnal_arrivals(7, 0.0, 500.0, 1.0, 8)
        with pytest.raises(ValueError, match="period_s"):
            diurnal_arrivals(7, 50.0, 500.0, 0.0, 8)

    def test_elastic_soak_is_byte_deterministic(self):
        r1 = run_elastic_soak(**_FAST_SOAK)
        r2 = run_elastic_soak(**_FAST_SOAK)
        assert report_bytes(r1["report"]) == report_bytes(r2["report"])
        assert r1["stats"] == r2["stats"]
        assert r1["stats"]["scale_ups"] >= 1    # the ramp actually fired
        assert r1["report"]["completed"] == _FAST_SOAK["n_runs"]
        assert r1["report"]["failed"] == 0

    def test_chaos_during_scale_settles_byte_identical(self):
        def killer():
            # crashes polled at arrival boundaries that land inside the
            # ramp (scale events in flight) and at the peak
            plan = FaultPlan([Fault(inject.SITE_REPLICA, 20, "crash"),
                              Fault(inject.SITE_REPLICA, 60, "crash")])
            return ReplicaKiller(plan)

        k1 = run_elastic_soak(killer=killer(), **_FAST_SOAK)
        k2 = run_elastic_soak(killer=killer(), **_FAST_SOAK)
        assert report_bytes(k1["report"]) == report_bytes(k2["report"])
        assert k1["stats"]["kills"] == 2
        assert k1["report"]["completed"] == _FAST_SOAK["n_runs"]
        assert k1["report"]["failed"] == 0
        # the fleet healed: every remaining member is healthy
        assert all(r.healthy()
                   for r in k1["router"].replicas.values())
        # scale stats live on the harness, never in the report
        assert "scale_ups" not in k1["report"]

    def test_soak_validates_elastic_band(self):
        with pytest.raises(ValueError, match="elastic band"):
            run_elastic_soak(n_min=4, n_max=4)

    @pytest.mark.slow
    def test_diurnal_ramp_acceptance_bar(self):
        """The ISSUE acceptance bar: under the open-loop Poisson diurnal
        ramp, the elastic fleet's p99 time-to-report is <= the static
        n_max fleet's, with STRICTLY fewer chip-seconds."""
        elastic = run_elastic_soak(seed=0, elastic=True)
        static = run_elastic_soak(seed=0, elastic=False)
        re_, rs = elastic["report"], static["report"]
        assert re_["completed"] == rs["completed"] == 520
        assert re_["failed"] == rs["failed"] == 0
        assert re_["p99_ttr_s"] <= rs["p99_ttr_s"]
        assert re_["chip_seconds"] < rs["chip_seconds"]
        # the fleet actually breathed: grew into the ramp, drained the
        # far side of the peak
        assert elastic["stats"]["scale_ups"] >= 3
        assert elastic["stats"]["scale_downs"] >= 1
        assert static["stats"]["scale_ups"] == 0
