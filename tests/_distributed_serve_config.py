"""ONE definition of the multi-process serving scenario, imported by BOTH
tests/_distributed_worker.py (which serves it over the 2-process mesh) and
tests/test_distributed.py (which serves it on a single-process unsharded
engine as the greedy reference) — so the parity assertion can never drift
into comparing two different configs.

Two generate calls per engine: a 2-prompt batch (batched admission) and a
single prompt (the single-request ``_admit`` path, whose device→host first-
token fetch must also survive a process-spanning mesh — engine.host_np).
"""

BATCH_PROMPTS = ["pod pending unschedulable", "pvc not bound"]
SINGLE_PROMPT = "node notready kubelet"
MAX_NEW = 6


def model_config():
    from k8s_llm_rca_tpu.config import TINY

    return TINY.replace(max_seq_len=64)


def engine_configs():
    """[(kind, paged, EngineConfig)] for the serve parity legs."""
    from k8s_llm_rca_tpu.config import EngineConfig

    out = []
    for paged in (False, True):
        extra = (dict(paged=True, page_size=8, num_pages=32,
                      prefix_cache=False) if paged else {})
        out.append(("paged" if paged else "contig", paged,
                    EngineConfig(max_batch=2, max_seq_len=64,
                                 prefill_buckets=(16, 32, 64),
                                 max_new_tokens=MAX_NEW, temperature=0.0,
                                 decode_chunk=4, **extra)))
    return out


def serve_all(make):
    """{key: "tok,tok;..."} for every (engine, call-shape) leg.  ``make``
    builds an engine from (model_cfg, engine_cfg, paged) — the worker
    passes a tp_mesh-sharded builder, the test an unsharded one."""
    import jax

    from k8s_llm_rca_tpu.models import llama
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = model_config()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    batch = [list(tok.encode(t, add_bos=True)) for t in BATCH_PROMPTS]
    single = [list(tok.encode(SINGLE_PROMPT, add_bos=True))]
    out = {}
    with jax.default_matmul_precision("float32"):
        for kind, paged, ecfg in engine_configs():
            eng = make(cfg, params, tok, ecfg, paged)
            for shape, prompts in (("batch", batch), ("single", single)):
                res = eng.generate([list(p) for p in prompts],
                                   max_new_tokens=MAX_NEW)
                out[f"{kind}/{shape}"] = ";".join(
                    ",".join(map(str, r.token_ids)) for r in res)
    return out
