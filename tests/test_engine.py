"""Engine tests: continuous batching must be invisible to each sequence.

The load-bearing invariant (SURVEY §5 race-detection note): a sequence
decoded in a shared batch — admitted/evicted alongside others — must produce
exactly the tokens it would produce alone.  This is the KV-slot-isolation
equivalent of the reference's "no double-free/alias of pages" requirement.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_llm_rca_tpu.config import TINY, EngineConfig
from k8s_llm_rca_tpu.engine import InferenceEngine
from k8s_llm_rca_tpu.engine.engine import decode_scan
from k8s_llm_rca_tpu.engine.sampling import SamplingParams, sample_tokens
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.utils import get_tokenizer


@pytest.fixture(scope="module")
def setup():
    cfg = TINY
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer()
    return cfg, params, tok


def make_engine(cfg, params, tok, **over):
    ecfg = EngineConfig(max_batch=4, max_seq_len=128,
                        prefill_buckets=(16, 32, 64), max_new_tokens=16, **over)
    return InferenceEngine(cfg, ecfg, params, tok)


def ref_greedy(cfg, params, prompt_ids, n_new):
    """Direct model loop: the ground truth the engine must reproduce."""
    cache = llama.init_cache(cfg, 1, 128)
    n = len(prompt_ids)
    padded = jnp.zeros((1, 32), jnp.int32).at[0, :n].set(jnp.array(prompt_ids))
    cache, logits = llama.prefill(cfg, params, cache, padded,
                                  jnp.int32(n), jnp.int32(0))
    out = [int(jnp.argmax(logits[0]))]
    lengths = jnp.array([n], jnp.int32)
    for _ in range(n_new - 1):
        cache, logits = llama.decode_step(
            cfg, params, cache, jnp.array([out[-1]], jnp.int32), lengths)
        out.append(int(jnp.argmax(logits[0])))
        lengths = lengths + 1
    return out


def test_engine_matches_direct_decode(setup):
    cfg, params, tok = setup
    engine = make_engine(cfg, params, tok)
    prompt = tok.encode("exceeded quota: pods=50", add_bos=True)
    [res] = engine.generate([prompt], max_new_tokens=8)
    assert res.token_ids == ref_greedy(cfg, params, prompt, 8)
    assert res.finish_reason in ("length", "eos")
    assert res.prompt_tokens == len(prompt)


def test_batched_equals_solo(setup):
    """3 sequences through one shared batch == each alone (greedy)."""
    cfg, params, tok = setup
    prompts = [tok.encode(s, add_bos=True) for s in
               ("secret not found", "configmap missing from pod spec",
                "stale NFS file handle on mount")]
    solo = []
    for p in prompts:
        engine = make_engine(cfg, params, tok)
        solo.append(engine.generate([p], max_new_tokens=8)[0].token_ids)
    engine = make_engine(cfg, params, tok)
    batched = engine.generate(prompts, max_new_tokens=8)
    for got, want in zip(batched, solo):
        assert got.token_ids == want


def test_queue_overflow_is_continuous(setup):
    """6 prompts through 4 slots: later admissions reuse freed slots."""
    cfg, params, tok = setup
    prompts = [tok.encode(f"incident number {i}", add_bos=True) for i in range(6)]
    engine = make_engine(cfg, params, tok)
    results = engine.generate(prompts, max_new_tokens=6)
    assert len(results) == 6
    for p, r in zip(prompts, results):
        assert r.token_ids == ref_greedy(cfg, params, p, 6)


def test_stop_string(setup):
    cfg, params, tok = setup
    engine = make_engine(cfg, params, tok)
    prompt = tok.encode("hello", add_bos=True)
    # pick the stop string from what the model actually generates
    free = engine.generate([prompt], max_new_tokens=12)[0]
    stop = free.text[2:5]
    engine2 = make_engine(cfg, params, tok)
    [res] = engine2.generate([prompt], max_new_tokens=12, stop_strings=(stop,))
    assert res.finish_reason == "stop"
    assert stop not in res.text
    assert free.text.startswith(res.text)


def test_decode_scan_matches_step_loop(setup):
    cfg, params, tok = setup
    prompt = tok.encode("MountVolume.SetUp failed", add_bos=True)
    want = ref_greedy(cfg, params, prompt, 9)

    cache = llama.init_cache(cfg, 2, 128)
    n = len(prompt)
    padded = jnp.zeros((1, 32), jnp.int32).at[0, :n].set(jnp.array(prompt))
    cache, logits = llama.prefill(cfg, params, cache, padded,
                                  jnp.int32(n), jnp.int32(0))
    first = int(jnp.argmax(logits[0]))
    cur = jnp.array([first, 0], jnp.int32)
    lengths = jnp.array([n, 0], jnp.int32)
    cache, toks, lengths = decode_scan(
        cfg, params, cache, cur, lengths, jax.random.PRNGKey(0), 8,
        SamplingParams(), eos_id=tok.eos_id)
    got = [first] + [int(t) for t in np.asarray(toks)[:, 0]]
    assert got == want


def test_sampling_modes():
    logits = jnp.array([[0.0, 5.0, 1.0, -2.0]], jnp.float32)
    key = jax.random.PRNGKey(0)
    assert int(sample_tokens(logits, key, SamplingParams())[0]) == 1
    # top_k=1 must always pick the argmax regardless of temperature
    for seed in range(5):
        t = sample_tokens(logits, jax.random.PRNGKey(seed),
                          SamplingParams(temperature=5.0, top_k=1))
        assert int(t[0]) == 1
    # top_p tiny keeps only the top token
    for seed in range(5):
        t = sample_tokens(logits, jax.random.PRNGKey(seed),
                          SamplingParams(temperature=5.0, top_p=0.01))
        assert int(t[0]) == 1
    # high temperature with no truncation eventually samples others
    seen = {int(sample_tokens(logits, jax.random.PRNGKey(s),
                              SamplingParams(temperature=50.0))[0])
            for s in range(64)}
    assert len(seen) > 1


def test_prompt_truncation_keeps_tail(setup):
    cfg, params, tok = setup
    engine = make_engine(cfg, params, tok)
    long_prompt = tok.encode("x" * 500, add_bos=True)   # >> max_seq_len 128
    seq = engine.submit(long_prompt, max_new_tokens=4)
    results = engine.run_to_completion()
    assert results and results[0].seq_id == seq
    assert results[0].prompt_tokens <= 128 - 4 - 1


def test_max_new_exceeding_cache_is_clamped(setup):
    """Regression: max_new >= max_seq_len used to drive the prompt budget
    negative (truncation to -1 tokens) and long prompts crashed _admit."""
    cfg, params, tok = setup
    engine = make_engine(cfg, params, tok)        # max_seq_len=128
    prompt = tok.encode("y" * 300, add_bos=True)  # longer than any bucket
    engine.submit(prompt, max_new_tokens=500)     # max_new >> cache
    [res] = engine.run_to_completion()
    assert res.finish_reason == "length"
    # reserved generation room: cap//4 = 32 tokens of prompt budget headroom
    assert res.prompt_tokens <= 128 - 32 - 1
    assert res.completion_tokens >= 32


def test_engine_runs_moe_model():
    """Continuous batching over a Mixtral-style MoE model (dense
    soft-dispatch MLP in decode): greedy generate works end-to-end."""
    from k8s_llm_rca_tpu.config import TINY_MOE, EngineConfig

    cfg = TINY_MOE.replace(max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    eng = InferenceEngine(
        cfg, EngineConfig(max_batch=2, max_seq_len=64,
                          prefill_buckets=(16, 32, 64), max_new_tokens=6,
                          temperature=0.0), params, tok)
    res = eng.generate([tok.encode("pod oom", add_bos=True),
                        tok.encode("pvc pending", add_bos=True)],
                       max_new_tokens=6)
    assert all(r.completion_tokens == 6 for r in res)


def test_batched_admission_matches_serial(setup):
    """Same-bucket pending prompts prefill in one dispatch; output must be
    bit-identical to one-at-a-time admission."""
    from k8s_llm_rca_tpu.utils.logging import METRICS

    cfg, params, tok = setup
    prompts = [tok.encode(t, add_bos=True) for t in
               ["pod oomkilled restarting", "pvc pending unbound",
                "node pressure evicting", "image pull backoff"]]

    def run(batch_admission):
        ecfg = EngineConfig(max_batch=4, max_seq_len=128,
                            prefill_buckets=(32, 64, 128),
                            max_new_tokens=8, temperature=0.0)
        eng = InferenceEngine(cfg, ecfg, params, tok)
        eng._batch_admission = batch_admission
        out = eng.generate([list(p) for p in prompts], max_new_tokens=8)
        return [(r.token_ids, r.finish_reason) for r in out]

    before = METRICS.counters.get("engine.batched_admissions", 0)
    batched = run(True)
    assert METRICS.counters.get("engine.batched_admissions", 0) > before
    assert batched == run(False)


def test_batched_admission_with_grammar_and_quantized_cache(setup):
    """Batch admission composes with grammar first-token constraints and
    the int8 KV cache."""
    import json as jsonlib

    from k8s_llm_rca_tpu.engine.constrain import make_grammar

    cfg, params, tok = setup
    ecfg = EngineConfig(max_batch=4, max_seq_len=128,
                        prefill_buckets=(32, 64, 128), max_new_tokens=16,
                        temperature=0.0, kv_cache_dtype="int8")
    eng = InferenceEngine(cfg, ecfg, params, tok)
    ids = []
    for _ in range(3):
        g = make_grammar("json", tok, prefer_native=False)
        ids.append(eng.submit(tok.encode("emit json", add_bos=True),
                              max_new_tokens=16, grammar=g))
    res = {r.seq_id: r for r in eng.run_to_completion()}
    for i in ids:
        jsonlib.loads(res[i].text)


def test_prompt_admission_forces_stepwise_while_queued(setup):
    """prompt_admission=True: while requests are queued the engine ticks
    stepwise (chunk == 1), so a freed slot is noticed within ONE decode
    step instead of up to decode_chunk-1; default (False) keeps the full
    scan chunk (tuned for dispatch-latency-dominated hosts)."""
    cfg, params, tok = setup
    prompts = [tok.encode("pod crashloop", add_bos=True),
               tok.encode("pvc pending", add_bos=True)]

    def build(prompt_admission):
        ecfg = EngineConfig(max_batch=1, max_seq_len=128,
                            prefill_buckets=(32,), max_new_tokens=12,
                            temperature=0.0, decode_chunk=8,
                            prompt_admission=prompt_admission)
        eng = InferenceEngine(cfg, ecfg, params, tok)
        for p in prompts:
            # budget 12 > decode_chunk 8, so one chunked scan cannot
            # retire the active sequence mid-assert
            eng.submit(list(p), max_new_tokens=12)
        eng.step()                         # admits the first; second queues
        assert eng._pending and eng._active
        return eng

    eng = build(True)
    assert eng._scan_chunk() == 1          # stepwise while the queue waits
    res = eng.run_to_completion()
    assert len(res) == 2                   # both complete, greedy unchanged

    eng2 = build(False)
    assert eng2._scan_chunk() == 8         # default amortizes dispatches
    res2 = eng2.run_to_completion()
    for a, b in zip(res, res2):
        assert a.token_ids == b.token_ids  # knob changes latency, not output
