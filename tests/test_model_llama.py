"""Model-stack unit tests: shapes, causality, prefill/decode agreement, MoE.

The decisive invariant is prefill/decode agreement: running the whole
sequence through ``forward`` must give the same logits as prefilling a prompt
and decoding token-by-token through the KV cache — this is what makes the
cache machinery trustworthy under the continuous-batching engine.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_llm_rca_tpu.config import TINY, TINY_MOE
from k8s_llm_rca_tpu.models import llama


@pytest.fixture(scope="module")
def tiny_setup():
    cfg = TINY
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_forward_shapes(tiny_setup):
    cfg, params = tiny_setup
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = llama.forward(cfg, params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_causality(tiny_setup):
    """Perturbing token t must not change logits at positions < t."""
    cfg, params = tiny_setup
    key = jax.random.PRNGKey(2)
    tokens = jax.random.randint(key, (1, 12), 0, cfg.vocab_size)
    t = 7
    perturbed = tokens.at[0, t].set((tokens[0, t] + 1) % cfg.vocab_size)
    la = llama.forward(cfg, params, tokens)
    lb = llama.forward(cfg, params, perturbed)
    np.testing.assert_allclose(la[0, :t], lb[0, :t], atol=1e-5)
    assert not np.allclose(la[0, t:], lb[0, t:], atol=1e-5)


def test_prefill_decode_matches_forward(tiny_setup):
    cfg, params = tiny_setup
    s_total, s_prompt = 12, 5
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, s_total), 0, cfg.vocab_size)
    full_logits = llama.forward(cfg, params, tokens)  # [1, S, V]

    cache = llama.init_cache(cfg, n_slots=4, max_seq_len=32)
    # prefill the prompt into slot 2, right-padded to bucket width 8
    padded = jnp.zeros((1, 8), tokens.dtype).at[:, :s_prompt].set(tokens[:, :s_prompt])
    cache, logits = llama.prefill(
        cfg, params, cache, padded, jnp.int32(s_prompt), jnp.int32(2))
    np.testing.assert_allclose(
        logits[0], full_logits[0, s_prompt - 1], rtol=2e-4, atol=2e-4)

    # decode the remaining tokens one at a time in slot 2 (other slots idle)
    lengths = jnp.zeros((4,), jnp.int32).at[2].set(s_prompt)
    for i in range(s_prompt, s_total):
        step_tokens = jnp.zeros((4,), tokens.dtype).at[2].set(tokens[0, i])
        cache, logits = llama.decode_step(cfg, params, cache, step_tokens, lengths)
        np.testing.assert_allclose(
            logits[2], full_logits[0, i], rtol=2e-4, atol=2e-4)
        lengths = lengths.at[2].add(1)


def test_prefill_only_touches_its_slot(tiny_setup):
    cfg, params = tiny_setup
    cache = llama.init_cache(cfg, n_slots=3, max_seq_len=16)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (1, 8), 0, cfg.vocab_size)
    cache2, _ = llama.prefill(cfg, params, cache, tokens, jnp.int32(8), jnp.int32(1))
    assert bool(jnp.all(cache2.k[:, 0] == 0)) and bool(jnp.all(cache2.k[:, 2] == 0))
    assert not bool(jnp.all(cache2.k[:, 1] == 0))


def test_moe_forward_runs():
    cfg = TINY_MOE
    params = llama.init_params(cfg, jax.random.PRNGKey(5))
    tokens = jax.random.randint(jax.random.PRNGKey(6), (2, 8), 0, cfg.vocab_size)
    logits = llama.forward(cfg, params, tokens)
    assert logits.shape == (2, 8, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_moe_prefill_decode_consistency():
    cfg = TINY_MOE
    params = llama.init_params(cfg, jax.random.PRNGKey(7))
    tokens = jax.random.randint(jax.random.PRNGKey(8), (1, 10), 0, cfg.vocab_size)
    full = llama.forward(cfg, params, tokens)
    cache = llama.init_cache(cfg, n_slots=2, max_seq_len=16)
    cache, logits = llama.prefill(
        cfg, params, cache, tokens[:, :6].reshape(1, 6), jnp.int32(6), jnp.int32(0))
    np.testing.assert_allclose(logits[0], full[0, 5], rtol=2e-4, atol=2e-4)
    lengths = jnp.array([6, 0], jnp.int32)
    step_tokens = jnp.array([tokens[0, 6], 0], tokens.dtype)
    cache, logits = llama.decode_step(cfg, params, cache, step_tokens, lengths)
    np.testing.assert_allclose(logits[0], full[0, 6], rtol=2e-4, atol=2e-4)
