"""Out-of-process replica tests (cluster/proc.py, cluster/wire.py).

Layers, cheapest first:

- **wire codec units** (no subprocess): frame round-trips, and every
  corruption class — torn frame, CRC flip, oversized header, non-JSON,
  non-dict — raises ``WireCorrupt`` instead of returning garbage, while
  a silent peer raises ``WireTimeout`` instead of wedging the reader.
- **loud exclusions** (no subprocess): proc × CP/PP composition, nested
  proc-in-proc, killer-mode misuse, and the pipelined sweep's
  proc-cluster refusal all ValueError with actionable messages.
- **worker fleet** (real spawns, scripted workers ~0.5 s each): the
  LMBackend surface over the pipe, REAL SIGKILL detected by the
  watchdog's hard-evidence path (pipe EOF / exit code — never a hung
  probe loop), failover byte-identity vs the in-process echo cluster,
  supervisor restart of the actual OS process (fresh pid, incarnation
  + 1), and the drain -> TERM -> KILL close ladder.
- **kill-and-heal soak** (the ISSUE acceptance bar): 100 incidents on
  proc-oracle replicas with seeded SIGKILLs, zero manual
  ``fail_replica`` calls, report bytes identical to the unkilled
  in-process cluster-oracle run — twice over.
- **engine parity** (slow): greedy byte-parity of a proc engine-worker
  cluster against the plain in-process engine.
"""

from __future__ import annotations

import io
import os

import pytest

from k8s_llm_rca_tpu.cluster import (
    ClusterRouter, HealthPolicy, HealthWatchdog, Replica,
    ReplicaSupervisor,
)
from k8s_llm_rca_tpu.cluster.proc import (
    WORKER_ENV, ProcReplica, build_proc_replicas,
)
from k8s_llm_rca_tpu.cluster.wire import (
    HEADER, FrameReader, WireCorrupt, WireEOF, WireTimeout, pack_frame,
    write_frame,
)
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.faults.plan import FaultPlan, VirtualClock
from k8s_llm_rca_tpu.serve.backend import EchoBackend, GenOptions
from k8s_llm_rca_tpu.utils import wal
from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

pytestmark = pytest.mark.procluster


def _close_all(router: ClusterRouter) -> None:
    for r in router.replicas.values():
        close = getattr(r, "close", None)
        if close is not None:
            close()


def _settle(router, handles, pumps=64):
    out = {}
    for _ in range(pumps):
        out.update(router.pump())
        if all(h in out for h in handles):
            return out
    raise AssertionError(f"runs never settled: {sorted(out)}")


def _watchdog():
    # hard-evidence escalation is one state per probe, so thresholds
    # only bound the SOFT (missed-signal) path
    return HealthWatchdog(HealthPolicy(miss_budget=1,
                                       hung_tick_threshold=2),
                          clock=VirtualClock())


def _proc_killer(seed=2, rate=0.03, horizon=100):
    from k8s_llm_rca_tpu.faults.supervisor import ProcKiller

    return ProcKiller(FaultPlan.from_spec(
        seed, {inject.SITE_PROC: {"rate": rate, "horizon": horizon,
                                  "kinds": ("crash",)}}))


# ---------------------------------------------------------------------------
# wire codec units (no subprocess)
# ---------------------------------------------------------------------------


class TestWireCodec:
    def test_frames_round_trip_in_order(self):
        buf = io.BytesIO()
        msgs = [{"op": "ping", "id": 0}, {"op": "pump", "id": 1,
                                          "nested": {"a": [1, 2]}}]
        for m in msgs:
            write_frame(buf, m)
        buf.seek(0)
        reader = FrameReader(buf)
        assert [reader.read_frame() for _ in msgs] == msgs
        with pytest.raises(WireEOF):
            reader.read_frame()

    def test_partial_chunks_are_buffered_across_fills(self):
        # a stream that trickles one frame in 3-byte chunks: the reader
        # must assemble it across fills, never mis-frame
        frame = pack_frame({"op": "start", "id": 7})

        class Trickle:
            def __init__(self, data):
                self._chunks = [data[i:i + 3]
                                for i in range(0, len(data), 3)]

            def read1(self, n):
                return self._chunks.pop(0) if self._chunks else b""

        assert FrameReader(Trickle(frame)).read_frame() == \
            {"op": "start", "id": 7}

    def test_torn_frame_raises_corrupt_not_clean_eof(self):
        frame = pack_frame({"op": "ping", "id": 0})
        reader = FrameReader(io.BytesIO(frame[:-3]))
        with pytest.raises(WireCorrupt, match="torn frame"):
            reader.read_frame()

    def test_crc_flip_raises_corrupt(self):
        frame = bytearray(pack_frame({"op": "ping", "id": 0}))
        frame[-1] ^= 0xFF
        with pytest.raises(WireCorrupt, match="CRC mismatch"):
            FrameReader(io.BytesIO(bytes(frame))).read_frame()

    def test_oversized_length_raises_corrupt(self):
        header = HEADER.pack(wal.MAX_RECORD_SIZE + 1, 0)
        with pytest.raises(WireCorrupt, match="exceeds MAX_FRAME_SIZE"):
            FrameReader(io.BytesIO(header + b"x" * 64)).read_frame()

    def test_valid_crc_non_json_raises_corrupt(self):
        with pytest.raises(WireCorrupt, match="not JSON"):
            FrameReader(io.BytesIO(
                wal.pack_record(b"\xff\xfe{"))).read_frame()

    def test_non_dict_payload_raises_corrupt(self):
        with pytest.raises(WireCorrupt, match="JSON object"):
            FrameReader(io.BytesIO(
                wal.pack_record(b"[1,2,3]"))).read_frame()

    def test_silent_peer_raises_timeout_on_real_fd(self):
        r_fd, w_fd = os.pipe()
        try:
            reader = FrameReader(os.fdopen(r_fd, "rb", buffering=0))
            with pytest.raises(WireTimeout, match="missed its protocol"):
                reader.read_frame(timeout_s=0.05)
        finally:
            os.close(w_fd)


# ---------------------------------------------------------------------------
# loud exclusions (no subprocess)
# ---------------------------------------------------------------------------


class _FakeProcReplica(Replica):
    """In-process stand-in exposing the proc surface the killer checks
    (``kill_process``) — lets the mode-policy tests run without spawning."""

    def __init__(self, rid, tok):
        super().__init__(rid, EchoBackend(tok))
        self.killed = False

    def kill_process(self):
        self.killed = True


def _always_fire_killer(mode, site=inject.SITE_REPLICA):
    from k8s_llm_rca_tpu.faults.supervisor import ReplicaKiller

    return ReplicaKiller(FaultPlan.from_spec(
        0, {site: {"rate": 1.0, "horizon": 4, "kinds": ("crash",)}}),
        mode=mode)


class TestExclusions:
    def test_proc_refuses_sharding_spec_keys(self):
        for key in ("mesh", "context_parallel", "pipeline_parallel",
                    "cp", "pp"):
            with pytest.raises(ValueError, match="do not compose"):
                build_proc_replicas(2, **{key: object()})

    def test_zero_replicas_rejected(self):
        with pytest.raises(ValueError, match="n_replicas"):
            build_proc_replicas(0)

    def test_nested_proc_in_proc_rejected(self, monkeypatch):
        monkeypatch.setenv(WORKER_ENV, "1")
        with pytest.raises(ValueError, match="nested proc-in-proc"):
            build_proc_replicas(1)

    def test_unknown_worker_kind_rejected_before_spawn(self):
        with pytest.raises(ValueError, match="unknown proc worker kind"):
            build_proc_replicas(1, kind="quantum")

    def test_unknown_kill_mode_rejected(self):
        from k8s_llm_rca_tpu.faults.supervisor import ReplicaKiller

        with pytest.raises(ValueError, match="unknown kill mode"):
            ReplicaKiller(FaultPlan.from_spec(0, {}), mode="nuke")

    def test_auto_mode_refuses_proc_victim(self):
        tok = get_tokenizer()
        router = ClusterRouter([_FakeProcReplica(0, tok),
                                _FakeProcReplica(1, tok)])
        k = _always_fire_killer("auto")
        k.router = router
        with pytest.raises(ValueError, match="refuses out-of-process"):
            k.checkpoint()

    def test_wedge_mode_requires_watchdog(self):
        tok = get_tokenizer()
        router = ClusterRouter([Replica(0, EchoBackend(tok)),
                                Replica(1, EchoBackend(tok))])
        k = _always_fire_killer("wedge")
        k.router = router
        with pytest.raises(ValueError, match="without an attached"):
            k.checkpoint()

    def test_sigkill_mode_requires_proc_victim(self):
        tok = get_tokenizer()
        router = ClusterRouter([Replica(0, EchoBackend(tok)),
                                Replica(1, EchoBackend(tok))])
        k = _always_fire_killer("sigkill")
        k.router = router
        with pytest.raises(ValueError, match="needs an out-of-process"):
            k.checkpoint()

    def test_sigkill_last_alive_without_restart_is_plan_bug(self):
        tok = get_tokenizer()
        router = ClusterRouter([_FakeProcReplica(0, tok)])
        k = _always_fire_killer("sigkill")
        k.router = router
        with pytest.raises(ValueError, match="refusing SIGKILL"):
            k.checkpoint()

    def test_pipelined_sweep_refuses_proc_cluster(self):
        from k8s_llm_rca_tpu.faults.soak import run_pipelined_sweep

        with pytest.raises(ValueError, match="chaos-soak-only"):
            run_pipelined_sweep(n_incidents=1, backend="proc-cluster")


# ---------------------------------------------------------------------------
# worker fleet (real subprocess spawns, scripted workers)
# ---------------------------------------------------------------------------


class TestWorkerFleet:
    def test_oracle_roundtrip_graceful_close_exits_zero(self):
        (rep,) = build_proc_replicas(1, kind="oracle")
        try:
            b = rep.backend
            assert rep.healthy() and b.proc_liveness() is None
            h = b.start("node notready", GenOptions())
            assert h >= 0 and b.busy(h) and b.queue_depth() == 1
            out = {}
            for _ in range(20):
                out.update(b.pump())
                if h in out:
                    break
            assert out[h].error is None and out[h].text
            assert not b.busy(h) and b.queue_depth() == 0
            assert b.count_tokens("abc def") == \
                get_tokenizer().count("abc def")
        finally:
            rep.close()
        # drain frame acked -> worker exited 0, pipes reaped
        assert rep.backend._proc.poll() == 0

    def test_sigkill_mid_flight_failover_is_byte_identical(self):
        tok = get_tokenizer()
        prompts = [f"incident p{i}" for i in range(4)]
        # reference: the SAME runs on an unkilled in-process echo cluster
        ref_router = ClusterRouter(
            [Replica(i, EchoBackend(tok, delay_pumps=2))
             for i in range(2)])
        ref_handles = [ref_router.start(p, GenOptions(session=f"s{i}"))
                       for i, p in enumerate(prompts)]
        ref = _settle(ref_router, ref_handles)

        router = ClusterRouter(
            build_proc_replicas(2, kind="echo", echo_delay_pumps=2))
        try:
            router.attach_health(_watchdog(), ReplicaSupervisor())
            handles = [router.start(p, GenOptions(session=f"s{i}"))
                       for i, p in enumerate(prompts)]
            victim = router._handle_map[handles[0]][0]
            router.replicas[victim].kill_process()
            # hard evidence (exit:-9) is already on record, mid-decode
            assert "exit:-9" in router.replicas[victim].proc_liveness()
            assert not router.replicas[victim].healthy()
            out = _settle(router, handles)
            for rh, h in zip(ref_handles, handles):
                assert out[h].text == ref[rh].text
                assert out[h].error is None
            # the whole loop ran in-tree on OS evidence
            assert router.health.hard_detections == [victim]
            assert router.supervisor.restarts == [victim]
            assert sorted(router.alive_ids()) == [0, 1]
            assert all(r.healthy() for r in router.replicas.values())
        finally:
            _close_all(router)

    def test_supervisor_restarts_the_actual_process(self):
        router = ClusterRouter(build_proc_replicas(2, kind="oracle"))
        try:
            router.attach_health(_watchdog(), ReplicaSupervisor())
            old_pid = router.replicas[0].backend.pid
            router.replicas[0].kill_process()
            for _ in range(6):
                if router.replicas[0].healthy():
                    break
                router.pump()
            fresh = router.replicas[0].backend
            assert fresh.pid != old_pid          # a NEW os process
            assert fresh.incarnation == 1
            assert fresh.proc_liveness() is None
            assert router.health.hard_detections == [0]
            assert router.supervisor.incarnations[0] == 1
            # the fresh incarnation actually serves
            h = fresh.start("node notready", GenOptions())
            out = {}
            for _ in range(20):
                out.update(fresh.pump())
                if h in out:
                    break
            assert out[h].error is None
        finally:
            _close_all(router)

    def test_corrupt_frame_marks_dead_never_hangs(self):
        # the worker writes garbage mid-stream and hard-exits after its
        # first handled request: the NEXT rpc sees a torn/corrupt frame,
        # records evidence, and the proxy black-holes instead of raising
        (rep,) = build_proc_replicas(1, kind="echo",
                                     chaos_corrupt_after=1)
        try:
            b = rep.backend
            h = b.start("p", GenOptions())      # request 1: served
            assert h >= 0
            assert b.pump() == {}               # request 2: corrupted
            evidence = b.proc_liveness()
            assert evidence is not None and "rpc failed" in evidence
            assert not rep.healthy()
            # post-mortem starts black-hole on synthetic local handles
            h2 = b.start("q", GenOptions())
            assert h2 < 0 and b.busy(h2)
        finally:
            rep.close()                          # idempotent over a corpse
        assert rep.backend._proc.poll() is not None

    def test_missed_protocol_heartbeat_times_out_dead(self):
        (rep,) = build_proc_replicas(1, kind="echo", chaos_hang_after=1,
                                     rpc_timeout_s=0.5)
        try:
            b = rep.backend
            assert b.start("p", GenOptions()) >= 0
            assert b.pump() == {}                # worker went silent
            evidence = b.proc_liveness()
            assert evidence is not None and "WireTimeout" in evidence
            assert not rep.healthy()
        finally:
            rep.close(timeout_s=0.5)             # TERM/KILL escalation
        assert rep.backend._proc.poll() is not None

    def test_watchdog_turns_corrupt_transport_into_failover(self):
        # replica 0's worker corrupts on its FIRST request; the run
        # black-holes, the watchdog escalates on evidence (SUSPECT ->
        # DEAD in two probes) and failover settles the run on replica 1
        reps = [ProcReplica(0, kind="echo", chaos_corrupt_after=0),
                ProcReplica(1, kind="echo")]
        router = ClusterRouter(reps)
        try:
            router.attach_health(_watchdog())    # no supervisor: fail over
            h = router.start("p", GenOptions())
            assert router._handle_map[h][0] == 0
            out = _settle(router, [h], pumps=8)
            assert out[h].error is None
            assert out[h].text == "echo: p"
            assert router.health.hard_detections == [0]
            assert router.alive_ids() == [1]
        finally:
            _close_all(router)

    def test_drain_refused_for_scripted_proc_replicas(self):
        router = ClusterRouter(build_proc_replicas(2, kind="oracle"))
        try:
            with pytest.raises(ValueError, match="needs engine replicas"):
                router.drain_replica(0)
        finally:
            _close_all(router)

    def test_prometheus_exports_per_process_gauges(self):
        from k8s_llm_rca_tpu.obs.export import prometheus_text

        router = ClusterRouter(build_proc_replicas(2, kind="echo"))
        try:
            router.replicas[1].kill_process()
            text = prometheus_text(router=router)
            pid0 = router.replicas[0].backend.pid
            pid1 = router.replicas[1].backend.pid
            assert (f'cluster_proc_alive{{replica="0",pid="{pid0}",'
                    f'incarnation="0"}} 1') in text
            assert (f'cluster_proc_alive{{replica="1",pid="{pid1}",'
                    f'incarnation="0"}} 0') in text
            assert "cluster_proc_rpcs" in text
        finally:
            _close_all(router)


# ---------------------------------------------------------------------------
# the acceptance bar: 100-incident SIGKILL-and-heal soak, byte-identical
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestProcKillAndHealSoak:
    def test_100_incident_sigkill_and_heal_byte_identical(self):
        """Real SIGKILLs against real worker processes, zero manual
        ``fail_replica`` calls: every kill is detected on hard OS
        evidence (pipe EOF / exit code), failed over, and the actual
        process restarted — and the report is byte-identical to the
        unkilled IN-PROCESS cluster-oracle run, twice over (transport
        and murder are deployment details, not outcomes)."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak

        base = run_chaos_soak(seed=11, n_incidents=100,
                              backend="cluster-oracle",
                              cluster_replicas=4)
        assert base["completed"] == 100
        assert base["failed"] == 0

        k1 = _proc_killer()
        healed = run_chaos_soak(seed=11, n_incidents=100,
                                backend="proc-cluster",
                                cluster_replicas=4, killer=k1,
                                selfheal=True)
        assert k1.kills                      # SIGKILLs actually landed
        assert report_bytes(healed) == report_bytes(base)
        router = k1.router
        # every detection carried hard OS evidence — the watchdog saw
        # actual process exits, not just wedged ticks
        assert router.health.detections == k1.kills
        assert router.health.hard_detections == k1.kills
        assert router.supervisor.restarts == k1.kills
        assert router.failovers == len(k1.kills)
        assert sorted(router.alive_ids()) == [0, 1, 2, 3]
        # the soak's reaping context closed every worker on exit
        for r in router.replicas.values():
            assert r.backend._proc.poll() is not None

        k2 = _proc_killer()
        again = run_chaos_soak(seed=11, n_incidents=100,
                               backend="proc-cluster",
                               cluster_replicas=4, killer=k2,
                               selfheal=True)
        assert k2.kills == k1.kills          # the kill schedule is seeded
        assert report_bytes(again) == report_bytes(base)

    def test_proc_soak_without_chaos_matches_in_process(self):
        """Transport invariance alone: no killer, no selfheal — the
        proc-cluster sweep's report must already be byte-identical to
        the in-process cluster-oracle run."""
        from k8s_llm_rca_tpu.faults.soak import report_bytes, run_chaos_soak

        base = run_chaos_soak(seed=3, n_incidents=6,
                              backend="cluster-oracle")
        proc = run_chaos_soak(seed=3, n_incidents=6,
                              backend="proc-cluster")
        assert report_bytes(proc) == report_bytes(base)
        assert proc["backend"] == "cluster-oracle"


# ---------------------------------------------------------------------------
# engine workers: greedy byte-parity over the wire (slow: worker compiles)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestEngineProcParity:
    def test_proc_engine_cluster_matches_plain_engine(self):
        """Each prompt's greedy text from a 2-worker proc engine cluster
        must be byte-identical to the plain in-process engine's on the
        identical TINY config and seed-0 params — the identical-replica
        invariant, now across a process boundary."""
        import jax

        from k8s_llm_rca_tpu.config import TINY, EngineConfig
        from k8s_llm_rca_tpu.engine import make_engine
        from k8s_llm_rca_tpu.models import llama

        cfg = TINY.replace(max_seq_len=2560)
        ecfg = EngineConfig(max_batch=4, max_seq_len=2560,
                            prefill_buckets=(2560,), max_new_tokens=96,
                            temperature=0.0, paged=True, page_size=64,
                            num_pages=168, prefix_cache=False,
                            decode_chunk=16)
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        ref_engine = make_engine(cfg, ecfg, params, tok, use_kernel=False)
        prompts = ["pod pending unschedulable node affinity mismatch",
                   "pvc not bound storageclass missing"]
        ref = ref_engine.generate(
            [tok.encode(p, add_bos=True) for p in prompts],
            max_new_tokens=8)

        router = ClusterRouter(build_proc_replicas(2, kind="engine",
                                                   seed=0))
        try:
            handles = [router.start(p, GenOptions(max_new_tokens=8))
                       for p in prompts]
            assert {router._handle_map[h][0] for h in handles} == {0, 1}
            out = _settle(router, handles, pumps=256)
            for h, r in zip(handles, ref):
                assert out[h].text == r.text   # byte-identical greedy text
                assert out[h].error is None
        finally:
            _close_all(router)
