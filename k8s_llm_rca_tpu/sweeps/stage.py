"""Stage-isolated operator harnesses (one CLI, four scenarios).

The reference ships four hand-run scripts that exercise one slice of the
pipeline with pinned inputs — stage 1 only (reference
test_find_metapath.py:44-63), stage 2 with a hardcoded Pod->Secret
metapath (test_generate_query.py:23-31,47-53), stage 3 with pinned
entity/timestamp (test_check_state.py:39-48), and an assistants-API +
token-accounting smoke (test_token.py:13-47).  This module is their
equivalent, sharing the sweep drivers' backend/graph wiring:

    python -m k8s_llm_rca_tpu.sweeps.stage locate   [--incident N] [...]
    python -m k8s_llm_rca_tpu.sweeps.stage cypher   [...]
    python -m k8s_llm_rca_tpu.sweeps.stage audit    [...]
    python -m k8s_llm_rca_tpu.sweeps.stage token    [...]

All four take the common flags (--backend oracle|engine, --model,
--neo4j-*); hermetic by default against the canned fixture graphs.
"""

from __future__ import annotations

import argparse
import json
import time

from k8s_llm_rca_tpu.graph.fixtures import INCIDENTS
from k8s_llm_rca_tpu.rca import auditor, cyphergen, locator
from k8s_llm_rca_tpu.sweeps.common import (
    add_common_args, build_executors, build_service,
)
from k8s_llm_rca_tpu.utils.logging import get_logger

log = get_logger(__name__)

# the reference's stage-2 pinned metapath: Pod -> Secret via the two
# implicit Event edges (reference test_generate_query.py:23-27)
PINNED_METAPATH = ("HasEvent, Event, EVENT, metadata_uid; "
                   "ReferInternal, Event, Pod, involvedObject_uid; "
                   "ReferInternal, Pod, Secret, "
                   "spec_volumes_secret_secretName; ")


def stage_locate(args, service, meta, state) -> dict:
    """Stage 1 only: srcKind discovery + destKind plan + metapath ladder."""
    message = INCIDENTS[args.incident % len(INCIDENTS)].message
    native, external = locator.find_native_external_kinds(meta)
    loc = locator.setup_root_cause_locator(
        service, args.model, kind_vocabulary=native + external)
    template = locator.build_prompt_template(native, external)
    src = locator.find_srcKind(state, message)
    plan = locator.find_destKind_relevantResources(message, src, template,
                                                   loc)
    # same intermediate derivation as the pipeline (rca/pipeline.py): drop
    # src/dest — leaving them in would make the directed rungs' interior-
    # membership clause unsatisfiable for short paths
    dest = plan["DestinationKind"]
    known = set(native) | set(external)
    intermediate = [k for k in plan.get("RelevantResources", [])
                    if k not in (src, dest) and k in known]
    metapaths = locator.find_metapath(meta, src, dest, intermediate)
    return {"message": message, "srcKind": src, "plan": plan,
            "metapaths": [[n["kind"] for n in mp.nodes]
                          for mp in metapaths]}


def stage_cypher(args, service, meta, state) -> dict:
    """Stage 2 only: LLM cypher generation for the pinned metapath, run +
    message-compatibility filter, deterministic compiler alongside."""
    message = INCIDENTS[args.incident % len(INCIDENTS)].message
    gen = cyphergen.setup_cypher_generator(service, args.model)
    out: dict = {"metapath": PINNED_METAPATH}
    try:
        query = cyphergen.generate_cypher_query(PINNED_METAPATH, message,
                                                gen)
        records = cyphergen.run_and_filter_query(state, query)
        out["cypher_query"] = query
        out["records"] = len(records)
    except Exception as e:            # scripted/weak models may misfire: the
        out["error"] = str(e)         # driver shows the failure, like the
    compiled = cyphergen.compile_metapath_query(PINNED_METAPATH, message)
    out["human_cypher_query"] = compiled
    out["human_records"] = len(cyphergen.run_and_filter_query(state,
                                                              compiled))
    return out


def stage_audit(args, service, meta, state) -> dict:
    """Stage 3 only: strict temporal state lookup + per-entity audit for a
    pinned entity (the reference pins a ResourceQuota case; our fixture's
    equivalent is the incident's involved Secret)."""
    message = INCIDENTS[args.incident % len(INCIDENTS)].message
    analyzer = auditor.setup_state_semantic_analyzer(service, args.model)
    records = state.run_query(
        "MATCH (n1:Event)-[s1:HasEvent]->(N1:EVENT) "
        "WHERE N1.message CONTAINS $message RETURN n1, N1 LIMIT 1",
        {"message": message})
    if not records:
        return {"error": f"no Event matches {message[:60]!r}"}
    timestamp = records[0]["N1"]["timestamp"]
    kind, ent_id = args.entity_kind, args.entity_id
    clues = auditor.check_states_of_entity(kind, ent_id, message, timestamp,
                                           state, analyzer)
    return {"entity": f"{kind}({ent_id})", "timestamp": timestamp,
            "clues": clues}


def stage_token(args, service, meta, state) -> dict:
    """Assistants-API smoke incl. token accounting (the test_token.py
    equivalent): unrelated math-tutor assistant, one run, windowed usage."""
    from k8s_llm_rca_tpu.serve.api import GenericAssistant
    from k8s_llm_rca_tpu.serve.backend import GenOptions

    tutor = GenericAssistant(service)
    tutor.create_assistant(
        "You are a personal math tutor; answer concisely.",
        "math-tutor", args.model, gen=GenOptions(max_new_tokens=32))
    tutor.create_thread()
    t0 = int(time.time())
    tutor.add_message("I need to solve the equation 3x + 11 = 14.")
    tutor.run_assistant()
    messages = tutor.wait_get_last_k_message(1)
    reply = (messages.data[0].content[0].text.value
             if messages is not None else None)
    usage = tutor.get_token_usage(t0, int(time.time()) + 1, limit=10)
    return {"run_status": tutor.get_run_status().status,
            "reply_chars": len(reply or ""), "token_usage": usage}


STAGES = {"locate": stage_locate, "cypher": stage_cypher,
          "audit": stage_audit, "token": stage_token}


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("stage", choices=sorted(STAGES))
    add_common_args(parser)
    parser.add_argument("--incident", type=int, default=0,
                        help="index into the canned incident corpus")
    parser.add_argument("--entity-kind", default="Secret",
                        help="audit harness: pinned entity kind")
    parser.add_argument("--entity-id", default="sec-0001",
                        help="audit harness: pinned entity id (default: the "
                             "fixture incident's missing Secret)")
    args = parser.parse_args(argv)

    service = build_service(args)
    meta, state = build_executors(args)
    try:
        result = STAGES[args.stage](args, service, meta, state)
    finally:
        meta.close()
        state.close()
    print(json.dumps(result, indent=2, default=str))
    return result


if __name__ == "__main__":
    main()
