"""Interactive end-to-end driver (the reference's test_all.py equivalent).

Runs the full pipeline over a slice of the built-in incident corpus and
prints the reports plus wall-clock bracketing (reference :52,143-151).

Usage:
    python -m k8s_llm_rca_tpu.sweeps.run_all [--backend oracle|engine]
        [--slice 0:4] [--model tiny]
"""

from __future__ import annotations

import argparse
import time

from k8s_llm_rca_tpu.config import RCAConfig
from k8s_llm_rca_tpu.graph.fixtures import INCIDENTS
from k8s_llm_rca_tpu.rca import RCAPipeline
from k8s_llm_rca_tpu.sweeps.common import (
    add_common_args, build_executors, build_service,
)
from k8s_llm_rca_tpu.utils.logging import get_logger

log = get_logger(__name__)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    add_common_args(parser)
    parser.add_argument("--slice", default="0:4",
                        help="incident corpus slice, python syntax lo:hi")
    parser.add_argument("--concurrency", type=int, default=1,
                        help="K incidents in flight via the pipelined "
                             "sweep scheduler (requires --fresh-threads; "
                             "reports stay in input order, byte-identical "
                             "to --concurrency 1 under greedy)")
    args = parser.parse_args(argv)
    if args.concurrency > 1 and not args.fresh_threads:
        parser.error("--concurrency > 1 requires --fresh-threads: "
                     "interleaved incidents on persistent stage threads "
                     "would make prompts depend on completion order")

    lo, hi = (int(x) if x else None for x in args.slice.split(":"))
    messages = [i.message for i in INCIDENTS[lo:hi]]

    service = build_service(args)
    start = time.time()
    if args.concurrency > 1:
        results, failures, closers = _run_pipelined(args, service, messages)
    else:
        meta, state = build_executors(args)
        pipeline = RCAPipeline(service, meta, state,
                               RCAConfig(model=args.model,
                          fresh_threads=args.fresh_threads))
        closers = [meta, state]
        results, failures = [], 0
        for message in messages:
            try:
                results.append(pipeline.analyze_incident(message))
            except Exception as e:
                # an exhausted retry budget on one incident must not kill
                # the sweep (run_file records failures the same way)
                log.warning("incident failed: %s", e)
                results.append(None)
                failures += 1
    for message, result in zip(messages, results):
        print("=" * 100)
        print(message)
        if result is None:
            continue
        for analysis in result["analysis"]:
            for sp in analysis["statepath"]:
                print("-" * 100)
                print(sp["report"])
    elapsed = time.time() - start
    print("*" * 100)
    print(f"analyzed {len(messages)} incident(s) in {elapsed:.2f}s "
          f"({elapsed / max(len(messages), 1):.2f}s per incident, "
          f"{failures} failure(s))")
    for ex in closers:
        ex.close()


def _run_pipelined(args, service, messages):
    """K-in-flight variant of the incident loop: same reports, printed in
    the same input order, via rca/scheduler.py instead of blocking waits."""
    from k8s_llm_rca_tpu.rca.scheduler import IncidentFailure, SweepScheduler

    executors = [build_executors(args) for _ in range(args.concurrency)]
    pipelines = [
        RCAPipeline(service, meta, state,
                    RCAConfig(model=args.model, fresh_threads=True))
        for meta, state in executors]
    raw = SweepScheduler(pipelines).run(messages)
    results, failures = [], 0
    for r in raw:
        if isinstance(r, IncidentFailure):
            log.warning("incident failed: %s", r.error)
            results.append(None)
            failures += 1
        else:
            results.append(r)
    return results, failures, [ex for pair in executors for ex in pair]


if __name__ == "__main__":
    main()
