"""Metered batch driver (the reference's test_with_file.py equivalent).

Reads incidents from a CSV (one message per row, header skipped), runs the
full pipeline, and APPENDS one JSON record per incident to the output file —
the sweep is resumable at incident granularity, exactly like the reference
(test_with_file.py:42-53,200-204).  Each record carries the reference's
schema: error_message, locator_attempts, analysis[{extend_metapath,
cypher_query, cypher_attempts, human_cypher_query?, statepath[{report,
clue}]}], time_cost, token_usage.

Usage:
    python -m k8s_llm_rca_tpu.sweeps.run_file --input data/incidents.csv \
        --output output/rca-results.json [--backend oracle|engine]
"""

from __future__ import annotations

import argparse
import contextlib
import csv
import json
import os
import time

from k8s_llm_rca_tpu.config import RCAConfig, SweepConfig
from k8s_llm_rca_tpu.graph.fixtures import INCIDENTS
from k8s_llm_rca_tpu.rca import RCAPipeline
from k8s_llm_rca_tpu.sweeps.common import (
    add_common_args, build_executors, build_service,
)
from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger

log = get_logger(__name__)


def chip_metrics(elapsed_s: float) -> dict:
    """Chip-level observability for the sweep summary (SURVEY §5): decode
    tokens/sec across the sweep, HBM stats, MFU when on a known TPU."""
    from k8s_llm_rca_tpu.runtime import profiling

    decode_tokens = METRICS.count("engine.decode_tokens")
    decode_s = METRICS.total("engine.decode_step")
    out = {
        "decode_tokens": decode_tokens,
        "prefill_tokens": METRICS.count("engine.prefill_tokens"),
        "decode_tokens_per_sec": round(decode_tokens / decode_s, 2)
        if decode_s > 0 else None,
        "sweep_tokens_per_sec": round(decode_tokens / elapsed_s, 2)
        if elapsed_s > 0 else None,
    }
    out.update({f"hbm_{k}": v
                for k, v in profiling.device_memory_stats().items()})
    return out


def write_default_corpus(path: str, repeat: int = 1) -> None:
    """Materialize the built-in incident corpus as a driver CSV."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow(["error_message"])
        for _ in range(repeat):
            for incident in INCIDENTS:
                writer.writerow([incident.message])


def load_corpus(path: str) -> list:
    messages = []
    with open(path, newline="") as f:
        reader = csv.reader(f)
        next(reader)                      # header
        for row in reader:
            if row:
                messages.append(row[0])
    return messages


def scan_output(output_path: str, truncate_partial: bool = False):
    """Resumability scan: (completed records' error_messages, character
    offset past the last COMPLETE record).  The file is a stream of
    concatenated pretty-printed JSON objects (reference format); a crash
    mid-append leaves a partial tail object, which the offset excludes —
    ``truncate_partial`` rewrites the file without it (one read, in here,
    so resume doesn't re-read the whole output just to truncate)."""
    if not os.path.exists(output_path):
        return [], 0
    with open(output_path) as f:
        text = f.read()
    decoder = json.JSONDecoder()
    idx, msgs, end = 0, [], 0
    while idx < len(text):
        while idx < len(text) and text[idx].isspace():
            idx += 1
        if idx >= len(text):
            break
        try:
            obj, idx = decoder.raw_decode(text, idx)
        except ValueError:
            break                         # trailing partial record
        msgs.append(obj.get("error_message"))
        end = idx
    if truncate_partial and len(text.rstrip()) > end:
        log.warning("truncating partial tail record in %s (crash artifact)",
                    output_path)
        # atomic: a crash between truncate and rewrite must not lose the
        # completed records, so write a sibling temp file and rename over
        tmp = output_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(text[:end] + ("\n" if end else ""))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, output_path)
    return msgs, end


def completed_incidents(output_path: str) -> int:
    """Count of complete records already in the output."""
    return len(scan_output(output_path)[0])


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser(description=__doc__)
    add_common_args(parser)
    parser.add_argument("--input", default="data/incidents.csv")
    parser.add_argument("--output", default="output/rca-results.json")
    parser.add_argument("--slice", default=":",
                        help="incident slice lo:hi")
    parser.add_argument("--resume", action="store_true",
                        help="skip incidents already present in --output")
    parser.add_argument("--replicas", type=int, default=1,
                        help="data-parallel serving: N pipeline replicas "
                             "(engine replicas pinned round-robin to local "
                             "devices) drain one incident queue "
                             "(BASELINE configs[2] pod-sweep shape)")
    parser.add_argument("--workers", type=int, default=1,
                        help="N worker threads sharing ONE engine/service: "
                             "concurrent incidents' runs merge into shared "
                             "continuous-batching decode ticks (per-chip "
                             "batching; --replicas scales across chips)")
    parser.add_argument("--concurrency", type=int, default=1,
                        help="K incidents in flight on ONE engine via the "
                             "single-threaded pipelined sweep scheduler "
                             "(rca/scheduler.py): async run submission + "
                             "shared pump, deterministic interleave, "
                             "byte-identical outputs to the sequential "
                             "sweep under greedy (requires "
                             "--fresh-threads)")
    args = parser.parse_args(argv)
    if args.replicas > 1 and args.workers > 1:
        parser.error("--replicas and --workers are mutually exclusive: "
                     "replicas build one engine per device, workers share "
                     "one engine (use replicas x workers via one process "
                     "per device if both are wanted)")
    if args.concurrency > 1 and (args.replicas > 1 or args.workers > 1):
        parser.error("--concurrency is the single-threaded pipelined "
                     "scheduler over ONE engine; it composes with neither "
                     "--replicas (engine per device) nor --workers "
                     "(thread per incident)")
    if args.concurrency > 1 and not args.fresh_threads:
        parser.error("--concurrency > 1 requires --fresh-threads: "
                     "interleaved incidents on persistent stage threads "
                     "would make prompts depend on completion order")

    if not os.path.exists(args.input):
        log.info("input %s missing; writing the built-in corpus", args.input)
        write_default_corpus(args.input)

    messages = load_corpus(args.input)
    lo, hi = (int(x) if x else None for x in args.slice.split(":"))
    messages = messages[lo:hi]
    if args.resume:
        # Resume matches completed records to input incidents by MESSAGE
        # (multiset), not by count: under --workers/--replicas incidents
        # complete out of input order, so "skip the first N" would both
        # duplicate unfinished early incidents and drop finished late
        # ones.  A crash mid-append can also leave a partial tail record;
        # truncate it so the resumed appends keep the file parseable.
        done_msgs, _ = scan_output(args.output, truncate_partial=True)
        if done_msgs:
            log.info("resuming: %d incidents already in %s",
                     len(done_msgs), args.output)
            from collections import Counter

            done = Counter(done_msgs)
            remaining = []
            for m in messages:
                if done[m] > 0:
                    done[m] -= 1
                else:
                    remaining.append(m)
            messages = remaining

    os.makedirs(os.path.dirname(args.output) or ".", exist_ok=True)
    start = time.time()
    n_rep = max(1, args.replicas)
    sweep_sched = None
    if args.concurrency > 1:
        costs, failures, per_replica, sweep_sched = _drain_pipelined(
            args, messages, args.concurrency)
    elif args.workers > 1:
        costs, failures, per_replica = _drain_shared(args, messages,
                                                     args.workers)
    elif n_rep == 1:
        costs, failures, per_replica = _drain_serial(args, messages)
    else:
        costs, failures, per_replica = _drain_replicated(args, messages,
                                                         n_rep)
    elapsed = time.time() - start

    summary = {
        "incidents": len(messages),
        "failures": failures,
        "wall_s": elapsed,
        "p50_incident_s": sorted(costs)[len(costs) // 2] if costs else 0.0,
        "metrics": METRICS.snapshot(),
        "chip": chip_metrics(elapsed),
    }
    if per_replica is not None:
        summary["replicas"] = per_replica
    if args.workers > 1:
        summary["workers"] = args.workers
    if sweep_sched is not None:
        summary["sweep_sched"] = sweep_sched
    print(json.dumps({k: v for k, v in summary.items() if k != "metrics"}))
    return summary


def _build_pipeline(args):
    service = build_service(args)
    meta, state = build_executors(args)
    return RCAPipeline(
        service, meta, state, RCAConfig(model=args.model,
                      fresh_threads=args.fresh_threads),
        sweep=SweepConfig(input_csv=args.input, output_json=args.output))


def _run_one(pipeline, message, output_path, lock=None):
    t0 = time.time()
    try:
        result = pipeline.analyze_incident(message)
        failed = False
    except Exception as e:              # a failed incident must not kill the
        log.warning("incident failed: %s", e)   # sweep; the record keeps it
        result = {"error_message": message, "error": str(e),   # resumable
                  "time_cost": time.time() - t0}
        failed = True
    ctx = lock if lock is not None else contextlib.nullcontext()
    with ctx:
        with open(output_path, "a") as f:
            f.write(json.dumps(result, indent=4) + "\n")
    log.info("incident done in %.2fs -> %s", result["time_cost"],
             output_path)
    return result["time_cost"], failed


def _drain_serial(args, messages):
    pipeline = _build_pipeline(args)
    costs, failures = [], 0
    for message in messages:
        cost, failed = _run_one(pipeline, message, args.output)
        costs.append(cost)
        failures += failed
    pipeline.meta_executor.close()
    pipeline.state_executor.close()
    return costs, failures, None


def _drain_pipelined(args, messages, k):
    """Pipelined sweep: K incidents in flight on ONE service via the
    single-threaded ``SweepScheduler`` (rca/scheduler.py) — each pipeline
    submits its next LLM run and yields, the scheduler pumps the shared
    engine once per quiescent round, so one incident's decode overlaps
    another's graph work.  Unlike --workers there are no threads and no
    completion-order nondeterminism: results come back in input order and
    (under greedy + --fresh-threads) are byte-identical to --concurrency 1.
    Records are appended at sweep end, in input order."""
    from k8s_llm_rca_tpu.rca.scheduler import IncidentFailure, SweepScheduler

    service = build_service(args)       # ONE engine, shared by all slots
    executors = [build_executors(args) for _ in range(k)]
    pipelines = [
        RCAPipeline(
            service, meta, state, RCAConfig(model=args.model,
                      fresh_threads=True),
            sweep=SweepConfig(input_csv=args.input,
                              output_json=args.output))
        for meta, state in executors]
    sched = SweepScheduler(pipelines)
    t0 = time.time()
    results = sched.run(messages)
    elapsed = time.time() - t0
    costs, failures = [], 0
    with open(args.output, "a") as f:
        for message, result in zip(messages, results):
            if isinstance(result, IncidentFailure):
                log.warning("incident failed: %s", result.error)
                record = {"error_message": message,
                          "error": str(result.error)}
                failures += 1
            else:
                record = result
            # interleaved incidents share wall time, so per-incident
            # time_cost is not observable here; report the amortized cost
            record.setdefault("time_cost", elapsed / max(1, len(messages)))
            costs.append(record["time_cost"])
            f.write(json.dumps(record, indent=4) + "\n")
    for meta, state in executors:
        meta.close()
        state.close()
    return costs, failures, None, sched.stats.snapshot()


def _drain_shared(args, messages, n_workers):
    """Shared-engine concurrent sweep: ``n_workers`` threads — each with
    its OWN RCAPipeline (own assistants/threads, so incident conversations
    stay isolated) — submit to ONE AssistantService/engine.  The
    continuous batcher merges the workers' in-flight runs into shared
    decode ticks: on dispatch-latency-dominated hosts this divides the
    per-incident tick cost by the overlap factor, which is the configs[2]
    per-chip story (--replicas covers the across-chip axis)."""
    import queue
    import threading

    service = build_service(args)       # ONE engine, shared by all workers
    work: "queue.Queue[str]" = queue.Queue()
    for m in messages:
        work.put(m)
    lock = threading.Lock()
    costs, failures = [], [0]

    def drain(idx: int) -> None:
        meta, state = build_executors(args)
        pipeline = RCAPipeline(
            service, meta, state, RCAConfig(model=args.model,
                      fresh_threads=args.fresh_threads),
            sweep=SweepConfig(input_csv=args.input,
                              output_json=args.output))
        while True:
            try:
                message = work.get_nowait()
            except queue.Empty:
                break
            cost, failed = _run_one(pipeline, message, args.output, lock)
            with lock:
                costs.append(cost)
                failures[0] += failed
        meta.close()
        state.close()

    threads = [threading.Thread(target=drain, args=(i,), daemon=True)
               for i in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return costs, failures[0], None


def _drain_replicated(args, messages, n_rep):
    """Data-parallel sweep serving: ``n_rep`` full pipeline replicas — each
    with its OWN assistants and (for --backend engine) its own engine whose
    arrays live on a round-robin-pinned local device — drain one shared
    incident queue.  This is the single-host shape of BASELINE configs[2]
    (a 100-incident sweep across a pod: one replica per chip, DP over
    incidents); multi-host runs launch one process per host with a slice.
    """
    import queue
    import threading

    work: "queue.Queue[str]" = queue.Queue()
    for m in messages:
        work.put(m)
    lock = threading.Lock()
    costs, failures, per_replica = [], [0], []

    devices = None
    if args.backend == "engine":
        import jax

        devices = jax.devices()

    def drain(idx: int) -> None:
        dev = devices[idx % len(devices)] if devices else None
        ctx = (jax.default_device(dev) if dev is not None
               else contextlib.nullcontext())
        with ctx:                      # engine arrays land on this device
            try:
                pipeline = _build_pipeline(args)
            except Exception as e:     # surface, don't die silently: the
                log.exception("replica %d failed to build", idx)   # queue
                with lock:             # drains through the other replicas
                    per_replica.append({"replica": idx, "incidents": 0,
                                        "error": str(e)})
                return
            count = 0
            while True:
                try:
                    message = work.get_nowait()
                except queue.Empty:
                    break
                cost, failed = _run_one(pipeline, message, args.output, lock)
                with lock:
                    costs.append(cost)
                    failures[0] += failed
                count += 1
        with lock:
            per_replica.append({"replica": idx, "incidents": count,
                                "device": str(dev) if dev else "host"})
        pipeline.meta_executor.close()
        pipeline.state_executor.close()

    threads = [threading.Thread(target=drain, args=(i,), daemon=True)
               for i in range(n_rep)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    per_replica.sort(key=lambda r: r["replica"])
    return costs, failures[0], per_replica


if __name__ == "__main__":
    main()
