"""Metered batch driver (the reference's test_with_file.py equivalent).

Reads incidents from a CSV (one message per row, header skipped), runs the
full pipeline, and APPENDS one JSON record per incident to the output file —
the sweep is resumable at incident granularity, exactly like the reference
(test_with_file.py:42-53,200-204).  Each record carries the reference's
schema: error_message, locator_attempts, analysis[{extend_metapath,
cypher_query, cypher_attempts, human_cypher_query?, statepath[{report,
clue}]}], time_cost, token_usage.

Usage:
    python -m k8s_llm_rca_tpu.sweeps.run_file --input data/incidents.csv \
        --output output/rca-results.json [--backend oracle|engine]
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import time

from k8s_llm_rca_tpu.config import RCAConfig, SweepConfig
from k8s_llm_rca_tpu.graph.fixtures import INCIDENTS
from k8s_llm_rca_tpu.rca import RCAPipeline
from k8s_llm_rca_tpu.sweeps.common import (
    add_common_args, build_executors, build_service,
)
from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger

log = get_logger(__name__)


def chip_metrics(elapsed_s: float) -> dict:
    """Chip-level observability for the sweep summary (SURVEY §5): decode
    tokens/sec across the sweep, HBM stats, MFU when on a known TPU."""
    from k8s_llm_rca_tpu.runtime import profiling

    decode_tokens = METRICS.count("engine.decode_tokens")
    decode_s = METRICS.total("engine.decode_step")
    out = {
        "decode_tokens": decode_tokens,
        "prefill_tokens": METRICS.count("engine.prefill_tokens"),
        "decode_tokens_per_sec": round(decode_tokens / decode_s, 2)
        if decode_s > 0 else None,
        "sweep_tokens_per_sec": round(decode_tokens / elapsed_s, 2)
        if elapsed_s > 0 else None,
    }
    out.update({f"hbm_{k}": v
                for k, v in profiling.device_memory_stats().items()})
    return out


def write_default_corpus(path: str, repeat: int = 1) -> None:
    """Materialize the built-in incident corpus as a driver CSV."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow(["error_message"])
        for _ in range(repeat):
            for incident in INCIDENTS:
                writer.writerow([incident.message])


def load_corpus(path: str) -> list:
    messages = []
    with open(path, newline="") as f:
        reader = csv.reader(f)
        next(reader)                      # header
        for row in reader:
            if row:
                messages.append(row[0])
    return messages


def completed_incidents(output_path: str) -> int:
    """Resumability: count already-written records (the file is a stream of
    concatenated pretty-printed JSON objects, reference format)."""
    if not os.path.exists(output_path):
        return 0
    with open(output_path) as f:
        text = f.read()
    decoder = json.JSONDecoder()
    idx, count = 0, 0
    while idx < len(text):
        while idx < len(text) and text[idx].isspace():
            idx += 1
        if idx >= len(text):
            break
        try:
            _, idx = decoder.raw_decode(text, idx)
        except ValueError:
            break                         # trailing partial record
        count += 1
    return count


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser(description=__doc__)
    add_common_args(parser)
    parser.add_argument("--input", default="data/incidents.csv")
    parser.add_argument("--output", default="output/rca-results.json")
    parser.add_argument("--slice", default=":",
                        help="incident slice lo:hi")
    parser.add_argument("--resume", action="store_true",
                        help="skip incidents already present in --output")
    args = parser.parse_args(argv)

    if not os.path.exists(args.input):
        log.info("input %s missing; writing the built-in corpus", args.input)
        write_default_corpus(args.input)

    messages = load_corpus(args.input)
    lo, hi = (int(x) if x else None for x in args.slice.split(":"))
    messages = messages[lo:hi]
    skip = completed_incidents(args.output) if args.resume else 0
    if skip:
        log.info("resuming: %d incidents already in %s", skip, args.output)
        messages = messages[skip:]

    service = build_service(args)
    meta, state = build_executors(args)
    pipeline = RCAPipeline(
        service, meta, state, RCAConfig(model=args.model),
        sweep=SweepConfig(input_csv=args.input, output_json=args.output))

    os.makedirs(os.path.dirname(args.output) or ".", exist_ok=True)
    start = time.time()
    costs = []
    failures = 0
    for message in messages:
        t0 = time.time()
        try:
            result = pipeline.analyze_incident(message)
        except Exception as e:          # a failed incident must not kill the
            failures += 1               # sweep; the record keeps it resumable
            log.warning("incident failed: %s", e)
            result = {"error_message": message, "error": str(e),
                      "time_cost": time.time() - t0}
        costs.append(result["time_cost"])
        with open(args.output, "a") as f:
            f.write(json.dumps(result, indent=4) + "\n")
        log.info("incident done in %.2fs -> %s", result["time_cost"],
                 args.output)
    elapsed = time.time() - start

    summary = {
        "incidents": len(messages),
        "failures": failures,
        "wall_s": elapsed,
        "p50_incident_s": sorted(costs)[len(costs) // 2] if costs else 0.0,
        "metrics": METRICS.snapshot(),
        "chip": chip_metrics(elapsed),
    }
    print(json.dumps({k: v for k, v in summary.items() if k != "metrics"}))
    meta.close()
    state.close()
    return summary


if __name__ == "__main__":
    main()
