"""Shared driver wiring: backend/executor construction from config.

Replaces the reference's copy-pasted hardcoded setup blocks (identical in
all six drivers, e.g. test_all.py:18-37): backends and graph endpoints are
chosen by config, and the hermetic in-memory backends are first-class.
"""

from __future__ import annotations

import argparse
from typing import Optional, Tuple

from k8s_llm_rca_tpu.config import (
    MODEL_REGISTRY, EngineConfig, RCAConfig, TINY,
)
from k8s_llm_rca_tpu.graph import InMemoryGraphExecutor
from k8s_llm_rca_tpu.graph.fixtures import build_metagraph, build_stategraph
from k8s_llm_rca_tpu.rca.oracle import OracleBackend
from k8s_llm_rca_tpu.serve.api import AssistantService
from k8s_llm_rca_tpu.utils import get_tokenizer


def add_common_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--backend", default="oracle",
                        choices=["oracle", "engine"],
                        help="LM backend: scripted oracle (hermetic) or the "
                             "TPU inference engine")
    parser.add_argument("--model", default="tiny",
                        help=f"model preset for --backend engine: "
                             f"{sorted(MODEL_REGISTRY)}")
    parser.add_argument("--max-batch", type=int, default=8)
    parser.add_argument("--max-seq-len", type=int, default=2048)
    parser.add_argument("--decode-chunk", type=int, default=None,
                        help="decode steps per device dispatch (default: "
                             "EngineConfig's; semantics identical to "
                             "stepwise — amortizes dispatch latency, and "
                             "DFA-grammar runs ride the scan)")
    parser.add_argument("--paged", action="store_true",
                        help="paged KV cache engine (preemption + prefix "
                             "caching) instead of contiguous slots")
    quant = parser.add_mutually_exclusive_group()
    quant.add_argument("--int8", action="store_true",
                       help="weight-only int8 quantization")
    quant.add_argument("--int4", action="store_true",
                       help="weight-only int4 quantization (nibble-packed)")
    parser.add_argument("--kv-dtype", default=None,
                        choices=["int8", "int4"],
                        help="quantized KV cache (default: model dtype)")
    parser.add_argument("--weights", default=None,
                        help="HF safetensors file/dir to load real weights "
                             "from (default: random init)")
    parser.add_argument("--neo4j-meta", default=None,
                        help="bolt://host:port for a live metagraph "
                             "(default: canned in-memory fixture)")
    parser.add_argument("--neo4j-state", default=None,
                        help="bolt://host:port for a live stategraph")
    parser.add_argument("--neo4j-auth", default="neo4j:neo4j",
                        help="user:password for live Neo4j")
    parser.add_argument("--fresh-threads", action="store_true",
                        help="start each incident on fresh stage threads "
                             "(re-seeded templates/rules) instead of the "
                             "reference's ever-growing sweep threads — "
                             "recommended for --backend engine sweeps, "
                             "whose max_seq_len is a real KV budget")


def build_service(args) -> AssistantService:
    tokenizer = get_tokenizer()
    if args.backend == "oracle":
        return AssistantService(OracleBackend(tokenizer))
    # engine backend: build the model + continuous-batching engine
    import jax

    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.models import llama
    from k8s_llm_rca_tpu.serve.backend import EngineBackend

    model_cfg = MODEL_REGISTRY.get(args.model, TINY)
    if getattr(args, "weights", None):
        from k8s_llm_rca_tpu.models.loader import load_llama

        params = load_llama(model_cfg, args.weights)
    else:
        params = llama.init_params(model_cfg, jax.random.PRNGKey(0))
    if getattr(args, "int8", False) or getattr(args, "int4", False):
        from k8s_llm_rca_tpu.models.quant import quantize_params

        params = quantize_params(
            params, bits=4 if getattr(args, "int4", False) else 8)
    # the CLI default (2048) may exceed a small preset's RoPE table; clamp
    # so `--backend engine` works out of the box for every --model
    max_seq = min(args.max_seq_len, model_cfg.max_seq_len)
    if max_seq < args.max_seq_len:
        from k8s_llm_rca_tpu.utils.logging import get_logger

        get_logger(__name__).warning(
            "clamping --max-seq-len %d to %s's model maximum %d",
            args.max_seq_len, model_cfg.name, max_seq)
    ecfg_kw = dict(max_batch=args.max_batch, max_seq_len=max_seq,
                   paged=getattr(args, "paged", False),
                   kv_cache_dtype=getattr(args, "kv_dtype", None))
    if getattr(args, "decode_chunk", None) is not None:
        ecfg_kw["decode_chunk"] = args.decode_chunk   # else EngineConfig's
    engine = make_engine(model_cfg, EngineConfig(**ecfg_kw),
                         params, tokenizer)
    return AssistantService(EngineBackend(engine))


def build_executors(args) -> Tuple[object, object]:
    if args.neo4j_meta or args.neo4j_state:
        from k8s_llm_rca_tpu.graph.executor import Neo4jQueryExecutor

        user, password = args.neo4j_auth.split(":", 1)
        meta = Neo4jQueryExecutor(args.neo4j_meta, user, password)
        state = Neo4jQueryExecutor(args.neo4j_state, user, password)
        return meta, state
    return (InMemoryGraphExecutor(build_metagraph()),
            InMemoryGraphExecutor(build_stategraph()))
