"""Pipeline parallelism: layer stages over the ``stage`` mesh axis.

GPipe-style microbatched forward under ``shard_map``: each device holds the
stacked params of ONE stage; activations flow device-to-device with
``ppermute`` over the schedule's M + P - 1 ticks (the P-1 bubble).  On real
pods the ``stage`` axis is laid out over DCN while TP stays on ICI
(SURVEY §2.2 PP row).

The stage function is arbitrary (a run of transformer blocks in practice);
``pipeline_apply`` is deliberately generic so tests can validate the
schedule with small closures.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_local(stage_params, x_mb, fn: Callable, axis_name: str):
    """Under shard_map: stage_params is this stage's slice (leading stage
    axis of size 1), x_mb [M, ...] microbatches (replicated)."""
    n_stages = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    params = jax.tree.map(lambda a: a[0], stage_params)
    m = x_mb.shape[0]
    ticks = m + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    out_buf = jnp.zeros_like(x_mb)
    cur = jnp.zeros_like(x_mb[0])

    def tick(t, carry):
        cur, out_buf = carry
        # stage 0 ingests microbatch t (when in range); others use received
        feed = x_mb[jnp.minimum(t, m - 1)]
        x_in = jnp.where(my == 0, feed, cur)
        y = fn(params, x_in)
        # the last stage writes its result for the microbatch finishing here
        mb_idx = t - (n_stages - 1)
        write = jnp.logical_and(my == n_stages - 1, mb_idx >= 0)
        out_buf = jax.lax.cond(
            write,
            lambda b: jax.lax.dynamic_update_index_in_dim(
                b, y, jnp.maximum(mb_idx, 0), 0),
            lambda b: b,
            out_buf)
        nxt = jax.lax.ppermute(y, axis_name, perm)
        return nxt, out_buf

    cur, out_buf = jax.lax.fori_loop(0, ticks, tick, (cur, out_buf))
    # broadcast the last stage's buffer to every device so the out_spec can
    # be replicated (psum of one-hot contribution)
    contrib = jnp.where(my == n_stages - 1, out_buf,
                        jnp.zeros_like(out_buf))
    return jax.lax.psum(contrib, axis_name)


def stack_llama_stages(params: Any, n_stages: int) -> Any:
    """Regroup a llama param tree's layer list into a [P, L/P, ...] stacked
    pytree for ``pipeline_apply``: stage i holds layers [i*L/P, (i+1)*L/P).
    """
    layers = params["layers"]
    assert len(layers) % n_stages == 0, (
        f"{len(layers)} layers do not divide into {n_stages} stages")
    per = len(layers) // n_stages
    stages = [
        jax.tree.map(lambda *xs: jnp.stack(xs), *layers[i * per:(i + 1) * per])
        for i in range(n_stages)
    ]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stages)


def llama_pipeline_forward(cfg, params: Any, tokens: jnp.ndarray, mesh: Mesh,
                           microbatches: int,
                           stage_axis: str = "stage",
                           stacked_layers: Any = None) -> jnp.ndarray:
    """Pipeline-parallel llama scoring forward: the transformer blocks are
    split into ``mesh.shape[stage_axis]`` stages and microbatched through
    ``pipeline_apply``; embedding lookup and the LM head run replicated
    outside the pipeline (they are <5% of FLOPs and keep the stage function
    uniform).  Matches ``models.llama.forward`` exactly on full-length
    sequences.  Reference has no model parallelism of any kind (SURVEY §2.2
    PP row); this is the DCN-friendly layer-stage axis for multi-host pods.

    Restacking the layer weights is O(model size); repeated callers should
    hoist it once via ``stack_llama_stages`` and pass ``stacked_layers``.
    """
    from k8s_llm_rca_tpu.models import llama as L

    b, s = tokens.shape
    assert b % microbatches == 0, (
        f"batch {b} must divide into {microbatches} microbatches")
    n_stages = mesh.shape[stage_axis]
    stacked = (stacked_layers if stacked_layers is not None
               else stack_llama_stages(params, n_stages))

    x = L.gather_rows(params["embedding"], tokens).astype(jnp.dtype(cfg.dtype))
    x_mb = x.reshape(microbatches, b // microbatches, s, x.shape[-1])

    def stage_fn(stage_layers, h):
        mb, s_, _ = h.shape
        angles = L.rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                    cfg.rope_theta)
        positions = jnp.broadcast_to(jnp.arange(s_)[None, :], (mb, s_))
        seq_lens = jnp.full((mb,), s_, jnp.int32)

        def body(carry, layer):
            carry, _, _ = L._block_prefill(cfg, layer, carry, angles,
                                           positions, seq_lens)
            return carry, None

        h, _ = jax.lax.scan(body, h, stage_layers)
        return h

    out = pipeline_apply(stage_fn, stacked, x_mb, mesh, stage_axis)
    return L._logits(cfg, params, out.reshape(b, s, -1))


def pipeline_apply(fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stacked_params: Any, x_mb: jnp.ndarray, mesh: Mesh,
                   stage_axis: str = "stage") -> jnp.ndarray:
    """Apply ``fn`` through P pipeline stages.

    stacked_params: pytree with a leading stage axis of size P (stage i's
    params at index i).  x_mb: [M, ...] microbatches.  Returns [M, ...] =
    stage_{P-1}(... stage_0(x) ...) per microbatch.
    """
    body = functools.partial(_pipeline_local, fn=fn, axis_name=stage_axis)
    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(stage_axis), P(*(None,) * x_mb.ndim)),
        out_specs=P(*(None,) * x_mb.ndim),
        check_vma=False,
    )(stacked_params, x_mb)
