"""Pipeline parallelism: layer stages over the ``stage`` mesh axis.

GPipe-style microbatched forward under ``shard_map``: each device holds the
stacked params of ONE stage; activations flow device-to-device with
``ppermute`` over the schedule's M + P - 1 ticks (the P-1 bubble).  On real
pods the ``stage`` axis is laid out over DCN while TP stays on ICI
(SURVEY §2.2 PP row).

The stage function is arbitrary (a run of transformer blocks in practice);
``pipeline_apply`` is deliberately generic so tests can validate the
schedule with small closures.

Serving entry points (``llama_pp_prefill``/``llama_pp_decode_step`` for the
contiguous cache, ``paged_pp_prefill``/``paged_pp_decode_step`` for the page
pool) share ONE schedule implementation (``_gpipe_loop``); what varies per
entry point is only the per-stage compute + KV write.  All four support
quantized KV (int8 / nibble-packed int4, same per-token scalar scales as
models/llama.KVCache and engine/paged.PagePool).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _gpipe_loop(stage_apply: Callable, x_mb: jnp.ndarray, kv: Tuple,
                m: int, n_st, my, perm, stage_axis: str):
    """The GPipe schedule, shared by every pipelined entry point.

    Runs M + P - 1 ticks; at tick t, this stage processes microbatch
    t - stage_index (clipped; ``valid`` is False on the warmup/drain
    garbage ticks).  ``stage_apply(h_in, mb_idx, valid, kv) -> (h_out,
    kv)`` owns the per-stage compute and any KV-cache writes (which must
    self-mask with ``valid``).  Returns (out [M, ...] = the last stage's
    per-microbatch outputs broadcast to every device, kv).
    """
    ticks = m + n_st - 1
    out_buf = jnp.zeros_like(x_mb)
    cur = jnp.zeros_like(x_mb[0])

    def tick(t, carry):
        cur, out_buf, kv = carry
        mb = jnp.clip(t - my, 0, m - 1)
        valid = jnp.logical_and(t - my >= 0, t - my < m)
        # stage 0 ingests microbatch t (when in range); others use received
        feed = x_mb[jnp.minimum(t, m - 1)]
        h_in = jnp.where(my == 0, feed, cur)
        h_out, kv = stage_apply(h_in, mb, valid, kv)
        # the last stage records its result for the microbatch finishing here
        mb_done = t - (n_st - 1)
        write = jnp.logical_and(my == n_st - 1, mb_done >= 0)
        out_buf = jax.lax.cond(
            write,
            lambda b: jax.lax.dynamic_update_index_in_dim(
                b, h_out, jnp.maximum(mb_done, 0), 0),
            lambda b: b, out_buf)
        cur = jax.lax.ppermute(h_out, stage_axis, perm)
        return cur, out_buf, kv

    cur, out_buf, kv = jax.lax.fori_loop(0, ticks, tick, (cur, out_buf, kv))
    # broadcast the last stage's buffer to every device so the out_spec can
    # be replicated (psum of one-hot contribution)
    contrib = jnp.where(my == n_st - 1, out_buf, jnp.zeros_like(out_buf))
    return jax.lax.psum(contrib, stage_axis), kv


def _stage_local_params(tree):
    """Unwrap grouped-repacked int4 leaves at the shard_map boundary.

    Inside a stage body each ``QuantTensor4Grouped`` leaf is this TP
    shard's contiguous block of the grouped packing — by construction a
    self-contained split-half buffer of its own columns (quant.
    repack_nibbles_grouped, "shard first, pack second") — so the local
    view IS a plain ``QuantTensor4`` and the stage code's ``dq()`` stays
    correct.  Globally the same leaves refuse ``dq()`` loudly; this
    unwrap is the one sanctioned crossing."""
    from k8s_llm_rca_tpu.models.quant import (
        QuantTensor4, QuantTensor4Grouped,
    )

    return jax.tree.map(
        lambda v: (QuantTensor4(q=v.q, scale=v.scale)
                   if isinstance(v, QuantTensor4Grouped) else v),
        tree, is_leaf=lambda v: isinstance(v, QuantTensor4Grouped))


def _stage_local_init(stage_layers, axis_name: str):
    n_stages = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    params = jax.tree.map(lambda a: a[0], stage_layers)   # strip stage dim
    params = _stage_local_params(params)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    return n_stages, my, params, perm


def stack_llama_stages(params: Any, n_stages: int) -> Any:
    """Regroup a llama param tree's layer list into a [P, L/P, ...] stacked
    pytree for ``pipeline_apply``: stage i holds layers [i*L/P, (i+1)*L/P).
    """
    layers = params["layers"]
    assert len(layers) % n_stages == 0, (
        f"{len(layers)} layers do not divide into {n_stages} stages")
    per = len(layers) // n_stages
    stages = [
        jax.tree.map(lambda *xs: jnp.stack(xs), *layers[i * per:(i + 1) * per])
        for i in range(n_stages)
    ]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stages)


def stacked_layer_specs(cfg, stage_axis: str = "stage",
                        tp_axis: str = None, ep_axis: str = None) -> Any:
    """PartitionSpec tree for a ``stack_llama_stages`` tree: stage axis
    leading; with ``tp_axis`` (PP×TP) each leaf additionally takes its TP
    dim from runtime.sharding.llama_param_specs shifted past the two
    stacking dims (stage over DCN, heads/hidden over ICI); with
    ``ep_axis`` (PP×EP) the stacked expert leaves keep their leading
    expert dim sharded (stage over DCN, experts over ICI).  Composed
    axes not being used map to None (replicated)."""
    from k8s_llm_rca_tpu.runtime.sharding import llama_param_specs

    layer = llama_param_specs(cfg)["layers"][0]
    if tp_axis is None and ep_axis is None:
        return {k: P(stage_axis) for k in layer}
    rename = {"model": tp_axis, "expert": ep_axis}
    return {k: P(stage_axis, None,
                 *(rename.get(a, a) if a in rename else a for a in spec))
            for k, spec in layer.items()}


def shard_stacked_layers(stacked: Any, mesh: Mesh,
                         stage_axis: str = "stage", cfg=None,
                         tp_axis: str = None, ep_axis: str = None) -> Any:
    """Place a ``stack_llama_stages`` tree with its leading stage axis
    sharded over ``mesh[stage_axis]`` — each device then holds ONLY its
    stage's layer weights, which is the HBM win that makes PP serve models
    whose weights exceed one chip.  Serving engines hoist this once.
    With ``tp_axis``/``ep_axis`` (requires ``cfg``), leaves also shard
    their TP/expert dims (stacked_layer_specs) for PP×TP / PP×EP serving;
    int8-quantized leaves (``QuantTensor``) shard their payload on the
    weight spec and their per-channel scales with reduced (size-1) dims
    replicated — runtime.sharding.shard_pytree's placement rule.

    int4 leaves (``QuantTensor4``) whose LAST axis shards over
    ``tp_axis`` are first RE-PACKED per shard
    (quant.repack_nibbles_grouped, "shard first, pack second"): each TP
    shard of the packed axis becomes a self-contained split-half buffer
    of its own columns, so the stage bodies' shard-local ``dq()`` is
    correct by construction.  Row-sharded int4 leaves (wo/w_down) keep
    the plain layout — packing is per-row independent.
    """
    if tp_axis is not None or ep_axis is not None:
        from k8s_llm_rca_tpu.runtime.sharding import shard_pytree

        specs = stacked_layer_specs(cfg, stage_axis, tp_axis, ep_axis)
        if tp_axis is not None:
            from k8s_llm_rca_tpu.models.quant import (
                QuantTensor4, repack_nibbles_grouped,
            )

            n_tp = mesh.shape[tp_axis]
            stacked = {
                k: (repack_nibbles_grouped(v, n_tp)
                    if isinstance(v, QuantTensor4) and tuple(specs[k])
                    and tuple(specs[k])[-1] == tp_axis else v)
                for k, v in stacked.items()
            }
        return shard_pytree(stacked, specs, mesh)

    def _put(x):
        spec = P(stage_axis, *(None,) * (x.ndim - 1))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(_put, stacked)


def _stacked_in_specs(stacked: Any, cfg, stage_axis: str,
                      tp_axis: str = None, ep_axis: str = None):
    """shard_map in_specs for a stacked layer tree.

    PP-only: the single prefix spec P(stage_axis) broadcasts over every
    leaf (including QuantTensor sub-leaves, whose q and scale both carry
    the leading stage dim).  Composed PP×TP / PP×EP: per-key specs, with
    quantized leaves (``QuantTensor``/``QuantTensor4``) expanded to
    (q spec, scale spec) — the scale takes the weight spec with its
    size-1 (reduced) dims replicated, mirroring
    runtime.sharding.shard_pytree's placement so the shard_map view
    matches where the bytes already live.  For int4 the q spec applies
    to the PACKED axis, which shard_stacked_layers re-packed per shard
    (``QuantTensor4Grouped``) so the local blocks are self-contained."""
    from k8s_llm_rca_tpu.models.quant import (
        QuantTensor, QuantTensor4, QuantTensor4Grouped,
    )

    if tp_axis is None and ep_axis is None:
        return P(stage_axis)
    base = stacked_layer_specs(cfg, stage_axis, tp_axis, ep_axis)
    out = {}
    for k, v in stacked.items():
        spec = base[k]
        if isinstance(v, (QuantTensor, QuantTensor4, QuantTensor4Grouped)):
            full = tuple(spec) + (None,) * (v.q.ndim - len(spec))
            scale_spec = P(*(s if d > 1 else None
                             for s, d in zip(full, v.scale.shape)))
            out[k] = type(v)(q=P(*full), scale=scale_spec)
        else:
            out[k] = spec
    return out


def llama_pipeline_forward(cfg, params: Any, tokens: jnp.ndarray, mesh: Mesh,
                           microbatches: int,
                           stage_axis: str = "stage",
                           stacked_layers: Any = None) -> jnp.ndarray:
    """Pipeline-parallel llama scoring forward: the transformer blocks are
    split into ``mesh.shape[stage_axis]`` stages and microbatched through
    ``pipeline_apply``; embedding lookup and the LM head run replicated
    outside the pipeline (they are <5% of FLOPs and keep the stage function
    uniform).  Matches ``models.llama.forward`` exactly on full-length
    sequences.  Reference has no model parallelism of any kind (SURVEY §2.2
    PP row); this is the DCN-friendly layer-stage axis for multi-host pods.

    Restacking the layer weights is O(model size); repeated callers should
    hoist it once via ``stack_llama_stages`` and pass ``stacked_layers``.
    """
    from k8s_llm_rca_tpu.models import llama as L

    b, s = tokens.shape
    assert b % microbatches == 0, (
        f"batch {b} must divide into {microbatches} microbatches")
    n_stages = mesh.shape[stage_axis]
    stacked = (stacked_layers if stacked_layers is not None
               else stack_llama_stages(params, n_stages))

    x = L.gather_rows(params["embedding"], tokens).astype(jnp.dtype(cfg.dtype))
    x_mb = x.reshape(microbatches, b // microbatches, s, x.shape[-1])

    def stage_fn(stage_layers, h):
        mb, s_, _ = h.shape
        angles = L.rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                    cfg.rope_theta)
        positions = jnp.broadcast_to(jnp.arange(s_)[None, :], (mb, s_))
        seq_lens = jnp.full((mb,), s_, jnp.int32)

        def body(carry, layer):
            carry, _, _ = L._block_prefill(cfg, layer, carry, angles,
                                           positions, seq_lens)
            return carry, None

        h, _ = jax.lax.scan(body, h, stage_layers)
        return h

    out = pipeline_apply(stage_fn, stacked, x_mb, mesh, stage_axis)
    return L._logits(cfg, params, out.reshape(b, s, -1))


def pipeline_apply(fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stacked_params: Any, x_mb: jnp.ndarray, mesh: Mesh,
                   stage_axis: str = "stage") -> jnp.ndarray:
    """Apply ``fn`` through P pipeline stages.

    stacked_params: pytree with a leading stage axis of size P (stage i's
    params at index i).  x_mb: [M, ...] microbatches.  Returns [M, ...] =
    stage_{P-1}(... stage_0(x) ...) per microbatch.
    """

    def body(stage_params, x_mb):
        n_st, my, params, perm = _stage_local_init(stage_params, stage_axis)

        def stage_apply(h, mb_idx, valid, kv):
            return fn(params, h), kv

        out, _ = _gpipe_loop(stage_apply, x_mb, (), x_mb.shape[0], n_st, my,
                             perm, stage_axis)
        return out

    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(stage_axis), P(*(None,) * x_mb.ndim)),
        out_specs=P(*(None,) * x_mb.ndim),
        check_vma=False,
    )(stacked_params, x_mb)


# ---------------------------------------------------------------------------
# PP serving: pipelined prefill + per-stage KV decode
# ---------------------------------------------------------------------------
#
# What makes PP serve-capable is the CACHE split, not just the weights:
# stage i holds only its layers' weights AND its layers' KV (the cache/pool
# LAYER axis shards over "stage"), so a model whose weights+cache exceed
# one device serves across the stage axis — the DCN-friendly scale-out the
# reference cannot express at all (SURVEY §2.2 PP row).  All entry points
# run the GPipe microbatch schedule of ``_gpipe_loop``: at tick t, stage s
# processes microbatch t-s; activations hop stages via ppermute; cache
# writes are masked to valid (stage, tick) pairs.  Decode pipelines the
# BATCH (slot groups are the microbatches), so all stages stay busy in
# steady state after the P-1 bubble.
#
# Quantized KV (int8 / packed int4) uses the same per-token scalar scales
# as the plain paths: quantization happens at the per-stage write, dequant
# at the per-stage attention read, so PP serving composes with the cache
# compression that carries the big single-chip configs.


def kv_cache_stage_specs(tp_axis: str = None,
                         stage_axis: str = "stage") -> P:
    """KVCache k/v [L, B, S, kv]: the LAYER axis shards over
    ``stage_axis``; under PP×TP the kv axis additionally shards over
    ``tp_axis``.  The ONE definition of the PP cache layout — the
    engines place the cache with it and the shard_map in/out specs
    reuse it, so the two cannot drift (a mismatch would silently
    reshard the full cache every decode tick)."""
    return P(stage_axis, None, None, tp_axis)


def kv_scale_stage_specs(stage_axis: str = "stage") -> P:
    """KVCache/PagePool scales [L, B, S] / [L, pages, page]: layer axis
    over ``stage_axis``, like the payload they scale."""
    return P(stage_axis, None, None)


def _kv_tuple(cache) -> Tuple:
    """Cache/pool -> flat array tuple for shard_map (scales only when
    quantized, so full-precision paths don't ship None through specs)."""
    if cache.k_scale is not None:
        return (cache.k, cache.v, cache.k_scale, cache.v_scale)
    return (cache.k, cache.v)


def _kv_specs(quant: bool, tp_axis: str = None,
              stage_axis: str = "stage") -> Tuple:
    kv = kv_cache_stage_specs(tp_axis, stage_axis)
    specs = (kv, kv)
    if quant:
        specs += (kv_scale_stage_specs(stage_axis),
                  kv_scale_stage_specs(stage_axis))
    return specs


def _rebuild(cache, kv_out: Tuple):
    if len(kv_out) == 4:
        return type(cache)(*kv_out)
    return type(cache)(kv_out[0], kv_out[1], None, None)


def _block_prefill_tp(cfg, layer, x, angles, positions, seq_lens,
                      tp_axis: str):
    """Manual-TP transformer block for use INSIDE a shard_map stage body
    (the PP×TP composition): column-parallel qkv / gate / up consume the
    replicated residual stream and produce LOCAL head / hidden shards,
    row-parallel wo / w_down produce partial sums combined with ``psum``
    over ``tp_axis``.  Numerically matches ``llama._block_prefill`` (the
    psum realizes the same contraction XLA's GSPMD inserts on the jitted
    path); returns (x, k_local, v_local) with k/v carrying this shard's
    kv heads only — the stage cache's kv axis is sharded to match."""
    from k8s_llm_rca_tpu.models.llama import _qkv, dq, rms_norm
    from k8s_llm_rca_tpu.ops.attention import causal_attention

    b, s, _ = x.shape
    h = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
    q, k, v = _qkv(cfg, layer, h, angles, positions)   # local head shards
    attn = causal_attention(q, k, v, seq_lens)
    out = attn.reshape(b, s, -1) @ dq(layer["wo"])
    x = x + jax.lax.psum(out, tp_axis)
    hm = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
    gate = jax.nn.silu(hm @ dq(layer["w_gate"]))
    up = hm @ dq(layer["w_up"])
    x = x + jax.lax.psum((gate * up) @ dq(layer["w_down"]), tp_axis)
    return x, k, v


def _decode_finish_tp(cfg, layer, x, attn_flat, tp_axis: str):
    """Decode-block back half under manual TP: row-parallel wo / w_down
    partial sums psum-combined (mirrors ``llama._decode_finish``)."""
    from k8s_llm_rca_tpu.models.llama import dq, rms_norm

    x = x + jax.lax.psum(attn_flat @ dq(layer["wo"]), tp_axis)
    hm = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
    gate = jax.nn.silu(hm @ dq(layer["w_gate"]))
    up = hm @ dq(layer["w_up"])
    return x + jax.lax.psum((gate * up) @ dq(layer["w_down"]), tp_axis)


def _moe_mlp_ep(cfg, layer, x, ep_axis: str):
    """EP MoE MLP for use INSIDE a shard_map stage body (the PP×EP
    composition): the residual stream ``x`` [b, s, H] is replicated
    across ``ep_axis``; each expert peer routes ITS token slice through
    the shared all-to-all dispatch (parallel.moe._moe_local — expert
    weights arrive pre-sliced by the stacked specs, leading dim E/P),
    then the outputs all_gather back to the full token set so the next
    stage-layer's attention sees every token.  Lossless capacity
    (capacity = tokens_local * top_k), matching the serving engines'
    expert_parallel_moe, so PP×EP is exactly the dense MoE function."""
    from k8s_llm_rca_tpu.models.llama import dq
    from k8s_llm_rca_tpu.parallel.moe import _moe_local

    b, s, h = x.shape
    p = jax.lax.axis_size(ep_axis)
    my = jax.lax.axis_index(ep_axis)
    t = b * s
    tl = t // p                     # validated: bm % n_ep == 0
    flat = x.reshape(t, h)
    x_local = jax.lax.dynamic_slice(flat, (my * tl, 0), (tl, h))
    out_local = _moe_local(
        x_local, dq(layer["router"]), dq(layer["w_gate"]),
        dq(layer["w_up"]), dq(layer["w_down"]), axis_name=ep_axis,
        n_experts=cfg.n_experts, top_k=cfg.n_experts_per_tok,
        capacity=max(1, tl * cfg.n_experts_per_tok))
    gathered = jax.lax.all_gather(out_local, ep_axis, axis=0, tiled=True)
    return gathered.reshape(b, s, h)


def _block_prefill_ep(cfg, layer, x, angles, positions, seq_lens,
                      ep_axis: str):
    """MoE transformer block for use inside a shard_map stage body
    (PP×EP): dense attention on the replicated stream, MoE MLP through
    the expert all-to-all (_moe_mlp_ep)."""
    from k8s_llm_rca_tpu.models.llama import _qkv, dq, rms_norm
    from k8s_llm_rca_tpu.ops.attention import causal_attention

    b, s, _ = x.shape
    h = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
    q, k, v = _qkv(cfg, layer, h, angles, positions)
    attn = causal_attention(q, k, v, seq_lens)
    x = x + attn.reshape(b, s, -1) @ dq(layer["wo"])
    hm = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
    x = x + _moe_mlp_ep(cfg, layer, hm, ep_axis)
    return x, k, v


def _decode_finish_ep(cfg, layer, x, attn_flat, ep_axis: str):
    """Decode-block back half under PP×EP: dense output projection, MoE
    MLP through the expert all-to-all."""
    from k8s_llm_rca_tpu.models.llama import dq, rms_norm

    x = x + attn_flat @ dq(layer["wo"])
    hm = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
    return x + _moe_mlp_ep(cfg, layer, hm, ep_axis)


def llama_pp_prefill(cfg, params, cache, tokens, lengths, mesh: Mesh,
                     microbatches: int = None, stage_axis: str = "stage",
                     stacked_layers=None, slots=None, tp_axis: str = None,
                     ep_axis: str = None):
    """Pipeline-parallel batched prefill with per-stage KV writes.

    tokens [B, S_pad] right-padded, lengths [B]; B divides into
    ``microbatches`` slot groups (default: one per stage); ``slots`` [B]
    cache rows to write (default arange(B); duplicates allowed only for
    identical rows — the engines pad admission batches by repeating the
    last real row, making the duplicate scatter writes idempotent).
    Returns (cache', logits [B, V] at each row's last valid token),
    matching ``llama.prefill_batch``.  Supports quantized caches.

    ``tp_axis``: the PP×TP composition — stage bodies run the manual-TP
    block (_block_prefill_tp: local head/hidden shards, psum combines)
    with weights sharded (stage, tp) and the cache's kv axis sharded
    over ``tp_axis``.  Quantized KV composes: the per-token scale is the
    FULL-row scale recovered by pmax over the TP group
    (llama._quantize_kv axis_name), so scale caches stay replicated
    across TP and numerics match the unsharded quantized path exactly.
    """
    from k8s_llm_rca_tpu.models import llama as L

    n_stages = mesh.shape[stage_axis]
    m = microbatches or n_stages
    b, s_pad = tokens.shape
    assert b % m == 0, (b, m)
    bm = b // m
    assert cfg.n_layers % n_stages == 0
    stacked = (stacked_layers if stacked_layers is not None
               else stack_llama_stages(params, n_stages))
    quant = cache.quantized
    packed = quant and L._kv_packed(cfg, cache)

    x = L.gather_rows(params["embedding"], tokens).astype(jnp.dtype(cfg.dtype))
    h_dim = x.shape[-1]
    x_mb = x.reshape(m, bm, s_pad, h_dim)
    lengths_mb = lengths.reshape(m, bm)
    if slots is None:
        slots = jnp.arange(b, dtype=jnp.int32)
    slots_mb = slots.reshape(m, bm)
    angles = L.rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)

    def local(stage_layers, kv, x_mb, lengths_mb, slots_mb):
        n_st, my, layers, perm = _stage_local_init(stage_layers, stage_axis)
        positions = jnp.broadcast_to(jnp.arange(s_pad)[None, :], (bm, s_pad))

        def stage_apply(h, mb_idx, valid, kv):
            seq_lens = lengths_mb[mb_idx]
            rows = slots_mb[mb_idx]                       # [bm] cache rows

            def body(carry, xs):
                layer, k_li, v_li = xs[0], xs[1], xs[2]
                if tp_axis is not None:
                    h2, k, v = _block_prefill_tp(cfg, layer, carry, angles,
                                                 positions, seq_lens,
                                                 tp_axis)
                elif ep_axis is not None:
                    h2, k, v = _block_prefill_ep(cfg, layer, carry, angles,
                                                 positions, seq_lens,
                                                 ep_axis)
                else:
                    h2, k, v = L._block_prefill(cfg, layer, carry, angles,
                                                positions, seq_lens)
                k_new = k.reshape(bm, s_pad, -1)     # kv_dim (or the local
                v_new = v.reshape(bm, s_pad, -1)     # TP shard of it)
                if quant:
                    ks_li, vs_li = xs[3], xs[4]
                    k_new, ks = L._quantize_kv(k_new, packed, tp_axis)
                    v_new, vs = L._quantize_kv(v_new, packed, tp_axis)
                    # row-granular garbage-tick masking, scales included
                    ks_li = ks_li.at[rows, :s_pad].set(
                        jnp.where(valid, ks, ks_li[rows, :s_pad]))
                    vs_li = vs_li.at[rows, :s_pad].set(
                        jnp.where(valid, vs, vs_li[rows, :s_pad]))
                k_li = k_li.at[rows, :s_pad].set(
                    jnp.where(valid, k_new.astype(k_li.dtype),
                              k_li[rows, :s_pad]))
                v_li = v_li.at[rows, :s_pad].set(
                    jnp.where(valid, v_new.astype(v_li.dtype),
                              v_li[rows, :s_pad]))
                return h2, ((k_li, v_li, ks_li, vs_li) if quant
                            else (k_li, v_li))

            h, kv = jax.lax.scan(body, h, (layers, *kv))
            return h, kv

        return _gpipe_loop(stage_apply, x_mb, kv, m, n_st, my, perm,
                           stage_axis)

    stacked_spec = _stacked_in_specs(stacked, cfg, stage_axis, tp_axis,
                                     ep_axis)
    out, kv_out = jax.shard_map(
        local, mesh=mesh,
        in_specs=(stacked_spec, _kv_specs(quant, tp_axis, stage_axis), P(*(None,) * 4),
                  P(None, None), P(None, None)),
        out_specs=(P(*(None,) * 4), _kv_specs(quant, tp_axis, stage_axis)),
        check_vma=False,
    )(stacked, _kv_tuple(cache), x_mb, lengths_mb, slots_mb)

    x_final = out.reshape(b, s_pad, h_dim)
    last = x_final[jnp.arange(b), lengths - 1][:, None]
    logits = L._logits(cfg, params, last)[:, 0]
    return _rebuild(cache, kv_out), logits


def llama_pp_decode_step(cfg, params, cache, tokens, lengths, mesh: Mesh,
                         microbatches: int = None,
                         stage_axis: str = "stage", stacked_layers=None,
                         tp_axis: str = None, ep_axis: str = None):
    """One pipeline-parallel decode step for ALL slots.

    tokens [B] current token per slot, lengths [B] cached tokens; the B
    slots split into ``microbatches`` groups that flow through the stages
    GPipe-style (steady-state keeps every stage busy).  Returns (cache',
    logits [B, V]) matching ``llama.decode_step``, including quantized
    caches and the PP×TP / PP×EP compositions.

    This IS the T=1 case of ``llama_pp_decode_multi`` — one shard_map
    body serves both the regular tick and speculative verification, so
    the masking/quantize-at-write/finish logic cannot drift between
    them.  Hot paths MUST hoist ``stack_llama_stages`` once and pass
    ``stacked_layers``.
    """
    cache, _, logits = llama_pp_decode_multi(
        cfg, params, cache, tokens[:, None], lengths, mesh, microbatches,
        stage_axis, stacked_layers, tp_axis, ep_axis)
    return cache, logits[:, 0]


def llama_pp_decode_multi(cfg, params, cache, tokens, lengths, mesh: Mesh,
                          microbatches: int = None,
                          stage_axis: str = "stage", stacked_layers=None,
                          tp_axis: str = None, ep_axis: str = None):
    """Pipeline-parallel MULTI-token decode (speculative verification).

    tokens [B, T] (current token + T-1 drafts per slot, as in
    ``llama.decode_multi``); lengths [B] cached tokens.  Writes all T
    tokens' KV at lengths..lengths+T-1 on each stage's local layer slice
    and returns (cache', greedy [B, T], logits [B, T, V]) — greedy
    computed on device so the [B, T] int transfer replaces the [B, T, V]
    logits except for grammar slots.  Composes with PP×TP (manual-TP
    halves, pmax quant scales) and PP×EP exactly like the single-token
    ``llama_pp_decode_step``."""
    from k8s_llm_rca_tpu.models import llama as L
    from k8s_llm_rca_tpu.ops.attention import decode_attention_multi

    n_stages = mesh.shape[stage_axis]
    m = microbatches or n_stages
    b, t = tokens.shape
    assert b % m == 0, (b, m)
    bm = b // m
    assert cfg.n_layers % n_stages == 0
    stacked = (stacked_layers if stacked_layers is not None
               else stack_llama_stages(params, n_stages))
    s_max = cache.max_seq_len
    quant = cache.quantized
    packed = quant and L._kv_packed(cfg, cache)

    x = L.gather_rows(params["embedding"],
                      tokens).astype(jnp.dtype(cfg.dtype))      # [B, T, H]
    h_dim = x.shape[-1]
    x_mb = x.reshape(m, bm, t, h_dim)
    lengths_mb = lengths.reshape(m, bm)
    angles = L.rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    dtype = jnp.dtype(cfg.dtype)

    def local(stage_layers, kv, x_mb, lengths_mb):
        n_st, my, layers, perm = _stage_local_init(stage_layers, stage_axis)

        def stage_apply(h, mb_idx, valid, kv):
            lens = lengths_mb[mb_idx]                     # [bm]
            positions = lens[:, None] + jnp.arange(t)[None, :]

            def body(carry, xs):
                layer, k_li, v_li = xs[0], xs[1], xs[2]
                q, k, v = L._decode_qkv(cfg, layer, carry, angles, positions)
                k_tok = k.reshape(bm, t, -1)   # kv_dim (or TP shard)
                v_tok = v.reshape(bm, t, -1)
                kv_last = k_li.shape[-1]
                orig_k = jax.lax.dynamic_slice(
                    k_li, (mb_idx * bm, 0, 0), (bm, s_max, kv_last))
                orig_v = jax.lax.dynamic_slice(
                    v_li, (mb_idx * bm, 0, 0), (bm, s_max, kv_last))
                if quant:
                    ks_li, vs_li = xs[3], xs[4]
                    k_tok, ks1 = L._quantize_kv(k_tok, packed, tp_axis)
                    v_tok, vs1 = L._quantize_kv(v_tok, packed, tp_axis)
                    orig_ks = jax.lax.dynamic_slice(
                        ks_li, (mb_idx * bm, 0), (bm, s_max))
                    orig_vs = jax.lax.dynamic_slice(
                        vs_li, (mb_idx * bm, 0), (bm, s_max))
                    ks_rows = L._write_tokens_scale(orig_ks, ks1, lens)
                    vs_rows = L._write_tokens_scale(orig_vs, vs1, lens)
                else:
                    ks_rows = vs_rows = None
                k_rows = L._write_tokens_kv(
                    orig_k, k_tok.astype(orig_k.dtype), lens)
                v_rows = L._write_tokens_kv(
                    orig_v, v_tok.astype(orig_v.dtype), lens)
                attn = decode_attention_multi(
                    q,
                    L._dequant_layer(k_rows, ks_rows, dtype, packed).reshape(
                        bm, s_max, -1, cfg.head_dim),
                    L._dequant_layer(v_rows, vs_rows, dtype, packed).reshape(
                        bm, s_max, -1, cfg.head_dim),
                    lens + 1)
                attn_flat = attn.reshape(bm, t, -1)
                if tp_axis is not None:
                    hx = _decode_finish_tp(cfg, layer, carry, attn_flat,
                                           tp_axis)
                elif ep_axis is not None:
                    hx = _decode_finish_ep(cfg, layer, carry, attn_flat,
                                           ep_axis)
                else:
                    hx = L._decode_finish(cfg, layer, carry, attn_flat)
                k_li = jax.lax.dynamic_update_slice(
                    k_li, jnp.where(valid, k_rows, orig_k),
                    (mb_idx * bm, 0, 0))
                v_li = jax.lax.dynamic_update_slice(
                    v_li, jnp.where(valid, v_rows, orig_v),
                    (mb_idx * bm, 0, 0))
                if quant:
                    ks_li = jax.lax.dynamic_update_slice(
                        ks_li, jnp.where(valid, ks_rows, orig_ks),
                        (mb_idx * bm, 0))
                    vs_li = jax.lax.dynamic_update_slice(
                        vs_li, jnp.where(valid, vs_rows, orig_vs),
                        (mb_idx * bm, 0))
                    return hx, (k_li, v_li, ks_li, vs_li)
                return hx, (k_li, v_li)

            h, kv = jax.lax.scan(body, h, (layers, *kv))
            return h, kv

        return _gpipe_loop(stage_apply, x_mb, kv, m, n_st, my, perm,
                           stage_axis)

    stacked_spec = _stacked_in_specs(stacked, cfg, stage_axis, tp_axis,
                                     ep_axis)
    out, kv_out = jax.shard_map(
        local, mesh=mesh,
        in_specs=(stacked_spec, _kv_specs(quant, tp_axis, stage_axis),
                  P(*(None,) * 4), P(None, None)),
        out_specs=(P(*(None,) * 4), _kv_specs(quant, tp_axis, stage_axis)),
        check_vma=False,
    )(stacked, _kv_tuple(cache), x_mb, lengths_mb)

    logits = L._logits(cfg, params, out.reshape(b, t, h_dim))   # [B, T, V]
    return (_rebuild(cache, kv_out), jnp.argmax(logits, axis=-1), logits)


# ---------------------------------------------------------------------------
# paged-pool PP serving
# ---------------------------------------------------------------------------


def paged_pp_prefill(cfg, params, pool, tokens, lengths, page_maps,
                     mesh: Mesh, microbatches: int = None,
                     stage_axis: str = "stage", stacked_layers=None,
                     tp_axis: str = None, ep_axis: str = None):
    """Pipeline-parallel paged prefill: N sequences' KV scattered into
    their pool pages, the pool's LAYER axis sharded over "stage".

    tokens [N, S_pad] right-padded with S_pad a page multiple; lengths
    [N]; page_maps [N, S_pad // page_size] page ids (same contract as
    engine/paged.paged_prefill_batch, incl. idempotent duplicate padding
    rows).  N must divide into ``microbatches``.  Returns (pool', logits
    [N, V] at each row's last valid token).  Supports quantized pools.

    ``tp_axis``: paged PP×TP — stage bodies run the manual-TP block and
    the pool's merged kv axis additionally shards over ``tp_axis`` (each
    device holds its stage's layers × its TP shard of every page).
    Quantized pools compose via the pmax full-row scale
    (llama._quantize_kv axis_name); scale pools replicate across TP.
    """
    from k8s_llm_rca_tpu.models import llama as L
    from k8s_llm_rca_tpu.engine.paged import PagePool, _pool_packed

    n_stages = mesh.shape[stage_axis]
    m = microbatches or n_stages
    b, s_pad = tokens.shape
    assert b % m == 0, (b, m)
    bm = b // m
    assert cfg.n_layers % n_stages == 0
    page_size = pool.page_size
    assert s_pad % page_size == 0, (s_pad, page_size)
    n_seq_pages = s_pad // page_size
    stacked = (stacked_layers if stacked_layers is not None
               else stack_llama_stages(params, n_stages))
    quant = pool.quantized
    packed = quant and _pool_packed(cfg, pool)

    x = L.gather_rows(params["embedding"], tokens).astype(jnp.dtype(cfg.dtype))
    h_dim = x.shape[-1]
    x_mb = x.reshape(m, bm, s_pad, h_dim)
    lengths_mb = lengths.reshape(m, bm)
    maps_mb = page_maps.reshape(m, bm, n_seq_pages)
    angles = L.rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)

    def local(stage_layers, kv, x_mb, lengths_mb, maps_mb):
        n_st, my, layers, perm = _stage_local_init(stage_layers, stage_axis)
        positions = jnp.broadcast_to(jnp.arange(s_pad)[None, :], (bm, s_pad))

        def stage_apply(h, mb_idx, valid, kv):
            seq_lens = lengths_mb[mb_idx]
            pages = maps_mb[mb_idx]               # [bm, n_seq_pages]

            def body(carry, xs):
                layer, k_li, v_li = xs[0], xs[1], xs[2]
                if tp_axis is not None:
                    h2, k, v = _block_prefill_tp(cfg, layer, carry, angles,
                                                 positions, seq_lens,
                                                 tp_axis)
                elif ep_axis is not None:
                    h2, k, v = _block_prefill_ep(cfg, layer, carry, angles,
                                                 positions, seq_lens,
                                                 ep_axis)
                else:
                    h2, k, v = L._block_prefill(cfg, layer, carry, angles,
                                                positions, seq_lens)
                # kv_dim, or the local TP shard of it
                k_new = k.reshape(bm, s_pad, -1)
                v_new = v.reshape(bm, s_pad, -1)
                if quant:
                    ks_li, vs_li = xs[3], xs[4]
                    k_new, ks = L._quantize_kv(k_new, packed, tp_axis)
                    v_new, vs = L._quantize_kv(v_new, packed, tp_axis)
                    ks = ks.reshape(bm, n_seq_pages, page_size)
                    vs = vs.reshape(bm, n_seq_pages, page_size)
                    ks_li = ks_li.at[pages].set(
                        jnp.where(valid, ks, ks_li[pages]))
                    vs_li = vs_li.at[pages].set(
                        jnp.where(valid, vs, vs_li[pages]))
                k_new = k_new.reshape(bm, n_seq_pages, page_size, -1)
                v_new = v_new.reshape(bm, n_seq_pages, page_size, -1)
                k_li = k_li.at[pages].set(
                    jnp.where(valid, k_new.astype(k_li.dtype), k_li[pages]))
                v_li = v_li.at[pages].set(
                    jnp.where(valid, v_new.astype(v_li.dtype), v_li[pages]))
                return h2, ((k_li, v_li, ks_li, vs_li) if quant
                            else (k_li, v_li))

            h, kv = jax.lax.scan(body, h, (layers, *kv))
            return h, kv

        return _gpipe_loop(stage_apply, x_mb, kv, m, n_st, my, perm,
                           stage_axis)

    stacked_spec = _stacked_in_specs(stacked, cfg, stage_axis, tp_axis,
                                     ep_axis)
    out, kv_out = jax.shard_map(
        local, mesh=mesh,
        in_specs=(stacked_spec, _kv_specs(quant, tp_axis, stage_axis), P(*(None,) * 4),
                  P(None, None), P(None, None, None)),
        out_specs=(P(*(None,) * 4), _kv_specs(quant, tp_axis, stage_axis)),
        check_vma=False,
    )(stacked, _kv_tuple(pool), x_mb, lengths_mb, maps_mb)

    x_final = out.reshape(b, s_pad, h_dim)
    last = x_final[jnp.arange(b), lengths - 1][:, None]
    logits = L._logits(cfg, params, last)[:, 0]
    return _rebuild(pool, kv_out), logits


def paged_pp_decode_step(cfg, params, pool, tokens, lengths, block_tables,
                         mesh: Mesh, microbatches: int = None,
                         stage_axis: str = "stage", stacked_layers=None,
                         tp_axis: str = None, ep_axis: str = None):
    """One pipeline-parallel paged decode step for ALL slots.

    tokens [B]; lengths [B]; block_tables [B, pages_per_seq].  The new
    token's KV scatters into each slot's current page on the LOCAL layer
    slice; attention reads the gathered dense view (the XLA paged path —
    pallas_call has no SPMD rule, and per-stage grids are small).  Returns
    (pool', logits [B, V]) matching ``paged.paged_decode_step``, incl.
    quantized pools and the PP×TP / PP×EP compositions.

    This IS the T=1 case of ``paged_pp_decode_multi`` — one shard_map
    body serves both the regular tick and speculative verification, so
    the masking/quantize-at-write/finish logic cannot drift between
    them.  Hot paths must pass a hoisted ``stacked_layers``.
    """
    pool, _, logits = paged_pp_decode_multi(
        cfg, params, pool, tokens[:, None], lengths, block_tables, mesh,
        microbatches, stage_axis, stacked_layers, tp_axis, ep_axis)
    return pool, logits[:, 0]

def paged_pp_decode_multi(cfg, params, pool, tokens, lengths, block_tables,
                          mesh: Mesh, microbatches: int = None,
                          stage_axis: str = "stage", stacked_layers=None,
                          tp_axis: str = None, ep_axis: str = None):
    """Pipeline-parallel paged MULTI-token decode (speculative
    verification): all T writes for a slot land in ONE page (the engine
    bounds T by each slot's in-page room, paged._spec_room_ok), so the
    page id is computed once per slot; attention reads the gathered
    dense view of the LOCAL layer slice.  Returns (pool', greedy [B, T],
    logits [B, T, V]) matching ``paged.paged_decode_multi``, composing
    with PP×TP (pmax quant scales) and PP×EP like the single-token
    pipelined step."""
    from k8s_llm_rca_tpu.models import llama as L
    from k8s_llm_rca_tpu.engine.paged import _pool_packed
    from k8s_llm_rca_tpu.ops.attention import decode_attention_multi

    n_stages = mesh.shape[stage_axis]
    m = microbatches or n_stages
    b, t = tokens.shape
    assert b % m == 0, (b, m)
    bm = b // m
    assert cfg.n_layers % n_stages == 0
    page_size = pool.page_size
    stacked = (stacked_layers if stacked_layers is not None
               else stack_llama_stages(params, n_stages))
    quant = pool.quantized
    packed = quant and _pool_packed(cfg, pool)
    pages_per_seq = block_tables.shape[1]
    s_max = pages_per_seq * page_size

    x = L.gather_rows(params["embedding"],
                      tokens).astype(jnp.dtype(cfg.dtype))      # [B, T, H]
    h_dim = x.shape[-1]
    x_mb = x.reshape(m, bm, t, h_dim)
    lengths_mb = lengths.reshape(m, bm)
    bt_mb = block_tables.reshape(m, bm, pages_per_seq)
    angles = L.rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    dtype = jnp.dtype(cfg.dtype)

    def local(stage_layers, kv, x_mb, lengths_mb, bt_mb):
        n_st, my, layers, perm = _stage_local_init(stage_layers, stage_axis)

        def stage_apply(h, mb_idx, valid, kv):
            lens = lengths_mb[mb_idx]                     # [bm]
            bt = bt_mb[mb_idx]                            # [bm, pages_per_seq]
            positions = lens[:, None] + jnp.arange(t)[None, :]
            page_idx = lens // page_size
            page_ids = jnp.take_along_axis(
                bt, page_idx[:, None], axis=1)            # [bm, 1]
            pages2d = jnp.broadcast_to(page_ids, (bm, t))
            offsets = (lens % page_size)[:, None] + jnp.arange(t)[None, :]

            def body(carry, xs):
                layer, k_li, v_li = xs[0], xs[1], xs[2]
                q, k, v = L._decode_qkv(cfg, layer, carry, angles, positions)
                k_tok = k.reshape(bm, t, -1)   # kv_dim (or TP shard)
                v_tok = v.reshape(bm, t, -1)
                if quant:
                    ks_li, vs_li = xs[3], xs[4]
                    k_tok, ks1 = L._quantize_kv(k_tok, packed, tp_axis)
                    v_tok, vs1 = L._quantize_kv(v_tok, packed, tp_axis)
                    ks_li = ks_li.at[pages2d, offsets].set(
                        jnp.where(valid, ks1, ks_li[pages2d, offsets]))
                    vs_li = vs_li.at[pages2d, offsets].set(
                        jnp.where(valid, vs1, vs_li[pages2d, offsets]))
                k_li = k_li.at[pages2d, offsets].set(
                    jnp.where(valid, k_tok.astype(k_li.dtype),
                              k_li[pages2d, offsets]))
                v_li = v_li.at[pages2d, offsets].set(
                    jnp.where(valid, v_tok.astype(v_li.dtype),
                              v_li[pages2d, offsets]))
                k_all = L._dequant_layer(
                    jnp.take(k_li, bt, axis=0),
                    jnp.take(ks_li, bt, axis=0) if quant else None,
                    dtype, packed).reshape(bm, s_max, -1, cfg.head_dim)
                v_all = L._dequant_layer(
                    jnp.take(v_li, bt, axis=0),
                    jnp.take(vs_li, bt, axis=0) if quant else None,
                    dtype, packed).reshape(bm, s_max, -1, cfg.head_dim)
                attn = decode_attention_multi(q, k_all, v_all, lens + 1)
                attn_flat = attn.reshape(bm, t, -1)
                if tp_axis is not None:
                    hx = _decode_finish_tp(cfg, layer, carry, attn_flat,
                                           tp_axis)
                elif ep_axis is not None:
                    hx = _decode_finish_ep(cfg, layer, carry, attn_flat,
                                           ep_axis)
                else:
                    hx = L._decode_finish(cfg, layer, carry, attn_flat)
                return hx, ((k_li, v_li, ks_li, vs_li) if quant
                            else (k_li, v_li))

            h, kv = jax.lax.scan(body, h, (layers, *kv))
            return h, kv

        return _gpipe_loop(stage_apply, x_mb, kv, m, n_st, my, perm,
                           stage_axis)

    stacked_spec = _stacked_in_specs(stacked, cfg, stage_axis, tp_axis,
                                     ep_axis)
    out, kv_out = jax.shard_map(
        local, mesh=mesh,
        in_specs=(stacked_spec, _kv_specs(quant, tp_axis, stage_axis),
                  P(*(None,) * 4), P(None, None), P(None, None, None)),
        out_specs=(P(*(None,) * 4), _kv_specs(quant, tp_axis, stage_axis)),
        check_vma=False,
    )(stacked, _kv_tuple(pool), x_mb, lengths_mb, bt_mb)

    logits = L._logits(cfg, params, out.reshape(b, t, h_dim))   # [B, T, V]
    return (_rebuild(pool, kv_out), jnp.argmax(logits, axis=-1), logits)


def paged_pp_prefill_chunk(cfg, params, pool, tokens, chunk_len,
                           prefix_len, prefix_table, page_map, mesh: Mesh,
                           stage_axis: str = "stage", stacked_layers=None,
                           tp_axis: str = None):
    """Pipeline-parallel CHUNKED prefix prefill: the prefix-cache hit
    path under PP serving.  Prefills the non-cached SUFFIX of one prompt
    whose first ``prefix_len`` tokens' KV already sit in pool pages —
    same contract as ``paged.paged_prefill_chunk`` — with each stage
    gathering its OWN layers' cached prefix pages from its local pool
    slice and scattering its chunk KV back (the pool's layer axis is
    stage-sharded).  One sequence, so the GPipe schedule degenerates to
    m=1 (sequential stages, no overlap) — the win here is the prefix KV
    REUSE, not pipelining.

    ``tp_axis``: the PP×TP composition — stage bodies run the manual-TP
    chunk layer (``engine/paged._chunk_layer(tp_axis=)``: local head
    shards, psum combines)
    over the pool's kv-lane shard, so the agent-thread reuse the cache
    was built for survives in the production stage×model mesh.  EP is
    not composed (the chunk layer has no expert dispatch; the engines
    reject prefix_cache under PP×EP)."""
    from k8s_llm_rca_tpu.engine.paged import _chunk_layer, _pool_packed
    from k8s_llm_rca_tpu.models import llama as L

    n_stages = mesh.shape[stage_axis]
    _, c_pad = tokens.shape
    page_size = pool.page_size
    assert c_pad % page_size == 0, (c_pad, page_size)
    n_chunk_pages = c_pad // page_size
    assert cfg.n_layers % n_stages == 0
    stacked = (stacked_layers if stacked_layers is not None
               else stack_llama_stages(params, n_stages))
    quant = pool.quantized
    packed = quant and _pool_packed(cfg, pool)
    s_prefix = prefix_table.shape[0] * page_size
    dtype = jnp.dtype(cfg.dtype)

    angles = L.rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    positions = prefix_len + jnp.arange(c_pad)[None, :]          # [1, C]
    # causal + validity mask in absolute positions (paged_prefill_chunk)
    q_pos = prefix_len + jnp.arange(c_pad)                       # [C]
    k_abs = jnp.concatenate([jnp.arange(s_prefix), q_pos])       # [S]
    k_valid = jnp.concatenate([
        jnp.arange(s_prefix) < prefix_len,
        jnp.arange(c_pad) < chunk_len,
    ])
    mask = (q_pos[:, None] >= k_abs[None, :]) & k_valid[None, :]  # [C, S]
    x = L.gather_rows(params["embedding"], tokens).astype(dtype)  # [1, C, H]
    h_dim = x.shape[-1]
    x_mb = x.reshape(1, 1, c_pad, h_dim)
    pages = page_map.reshape(1, n_chunk_pages)

    def local(stage_layers, kv, x_mb, mask, positions, prefix_tbl, pages):
        n_st, my, layers, perm = _stage_local_init(stage_layers, stage_axis)
        pages1 = pages[0]                                 # [n_chunk_pages]

        def stage_apply(h, mb_idx, valid, kv):
            def body(carry, xs):
                layer, k_li, v_li = xs[0], xs[1], xs[2]
                ks_li = vs_li = None
                if quant:
                    ks_li, vs_li = xs[3], xs[4]
                # shared per-layer chunk block (engine/paged._chunk_layer
                # or its manual-TP twin): gather cached prefix, attend,
                # finish — only the page WRITE below is PP-specific
                x2, k, v = _chunk_layer(cfg, layer, carry, angles,
                                        positions, mask, k_li, v_li,
                                        ks_li, vs_li, prefix_tbl, dtype,
                                        packed, tp_axis=tp_axis)
                # scatter the chunk's KV into its new pages (valid-masked)
                k_new = k[0].reshape(c_pad, -1)    # kv_dim or its TP shard
                v_new = v[0].reshape(c_pad, -1)
                if quant:
                    k_new, ks = L._quantize_kv(k_new, packed, tp_axis)
                    v_new, vs = L._quantize_kv(v_new, packed, tp_axis)
                    ks = ks.reshape(n_chunk_pages, page_size)
                    vs = vs.reshape(n_chunk_pages, page_size)
                    ks_li = ks_li.at[pages1].set(
                        jnp.where(valid, ks, ks_li[pages1]))
                    vs_li = vs_li.at[pages1].set(
                        jnp.where(valid, vs, vs_li[pages1]))
                k_new = k_new.reshape(n_chunk_pages, page_size, -1)
                v_new = v_new.reshape(n_chunk_pages, page_size, -1)
                k_li = k_li.at[pages1].set(
                    jnp.where(valid, k_new.astype(k_li.dtype),
                              k_li[pages1]))
                v_li = v_li.at[pages1].set(
                    jnp.where(valid, v_new.astype(v_li.dtype),
                              v_li[pages1]))
                return x2, ((k_li, v_li, ks_li, vs_li) if quant
                            else (k_li, v_li))

            h, kv = jax.lax.scan(body, h, (layers, *kv))
            return h, kv

        return _gpipe_loop(stage_apply, x_mb, kv, 1, n_st, my, perm,
                           stage_axis)

    stacked_spec = _stacked_in_specs(stacked, cfg, stage_axis, tp_axis,
                                     None)
    out, kv_out = jax.shard_map(
        local, mesh=mesh,
        in_specs=(stacked_spec, _kv_specs(quant, tp_axis, stage_axis),
                  P(*(None,) * 4), P(None, None), P(None, None), P(None),
                  P(None, None)),
        out_specs=(P(*(None,) * 4), _kv_specs(quant, tp_axis, stage_axis)),
        check_vma=False,
    )(stacked, _kv_tuple(pool), x_mb, mask, positions, prefix_table, pages)

    x_final = out.reshape(1, c_pad, h_dim)
    last = jax.lax.dynamic_slice_in_dim(x_final, chunk_len - 1, 1, axis=1)
    logits = L._logits(cfg, params, last)[:, 0]                  # [1, V]
    return _rebuild(pool, kv_out), logits
