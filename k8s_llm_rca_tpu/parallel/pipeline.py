"""Pipeline parallelism: layer stages over the ``stage`` mesh axis.

GPipe-style microbatched forward under ``shard_map``: each device holds the
stacked params of ONE stage; activations flow device-to-device with
``ppermute`` over the schedule's M + P - 1 ticks (the P-1 bubble).  On real
pods the ``stage`` axis is laid out over DCN while TP stays on ICI
(SURVEY §2.2 PP row).

The stage function is arbitrary (a run of transformer blocks in practice);
``pipeline_apply`` is deliberately generic so tests can validate the
schedule with small closures.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_local(stage_params, x_mb, fn: Callable, axis_name: str):
    """Under shard_map: stage_params is this stage's slice (leading stage
    axis of size 1), x_mb [M, ...] microbatches (replicated)."""
    n_stages = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    params = jax.tree.map(lambda a: a[0], stage_params)
    m = x_mb.shape[0]
    ticks = m + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    out_buf = jnp.zeros_like(x_mb)
    cur = jnp.zeros_like(x_mb[0])

    def tick(t, carry):
        cur, out_buf = carry
        # stage 0 ingests microbatch t (when in range); others use received
        feed = x_mb[jnp.minimum(t, m - 1)]
        x_in = jnp.where(my == 0, feed, cur)
        y = fn(params, x_in)
        # the last stage writes its result for the microbatch finishing here
        mb_idx = t - (n_stages - 1)
        write = jnp.logical_and(my == n_stages - 1, mb_idx >= 0)
        out_buf = jax.lax.cond(
            write,
            lambda b: jax.lax.dynamic_update_index_in_dim(
                b, y, jnp.maximum(mb_idx, 0), 0),
            lambda b: b,
            out_buf)
        nxt = jax.lax.ppermute(y, axis_name, perm)
        return nxt, out_buf

    cur, out_buf = jax.lax.fori_loop(0, ticks, tick, (cur, out_buf))
    # broadcast the last stage's buffer to every device so the out_spec can
    # be replicated (psum of one-hot contribution)
    contrib = jnp.where(my == n_stages - 1, out_buf,
                        jnp.zeros_like(out_buf))
    return jax.lax.psum(contrib, axis_name)


def stack_llama_stages(params: Any, n_stages: int) -> Any:
    """Regroup a llama param tree's layer list into a [P, L/P, ...] stacked
    pytree for ``pipeline_apply``: stage i holds layers [i*L/P, (i+1)*L/P).
    """
    layers = params["layers"]
    assert len(layers) % n_stages == 0, (
        f"{len(layers)} layers do not divide into {n_stages} stages")
    per = len(layers) // n_stages
    stages = [
        jax.tree.map(lambda *xs: jnp.stack(xs), *layers[i * per:(i + 1) * per])
        for i in range(n_stages)
    ]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stages)


def llama_pipeline_forward(cfg, params: Any, tokens: jnp.ndarray, mesh: Mesh,
                           microbatches: int,
                           stage_axis: str = "stage",
                           stacked_layers: Any = None) -> jnp.ndarray:
    """Pipeline-parallel llama scoring forward: the transformer blocks are
    split into ``mesh.shape[stage_axis]`` stages and microbatched through
    ``pipeline_apply``; embedding lookup and the LM head run replicated
    outside the pipeline (they are <5% of FLOPs and keep the stage function
    uniform).  Matches ``models.llama.forward`` exactly on full-length
    sequences.  Reference has no model parallelism of any kind (SURVEY §2.2
    PP row); this is the DCN-friendly layer-stage axis for multi-host pods.

    Restacking the layer weights is O(model size); repeated callers should
    hoist it once via ``stack_llama_stages`` and pass ``stacked_layers``.
    """
    from k8s_llm_rca_tpu.models import llama as L

    b, s = tokens.shape
    assert b % microbatches == 0, (
        f"batch {b} must divide into {microbatches} microbatches")
    n_stages = mesh.shape[stage_axis]
    stacked = (stacked_layers if stacked_layers is not None
               else stack_llama_stages(params, n_stages))

    x = L.gather_rows(params["embedding"], tokens).astype(jnp.dtype(cfg.dtype))
    x_mb = x.reshape(microbatches, b // microbatches, s, x.shape[-1])

    def stage_fn(stage_layers, h):
        mb, s_, _ = h.shape
        angles = L.rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                    cfg.rope_theta)
        positions = jnp.broadcast_to(jnp.arange(s_)[None, :], (mb, s_))
        seq_lens = jnp.full((mb,), s_, jnp.int32)

        def body(carry, layer):
            carry, _, _ = L._block_prefill(cfg, layer, carry, angles,
                                           positions, seq_lens)
            return carry, None

        h, _ = jax.lax.scan(body, h, stage_layers)
        return h

    out = pipeline_apply(stage_fn, stacked, x_mb, mesh, stage_axis)
    return L._logits(cfg, params, out.reshape(b, s, -1))


def pipeline_apply(fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stacked_params: Any, x_mb: jnp.ndarray, mesh: Mesh,
                   stage_axis: str = "stage") -> jnp.ndarray:
    """Apply ``fn`` through P pipeline stages.

    stacked_params: pytree with a leading stage axis of size P (stage i's
    params at index i).  x_mb: [M, ...] microbatches.  Returns [M, ...] =
    stage_{P-1}(... stage_0(x) ...) per microbatch.
    """
    body = functools.partial(_pipeline_local, fn=fn, axis_name=stage_axis)
    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(stage_axis), P(*(None,) * x_mb.ndim)),
        out_specs=P(*(None,) * x_mb.ndim),
        check_vma=False,
    )(stacked_params, x_mb)


# ---------------------------------------------------------------------------
# PP serving: pipelined prefill + per-stage KV decode
# ---------------------------------------------------------------------------
#
# What makes PP serve-capable is the CACHE split, not just the weights:
# stage i holds only its layers' weights AND its layers' KV (the KVCache
# layer axis shards over "stage"), so a model whose weights+cache exceed
# one device serves across the stage axis — the DCN-friendly scale-out the
# reference cannot express at all (SURVEY §2.2 PP row).  Both entry points
# run the GPipe microbatch schedule of ``_pipeline_local``: at tick t,
# stage s processes microbatch t-s; activations hop stages via ppermute;
# cache writes are masked to valid (stage, tick) pairs.  Decode pipelines
# the BATCH (slot groups are the microbatches), so all stages stay busy in
# steady state after the P-1 bubble.
#
# Scope: full-precision KV only (quantized per-stage scales would need the
# same masked-write plumbing per scale pool); engines integrate TP/EP/DP
# first — these entry points are the building blocks and the parity proof.


def kv_cache_stage_specs() -> P:
    """KVCache k/v [L, B, S, kv]: the LAYER axis shards over "stage"."""
    return P("stage", None, None, None)


def _stage_local_init(stage_layers, axis_name: str):
    n_stages = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    params = jax.tree.map(lambda a: a[0], stage_layers)   # strip stage dim
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    return n_stages, my, params, perm


def llama_pp_prefill(cfg, params, cache, tokens, lengths, mesh: Mesh,
                     microbatches: int = None, stage_axis: str = "stage",
                     stacked_layers=None):
    """Pipeline-parallel batched prefill with per-stage KV writes.

    tokens [B, S_pad] right-padded, lengths [B]; B divides into
    ``microbatches`` slot groups (default: one per stage).  Returns
    (cache', logits [B, V] at each row's last valid token), matching
    ``llama.prefill_batch`` with slots = arange(B).
    """
    from k8s_llm_rca_tpu.models import llama as L

    assert cache.k_scale is None, "PP serving supports full-precision KV"
    n_stages = mesh.shape[stage_axis]
    m = microbatches or n_stages
    b, s_pad = tokens.shape
    assert b % m == 0, (b, m)
    bm = b // m
    assert cfg.n_layers % n_stages == 0
    stacked = (stacked_layers if stacked_layers is not None
               else stack_llama_stages(params, n_stages))

    x = L.gather_rows(params["embedding"], tokens).astype(jnp.dtype(cfg.dtype))
    h_dim = x.shape[-1]
    x_mb = x.reshape(m, bm, s_pad, h_dim)
    lengths_mb = lengths.reshape(m, bm)
    angles = L.rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)

    def local(stage_layers, k_c, v_c, x_mb, lengths_mb):
        n_st, my, layers, perm = _stage_local_init(stage_layers, stage_axis)
        positions = jnp.broadcast_to(jnp.arange(s_pad)[None, :], (bm, s_pad))

        def stage_apply(h, mb_idx, valid, k_c, v_c):
            seq_lens = lengths_mb[mb_idx]

            def body(carry, xs):
                layer, k_li, v_li = xs
                h2, k, v = L._block_prefill(cfg, layer, carry, angles,
                                            positions, seq_lens)
                # row-granular garbage-tick masking (see decode stage_apply)
                orig_k = jax.lax.dynamic_slice(
                    k_li, (mb_idx * bm, 0, 0), (bm, s_pad, cfg.kv_dim))
                orig_v = jax.lax.dynamic_slice(
                    v_li, (mb_idx * bm, 0, 0), (bm, s_pad, cfg.kv_dim))
                k_li = jax.lax.dynamic_update_slice(
                    k_li, jnp.where(
                        valid,
                        k.reshape(bm, s_pad, cfg.kv_dim).astype(k_li.dtype),
                        orig_k),
                    (mb_idx * bm, 0, 0))
                v_li = jax.lax.dynamic_update_slice(
                    v_li, jnp.where(
                        valid,
                        v.reshape(bm, s_pad, cfg.kv_dim).astype(v_li.dtype),
                        orig_v),
                    (mb_idx * bm, 0, 0))
                return h2, (k_li, v_li)

            h, (k_new, v_new) = jax.lax.scan(body, h, (layers, k_c, v_c))
            return h, k_new, v_new

        ticks = m + n_st - 1
        out_buf = jnp.zeros((m, bm, s_pad, h_dim), x_mb.dtype)
        cur = jnp.zeros((bm, s_pad, h_dim), x_mb.dtype)

        def tick(t, carry):
            cur, out_buf, k_c, v_c = carry
            mb = jnp.clip(t - my, 0, m - 1)
            valid = jnp.logical_and(t - my >= 0, t - my < m)
            feed = x_mb[jnp.minimum(t, m - 1)]
            h_in = jnp.where(my == 0, feed, cur)
            h_out, k_c, v_c = stage_apply(h_in, mb, valid, k_c, v_c)
            mb_done = t - (n_st - 1)
            write = jnp.logical_and(my == n_st - 1, mb_done >= 0)
            out_buf = jax.lax.cond(
                write,
                lambda buf: jax.lax.dynamic_update_index_in_dim(
                    buf, h_out, jnp.maximum(mb_done, 0), 0),
                lambda buf: buf, out_buf)
            cur = jax.lax.ppermute(h_out, stage_axis, perm)
            return cur, out_buf, k_c, v_c

        cur, out_buf, k_c, v_c = jax.lax.fori_loop(
            0, ticks, tick, (cur, out_buf, k_c, v_c))
        contrib = jnp.where(my == n_st - 1, out_buf, jnp.zeros_like(out_buf))
        return jax.lax.psum(contrib, stage_axis), k_c, v_c

    out, k_new, v_new = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(stage_axis), kv_cache_stage_specs(),
                  kv_cache_stage_specs(), P(*(None,) * 4), P(None, None)),
        out_specs=(P(*(None,) * 4), kv_cache_stage_specs(),
                   kv_cache_stage_specs()),
        check_vma=False,
    )(stacked, cache.k, cache.v, x_mb, lengths_mb)

    x_final = out.reshape(b, s_pad, h_dim)
    last = x_final[jnp.arange(b), lengths - 1][:, None]
    logits = L._logits(cfg, params, last)[:, 0]
    return type(cache)(k_new, v_new), logits


def llama_pp_decode_step(cfg, params, cache, tokens, lengths, mesh: Mesh,
                         microbatches: int = None,
                         stage_axis: str = "stage", stacked_layers=None):
    """One pipeline-parallel decode step for ALL slots.

    tokens [B] current token per slot, lengths [B] cached tokens; the B
    slots split into ``microbatches`` groups that flow through the stages
    GPipe-style (steady-state keeps every stage busy).  Returns (cache',
    logits [B, V]) matching ``llama.decode_step``.

    Hot paths MUST hoist ``stack_llama_stages`` once and pass
    ``stacked_layers``: the default restacks every layer's weights (a
    full-model copy) on every call.
    """
    from k8s_llm_rca_tpu.models import llama as L
    from k8s_llm_rca_tpu.ops.attention import decode_attention

    assert cache.k_scale is None, "PP serving supports full-precision KV"
    n_stages = mesh.shape[stage_axis]
    m = microbatches or n_stages
    b = tokens.shape[0]
    assert b % m == 0, (b, m)
    bm = b // m
    assert cfg.n_layers % n_stages == 0
    stacked = (stacked_layers if stacked_layers is not None
               else stack_llama_stages(params, n_stages))
    s_max = cache.max_seq_len

    x = L.gather_rows(params["embedding"],
                      tokens[:, None]).astype(jnp.dtype(cfg.dtype))  # [B,1,H]
    h_dim = x.shape[-1]
    x_mb = x.reshape(m, bm, 1, h_dim)
    lengths_mb = lengths.reshape(m, bm)
    angles = L.rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    dtype = jnp.dtype(cfg.dtype)

    def local(stage_layers, k_c, v_c, x_mb, lengths_mb):
        n_st, my, layers, perm = _stage_local_init(stage_layers, stage_axis)

        def stage_apply(h, mb_idx, valid, k_c, v_c):
            lens = lengths_mb[mb_idx]                     # [bm]
            positions = lens[:, None]

            def body(carry, xs):
                layer, k_li, v_li = xs
                # shared decode block halves (models/llama._decode_qkv /
                # _decode_finish) keep PP token-for-token with decode_step
                q, k, v = L._decode_qkv(cfg, layer, carry, angles, positions)
                orig_k = jax.lax.dynamic_slice(
                    k_li, (mb_idx * bm, 0, 0), (bm, s_max, cfg.kv_dim))
                orig_v = jax.lax.dynamic_slice(
                    v_li, (mb_idx * bm, 0, 0), (bm, s_max, cfg.kv_dim))
                k_rows = L._write_token_kv(
                    orig_k, k[:, 0].reshape(bm, cfg.kv_dim).astype(
                        orig_k.dtype), lens)
                v_rows = L._write_token_kv(
                    orig_v, v[:, 0].reshape(bm, cfg.kv_dim).astype(
                        orig_v.dtype), lens)
                attn = decode_attention(
                    q,
                    k_rows.astype(dtype).reshape(bm, s_max, cfg.n_kv_heads,
                                                 cfg.head_dim),
                    v_rows.astype(dtype).reshape(bm, s_max, cfg.n_kv_heads,
                                                 cfg.head_dim),
                    lens + 1)
                hx = L._decode_finish(
                    cfg, layer, carry, attn.reshape(bm, 1, cfg.q_dim))
                # garbage-tick masking at ROW granularity: only this
                # microbatch's bm rows move, not the whole cache slice
                k_li = jax.lax.dynamic_update_slice(
                    k_li, jnp.where(valid, k_rows, orig_k),
                    (mb_idx * bm, 0, 0))
                v_li = jax.lax.dynamic_update_slice(
                    v_li, jnp.where(valid, v_rows, orig_v),
                    (mb_idx * bm, 0, 0))
                return hx, (k_li, v_li)

            h, (k_new, v_new) = jax.lax.scan(body, h, (layers, k_c, v_c))
            return h, k_new, v_new

        ticks = m + n_st - 1
        out_buf = jnp.zeros((m, bm, 1, h_dim), x_mb.dtype)
        cur = jnp.zeros((bm, 1, h_dim), x_mb.dtype)

        def tick(t, carry):
            cur, out_buf, k_c, v_c = carry
            mb = jnp.clip(t - my, 0, m - 1)
            valid = jnp.logical_and(t - my >= 0, t - my < m)
            feed = x_mb[jnp.minimum(t, m - 1)]
            h_in = jnp.where(my == 0, feed, cur)
            h_out, k_c, v_c = stage_apply(h_in, mb, valid, k_c, v_c)
            mb_done = t - (n_st - 1)
            write = jnp.logical_and(my == n_st - 1, mb_done >= 0)
            out_buf = jax.lax.cond(
                write,
                lambda buf: jax.lax.dynamic_update_index_in_dim(
                    buf, h_out, jnp.maximum(mb_done, 0), 0),
                lambda buf: buf, out_buf)
            cur = jax.lax.ppermute(h_out, stage_axis, perm)
            return cur, out_buf, k_c, v_c

        cur, out_buf, k_c, v_c = jax.lax.fori_loop(
            0, ticks, tick, (cur, out_buf, k_c, v_c))
        contrib = jnp.where(my == n_st - 1, out_buf, jnp.zeros_like(out_buf))
        return jax.lax.psum(contrib, stage_axis), k_c, v_c

    out, k_new, v_new = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(stage_axis), kv_cache_stage_specs(),
                  kv_cache_stage_specs(), P(*(None,) * 4), P(None, None)),
        out_specs=(P(*(None,) * 4), kv_cache_stage_specs(),
                   kv_cache_stage_specs()),
        check_vma=False,
    )(stacked, cache.k, cache.v, x_mb, lengths_mb)

    logits = L._logits(cfg, params, out.reshape(b, 1, h_dim))[:, 0]
    return type(cache)(k_new, v_new), logits
