"""Ulysses-style sequence parallelism: all-to-all head<->sequence reshard.

The alternative long-context mode (SURVEY §2.2): activations arrive
sequence-sharded; an all-to-all over the ``seq`` axis re-shards them to
head-sharded with the FULL sequence per device, plain causal attention runs
locally (each device owns n_heads/P heads), and a second all-to-all restores
sequence sharding.  Two collectives per attention instead of ring's P
ppermute steps — better when n_heads >= axis size and the full sequence fits
one device's memory.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from k8s_llm_rca_tpu.ops.attention import causal_attention, repeat_kv


def _ulysses_local(q, k, v, axis_name: str):
    """Under shard_map: q/k/v [B, S/P, H, D] -> out [B, S/P, H, D].

    KV heads stay unexpanded through the all-to-all when they divide the
    axis size (the per-device q-head block [d*H/P, (d+1)*H/P) maps exactly
    onto kv-head block [d*Kv/P, (d+1)*Kv/P) under blockwise GQA grouping),
    saving n_rep x collective volume; otherwise expand first.
    """
    n_dev = jax.lax.axis_size(axis_name)
    if k.shape[2] % n_dev != 0:
        n_rep = q.shape[2] // k.shape[2]
        k = repeat_kv(k, n_rep)
        v = repeat_kv(v, n_rep)

    # [B, S/P, H, D] -> all_to_all: split heads (axis 2), concat seq (axis 1)
    def to_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)   # [B, S, H/P, D]
    b, s, _, _ = qh.shape
    seq_lens = jnp.full((b,), s, jnp.int32)
    out = causal_attention(qh, kh, vh, seq_lens)         # repeats kv inside
    return to_seq(out)                                   # [B, S/P, H, D]


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh: Mesh, seq_axis: str = "seq",
                      head_axis: Optional[str] = None) -> jnp.ndarray:
    """Causal attention with sequence sharded over ``seq_axis`` via
    head<->sequence all-to-all.  n_heads must be divisible by the axis size
    (GQA kv heads are expanded first).

    ``head_axis``: optional second mesh axis sharding the HEAD dim (CP×TP
    composition): each model shard runs the seq<->head all-to-all on its
    own head block, so the per-device head count (n_heads / tp) must still
    divide the ``seq_axis`` size."""
    axis = mesh.shape[seq_axis]
    n_tp = mesh.shape[head_axis] if head_axis is not None else 1
    if q.shape[2] % n_tp or (head_axis is not None and k.shape[2] % n_tp):
        raise ValueError(
            f"heads {q.shape[2]}/{k.shape[2]} not divisible by "
            f"{head_axis}={n_tp}")
    if (q.shape[2] // n_tp) % axis:
        raise ValueError(
            f"n_heads {q.shape[2]}/{n_tp} per shard not divisible by "
            f"{seq_axis}={axis}")
    body = functools.partial(_ulysses_local, axis_name=seq_axis)
    spec = P(None, seq_axis, head_axis, None)
    return jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
