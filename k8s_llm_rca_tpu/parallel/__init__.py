from k8s_llm_rca_tpu.parallel.ring_attention import ring_attention  # noqa: F401
from k8s_llm_rca_tpu.parallel.ulysses import ulysses_attention  # noqa: F401
from k8s_llm_rca_tpu.parallel.pipeline import pipeline_apply  # noqa: F401
from k8s_llm_rca_tpu.parallel.moe import expert_parallel_moe  # noqa: F401
