from k8s_llm_rca_tpu.parallel.ring_attention import ring_attention  # noqa: F401
from k8s_llm_rca_tpu.parallel.ulysses import ulysses_attention  # noqa: F401
from k8s_llm_rca_tpu.parallel.pipeline import (  # noqa: F401
    kv_cache_stage_specs, kv_scale_stage_specs, llama_pipeline_forward,
    llama_pp_decode_step, llama_pp_prefill, paged_pp_decode_step,
    paged_pp_prefill, pipeline_apply, shard_stacked_layers,
    stack_llama_stages,
)
from k8s_llm_rca_tpu.parallel.moe import expert_parallel_moe  # noqa: F401
