"""Expert parallelism: hard top-k dispatch with all-to-all token exchange.

The bandwidth-real MoE path (SURVEY §2.2 EP row): experts are sharded over
the ``expert`` mesh axis, tokens are batch-sharded over ``data``; each
device routes its local tokens, packs them into per-expert capacity slots
(Switch/Mesh-TF dispatch-combine formulation — one-hot einsums, fully
static shapes for XLA), exchanges them with ``jax.lax.all_to_all`` so every
device receives exactly the tokens destined for ITS experts, applies its
expert MLPs, and reverses the exchange.

With sufficient capacity this computes exactly the same function as
models/llama._moe_mlp's dense soft-dispatch (tests assert parity); under
pressure it drops overflow tokens like production MoE stacks do.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from k8s_llm_rca_tpu.models.quant import dq


def _route_exact(x, router_w, n_experts: int, top_k: int, capacity: int):
    """Dispatch/combine with a SINGLE shared cumsum across the k lanes so
    capacity slots never collide."""
    logits = (x @ router_w).astype(jnp.float32)
    topv, topi = jax.lax.top_k(logits, top_k)
    weights = jax.nn.softmax(topv, axis=-1)                  # [T, K]
    t = x.shape[0]
    # flatten (k, t) so lane 0 routes first (priority), then lane 1, ...
    flat_idx = topi.T.reshape(-1)                            # [K*T]
    flat_w = weights.T.reshape(-1)
    onehot = jax.nn.one_hot(flat_idx, n_experts)             # [K*T, E]
    pos = jnp.cumsum(onehot, axis=0) - 1.0
    in_cap = pos < capacity
    sel = onehot * in_cap
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity)  # [K*T, E, C]
    disp_flat = sel[..., None] * pos_oh                       # [K*T, E, C]
    comb_flat = (sel * flat_w[:, None])[..., None] * pos_oh
    dispatch = disp_flat.reshape(top_k, t, n_experts, capacity).sum(0)
    combine = comb_flat.reshape(top_k, t, n_experts, capacity).sum(0)
    return dispatch, combine


def _expert_mlp(x, w_gate, w_up, w_down):
    """x [E_local, C', H] through per-expert SwiGLU MLPs."""
    gate = jax.nn.silu(jnp.einsum("ech,ehi->eci", x, w_gate))
    up = jnp.einsum("ech,ehi->eci", x, w_up)
    return jnp.einsum("eci,eih->ech", gate * up, w_down)


def _moe_local(x, router_w, w_gate, w_up, w_down, *, axis_name: str,
               n_experts: int, top_k: int, capacity: int):
    """Under shard_map: x [T_local, H] (sharded over 'data'); expert weights
    sharded over ``axis_name`` (leading dim E/P)."""
    dispatch, combine = _route_exact(x, router_w, n_experts, top_k, capacity)

    # pack: [T, E, C] x [T, H] -> [E, C, H]
    expert_inputs = jnp.einsum("tec,th->ech", dispatch,
                               x.astype(jnp.float32))
    # exchange: split experts across devices, gather every device's slots
    # [E, C, H] -> [E/P, P*C, H]
    expert_inputs = jax.lax.all_to_all(
        expert_inputs, axis_name, split_axis=0, concat_axis=1, tiled=True)
    expert_outputs = _expert_mlp(expert_inputs.astype(x.dtype),
                                 w_gate, w_up, w_down)
    # reverse exchange: [E/P, P*C, H] -> [E, C, H]
    expert_outputs = jax.lax.all_to_all(
        expert_outputs, axis_name, split_axis=1, concat_axis=0, tiled=True)
    # unpack: [T, E, C] x [E, C, H] -> [T, H]
    out = jnp.einsum("tec,ech->th", combine,
                     expert_outputs.astype(jnp.float32))
    return out.astype(x.dtype)


def expert_parallel_moe(x: jnp.ndarray, layer: Dict, mesh: Mesh,
                        top_k: int, capacity_factor: float = 2.0,
                        expert_axis: str = "expert",
                        data_axis: str = "data") -> jnp.ndarray:
    """MoE forward with experts sharded over ``expert_axis`` and tokens over
    ``data_axis``.

    x [B, S, H]; layer holds 'router' [H, E] (replicated) and stacked expert
    weights 'w_gate'/'w_up' [E, H, I], 'w_down' [E, I, H] sharded on their
    leading expert dim.  Returns [B, S, H].
    """
    b, s, h = x.shape
    e = layer["router"].shape[-1]
    # tokens shard over BOTH axes so each expert-axis peer routes a distinct
    # token shard (otherwise the exchange carries P identical slot copies)
    n_tok_shards = mesh.shape[data_axis] * mesh.shape[expert_axis]
    if (b * s) % n_tok_shards:
        raise ValueError(
            f"tokens {b * s} not divisible by data*expert={n_tok_shards}")
    tokens_local = (b * s) // n_tok_shards
    capacity = max(1, int(capacity_factor * tokens_local * top_k / e))

    body = functools.partial(
        _moe_local, axis_name=expert_axis, n_experts=e, top_k=top_k,
        capacity=capacity)

    flat = x.reshape(b * s, h)
    tok_spec = P((data_axis, expert_axis), None)
    out = jax.shard_map(
        body, mesh=mesh,
        in_specs=(tok_spec, P(None, None),
                  P(expert_axis, None, None), P(expert_axis, None, None),
                  P(expert_axis, None, None)),
        out_specs=tok_spec,
        check_vma=False,
    )(flat, dq(layer["router"]), dq(layer["w_gate"]), dq(layer["w_up"]),
      dq(layer["w_down"]))
    return out.reshape(b, s, h)
