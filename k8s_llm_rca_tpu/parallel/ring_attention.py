"""Ring attention: context parallelism over the ``seq`` mesh axis.

The long-context mode SURVEY §2.2 calls for: activations are sharded along
the sequence; each device keeps its query block resident while KV blocks
rotate around the ICI ring via ``jax.lax.ppermute``, with flash-style
online-softmax accumulation so the full [S, S] score matrix never
materializes.  Causality is enforced at block granularity (a KV block
entirely in the future is skipped via masking) and elementwise inside the
diagonal block.

This is the CP prefill path for RCA prompts that exceed one device's cache
(the reference's threads grow monotonically — SURVEY §5 long-context note).
Pure-XLA implementation (collectives + einsums); the Pallas fused variant
can swap in per-step later without changing the calling convention.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from k8s_llm_rca_tpu.ops.attention import NEG_INF


def _block_attention_step(q, k, v, q_pos, k_pos, acc, m, l):
    """One online-softmax accumulation step.

    q [B,Sq,H,D]; k/v [B,Skv,Kv,D] — kv heads stay UNEXPANDED (grouped
    einsums handle GQA) so the ring carries 1/n_rep of the bytes per
    ppermute; q_pos [Sq]; k_pos [Skv]; acc [B,Sq,H,D] fp32; m,l [B,Sq,H]
    fp32 running max / denominator.
    """
    b, sq, n_heads, d = q.shape
    n_kv = k.shape[2]
    n_rep = n_heads // n_kv
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    qg = q.astype(jnp.float32).reshape(b, sq, n_kv, n_rep, d)
    scores = jnp.einsum("bqgrd,bkgd->bqgrk", qg,
                        k.astype(jnp.float32)).reshape(
                            b, sq, n_heads, -1) * scale         # [B,Sq,H,Skv]
    causal = q_pos[:, None] >= k_pos[None, :]                   # [Sq,Skv]
    scores = jnp.where(causal[None, :, None, :], scores, NEG_INF)

    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))            # [B,Sq,H]
    # guard fully-masked rows (m_new == NEG_INF): keep them at zero weight
    m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(scores - m_safe[..., None])
    p = jnp.where(causal[None, :, None, :], p, 0.0)
    correction = jnp.where(m <= NEG_INF / 2, 0.0,
                           jnp.exp(m - m_safe))
    l_new = l * correction + jnp.sum(p, axis=-1)
    pg = p.reshape(b, sq, n_kv, n_rep, -1)
    upd = jnp.einsum("bqgrk,bkgd->bqgrd", pg,
                     v.astype(jnp.float32)).reshape(b, sq, n_heads, d)
    acc_new = acc * correction[..., None] + upd
    return acc_new, m_new, l_new


def _ring_attention_local(q, k, v, axis_name: str):
    """Per-shard body under shard_map: q/k/v [B, S_local, h, d]."""
    n_dev = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s_local, n_heads, d = q.shape

    q_pos = my * s_local + jnp.arange(s_local)
    acc = jnp.zeros((b, s_local, n_heads, d), jnp.float32)
    m = jnp.full((b, s_local, n_heads), NEG_INF, jnp.float32)
    l = jnp.zeros((b, s_local, n_heads), jnp.float32)

    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def step(s, carry):
        k_cur, v_cur, acc, m, l = carry
        src = (my - s) % n_dev                 # owner of the block we hold
        k_pos = src * s_local + jnp.arange(s_local)
        acc, m, l = _block_attention_step(q, k_cur, v_cur, q_pos, k_pos,
                                          acc, m, l)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, acc, m, l)

    carry = (k, v, acc, m, l)
    for s in range(n_dev):                     # static unroll over ring steps
        carry = step(s, carry)
    _, _, acc, m, l = carry
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    return out.astype(q.dtype)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, seq_axis: str = "seq",
                   head_axis: Optional[str] = None) -> jnp.ndarray:
    """Causal self-attention with sequence sharded over ``seq_axis``.

    q [B, S, n_heads, d], k/v [B, S, n_kv, d] (global views).  S must be
    divisible by the axis size.  Returns [B, S, n_heads, d].

    ``head_axis``: optional second mesh axis sharding the HEAD dim — the
    CP×TP composition.  Heads are independent in attention, so the body
    runs unchanged on its local head block while KV blocks ring over
    ``seq_axis`` per head-shard; without it, a TP-sharded caller would
    all-gather heads at the shard_map boundary and duplicate the ring on
    every model device.  n_heads AND n_kv must divide the axis size (the
    ring carries unexpanded GQA KV).
    """
    if head_axis is not None:
        n_tp = mesh.shape[head_axis]
        if q.shape[2] % n_tp or k.shape[2] % n_tp:
            raise ValueError(
                f"heads {q.shape[2]}/{k.shape[2]} not divisible by "
                f"{head_axis}={n_tp}")
    body = functools.partial(_ring_attention_local, axis_name=seq_axis)
    spec = P(None, seq_axis, head_axis, None)
    return jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
