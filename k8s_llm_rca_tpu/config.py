"""Configuration layer.

The reference has no config system at all — Neo4j URIs, model names, polling
constants, retry counts and file paths are hardcoded in every driver
(reference: test_all.py:21-22, find_metapath/find_srckind_metapath_neo4j.py:50,
common/openai_generic_assistant.py:94-95).  Here every knob is an explicit
frozen dataclass so drivers, tests and benches share one source of truth.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass(frozen=True)
class ModelConfig:
    """Decoder-LM architecture config (Llama family; Mixtral via n_experts>0)."""

    name: str = "tiny"
    vocab_size: int = 512
    hidden_size: int = 128
    n_layers: int = 2
    n_heads: int = 4
    n_kv_heads: int = 2
    head_dim: int = 32
    intermediate_size: int = 256
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    max_seq_len: int = 1024
    dtype: str = "float32"          # compute/weight dtype ("bfloat16" on TPU)
    tie_embeddings: bool = True
    # MoE (0 experts == dense Llama MLP)
    n_experts: int = 0
    n_experts_per_tok: int = 2
    # route every weight-dequant GEMM (wq/wk/wv/wo, MLP, lm head, stacked
    # experts) through the fused Pallas kernels (ops/quant_matmul.py) that
    # stream PACKED int8/int4 tiles and dequantize in-register — on a real
    # TPU backend with quantized unsharded-or-shard-local weights; every
    # other case (plain arrays, CPU/interpret hosts, GSPMD-sharded
    # consumption) falls back to the identical x @ dq(w) XLA path
    fused_quant_matmul: bool = False

    @property
    def q_dim(self) -> int:
        return self.n_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim

    def replace(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)


# ---------------------------------------------------------------------------
# Named architecture presets.  TINY/_MOE are for hermetic CPU tests; the
# 1B/8B/8x7B presets mirror the public architectures of the target models in
# BASELINE.md (TinyLlama-1.1B-Chat, Llama-3-8B-Instruct, Mixtral-8x7B).
# ---------------------------------------------------------------------------

TINY = ModelConfig(name="tiny")

TINY_MOE = ModelConfig(name="tiny_moe", n_experts=4, n_experts_per_tok=2)

TINYLLAMA_1B = ModelConfig(
    name="tinyllama-1.1b",
    vocab_size=32000,
    hidden_size=2048,
    n_layers=22,
    n_heads=32,
    n_kv_heads=4,
    head_dim=64,
    intermediate_size=5632,
    rope_theta=10000.0,
    max_seq_len=2048,
    dtype="bfloat16",
    tie_embeddings=False,
)

LLAMA3_8B = ModelConfig(
    name="llama3-8b",
    vocab_size=128256,
    hidden_size=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=8,
    head_dim=128,
    intermediate_size=14336,
    rope_theta=500000.0,
    max_seq_len=8192,
    dtype="bfloat16",
    tie_embeddings=False,
)

MIXTRAL_8X7B = ModelConfig(
    name="mixtral-8x7b",
    vocab_size=32000,
    hidden_size=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=8,
    head_dim=128,
    intermediate_size=14336,
    rope_theta=1000000.0,
    max_seq_len=8192,
    dtype="bfloat16",
    tie_embeddings=False,
    n_experts=8,
    n_experts_per_tok=2,
)

MODEL_REGISTRY = {
    c.name: c for c in (TINY, TINY_MOE, TINYLLAMA_1B, LLAMA3_8B, MIXTRAL_8X7B)
}


@dataclass(frozen=True)
class EncoderConfig:
    """Bidirectional encoder config (e5 family) for embedding/rerank."""

    name: str = "tiny-encoder"
    vocab_size: int = 512
    hidden_size: int = 128
    n_layers: int = 2
    n_heads: int = 4
    intermediate_size: int = 256
    max_seq_len: int = 512
    layer_norm_eps: float = 1e-12
    dtype: str = "float32"


TINY_ENCODER = EncoderConfig()

E5_LARGE = EncoderConfig(
    name="e5-large",
    vocab_size=30522,
    hidden_size=1024,
    n_layers=24,
    n_heads=16,
    intermediate_size=4096,
    max_seq_len=512,
    dtype="bfloat16",
)


@dataclass(frozen=True)
class MeshConfig:
    """Logical device-mesh shape.  Axis names are load-bearing throughout:

    - ``data``   — DP: batch sharding
    - ``fsdp``   — FSDP: parameter sharding with all-gather-on-use (weights
                   split along their non-TP dim; runtime/rules.py SpecLayout
                   decides which params land on it)
    - ``model``  — TP: attention heads / MLP hidden dim over ICI
    - ``expert`` — EP: MoE experts (all-to-all token dispatch)
    - ``seq``    — SP/CP: sequence sharding (ring attention / Ulysses)
    - ``stage``  — PP: pipeline stages over DCN
    """

    data: int = 1
    fsdp: int = 1
    model: int = 1
    expert: int = 1
    seq: int = 1
    stage: int = 1

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return ("data", "fsdp", "model", "expert", "seq", "stage")

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.data, self.fsdp, self.model, self.expert, self.seq,
                self.stage)

    @property
    def n_devices(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n


@dataclass(frozen=True)
class EngineConfig:
    """Inference-engine config: batching, KV cache, sampling, limits."""

    max_batch: int = 8                 # decode slots (continuous batching width)
    max_seq_len: int = 1024            # per-slot KV capacity
    prefill_buckets: Tuple[int, ...] = (64, 128, 256, 512, 1024)
    max_new_tokens: int = 256
    # contiguous-cache KV storage: None = model dtype; "int8" = per-token
    # quantized KV (half the cache HBM/bandwidth, small quality cost)
    kv_cache_dtype: Optional[str] = None
    # paged KV cache
    paged: bool = False
    page_size: int = 16
    num_pages: int = 1024
    # share page-aligned prompt-prefix KV between sequences (paged engine
    # only; engine/prefix.py) — the RCA agent threads grow monotonically,
    # so consecutive runs re-submit almost identical prompts
    prefix_cache: bool = True
    # sampling defaults
    temperature: float = 0.0           # 0 == greedy
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    # decode loop
    decode_chunk: int = 16             # device steps per host sync in scan mode
    # tick stepwise while requests are queued, so a freed slot is noticed
    # within ONE decode step (prompt admission, lower TTFT under load).
    # Off by default: on dispatch-latency-dominated hosts (the tunnel),
    # draining the queue with per-token ticks costs more wall-clock than a
    # request waiting out the current chunk.  Turn on for directly-attached
    # chips where per-dispatch latency is negligible.
    prompt_admission: bool = False
    # n-gram speculative decoding (greedy only; engine/speculative.py):
    # k drafts verified per tick by one multi-token decode.  0 = off.
    speculative_k: int = 0
    speculative_ngram: int = 3
    # host-side runtime: use the C++ components (page allocator, grammar
    # mask engine) when a toolchain can build them; pure-Python fallback
    # is behavior-identical
    native: bool = True
    # overlapped serving hot loop (docs/performance.md): device-resident
    # decode state (cur_tokens/lengths/block_tables stay on device between
    # ticks), coalesced device->host syncs (one packed fetch per flush),
    # deferred admission first-token fetches, and — for decode_chunk == 1
    # engines without speculation or live grammars — a one-tick-lagged
    # commit so host bookkeeping overlaps the in-flight device step.
    # Greedy byte-parity with host_overlap=False is guaranteed for every
    # supported composition; cp_mesh is excluded (loud ValueError).
    host_overlap: bool = False
    # per-tick prefill token budget (paged engine only; 0 = off): a
    # prompt whose post-prefix-hit suffix exceeds the budget admits
    # through the existing jitted chunk-prefill path spread across ticks
    # — one <=budget page-aligned chunk per tick, the sequence's own
    # already-written pages as the growing prefix — instead of stalling
    # one tick on a monolithic prefill.  Must be a page_size multiple
    # (chunks scatter whole pages); greedy byte-parity with budget=0 is
    # guaranteed; cp_mesh/pp_mesh and the contiguous engine are excluded
    # (loud ValueErrors).
    prefill_chunk_budget: int = 0
    # overload survival (paged engine only; docs/serving.md "overload &
    # priorities"): when > 0, a preempted sequence spills its written KV
    # pages to host buffers (one coalesced d2h fetch) and resumes by h2d
    # page restore instead of re-prefill — byte-identical greedy output,
    # no re-burned prefill FLOPs.  The value caps the TOTAL host-resident
    # spilled pages; a preemption that would exceed it falls back to the
    # free-and-re-prefill path.  0 = off (today's behavior).  Excluded
    # (loud ValueError) on cp_mesh (page axis sequence-sharded) and
    # pp_mesh (pool layer axis stage-sharded) and on the contiguous
    # engine.
    max_spilled_pages: int = 0
    # tiered prefix cache (paged engine only; engine/prefix.py
    # ``PrefixStore``, docs/performance.md "tiered prefix cache"): when
    # any knob is set, prefix-cache eviction DEMOTES page KV to a
    # host-RAM store (one coalesced d2h gather, the same page-record
    # layout as KV spill) instead of discarding it, and tier-aware
    # ``match`` PROMOTES store hits back by h2d page writes — a warm
    # miss costs a page copy, not a re-prefill.  ``prefix_host_pages``
    # caps the host-RAM tier (L1).  ``prefix_disk_dir`` persists
    # demoted pages to disk (L2) with the utils/wal.py atomic
    # temp+fsync+replace recipe and CRC-verified load: a torn/corrupt
    # entry is a silent cold miss, never a crash.  ``prefix_disk_pages``
    # caps the disk tier (0 with a dir set = unbounded).  The store's
    # budget is its OWN — spilled-run pages (``max_spilled_pages``) and
    # cached prefix pages never share a cap.  Greedy byte-parity across
    # cold-miss / L0 / L1 / L2 hits is guaranteed; excluded (loud
    # ValueError) on cp_mesh (page axis sequence-sharded), pp_mesh
    # (pool layer axis stage-sharded) and the contiguous engine,
    # mirroring the spill exclusions.
    prefix_host_pages: int = 0
    prefix_disk_dir: Optional[str] = None
    prefix_disk_pages: int = 0
    # pressure-driven demotion (paged engine only; docs/performance.md
    # "cache fabric"): when > 0, an HBM high-water mark in PAGES — at
    # every tick boundary where the allocator's free-page count dips
    # below it, refcount-0 prefix pages demote autonomously through the
    # same coalesced ``_demote`` gather explicit eviction uses, oldest
    # first, until the watermark is restored (or the evictable set runs
    # dry).  Engines keep hot pages resident under production load with
    # no router intervention; with a store attached the demoted pages
    # stay promotable, without one this is plain pressure eviction.
    # Requires ``prefix_cache=True``; excluded (loud ValueError) on the
    # contiguous engine and for negative / over-capacity (>= num_pages)
    # values.  0 = off (explicit evict only, today's behavior).
    prefix_hbm_watermark: int = 0
    # store-backed instant recovery (paged engine only, requires a
    # tiered/remote store; docs/durability.md "store-backed restore"):
    # when True, every tick that grew the prefix cache also publishes
    # the newly-resident full-page chains to the store WITHOUT freeing
    # them (``PrefixCache.flush_to_store``), so a crash-restart, drain
    # migration or disagg prefill-death fallback on ANOTHER engine
    # re-prefills against a warm fabric — near-instant, promote-then-
    # adopt, spill-identical bucket math.  Excluded (loud ValueError)
    # without a store: write-through with nowhere to write is a config
    # bug, not a degraded mode.
    prefix_store_writethrough: bool = False


@dataclass(frozen=True)
class RCAConfig:
    """Agent-pipeline config (retry budgets mirror the reference's:
    test_all.py:63,99; polling limits common/openai_generic_assistant.py:94-95)."""

    locator_max_attempts: int = 3
    cypher_max_attempts: int = 3
    metapath_max_hops: int = 3
    # per-stage decode budgets (tokens); the locator's must exceed its
    # structured-output schema's minimal document (constrain.SchemaGrammar
    # .min_budget — EngineBackend.start rejects budgets below it)
    locator_max_new_tokens: int = 768
    cypher_max_new_tokens: int = 512
    analyzer_max_new_tokens: int = 512
    srckind_limit: int = 5
    state_limit: int = 10
    # submit all per-entity audit runs before awaiting any (SURVEY §3.4:
    # they are independent until the summary barrier), so the engine
    # decodes them in one continuous batch; False = reference-serial order
    concurrent_audits: bool = True
    run_timeout_s: float = 600.0
    model: str = "tiny"                # serve-side model name
    rerank_top_k: int = 0              # cap audited records when reranking (0 = all)
    # cap the STATE fields entering each audit prompt to the k most
    # relevant by embedding (0 = all 12 reference fields); requires a
    # pipeline reranker — the rerank result then shapes prompt CONTENT,
    # not just record order (BASELINE configs[4])
    rerank_fields_top_k: int = 0
    # start every incident on FRESH stage threads (templates/rules
    # re-seeded).  The reference reuses one monotonically growing thread
    # per assistant across a whole sweep (test_with_file.py loops over
    # setup-once assistants) — viable only against a remote model with
    # effectively unbounded context; with an in-tree engine whose
    # max_seq_len is a real KV budget, long sweeps need re-anchoring.
    # Retry-with-feedback WITHIN an incident still accumulates.
    fresh_threads: bool = False
    # grammar-constrained decode for the three structured stages (plan
    # schema, cypher skeleton, report schema).  False = raw free decode:
    # output validity then rests entirely on the MODEL — the content-
    # validation mode for distilled checkpoints (rca/distill.py), and the
    # reference's own hope-and-retry regime (test_all.py:63-83)
    constrained: bool = True


@dataclass(frozen=True)
class SweepConfig:
    """Batch-driver config (reference: test_with_file.py:42-43,177-198)."""

    input_csv: str = "data/incidents.csv"
    output_json: str = "output/rca-results.json"
    locator_usage_limit: int = 10
    cypher_usage_limit: int = 20
    analyzer_usage_limit: int = 30


@dataclass(frozen=True)
class FrameworkConfig:
    model: ModelConfig = field(default_factory=lambda: TINY)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    engine: EngineConfig = field(default_factory=EngineConfig)
    rca: RCAConfig = field(default_factory=RCAConfig)
    sweep: SweepConfig = field(default_factory=SweepConfig)
