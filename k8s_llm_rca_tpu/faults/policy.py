"""Production-side resilience: retry, circuit breaking, degradation.

The reference's only failure policy is retry-with-feedback around LLM
parses (test_all.py:63-83,99-131); dependency failures (Neo4j down, run
stuck) simply crash or hang a sweep.  This module adds the explicit
policies the chaos harness (faults/plan.py, faults/inject.py) exists to
exercise:

- ``RetryPolicy`` — capped exponential backoff with SEEDED jitter and a
  deadline-aware retry budget, on an injectable clock (so chaos runs
  neither sleep for real nor depend on the wall clock);
- ``CircuitBreaker`` — per-dependency closed/open/half-open breaker, so a
  persistently failing dependency stops eating each incident's retry
  budget and the sweep degrades instead of stalling;
- ``ResilientExecutor`` — a GraphQueryExecutor decorator wiring both
  around ``run_query`` with degrade-to-empty-rows as the last resort;
- ``ResiliencePolicy`` — the pipeline-facing bundle: shared retry/breaker
  state, degradation ledger, and the graceful-degradation ladder
  ``rca/pipeline.py`` walks per stage (full engine run -> reduced token
  budget -> scripted-oracle fallback -> annotated partial report).
"""

from __future__ import annotations

import random
import time as _time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from k8s_llm_rca_tpu.obs import trace as obs_trace
from k8s_llm_rca_tpu.utils.logging import get_logger

log = get_logger(__name__)


class RetriesExhausted(RuntimeError):
    """The retry budget (attempts or deadline) ran out."""


class CircuitOpen(RuntimeError):
    """The dependency's breaker is open; the call was not attempted."""


@dataclass
class RetryPolicy:
    """Capped exponential backoff with seeded jitter and a deadline-aware
    total budget.  ``clock`` must expose ``time()``/``sleep()`` — the real
    ``time`` module in production, ``plan.VirtualClock`` under chaos."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: float = 0.5            # delay *= 1 + jitter * U[0, 1)
    deadline_s: Optional[float] = None   # total budget incl. backoff waits
    seed: int = 0
    clock: Any = _time

    def delays(self):
        """The deterministic backoff sequence for one call: capped
        exponential, then seeded jitter (one RNG per call, so two calls
        with the same policy see identical delays)."""
        rng = random.Random(self.seed)
        for attempt in range(self.max_attempts - 1):
            delay = min(self.max_delay_s, self.base_delay_s * (2 ** attempt))
            yield delay * (1.0 + self.jitter * rng.random())

    def call(self, fn: Callable[[], Any],
             retry_on: Tuple[type, ...] = (Exception,),
             breaker: Optional["CircuitBreaker"] = None,
             on_retry: Optional[Callable[[BaseException], None]] = None):
        """Run ``fn`` with retries.  A breaker, when given, gates every
        attempt and records its outcome; an open breaker raises
        ``CircuitOpen`` without consuming the retry budget."""
        start = self.clock.time()
        backoffs = self.delays()
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            if breaker is not None and not breaker.allow():
                raise CircuitOpen(f"circuit {breaker.name!r} is open") \
                    from last
            try:
                out = fn()
            except retry_on as e:
                if breaker is not None:
                    breaker.record_failure()
                last = e
                delay = next(backoffs, None)
                if delay is None:
                    break
                if (self.deadline_s is not None
                        and self.clock.time() + delay - start
                        > self.deadline_s):
                    # the budget cannot absorb the wait: fail now rather
                    # than blow the caller's deadline sleeping
                    break
                if on_retry is not None:
                    on_retry(e)
                self.clock.sleep(delay)
                continue
            if breaker is not None:
                breaker.record_success()
            return out
        raise RetriesExhausted(
            f"retries exhausted after {self.max_attempts} attempts: "
            f"{last}") from last


class CircuitBreaker:
    """Per-dependency breaker: ``failure_threshold`` consecutive failures
    open it; after ``reset_timeout_s`` (on the policy clock) one probe call
    is allowed through (half-open) — success closes, failure re-opens."""

    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_timeout_s: float = 30.0, clock: Any = _time):
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.clock = clock
        self.state = "closed"            # closed | open | half_open
        self.failures = 0
        self.opens = 0                   # lifetime open transitions
        self._opened_at = 0.0

    def allow(self) -> bool:
        if self.state == "open":
            if self.clock.time() - self._opened_at >= self.reset_timeout_s:
                self.state = "half_open"
                return True
            return False
        return True                      # closed or half_open (the probe)

    def record_success(self) -> None:
        self.failures = 0
        if self.state != "closed":
            # half_open probe succeeded (or an out-of-band success while
            # open): the dependency recovered — a flight-record event
            obs_trace.event("resilience.breaker_close", dep=self.name)
        self.state = "closed"

    def record_failure(self) -> None:
        self.failures += 1
        if self.state == "half_open" or \
                self.failures >= self.failure_threshold:
            if self.state != "open":
                self.opens += 1
                log.warning("circuit %r opened after %d failures",
                            self.name, self.failures)
                obs_trace.event("resilience.breaker_open", dep=self.name,
                                failures=self.failures)
            self.state = "open"
            self._opened_at = self.clock.time()

    def snapshot(self) -> Dict[str, Any]:
        return {"state": self.state, "opens": self.opens,
                "failures": self.failures}


@dataclass(frozen=True)
class StageDegradation:
    """One rung-drop on the degradation ladder, kept in the incident's
    report so a degraded answer is always annotated as such."""

    stage: str
    rung: str           # the rung that finally served the stage
    error: str          # why the rung(s) above it failed

    def as_dict(self) -> Dict[str, str]:
        return {"stage": self.stage, "rung": self.rung, "error": self.error}


class ResiliencePolicy:
    """The pipeline-facing bundle: one RetryPolicy template, per-dependency
    breakers, counters, and the stage degradation ladder.

    ``ladder(stage, rungs)`` tries ``(name, fn)`` rungs in order; the first
    one that returns wins.  Serving from any rung below the first records
    a ``StageDegradation`` (the incident report's annotation).  If every
    rung fails the last error re-raises — by convention the bottom rung is
    infallible (scripted fallback / empty result), so a resilient incident
    always completes, merely degraded.
    """

    def __init__(self, retry: Optional[RetryPolicy] = None,
                 failure_threshold: int = 5, reset_timeout_s: float = 30.0,
                 reduced_tokens: int = 256):
        self.retry = retry if retry is not None else RetryPolicy()
        self.clock = self.retry.clock
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.reduced_tokens = reduced_tokens
        self.breakers: Dict[str, CircuitBreaker] = {}
        self.counters: Dict[str, int] = {"retries": 0, "degraded_stages": 0}
        self.degradations: List[StageDegradation] = []   # current incident

    # -------------------------------------------------------- dependencies

    def breaker(self, dep: str) -> CircuitBreaker:
        br = self.breakers.get(dep)
        if br is None:
            br = self.breakers[dep] = CircuitBreaker(
                dep, self.failure_threshold, self.reset_timeout_s,
                clock=self.clock)
        return br

    def call(self, dep: str, fn: Callable[[], Any]):
        """Retry + breaker around one dependency call."""
        return self.retry.call(fn, breaker=self.breaker(dep),
                               on_retry=self._count_retry)

    def _count_retry(self, _exc: BaseException) -> None:
        self.counters["retries"] += 1
        obs_trace.event("resilience.retry", error=type(_exc).__name__)

    # ------------------------------------------------------------- ladder

    def begin_incident(self) -> None:
        self.degradations = []

    def ladder(self, stage: str,
               rungs: Sequence[Tuple[str, Callable[[], Any]]]):
        last: Optional[BaseException] = None
        for i, (name, fn) in enumerate(rungs):
            try:
                out = fn()
            except Exception as e:      # noqa: BLE001 — each rung may fail
                log.warning("stage %s rung %s failed: %s", stage, name, e)
                last = e
                continue
            if i > 0:
                self.degradations.append(
                    StageDegradation(stage, name, str(last)))
                self.counters["degraded_stages"] += 1
                obs_trace.event("resilience.degraded", stage=stage,
                                rung=name)
            return out
        raise last if last is not None else RuntimeError(
            f"stage {stage}: empty ladder")

    # ------------------------------------------------------------- report

    def incident_snapshot(self) -> List[Dict[str, str]]:
        return [d.as_dict() for d in self.degradations]

    def snapshot(self) -> Dict[str, Any]:
        return {
            "counters": dict(self.counters),
            "breakers": {k: self.breakers[k].snapshot()
                         for k in sorted(self.breakers)},
        }


class ResilientExecutor:
    """GraphQueryExecutor decorator: retry + breaker around ``run_query``,
    degrading to empty rows (annotated in the policy counters) when the
    dependency stays down — the stage code's own zero-record fallbacks
    then carry the incident instead of an unhandled exception killing it.
    """

    def __init__(self, inner, policy: ResiliencePolicy,
                 dep: str = "graph", degrade_to_empty: bool = True):
        self.inner = inner
        self.policy = policy
        self.dep = dep
        self.degrade_to_empty = degrade_to_empty

    def run_query(self, query: str,
                  parameters: Optional[Dict[str, Any]] = None):
        try:
            return self.policy.call(
                self.dep, lambda: self.inner.run_query(query, parameters))
        except (RetriesExhausted, CircuitOpen) as e:
            if not self.degrade_to_empty:
                raise
            self.policy.counters[f"degraded_queries:{self.dep}"] = \
                self.policy.counters.get(f"degraded_queries:{self.dep}",
                                         0) + 1
            log.warning("dependency %s degraded to empty rows: %s",
                        self.dep, e)
            return []

    def close(self) -> None:
        self.inner.close()
