"""Injection points: arming a FaultPlan and applying faults at call sites.

Threading model — zero overhead when disarmed: hot call sites (the engine
tick loops, graph ``run_query``, ``EngineBackend.start``) guard with a
single ``inject._ARMED is not None`` check and only then poll the plan.
``_ARMED`` is a module-level slot, so the disarmed cost is one global
load + identity test per call — nothing allocates, nothing is looked up
in a dict (the acceptance bar in ISSUE "new_subsystem": inert sites must
not perturb greedy-parity or differential suites, and the engine hot path
gains no per-tick Python work beyond the ``is None`` check).

Sites in the real stack:

- ``SITE_GRAPH`` (``graph/executor.py``): Neo4j/in-memory query failure,
  timeout, slow call, empty rows, poisoned payload;
- ``SITE_BACKEND`` (``serve/backend.py::EngineBackend.start``): engine
  run failure, BudgetError, stalled run (result withheld until the serve
  deadline expires it);
- ``SITE_ENGINE_TICK`` (``engine/engine.py`` / ``engine/paged.py``
  ``step``): host stall (virtual clock), allocator exhaustion ("oom":
  the free list is stolen for one tick), forced preemption wave, and
  "crash" (every active sequence loses its device KV between ticks and
  is requeued for re-prefill — the in-engine half of a worker kill);
- ``SITE_PROCESS`` (``faults/supervisor.py``): process-level "crash" —
  the supervisor tears the serving stack down (backend discarded,
  service dropped) and restarts it from the run journal
  (serve/recover.py).  Polled from the supervisor's OWN plan at
  incident boundaries, never from the armed chaos plan, so a crash
  cannot perturb the armed plan's poll counters (the soak's
  byte-identity proof depends on that);
- ``SITE_REPLICA`` (``faults/supervisor.py::ReplicaKiller``): cluster
  replica "crash" — one replica dies and the router fails its in-flight
  runs over onto survivors (cluster/router.py).  Same discipline as
  SITE_PROCESS: polled from the killer's OWN plan at incident
  boundaries, never from the armed chaos plan;
- ``SITE_PROC`` (``faults/supervisor.py::ProcKiller``): REAL process
  kill — a scheduled "crash" delivers SIGKILL to an out-of-process
  replica's worker (cluster/proc.py), and the health watchdog must
  detect the actual OS death (pipe EOF / exit code) and heal.  Same
  own-plan, incident-boundary discipline as SITE_REPLICA;
- ``SITE_NET`` (``faults/netem.py`` + ``faults/supervisor.py::
  NetKiller``): deterministic network faults on the parent<->worker
  link of a SOCKET-transport replica — partition/halfopen at incident
  boundaries (NetKiller severs the real loopback socket; the router's
  relink path must heal the SAME incarnation under a fresh session
  nonce), and the full netem vocabulary (delay/trickle/duplicate/
  corrupt/heal) when a ``NetemTransport`` wraps the link.  Own-plan
  discipline again: link faults never touch the armed plan's counters;
- ``SITE_HANDOFF`` (``cluster/disagg.py::TierRouter`` +
  ``faults/supervisor.py::HandoffKiller``): faults on the per-run KV
  handoff between the prefill and decode tiers — "drop" (EXPORT frame
  lost), "corrupt" (frame torn in flight; the adopter discards it
  whole), "delay" (virtual-clock transfer latency), "stale-fence" (the
  ADOPT ack loses the fencing race and the adopted twin is cancelled),
  plus the killer's crash/partition/halfopen landing exactly between
  EXPORT and ADOPT.  Own-plan discipline: polled once per transfer
  attempt from the handoff plan, never from the armed chaos plan;
- ``SITE_STORE`` (``cluster/store.py::RemoteStore`` +
  ``faults/supervisor.py::StoreKiller``): faults on the cross-host
  prefix-store fabric — "drop" (the store op silently never happens),
  "corrupt" (one payload byte flipped, so the CRC/record decoder
  rejects it downstream), "delay" (virtual-clock RPC latency),
  "partition" (the store link stays severed until a "heal" fault),
  plus the killer's SIGKILL/respawn of the store server itself.  Every
  one degrades to a counted cold miss, never an engine error.
  Own-plan discipline: polled exactly once per store op from the
  store's plan, never from the armed chaos plan.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, List, Optional

from k8s_llm_rca_tpu.faults.plan import Fault, FaultPlan

SITE_GRAPH = "graph.query"
SITE_BACKEND = "backend.start"
SITE_ENGINE_TICK = "engine.tick"
SITE_PROCESS = "serve.process"
SITE_REPLICA = "cluster.replica"
SITE_PROC = "cluster.proc"
SITE_NET = "cluster.net"
SITE_HANDOFF = "cluster.handoff"
SITE_STORE = "cluster.store"

# the armed plan; hot paths read this directly (see module docstring)
_ARMED: Optional[FaultPlan] = None


class InjectedFault(RuntimeError):
    """A scheduled transient dependency failure (retryable)."""


class InjectedTimeout(InjectedFault, TimeoutError):
    """A scheduled dependency timeout (retryable)."""


class PoisonedRecord:
    """Deterministic stand-in for a corrupted wire row: every field access
    raises, forcing the consumer's error path (the pipeline's retry /
    fallback ladder) instead of silently propagating garbage."""

    def __getitem__(self, key):
        raise KeyError(f"poisoned payload: field {key!r} unreadable")

    def get(self, key, default=None):
        raise KeyError(f"poisoned payload: field {key!r} unreadable")

    def __repr__(self) -> str:  # deterministic in reports
        return "PoisonedRecord()"


def arm(plan: FaultPlan) -> FaultPlan:
    global _ARMED
    if _ARMED is not None:
        raise RuntimeError("a FaultPlan is already armed")
    _ARMED = plan
    return plan


def disarm() -> None:
    global _ARMED
    plan, _ARMED = _ARMED, None
    if plan is not None:
        plan.run_cleanups()


def active() -> Optional[FaultPlan]:
    return _ARMED


@contextlib.contextmanager
def armed(plan: FaultPlan):
    """``with inject.armed(plan): ...`` — arms for the block, disarms and
    runs plan cleanups on exit (even on error)."""
    arm(plan)
    try:
        yield plan
    finally:
        disarm()


@contextlib.contextmanager
def readmission():
    """Suppress injection polls for a re-admission of an ALREADY-admitted
    run (cluster failover / drain re-starts an orphan's ``(prompt, opts)``
    on a survivor).  A logical run draws its admission fault exactly once,
    at its first ``start``: re-polling on failover would let the re-run
    draw a DIFFERENT fault than the original admission (breaking the
    byte-identical-failover contract whenever SITE_BACKEND is armed) and
    would shift every later draw's poll index in the plan snapshot, so
    kill-and-heal reports could never match the unkilled run."""
    global _ARMED
    plan, _ARMED = _ARMED, None
    try:
        yield
    finally:
        _ARMED = plan


def apply_query_fault(fault: Fault, plan: FaultPlan,
                      run: Callable[[], List[Any]]) -> List[Any]:
    """Apply a graph-query fault: raise, degrade, or distort the rows the
    real ``run()`` would return.  One implementation for every executor so
    the fault semantics cannot drift between backends."""
    if fault.kind == "error":
        raise InjectedFault(
            f"injected graph failure at {fault.site}[{fault.index}]")
    if fault.kind == "timeout":
        raise InjectedTimeout(
            f"injected graph timeout at {fault.site}[{fault.index}]")
    if fault.kind == "empty":
        return []
    if fault.kind == "slow":
        plan.clock.sleep(fault.delay_s or 0.05)
        return run()
    if fault.kind == "poison":
        rows = run()
        # corrupt, don't hide: same cardinality, unreadable payloads
        return [PoisonedRecord() for _ in rows] or [PoisonedRecord()]
    raise InjectedFault(
        f"injected fault kind {fault.kind!r} at {fault.site}[{fault.index}]")
